// Pure C++ end-to-end integration: a 4-thread full mesh over a HashStore
// runs every collective, p2p messaging, a fork, and a graceful teardown —
// with no Python in the loop, so ASAN leak checking covers the whole
// library lifecycle (contexts, pairs, buffers, scratch, stores).
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include "tpucoll/collectives/collectives.h"
#include "tpucoll/context.h"
#include "tpucoll/rendezvous/hash_store.h"
#include "tpucoll/transport/device.h"

namespace {

int failures = 0;

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);      \
      __atomic_fetch_add(&failures, 1, __ATOMIC_SEQ_CST);                  \
    }                                                                      \
  } while (0)

void worker(std::shared_ptr<tpucoll::Store> store, int rank, int size) {
  using namespace tpucoll;
  auto device =
      std::make_shared<transport::Device>(transport::DeviceAttr{});
  Context ctx(rank, size);
  ctx.setTimeout(std::chrono::milliseconds(15000));
  ctx.connectFullMesh(store, device);

  // Allreduce across every algorithm.
  for (auto algo : {AllreduceAlgorithm::kRing,
                    AllreduceAlgorithm::kHalvingDoubling,
                    AllreduceAlgorithm::kBcube,
                    AllreduceAlgorithm::kRingBf16Wire}) {
    std::vector<float> x(1000, float(rank + 1));
    AllreduceOptions opts;
    opts.context = &ctx;
    opts.inputs = {x.data()};
    opts.outputs = {x.data()};
    opts.count = x.size();
    opts.algorithm = algo;
    opts.tag = static_cast<uint32_t>(algo);
    allreduce(opts);
    const float expect = size * (size + 1) / 2.0f;
    CHECK(x[0] == expect && x.back() == expect);
  }

  // Broadcast + barrier + allgather + reduce_scatter + alltoall.
  {
    std::vector<double> b(64, rank == 1 ? 42.0 : 0.0);
    BroadcastOptions opts;
    opts.context = &ctx;
    opts.buffer = b.data();
    opts.count = b.size();
    opts.dtype = DataType::kFloat64;
    opts.root = 1;
    broadcast(opts);
    CHECK(b[0] == 42.0);
  }
  {
    BarrierOptions opts;
    opts.context = &ctx;
    barrier(opts);
  }
  {
    std::vector<int32_t> in(10, rank), out(10 * size, -1);
    AllgatherOptions opts;
    opts.context = &ctx;
    opts.input = in.data();
    opts.output = out.data();
    opts.count = in.size();
    opts.dtype = DataType::kInt32;
    allgather(opts);
    for (int r = 0; r < size; r++) {
      CHECK(out[r * 10] == r);
    }
  }
  {
    std::vector<float> in(size * 8, 1.0f), out(8, 0.0f);
    ReduceScatterOptions opts;
    opts.context = &ctx;
    opts.input = in.data();
    opts.output = out.data();
    opts.recvCounts.assign(size, 8);
    reduceScatter(opts);
    CHECK(out[0] == float(size));
  }
  {
    std::vector<int64_t> in(size * 4), out(size * 4, -1);
    for (int j = 0; j < size; j++) {
      for (int k = 0; k < 4; k++) {
        in[j * 4 + k] = rank * 100 + j;
      }
    }
    AlltoallOptions opts;
    opts.context = &ctx;
    opts.input = in.data();
    opts.output = out.data();
    opts.count = 4;
    opts.dtype = DataType::kInt64;
    alltoall(opts);
    for (int j = 0; j < size; j++) {
      CHECK(out[j * 4] == j * 100 + rank);
    }
  }

  // Tagged p2p ring: send to right, recv from left.
  {
    int right = (rank + 1) % size;
    int left = (rank - 1 + size) % size;
    uint64_t v = rank, got = 0;
    auto sb = ctx.createUnboundBuffer(&v, sizeof(v));
    auto rb = ctx.createUnboundBuffer(&got, sizeof(got));
    rb->recv(left, 777);
    sb->send(right, 777);
    sb->waitSend(std::chrono::milliseconds(15000));
    rb->waitRecv(nullptr, std::chrono::milliseconds(15000));
    CHECK(got == uint64_t(left));
  }

  // Fork a child communicator over the parent and use it.
  {
    Context child(rank, size);
    child.forkFrom(ctx);
    std::vector<float> x(16, 2.0f);
    AllreduceOptions opts;
    opts.context = &child;
    opts.inputs = {x.data()};
    opts.outputs = {x.data()};
    opts.count = x.size();
    allreduce(opts);
    CHECK(x[0] == 2.0f * size);
    child.close();
  }

  ctx.close();
}

}  // namespace

int main() {
  const int size = 4;
  auto store = std::make_shared<tpucoll::HashStore>();
  std::vector<std::thread> threads;
  for (int r = 0; r < size; r++) {
    threads.emplace_back(worker, store, r, size);
  }
  for (auto& t : threads) {
    t.join();
  }
  if (failures == 0) {
    printf("tpucoll_integration: all checks passed\n");
    return 0;
  }
  fprintf(stderr, "tpucoll_integration: %d failure(s)\n", failures);
  return 1;
}
