// Pure C++ end-to-end integration: a 4-thread full mesh over a HashStore
// runs every collective, p2p messaging, a fork, and a graceful teardown —
// with no Python in the loop, so ASAN leak checking covers the whole
// library lifecycle (contexts, pairs, buffers, scratch, stores).
#include <cmath>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include <arpa/inet.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>

#include "tpucoll/collectives/collectives.h"
#include "tpucoll/common/debug.h"
#include "tpucoll/common/crypto.h"
#include "tpucoll/common/hmac.h"
#include "tpucoll/context.h"
#include "tpucoll/rendezvous/hash_store.h"
#include "tpucoll/transport/device.h"
#include "tpucoll/transport/wire.h"

namespace {

int failures = 0;

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);      \
      __atomic_fetch_add(&failures, 1, __ATOMIC_SEQ_CST);                  \
    }                                                                      \
  } while (0)

void worker(std::shared_ptr<tpucoll::Store> store, int rank, int size,
            tpucoll::transport::DeviceAttr attr = {}) {
  using namespace tpucoll;
  auto device = std::make_shared<transport::Device>(attr);
  Context ctx(rank, size);
  ctx.setTimeout(std::chrono::milliseconds(15000));
  ctx.connectFullMesh(store, device);

  // Allreduce across every algorithm.
  for (auto algo : {AllreduceAlgorithm::kRing,
                    AllreduceAlgorithm::kHalvingDoubling,
                    AllreduceAlgorithm::kBcube,
                    AllreduceAlgorithm::kRingBf16Wire}) {
    std::vector<float> x(1000, float(rank + 1));
    AllreduceOptions opts;
    opts.context = &ctx;
    opts.inputs = {x.data()};
    opts.outputs = {x.data()};
    opts.count = x.size();
    opts.algorithm = algo;
    opts.tag = static_cast<uint32_t>(algo);
    allreduce(opts);
    const float expect = size * (size + 1) / 2.0f;
    CHECK(x[0] == expect && x.back() == expect);
  }

  // q8-wire allreduce: tolerance-based (the int8 codec's decode of a
  // small-integer sum is within one quantization step but not exact),
  // plus the consensus contract — every rank's bytes identical —
  // checked via an allgather of the q8 result.
  {
    std::vector<float> x(1000, float(rank + 1));
    AllreduceOptions opts;
    opts.context = &ctx;
    opts.inputs = {x.data()};
    opts.outputs = {x.data()};
    opts.count = x.size();
    opts.algorithm = AllreduceAlgorithm::kRingQ8Wire;
    opts.tag = 40;
    allreduce(opts);
    const float expect = size * (size + 1) / 2.0f;
    // Per-hop bound: <= (hops) * max/254 per element; generous 2%.
    CHECK(std::fabs(x[0] - expect) <= 0.02f * expect);
    CHECK(std::fabs(x.back() - expect) <= 0.02f * expect);
    std::vector<float> all(x.size() * size);
    AllgatherOptions ag;
    ag.context = &ctx;
    ag.input = x.data();
    ag.output = all.data();
    ag.count = x.size();
    ag.tag = 41;
    allgather(ag);
    for (int r = 0; r < size; r++) {
      CHECK(std::memcmp(all.data() + size_t(r) * x.size(), x.data(),
                        x.size() * sizeof(float)) == 0);
    }
  }

  // Broadcast + barrier + allgather + reduce_scatter + alltoall.
  {
    std::vector<double> b(64, rank == 1 ? 42.0 : 0.0);
    BroadcastOptions opts;
    opts.context = &ctx;
    opts.buffer = b.data();
    opts.count = b.size();
    opts.dtype = DataType::kFloat64;
    opts.root = 1;
    broadcast(opts);
    CHECK(b[0] == 42.0);
  }
  {
    BarrierOptions opts;
    opts.context = &ctx;
    barrier(opts);
  }
  {
    std::vector<int32_t> in(10, rank), out(10 * size, -1);
    AllgatherOptions opts;
    opts.context = &ctx;
    opts.input = in.data();
    opts.output = out.data();
    opts.count = in.size();
    opts.dtype = DataType::kInt32;
    allgather(opts);
    for (int r = 0; r < size; r++) {
      CHECK(out[r * 10] == r);
    }
  }
  {
    std::vector<float> in(size * 8, 1.0f), out(8, 0.0f);
    ReduceScatterOptions opts;
    opts.context = &ctx;
    opts.input = in.data();
    opts.output = out.data();
    opts.recvCounts.assign(size, 8);
    reduceScatter(opts);
    CHECK(out[0] == float(size));
  }
  {
    std::vector<int64_t> in(size * 4), out(size * 4, -1);
    for (int j = 0; j < size; j++) {
      for (int k = 0; k < 4; k++) {
        in[j * 4 + k] = rank * 100 + j;
      }
    }
    AlltoallOptions opts;
    opts.context = &ctx;
    opts.input = in.data();
    opts.output = out.data();
    opts.count = 4;
    opts.dtype = DataType::kInt64;
    alltoall(opts);
    for (int j = 0; j < size; j++) {
      CHECK(out[j * 4] == j * 100 + rank);
    }
  }

  // Fused receive-reduce, straight on the transport API. Covers: the shm
  // ring path with a 24-byte element (ring chunks are powers of two, so
  // chunk boundaries split elements and exercise the carry buffer), the
  // eager TCP path (small payload), combine-from-stash (send lands before
  // the recvReduce posts; pair FIFO makes the ordering deterministic),
  // and the self-send short-circuit in both post orders.
  if (size >= 2) {
    struct Triple {
      double a, b, c;
    };
    static_assert(sizeof(Triple) == 24, "carry test needs a 24-byte element");
    auto addTriples = [](void* acc, const void* in, size_t n) {
      auto* A = static_cast<Triple*>(acc);
      auto* I = static_cast<const Triple*>(in);
      for (size_t i = 0; i < n; i++) {
        A[i].a += I[i].a;
        A[i].b += I[i].b;
        A[i].c += I[i].c;
      }
    };
    const auto tmo = std::chrono::milliseconds(15000);
    if (rank == 0) {
      // 3 MiB of triples: rides the shm ring in multiple chunks.
      const size_t n = 128 * 1024;
      std::vector<Triple> acc(n);
      for (size_t i = 0; i < n; i++) {
        acc[i] = {double(i), 1.0, -2.0};
      }
      auto buf = ctx.createUnboundBuffer(acc.data(), n * sizeof(Triple));
      buf->recvReduce(1, 900, addTriples, sizeof(Triple));
      buf->waitRecv(nullptr, tmo);
      bool ok = true;
      for (size_t i = 0; i < n && ok; i++) {
        ok = acc[i].a == double(2 * i) && acc[i].b == 4.0 && acc[i].c == 3.0;
      }
      CHECK(ok);
      // Small payload: eager TCP path (below any shm threshold).
      float small[8] = {1, 1, 1, 1, 1, 1, 1, 1};
      auto sbuf = ctx.createUnboundBuffer(small, sizeof(small));
      sbuf->recvReduce(1, 901, tpucoll::getReduceFn(DataType::kFloat32,
                                                    ReduceOp::kSum),
                       sizeof(float));
      sbuf->waitRecv(nullptr, tmo);
      CHECK(small[0] == 3.0f && small[7] == 3.0f);
      // Stash order: rank 1 sent slot 902 BEFORE the flag on 903; by pair
      // FIFO the 902 payload is already stashed when this recvReduce
      // posts, so the combine runs on the stash-hit path.
      int32_t flag = 0;
      auto fbuf = ctx.createUnboundBuffer(&flag, sizeof(flag));
      fbuf->recv(1, 903);
      fbuf->waitRecv(nullptr, tmo);
      double accd[4] = {10.0, 20.0, 30.0, 40.0};
      auto dbuf = ctx.createUnboundBuffer(accd, sizeof(accd));
      dbuf->recvReduce(1, 902, tpucoll::getReduceFn(DataType::kFloat64,
                                                    ReduceOp::kMax),
                       sizeof(double));
      dbuf->waitRecv(nullptr, tmo);
      CHECK(accd[0] == 10.0 && accd[1] == 25.0 && accd[2] == 30.0 &&
            accd[3] == 45.0);
    } else if (rank == 1) {
      const size_t n = 128 * 1024;
      std::vector<Triple> in(n);
      for (size_t i = 0; i < n; i++) {
        in[i] = {double(i), 3.0, 5.0};
      }
      auto buf = ctx.createUnboundBuffer(in.data(), n * sizeof(Triple));
      buf->send(0, 900);
      buf->waitSend(tmo);
      float small[8] = {2, 2, 2, 2, 2, 2, 2, 2};
      auto sbuf = ctx.createUnboundBuffer(small, sizeof(small));
      sbuf->send(0, 901);
      sbuf->waitSend(tmo);
      double vals[4] = {5.0, 25.0, 15.0, 45.0};
      auto dbuf = ctx.createUnboundBuffer(vals, sizeof(vals));
      dbuf->send(0, 902);  // stashes at rank 0 until its recvReduce posts
      int32_t flag = 1;
      auto fbuf = ctx.createUnboundBuffer(&flag, sizeof(flag));
      fbuf->send(0, 903);
      dbuf->waitSend(tmo);
      fbuf->waitSend(tmo);
    }
    // Self-send recvReduce, both post orders, on every rank.
    {
      int32_t acc[4] = {1, 2, 3, 4};
      int32_t inc[4] = {10, 10, 10, 10};
      auto abuf = ctx.createUnboundBuffer(acc, sizeof(acc));
      auto ibuf = ctx.createUnboundBuffer(inc, sizeof(inc));
      // recv posted first: postSend's matcher hit runs the combine.
      abuf->recvReduce(rank, 904, tpucoll::getReduceFn(DataType::kInt32,
                                                       ReduceOp::kSum),
                       sizeof(int32_t));
      ibuf->send(rank, 904);
      ibuf->waitSend(tmo);
      abuf->waitRecv(nullptr, tmo);
      // send first: combine runs on the stash-hit path inside postRecv.
      ibuf->send(rank, 905);
      ibuf->waitSend(tmo);
      abuf->recvReduce(rank, 905, tpucoll::getReduceFn(DataType::kInt32,
                                                       ReduceOp::kSum),
                       sizeof(int32_t));
      abuf->waitRecv(nullptr, tmo);
      CHECK(acc[0] == 21 && acc[3] == 24);
    }
  }

  // Tagged p2p ring: send to right, recv from left.
  {
    int right = (rank + 1) % size;
    int left = (rank - 1 + size) % size;
    uint64_t v = rank, got = 0;
    auto sb = ctx.createUnboundBuffer(&v, sizeof(v));
    auto rb = ctx.createUnboundBuffer(&got, sizeof(got));
    rb->recv(left, 777);
    sb->send(right, 777);
    sb->waitSend(std::chrono::milliseconds(15000));
    rb->waitRecv(nullptr, std::chrono::milliseconds(15000));
    CHECK(got == uint64_t(left));
  }

  // Fork a child communicator over the parent and use it.
  {
    Context child(rank, size);
    child.forkFrom(ctx);
    std::vector<float> x(16, 2.0f);
    AllreduceOptions opts;
    opts.context = &child;
    opts.inputs = {x.data()};
    opts.outputs = {x.data()};
    opts.count = x.size();
    allreduce(opts);
    CHECK(x[0] == 2.0f * size);
    child.close();
  }

  ctx.close();
}

}  // namespace

// Wire-level tamper scenario: a hand-rolled malicious peer that KNOWS the
// PSK completes the authenticated+encrypted handshake against a real
// context, proves it can deliver a correctly sealed message (positive
// control), then sends a frame with one flipped ciphertext byte — the
// victim pair must reject it with an authentication IoException instead
// of delivering corrupted plaintext.
void tamperScenario() {
  using namespace tpucoll;
  const std::string psk = "integration-psk";
  auto store = std::make_shared<HashStore>();

  std::thread victim([&] {
    transport::DeviceAttr attr;
    attr.authKey = psk;
    attr.encrypt = true;
    auto device = std::make_shared<transport::Device>(attr);
    Context ctx(0, 2);
    ctx.setTimeout(std::chrono::milliseconds(15000));
    ctx.connectFullMesh(store, device);
    std::vector<char> data(64, 0);
    {  // Positive control: a correctly sealed message lands intact.
      auto buf = ctx.createUnboundBuffer(data.data(), data.size());
      buf->recv(1, 7001);
      CHECK(buf->waitRecv(nullptr, std::chrono::milliseconds(15000)));
      CHECK(data[0] == 'A' && data[63] == 'A');
    }
    {  // Tampered frame: the recv must fail, not deliver. The pair may
       // already be poisoned by the time the recv is posted (the frame
       // races the post), so either recv() or waitRecv() may throw.
      bool threw = false;
      try {
        auto buf = ctx.createUnboundBuffer(data.data(), data.size());
        buf->recv(1, 7002);
        buf->waitRecv(nullptr, std::chrono::milliseconds(15000));
      } catch (const IoException& e) {
        threw = std::string(e.what()).find("authentication") !=
                std::string::npos;
      }
      CHECK(threw);
    }
  });

  // ---- the attacker-with-the-key ----
  // Play along with topology discovery first: the victim's
  // connectFullMesh blocks on every rank's host fingerprint before it
  // publishes its rank blob (group/topology.h).
  store->set("tc/topo/1", Store::Buf{'e', 'v', 'i', 'l'});
  // Read the victim's rank blob: [u32 n][u32 alen][addr][u64 pairId * n].
  auto blob = store->get("tc/rank/0", std::chrono::milliseconds(15000));
  uint32_t n32 = 0, alen = 0;
  std::memcpy(&n32, blob.data(), 4);
  std::memcpy(&alen, blob.data() + 4, 4);
  CHECK(n32 == 2);
  auto addr = transport::SockAddr::deserialize(blob.data() + 8, alen);
  uint64_t pairIds[2];
  std::memcpy(pairIds, blob.data() + 8 + alen, 16);
  const uint64_t pairId = pairIds[1];  // the victim's pair expecting us
  // Publish a throwaway rank-1 blob. Rank 0 never CONNECTS with it (it
  // only initiates toward lower ranks) but it does parse every peer blob
  // to validate the channel-count extension, so the throwaway must be
  // well-formed — the victim's own blob (right rank count, default
  // channel count) serves.
  store->set("tc/rank/1", blob);

  int fd = socket(addr.sa()->sa_family, SOCK_STREAM, 0);
  CHECK(fd >= 0);
  CHECK(::connect(fd, addr.sa(), addr.len) == 0);
  auto writeAll = [&](const void* p, size_t len) {
    const char* c = static_cast<const char*>(p);
    size_t done = 0;
    while (done < len) {
      ssize_t rv = ::send(fd, c + done, len - done, MSG_NOSIGNAL);
      CHECK(rv > 0);
      if (rv <= 0) return;
      done += size_t(rv);
    }
  };
  auto readAll = [&](void* p, size_t len) {
    char* c = static_cast<char*>(p);
    size_t done = 0;
    while (done < len) {
      ssize_t rv = ::recv(fd, c + done, len - done, 0);
      CHECK(rv > 0);
      if (rv <= 0) return;
      done += size_t(rv);
    }
  };

  // Authenticated+encrypted hello handshake (wire.h protocol).
  transport::WireHello hello{transport::kHelloAuthEncMagic, 0, pairId};
  writeAll(&hello, sizeof(hello));
  uint8_t nonceI[transport::kAuthNonceBytes];
  randomBytes(nonceI, sizeof(nonceI));
  writeAll(nonceI, sizeof(nonceI));
  uint8_t reply[transport::kAuthNonceBytes + transport::kAuthMacBytes];
  readAll(reply, sizeof(reply));
  auto transcript = [&](const char* role) {
    std::string msg(role);
    msg.append(reinterpret_cast<const char*>(&pairId), sizeof(pairId));
    msg.append(reinterpret_cast<const char*>(nonceI), sizeof(nonceI));
    msg.append(reinterpret_cast<const char*>(reply),
               transport::kAuthNonceBytes);
    return hmacSha256(psk.data(), psk.size(), msg.data(), msg.size());
  };
  auto srv = transcript("srv");
  CHECK(macEqual(reply + transport::kAuthNonceBytes, srv.data(), 32));
  auto cli = transcript("cli");
  writeAll(cli.data(), cli.size());
  auto keys = transport::deriveConnKeys(psk, pairId, nonceI, reply,
                                        /*initiator=*/true);

  uint64_t seq = 0;
  auto sendSealed = [&](uint64_t slot, const std::vector<char>& payload,
                        bool flipByte) {
    transport::WireHeader hdr{transport::kMsgMagic, 1 /* kData */,
                              0, {0, 0}, slot, payload.size(), 0};
    std::vector<uint8_t> frame(sizeof(hdr) + kAeadTagBytes +
                               payload.size() + kAeadTagBytes);
    aeadSeal(keys.tx, seq++, nullptr, 0,
             reinterpret_cast<const uint8_t*>(&hdr), sizeof(hdr),
             frame.data(), frame.data() + sizeof(hdr));
    uint8_t* c = frame.data() + sizeof(hdr) + kAeadTagBytes;
    aeadSeal(keys.tx, seq++, nullptr, 0,
             reinterpret_cast<const uint8_t*>(payload.data()),
             payload.size(), c, c + payload.size());
    if (flipByte) {
      c[3] ^= 1;
    }
    writeAll(frame.data(), frame.size());
  };

  std::vector<char> payload(64, 'A');
  sendSealed(7001, payload, /*flipByte=*/false);
  sendSealed(7002, payload, /*flipByte=*/true);

  victim.join();
  ::close(fd);
}

// Connect-retry diagnostics: a fake peer accepts and immediately closes
// every connection, so the initiator must retry with backoff, emit
// structured willRetry records, and finally surface an IoException —
// never a silent hang or an instant give-up.
void retryScenario() {
  using namespace tpucoll;
  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  CHECK(lfd >= 0);
  sockaddr_in sin{};
  sin.sin_family = AF_INET;
  sin.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  CHECK(bind(lfd, reinterpret_cast<sockaddr*>(&sin), sizeof(sin)) == 0);
  CHECK(listen(lfd, 16) == 0);
  sockaddr_in bound{};
  socklen_t blen = sizeof(bound);
  CHECK(getsockname(lfd, reinterpret_cast<sockaddr*>(&bound), &blen) == 0);

  std::atomic<bool> stop{false};
  std::thread closer([&] {
    while (!stop.load()) {
      int fd = accept(lfd, nullptr, nullptr);
      if (fd >= 0) {
        ::close(fd);  // slam the door: handshake EOF on the initiator
      }
    }
  });

  std::atomic<int> retryRecords{0};
  std::atomic<int> terminalRecords{0};
  setConnectDebugLogger([&](const ConnectDebugData& d) {
    if (d.willRetry) {
      retryRecords++;
    }
    if (!d.ok && !d.willRetry) {
      terminalRecords++;
    }
  });

  // Forge rank 0's blob pointing at the slammer; rank 1 initiates.
  auto addr = transport::resolve(
      "127.0.0.1", ntohs(bound.sin_port));
  auto addrBytes = addr.serialize();
  std::vector<uint8_t> blob;
  uint32_t n32 = 2, alen = addrBytes.size();
  blob.insert(blob.end(), reinterpret_cast<uint8_t*>(&n32),
              reinterpret_cast<uint8_t*>(&n32) + 4);
  blob.insert(blob.end(), reinterpret_cast<uint8_t*>(&alen),
              reinterpret_cast<uint8_t*>(&alen) + 4);
  blob.insert(blob.end(), addrBytes.begin(), addrBytes.end());
  uint64_t pairIds[2] = {100, 101};
  blob.insert(blob.end(), reinterpret_cast<uint8_t*>(pairIds),
              reinterpret_cast<uint8_t*>(pairIds) + 16);
  auto store = std::make_shared<HashStore>();
  store->set("tc/rank/0", blob);
  // Forged peer must also answer topology discovery, or the connect
  // timeout burns inside the fingerprint exchange instead of the
  // retry loop under test.
  store->set("tc/topo/0", Store::Buf{'f', 'a', 'k', 'e'});

  // PSK handshake: the initiator must READ the listener's challenge, so
  // the slammed connection surfaces as a retryable EOF (a plain hello is
  // write-only and would "succeed" into the doomed socket).
  transport::DeviceAttr attr;
  attr.authKey = "retry-psk";
  auto device = std::make_shared<transport::Device>(attr);
  Context ctx(1, 2);
  ctx.setTimeout(std::chrono::milliseconds(700));
  bool threw = false;
  try {
    ctx.connectFullMesh(store, device);
  } catch (const IoException&) {
    // Covers TimeoutException too: the deadline can expire inside an
    // attempt's handshake.
    threw = true;
  }
  CHECK(threw);
  CHECK(retryRecords.load() >= 2);  // ~700ms / 50ms backoff: plenty
  CHECK(terminalRecords.load() >= 1);  // the final attempt is recorded
  setConnectDebugLogger(nullptr);
  stop.store(true);
  ::shutdown(lfd, SHUT_RDWR);
  ::close(lfd);
  closer.join();
}

int main() {
  const int size = 4;
  auto store = std::make_shared<tpucoll::HashStore>();
  std::vector<std::thread> threads;
  for (int r = 0; r < size; r++) {
    threads.emplace_back(worker, store, r, size,
                         tpucoll::transport::DeviceAttr{});
  }
  for (auto& t : threads) {
    t.join();
  }

  // Encrypted full mesh: every collective again, over AEAD framing.
  {
    tpucoll::transport::DeviceAttr enc;
    enc.authKey = "integration-psk";
    enc.encrypt = true;
    auto encStore = std::make_shared<tpucoll::HashStore>();
    std::vector<std::thread> encThreads;
    for (int r = 0; r < size; r++) {
      encThreads.emplace_back(worker, encStore, r, size, enc);
    }
    for (auto& t : encThreads) {
      t.join();
    }
  }

  tamperScenario();
  retryScenario();
  if (failures == 0) {
    printf("tpucoll_integration: all checks passed\n");
    return 0;
  }
  fprintf(stderr, "tpucoll_integration: %d failure(s)\n", failures);
  return 1;
}
