// Native unit tests for pieces below the Python binding surface: slot
// arithmetic, dtype/reduction kernels (including the vector half paths),
// float16/bfloat16 conversions, and the HMAC-SHA256 vectors. The pytest
// suite covers everything above via the C API; this binary covers what it
// cannot observe directly. Exit code 0 = all passed.
#include <algorithm>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "tpucoll/common/crypto.h"
#include "tpucoll/common/hmac.h"
#include "tpucoll/common/sysinfo.h"
#include "tpucoll/math.h"
#include "tpucoll/types.h"

namespace {

int failures = 0;

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);      \
      failures++;                                                          \
    }                                                                      \
  } while (0)

void testSlot() {
  using tpucoll::Slot;
  using tpucoll::SlotPrefix;
  auto s = Slot::build(SlotPrefix::kAllreduce, 0xABCD);
  CHECK(s.value() >> 56 == uint64_t(SlotPrefix::kAllreduce));
  CHECK(((s.value() >> 24) & 0xFFFFFFFF) == 0xABCD);
  CHECK(s.offset(7).value() == s.value() + 7);
  bool threw = false;
  try {
    s.offset(uint64_t(1) << 24);
  } catch (const tpucoll::EnforceError&) {
    threw = true;
  }
  CHECK(threw);  // delta overflow must be rejected
}

void testHalfConversions() {
  using tpucoll::floatToHalf;
  using tpucoll::halfToFloat;
  // Exact round trips for representable values.
  for (float v : {0.0f, 1.0f, -2.5f, 65504.0f, 0.0009765625f}) {
    CHECK(halfToFloat(floatToHalf(v)) == v);
  }
  CHECK(std::isinf(halfToFloat(floatToHalf(1e6f))));     // overflow -> inf
  CHECK(halfToFloat(floatToHalf(1e-10f)) == 0.0f);       // underflow -> 0
  CHECK(std::isnan(halfToFloat(floatToHalf(NAN))));
  // bfloat16: round-to-nearest-even.
  using tpucoll::bfloat16ToFloat;
  using tpucoll::floatToBfloat16;
  CHECK(bfloat16ToFloat(floatToBfloat16(1.0f)) == 1.0f);
  CHECK(std::isnan(bfloat16ToFloat(floatToBfloat16(NAN))));
}

void testReduceKernels() {
  using tpucoll::DataType;
  using tpucoll::getReduceFn;
  using tpucoll::ReduceOp;
  // fp32 sum
  std::vector<float> a(1037, 1.5f), b(1037, 2.25f);
  getReduceFn(DataType::kFloat32, ReduceOp::kSum)(a.data(), b.data(),
                                                  a.size());
  for (float v : a) {
    CHECK(v == 3.75f);
  }
  // fp16 vector+tail path
  std::vector<uint16_t> ha(1037, tpucoll::floatToHalf(1.5f));
  std::vector<uint16_t> hb(1037, tpucoll::floatToHalf(2.25f));
  getReduceFn(DataType::kFloat16, ReduceOp::kSum)(ha.data(), hb.data(),
                                                  ha.size());
  for (uint16_t v : ha) {
    CHECK(tpucoll::halfToFloat(v) == 3.75f);
  }
  // bf16 vector+tail path
  std::vector<uint16_t> ba(1037, tpucoll::floatToBfloat16(1.5f));
  std::vector<uint16_t> bb(1037, tpucoll::floatToBfloat16(2.25f));
  getReduceFn(DataType::kBFloat16, ReduceOp::kSum)(ba.data(), bb.data(),
                                                   ba.size());
  for (uint16_t v : ba) {
    CHECK(tpucoll::bfloat16ToFloat(v) == 3.75f);
  }
  // int64 max
  std::vector<int64_t> ia{3, -5, 7}, ib{1, -2, 9};
  getReduceFn(DataType::kInt64, ReduceOp::kMax)(ia.data(), ib.data(), 3);
  CHECK(ia[0] == 3 && ia[1] == -2 && ia[2] == 9);
}

// min/max/product on the 16-bit float paths: the AVX2 vector body and
// the scalar tail must agree with the scalar widen-op-narrow reference
// on every lane — including negatives, +-0 ties (std::min/max keep the
// accumulator operand), product lanes that need round-to-nearest-even,
// and NaN lanes (which must stay NaN; payload bits are not contractual).
void testHalfMinMaxProdKernels() {
  using tpucoll::bfloat16ToFloat;
  using tpucoll::DataType;
  using tpucoll::floatToBfloat16;
  using tpucoll::floatToHalf;
  using tpucoll::getReduceFn;
  using tpucoll::halfToFloat;
  using tpucoll::ReduceOp;
  const size_t n = 41;  // 5 vector blocks + a scalar tail
  std::vector<float> af(n), bf(n);
  for (size_t i = 0; i < n; i++) {
    af[i] = (static_cast<float>(i) - 20.0f) * 0.375f;
    bf[i] = (20.0f - static_cast<float>(i)) * 0.4375f;
  }
  af[3] = 0.0f;
  bf[3] = -0.0f;  // signed-zero tie in a vector lane
  af[7] = NAN;    // NaN acc lane (vector)
  bf[11] = NAN;   // NaN input lane (vector)
  af[40] = NAN;   // NaN in the scalar tail
  // Product pair whose f32 result is not bf16/f16 representable, so the
  // narrowing must round (1.2109375 * 1.2109375 = 1.46636...).
  af[13] = 1.2109375f;
  bf[13] = 1.2109375f;
  struct Case {
    ReduceOp op;
    float (*ref)(float, float);
  };
  const Case cases[] = {
      {ReduceOp::kMin, [](float x, float y) { return std::min(x, y); }},
      {ReduceOp::kMax, [](float x, float y) { return std::max(x, y); }},
      {ReduceOp::kProduct, [](float x, float y) { return x * y; }},
  };
  for (const Case& c : cases) {
    // float16
    std::vector<uint16_t> ha(n), hb(n);
    for (size_t i = 0; i < n; i++) {
      ha[i] = floatToHalf(af[i]);
      hb[i] = floatToHalf(bf[i]);
    }
    std::vector<uint16_t> href = ha;
    for (size_t i = 0; i < n; i++) {
      href[i] = floatToHalf(
          c.ref(halfToFloat(href[i]), halfToFloat(hb[i])));
    }
    getReduceFn(DataType::kFloat16, c.op)(ha.data(), hb.data(), n);
    for (size_t i = 0; i < n; i++) {
      if (std::isnan(halfToFloat(href[i]))) {
        CHECK(std::isnan(halfToFloat(ha[i])));
      } else {
        CHECK(ha[i] == href[i]);
      }
    }
    // bfloat16
    std::vector<uint16_t> ba(n), bb(n);
    for (size_t i = 0; i < n; i++) {
      ba[i] = floatToBfloat16(af[i]);
      bb[i] = floatToBfloat16(bf[i]);
    }
    std::vector<uint16_t> bref = ba;
    for (size_t i = 0; i < n; i++) {
      bref[i] = floatToBfloat16(
          c.ref(bfloat16ToFloat(bref[i]), bfloat16ToFloat(bb[i])));
    }
    getReduceFn(DataType::kBFloat16, c.op)(ba.data(), bb.data(), n);
    for (size_t i = 0; i < n; i++) {
      if (std::isnan(bfloat16ToFloat(bref[i]))) {
        CHECK(std::isnan(bfloat16ToFloat(ba[i])));
      } else {
        CHECK(ba[i] == bref[i]);
      }
    }
  }
  // The signed-zero tie keeps the accumulator operand, exactly as
  // std::min/std::max do (min(+0, -0) == +0, max(+0, -0) == +0).
  std::vector<uint16_t> za{floatToHalf(0.0f)}, zb{floatToHalf(-0.0f)};
  getReduceFn(DataType::kFloat16, ReduceOp::kMin)(za.data(), zb.data(), 1);
  CHECK(za[0] == floatToHalf(0.0f));
}

void testBf16NanLanes() {
  using tpucoll::bfloat16ToFloat;
  using tpucoll::DataType;
  using tpucoll::f32StreamToBf16;
  using tpucoll::floatToBfloat16;
  using tpucoll::getReduceFn;
  using tpucoll::ReduceOp;
  // NaN payloads that defeat naive 0x7fff+lsb rounding: 0x7f800001 would
  // carry into +Inf, 0x7fffffff would wrap into -0.0. NaN lanes must stay
  // NaN in both the AVX2 body (first 8+ lanes) and the scalar tail, for
  // the f32->bf16 wire narrowing and the bf16 sum reduction alike.
  float sigNan, maxNan;
  uint32_t u1 = 0x7f800001u, u2 = 0x7fffffffu;
  std::memcpy(&sigNan, &u1, 4);
  std::memcpy(&maxNan, &u2, 4);
  std::vector<float> src(19, 1.0f);
  src[0] = sigNan;   // vector lane
  src[5] = maxNan;   // vector lane
  src[17] = sigNan;  // scalar tail lane
  std::vector<uint16_t> dst(src.size());
  f32StreamToBf16(src.data(), dst.data(), src.size());
  for (size_t i = 0; i < src.size(); i++) {
    if (std::isnan(src[i])) {
      CHECK(std::isnan(bfloat16ToFloat(dst[i])));
    } else {
      CHECK(bfloat16ToFloat(dst[i]) == 1.0f);
    }
  }
  // bf16 + bf16 sum where one side is NaN: NaN must propagate per-lane
  // identically in vector and tail regions.
  std::vector<uint16_t> acc(19, floatToBfloat16(1.0f));
  std::vector<uint16_t> in(19, floatToBfloat16(2.0f));
  in[1] = floatToBfloat16(sigNan);
  in[18] = floatToBfloat16(sigNan);
  getReduceFn(DataType::kBFloat16, ReduceOp::kSum)(acc.data(), in.data(),
                                                   acc.size());
  for (size_t i = 0; i < acc.size(); i++) {
    if (i == 1 || i == 18) {
      CHECK(std::isnan(bfloat16ToFloat(acc[i])));
    } else {
      CHECK(bfloat16ToFloat(acc[i]) == 3.0f);
    }
  }
}

void testQ8Codec() {
  using tpucoll::f32StreamToQ8;
  using tpucoll::q8StreamAccumulate;
  using tpucoll::q8StreamToF32;
  using tpucoll::q8WireBytes;
  const size_t block = 32;  // small block: exercises several units
  // Sizes straddling unit boundaries, including a ragged tail and a
  // sub-block stream.
  for (size_t n : {size_t(1), size_t(31), size_t(32), size_t(33),
                   size_t(100), size_t(96)}) {
    std::vector<float> src(n);
    uint64_t seed = 0x9E3779B97F4A7C15ull + n;
    for (size_t i = 0; i < n; i++) {
      seed = seed * 6364136223846793005ull + 1442695040888963407ull;
      // Mixed magnitudes, signs, exact zeros.
      src[i] = (int64_t(seed >> 33) % 2001 - 1000) / 7.0f;
    }
    std::vector<uint8_t> wire(q8WireBytes(n, block), 0xAB);
    f32StreamToQ8(src.data(), wire.data(), n, block);
    std::vector<float> dec(n);
    q8StreamToF32(wire.data(), dec.data(), n, block);
    for (size_t off = 0; off < n; off += block) {
      const size_t b = std::min(block, n - off);
      float maxAbs = 0.0f;
      for (size_t i = 0; i < b; i++) {
        maxAbs = std::max(maxAbs, std::fabs(src[off + i]));
      }
      const float bound = maxAbs / 254.0f * 1.000001f;
      for (size_t i = 0; i < b; i++) {
        CHECK(std::fabs(src[off + i] - dec[off + i]) <= bound);
      }
    }
    // Accumulate == decode + add, element-wise identical.
    std::vector<float> acc1(n, 0.5f), acc2(n, 0.5f);
    q8StreamAccumulate(acc1.data(), wire.data(), n, block);
    for (size_t i = 0; i < n; i++) {
      acc2[i] += dec[i];
      CHECK(acc1[i] == acc2[i]);
    }
  }
  // All-zero blocks are exactly representable (scale 0, zero codes).
  std::vector<float> zeros(70, 0.0f);
  std::vector<uint8_t> zwire(q8WireBytes(zeros.size(), block));
  f32StreamToQ8(zeros.data(), zwire.data(), zeros.size(), block);
  std::vector<float> zdec(zeros.size(), 1.0f);
  q8StreamToF32(zwire.data(), zdec.data(), zeros.size(), block);
  for (float v : zdec) {
    CHECK(v == 0.0f);
  }
  // The max element of every nonzero block always codes to ±127 (the
  // symmetric-scale invariant the error bound rests on).
  std::vector<float> one{3.5f, -7.0f, 1.0f};
  std::vector<uint8_t> owire(q8WireBytes(one.size(), block));
  f32StreamToQ8(one.data(), owire.data(), one.size(), block);
  CHECK(static_cast<int8_t>(owire[4 + 1]) == -127);
}

void testCryptoVectors() {
  using tpucoll::AeadKey;
  using tpucoll::aeadOpen;
  using tpucoll::aeadSeal;
  using tpucoll::hkdfSha256;
  using tpucoll::crypto_detail::chacha20Block;
  using tpucoll::crypto_detail::poly1305;

  auto unhex = [](const char* s) {
    std::vector<uint8_t> out;
    for (size_t i = 0; s[i] != '\0'; i += 2) {
      auto nib = [](char c) -> uint8_t {
        return c <= '9' ? c - '0' : (c | 0x20) - 'a' + 10;
      };
      out.push_back((nib(s[i]) << 4) | nib(s[i + 1]));
    }
    return out;
  };
  auto hex = [](const uint8_t* p, size_t n) {
    std::string out;
    for (size_t i = 0; i < n; i++) {
      char b[3];
      snprintf(b, 3, "%02x", p[i]);
      out += b;
    }
    return out;
  };

  // RFC 8439 2.3.2: ChaCha20 block function test vector.
  {
    auto key = unhex("000102030405060708090a0b0c0d0e0f"
                     "101112131415161718191a1b1c1d1e1f");
    auto nonce = unhex("000000090000004a00000000");
    uint8_t block[64];
    chacha20Block(key.data(), 1, nonce.data(), block);
    CHECK(hex(block, 16) == "10f1e7e4d13b5915500fdd1fa32071c4");
    CHECK(hex(block + 48, 16) == "b5129cd1de164eb9cbd083e8a2503c4e");
  }

  // RFC 8439 2.5.2: Poly1305 tag test vector.
  {
    auto key = unhex("85d6be7857556d337f4452fe42d506a8"
                     "0103808afb0db2fd4abff6af4149f51b");
    const char* msg = "Cryptographic Forum Research Group";
    uint8_t tag[16];
    poly1305(key.data(), reinterpret_cast<const uint8_t*>(msg),
             strlen(msg), tag);
    CHECK(hex(tag, 16) == "a8061dc1305136c6c22b8baf0c0127a9");
  }

  // RFC 8439 2.8.2: full AEAD test vector (96-bit nonce with a 32-bit
  // constant prefix — our seal() builds nonces as 4 zero bytes || seq,
  // so drive the layout-compatible parts directly through the tag path
  // by reproducing the seal with the RFC's nonce via the block fn).
  {
    auto key = unhex("808182838485868788898a8b8c8d8e8f"
                     "909192939495969798999a9b9c9d9e9f");
    AeadKey k;
    std::memcpy(k.bytes, key.data(), 32);
    auto aad = unhex("50515253c0c1c2c3c4c5c6c7");
    const char* pt = "Ladies and Gentlemen of the class of '99: "
                     "If I could offer you only one tip for the future, "
                     "sunscreen would be it.";
    const size_t n = strlen(pt);
    // Pin the exact RFC ciphertext+tag via the explicit-nonce hook.
    {
      auto nonce = unhex("070000004041424344454647");
      std::vector<uint8_t> rfcCt(n);
      uint8_t rfcTag[16];
      tpucoll::crypto_detail::aeadSealWithNonce(
          k, nonce.data(), aad.data(), aad.size(),
          reinterpret_cast<const uint8_t*>(pt), n, rfcCt.data(), rfcTag);
      CHECK(hex(rfcCt.data(), 16) == "d31a8d34648e60db7b86afbc53ef7ec2");
      CHECK(hex(rfcCt.data() + 96, 18) ==
            "3ff4def08e4b7a9de576d26586cec64b6116");
      CHECK(hex(rfcTag, 16) == "1ae10b594f09e26a7e902ecbd0600691");
    }
    // Then the transport's seq-derived nonce layout: round-trip + tamper.
    std::vector<uint8_t> ct(n), back(n);
    uint8_t tag[16];
    aeadSeal(k, 7, aad.data(), aad.size(),
             reinterpret_cast<const uint8_t*>(pt), n, ct.data(), tag);
    CHECK(aeadOpen(k, 7, aad.data(), aad.size(), ct.data(), n, back.data(),
                   tag));
    CHECK(std::memcmp(back.data(), pt, n) == 0);
    // Wrong seq (nonce) must fail.
    CHECK(!aeadOpen(k, 8, aad.data(), aad.size(), ct.data(), n, back.data(),
                    tag));
    // Flipped ciphertext byte must fail.
    ct[5] ^= 1;
    CHECK(!aeadOpen(k, 7, aad.data(), aad.size(), ct.data(), n, back.data(),
                    tag));
    ct[5] ^= 1;
    // Flipped tag byte must fail.
    tag[0] ^= 1;
    CHECK(!aeadOpen(k, 7, aad.data(), aad.size(), ct.data(), n, back.data(),
                    tag));
    tag[0] ^= 1;
    // Flipped aad byte must fail.
    aad[0] ^= 1;
    CHECK(!aeadOpen(k, 7, aad.data(), aad.size(), ct.data(), n, back.data(),
                    tag));
    // In-place decryption works.
    CHECK(aeadOpen(k, 7, unhex("50515253c0c1c2c3c4c5c6c7").data(), 12,
                   ct.data(), n, ct.data(), tag));
    CHECK(std::memcmp(ct.data(), pt, n) == 0);
  }

  // Long-message path: the AVX2 8-block keystream must match the scalar
  // block function exactly (the RFC vectors are all < 512 bytes and
  // never reach it). Build the expected keystream block-by-block.
  {
    AeadKey k;
    for (int i = 0; i < 32; i++) {
      k.bytes[i] = static_cast<uint8_t>(i * 7 + 1);
    }
    const size_t n = 8 * 512 + 137;  // several vector chunks + tail
    std::vector<uint8_t> pt(n);
    for (size_t i = 0; i < n; i++) {
      pt[i] = static_cast<uint8_t>(i * 13 + 5);
    }
    std::vector<uint8_t> ct(n), expect(n);
    uint8_t tag[16];
    aeadSeal(k, 42, nullptr, 0, pt.data(), n, ct.data(), tag);
    // Scalar reference: nonce = 4 zero bytes || seq le64; payload
    // keystream starts at counter 1.
    uint8_t nonce[12] = {0};
    uint64_t seq = 42;
    std::memcpy(nonce + 4, &seq, 8);
    for (size_t off = 0; off < n; off += 64) {
      uint8_t block[64];
      chacha20Block(k.bytes, 1 + static_cast<uint32_t>(off / 64), nonce,
                    block);
      for (size_t i = 0; i < 64 && off + i < n; i++) {
        expect[off + i] = pt[off + i] ^ block[i];
      }
    }
    CHECK(std::memcmp(ct.data(), expect.data(), n) == 0);
    std::vector<uint8_t> back(n);
    CHECK(aeadOpen(k, 42, nullptr, 0, ct.data(), n, back.data(), tag));
    CHECK(std::memcmp(back.data(), pt.data(), n) == 0);
  }

  // RFC 5869 A.1: HKDF-SHA256 test case 1.
  {
    auto ikm = unhex("0b0b0b0b0b0b0b0b0b0b0b0b0b0b0b0b0b0b0b0b0b0b");
    auto salt = unhex("000102030405060708090a0b0c");
    auto info = unhex("f0f1f2f3f4f5f6f7f8f9");
    uint8_t okm[42];
    hkdfSha256(ikm.data(), ikm.size(), salt.data(), salt.size(),
               info.data(), info.size(), okm, sizeof(okm));
    CHECK(hex(okm, 42) ==
          "3cb25f25faacd57a90434f64d0362f2a"
          "2d2d0a90cf1a5a4c5db02d56ecc4c5bf"
          "34007208d5b887185865");
  }
}

void testHmacVectors() {
  auto hex = [](const std::array<uint8_t, 32>& mac) {
    char buf[65];
    for (int i = 0; i < 32; i++) {
      snprintf(buf + 2 * i, 3, "%02x", mac[i]);
    }
    return std::string(buf);
  };
  CHECK(hex(tpucoll::sha256("abc", 3)) ==
        "ba7816bf8f01cfea414140de5dae2223"
        "b00361a396177a9cb410ff61f20015ad");
  CHECK(hex(tpucoll::hmacSha256("Jefe", 4,
                                "what do ya want for nothing?", 28)) ==
        "5bdcc146bf60754e6a042426089575c7"
        "5a003f089d2739839dec58b964ec3843");
  // Long-key path (key > block size gets hashed first).
  std::string longKey(131, 0xaa);
  std::string msg = "Test Using Larger Than Block-Size Key - Hash Key First";
  CHECK(hex(tpucoll::hmacSha256(longKey.data(), longKey.size(), msg.data(),
                                msg.size())) ==
        "60e431591ee0b67f0d8a26aacbf5b77f"
        "8e0bc6213728c5140546040f0ee37f54");
  // Constant-time compare behaves as equality.
  auto m1 = tpucoll::sha256("x", 1);
  auto m2 = m1;
  CHECK(tpucoll::macEqual(m1.data(), m2.data(), 32));
  m2[31] ^= 1;
  CHECK(!tpucoll::macEqual(m1.data(), m2.data(), 32));
}

// Topology probes degrade gracefully (no PCI NIC in containers): virtual
// interfaces report "", unknown ids report distance -1, identical ids 0.
void testSysinfoProbes() {
  CHECK(tpucoll::interfacePciBusId("lo").empty());
  CHECK(tpucoll::interfacePciBusId("").empty());
  CHECK(tpucoll::interfacePciBusId("definitely-not-an-iface").empty());
  CHECK(tpucoll::pciDistance("", "0000:00:00.0") == -1);
  CHECK(tpucoll::pciDistance("0000:00:00.0", "0000:00:00.0") == 0);
  CHECK(tpucoll::pciDistance("bogus", "alsobogus") == -1);
  // A NIC on a non-PCI leaf bus (virtio/usb) must report either a real
  // BDF ancestor or nothing — never a non-PCI token like "virtio3"
  // (observed on cloud VMs: /sys/class/net/eth0/device -> .../virtio3).
  for (const auto& iface : {std::string("eth0"), std::string("ens4")}) {
    const std::string id = tpucoll::interfacePciBusId(iface);
    CHECK(id.empty() || (id.size() == 12 && id[4] == ':' && id[7] == ':' &&
                         id[10] == '.'));
  }
}

}  // namespace

int main() {
  testSlot();
  testHalfConversions();
  testReduceKernels();
  testHalfMinMaxProdKernels();
  testBf16NanLanes();
  testQ8Codec();
  testHmacVectors();
  testCryptoVectors();
  testSysinfoProbes();
  if (failures == 0) {
    printf("tpucoll_unit: all tests passed\n");
    return 0;
  }
  fprintf(stderr, "tpucoll_unit: %d failure(s)\n", failures);
  return 1;
}
