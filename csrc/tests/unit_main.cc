// Native unit tests for pieces below the Python binding surface: slot
// arithmetic, dtype/reduction kernels (including the vector half paths),
// float16/bfloat16 conversions, and the HMAC-SHA256 vectors. The pytest
// suite covers everything above via the C API; this binary covers what it
// cannot observe directly. Exit code 0 = all passed.
#include <cmath>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "tpucoll/common/hmac.h"
#include "tpucoll/math.h"
#include "tpucoll/types.h"

namespace {

int failures = 0;

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);      \
      failures++;                                                          \
    }                                                                      \
  } while (0)

void testSlot() {
  using tpucoll::Slot;
  using tpucoll::SlotPrefix;
  auto s = Slot::build(SlotPrefix::kAllreduce, 0xABCD);
  CHECK(s.value() >> 56 == uint64_t(SlotPrefix::kAllreduce));
  CHECK(((s.value() >> 24) & 0xFFFFFFFF) == 0xABCD);
  CHECK(s.offset(7).value() == s.value() + 7);
  bool threw = false;
  try {
    s.offset(uint64_t(1) << 24);
  } catch (const tpucoll::EnforceError&) {
    threw = true;
  }
  CHECK(threw);  // delta overflow must be rejected
}

void testHalfConversions() {
  using tpucoll::floatToHalf;
  using tpucoll::halfToFloat;
  // Exact round trips for representable values.
  for (float v : {0.0f, 1.0f, -2.5f, 65504.0f, 0.0009765625f}) {
    CHECK(halfToFloat(floatToHalf(v)) == v);
  }
  CHECK(std::isinf(halfToFloat(floatToHalf(1e6f))));     // overflow -> inf
  CHECK(halfToFloat(floatToHalf(1e-10f)) == 0.0f);       // underflow -> 0
  CHECK(std::isnan(halfToFloat(floatToHalf(NAN))));
  // bfloat16: round-to-nearest-even.
  using tpucoll::bfloat16ToFloat;
  using tpucoll::floatToBfloat16;
  CHECK(bfloat16ToFloat(floatToBfloat16(1.0f)) == 1.0f);
  CHECK(std::isnan(bfloat16ToFloat(floatToBfloat16(NAN))));
}

void testReduceKernels() {
  using tpucoll::DataType;
  using tpucoll::getReduceFn;
  using tpucoll::ReduceOp;
  // fp32 sum
  std::vector<float> a(1037, 1.5f), b(1037, 2.25f);
  getReduceFn(DataType::kFloat32, ReduceOp::kSum)(a.data(), b.data(),
                                                  a.size());
  for (float v : a) {
    CHECK(v == 3.75f);
  }
  // fp16 vector+tail path
  std::vector<uint16_t> ha(1037, tpucoll::floatToHalf(1.5f));
  std::vector<uint16_t> hb(1037, tpucoll::floatToHalf(2.25f));
  getReduceFn(DataType::kFloat16, ReduceOp::kSum)(ha.data(), hb.data(),
                                                  ha.size());
  for (uint16_t v : ha) {
    CHECK(tpucoll::halfToFloat(v) == 3.75f);
  }
  // bf16 vector+tail path
  std::vector<uint16_t> ba(1037, tpucoll::floatToBfloat16(1.5f));
  std::vector<uint16_t> bb(1037, tpucoll::floatToBfloat16(2.25f));
  getReduceFn(DataType::kBFloat16, ReduceOp::kSum)(ba.data(), bb.data(),
                                                   ba.size());
  for (uint16_t v : ba) {
    CHECK(tpucoll::bfloat16ToFloat(v) == 3.75f);
  }
  // int64 max
  std::vector<int64_t> ia{3, -5, 7}, ib{1, -2, 9};
  getReduceFn(DataType::kInt64, ReduceOp::kMax)(ia.data(), ib.data(), 3);
  CHECK(ia[0] == 3 && ia[1] == -2 && ia[2] == 9);
}

void testBf16NanLanes() {
  using tpucoll::bfloat16ToFloat;
  using tpucoll::DataType;
  using tpucoll::f32StreamToBf16;
  using tpucoll::floatToBfloat16;
  using tpucoll::getReduceFn;
  using tpucoll::ReduceOp;
  // NaN payloads that defeat naive 0x7fff+lsb rounding: 0x7f800001 would
  // carry into +Inf, 0x7fffffff would wrap into -0.0. NaN lanes must stay
  // NaN in both the AVX2 body (first 8+ lanes) and the scalar tail, for
  // the f32->bf16 wire narrowing and the bf16 sum reduction alike.
  float sigNan, maxNan;
  uint32_t u1 = 0x7f800001u, u2 = 0x7fffffffu;
  std::memcpy(&sigNan, &u1, 4);
  std::memcpy(&maxNan, &u2, 4);
  std::vector<float> src(19, 1.0f);
  src[0] = sigNan;   // vector lane
  src[5] = maxNan;   // vector lane
  src[17] = sigNan;  // scalar tail lane
  std::vector<uint16_t> dst(src.size());
  f32StreamToBf16(src.data(), dst.data(), src.size());
  for (size_t i = 0; i < src.size(); i++) {
    if (std::isnan(src[i])) {
      CHECK(std::isnan(bfloat16ToFloat(dst[i])));
    } else {
      CHECK(bfloat16ToFloat(dst[i]) == 1.0f);
    }
  }
  // bf16 + bf16 sum where one side is NaN: NaN must propagate per-lane
  // identically in vector and tail regions.
  std::vector<uint16_t> acc(19, floatToBfloat16(1.0f));
  std::vector<uint16_t> in(19, floatToBfloat16(2.0f));
  in[1] = floatToBfloat16(sigNan);
  in[18] = floatToBfloat16(sigNan);
  getReduceFn(DataType::kBFloat16, ReduceOp::kSum)(acc.data(), in.data(),
                                                   acc.size());
  for (size_t i = 0; i < acc.size(); i++) {
    if (i == 1 || i == 18) {
      CHECK(std::isnan(bfloat16ToFloat(acc[i])));
    } else {
      CHECK(bfloat16ToFloat(acc[i]) == 3.0f);
    }
  }
}

void testHmacVectors() {
  auto hex = [](const std::array<uint8_t, 32>& mac) {
    char buf[65];
    for (int i = 0; i < 32; i++) {
      snprintf(buf + 2 * i, 3, "%02x", mac[i]);
    }
    return std::string(buf);
  };
  CHECK(hex(tpucoll::sha256("abc", 3)) ==
        "ba7816bf8f01cfea414140de5dae2223"
        "b00361a396177a9cb410ff61f20015ad");
  CHECK(hex(tpucoll::hmacSha256("Jefe", 4,
                                "what do ya want for nothing?", 28)) ==
        "5bdcc146bf60754e6a042426089575c7"
        "5a003f089d2739839dec58b964ec3843");
  // Long-key path (key > block size gets hashed first).
  std::string longKey(131, 0xaa);
  std::string msg = "Test Using Larger Than Block-Size Key - Hash Key First";
  CHECK(hex(tpucoll::hmacSha256(longKey.data(), longKey.size(), msg.data(),
                                msg.size())) ==
        "60e431591ee0b67f0d8a26aacbf5b77f"
        "8e0bc6213728c5140546040f0ee37f54");
  // Constant-time compare behaves as equality.
  auto m1 = tpucoll::sha256("x", 1);
  auto m2 = m1;
  CHECK(tpucoll::macEqual(m1.data(), m2.data(), 32));
  m2[31] ^= 1;
  CHECK(!tpucoll::macEqual(m1.data(), m2.data(), 32));
}

}  // namespace

int main() {
  testSlot();
  testHalfConversions();
  testReduceKernels();
  testBf16NanLanes();
  testHmacVectors();
  if (failures == 0) {
    printf("tpucoll_unit: all tests passed\n");
    return 0;
  }
  fprintf(stderr, "tpucoll_unit: %d failure(s)\n", failures);
  return 1;
}
