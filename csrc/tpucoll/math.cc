#include "tpucoll/math.h"

#include <algorithm>
#include <cmath>
#include <cstring>
#include <type_traits>

#include "tpucoll/common/env.h"

#if defined(__AVX2__) && defined(__F16C__)
#include <immintrin.h>
#define TC_HAVE_VECTOR_HALF 1
#endif

namespace tpucoll {

float halfToFloat(uint16_t h) {
  uint32_t sign = static_cast<uint32_t>(h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t mant = h & 0x3ffu;
  uint32_t u;
  if (exp == 0) {
    if (mant == 0) {
      u = sign;  // +-0
    } else {
      // Subnormal: normalize.
      int shift = 0;
      while ((mant & 0x400u) == 0) {
        mant <<= 1;
        shift++;
      }
      mant &= 0x3ffu;
      u = sign | ((127 - 15 - shift + 1) << 23) | (mant << 13);
    }
  } else if (exp == 31) {
    u = sign | 0x7f800000u | (mant << 13);  // inf / nan
  } else {
    u = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float f;
  std::memcpy(&f, &u, 4);
  return f;
}

uint16_t floatToHalf(float f) {
  uint32_t u;
  std::memcpy(&u, &f, 4);
  uint32_t sign = (u >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((u >> 23) & 0xff) - 127 + 15;
  uint32_t mant = u & 0x7fffffu;
  if (((u >> 23) & 0xff) == 0xff) {
    // inf / nan
    return static_cast<uint16_t>(sign | 0x7c00u | (mant ? 0x200u : 0));
  }
  if (exp >= 31) {
    return static_cast<uint16_t>(sign | 0x7c00u);  // overflow -> inf
  }
  if (exp <= 0) {
    if (exp < -10) {
      return static_cast<uint16_t>(sign);  // underflow -> 0
    }
    // Subnormal half: shift with round-to-nearest-even.
    mant |= 0x800000u;
    int shift = 14 - exp;
    uint32_t q = mant >> shift;
    uint32_t rem = mant & ((1u << shift) - 1);
    uint32_t half = 1u << (shift - 1);
    if (rem > half || (rem == half && (q & 1))) {
      q++;
    }
    return static_cast<uint16_t>(sign | q);
  }
  // Normal: round mantissa 23 -> 10 bits, nearest-even.
  uint32_t q = mant >> 13;
  uint32_t rem = mant & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (q & 1))) {
    q++;
    if (q == 0x400u) {
      q = 0;
      exp++;
      if (exp >= 31) {
        return static_cast<uint16_t>(sign | 0x7c00u);
      }
    }
  }
  return static_cast<uint16_t>(sign | (static_cast<uint32_t>(exp) << 10) | q);
}

uint16_t floatToBfloat16(float f) {
  uint32_t u;
  std::memcpy(&u, &f, 4);
  if ((u & 0x7f800000u) == 0x7f800000u && (u & 0x7fffffu)) {
    return static_cast<uint16_t>((u >> 16) | 0x40u);  // quiet nan
  }
  uint32_t lsb = (u >> 16) & 1;
  u += 0x7fffu + lsb;  // round to nearest even
  return static_cast<uint16_t>(u >> 16);
}

namespace {

// applyVec mirrors apply on 8 f32 lanes (instantiated only for the
// half/bfloat16 widen-reduce-narrow paths, which accumulate in float).
// Min/max operand order is deliberate: std::min(a, b) returns `a` on a
// tie OR when either operand is NaN (the comparison is false), while
// _mm256_min_ps(x, y) returns `y` in those cases — so the vector forms
// pass (b, a) to keep tie/NaN selection identical to the scalar tail.
template <typename T>
struct OpSum {
  static T apply(T a, T b) { return a + b; }
#ifdef TC_HAVE_VECTOR_HALF
  static __m256 applyVec(__m256 a, __m256 b) { return _mm256_add_ps(a, b); }
#endif
};
template <typename T>
struct OpProd {
  static T apply(T a, T b) { return a * b; }
#ifdef TC_HAVE_VECTOR_HALF
  static __m256 applyVec(__m256 a, __m256 b) { return _mm256_mul_ps(a, b); }
#endif
};
template <typename T>
struct OpMin {
  static T apply(T a, T b) { return std::min(a, b); }
#ifdef TC_HAVE_VECTOR_HALF
  static __m256 applyVec(__m256 a, __m256 b) { return _mm256_min_ps(b, a); }
#endif
};
template <typename T>
struct OpMax {
  static T apply(T a, T b) { return std::max(a, b); }
#ifdef TC_HAVE_VECTOR_HALF
  static __m256 applyVec(__m256 a, __m256 b) { return _mm256_max_ps(b, a); }
#endif
};

template <typename T, template <typename> class Op>
void reduceTyped(void* acc, const void* in, size_t n) {
  T* a = static_cast<T*>(acc);
  const T* b = static_cast<const T*>(in);
  for (size_t i = 0; i < n; i++) {
    a[i] = Op<T>::apply(a[i], b[i]);
  }
}

// float16/bfloat16: widen to float, reduce, narrow — all four ops on the
// AVX2/F16C vector path (reference analog: the F16C-vectorized fp16
// reductions in gloo/math.cc:21-98). Narrowing is round-to-nearest-even
// for sum/product; min/max select one of the (exactly representable)
// operands, so their narrowing is exact by construction. A Pallas/VPU
// path handles the on-device case, so this host path only sees staging
// buffers.

#ifdef TC_HAVE_VECTOR_HALF
// Narrow 8 f32 lanes to bf16 with round-to-nearest-even. NaN lanes must
// bypass the rounding bias: 0x7fff+lsb can carry into the exponent and
// turn a NaN into +Inf (0x7f800001) or wrap into -0.0 (0x7fffffff), so
// unordered lanes blend in the same quieted-NaN value the scalar
// floatToBfloat16 produces ((bits>>16)|0x40).
inline __m128i f32x8ToBf16Rne(__m256 v) {
  __m256i bits = _mm256_castps_si256(v);
  __m256i lsb = _mm256_and_si256(_mm256_srli_epi32(bits, 16),
                                 _mm256_set1_epi32(1));
  __m256i rounded = _mm256_add_epi32(
      _mm256_add_epi32(bits, _mm256_set1_epi32(0x7fff)), lsb);
  __m256i hi = _mm256_srli_epi32(rounded, 16);
  __m256i nanHi = _mm256_or_si256(_mm256_srli_epi32(bits, 16),
                                  _mm256_set1_epi32(0x40));
  __m256i isNan = _mm256_castps_si256(_mm256_cmp_ps(v, v, _CMP_UNORD_Q));
  hi = _mm256_blendv_epi8(hi, nanHi, isNan);
  __m256i packed = _mm256_packus_epi32(hi, _mm256_setzero_si256());
  packed = _mm256_permute4x64_epi64(packed, 0x08);
  return _mm256_castsi256_si128(packed);
}

#endif  // TC_HAVE_VECTOR_HALF

template <template <typename> class Op>
void reduceHalf(void* acc, const void* in, size_t n) {
  uint16_t* a = static_cast<uint16_t*>(acc);
  const uint16_t* b = static_cast<const uint16_t*>(in);
  size_t i = 0;
#ifdef TC_HAVE_VECTOR_HALF
  for (; i + 8 <= n; i += 8) {
    __m256 fa = _mm256_cvtph_ps(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(a + i)));
    __m256 fb = _mm256_cvtph_ps(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(b + i)));
    __m128i packed = _mm256_cvtps_ph(Op<float>::applyVec(fa, fb),
                                     _MM_FROUND_TO_NEAREST_INT);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(a + i), packed);
  }
#endif
  for (; i < n; i++) {
    a[i] = floatToHalf(Op<float>::apply(halfToFloat(a[i]), halfToFloat(b[i])));
  }
}

template <template <typename> class Op>
void reduceBf16(void* acc, const void* in, size_t n) {
  uint16_t* a = static_cast<uint16_t*>(acc);
  const uint16_t* b = static_cast<const uint16_t*>(in);
  size_t i = 0;
#ifdef TC_HAVE_VECTOR_HALF
  for (; i + 8 <= n; i += 8) {
    // Widen bf16 -> f32: zero-extend to u32, shift into the high half.
    __m256i wa = _mm256_slli_epi32(
        _mm256_cvtepu16_epi32(_mm_loadu_si128(
            reinterpret_cast<const __m128i*>(a + i))), 16);
    __m256i wb = _mm256_slli_epi32(
        _mm256_cvtepu16_epi32(_mm_loadu_si128(
            reinterpret_cast<const __m128i*>(b + i))), 16);
    __m256 combined = Op<float>::applyVec(_mm256_castsi256_ps(wa),
                                          _mm256_castsi256_ps(wb));
    // f32x8ToBf16Rne is exact for min/max (the selected operand is a
    // widened bf16, so the RNE bias adds nothing) and RNE for
    // sum/product, with the scalar-identical quiet-NaN blend.
    _mm_storeu_si128(reinterpret_cast<__m128i*>(a + i),
                     f32x8ToBf16Rne(combined));
  }
#endif
  for (; i < n; i++) {
    a[i] = floatToBfloat16(
        Op<float>::apply(bfloat16ToFloat(a[i]), bfloat16ToFloat(b[i])));
  }
}

template <typename T>
ReduceFn pickOp(ReduceOp op) {
  switch (op) {
    case ReduceOp::kSum:
      return &reduceTyped<T, OpSum>;
    case ReduceOp::kProduct:
      return &reduceTyped<T, OpProd>;
    case ReduceOp::kMin:
      return &reduceTyped<T, OpMin>;
    case ReduceOp::kMax:
      return &reduceTyped<T, OpMax>;
  }
  TC_THROW(EnforceError, "unknown reduce op");
}

ReduceFn pickHalfOp(ReduceOp op) {
  switch (op) {
    case ReduceOp::kSum:
      return &reduceHalf<OpSum>;
    case ReduceOp::kProduct:
      return &reduceHalf<OpProd>;
    case ReduceOp::kMin:
      return &reduceHalf<OpMin>;
    case ReduceOp::kMax:
      return &reduceHalf<OpMax>;
  }
  TC_THROW(EnforceError, "unknown reduce op");
}

ReduceFn pickBf16Op(ReduceOp op) {
  switch (op) {
    case ReduceOp::kSum:
      return &reduceBf16<OpSum>;
    case ReduceOp::kProduct:
      return &reduceBf16<OpProd>;
    case ReduceOp::kMin:
      return &reduceBf16<OpMin>;
    case ReduceOp::kMax:
      return &reduceBf16<OpMax>;
  }
  TC_THROW(EnforceError, "unknown reduce op");
}

}  // namespace

void f32StreamToBf16(const float* src, uint16_t* dst, size_t n) {
  size_t i = 0;
#ifdef TC_HAVE_VECTOR_HALF
  for (; i + 8 <= n; i += 8) {
    _mm_storeu_si128(reinterpret_cast<__m128i*>(dst + i),
                     f32x8ToBf16Rne(_mm256_loadu_ps(src + i)));
  }
#endif
  for (; i < n; i++) {
    dst[i] = floatToBfloat16(src[i]);
  }
}

void bf16StreamToF32(const uint16_t* src, float* dst, size_t n) {
  size_t i = 0;
#ifdef TC_HAVE_VECTOR_HALF
  for (; i + 8 <= n; i += 8) {
    __m256i w = _mm256_slli_epi32(
        _mm256_cvtepu16_epi32(_mm_loadu_si128(
            reinterpret_cast<const __m128i*>(src + i))), 16);
    _mm256_storeu_ps(dst + i, _mm256_castsi256_ps(w));
  }
#endif
  for (; i < n; i++) {
    dst[i] = bfloat16ToFloat(src[i]);
  }
}

void bf16StreamAccumulate(float* dst, const uint16_t* src, size_t n) {
  size_t i = 0;
#ifdef TC_HAVE_VECTOR_HALF
  for (; i + 8 <= n; i += 8) {
    __m256i w = _mm256_slli_epi32(
        _mm256_cvtepu16_epi32(_mm_loadu_si128(
            reinterpret_cast<const __m128i*>(src + i))), 16);
    _mm256_storeu_ps(dst + i, _mm256_add_ps(_mm256_loadu_ps(dst + i),
                                            _mm256_castsi256_ps(w)));
  }
#endif
  for (; i < n; i++) {
    dst[i] += bfloat16ToFloat(src[i]);
  }
}

// ---- int8 block-quantized wire codec (math.h for the stream layout) ----

// The codec's documented arithmetic is mul-THEN-add (two roundings):
// GCC's default -ffp-contract=fast would fuse both the scalar tails and
// the explicit _mm256_mul_ps/_mm256_add_ps pairs into FMAs, silently
// changing the accumulate's rounding vs a decode-then-add (and vs
// clang-built or no-FMA-ISA peers). Pin contraction off for the codec
// functions so `q8StreamAccumulate == q8StreamToF32 + add` holds
// exactly (unit-tested) on every build of one ISA generation.
#if defined(__GNUC__) && !defined(__clang__)
#define TC_Q8_NO_FP_CONTRACT __attribute__((optimize("fp-contract=off")))
#else
// clang defaults to ISO contraction (never across statements), which
// already preserves the mul-then-add shape used here.
#define TC_Q8_NO_FP_CONTRACT
#endif

size_t q8BlockElems() {
  static const size_t block = static_cast<size_t>(
      envCount("TPUCOLL_Q8_BLOCK", 256, 8,
               static_cast<long>(kQ8MaxBlockElems)));
  return block;
}

namespace {

#ifndef TC_HAVE_VECTOR_HALF
// Scalar quantize of one block: the reference semantics the vector path
// must match byte-for-byte. nearbyintf under the default FE_TONEAREST
// mode is round-half-to-even, the same rounding
// _mm256_round_ps(NEAREST) uses.
TC_Q8_NO_FP_CONTRACT
inline void q8EncodeBlockScalar(const float* src, uint8_t* dst, size_t n) {
  float maxAbs = 0.0f;
  for (size_t i = 0; i < n; i++) {
    maxAbs = std::max(maxAbs, std::fabs(src[i]));
  }
  const float scale = maxAbs / 127.0f;
  std::memcpy(dst, &scale, kQ8ScaleBytes);
  int8_t* codes = reinterpret_cast<int8_t*>(dst + kQ8ScaleBytes);
  if (scale == 0.0f) {
    std::memset(codes, 0, n);
    return;
  }
  for (size_t i = 0; i < n; i++) {
    // The max element can land on ±128 when the scale division rounds
    // down; clip keeps codes in the symmetric ±127 range.
    int q = static_cast<int>(nearbyintf(src[i] / scale));
    q = std::min(127, std::max(-127, q));
    codes[i] = static_cast<int8_t>(q);
  }
}

template <bool accumulate>
TC_Q8_NO_FP_CONTRACT
inline void q8DecodeBlockScalar(float* acc, const uint8_t* unit, size_t n) {
  float scale;
  std::memcpy(&scale, unit, kQ8ScaleBytes);
  const int8_t* codes = reinterpret_cast<const int8_t*>(unit +
                                                        kQ8ScaleBytes);
  for (size_t i = 0; i < n; i++) {
    const float v = static_cast<float>(codes[i]) * scale;
    acc[i] = accumulate ? acc[i] + v : v;
  }
}
#endif  // !TC_HAVE_VECTOR_HALF

#ifdef TC_HAVE_VECTOR_HALF

inline float hmax8(__m256 v) {
  __m128 lo = _mm256_castps256_ps128(v);
  __m128 hi = _mm256_extractf128_ps(v, 1);
  __m128 m = _mm_max_ps(lo, hi);
  m = _mm_max_ps(m, _mm_movehl_ps(m, m));
  m = _mm_max_ss(m, _mm_shuffle_ps(m, m, 1));
  return _mm_cvtss_f32(m);
}

// Vector quantize of one block. Identical bytes to the scalar path:
// max over |x| is order-insensitive, the per-element work is a genuine
// IEEE division (not a reciprocal multiply) with round-to-nearest-even,
// and the clip happens on the converted int32 lanes.
TC_Q8_NO_FP_CONTRACT
inline void q8EncodeBlockVec(const float* src, uint8_t* dst, size_t n) {
  const __m256 absMask = _mm256_castsi256_ps(_mm256_set1_epi32(0x7fffffff));
  __m256 vmax = _mm256_setzero_ps();
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    vmax = _mm256_max_ps(vmax, _mm256_and_ps(_mm256_loadu_ps(src + i),
                                             absMask));
  }
  float maxAbs = hmax8(vmax);
  for (; i < n; i++) {
    maxAbs = std::max(maxAbs, std::fabs(src[i]));
  }
  const float scale = maxAbs / 127.0f;
  std::memcpy(dst, &scale, kQ8ScaleBytes);
  int8_t* codes = reinterpret_cast<int8_t*>(dst + kQ8ScaleBytes);
  if (scale == 0.0f) {
    std::memset(codes, 0, n);
    return;
  }
  const __m256 vscale = _mm256_set1_ps(scale);
  const __m256i lim = _mm256_set1_epi32(127);
  const __m256i nlim = _mm256_set1_epi32(-127);
  i = 0;
  for (; i + 8 <= n; i += 8) {
    __m256 q = _mm256_round_ps(
        _mm256_div_ps(_mm256_loadu_ps(src + i), vscale),
        _MM_FROUND_TO_NEAREST_INT | _MM_FROUND_NO_EXC);
    __m256i qi = _mm256_min_epi32(_mm256_max_epi32(_mm256_cvtps_epi32(q),
                                                   nlim), lim);
    // 8 x int32 -> 8 x int8: pack within 128-bit lanes, then stitch.
    __m128i lo = _mm256_castsi256_si128(qi);
    __m128i hi = _mm256_extracti128_si256(qi, 1);
    __m128i p16 = _mm_packs_epi32(lo, hi);
    __m128i p8 = _mm_packs_epi16(p16, p16);
    _mm_storel_epi64(reinterpret_cast<__m128i*>(codes + i), p8);
  }
  for (; i < n; i++) {
    int q = static_cast<int>(nearbyintf(src[i] / scale));
    q = std::min(127, std::max(-127, q));
    codes[i] = static_cast<int8_t>(q);
  }
}

// acc[i] (+)= codes[i] * scale over one block: accumulate=true folds,
// false overwrites (pure decode). Mul then add — never FMA — so the
// vector result equals the scalar fallback bit-for-bit.
template <bool accumulate>
TC_Q8_NO_FP_CONTRACT
inline void q8DecodeBlockVec(float* acc, const uint8_t* unit, size_t n) {
  float scale;
  std::memcpy(&scale, unit, kQ8ScaleBytes);
  const int8_t* codes = reinterpret_cast<const int8_t*>(unit +
                                                        kQ8ScaleBytes);
  const __m256 vscale = _mm256_set1_ps(scale);
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    __m256i qi = _mm256_cvtepi8_epi32(
        _mm_loadl_epi64(reinterpret_cast<const __m128i*>(codes + i)));
    __m256 v = _mm256_mul_ps(_mm256_cvtepi32_ps(qi), vscale);
    if (accumulate) {
      v = _mm256_add_ps(_mm256_loadu_ps(acc + i), v);
    }
    _mm256_storeu_ps(acc + i, v);
  }
  for (; i < n; i++) {
    const float v = static_cast<float>(codes[i]) * scale;
    acc[i] = accumulate ? acc[i] + v : v;
  }
}

#endif  // TC_HAVE_VECTOR_HALF

}  // namespace

TC_Q8_NO_FP_CONTRACT
void f32StreamToQ8(const float* src, uint8_t* dst, size_t n, size_t block) {
  for (size_t off = 0; off < n; off += block) {
    const size_t b = std::min(block, n - off);
#ifdef TC_HAVE_VECTOR_HALF
    q8EncodeBlockVec(src + off, dst, b);
#else
    q8EncodeBlockScalar(src + off, dst, b);
#endif
    dst += q8UnitBytes(b);
  }
}

TC_Q8_NO_FP_CONTRACT
void q8StreamToF32(const uint8_t* src, float* dst, size_t n, size_t block) {
  for (size_t off = 0; off < n; off += block) {
    const size_t b = std::min(block, n - off);
#ifdef TC_HAVE_VECTOR_HALF
    q8DecodeBlockVec<false>(dst + off, src, b);
#else
    q8DecodeBlockScalar<false>(dst + off, src, b);
#endif
    src += q8UnitBytes(b);
  }
}

TC_Q8_NO_FP_CONTRACT
void q8StreamAccumulate(float* dst, const uint8_t* src, size_t n,
                        size_t block) {
  for (size_t off = 0; off < n; off += block) {
    const size_t b = std::min(block, n - off);
#ifdef TC_HAVE_VECTOR_HALF
    q8DecodeBlockVec<true>(dst + off, src, b);
#else
    q8DecodeBlockScalar<true>(dst + off, src, b);
#endif
    src += q8UnitBytes(b);
  }
}

// ---- int4 block-quantized wire codec (math.h for the stream layout) ----

size_t q4BlockElems() {
  static const size_t block = static_cast<size_t>(
      envCount("TPUCOLL_Q4_BLOCK", 256, 8,
               static_cast<long>(kQ4MaxBlockElems)));
  return block;
}

namespace {

// Pack n int32 codes (already clipped to [-7, 7]) into biased nibbles.
// Integer-exact, so sharing it between the scalar and vector encoders
// cannot break byte identity.
inline void q4PackCodes(const int* q, uint8_t* codes, size_t n) {
  const size_t nb = (n + 1) / 2;
  for (size_t i = 0; i < nb; i++) {
    const uint8_t lo = static_cast<uint8_t>(q[2 * i] + 8);
    const uint8_t hi =
        2 * i + 1 < n ? static_cast<uint8_t>(q[2 * i + 1] + 8) : 0;
    codes[i] = static_cast<uint8_t>(lo | (hi << 4));
  }
}

#ifndef TC_HAVE_VECTOR_HALF
TC_Q8_NO_FP_CONTRACT
inline void q4EncodeBlockScalar(const float* src, uint8_t* dst, size_t n) {
  float maxAbs = 0.0f;
  for (size_t i = 0; i < n; i++) {
    maxAbs = std::max(maxAbs, std::fabs(src[i]));
  }
  const float scale = maxAbs / 7.0f;
  std::memcpy(dst, &scale, kQ4ScaleBytes);
  uint8_t* codes = dst + kQ4ScaleBytes;
  const size_t nb = (n + 1) / 2;
  if (scale == 0.0f) {
    // Biased zero code in every nibble; a dangling odd tail keeps its
    // high nibble 0 like the non-zero path.
    std::memset(codes, 0x88, nb);
    if (n % 2 != 0) {
      codes[nb - 1] = 0x08;
    }
    return;
  }
  int q[2];
  for (size_t i = 0; i < n; i += 2) {
    const size_t pair = std::min<size_t>(2, n - i);
    for (size_t j = 0; j < pair; j++) {
      int v = static_cast<int>(nearbyintf(src[i + j] / scale));
      q[j] = std::min(7, std::max(-7, v));
    }
    q4PackCodes(q, codes + i / 2, pair);
  }
}

template <bool accumulate>
TC_Q8_NO_FP_CONTRACT
inline void q4DecodeBlockScalar(float* acc, const uint8_t* unit, size_t n) {
  float scale;
  std::memcpy(&scale, unit, kQ4ScaleBytes);
  const uint8_t* codes = unit + kQ4ScaleBytes;
  for (size_t i = 0; i < n; i++) {
    const uint8_t byte = codes[i / 2];
    const int nib = (i % 2 != 0) ? (byte >> 4) : (byte & 0x0f);
    const float v = static_cast<float>(nib - 8) * scale;
    acc[i] = accumulate ? acc[i] + v : v;
  }
}
#endif  // !TC_HAVE_VECTOR_HALF

#ifdef TC_HAVE_VECTOR_HALF

// Vector quantize of one block: the expensive per-element IEEE division
// and round run 8 lanes wide (identical ops to the scalar path); the
// clipped int32 codes round-trip through a small stack array into the
// integer-exact nibble packer.
TC_Q8_NO_FP_CONTRACT
inline void q4EncodeBlockVec(const float* src, uint8_t* dst, size_t n) {
  const __m256 absMask = _mm256_castsi256_ps(_mm256_set1_epi32(0x7fffffff));
  __m256 vmax = _mm256_setzero_ps();
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    vmax = _mm256_max_ps(vmax, _mm256_and_ps(_mm256_loadu_ps(src + i),
                                             absMask));
  }
  float maxAbs = hmax8(vmax);
  for (; i < n; i++) {
    maxAbs = std::max(maxAbs, std::fabs(src[i]));
  }
  const float scale = maxAbs / 7.0f;
  std::memcpy(dst, &scale, kQ4ScaleBytes);
  uint8_t* codes = dst + kQ4ScaleBytes;
  const size_t nb = (n + 1) / 2;
  if (scale == 0.0f) {
    std::memset(codes, 0x88, nb);
    if (n % 2 != 0) {
      codes[nb - 1] = 0x08;
    }
    return;
  }
  const __m256 vscale = _mm256_set1_ps(scale);
  const __m256i lim = _mm256_set1_epi32(7);
  const __m256i nlim = _mm256_set1_epi32(-7);
  alignas(32) int q[8];
  i = 0;
  for (; i + 8 <= n; i += 8) {
    __m256 r = _mm256_round_ps(
        _mm256_div_ps(_mm256_loadu_ps(src + i), vscale),
        _MM_FROUND_TO_NEAREST_INT | _MM_FROUND_NO_EXC);
    __m256i qi = _mm256_min_epi32(_mm256_max_epi32(_mm256_cvtps_epi32(r),
                                                   nlim), lim);
    _mm256_store_si256(reinterpret_cast<__m256i*>(q), qi);
    q4PackCodes(q, codes + i / 2, 8);
  }
  for (; i < n; i++) {
    int v = static_cast<int>(nearbyintf(src[i] / scale));
    v = std::min(7, std::max(-7, v));
    // i is even here whenever the vector loop ran (it advances by 8),
    // but a short block can enter the tail at any parity.
    const uint8_t c = static_cast<uint8_t>(v + 8);
    if (i % 2 == 0) {
      codes[i / 2] = c;
    } else {
      codes[i / 2] = static_cast<uint8_t>(codes[i / 2] | (c << 4));
    }
  }
}

// acc[i] (+)= (nibble - 8) * scale: the nibble unpack is integer-exact
// scalar work; the float mul/add runs 8 lanes wide, mul then add (never
// FMA) so vector equals scalar bit-for-bit.
template <bool accumulate>
TC_Q8_NO_FP_CONTRACT
inline void q4DecodeBlockVec(float* acc, const uint8_t* unit, size_t n) {
  float scale;
  std::memcpy(&scale, unit, kQ4ScaleBytes);
  const uint8_t* codes = unit + kQ4ScaleBytes;
  const __m256 vscale = _mm256_set1_ps(scale);
  alignas(16) int8_t w[8];
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    for (size_t j = 0; j < 4; j++) {
      const uint8_t byte = codes[i / 2 + j];
      w[2 * j] = static_cast<int8_t>(static_cast<int>(byte & 0x0f) - 8);
      w[2 * j + 1] = static_cast<int8_t>(static_cast<int>(byte >> 4) - 8);
    }
    __m256i qi = _mm256_cvtepi8_epi32(
        _mm_loadl_epi64(reinterpret_cast<const __m128i*>(w)));
    __m256 v = _mm256_mul_ps(_mm256_cvtepi32_ps(qi), vscale);
    if (accumulate) {
      v = _mm256_add_ps(_mm256_loadu_ps(acc + i), v);
    }
    _mm256_storeu_ps(acc + i, v);
  }
  for (; i < n; i++) {
    const uint8_t byte = codes[i / 2];
    const int nib = (i % 2 != 0) ? (byte >> 4) : (byte & 0x0f);
    const float v = static_cast<float>(nib - 8) * scale;
    acc[i] = accumulate ? acc[i] + v : v;
  }
}

#endif  // TC_HAVE_VECTOR_HALF

}  // namespace

TC_Q8_NO_FP_CONTRACT
void f32StreamToQ4(const float* src, uint8_t* dst, size_t n, size_t block) {
  for (size_t off = 0; off < n; off += block) {
    const size_t b = std::min(block, n - off);
#ifdef TC_HAVE_VECTOR_HALF
    q4EncodeBlockVec(src + off, dst, b);
#else
    q4EncodeBlockScalar(src + off, dst, b);
#endif
    dst += q4UnitBytes(b);
  }
}

TC_Q8_NO_FP_CONTRACT
void q4StreamToF32(const uint8_t* src, float* dst, size_t n, size_t block) {
  for (size_t off = 0; off < n; off += block) {
    const size_t b = std::min(block, n - off);
#ifdef TC_HAVE_VECTOR_HALF
    q4DecodeBlockVec<false>(dst + off, src, b);
#else
    q4DecodeBlockScalar<false>(dst + off, src, b);
#endif
    src += q4UnitBytes(b);
  }
}

TC_Q8_NO_FP_CONTRACT
void q4StreamAccumulate(float* dst, const uint8_t* src, size_t n,
                        size_t block) {
  for (size_t off = 0; off < n; off += block) {
    const size_t b = std::min(block, n - off);
#ifdef TC_HAVE_VECTOR_HALF
    q4DecodeBlockVec<true>(dst + off, src, b);
#else
    q4DecodeBlockScalar<true>(dst + off, src, b);
#endif
    src += q4UnitBytes(b);
  }
}

ReduceFn getReduceFn(DataType dtype, ReduceOp op) {
  switch (dtype) {
    case DataType::kInt8:
      return pickOp<int8_t>(op);
    case DataType::kUint8:
      return pickOp<uint8_t>(op);
    case DataType::kInt32:
      return pickOp<int32_t>(op);
    case DataType::kUint32:
      return pickOp<uint32_t>(op);
    case DataType::kInt64:
      return pickOp<int64_t>(op);
    case DataType::kUint64:
      return pickOp<uint64_t>(op);
    case DataType::kFloat16:
      return pickHalfOp(op);
    case DataType::kBFloat16:
      return pickBf16Op(op);
    case DataType::kFloat32:
      return pickOp<float>(op);
    case DataType::kFloat64:
      return pickOp<double>(op);
  }
  TC_THROW(EnforceError, "unknown dtype");
}

}  // namespace tpucoll
