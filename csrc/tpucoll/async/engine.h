// Async collective engine: a small pool of LANES — worker threads that
// each own a privately-tagged forked sub-context (Context::forkFrom, the
// ContextFactory machinery) — executing collectives submitted as Work
// handles with wait/test semantics, so a caller can issue bucket k+1's
// pack/copy while bucket k is on the wire (HiCCL-style inter-collective
// pipelining; GC3's "issue order decoupled from completion order").
//
// Isolation contract: concurrent collectives on DIFFERENT lanes can never
// cross-match slots because each lane's traffic runs on its own transport
// mesh (own pairs, own slot namespace). Within one lane ops run strictly
// FIFO on one thread, which is exactly the safety profile of an
// application loop issuing blocking collectives back-to-back on one tag.
//
// Determinism contract: submissions are assigned to lanes round-robin in
// submission order (submit #i runs on lane i % lanes). Every rank must
// submit the same collectives in the same order — the ordinary collective
// matching contract — which then guarantees (a) lane k executes the same
// op sequence on every rank, so each lane's flight-recorder cseq /
// fingerprint stream stays cross-rank comparable and the desync detector
// stays false-positive free, and (b) the fault plane's per-(rule, rank,
// channel, domain) state sees a deterministic event stream per lane (each
// lane context carries fault domain = lane + 1).
//
// Error contract: an op that fails surfaces its exception — typed, with
// the lane and op named — at Work::wait()/test(), never on the engine
// thread. The collective ran in place, so the buffer contents are
// undefined (docs/errors.md "In-place collectives"); the failing lane is
// poisoned and every later op already assigned to it fails fast citing
// the original error. shutdown() (also run by ~Engine and by the owning
// Python Context's close()) fails queued-but-unstarted work with
// AbortedException and aborts the in-flight op by closing its lane's
// context — waiters always unblock, loudly, naming the blamed lane/op.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <exception>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "tpucoll/context.h"
#include "tpucoll/types.h"

namespace tpucoll {
namespace async {

class Engine;

// One submitted collective. Created by Engine::submit; shared between the
// engine (until execution finishes) and the caller (until freed).
class Work {
 public:
  enum class Status : int {
    kQueued = 0,
    kRunning = 1,
    kDone = 2,
    kError = 3,
  };

  // Blocks until the op completes or `timeout` elapses. On completion
  // with error, rethrows the stored (lane/op-augmented) exception. A
  // timeout here throws TimeoutException and does NOT cancel the op —
  // it is still in flight on its lane.
  void wait(std::chrono::milliseconds timeout);

  // Non-blocking: true once the op reached kDone or kError. Never
  // throws; the error (if any) surfaces at wait().
  bool done() const {
    Status s = status_.load(std::memory_order_acquire);
    return s == Status::kDone || s == Status::kError;
  }
  Status status() const { return status_.load(std::memory_order_acquire); }

  // Error message of a kError op ("" otherwise) — introspection without
  // rethrow.
  std::string errorMessage() const;

  const char* opName() const { return opName_; }
  int lane() const { return lane_; }
  uint64_t seq() const { return seq_; }

 private:
  friend class Engine;
  Work(const char* opName, int lane, uint64_t seq)
      : opName_(opName), lane_(lane), seq_(seq) {}

  void finish(std::exception_ptr err);

  const char* opName_;  // static string
  const int lane_;
  const uint64_t seq_;  // engine-wide submission index
  std::function<void(Context*)> fn_;

  mutable std::mutex mu_;
  std::condition_variable cv_;
  std::atomic<Status> status_{Status::kQueued};
  std::exception_ptr error_;     // set before status_ -> kError
  std::string errorMessage_;
};

struct EngineOptions {
  int lanes = 2;
  // Base user tag for the lane forks on the parent context; lane k's
  // fork bootstraps on tags (tagBase + 2k, tagBase + 2k + 1). Must not
  // collide with collectives running concurrently on the parent.
  uint32_t tagBase = 0xFFFFD00u;
};

class Engine {
 public:
  // COLLECTIVE: forks `opts.lanes` sub-contexts over `parent`, so every
  // rank must construct the engine concurrently with the same lane
  // count and tag base. The parent must outlive the engine.
  Engine(Context* parent, const EngineOptions& opts);
  ~Engine();

  Engine(const Engine&) = delete;
  Engine& operator=(const Engine&) = delete;

  int lanes() const { return static_cast<int>(lanes_.size()); }

  // Async collectives, mirroring the blocking API's semantics; buffers
  // must stay valid until the returned Work completes. timeout 0 uses
  // the parent context's default. Custom reduce callbacks are not
  // supported (they would run on a lane thread; Python trampolines need
  // the caller's interpreter state).
  std::shared_ptr<Work> allreduce(const void* input, void* output,
                                  size_t count, DataType dtype, ReduceOp op,
                                  int algorithm,
                                  std::chrono::milliseconds timeout);
  std::shared_ptr<Work> reduceScatter(const void* input, void* output,
                                      std::vector<size_t> recvCounts,
                                      DataType dtype, ReduceOp op,
                                      int algorithm,
                                      std::chrono::milliseconds timeout);
  std::shared_ptr<Work> allgather(const void* input, void* output,
                                  size_t count, DataType dtype,
                                  int algorithm,
                                  std::chrono::milliseconds timeout);

  // Borrowed lane context (metrics / flight recorder introspection).
  Context* laneContext(int lane) const;

  // Fail queued work (AbortedException), abort the in-flight op on each
  // lane by closing its context, join the lane threads. Idempotent;
  // after shutdown every submit throws.
  void shutdown();

  // {"lanes", "in_flight", "submitted", "completed", "errors",
  //  "per_lane": [{"submitted","completed","errors","queue_depth",
  //  "poisoned"}]}
  std::string statsJson() const;

 private:
  struct Lane {
    std::unique_ptr<Context> ctx;
    std::thread thread;
    std::mutex mu;
    std::condition_variable cv;
    std::deque<std::shared_ptr<Work>> queue;  // mu
    std::shared_ptr<Work> running;            // mu
    bool poisoned{false};                     // mu; first Io failure
    std::string poisonMessage;                // mu
    std::atomic<uint64_t> submitted{0};
    std::atomic<uint64_t> completed{0};
    std::atomic<uint64_t> errors{0};
  };

  std::shared_ptr<Work> submit(const char* opName,
                               std::function<void(Context*)> fn);
  void laneMain(Lane* lane, int laneIdx);

  Context* const parent_;
  std::vector<std::unique_ptr<Lane>> lanes_;
  std::atomic<uint64_t> submitSeq_{0};
  std::atomic<bool> stopping_{false};
  std::mutex shutdownMu_;  // serializes shutdown()
  bool shutdownDone_{false};
};

}  // namespace async
}  // namespace tpucoll
