#include "tpucoll/async/engine.h"

#include <sstream>
#include <utility>

#include "tpucoll/collectives/collectives.h"
#include "tpucoll/common/logging.h"

namespace tpucoll {
namespace async {

namespace {

std::string describeOp(const char* opName, int lane, uint64_t seq) {
  std::ostringstream os;
  os << opName << " (async seq " << seq << ", lane " << lane << ")";
  return os.str();
}

// Rethrow the in-flight exception with the lane/op named, preserving the
// type (Timeout < Io, Aborted, Enforce) so the C API keeps mapping it to
// the right Python exception.
[[noreturn]] void rethrowAugmented(const char* opName, int lane,
                                   uint64_t seq) {
  const std::string who = describeOp(opName, lane, seq);
  try {
    throw;
  } catch (const TimeoutException& e) {
    throw TimeoutException(who + ": " + e.what());
  } catch (const AbortedException& e) {
    throw AbortedException(who + ": " + e.what());
  } catch (const IoException& e) {
    throw IoException(who + ": " + e.what());
  } catch (const EnforceError& e) {
    throw EnforceError(who + ": " + e.what());
  } catch (const std::exception& e) {
    throw IoException(who + ": " + e.what());
  } catch (...) {
    throw IoException(who + ": unknown error");
  }
}

}  // namespace

// ---- Work -----------------------------------------------------------------

void Work::wait(std::chrono::milliseconds timeout) {
  {
    std::unique_lock<std::mutex> lk(mu_);
    const bool completed = cv_.wait_for(lk, timeout, [&] {
      Status s = status_.load(std::memory_order_acquire);
      return s == Status::kDone || s == Status::kError;
    });
    if (!completed) {
      TC_THROW(TimeoutException, "tc_work_wait: ",
               describeOp(opName_, lane_, seq_), " still in flight after ",
               timeout.count(),
               "ms (the op is NOT cancelled by a wait timeout)");
    }
  }
  if (status_.load(std::memory_order_acquire) == Status::kError) {
    std::rethrow_exception(error_);
  }
}

std::string Work::errorMessage() const {
  std::lock_guard<std::mutex> lk(mu_);
  return errorMessage_;
}

void Work::finish(std::exception_ptr err) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (err != nullptr) {
      error_ = err;
      try {
        std::rethrow_exception(err);
      } catch (const std::exception& e) {
        errorMessage_ = e.what();
      } catch (...) {
        errorMessage_ = "unknown error";
      }
      status_.store(Status::kError, std::memory_order_release);
    } else {
      status_.store(Status::kDone, std::memory_order_release);
    }
  }
  cv_.notify_all();
}

// ---- Engine ---------------------------------------------------------------

Engine::Engine(Context* parent, const EngineOptions& opts)
    : parent_(parent) {
  TC_ENFORCE(parent != nullptr, "async engine: null parent context");
  TC_ENFORCE(opts.lanes >= 1 && opts.lanes <= 16,
             "async engine: lanes must be in [1, 16], got ", opts.lanes);
  lanes_.reserve(opts.lanes);
  for (int k = 0; k < opts.lanes; k++) {
    auto lane = std::make_unique<Lane>();
    lane->ctx = std::make_unique<Context>(parent->rank(), parent->size());
    lane->ctx->setTimeout(parent->getTimeout());
    // Lane identity for the post-mortem planes, set BEFORE the fork so
    // even bootstrap-time faults/dumps carry it: the fault table keys
    // its deterministic per-rule state by this domain, and the flight
    // recorder's automatic dumps go to flightrec-rank<r>-lane<k>.json so
    // they never clobber the parent's dump. Lanes of a split sub-group
    // compose with the parent's identity: domain offsets from the
    // parent's (root parents keep the historical lane+1), and the
    // group dump-tag carries through so a split group's lane dumps
    // partition with the group (flightrec-rank<r>-g<tag>-lane<k>.json).
    lane->ctx->setFaultDomain(parent->faultDomain() + k + 1);
    lane->ctx->flightrec().setDumpTag(k);
    if (!parent->groupTag().empty()) {
      lane->ctx->flightrec().setGroupTag(parent->groupTag().c_str());
      lane->ctx->metrics().setGroup(parent->groupTag());
    }
    // Two bootstrap tags per fork (allgather + allgatherv); stride 2.
    lane->ctx->forkFrom(*parent, opts.tagBase + 2 * k);
    lanes_.push_back(std::move(lane));
  }
  for (size_t k = 0; k < lanes_.size(); k++) {
    Lane* lane = lanes_[k].get();
    lane->thread = std::thread(
        [this, lane, k] { laneMain(lane, static_cast<int>(k)); });
  }
}

Engine::~Engine() {
  try {
    shutdown();
  } catch (...) {
    // Destructor must not throw; shutdown already recorded per-work
    // errors and joined what it could.
  }
}

Context* Engine::laneContext(int lane) const {
  TC_ENFORCE(lane >= 0 && lane < static_cast<int>(lanes_.size()),
             "async engine: lane ", lane, " out of range");
  return lanes_[lane]->ctx.get();
}

std::shared_ptr<Work> Engine::submit(const char* opName,
                                     std::function<void(Context*)> fn) {
  if (stopping_.load(std::memory_order_acquire)) {
    TC_THROW(IoException, "async engine: submit after shutdown");
  }
  const uint64_t seq = submitSeq_.fetch_add(1, std::memory_order_relaxed);
  const int laneIdx = static_cast<int>(seq % lanes_.size());
  Lane* lane = lanes_[laneIdx].get();
  std::shared_ptr<Work> w(new Work(opName, laneIdx, seq));
  w->fn_ = std::move(fn);
  {
    std::lock_guard<std::mutex> lk(lane->mu);
    // Recheck under the lane lock: shutdown drains this queue exactly
    // once, so a submit racing shutdown must not slip a work in after
    // the drain (it would never run and never be failed).
    if (stopping_.load(std::memory_order_acquire)) {
      TC_THROW(IoException, "async engine: submit after shutdown");
    }
    lane->queue.push_back(w);
    lane->submitted.fetch_add(1, std::memory_order_relaxed);
  }
  lane->cv.notify_one();
  return w;
}

std::shared_ptr<Work> Engine::allreduce(const void* input, void* output,
                                        size_t count, DataType dtype,
                                        ReduceOp op, int algorithm,
                                        std::chrono::milliseconds timeout) {
  return submit("allreduce", [=](Context* ctx) {
    AllreduceOptions opts;
    opts.context = ctx;
    opts.timeout = timeout;
    opts.inputs = {input};
    opts.outputs = {output};
    opts.count = count;
    opts.dtype = dtype;
    opts.op = op;
    opts.algorithm = static_cast<AllreduceAlgorithm>(algorithm);
    tpucoll::allreduce(opts);
  });
}

std::shared_ptr<Work> Engine::reduceScatter(
    const void* input, void* output, std::vector<size_t> recvCounts,
    DataType dtype, ReduceOp op, int algorithm,
    std::chrono::milliseconds timeout) {
  return submit("reduce_scatter",
                [=, counts = std::move(recvCounts)](Context* ctx) {
    ReduceScatterOptions opts;
    opts.context = ctx;
    opts.timeout = timeout;
    opts.input = input;
    opts.output = output;
    opts.recvCounts = counts;
    opts.dtype = dtype;
    opts.op = op;
    opts.algorithm = static_cast<ReduceScatterAlgorithm>(algorithm);
    tpucoll::reduceScatter(opts);
  });
}

std::shared_ptr<Work> Engine::allgather(const void* input, void* output,
                                        size_t count, DataType dtype,
                                        int algorithm,
                                        std::chrono::milliseconds timeout) {
  return submit("allgather", [=](Context* ctx) {
    AllgatherOptions opts;
    opts.context = ctx;
    opts.timeout = timeout;
    opts.input = input;
    opts.output = output;
    opts.count = count;
    opts.dtype = dtype;
    opts.algorithm = static_cast<HierDispatch>(algorithm);
    tpucoll::allgather(opts);
  });
}

void Engine::laneMain(Lane* lane, int laneIdx) {
  for (;;) {
    std::shared_ptr<Work> w;
    bool poisoned = false;
    std::string poisonMessage;
    {
      std::unique_lock<std::mutex> lk(lane->mu);
      lane->cv.wait(lk, [&] {
        return stopping_.load(std::memory_order_acquire) ||
               !lane->queue.empty();
      });
      if (lane->queue.empty()) {
        return;  // stopping, nothing left to run
      }
      w = lane->queue.front();
      lane->queue.pop_front();
      lane->running = w;
      poisoned = lane->poisoned;
      poisonMessage = lane->poisonMessage;
    }
    w->status_.store(Work::Status::kRunning, std::memory_order_release);
    std::exception_ptr err;
    try {
      if (poisoned) {
        TC_THROW(IoException, "not run: lane ", laneIdx,
                 " poisoned by an earlier failure: ", poisonMessage);
      }
      w->fn_(lane->ctx.get());
    } catch (...) {
      try {
        rethrowAugmented(w->opName_, laneIdx, w->seq_);
      } catch (...) {
        err = std::current_exception();
      }
    }
    {
      std::lock_guard<std::mutex> lk(lane->mu);
      lane->running = nullptr;
      if (err != nullptr && !lane->poisoned) {
        // An Io/Timeout failure poisons the lane context (docs/errors.md);
        // later ops on this lane must fail fast instead of hanging on a
        // dead mesh. Argument errors (EnforceError) do not poison.
        try {
          std::rethrow_exception(err);
        } catch (const IoException& e) {
          lane->poisoned = true;
          lane->poisonMessage = e.what();
        } catch (...) {
        }
      }
    }
    (err == nullptr ? lane->completed : lane->errors)
        .fetch_add(1, std::memory_order_relaxed);
    w->fn_ = nullptr;  // release captured state promptly
    w->finish(err);
  }
}

void Engine::shutdown() {
  std::lock_guard<std::mutex> shutdownGuard(shutdownMu_);
  if (shutdownDone_) {
    return;
  }
  stopping_.store(true, std::memory_order_release);
  // Fail everything queued-but-unstarted, loudly and typed.
  std::vector<std::shared_ptr<Work>> orphans;
  for (size_t k = 0; k < lanes_.size(); k++) {
    Lane* lane = lanes_[k].get();
    std::lock_guard<std::mutex> lk(lane->mu);
    lane->errors.fetch_add(lane->queue.size(), std::memory_order_relaxed);
    for (auto& w : lane->queue) {
      orphans.push_back(w);
    }
    lane->queue.clear();
  }
  for (auto& w : orphans) {
    std::exception_ptr err;
    try {
      TC_THROW(AbortedException, "async engine shut down with work in "
               "flight: ", describeOp(w->opName_, w->lane_, w->seq_),
               " was still queued and never ran");
    } catch (...) {
      err = std::current_exception();
    }
    w->fn_ = nullptr;
    w->finish(err);
  }
  // Abort whatever is mid-collective: closing the lane context fails its
  // pending and future transport ops with IoException, which unwinds the
  // lane thread's blocking collective and lands — lane/op-augmented — in
  // that Work's error slot.
  for (auto& lane : lanes_) {
    try {
      lane->ctx->close();
    } catch (...) {
    }
  }
  for (auto& lane : lanes_) {
    lane->cv.notify_all();
    if (lane->thread.joinable()) {
      lane->thread.join();
    }
  }
  shutdownDone_ = true;
}

std::string Engine::statsJson() const {
  uint64_t submitted = 0;
  uint64_t completed = 0;
  uint64_t errors = 0;
  std::ostringstream lanesJson;
  lanesJson << "[";
  for (size_t k = 0; k < lanes_.size(); k++) {
    Lane* lane = lanes_[k].get();
    const uint64_t s = lane->submitted.load(std::memory_order_relaxed);
    const uint64_t c = lane->completed.load(std::memory_order_relaxed);
    const uint64_t e = lane->errors.load(std::memory_order_relaxed);
    size_t depth;
    bool poisoned;
    {
      std::lock_guard<std::mutex> lk(lane->mu);
      depth = lane->queue.size();
      poisoned = lane->poisoned;
    }
    submitted += s;
    completed += c;
    errors += e;
    lanesJson << (k == 0 ? "" : ",") << "{\"submitted\":" << s
              << ",\"completed\":" << c << ",\"errors\":" << e
              << ",\"queue_depth\":" << depth
              << ",\"poisoned\":" << (poisoned ? "true" : "false") << "}";
  }
  lanesJson << "]";
  // Counter reads are not a consistent snapshot; clamp so a mid-flight
  // read can never print a wrapped gauge.
  const uint64_t finished = completed + errors;
  const uint64_t inFlight = finished < submitted ? submitted - finished : 0;
  std::ostringstream os;
  os << "{\"lanes\":" << lanes_.size() << ",\"in_flight\":" << inFlight
     << ",\"submitted\":" << submitted << ",\"completed\":" << completed
     << ",\"errors\":" << errors << ",\"per_lane\":" << lanesJson.str()
     << "}";
  return os.str();
}

}  // namespace async
}  // namespace tpucoll
