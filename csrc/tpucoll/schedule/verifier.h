// Static verifier for collective schedules (ir.h).
//
// A schedule is admitted to the interpreter only after this module
// proves, per rank and globally, that it computes its declared
// collective:
//
//   - structure: every operand in range, op-specific slot/peer rules,
//     dependency indices valid (bad_step);
//   - liveness: the per-rank dependency graph is acyclic
//     (dependency_cycle) and the global exchange reaches completion
//     under a conservative rendezvous model — a send and its matching
//     receive complete together (deadlock);
//   - matching: the k-th send rank a posts toward rank b pairs with the
//     k-th receive rank b posts from rank a (the transport's per-pair
//     FIFO), and the pair must agree on chunk id and wire coding
//     (message_mismatch);
//   - dataflow: contribution sets are tracked per chunk per rank —
//     reading an unwritten region is stale_read, folding a contribution
//     a chunk already holds is chunk_reduced_twice, touching a region
//     with an unordered in-flight receive is hazard;
//   - completeness: the final contribution sets match the collective's
//     postcondition everywhere, else undelivered.
//
// The model is conservative with respect to the interpreter
// (interpreter.cc): each rank issues steps sequentially in the
// deterministic topological order computed here (Kahn, smallest index
// first), waiting only on declared dependency edges, so any execution
// the interpreter can produce is an interleaving this simulation
// admits. Worlds up to 64 ranks are supported (contribution sets are
// one machine word); larger schedules are rejected loudly rather than
// checked partially.
#pragma once

#include <cstdint>
#include <optional>
#include <string>
#include <vector>

#include "tpucoll/schedule/ir.h"

namespace tpucoll {
namespace schedule {

enum class VerifyCode : uint8_t {
  kBadStep = 0,
  kDependencyCycle = 1,
  kMessageMismatch = 2,
  kStaleRead = 3,
  kChunkReducedTwice = 4,
  kHazard = 5,
  kDeadlock = 6,
  kUndelivered = 7,
};

const char* verifyCodeName(VerifyCode code);

struct VerifyError {
  VerifyCode code{VerifyCode::kBadStep};
  int rank{-1};  // -1 = not rank-specific
  int step{-1};  // -1 = not step-specific
  std::string message;

  // "chunk_reduced_twice at rank 1 step 4 (rs_rr_1): ..."
  std::string format(const Schedule& s) const;
};

// Full static check; nullopt = the schedule provably computes its
// declared collective under the model above.
std::optional<VerifyError> verify(const Schedule& s);

// verify() + TC_THROW(EnforceError) with the formatted error.
void verifyOrThrow(const Schedule& s);

// The deterministic per-rank execution order the verifier proved safe:
// Kahn's algorithm, smallest step index first among ready steps. The
// interpreter issues steps in exactly this order. Throws on a
// dependency cycle (callers verify first).
std::vector<int32_t> topoOrder(const Schedule& s, int rank);

}  // namespace schedule
}  // namespace tpucoll
