#include "tpucoll/schedule/verifier.h"

#include <algorithm>
#include <map>
#include <queue>
#include <sstream>
#include <utility>

#include "tpucoll/collectives/wire_codec.h"
#include "tpucoll/common/logging.h"

namespace tpucoll {
namespace schedule {

const char* verifyCodeName(VerifyCode code) {
  switch (code) {
    case VerifyCode::kBadStep:
      return "bad_step";
    case VerifyCode::kDependencyCycle:
      return "dependency_cycle";
    case VerifyCode::kMessageMismatch:
      return "message_mismatch";
    case VerifyCode::kStaleRead:
      return "stale_read";
    case VerifyCode::kChunkReducedTwice:
      return "chunk_reduced_twice";
    case VerifyCode::kHazard:
      return "hazard";
    case VerifyCode::kDeadlock:
      return "deadlock";
    case VerifyCode::kUndelivered:
      return "undelivered";
  }
  TC_THROW(EnforceError, "unknown verify code ", static_cast<int>(code));
}

std::string VerifyError::format(const Schedule& s) const {
  std::ostringstream out;
  out << "schedule \"" << s.name << "\": " << verifyCodeName(code);
  if (rank >= 0) {
    out << " at rank " << rank;
  }
  if (step >= 0) {
    out << " step " << step;
    if (step < static_cast<int>(s.steps.size()) &&
        !s.steps[step].note.empty()) {
      out << " (" << s.steps[step].note << ")";
    }
  }
  out << ": " << message;
  return out.str();
}

namespace {

bool isWire(StepOp op) {
  return op == StepOp::kSend || op == StepOp::kRecv ||
         op == StepOp::kRecvReduce;
}

bool isRecvKind(StepOp op) {
  return op == StepOp::kRecv || op == StepOp::kRecvReduce;
}

// Concrete per-rank operands of one step (exprs evaluated).
struct Operands {
  bool active{false};
  int peer{-1};
  int chunk{0};
  int slot{-1};
};

// How a step touches a region (work chunk or scratch slot). The hazard
// check orders conflicting accesses: a wire step's effect is
// asynchronous (send reads its source until drained; a receive writes
// its landing region on arrival; recv_reduce's fold is deferred to the
// first dependency demand), so any access that does not commute with it
// needs a dependency path. Two reads commute; two reduce-folds into the
// same chunk commute at contribution-set level (the interpreter
// serializes them in program order); everything else does not.
enum class AccessKind : uint8_t { kRead, kWrite, kRmw };

struct Access {
  bool slot;  // region kind: scratch slot vs work chunk
  int idx;
  AccessKind kind;
};

// The (at most two) region accesses of one step. Identical for the
// synchronous view (the issuing step) and the asynchronous view (an
// in-flight wire step) — wire opcodes' listed accesses ARE their async
// effects.
void accessesOf(StepOp op, const Operands& o, uint8_t flags,
                std::vector<Access>& out) {
  out.clear();
  switch (op) {
    case StepOp::kSend:
      if (o.slot >= 0) {
        out.push_back(Access{true, o.slot, AccessKind::kRead});
      } else {
        out.push_back(Access{false, o.chunk, AccessKind::kRead});
      }
      return;
    case StepOp::kRecv:
      if (o.slot >= 0) {
        out.push_back(Access{true, o.slot, AccessKind::kWrite});
      } else {
        out.push_back(Access{false, o.chunk, AccessKind::kWrite});
      }
      return;
    case StepOp::kRecvReduce:
      out.push_back(Access{true, o.slot, AccessKind::kWrite});
      out.push_back(Access{false, o.chunk, AccessKind::kRmw});
      return;
    case StepOp::kReduceLocal:
      out.push_back(Access{true, o.slot, AccessKind::kRead});
      out.push_back(Access{false, o.chunk, AccessKind::kRmw});
      return;
    case StepOp::kCopy:
      if (flags & Step::kFlagToSlot) {
        out.push_back(Access{false, o.chunk, AccessKind::kRead});
        out.push_back(Access{true, o.slot, AccessKind::kWrite});
      } else {
        out.push_back(Access{true, o.slot, AccessKind::kRead});
        out.push_back(Access{false, o.chunk, AccessKind::kWrite});
      }
      return;
    case StepOp::kEncode:
      out.push_back(Access{false, o.chunk, AccessKind::kRead});
      out.push_back(Access{true, o.slot, AccessKind::kWrite});
      return;
    case StepOp::kDecode:
      out.push_back(Access{true, o.slot, AccessKind::kRead});
      out.push_back(Access{false, o.chunk, AccessKind::kWrite});
      return;
  }
  TC_THROW(EnforceError, "unknown step op ", static_cast<int>(op));
}

bool accessesConflict(AccessKind inflight, AccessKind issuing) {
  switch (inflight) {
    case AccessKind::kRead:
      return issuing != AccessKind::kRead;
    case AccessKind::kWrite:
      return true;
    case AccessKind::kRmw:
      return issuing != AccessKind::kRmw;
  }
  TC_THROW(EnforceError, "unknown access kind");
}

std::string maskStr(uint64_t mask) {
  std::ostringstream out;
  out << "{";
  bool first = true;
  for (int r = 0; r < 64; r++) {
    if (mask & (uint64_t(1) << r)) {
      if (!first) {
        out << ",";
      }
      first = false;
      out << r;
    }
  }
  out << "}";
  return out.str();
}

// Kahn's algorithm, smallest step index first among ready steps — the
// one execution order every rank uses (deps are rank-independent).
// Returns false and names a cycle member on failure.
bool tryTopo(const Schedule& s, std::vector<int32_t>* order,
             int* cycleStep) {
  const int n = static_cast<int>(s.steps.size());
  std::vector<int> indeg(n, 0);
  std::vector<std::vector<int32_t>> dependents(n);
  for (int i = 0; i < n; i++) {
    for (int32_t d : s.steps[i].deps) {
      dependents[d].push_back(i);
      indeg[i]++;
    }
  }
  std::priority_queue<int, std::vector<int>, std::greater<int>> ready;
  for (int i = 0; i < n; i++) {
    if (indeg[i] == 0) {
      ready.push(i);
    }
  }
  order->clear();
  order->reserve(n);
  while (!ready.empty()) {
    const int i = ready.top();
    ready.pop();
    order->push_back(i);
    for (int32_t dep : dependents[i]) {
      if (--indeg[dep] == 0) {
        ready.push(dep);
      }
    }
  }
  if (static_cast<int>(order->size()) == n) {
    return true;
  }
  for (int i = 0; i < n; i++) {
    if (indeg[i] > 0) {
      *cycleStep = i;
      break;
    }
  }
  return false;
}

// One matched wire message: the k-th send a->b paired with the k-th
// receive b posts from a (transport per-pair FIFO order == issue order,
// because each rank issues in the shared topological order).
struct Msg {
  int sendRank, sendStep;
  int recvRank, recvStep;
  int chunk;
  bool coded;
  uint64_t mask{0};
  bool sendIssued{false};
  bool recvIssued{false};
  bool applied{false};
};

struct RankState {
  std::vector<uint64_t> work;     // per chunk: contribution set, 0 = unwritten
  std::vector<uint64_t> scratch;  // per slot: contribution set, 0 = unwritten
  std::vector<int> scratchChunk;  // per slot: geometry tag (chunk id), -1 none
  std::vector<char> scratchCoded;
  std::vector<char> issued;
  int ptr{0};  // position in the topological order
};

}  // namespace

std::optional<VerifyError> verify(const Schedule& s) {
  const int world = s.worldSize;
  const int n = static_cast<int>(s.steps.size());
  auto err = [](VerifyCode code, int rank, int step, std::string msg) {
    return VerifyError{code, rank, step, std::move(msg)};
  };

  if (world <= 0 || world > 64) {
    return err(VerifyCode::kBadStep, -1, -1,
               "world size must be in [1, 64] (contribution sets are one "
               "machine word)");
  }
  if (s.nChunks <= 0) {
    return err(VerifyCode::kBadStep, -1, -1, "chunk count must be positive");
  }
  if ((s.collective == Collective::kReduceScatter ||
       s.collective == Collective::kAllgather) &&
      s.nChunks != world) {
    return err(VerifyCode::kBadStep, -1, -1,
               "reduce_scatter/allgather schedules require chunks == "
               "world_size (chunk c is rank c's block)");
  }

  // ---- structure: deps in range (rank-independent) ----
  for (int i = 0; i < n; i++) {
    for (int32_t d : s.steps[i].deps) {
      if (d < 0 || d >= n) {
        std::ostringstream msg;
        msg << "dep " << d << " out of range [0, " << n << ")";
        return err(VerifyCode::kBadStep, -1, i, msg.str());
      }
    }
  }

  // ---- structure: per-rank operands ----
  std::vector<std::vector<Operands>> ops(world, std::vector<Operands>(n));
  for (int r = 0; r < world; r++) {
    for (int i = 0; i < n; i++) {
      const Step& st = s.steps[i];
      Operands& o = ops[r][i];
      try {
        o.active = st.guard.eval(r, world) != 0;
        if (!o.active) {
          continue;
        }
        o.peer = static_cast<int>(st.peer.eval(r, world));
        o.chunk = static_cast<int>(st.chunk.eval(r, world));
        o.slot = static_cast<int>(st.slot.eval(r, world));
      } catch (const std::exception& e) {
        return err(VerifyCode::kBadStep, r, i, e.what());
      }
      if (st.flags & ~(Step::kFlagToSlot | Step::kFlagCoded)) {
        return err(VerifyCode::kBadStep, r, i, "unknown flag bits");
      }
      if ((st.flags & Step::kFlagToSlot) && st.op != StepOp::kCopy) {
        return err(VerifyCode::kBadStep, r, i,
                   "to_slot flag only applies to copy");
      }
      if ((st.flags & Step::kFlagCoded) &&
          !(st.op == StepOp::kSend || st.op == StepOp::kRecv)) {
        return err(VerifyCode::kBadStep, r, i,
                   "coded flag only applies to send/recv (recv_reduce "
                   "cannot fold coded bytes; recv then decode)");
      }
      if (st.pipeline < 1 ||
          st.pipeline > static_cast<int32_t>(algorithms::kMaxPipelineDepth)) {
        std::ostringstream msg;
        msg << "pipeline depth " << st.pipeline << " out of range [1, "
            << algorithms::kMaxPipelineDepth << "]";
        return err(VerifyCode::kBadStep, r, i, msg.str());
      }
      if (st.pipeline > 1 &&
          !(st.op == StepOp::kEncode || st.op == StepOp::kDecode)) {
        return err(VerifyCode::kBadStep, r, i,
                   "pipeline depth only applies to encode/decode (only "
                   "codec steps have a sub-block walk to split)");
      }
      if (o.chunk < 0 || o.chunk >= s.nChunks) {
        std::ostringstream msg;
        msg << "chunk " << o.chunk << " out of range [0, " << s.nChunks
            << ")";
        return err(VerifyCode::kBadStep, r, i, msg.str());
      }
      if (isWire(st.op)) {
        if (o.peer < 0 || o.peer >= world || o.peer == r) {
          std::ostringstream msg;
          msg << "peer " << o.peer << " invalid for world " << world;
          return err(VerifyCode::kBadStep, r, i, msg.str());
        }
      }
      const bool slotRequired = st.op == StepOp::kRecvReduce ||
                                st.op == StepOp::kReduceLocal ||
                                st.op == StepOp::kCopy ||
                                st.op == StepOp::kEncode ||
                                st.op == StepOp::kDecode ||
                                (st.flags & Step::kFlagCoded);
      if (slotRequired && o.slot < 0) {
        return err(VerifyCode::kBadStep, r, i,
                   "step requires a scratch slot");
      }
      if (o.slot >= s.nScratch) {
        std::ostringstream msg;
        msg << "slot " << o.slot << " out of range [0, " << s.nScratch
            << ")";
        return err(VerifyCode::kBadStep, r, i, msg.str());
      }
    }
  }

  // ---- liveness: acyclic dependency graph ----
  std::vector<int32_t> topo;
  int cycleStep = -1;
  if (!tryTopo(s, &topo, &cycleStep)) {
    return err(VerifyCode::kDependencyCycle, -1, cycleStep,
               "dependency edges form a cycle through this step");
  }

  // ---- matching: per-pair FIFO pairing of sends and receives ----
  struct End {
    int rank, step, chunk;
    bool coded;
  };
  std::map<std::pair<int, int>, std::vector<End>> sendsOf, recvsOf;
  for (int r = 0; r < world; r++) {
    for (int32_t i : topo) {
      const Operands& o = ops[r][i];
      if (!o.active) {
        continue;
      }
      const Step& st = s.steps[i];
      const bool coded = (st.flags & Step::kFlagCoded) != 0;
      if (st.op == StepOp::kSend) {
        sendsOf[{r, o.peer}].push_back(End{r, i, o.chunk, coded});
      } else if (isRecvKind(st.op)) {
        recvsOf[{o.peer, r}].push_back(End{r, i, o.chunk, coded});
      }
    }
  }
  std::vector<Msg> msgs;
  // msgOf[rank][step] -> index into msgs (each step is at most one
  // message endpoint per rank).
  std::vector<std::vector<int>> msgOf(world, std::vector<int>(n, -1));
  for (const auto& pairSends : sendsOf) {
    const auto& key = pairSends.first;
    const auto& sends = pairSends.second;
    auto rit = recvsOf.find(key);
    const size_t nRecvs = rit == recvsOf.end() ? 0 : rit->second.size();
    if (sends.size() != nRecvs) {
      std::ostringstream msg;
      msg << "rank " << key.first << " posts " << sends.size()
          << " send(s) to rank " << key.second << " but rank " << key.second
          << " posts " << nRecvs << " receive(s) from it";
      return err(VerifyCode::kMessageMismatch, key.first, sends[0].step,
                 msg.str());
    }
    for (size_t k = 0; k < sends.size(); k++) {
      const End& se = sends[k];
      const End& re = rit->second[k];
      if (se.chunk != re.chunk || se.coded != re.coded) {
        std::ostringstream msg;
        msg << "message " << k << " of pair " << key.first << "->"
            << key.second << ": send carries chunk " << se.chunk
            << (se.coded ? " (coded)" : "") << " but receive step "
            << re.step << " expects chunk " << re.chunk
            << (re.coded ? " (coded)" : "");
        return err(VerifyCode::kMessageMismatch, se.rank, se.step,
                   msg.str());
      }
      msgOf[se.rank][se.step] = static_cast<int>(msgs.size());
      msgOf[re.rank][re.step] = static_cast<int>(msgs.size());
      msgs.push_back(Msg{se.rank, se.step, re.rank, re.step, se.chunk,
                         se.coded});
    }
  }
  for (const auto& pairRecvs : recvsOf) {
    if (sendsOf.find(pairRecvs.first) == sendsOf.end()) {
      const auto& key = pairRecvs.first;
      std::ostringstream msg;
      msg << "rank " << key.second << " posts "
          << pairRecvs.second.size() << " receive(s) from rank "
          << key.first << " but rank " << key.first << " posts no sends "
          << "to it";
      return err(VerifyCode::kMessageMismatch, key.second,
                 pairRecvs.second[0].step, msg.str());
    }
  }

  // ---- transitive dependency closure (rank-independent) ----
  // closure[i] bit d set = step i transitively depends on step d. The
  // hazard rule needs paths, not just direct edges.
  const int words = (n + 63) / 64;
  std::vector<std::vector<uint64_t>> closure(
      n, std::vector<uint64_t>(words, 0));
  for (int32_t i : topo) {
    for (int32_t d : s.steps[i].deps) {
      for (int w = 0; w < words; w++) {
        closure[i][w] |= closure[d][w];
      }
      closure[i][d / 64] |= uint64_t(1) << (d % 64);
    }
  }
  auto dependsOn = [&](int32_t i, int32_t d) {
    return (closure[i][d / 64] >> (d % 64)) & 1;
  };

  // ---- dataflow + liveness simulation ----
  std::vector<RankState> state(world);
  for (int r = 0; r < world; r++) {
    RankState& rs = state[r];
    rs.work.assign(s.nChunks, 0);
    rs.scratch.assign(s.nScratch, 0);
    rs.scratchChunk.assign(s.nScratch, -1);
    rs.scratchCoded.assign(s.nScratch, 0);
    rs.issued.assign(n, 0);
    const uint64_t self = uint64_t(1) << r;
    if (s.collective == Collective::kAllgather) {
      rs.work[r] = self;  // the rank's own block is the only valid input
    } else {
      for (int c = 0; c < s.nChunks; c++) {
        rs.work[c] = self;
      }
    }
  }

  // Arrival effect of a matched message at its receiver.
  auto applyArrival = [&](Msg& m) -> std::optional<VerifyError> {
    RankState& rs = state[m.recvRank];
    const Operands& o = ops[m.recvRank][m.recvStep];
    const Step& st = s.steps[m.recvStep];
    if (st.op == StepOp::kRecv) {
      if (o.slot >= 0) {
        rs.scratch[o.slot] = m.mask;
        rs.scratchChunk[o.slot] = o.chunk;
        rs.scratchCoded[o.slot] = m.coded ? 1 : 0;
      } else {
        rs.work[o.chunk] = m.mask;
      }
    } else {  // recv_reduce
      if (rs.work[o.chunk] == 0) {
        return err(VerifyCode::kStaleRead, m.recvRank, m.recvStep,
                   "recv_reduce folds into an unwritten chunk");
      }
      if (rs.work[o.chunk] & m.mask) {
        std::ostringstream msg;
        msg << "chunk " << o.chunk << " already holds contributions "
            << maskStr(rs.work[o.chunk]) << "; folding "
            << maskStr(m.mask) << " from rank " << m.sendRank
            << " would reduce " << maskStr(rs.work[o.chunk] & m.mask)
            << " twice";
        return err(VerifyCode::kChunkReducedTwice, m.recvRank, m.recvStep,
                   msg.str());
      }
      rs.work[o.chunk] |= m.mask;
      rs.scratch[o.slot] = m.mask;
      rs.scratchChunk[o.slot] = o.chunk;
      rs.scratchCoded[o.slot] = 0;
    }
    m.applied = true;
    return std::nullopt;
  };

  // A dependency edge is satisfied when the dep step's *effects* are
  // visible: locals on issue, sends once the matching receive is posted
  // (the interpreter's drain), receives once the payload has arrived
  // (matching send issued) and been applied.
  auto depDone = [&](int r, int32_t d) {
    const Operands& o = ops[r][d];
    if (!o.active) {
      return true;
    }
    if (!state[r].issued[d]) {
      return false;
    }
    const StepOp op = s.steps[d].op;
    if (op == StepOp::kSend) {
      return msgs[msgOf[r][d]].recvIssued;
    }
    if (isRecvKind(op)) {
      return msgs[msgOf[r][d]].applied;
    }
    return true;
  };

  // Issue-time effect of a step (wire arrivals excepted).
  std::vector<Access> accesses, inflight;
  auto issueStep = [&](int r, int32_t i) -> std::optional<VerifyError> {
    RankState& rs = state[r];
    const Operands& o = ops[r][i];
    const Step& st = s.steps[i];
    // Hazard: this step's accesses must commute with the asynchronous
    // tail of every wire step already issued on this rank unless a
    // dependency path orders them. (A send's source is read until the
    // drain a dependency edge performs; a receive's landing region is
    // written at arrival; a recv_reduce's fold into its chunk is
    // deferred to the first dependency demand. The interpreter only
    // synchronizes on declared edges, so nothing else orders these.)
    accessesOf(st.op, o, st.flags, accesses);
    for (int32_t q = 0; q < n; q++) {
      if (q == i || !rs.issued[q] || !ops[r][q].active ||
          !isWire(s.steps[q].op) || dependsOn(i, q)) {
        continue;
      }
      accessesOf(s.steps[q].op, ops[r][q], s.steps[q].flags, inflight);
      for (const Access& a : accesses) {
        for (const Access& b : inflight) {
          if (a.slot == b.slot && a.idx == b.idx &&
              accessesConflict(b.kind, a.kind)) {
            std::ostringstream msg;
            msg << "touches " << (a.slot ? "slot " : "chunk ") << a.idx
                << " while wire step " << q
                << " is in flight with no dependency path between them";
            return err(VerifyCode::kHazard, r, i, msg.str());
          }
        }
      }
    }
    switch (st.op) {
      case StepOp::kSend: {
        uint64_t mask;
        if (o.slot >= 0) {
          if (rs.scratchChunk[o.slot] != o.chunk) {
            std::ostringstream msg;
            msg << "slot " << o.slot << " holds chunk "
                << rs.scratchChunk[o.slot] << ", step sends chunk "
                << o.chunk;
            return err(VerifyCode::kBadStep, r, i, msg.str());
          }
          const bool coded = (st.flags & Step::kFlagCoded) != 0;
          if (coded != (rs.scratchCoded[o.slot] != 0)) {
            return err(VerifyCode::kBadStep, r, i,
                       coded ? "coded send from an un-encoded slot"
                             : "un-coded send from an encoded slot");
          }
          mask = rs.scratch[o.slot];
        } else {
          mask = rs.work[o.chunk];
        }
        if (mask == 0) {
          return err(VerifyCode::kStaleRead, r, i,
                     "send reads an unwritten region");
        }
        msgs[msgOf[r][i]].mask = mask;
        msgs[msgOf[r][i]].sendIssued = true;
        return std::nullopt;
      }
      case StepOp::kRecv:
      case StepOp::kRecvReduce:
        msgs[msgOf[r][i]].recvIssued = true;
        return std::nullopt;
      case StepOp::kReduceLocal: {
        if (rs.scratch[o.slot] == 0) {
          return err(VerifyCode::kStaleRead, r, i,
                     "reduce_local reads an unwritten slot");
        }
        if (rs.scratchCoded[o.slot]) {
          return err(VerifyCode::kBadStep, r, i,
                     "reduce_local on a coded slot (decode first)");
        }
        if (rs.scratchChunk[o.slot] != o.chunk) {
          std::ostringstream msg;
          msg << "slot " << o.slot << " holds chunk "
              << rs.scratchChunk[o.slot] << ", step folds into chunk "
              << o.chunk;
          return err(VerifyCode::kBadStep, r, i, msg.str());
        }
        if (rs.work[o.chunk] == 0) {
          return err(VerifyCode::kStaleRead, r, i,
                     "reduce_local folds into an unwritten chunk");
        }
        if (rs.work[o.chunk] & rs.scratch[o.slot]) {
          std::ostringstream msg;
          msg << "chunk " << o.chunk << " already holds contributions "
              << maskStr(rs.work[o.chunk]) << "; folding "
              << maskStr(rs.scratch[o.slot]) << " would reduce "
              << maskStr(rs.work[o.chunk] & rs.scratch[o.slot])
              << " twice";
          return err(VerifyCode::kChunkReducedTwice, r, i, msg.str());
        }
        rs.work[o.chunk] |= rs.scratch[o.slot];
        return std::nullopt;
      }
      case StepOp::kCopy:
        if (st.flags & Step::kFlagToSlot) {
          if (rs.work[o.chunk] == 0) {
            return err(VerifyCode::kStaleRead, r, i,
                       "copy reads an unwritten chunk");
          }
          rs.scratch[o.slot] = rs.work[o.chunk];
          rs.scratchChunk[o.slot] = o.chunk;
          rs.scratchCoded[o.slot] = 0;
        } else {
          if (rs.scratch[o.slot] == 0) {
            return err(VerifyCode::kStaleRead, r, i,
                       "copy reads an unwritten slot");
          }
          if (rs.scratchCoded[o.slot]) {
            return err(VerifyCode::kBadStep, r, i,
                       "copy from a coded slot (decode instead)");
          }
          if (rs.scratchChunk[o.slot] != o.chunk) {
            std::ostringstream msg;
            msg << "slot " << o.slot << " holds chunk "
                << rs.scratchChunk[o.slot] << ", step copies to chunk "
                << o.chunk;
            return err(VerifyCode::kBadStep, r, i, msg.str());
          }
          rs.work[o.chunk] = rs.scratch[o.slot];
        }
        return std::nullopt;
      case StepOp::kEncode:
        if (rs.work[o.chunk] == 0) {
          return err(VerifyCode::kStaleRead, r, i,
                     "encode reads an unwritten chunk");
        }
        rs.scratch[o.slot] = rs.work[o.chunk];
        rs.scratchChunk[o.slot] = o.chunk;
        rs.scratchCoded[o.slot] = 1;
        return std::nullopt;
      case StepOp::kDecode:
        if (rs.scratch[o.slot] == 0) {
          return err(VerifyCode::kStaleRead, r, i,
                     "decode reads an unwritten slot");
        }
        if (!rs.scratchCoded[o.slot]) {
          return err(VerifyCode::kBadStep, r, i,
                     "decode of an un-encoded slot");
        }
        if (rs.scratchChunk[o.slot] != o.chunk) {
          std::ostringstream msg;
          msg << "slot " << o.slot << " holds chunk "
              << rs.scratchChunk[o.slot] << ", step decodes to chunk "
              << o.chunk;
          return err(VerifyCode::kBadStep, r, i, msg.str());
        }
        rs.work[o.chunk] = rs.scratch[o.slot];
        return std::nullopt;
    }
    TC_THROW(EnforceError, "unknown step op ", static_cast<int>(st.op));
  };

  bool progress = true;
  while (progress) {
    progress = false;
    for (Msg& m : msgs) {
      if (m.sendIssued && m.recvIssued && !m.applied) {
        if (auto e = applyArrival(m)) {
          return e;
        }
        progress = true;
      }
    }
    for (int r = 0; r < world; r++) {
      RankState& rs = state[r];
      while (rs.ptr < n) {
        const int32_t i = topo[rs.ptr];
        if (!ops[r][i].active) {
          rs.issued[i] = 1;
          rs.ptr++;
          progress = true;
          continue;
        }
        bool ready = true;
        for (int32_t d : s.steps[i].deps) {
          if (!depDone(r, d)) {
            ready = false;
            break;
          }
        }
        if (!ready) {
          break;
        }
        if (auto e = issueStep(r, i)) {
          return e;
        }
        rs.issued[i] = 1;
        rs.ptr++;
        progress = true;
      }
    }
  }
  for (int r = 0; r < world; r++) {
    if (state[r].ptr < n) {
      const int32_t i = topo[state[r].ptr];
      std::ostringstream msg;
      msg << "no global progress possible; this step's dependencies can "
             "never complete";
      return err(VerifyCode::kDeadlock, r, i, msg.str());
    }
  }

  // ---- completeness: the collective's postcondition ----
  const uint64_t full =
      world == 64 ? ~uint64_t(0) : (uint64_t(1) << world) - 1;
  for (int r = 0; r < world; r++) {
    for (int c = 0; c < s.nChunks; c++) {
      uint64_t expected;
      switch (s.collective) {
        case Collective::kAllreduce:
          expected = full;
          break;
        case Collective::kReduceScatter:
          if (c != r) {
            continue;  // only the rank's own block is the output
          }
          expected = full;
          break;
        case Collective::kAllgather:
          expected = uint64_t(1) << c;
          break;
        default:
          TC_THROW(EnforceError, "unknown collective");
      }
      if (state[r].work[c] != expected) {
        std::ostringstream msg;
        msg << "chunk " << c << " at rank " << r << " ends holding "
            << maskStr(state[r].work[c]) << ", expected "
            << maskStr(expected);
        return err(VerifyCode::kUndelivered, r, -1, msg.str());
      }
    }
  }
  return std::nullopt;
}

void verifyOrThrow(const Schedule& s) {
  if (auto e = verify(s)) {
    TC_THROW(EnforceError, e->format(s));
  }
}

std::vector<int32_t> topoOrder(const Schedule& s, int rank) {
  (void)rank;  // deps are rank-independent; every rank shares one order
  std::vector<int32_t> order;
  int cycleStep = -1;
  TC_ENFORCE(tryTopo(s, &order, &cycleStep), "schedule \"", s.name,
             "\": dependency cycle through step ", cycleStep);
  return order;
}

}  // namespace schedule
}  // namespace tpucoll
