#include "tpucoll/schedule/generators.h"

#include <algorithm>
#include <utility>

#include "tpucoll/common/logging.h"

namespace tpucoll {
namespace schedule {

namespace {

using E = RankExpr;

int32_t push(Schedule& s, Step st) {
  s.steps.push_back(std::move(st));
  return static_cast<int32_t>(s.steps.size() - 1);
}

std::string tag(const char* base, int t, int j) {
  return std::string(base) + "_" + std::to_string(t) + "_" + std::to_string(j);
}

bool isPow2(int n) { return n >= 1 && (n & (n - 1)) == 0; }

std::vector<int> primeFactors(int n) {
  std::vector<int> factors;
  for (int p = 2; p * p <= n; p++) {
    while (n % p == 0) {
      factors.push_back(p);
      n /= p;
    }
  }
  if (n > 1) {
    factors.push_back(n);
  }
  return factors;
}

// --- ring (allreduce, pipeline depth k) --------------------------------
//
// Chunk (a, j) = segment owned by rank a, sub-chunk j: id = a * k + j.
// The k sub-streams pipeline independently; within one, the classic
// two-deep slot rotation (fold round t - 2 before reusing its slot).
Schedule ringAllreduce(int world, int depth) {
  TC_ENFORCE(depth >= 1 && depth <= 64, "ring: depth must be in [1, 64]");
  Schedule s;
  s.name = "ring_p" + std::to_string(world) +
           (depth > 1 ? "_k" + std::to_string(depth) : "");
  s.collective = Collective::kAllreduce;
  s.worldSize = world;
  s.nChunks = world * depth;
  const int rounds = world - 1;
  const int par = std::min(2, rounds);
  s.nScratch = par * depth;
  if (world == 1) {
    return s;
  }
  std::vector<std::vector<int32_t>> sId(rounds, std::vector<int32_t>(depth));
  std::vector<std::vector<int32_t>> rrId(rounds, std::vector<int32_t>(depth));
  std::vector<std::vector<int32_t>> agR(rounds, std::vector<int32_t>(depth));
  for (int t = 0; t < rounds; t++) {
    for (int j = 0; j < depth; j++) {
      Step snd;
      snd.op = StepOp::kSend;
      snd.peer = E::ring(1);
      snd.chunk = E::ring(-t, depth, j);
      if (t > 0) {
        snd.deps = {rrId[t - 1][j]};
      }
      snd.note = tag("rs_s", t, j);
      sId[t][j] = push(s, std::move(snd));

      Step rr;
      rr.op = StepOp::kRecvReduce;
      rr.peer = E::ring(-1);
      rr.chunk = E::ring(-1 - t, depth, j);
      rr.slot = E::constant((t % par) * depth + j);
      if (t >= 2) {
        rr.deps = {rrId[t - 2][j]};
      }
      rr.note = tag("rs_rr", t, j);
      rrId[t][j] = push(s, std::move(rr));
    }
  }
  for (int t = 0; t < rounds; t++) {
    for (int j = 0; j < depth; j++) {
      Step snd;
      snd.op = StepOp::kSend;
      snd.peer = E::ring(1);
      snd.chunk = E::ring(1 - t, depth, j);
      snd.deps = {t == 0 ? rrId[rounds - 1][j] : agR[t - 1][j]};
      snd.note = tag("ag_s", t, j);
      push(s, std::move(snd));

      Step rcv;
      rcv.op = StepOp::kRecv;
      rcv.peer = E::ring(-1);
      rcv.chunk = E::ring(-t, depth, j);
      // Drain the reduce-scatter send that read this chunk before the
      // gathered copy overwrites it in place.
      rcv.deps = {sId[t][j]};
      rcv.note = tag("ag_r", t, j);
      agR[t][j] = push(s, std::move(rcv));
    }
  }
  return s;
}

// --- ring_rs (reduce-scatter) ------------------------------------------
//
// Shifted by one versus the allreduce phase so rank r finishes holding
// chunk r (the standalone contract): round t sends chunk r - 1 - t,
// folds chunk r - 2 - t; the final fold lands on chunk r.
Schedule ringReduceScatter(int world) {
  Schedule s;
  s.name = "ring_rs_p" + std::to_string(world);
  s.collective = Collective::kReduceScatter;
  s.worldSize = world;
  s.nChunks = world;
  const int rounds = world - 1;
  const int par = std::min(2, rounds);
  s.nScratch = par;
  if (world == 1) {
    return s;
  }
  std::vector<int32_t> rrId(rounds);
  for (int t = 0; t < rounds; t++) {
    Step snd;
    snd.op = StepOp::kSend;
    snd.peer = E::ring(1);
    snd.chunk = E::ring(-1 - t);
    if (t > 0) {
      snd.deps = {rrId[t - 1]};
    }
    snd.note = tag("rs_s", t, 0);
    push(s, std::move(snd));

    Step rr;
    rr.op = StepOp::kRecvReduce;
    rr.peer = E::ring(-1);
    rr.chunk = E::ring(-2 - t);
    rr.slot = E::constant(t % par);
    if (t >= 2) {
      rr.deps = {rrId[t - 2]};
    }
    rr.note = tag("rs_rr", t, 0);
    rrId[t] = push(s, std::move(rr));
  }
  return s;
}

// --- ring_ag (allgather) -----------------------------------------------
Schedule ringAllgather(int world) {
  Schedule s;
  s.name = "ring_ag_p" + std::to_string(world);
  s.collective = Collective::kAllgather;
  s.worldSize = world;
  s.nChunks = world;
  s.nScratch = 0;
  if (world == 1) {
    return s;
  }
  const int rounds = world - 1;
  std::vector<int32_t> agR(rounds);
  for (int t = 0; t < rounds; t++) {
    Step snd;
    snd.op = StepOp::kSend;
    snd.peer = E::ring(1);
    snd.chunk = E::ring(-t);
    if (t > 0) {
      snd.deps = {agR[t - 1]};
    }
    snd.note = tag("ag_s", t, 0);
    push(s, std::move(snd));

    Step rcv;
    rcv.op = StepOp::kRecv;
    rcv.peer = E::ring(-1);
    rcv.chunk = E::ring(-1 - t);
    rcv.note = tag("ag_r", t, 0);
    agR[t] = push(s, std::move(rcv));
  }
  return s;
}

// --- hd family (power-of-two halving-doubling) -------------------------
//
// Per stage, per rank: window = the blockSize chunks sharing the rank's
// high bits; the half containing the rank's own index is kept (so rank
// r finishes the reduce-scatter owning chunk r), the other half is
// sent. Chunk ids are rank-dependent -> table expressions. Stages are
// fully barriered: every stage-s step depends on all stage-(s-1) steps,
// exactly the native phase structure.
enum class HdPhase { kReduceScatter, kAllgather, kBoth };

Schedule hdSchedule(int world, HdPhase phase) {
  TC_ENFORCE(isPow2(world), "hd: world must be a power of two, got ", world);
  Schedule s;
  s.worldSize = world;
  s.nChunks = world;
  s.nScratch = phase == HdPhase::kAllgather ? 0 : world / 2;
  switch (phase) {
    case HdPhase::kReduceScatter:
      s.name = "hd_rs_p" + std::to_string(world);
      s.collective = Collective::kReduceScatter;
      break;
    case HdPhase::kAllgather:
      s.name = "hd_ag_p" + std::to_string(world);
      s.collective = Collective::kAllgather;
      break;
    case HdPhase::kBoth:
      s.name = "hd_p" + std::to_string(world);
      s.collective = Collective::kAllreduce;
      break;
  }
  if (world == 1) {
    return s;
  }
  int numStages = 0;
  while ((1 << numStages) < world) {
    numStages++;
  }
  auto windows = [&](int stage, std::vector<int64_t>* kept,
                     std::vector<int64_t>* sent, int i) {
    const int blockSize = world >> stage;
    const int dist = blockSize / 2;
    for (int r = 0; r < world; r++) {
      const int winStart = r & ~(blockSize - 1);
      const bool upper = (r & dist) != 0;
      (*kept)[r] = winStart + (upper ? dist : 0) + i;
      (*sent)[r] = winStart + (upper ? 0 : dist) + i;
    }
  };
  std::vector<int32_t> prev;
  if (phase != HdPhase::kAllgather) {
    for (int stage = 0; stage < numStages; stage++) {
      const int dist = (world >> stage) / 2;
      std::vector<int32_t> cur;
      for (int i = 0; i < dist; i++) {
        std::vector<int64_t> kept(world), sent(world);
        windows(stage, &kept, &sent, i);
        Step snd;
        snd.op = StepOp::kSend;
        snd.peer = E::xorOf(dist);
        snd.chunk = E::tableOf(sent);
        snd.deps = prev;
        snd.note = tag("rs_s", stage, i);
        cur.push_back(push(s, std::move(snd)));

        Step rr;
        rr.op = StepOp::kRecvReduce;
        rr.peer = E::xorOf(dist);
        rr.chunk = E::tableOf(kept);
        rr.slot = E::constant(i);
        rr.deps = prev;
        rr.note = tag("rs_rr", stage, i);
        cur.push_back(push(s, std::move(rr)));
      }
      prev = cur;
    }
  }
  if (phase != HdPhase::kReduceScatter) {
    for (int stage = numStages - 1; stage >= 0; stage--) {
      const int dist = (world >> stage) / 2;
      std::vector<int32_t> cur;
      for (int i = 0; i < dist; i++) {
        std::vector<int64_t> kept(world), sent(world);
        windows(stage, &kept, &sent, i);
        Step snd;
        snd.op = StepOp::kSend;
        snd.peer = E::xorOf(dist);
        snd.chunk = E::tableOf(kept);
        snd.deps = prev;
        snd.note = tag("ag_s", stage, i);
        cur.push_back(push(s, std::move(snd)));

        Step rcv;
        rcv.op = StepOp::kRecv;
        rcv.peer = E::xorOf(dist);
        rcv.chunk = E::tableOf(sent);
        rcv.deps = prev;
        rcv.note = tag("ag_r", stage, i);
        cur.push_back(push(s, std::move(rcv)));
      }
      prev = cur;
    }
  }
  return s;
}

// --- bcube (mixed-radix grouped hypercube allreduce) -------------------
//
// Stage st: ranks sharing all mixed-radix digits except digit st form a
// group of g = radices[st]; the window splits into g parts, part j goes
// to the member whose digit is j, contributions fold into the kept
// part. Guards deactivate the self-directed (j == own digit) steps; the
// allgather phase replays the stages in reverse with plain receives.
Schedule bcubeAllreduce(int world) {
  Schedule s;
  s.name = "bcube_p" + std::to_string(world);
  s.collective = Collective::kAllreduce;
  s.worldSize = world;
  s.nChunks = world;
  s.nScratch = world > 1 ? world : 0;
  if (world == 1) {
    return s;
  }
  const std::vector<int> radices = primeFactors(world);
  const int numStages = static_cast<int>(radices.size());
  std::vector<int> stride(numStages);
  stride[0] = 1;
  for (int st = 1; st < numStages; st++) {
    stride[st] = stride[st - 1] * radices[st - 1];
  }
  // Per-stage window geometry: winCount is rank-independent, winStart
  // per rank; saved per stage so the allgather phase can replay it.
  std::vector<std::vector<int>> winStartAt(numStages + 1,
                                           std::vector<int>(world, 0));
  std::vector<int> winCountAt(numStages + 1, world);
  for (int st = 0; st < numStages; st++) {
    const int g = radices[st];
    const int part = winCountAt[st] / g;
    for (int r = 0; r < world; r++) {
      const int digit = (r / stride[st]) % g;
      winStartAt[st + 1][r] = winStartAt[st][r] + digit * part;
    }
    winCountAt[st + 1] = part;
  }
  auto stageTables = [&](int st, int j, int i, std::vector<int64_t>* guard,
                         std::vector<int64_t>* peer,
                         std::vector<int64_t>* partChunk,
                         std::vector<int64_t>* myChunk) {
    const int g = radices[st];
    const int part = winCountAt[st] / g;
    for (int r = 0; r < world; r++) {
      const int digit = (r / stride[st]) % g;
      (*guard)[r] = digit == j ? 0 : 1;
      (*peer)[r] = digit == j ? (r + 1) % world : r + (j - digit) * stride[st];
      (*partChunk)[r] = winStartAt[st][r] + j * part + i;
      (*myChunk)[r] = winStartAt[st][r] + digit * part + i;
    }
  };
  std::vector<int32_t> prev;
  for (int st = 0; st < numStages; st++) {
    const int g = radices[st];
    const int part = winCountAt[st] / g;
    std::vector<int32_t> cur;
    for (int j = 0; j < g; j++) {
      for (int i = 0; i < part; i++) {
        std::vector<int64_t> guard(world), peer(world), partChunk(world),
            myChunk(world);
        stageTables(st, j, i, &guard, &peer, &partChunk, &myChunk);
        Step snd;
        snd.op = StepOp::kSend;
        snd.guard = E::tableOf(guard);
        snd.peer = E::tableOf(peer);
        snd.chunk = E::tableOf(partChunk);
        snd.deps = prev;
        snd.note = tag("rs_s", st, j * part + i);
        cur.push_back(push(s, std::move(snd)));

        Step rr;
        rr.op = StepOp::kRecvReduce;
        rr.guard = E::tableOf(guard);
        rr.peer = E::tableOf(peer);
        rr.chunk = E::tableOf(myChunk);
        rr.slot = E::constant(j * part + i);
        rr.deps = prev;
        rr.note = tag("rs_rr", st, j * part + i);
        cur.push_back(push(s, std::move(rr)));
      }
    }
    prev = cur;
  }
  for (int st = numStages - 1; st >= 0; st--) {
    const int g = radices[st];
    const int part = winCountAt[st] / g;
    std::vector<int32_t> cur;
    for (int j = 0; j < g; j++) {
      for (int i = 0; i < part; i++) {
        std::vector<int64_t> guard(world), peer(world), partChunk(world),
            myChunk(world);
        stageTables(st, j, i, &guard, &peer, &partChunk, &myChunk);
        Step snd;
        snd.op = StepOp::kSend;
        snd.guard = E::tableOf(guard);
        snd.peer = E::tableOf(peer);
        snd.chunk = E::tableOf(myChunk);
        snd.deps = prev;
        snd.note = tag("ag_s", st, j * part + i);
        cur.push_back(push(s, std::move(snd)));

        Step rcv;
        rcv.op = StepOp::kRecv;
        rcv.guard = E::tableOf(guard);
        rcv.peer = E::tableOf(peer);
        rcv.chunk = E::tableOf(partChunk);
        rcv.deps = prev;
        rcv.note = tag("ag_r", st, j * part + i);
        cur.push_back(push(s, std::move(rcv)));
      }
    }
    prev = cur;
  }
  return s;
}

// --- ring_bf16 (coded-wire ring allreduce) -----------------------------
//
// Each hop encodes the outgoing chunk to bf16 in a scratch slot, sends
// the coded bytes, receives coded bytes into another slot, saves the
// local partial, decodes the arrival over the chunk and folds the saved
// partial back — recv_reduce cannot fold coded bytes, so the codec is
// explicit IR. Slots rotate two-deep per role (encode/recv/save).
Schedule ringBf16Allreduce(int world) {
  Schedule s;
  s.name = "ring_bf16_p" + std::to_string(world);
  s.collective = Collective::kAllreduce;
  s.worldSize = world;
  s.nChunks = world;
  const int rounds = world - 1;
  const int par = std::min(2, rounds);
  s.nScratch = 3 * par;
  if (world == 1) {
    return s;
  }
  // Global round u: reduce-scatter rounds [0, rounds), allgather rounds
  // [rounds, 2 * rounds). Per-u ids for the slot-rotation deps.
  std::vector<int32_t> sndId(2 * rounds), rcvId(2 * rounds),
      doneId(2 * rounds);
  auto slotE = [&](int u) { return E::constant(u % par); };
  auto slotR = [&](int u) { return E::constant(par + u % par); };
  for (int t = 0; t < rounds; t++) {
    const int u = t;
    Step enc;
    enc.op = StepOp::kEncode;
    enc.chunk = E::ring(-t);
    enc.slot = slotE(u);
    if (t > 0) {
      enc.deps.push_back(doneId[u - 1]);  // chunk r-t finalized last round
    }
    if (u >= par) {
      enc.deps.push_back(sndId[u - par]);  // drain the slot's last send
    }
    enc.note = tag("rs_e", t, 0);
    const int32_t encId = push(s, std::move(enc));

    Step snd;
    snd.op = StepOp::kSend;
    snd.flags = Step::kFlagCoded;
    snd.peer = E::ring(1);
    snd.chunk = E::ring(-t);
    snd.slot = slotE(u);
    snd.deps = {encId};
    snd.note = tag("rs_s", t, 0);
    sndId[u] = push(s, std::move(snd));

    Step rcv;
    rcv.op = StepOp::kRecv;
    rcv.flags = Step::kFlagCoded;
    rcv.peer = E::ring(-1);
    rcv.chunk = E::ring(-1 - t);
    rcv.slot = slotR(u);
    if (u >= par) {
      rcv.deps = {doneId[u - par]};  // the slot's last decode consumed it
    }
    rcv.note = tag("rs_r", t, 0);
    rcvId[u] = push(s, std::move(rcv));

    Step save;
    save.op = StepOp::kCopy;
    save.flags = Step::kFlagToSlot;
    save.chunk = E::ring(-1 - t);
    save.slot = E::constant(2 * par + u % par);
    save.note = tag("rs_save", t, 0);
    const int32_t saveId = push(s, std::move(save));

    Step dec;
    dec.op = StepOp::kDecode;
    dec.chunk = E::ring(-1 - t);
    dec.slot = slotR(u);
    dec.deps = {rcvId[u], saveId};
    dec.note = tag("rs_d", t, 0);
    const int32_t decId = push(s, std::move(dec));

    Step fold;
    fold.op = StepOp::kReduceLocal;
    fold.chunk = E::ring(-1 - t);
    fold.slot = E::constant(2 * par + u % par);
    fold.deps = {decId};
    fold.note = tag("rs_rl", t, 0);
    doneId[u] = push(s, std::move(fold));
  }
  for (int t = 0; t < rounds; t++) {
    const int u = rounds + t;
    Step enc;
    enc.op = StepOp::kEncode;
    enc.chunk = E::ring(1 - t);
    enc.slot = slotE(u);
    enc.deps = {doneId[u - 1], sndId[u - par]};
    enc.note = tag("ag_e", t, 0);
    const int32_t encId = push(s, std::move(enc));

    Step snd;
    snd.op = StepOp::kSend;
    snd.flags = Step::kFlagCoded;
    snd.peer = E::ring(1);
    snd.chunk = E::ring(1 - t);
    snd.slot = slotE(u);
    snd.deps = {encId};
    snd.note = tag("ag_s", t, 0);
    sndId[u] = push(s, std::move(snd));

    Step rcv;
    rcv.op = StepOp::kRecv;
    rcv.flags = Step::kFlagCoded;
    rcv.peer = E::ring(-1);
    rcv.chunk = E::ring(-t);
    rcv.slot = slotR(u);
    rcv.deps = {doneId[u - par]};
    rcv.note = tag("ag_r", t, 0);
    rcvId[u] = push(s, std::move(rcv));

    Step dec;
    dec.op = StepOp::kDecode;
    dec.chunk = E::ring(-t);
    dec.slot = slotR(u);
    dec.deps = {rcvId[u]};
    dec.note = tag("ag_d", t, 0);
    doneId[u] = push(s, std::move(dec));
  }
  return s;
}

// --- hier (2-level hierarchy allreduce) --------------------------------
//
// P = L hosts x h ranks. Members push every chunk to their host leader
// (fold on arrival), the L leaders ring-allreduce the host sums, then
// fan the result back out. Guards split the one program into leader and
// member roles; nChunks = L so the leader ring is chunk-balanced.
Schedule hierAllreduce(int world, int ranksPerHost) {
  TC_ENFORCE(ranksPerHost >= 1 && world % ranksPerHost == 0,
             "hier: ranks_per_host (", ranksPerHost, ") must divide world (",
             world, ")");
  const int h = ranksPerHost;
  const int hosts = world / h;
  Schedule s;
  s.name = "hier_p" + std::to_string(world) + "_h" + std::to_string(h);
  s.collective = Collective::kAllreduce;
  s.worldSize = world;
  s.nChunks = hosts;
  const int ringRounds = hosts - 1;
  const int ringPar = std::min(2, std::max(ringRounds, 0));
  s.nScratch = (h - 1) * hosts + ringPar;
  if (world == 1) {
    return s;
  }
  std::vector<int64_t> leaderGuard(world), nextLeader(world),
      prevLeader(world);
  for (int r = 0; r < world; r++) {
    const bool leader = r % h == 0;
    leaderGuard[r] = leader ? 1 : 0;
    const int l = r / h;
    nextLeader[r] = leader ? ((l + 1) % hosts) * h : (r + 1) % world;
    prevLeader[r] = leader ? ((l - 1 + hosts) % hosts) * h : (r + 1) % world;
  }
  std::vector<int32_t> phase1;
  std::vector<std::vector<int32_t>> upSend(h, std::vector<int32_t>(hosts));
  for (int m = 1; m < h; m++) {
    std::vector<int64_t> memberGuard(world);
    for (int r = 0; r < world; r++) {
      memberGuard[r] = r % h == m ? 1 : 0;
    }
    for (int c = 0; c < hosts; c++) {
      Step snd;
      snd.op = StepOp::kSend;
      snd.guard = E::tableOf(memberGuard);
      snd.peer = E::ring(-m);
      snd.chunk = E::constant(c);
      snd.note = tag("up_s", m, c);
      upSend[m][c] = push(s, std::move(snd));
      phase1.push_back(upSend[m][c]);

      Step rr;
      rr.op = StepOp::kRecvReduce;
      rr.guard = E::tableOf(leaderGuard);
      rr.peer = E::ring(m);
      rr.chunk = E::constant(c);
      rr.slot = E::constant((m - 1) * hosts + c);
      rr.note = tag("up_rr", m, c);
      phase1.push_back(push(s, std::move(rr)));
    }
  }
  // Leader ring allreduce over the host sums (shift +1: leader l ends
  // holding chunk l + 1 reduced, then gathers the rest).
  std::vector<int32_t> phase2 = phase1;
  if (hosts > 1) {
    std::vector<int32_t> lsId(ringRounds), lrrId(ringRounds),
        lagR(ringRounds);
    auto leaderChunk = [&](int shift) {
      std::vector<int64_t> t(world);
      for (int r = 0; r < world; r++) {
        t[r] = r % h == 0 ? ((r / h + shift) % hosts + hosts) % hosts : 0;
      }
      return E::tableOf(std::move(t));
    };
    for (int t = 0; t < ringRounds; t++) {
      Step snd;
      snd.op = StepOp::kSend;
      snd.guard = E::tableOf(leaderGuard);
      snd.peer = E::tableOf(nextLeader);
      snd.chunk = leaderChunk(-t);
      snd.deps = t == 0 ? phase1 : std::vector<int32_t>{lrrId[t - 1]};
      snd.note = tag("lr_s", t, 0);
      lsId[t] = push(s, std::move(snd));

      Step rr;
      rr.op = StepOp::kRecvReduce;
      rr.guard = E::tableOf(leaderGuard);
      rr.peer = E::tableOf(prevLeader);
      rr.chunk = leaderChunk(-1 - t);
      rr.slot = E::constant((h - 1) * hosts + t % ringPar);
      // t >= 2: slot reuse (ringPar rotation). t < 2: anchor on the
      // phase-1 folds so every later ring step (they all chain through
      // lrrId) has a dependency path back to the host-local
      // recv_reduces — round t's send ships chunk (l - t), which must
      // already hold this host's member contributions.
      rr.deps = t >= 2 ? std::vector<int32_t>{lrrId[t - 2]} : phase1;
      rr.note = tag("lr_rr", t, 0);
      lrrId[t] = push(s, std::move(rr));
    }
    for (int t = 0; t < ringRounds; t++) {
      Step snd;
      snd.op = StepOp::kSend;
      snd.guard = E::tableOf(leaderGuard);
      snd.peer = E::tableOf(nextLeader);
      snd.chunk = leaderChunk(1 - t);
      snd.deps = {t == 0 ? lrrId[ringRounds - 1] : lagR[t - 1]};
      snd.note = tag("lg_s", t, 0);
      push(s, std::move(snd));

      Step rcv;
      rcv.op = StepOp::kRecv;
      rcv.guard = E::tableOf(leaderGuard);
      rcv.peer = E::tableOf(prevLeader);
      rcv.chunk = leaderChunk(-t);
      rcv.deps = {lsId[t]};
      rcv.note = tag("lg_r", t, 0);
      lagR[t] = push(s, std::move(rcv));
    }
    phase2.clear();
    for (int t = 0; t < ringRounds; t++) {
      phase2.push_back(lsId[t]);
      phase2.push_back(lrrId[t]);
      phase2.push_back(lagR[t]);
    }
  }
  for (int m = 1; m < h; m++) {
    std::vector<int64_t> memberGuard(world);
    for (int r = 0; r < world; r++) {
      memberGuard[r] = r % h == m ? 1 : 0;
    }
    for (int c = 0; c < hosts; c++) {
      Step snd;
      snd.op = StepOp::kSend;
      snd.guard = E::tableOf(leaderGuard);
      snd.peer = E::ring(m);
      snd.chunk = E::constant(c);
      snd.deps = phase2;
      snd.note = tag("down_s", m, c);
      push(s, std::move(snd));

      Step rcv;
      rcv.op = StepOp::kRecv;
      rcv.guard = E::tableOf(memberGuard);
      rcv.peer = E::ring(-m);
      rcv.chunk = E::constant(c);
      // Drain the member's own upward send before the reduced copy
      // overwrites the chunk in place.
      rcv.deps = {upSend[m][c]};
      rcv.note = tag("down_r", m, c);
      push(s, std::move(rcv));
    }
  }
  return s;
}

int param(const std::map<std::string, int>& params, const std::string& name,
          int fallback, std::vector<std::string>* known) {
  known->push_back(name);
  auto it = params.find(name);
  return it == params.end() ? fallback : it->second;
}

}  // namespace

Schedule generate(const std::string& family, int worldSize,
                  const std::map<std::string, int>& params) {
  TC_ENFORCE(worldSize >= 1 && worldSize <= 64,
             "schedule generators support worlds in [1, 64], got ", worldSize);
  std::vector<std::string> known;
  Schedule s;
  if (family == "ring") {
    s = ringAllreduce(worldSize, param(params, "depth", 1, &known));
  } else if (family == "ring_rs") {
    s = ringReduceScatter(worldSize);
  } else if (family == "ring_ag") {
    s = ringAllgather(worldSize);
  } else if (family == "hd") {
    s = hdSchedule(worldSize, HdPhase::kBoth);
  } else if (family == "hd_rs") {
    s = hdSchedule(worldSize, HdPhase::kReduceScatter);
  } else if (family == "hd_ag") {
    s = hdSchedule(worldSize, HdPhase::kAllgather);
  } else if (family == "bcube") {
    s = bcubeAllreduce(worldSize);
  } else if (family == "ring_bf16") {
    s = ringBf16Allreduce(worldSize);
  } else if (family == "hier") {
    s = hierAllreduce(worldSize,
                      param(params, "ranks_per_host", 1, &known));
  } else {
    TC_THROW(EnforceError, "unknown schedule family \"", family, "\"");
  }
  for (const auto& kv : params) {
    TC_ENFORCE(std::find(known.begin(), known.end(), kv.first) != known.end(),
               "schedule family \"", family, "\" has no param \"", kv.first,
               "\"");
  }
  return s;
}

std::vector<std::string> generatorFamilies() {
  return {"ring",  "ring_rs",   "ring_ag", "hd",  "hd_rs",
          "hd_ag", "bcube",     "ring_bf16", "hier"};
}

}  // namespace schedule
}  // namespace tpucoll
