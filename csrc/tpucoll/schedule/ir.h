// Collective schedule IR: algorithms as data.
//
// A Schedule is a rank-parameterized program over chunk ids — the same
// step list describes every rank, with per-step operand expressions
// (RankExpr) evaluated against the executing rank, the way GC3
// (arXiv:2201.11840) lifts collectives into a searchable program
// representation instead of a closed algorithm enum. Seven step opcodes
// cover everything the native schedules do on the wire:
//
//   send         post chunk bytes to a peer
//   recv         receive chunk bytes from a peer (overwrite)
//   recv_reduce  receive into a scratch slot, then fold into the chunk
//   reduce_local fold a scratch slot into a chunk
//   copy         move bytes between a chunk and a scratch slot
//   encode       bf16-encode a chunk into a scratch slot (wire codec)
//   decode       bf16-decode a scratch slot into a chunk
//
// Steps carry explicit dependency edges (indices into the step list,
// same-rank); everything not ordered by an edge may overlap. The
// verifier (verifier.h) statically proves a schedule computes its
// declared collective before the interpreter (interpreter.h) is allowed
// to lower it onto the transport; generators (generators.h) emit the
// native ring/halving-doubling/bcube algorithms — plus families no enum
// entry can express — as plain data for the tuner to search.
//
// Geometry: the payload is split into nChunks data chunks (evenBlocks,
// detail.h — the same split every native schedule uses), numbered
// [0, nChunks). Scratch slots [0, nScratch) are staging regions sized by
// the largest chunk; a step that touches a slot also names the data
// chunk giving the transfer its element count, so slots can be reused
// across rounds with different geometry.
#pragma once

#include <cstddef>
#include <cstdint>
#include <optional>
#include <string>
#include <vector>

namespace tpucoll {
namespace schedule {

// Step opcodes. tools/check's schedule-step-coverage rule requires every
// enumerator here to be handled in the verifier and interpreter
// switches — extend all three together.
enum class StepOp : uint8_t {
  kSend = 0,
  kRecv = 1,
  kRecvReduce = 2,
  kReduceLocal = 3,
  kCopy = 4,
  kEncode = 5,
  kDecode = 6,
};

const char* stepOpName(StepOp op);
std::optional<StepOp> stepOpFromName(const std::string& name);

// Rank-parameterized integer expression — the reason ONE program
// describes all ranks. Evaluated against (rank, worldSize):
//   const  -> a
//   ring   -> ((rank + a) mod world) * scale + offset
//   xor    -> ((rank ^ a) mod world) * scale + offset
//   table  -> table[rank]           (per-rank escape hatch)
// ring/xor cover the symmetric algorithms (ring shifts, halving-
// doubling partners); table expresses anything else (bcube mixed-radix
// partners, hierarchy roles) without growing the language.
struct RankExpr {
  enum class Kind : uint8_t { kConst = 0, kRing = 1, kXor = 2, kTable = 3 };
  Kind kind{Kind::kConst};
  int64_t a{0};
  int64_t scale{1};
  int64_t offset{0};
  std::vector<int64_t> table;

  int64_t eval(int rank, int worldSize) const;

  static RankExpr constant(int64_t v);
  static RankExpr ring(int64_t add, int64_t scale = 1, int64_t offset = 0);
  static RankExpr xorOf(int64_t mask, int64_t scale = 1, int64_t offset = 0);
  static RankExpr tableOf(std::vector<int64_t> values);
};

// One step of the program. Operand roles by opcode:
//   send        peer, chunk, slot (-1 = send the chunk region itself,
//               >=0 = send the slot's bytes with the chunk's geometry)
//   recv        peer, chunk, slot (-1 = land in the chunk, overwrite;
//               >=0 = land in the slot)
//   recv_reduce peer, chunk, slot (slot required: the landing region;
//               the payload is folded into the chunk on completion)
//   reduce_local chunk, slot (fold slot into chunk)
//   copy        chunk, slot (+kFlagToSlot: chunk -> slot; default
//               slot -> chunk)
//   encode      chunk, slot (bf16(chunk) -> slot)
//   decode      chunk, slot (f32(slot) -> chunk)
struct Step {
  StepOp op{StepOp::kSend};
  RankExpr peer = RankExpr::constant(-1);
  RankExpr chunk = RankExpr::constant(0);
  RankExpr slot = RankExpr::constant(-1);
  // Nonzero = this rank runs the step; zero = the step is skipped (its
  // dependents treat it as already satisfied). How hierarchy shapes
  // give leaders and members different programs inside one schedule.
  RankExpr guard = RankExpr::constant(1);
  // Flag bits (per-step modifiers).
  static constexpr uint8_t kFlagToSlot = 1;  // copy direction
  static constexpr uint8_t kFlagCoded = 2;   // send/recv move bf16 bytes
  uint8_t flags{0};
  // Pipeline depth for encode/decode steps: the codec walk is split
  // into `pipeline` deterministic sub-spans sharded across the codec
  // pool (wire_codec.h subSpans — byte-identical to the serial walk),
  // so a generator can stripe codec work the way the native pipelined
  // wire rings do. Must be 1 on every other opcode (the verifier
  // rejects it elsewhere: only codec steps have a sub-block walk).
  int32_t pipeline{1};
  // Indices into Schedule::steps that must complete (on this rank)
  // before this step may run. Any order; the verifier topo-sorts and
  // rejects cycles.
  std::vector<int32_t> deps;
  // Optional label surfaced by verifier errors and describe().
  std::string note;
};

enum class Collective : uint8_t {
  kAllreduce = 0,
  kReduceScatter = 1,
  kAllgather = 2,
};

const char* collectiveName(Collective c);
std::optional<Collective> collectiveFromName(const std::string& name);

struct Schedule {
  std::string name;
  Collective collective{Collective::kAllreduce};
  int worldSize{0};
  int nChunks{0};
  int nScratch{0};
  std::vector<Step> steps;
};

// One tuner-elected cell: "for (collective, world_size, dtype, log2
// size bucket), run this named schedule instead of the native
// algorithms". dtype "" matches any. Same rank-agreement contract as
// the tuning table: every rank installs byte-identical JSON.
struct Election {
  std::string collective;
  int worldSize{0};
  std::string dtype;
  int bucket{0};
  std::string schedule;
};

// Named schedules + per-cell elections, JSON round trip — the
// TPUCOLL_SCHEDULE_FILE interchange format (docs/schedules.md):
//   {"version":1,
//    "schedules":[{"name","collective","world_size","chunks","scratch",
//                  "steps":[{"op","peer","chunk","slot","guard","flags",
//                            "deps","note"}]}],
//    "elections":[{"collective","world_size","dtype","bucket",
//                  "schedule"}]}
// fromJson throws EnforceError on malformed input (including duplicate
// object keys — common/json.h strict mode), never installs partially.
class ScheduleTable {
 public:
  // Adds a schedule; the name must be unique (EnforceError otherwise).
  // Structural validation only — semantic verification happens at
  // install (Context::setScheduleTable runs the verifier on every
  // schedule matching the context's world size).
  void add(Schedule s);

  const Schedule* find(const std::string& name) const;
  const std::vector<Schedule>& schedules() const { return schedules_; }

  void elect(Election e);
  const std::vector<Election>& elections() const { return elections_; }

  // The schedule elected for this cell, or nullptr. Exact-dtype
  // elections win over wildcard ("") ones; bucket = floor(log2(nbytes))
  // must match exactly (elections are per-cell, not interpolated — a
  // schedule measured at one size says nothing about another).
  const Schedule* elected(const std::string& collective, int worldSize,
                          const std::string& dtype, size_t nbytes) const;

  bool empty() const { return schedules_.empty() && elections_.empty(); }

  std::string toJson() const;
  static ScheduleTable fromJson(const std::string& json);

 private:
  std::vector<Schedule> schedules_;
  std::vector<Election> elections_;
};

}  // namespace schedule
}  // namespace tpucoll
