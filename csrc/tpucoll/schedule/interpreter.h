// Schedule interpreter: lowers a verified schedule (ir.h, verifier.h)
// onto the transport through the plan-cache machinery.
//
// Two stages, split exactly where the cost is:
//
//   resolve(): per-rank, per-install. Evaluates every RankExpr,
//   topologically orders the steps (the same deterministic order the
//   verifier proved safe), assigns each matched wire message a unique
//   slot delta (identical on sender and receiver — both sides replay
//   the verifier's global FIFO matching), and precomputes the per-
//   (buffer, source) receive queues that waitRecv completions pop.
//   The result is immutable and shared by every call.
//
//   run(): per-call. Walks the resolved program in order; before a step
//   runs, its declared dependencies are completed (receive: wait for
//   arrival and fold; send: drain the buffer); local steps execute
//   inline. All bookkeeping (arrival flags, queue heads, outstanding
//   send counts) lives in plan scratch, and buffers/blocks come from
//   the plan — warm replays through the plan cache perform zero
//   allocations and zero registrations, the same `ubuf_creates`
//   steady-state contract the native algorithms meet.
//
// Determinism: receive completions may arrive in any order (waitRecv
// reports the source; the per-source FIFO attributes it), but folds
// execute in program order at dependency-completion time — the same
// payload and seed always produce the same float reduction order, which
// the chaos-determinism suite asserts via flightrec fingerprints.
//
// Phase attribution (profiler): posts -> kPost, waits -> kWireWait,
// folds -> kReduce, copy/encode -> kPack, decode -> kUnpack; the
// schedule label ("sched:<name>") flows into flightrec op records and
// profiler op summaries through the dispatch layer.
#pragma once

#include <chrono>
#include <cstddef>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "tpucoll/math.h"
#include "tpucoll/schedule/ir.h"
#include "tpucoll/types.h"

namespace tpucoll {

class Context;
namespace plan {
class Plan;
}
namespace transport {
class UnboundBuffer;
}

namespace schedule {

// One step with every expression evaluated for the executing rank,
// stored in execution (topological) order; `deps` are positions in
// that order, sorted ascending.
struct RStep {
  StepOp op{StepOp::kSend};
  bool active{false};
  int peer{-1};
  int chunk{0};
  int slot{-1};
  uint8_t flags{0};
  // encode/decode: codec-pool shard count for the sub-block walk
  // (wire_codec.h subSpans) — byte-identical to the serial walk.
  int32_t pipeline{1};
  uint32_t delta{0};  // wire steps: sub-slot of the collective's base slot
  std::vector<int32_t> deps;
};

struct ResolvedProgram {
  std::string name;
  std::string label;  // "sched:<name>"; stable storage for profiler tags
  Collective collective{Collective::kAllreduce};
  int worldSize{0};
  int rank{0};
  int nChunks{0};
  int nScratch{0};
  bool hasCoded{false};  // any bf16-coded wire step (float32-only)
  std::vector<RStep> steps;
  // Per buffer (0 = work, 1 = scratch arena), per source rank: positions
  // of this rank's receive steps in post order — the FIFO a waitRecv
  // completion from that source pops.
  std::vector<std::vector<int32_t>> recvQueues[2];
  size_t stateBytes() const;  // plan-scratch bookkeeping footprint
};

// Evaluate + order `s` for `rank`. Callers verify first
// (verifyOrThrow); resolve re-derives the global message matching the
// verifier proved consistent, so it runs on all ranks with identical
// results. Throws EnforceError on schedules the verifier would reject
// structurally (defense in depth), never returns a partial program.
std::shared_ptr<const ResolvedProgram> resolve(const Schedule& s, int rank);

// Execute one collective call. `work` is the full payload (count
// elements of elsize bytes) laid out in nChunks even blocks; `fn` is
// the reduction (may be null for fold-free programs, e.g. allgather).
// Plan slots used: userBuf 0 (work), stage 0 (scratch chunk arena),
// scratch 1 (bookkeeping) — entry points staging their own copies
// start at slot 2, and pass that stage's registration as `workBuf`
// (null = register `work` via plan.userBuf(0)).
void run(Context* ctx, plan::Plan& plan, const ResolvedProgram& prog,
         char* work, size_t count, size_t elsize, ReduceFn fn,
         DataType dtype, Slot slotBase, std::chrono::milliseconds timeout,
         transport::UnboundBuffer* workBuf = nullptr);

// The verified + per-rank-resolved schedule plane a Context holds
// behind its schedule mutex (Context::setScheduleTable installs one
// atomically; dispatch reads it once per collective call). Schedules
// whose worldSize differs from the context's are kept in `table` (so
// the installed JSON round-trips) but get no resolved program — their
// elections can never fire because elected() matches worldSize.
struct InstalledSchedules {
  std::shared_ptr<const ScheduleTable> table;
  std::map<std::string, std::shared_ptr<const ResolvedProgram>> programs;
};

// Verify (verifyOrThrow) and resolve every schedule in `table` matching
// `worldSize`, for `rank`. Throws on the first invalid schedule —
// installation is all-or-nothing.
std::shared_ptr<const InstalledSchedules> installSchedules(
    std::shared_ptr<const ScheduleTable> table, int rank, int worldSize);

// Process-lifetime interned copy of a label string — safe to hand to
// the flight recorder / profiler const char* algorithm fields even
// after the schedule table is reinstalled or cleared.
const char* internedLabel(const std::string& label);

}  // namespace schedule
}  // namespace tpucoll
