#include "tpucoll/schedule/ir.h"

#include <sstream>
#include <utility>

#include "tpucoll/common/json.h"
#include "tpucoll/common/logging.h"
#include "tpucoll/tuning/tuning_table.h"

namespace tpucoll {
namespace schedule {

const char* stepOpName(StepOp op) {
  switch (op) {
    case StepOp::kSend:
      return "send";
    case StepOp::kRecv:
      return "recv";
    case StepOp::kRecvReduce:
      return "recv_reduce";
    case StepOp::kReduceLocal:
      return "reduce_local";
    case StepOp::kCopy:
      return "copy";
    case StepOp::kEncode:
      return "encode";
    case StepOp::kDecode:
      return "decode";
  }
  TC_THROW(EnforceError, "unknown step op ", static_cast<int>(op));
}

std::optional<StepOp> stepOpFromName(const std::string& name) {
  for (uint8_t i = 0; i <= static_cast<uint8_t>(StepOp::kDecode); i++) {
    const StepOp op = static_cast<StepOp>(i);
    if (name == stepOpName(op)) {
      return op;
    }
  }
  return std::nullopt;
}

const char* collectiveName(Collective c) {
  switch (c) {
    case Collective::kAllreduce:
      return "allreduce";
    case Collective::kReduceScatter:
      return "reduce_scatter";
    case Collective::kAllgather:
      return "allgather";
  }
  TC_THROW(EnforceError, "unknown collective ", static_cast<int>(c));
}

std::optional<Collective> collectiveFromName(const std::string& name) {
  for (uint8_t i = 0; i <= static_cast<uint8_t>(Collective::kAllgather);
       i++) {
    const Collective c = static_cast<Collective>(i);
    if (name == collectiveName(c)) {
      return c;
    }
  }
  return std::nullopt;
}

namespace {

// Euclidean remainder: ring arithmetic must wrap negative shifts
// ((rank - t) mod world) into [0, world), which C++ % does not.
int64_t posMod(int64_t v, int64_t m) {
  const int64_t r = v % m;
  return r < 0 ? r + m : r;
}

}  // namespace

int64_t RankExpr::eval(int rank, int worldSize) const {
  TC_ENFORCE(worldSize > 0, "schedule expr: world size must be positive");
  switch (kind) {
    case Kind::kConst:
      return a;
    case Kind::kRing:
      return posMod(rank + a, worldSize) * scale + offset;
    case Kind::kXor:
      return posMod(rank ^ a, worldSize) * scale + offset;
    case Kind::kTable:
      TC_ENFORCE(static_cast<size_t>(rank) < table.size(),
                 "schedule expr: table has ", table.size(),
                 " entries, rank ", rank, " out of range");
      return table[rank];
  }
  TC_THROW(EnforceError, "unknown expr kind ", static_cast<int>(kind));
}

RankExpr RankExpr::constant(int64_t v) {
  RankExpr e;
  e.kind = Kind::kConst;
  e.a = v;
  return e;
}

RankExpr RankExpr::ring(int64_t add, int64_t scale, int64_t offset) {
  RankExpr e;
  e.kind = Kind::kRing;
  e.a = add;
  e.scale = scale;
  e.offset = offset;
  return e;
}

RankExpr RankExpr::xorOf(int64_t mask, int64_t scale, int64_t offset) {
  RankExpr e;
  e.kind = Kind::kXor;
  e.a = mask;
  e.scale = scale;
  e.offset = offset;
  return e;
}

RankExpr RankExpr::tableOf(std::vector<int64_t> values) {
  RankExpr e;
  e.kind = Kind::kTable;
  e.table = std::move(values);
  return e;
}

void ScheduleTable::add(Schedule s) {
  TC_ENFORCE(!s.name.empty(), "schedule table: schedule needs a name");
  TC_ENFORCE(find(s.name) == nullptr, "schedule table: duplicate schedule \"",
             s.name, "\"");
  TC_ENFORCE(s.worldSize > 0, "schedule \"", s.name,
             "\": world size must be positive");
  TC_ENFORCE(s.nChunks > 0, "schedule \"", s.name,
             "\": chunk count must be positive");
  TC_ENFORCE(s.nScratch >= 0, "schedule \"", s.name,
             "\": scratch count must be non-negative");
  schedules_.push_back(std::move(s));
}

const Schedule* ScheduleTable::find(const std::string& name) const {
  for (const Schedule& s : schedules_) {
    if (s.name == name) {
      return &s;
    }
  }
  return nullptr;
}

void ScheduleTable::elect(Election e) {
  TC_ENFORCE(find(e.schedule) != nullptr, "schedule table: election names "
             "unknown schedule \"", e.schedule, "\"");
  TC_ENFORCE(collectiveFromName(e.collective).has_value(),
             "schedule table: election has unknown collective \"",
             e.collective, "\"");
  elections_.push_back(std::move(e));
}

const Schedule* ScheduleTable::elected(const std::string& collective,
                                       int worldSize,
                                       const std::string& dtype,
                                       size_t nbytes) const {
  const int bucket = tuning::sizeBucket(nbytes);
  const Election* wildcard = nullptr;
  for (const Election& e : elections_) {
    if (e.collective != collective || e.worldSize != worldSize ||
        e.bucket != bucket) {
      continue;
    }
    if (e.dtype == dtype) {
      return find(e.schedule);
    }
    if (e.dtype.empty() && wildcard == nullptr) {
      wildcard = &e;
    }
  }
  return wildcard != nullptr ? find(wildcard->schedule) : nullptr;
}

namespace {

using Kind = JsonReader::Value::Kind;

const JsonReader::Value& requireField(const JsonReader::Value& obj,
                                      const std::string& name, Kind kind) {
  const JsonReader::Value* f = obj.field(name);
  TC_ENFORCE(f != nullptr, "schedule JSON: missing \"", name, "\"");
  TC_ENFORCE(f->kind == kind, "schedule JSON: \"", name,
             "\" has wrong type");
  return *f;
}

int64_t requireInt(const JsonReader::Value& obj, const std::string& name) {
  const JsonReader::Value& f = requireField(obj, name, Kind::kNumber);
  const int64_t v = static_cast<int64_t>(f.number);
  TC_ENFORCE(static_cast<double>(v) == f.number, "schedule JSON: \"", name,
             "\" must be an integer");
  return v;
}

int64_t optionalInt(const JsonReader::Value& obj, const std::string& name,
                    int64_t fallback) {
  if (obj.field(name) == nullptr) {
    return fallback;
  }
  return requireInt(obj, name);
}

void appendExpr(std::ostringstream& out, const RankExpr& e) {
  switch (e.kind) {
    case RankExpr::Kind::kConst:
      out << e.a;
      return;
    case RankExpr::Kind::kRing:
    case RankExpr::Kind::kXor:
      out << "{\"kind\":\""
          << (e.kind == RankExpr::Kind::kRing ? "ring" : "xor")
          << "\",\"a\":" << e.a;
      if (e.scale != 1) {
        out << ",\"scale\":" << e.scale;
      }
      if (e.offset != 0) {
        out << ",\"offset\":" << e.offset;
      }
      out << "}";
      return;
    case RankExpr::Kind::kTable:
      out << "{\"kind\":\"table\",\"values\":[";
      for (size_t i = 0; i < e.table.size(); i++) {
        if (i > 0) {
          out << ",";
        }
        out << e.table[i];
      }
      out << "]}";
      return;
  }
  TC_THROW(EnforceError, "unknown expr kind ", static_cast<int>(e.kind));
}

RankExpr parseExpr(const JsonReader::Value& v, const char* what) {
  if (v.kind == Kind::kNumber) {
    const int64_t n = static_cast<int64_t>(v.number);
    TC_ENFORCE(static_cast<double>(n) == v.number, "schedule JSON: ", what,
               " must be an integer or expr object");
    return RankExpr::constant(n);
  }
  TC_ENFORCE(v.kind == Kind::kObject, "schedule JSON: ", what,
             " must be an integer or expr object");
  const std::string& kind = requireField(v, "kind", Kind::kString).str;
  if (kind == "ring" || kind == "xor") {
    const int64_t a = requireInt(v, "a");
    const int64_t scale = optionalInt(v, "scale", 1);
    const int64_t offset = optionalInt(v, "offset", 0);
    return kind == "ring" ? RankExpr::ring(a, scale, offset)
                          : RankExpr::xorOf(a, scale, offset);
  }
  if (kind == "table") {
    const JsonReader::Value& values = requireField(v, "values", Kind::kArray);
    std::vector<int64_t> table;
    table.reserve(values.items.size());
    for (const JsonReader::Value& item : values.items) {
      TC_ENFORCE(item.kind == Kind::kNumber, "schedule JSON: ", what,
                 " table values must be integers");
      table.push_back(static_cast<int64_t>(item.number));
    }
    return RankExpr::tableOf(std::move(table));
  }
  TC_THROW(EnforceError, "schedule JSON: ", what, " has unknown expr kind \"",
           kind, "\"");
}

bool isConst(const RankExpr& e, int64_t v) {
  return e.kind == RankExpr::Kind::kConst && e.a == v;
}

}  // namespace

std::string ScheduleTable::toJson() const {
  std::ostringstream out;
  out << "{\"version\":1,\"schedules\":[";
  for (size_t si = 0; si < schedules_.size(); si++) {
    const Schedule& s = schedules_[si];
    if (si > 0) {
      out << ",";
    }
    out << "{\"name\":";
    appendJsonString(out, s.name);
    out << ",\"collective\":\"" << collectiveName(s.collective)
        << "\",\"world_size\":" << s.worldSize << ",\"chunks\":" << s.nChunks
        << ",\"scratch\":" << s.nScratch << ",\"steps\":[";
    for (size_t i = 0; i < s.steps.size(); i++) {
      const Step& st = s.steps[i];
      if (i > 0) {
        out << ",";
      }
      out << "{\"op\":\"" << stepOpName(st.op) << "\"";
      // Defaults are omitted so generated files stay reviewable; the
      // parser restores them, making omission/presence round-trip clean.
      if (!isConst(st.peer, -1)) {
        out << ",\"peer\":";
        appendExpr(out, st.peer);
      }
      out << ",\"chunk\":";
      appendExpr(out, st.chunk);
      if (!isConst(st.slot, -1)) {
        out << ",\"slot\":";
        appendExpr(out, st.slot);
      }
      if (!isConst(st.guard, 1)) {
        out << ",\"guard\":";
        appendExpr(out, st.guard);
      }
      if (st.flags != 0) {
        out << ",\"flags\":" << static_cast<int>(st.flags);
      }
      if (st.pipeline != 1) {
        out << ",\"pipeline\":" << st.pipeline;
      }
      if (!st.deps.empty()) {
        out << ",\"deps\":[";
        for (size_t d = 0; d < st.deps.size(); d++) {
          if (d > 0) {
            out << ",";
          }
          out << st.deps[d];
        }
        out << "]";
      }
      if (!st.note.empty()) {
        out << ",\"note\":";
        appendJsonString(out, st.note);
      }
      out << "}";
    }
    out << "]}";
  }
  out << "],\"elections\":[";
  for (size_t i = 0; i < elections_.size(); i++) {
    const Election& e = elections_[i];
    if (i > 0) {
      out << ",";
    }
    out << "{\"collective\":";
    appendJsonString(out, e.collective);
    out << ",\"world_size\":" << e.worldSize << ",\"dtype\":";
    appendJsonString(out, e.dtype);
    out << ",\"bucket\":" << e.bucket << ",\"schedule\":";
    appendJsonString(out, e.schedule);
    out << "}";
  }
  out << "]}";
  return out.str();
}

ScheduleTable ScheduleTable::fromJson(const std::string& json) {
  JsonReader reader(json, "schedule JSON", /*rejectDuplicateKeys=*/true);
  const JsonReader::Value root = reader.parse();
  TC_ENFORCE(root.kind == Kind::kObject,
             "schedule JSON: root must be an object");
  const JsonReader::Value* version = root.field("version");
  TC_ENFORCE(version != nullptr && version->kind == Kind::kNumber &&
                 version->number == 1.0,
             "schedule JSON: unsupported version");
  ScheduleTable table;
  // Both top-level arrays are optional (absent == empty): hand-written
  // tables often carry only one of them.
  static const JsonReader::Value kEmptyArray = [] {
    JsonReader::Value v;
    v.kind = Kind::kArray;
    return v;
  }();
  const JsonReader::Value& schedules =
      root.field("schedules") != nullptr
          ? requireField(root, "schedules", Kind::kArray)
          : kEmptyArray;
  for (const JsonReader::Value& sv : schedules.items) {
    TC_ENFORCE(sv.kind == Kind::kObject,
               "schedule JSON: schedule must be an object");
    Schedule s;
    s.name = requireField(sv, "name", Kind::kString).str;
    const std::string& coll =
        requireField(sv, "collective", Kind::kString).str;
    auto c = collectiveFromName(coll);
    TC_ENFORCE(c.has_value(), "schedule JSON: schedule \"", s.name,
               "\" has unknown collective \"", coll, "\"");
    s.collective = *c;
    s.worldSize = static_cast<int>(requireInt(sv, "world_size"));
    s.nChunks = static_cast<int>(requireInt(sv, "chunks"));
    s.nScratch = static_cast<int>(requireInt(sv, "scratch"));
    const JsonReader::Value& steps = requireField(sv, "steps", Kind::kArray);
    for (const JsonReader::Value& stv : steps.items) {
      TC_ENFORCE(stv.kind == Kind::kObject,
                 "schedule JSON: step must be an object");
      Step st;
      const std::string& opName = requireField(stv, "op", Kind::kString).str;
      auto op = stepOpFromName(opName);
      TC_ENFORCE(op.has_value(), "schedule JSON: schedule \"", s.name,
                 "\" has unknown step op \"", opName, "\"");
      st.op = *op;
      if (const JsonReader::Value* p = stv.field("peer")) {
        st.peer = parseExpr(*p, "peer");
      }
      const JsonReader::Value* chunk = stv.field("chunk");
      TC_ENFORCE(chunk != nullptr, "schedule JSON: step missing \"chunk\"");
      st.chunk = parseExpr(*chunk, "chunk");
      if (const JsonReader::Value* sl = stv.field("slot")) {
        st.slot = parseExpr(*sl, "slot");
      }
      if (const JsonReader::Value* g = stv.field("guard")) {
        st.guard = parseExpr(*g, "guard");
      }
      const int64_t flags = optionalInt(stv, "flags", 0);
      TC_ENFORCE(flags >= 0 && flags <= 0xff,
                 "schedule JSON: step flags out of range");
      st.flags = static_cast<uint8_t>(flags);
      // Range-checked here so a malformed file fails at parse; the
      // verifier owns the per-opcode rule (pipeline > 1 only on codec
      // steps).
      const int64_t pipeline = optionalInt(stv, "pipeline", 1);
      TC_ENFORCE(pipeline >= 1 && pipeline <= 0x7fffffff,
                 "schedule JSON: step pipeline out of range");
      st.pipeline = static_cast<int32_t>(pipeline);
      if (const JsonReader::Value* deps = stv.field("deps")) {
        TC_ENFORCE(deps->kind == Kind::kArray,
                   "schedule JSON: \"deps\" must be an array");
        for (const JsonReader::Value& d : deps->items) {
          TC_ENFORCE(d.kind == Kind::kNumber,
                     "schedule JSON: deps must be integers");
          st.deps.push_back(static_cast<int32_t>(d.number));
        }
      }
      if (const JsonReader::Value* note = stv.field("note")) {
        TC_ENFORCE(note->kind == Kind::kString,
                   "schedule JSON: \"note\" must be a string");
        st.note = note->str;
      }
      s.steps.push_back(std::move(st));
    }
    table.add(std::move(s));
  }
  const JsonReader::Value& elections =
      root.field("elections") != nullptr
          ? requireField(root, "elections", Kind::kArray)
          : kEmptyArray;
  for (const JsonReader::Value& ev : elections.items) {
    TC_ENFORCE(ev.kind == Kind::kObject,
               "schedule JSON: election must be an object");
    Election e;
    e.collective = requireField(ev, "collective", Kind::kString).str;
    e.worldSize = static_cast<int>(requireInt(ev, "world_size"));
    e.dtype = requireField(ev, "dtype", Kind::kString).str;
    e.bucket = static_cast<int>(requireInt(ev, "bucket"));
    e.schedule = requireField(ev, "schedule", Kind::kString).str;
    table.elect(std::move(e));
  }
  return table;
}

}  // namespace schedule
}  // namespace tpucoll
