// Schedule generators: the native algorithm families — and families no
// enum entry can express — emitted as schedule IR (ir.h).
//
// Families (parameters via generate()'s params map; see
// docs/schedules.md):
//
//   ring       allreduce ring. Param "depth" (>= 1, default 1): depth k
//              splits each of the P rank segments into k sub-chunks
//              pipelined independently — k in-flight messages per
//              direction instead of one, hiding per-hop latency on
//              large payloads. k = 1 reproduces the native ring
//              byte-for-byte.
//   ring_rs    reduce-scatter ring (rank r ends owning block r).
//   ring_ag    allgather ring.
//   hd         allreduce halving-doubling (power-of-two worlds).
//   hd_rs      reduce-scatter recursive halving (power-of-two worlds).
//   hd_ag      allgather recursive doubling (power-of-two worlds).
//   bcube      allreduce mixed-radix bcube (prime-factor stages, the
//              native generalization).
//   ring_bf16  allreduce ring with bf16-coded wire (encode/decode
//              steps; float32 payloads, lossy-wire opt-in only).
//   hier       allreduce 2-level hierarchy. Param "ranks_per_host"
//              (must divide world): members send chunks to their host
//              leader, leaders ring-allreduce, leaders fan out — two
//              wire hops over the slow tier instead of P - 1.
//
// Every generated schedule passes the verifier by construction; tests
// assert it, and the equivalence suite proves the native-family outputs
// byte-identical to the hardcoded algorithms.
#pragma once

#include <map>
#include <string>
#include <vector>

#include "tpucoll/schedule/ir.h"

namespace tpucoll {
namespace schedule {

// Generate family `family` for `worldSize` ranks. Unknown families,
// unknown or out-of-range params, and family/world mismatches (hd on a
// non-power-of-two world, hier with ranks_per_host not dividing world)
// throw EnforceError.
Schedule generate(const std::string& family, int worldSize,
                  const std::map<std::string, int>& params = {});

// All family names, in a stable order (sweep + describe listings).
std::vector<std::string> generatorFamilies();

}  // namespace schedule
}  // namespace tpucoll
