#include "tpucoll/schedule/interpreter.h"

#include <algorithm>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <utility>

#include "tpucoll/collectives/detail.h"
#include "tpucoll/collectives/plan.h"
#include "tpucoll/collectives/wire_codec.h"
#include "tpucoll/common/logging.h"
#include "tpucoll/common/profile.h"
#include "tpucoll/common/span.h"
#include "tpucoll/context.h"
#include "tpucoll/schedule/verifier.h"
#include "tpucoll/transport/unbound_buffer.h"

namespace tpucoll {
namespace schedule {

using collectives_detail::evenBlocks;
using profile::Phase;
using profile::PhaseScope;

namespace {

bool isWire(StepOp op) {
  return op == StepOp::kSend || op == StepOp::kRecv ||
         op == StepOp::kRecvReduce;
}

bool isRecvKind(StepOp op) {
  return op == StepOp::kRecv || op == StepOp::kRecvReduce;
}

// Bookkeeping step flags, kept in plan scratch.
constexpr uint8_t kArrived = 1;  // wire completion observed
constexpr uint8_t kDone = 2;     // arrival effect (fold) applied

size_t align4(size_t n) { return (n + 3) & ~size_t(3); }

}  // namespace

size_t ResolvedProgram::stateBytes() const {
  // [per-step flags][queue heads: 2 buffers x world][outstanding sends x 2]
  return align4(steps.size()) +
         size_t(2) * static_cast<size_t>(worldSize) * sizeof(int32_t) +
         2 * sizeof(int32_t);
}

std::shared_ptr<const ResolvedProgram> resolve(const Schedule& s, int rank) {
  const int world = s.worldSize;
  TC_ENFORCE(rank >= 0 && rank < world, "schedule \"", s.name,
             "\": rank ", rank, " out of range for world ", world);
  const int n = static_cast<int>(s.steps.size());
  const std::vector<int32_t> topo = topoOrder(s, rank);

  // Evaluate every rank's operands: the slot-delta assignment below must
  // replay the verifier's global FIFO matching, which needs all ranks'
  // wire steps, not just ours.
  struct Ev {
    bool active{false};
    int peer{-1};
    int chunk{0};
    int slot{-1};
  };
  std::vector<std::vector<Ev>> ev(world, std::vector<Ev>(n));
  bool hasCoded = false;
  for (int r = 0; r < world; r++) {
    for (int i = 0; i < n; i++) {
      const Step& st = s.steps[i];
      Ev& e = ev[r][i];
      e.active = st.guard.eval(r, world) != 0;
      if (!e.active) {
        continue;
      }
      e.chunk = st.chunk.eval(r, world);
      e.slot = st.slot.eval(r, world);
      if ((st.flags & Step::kFlagCoded) || st.op == StepOp::kEncode ||
          st.op == StepOp::kDecode) {
        hasCoded = true;
      }
      TC_ENFORCE(e.chunk >= 0 && e.chunk < s.nChunks, "schedule \"", s.name,
                 "\": step ", i, " chunk ", e.chunk, " out of range");
      TC_ENFORCE(e.slot >= -1 && e.slot < s.nScratch, "schedule \"", s.name,
                 "\": step ", i, " slot ", e.slot, " out of range");
      if (isWire(st.op)) {
        e.peer = st.peer.eval(r, world);
        TC_ENFORCE(e.peer >= 0 && e.peer < world && e.peer != r,
                   "schedule \"", s.name, "\": step ", i, " peer ", e.peer,
                   " invalid at rank ", r);
      }
    }
  }

  // Global message matching in the verifier's deterministic order: per
  // directed pair (a, b), the k-th send a posts toward b pairs with the
  // k-th receive b posts from a; pairs are visited in std::map key order
  // and each message gets the next sequential slot delta. Both endpoints
  // derive the same delta, and every rank resolves the same table.
  std::map<std::pair<int, int>, std::vector<std::pair<int, int>>> sends;
  std::map<std::pair<int, int>, std::vector<std::pair<int, int>>> recvs;
  for (int r = 0; r < world; r++) {
    for (int32_t i : topo) {
      const Ev& e = ev[r][i];
      if (!e.active || !isWire(s.steps[i].op)) {
        continue;
      }
      if (s.steps[i].op == StepOp::kSend) {
        sends[{r, e.peer}].push_back({r, i});
      } else {
        recvs[{e.peer, r}].push_back({r, i});
      }
    }
  }
  std::vector<uint32_t> deltaOf(n, 0);
  uint32_t next = 0;
  for (const auto& kv : sends) {
    auto rit = recvs.find(kv.first);
    TC_ENFORCE(rit != recvs.end() && rit->second.size() == kv.second.size(),
               "schedule \"", s.name, "\": unmatched wire steps between ranks ",
               kv.first.first, " and ", kv.first.second,
               " (schedule was not verified)");
    for (size_t k = 0; k < kv.second.size(); k++) {
      const uint32_t delta = next++;
      if (kv.second[k].first == rank) {
        deltaOf[kv.second[k].second] = delta;
      }
      if (rit->second[k].first == rank) {
        deltaOf[rit->second[k].second] = delta;
      }
    }
  }
  TC_ENFORCE(next < (uint32_t(1) << Slot::kDeltaBits), "schedule \"", s.name,
             "\": ", next, " wire messages exceed the slot delta space");

  auto prog = std::make_shared<ResolvedProgram>();
  prog->name = s.name;
  prog->label = "sched:" + s.name;
  prog->collective = s.collective;
  prog->worldSize = world;
  prog->rank = rank;
  prog->nChunks = s.nChunks;
  prog->nScratch = s.nScratch;
  prog->hasCoded = hasCoded;

  // Reorder into the shared topological order; positions are identical
  // across ranks (deps are rank-independent), so dependency remapping is
  // a pure index translation.
  std::vector<int32_t> pos(n, -1);
  for (int p = 0; p < n; p++) {
    pos[topo[p]] = p;
  }
  prog->steps.resize(n);
  for (int p = 0; p < n; p++) {
    const int32_t i = topo[p];
    const Step& st = s.steps[i];
    const Ev& e = ev[rank][i];
    RStep& r = prog->steps[p];
    r.op = st.op;
    r.active = e.active;
    r.peer = e.peer;
    r.chunk = e.chunk;
    r.slot = e.slot;
    r.flags = st.flags;
    r.pipeline = st.pipeline;
    r.delta = deltaOf[i];
    r.deps.reserve(st.deps.size());
    for (int32_t d : st.deps) {
      r.deps.push_back(pos[d]);
    }
    std::sort(r.deps.begin(), r.deps.end());
  }

  prog->recvQueues[0].assign(world, {});
  prog->recvQueues[1].assign(world, {});
  for (int p = 0; p < n; p++) {
    const RStep& r = prog->steps[p];
    if (r.active && isRecvKind(r.op)) {
      prog->recvQueues[r.slot >= 0 ? 1 : 0][r.peer].push_back(p);
    }
  }
  return prog;
}

void run(Context* ctx, plan::Plan& plan, const ResolvedProgram& prog,
         char* work, size_t count, size_t elsize, ReduceFn fn,
         DataType dtype, Slot slotBase, std::chrono::milliseconds timeout,
         transport::UnboundBuffer* callerWorkBuf) {
  TC_ENFORCE(prog.worldSize == ctx->size() && prog.rank == ctx->rank(),
             "schedule \"", prog.name, "\" resolved for rank ", prog.rank,
             "/", prog.worldSize, " cannot run on rank ", ctx->rank(), "/",
             ctx->size());
  if (prog.hasCoded) {
    TC_ENFORCE(dtype == DataType::kFloat32,
               "schedule \"", prog.name,
               "\" carries bf16-coded wire steps and requires float32");
  }
  const int world = prog.worldSize;
  const size_t nbytes = count * elsize;
  const auto& blocks =
      plan.blocks(0, [&] { return evenBlocks(count, prog.nChunks, elsize); });
  size_t maxChunk = elsize;
  for (size_t b : blocks.bytes) {
    maxChunk = std::max(maxChunk, b);
  }
  const size_t slotStride = maxChunk;

  auto* workBuf = callerWorkBuf != nullptr ? callerWorkBuf
                                           : plan.userBuf(0, work, nbytes);
  plan::Plan::Stage arena{};
  if (prog.nScratch > 0) {
    arena = plan.stage(0, static_cast<size_t>(prog.nScratch) * slotStride);
  }
  transport::UnboundBuffer* bufs[2] = {workBuf, arena.buf};

  // All bookkeeping lives in plan scratch: warm replays reset it with one
  // memset and allocate nothing.
  const int n = static_cast<int>(prog.steps.size());
  char* state = plan.scratch(1, prog.stateBytes());
  std::memset(state, 0, prog.stateBytes());
  uint8_t* stepState = reinterpret_cast<uint8_t*>(state);
  int32_t* heads = reinterpret_cast<int32_t*>(state + align4(n));
  int32_t* sendsOut = heads + size_t(2) * world;

  // Per-step receive span bookkeeping: the recv span's interval is
  // [post time, FIFO-attributed arrival time], not the wait that
  // happened to observe it (a waitRecv can complete a DIFFERENT step's
  // message). Allocated only when a span op is live on this thread, so
  // the disabled path stays allocation- and clock-free.
  span::OpState* const spanOp = span::currentOp();
  std::vector<int64_t> recvPostUs, recvArriveUs;
  if (spanOp != nullptr) {
    recvPostUs.assign(n, 0);
    recvArriveUs.assign(n, 0);
  }

  auto chunkPtr = [&](const RStep& st) { return work + blocks.offset[st.chunk]; };
  auto slotPtr = [&](const RStep& st) {
    return arena.data + static_cast<size_t>(st.slot) * slotStride;
  };
  auto chunkElems = [&](const RStep& st) { return blocks.bytes[st.chunk] / elsize; };
  // Wire operand: coded steps move bf16 (2 bytes/elem) through their
  // slot; uncoded steps move the chunk's bytes from the slot (if one is
  // named) or in place from the work buffer.
  auto wireLoc = [&](const RStep& st, int* bufIdx, size_t* off, size_t* len) {
    const bool coded = (st.flags & Step::kFlagCoded) != 0;
    *len = coded ? chunkElems(st) * 2 : blocks.bytes[st.chunk];
    if (st.slot >= 0) {
      *bufIdx = 1;
      *off = static_cast<size_t>(st.slot) * slotStride;
    } else {
      *bufIdx = 0;
      *off = blocks.offset[st.chunk];
    }
  };

  auto drainSends = [&](int b) {
    while (sendsOut[b] > 0) {
      PhaseScope ws(Phase::kWireWait);
      bufs[b]->waitSend(timeout);
      sendsOut[b]--;
    }
  };
  // Wait until step `p` (a receive posted on buffer `b`) has arrived,
  // attributing each waitRecv completion through the per-source FIFO,
  // then apply its fold (recv_reduce) exactly once. Folds thus execute
  // at dependency-demand time in program order — deterministic float
  // reduction order, independent of wire arrival order.
  auto completeRecv = [&](int p) {
    const RStep& st = prog.steps[p];
    if (stepState[p] & kDone) {
      return;
    }
    const int b = st.slot >= 0 ? 1 : 0;
    while (!(stepState[p] & kArrived)) {
      int src = -1;
      {
        PhaseScope ws(Phase::kWireWait);
        bufs[b]->waitRecv(&src, timeout);
      }
      TC_ENFORCE(src >= 0 && src < world, "schedule \"", prog.name,
                 "\": waitRecv reported bad source ", src);
      const auto& q = prog.recvQueues[b][src];
      int32_t& head = heads[b * world + src];
      TC_ENFORCE(static_cast<size_t>(head) < q.size(), "schedule \"",
                 prog.name, "\": unexpected receive completion from rank ",
                 src);
      stepState[q[head]] |= kArrived;
      if (spanOp != nullptr) {
        recvArriveUs[q[head]] = FlightRecorder::nowUs();
      }
      head++;
    }
    if (spanOp != nullptr) {
      int wb;
      size_t woff, wlen;
      wireLoc(st, &wb, &woff, &wlen);
      span::emit(span::Kind::kRecv, static_cast<uint8_t>(Phase::kWireWait),
                 st.peer, slotBase.offset(st.delta).value(), wlen,
                 recvPostUs[p], recvArriveUs[p]);
    }
    if (st.op == StepOp::kRecvReduce) {
      PhaseScope rs(Phase::kReduce);
      const size_t elems = chunkElems(st);
      if (elems > 0) {
        fn(chunkPtr(st), slotPtr(st), elems);
      }
    }
    stepState[p] |= kDone;
  };
  auto completeDep = [&](int d) {
    const RStep& ds = prog.steps[d];
    if (!ds.active) {
      return;
    }
    if (ds.op == StepOp::kSend) {
      // waitSend carries no identity: a dependency on any send drains
      // every outstanding send on that buffer (a superset, still safe).
      drainSends(ds.slot >= 0 ? 1 : 0);
    } else if (isRecvKind(ds.op)) {
      completeRecv(d);
    }
    // Local steps already executed inline (sequential walk).
  };

  for (int p = 0; p < n; p++) {
    const RStep& st = prog.steps[p];
    if (!st.active) {
      continue;
    }
    for (int32_t d : st.deps) {
      completeDep(d);
    }
    switch (st.op) {
      case StepOp::kSend: {
        int b;
        size_t off, len;
        wireLoc(st, &b, &off, &len);
        const uint64_t wslot = slotBase.offset(st.delta).value();
        PhaseScope ps(Phase::kPost, st.peer, wslot, len);
        bufs[b]->send(st.peer, wslot, off, len);
        sendsOut[b]++;
        break;
      }
      case StepOp::kRecv:
      case StepOp::kRecvReduce: {
        int b;
        size_t off, len;
        wireLoc(st, &b, &off, &len);
        if (spanOp != nullptr) {
          recvPostUs[p] = FlightRecorder::nowUs();
        }
        PhaseScope ps(Phase::kPost);
        bufs[b]->recv(st.peer, slotBase.offset(st.delta).value(), off, len);
        break;
      }
      case StepOp::kReduceLocal: {
        PhaseScope rs(Phase::kReduce);
        const size_t elems = chunkElems(st);
        if (elems > 0) {
          fn(chunkPtr(st), slotPtr(st), elems);
        }
        break;
      }
      case StepOp::kCopy: {
        PhaseScope cs(Phase::kPack);
        const size_t len = blocks.bytes[st.chunk];
        if (len > 0) {
          if (st.flags & Step::kFlagToSlot) {
            std::memcpy(slotPtr(st), chunkPtr(st), len);
          } else {
            std::memcpy(chunkPtr(st), slotPtr(st), len);
          }
        }
        break;
      }
      case StepOp::kEncode: {
        PhaseScope cs(Phase::kPack);
        // pipeline > 1 shards the walk across the codec pool
        // (wire_codec.h) — byte-identical to the serial stream calls.
        algorithms::wireEncode(
            algorithms::bf16WireCodec(),
            reinterpret_cast<const float*>(chunkPtr(st)),
            reinterpret_cast<uint8_t*>(slotPtr(st)), chunkElems(st),
            static_cast<size_t>(st.pipeline));
        break;
      }
      case StepOp::kDecode: {
        PhaseScope cs(Phase::kUnpack);
        algorithms::wireDecode(
            algorithms::bf16WireCodec(),
            reinterpret_cast<const uint8_t*>(slotPtr(st)),
            reinterpret_cast<float*>(chunkPtr(st)), chunkElems(st),
            static_cast<size_t>(st.pipeline));
        break;
      }
    }
  }

  // Completion: every posted receive must be consumed (in program order,
  // so trailing folds stay deterministic) and every send drained before
  // the plan is released back to the cache.
  for (int p = 0; p < n; p++) {
    const RStep& st = prog.steps[p];
    if (st.active && isRecvKind(st.op)) {
      completeRecv(p);
    }
  }
  drainSends(0);
  drainSends(1);
}

std::shared_ptr<const InstalledSchedules> installSchedules(
    std::shared_ptr<const ScheduleTable> table, int rank, int worldSize) {
  TC_ENFORCE(table != nullptr, "installSchedules: null table");
  auto inst = std::make_shared<InstalledSchedules>();
  inst->table = table;
  for (const Schedule& s : table->schedules()) {
    if (s.worldSize != worldSize) {
      continue;
    }
    verifyOrThrow(s);
    inst->programs[s.name] = resolve(s, rank);
  }
  return inst;
}

const char* internedLabel(const std::string& label) {
  static std::mutex mu;
  static std::set<std::string>* pool = new std::set<std::string>();
  std::lock_guard<std::mutex> guard(mu);
  return pool->insert(label).first->c_str();
}

}  // namespace schedule
}  // namespace tpucoll
