#include "tpucoll/context.h"

#include "tpucoll/types.h"

namespace tpucoll {

constexpr std::chrono::milliseconds Context::kDefaultTimeout;

Context::Context(int rank, int size) : rank_(rank), size_(size) {
  TC_ENFORCE(size > 0, "context size must be positive");
  TC_ENFORCE(rank >= 0 && rank < size, "rank ", rank, " out of range for size ",
             size);
}

Context::~Context() = default;

void Context::connectFullMesh(std::shared_ptr<Store> store,
                              std::shared_ptr<transport::Device> device) {
  TC_ENFORCE(tctx_ == nullptr, "context already connected");
  store_ = std::move(store);
  device_ = std::move(device);
  tctx_ = std::make_unique<transport::Context>(device_, rank_, size_);
  tctx_->connectFullMesh(*store_, timeout_);
}

uint64_t Context::nextSlot(uint32_t numToSkip) {
  uint32_t base = slotCounter_.fetch_add(numToSkip);
  return Slot::build(SlotPrefix::kUser, base).value();
}

std::unique_ptr<transport::UnboundBuffer> Context::createUnboundBuffer(
    void* ptr, size_t size) {
  TC_ENFORCE(tctx_ != nullptr, "context not connected");
  return tctx_->createUnboundBuffer(ptr, size);
}

void Context::close() {
  if (tctx_) {
    tctx_->close();
  }
}

Context::Scratch Context::acquireScratch(size_t minBytes) {
  {
    std::lock_guard<std::mutex> guard(scratchMu_);
    for (auto it = scratchPool_.begin(); it != scratchPool_.end(); ++it) {
      if (it->size() >= minBytes) {
        std::vector<char> buf = std::move(*it);
        scratchPool_.erase(it);
        return Scratch(this, std::move(buf));
      }
    }
  }
  return Scratch(this, std::vector<char>(minBytes));
}

Context::Scratch::~Scratch() {
  if (ctx_ != nullptr && !buf_.empty()) {
    std::lock_guard<std::mutex> guard(ctx_->scratchMu_);
    if (ctx_->scratchPool_.size() < 4) {
      ctx_->scratchPool_.push_back(std::move(buf_));
    }
  }
}

}  // namespace tpucoll
