#include "tpucoll/context.h"

#include <cstdlib>
#include <fstream>
#include <sstream>

#include "tpucoll/boot/boot.h"
#include "tpucoll/collectives/collectives.h"
#include "tpucoll/collectives/plan.h"
#include "tpucoll/common/env.h"
#include "tpucoll/common/fleetobs.h"
#include "tpucoll/fault/fault.h"
#include "tpucoll/schedule/interpreter.h"
#include "tpucoll/tuning/tuning_table.h"
#include "tpucoll/types.h"

namespace tpucoll {

constexpr std::chrono::milliseconds Context::kDefaultTimeout;

Context::Context(int rank, int size)
    : rank_(rank), size_(size), metrics_(size),
      profiler_(rank, size, &metrics_), spanrec_(rank, size, &metrics_),
      flightrec_(rank, size) {
  TC_ENFORCE(size > 0, "context size must be positive");
  TC_ENFORCE(rank >= 0 && rank < size, "rank ", rank, " out of range for size ",
             size);
  // Force the lazy TPUCOLL_LOG_LEVEL parse here, where the strict
  // parser's throw crosses the wrapped C ABI as a typed error — the
  // first organic log call can be on a loop thread, where an
  // EnforceError would std::terminate instead.
  logThreshold();
  // Bounded tracer (tracer.h): overflow drops are counted in the
  // registry instead of growing the event vector without limit.
  tracer_.setMetrics(&metrics_);
  // Strict knobs parse here, where the throw crosses the wrapped C ABI
  // as a typed error rather than killing a loop thread.
  planCache_ = std::make_unique<plan::PlanCache>(this);
}

Context::~Context() {
  // The fleet observability plane goes first of everything: its
  // aggregation thread posts sends/recvs through the transport mesh,
  // and its wire buffers unregister against the live transport on
  // destruction.
  {
    std::lock_guard<std::mutex> guard(fleetObsMu_);
    fleetObs_.reset();
  }
  // Hier sub-communicators are whole Contexts of their own; drop them
  // first so their collectives cannot outlive the parent state hier.cc
  // reaches through (topology, tracer).
  hierLeaders_.reset();
  hierLocal_.reset();
  // The transport context holds raw pointers into tracer_/metrics_/
  // flightrec_ (setInstrumentation), and its destructor quiesces the
  // loop threads that may still be running a failure callback through
  // them (onPairError on a self-failed pair runs on the loop thread
  // AFTER the pair went kFailed, so a concurrent close() sails past it
  // without a barrier). Members destroy in reverse declaration order
  // and tctx_ is declared FIRST — i.e. it would be destroyed LAST,
  // after the members those callbacks write — so tear it down
  // explicitly before any member dies. Plans go first of all: they own
  // UnboundBuffers whose destructors walk the live transport.
  if (planCache_ != nullptr) {
    planCache_->clear();
  }
  tctx_.reset();
}

void Context::setHostId(std::string hostId) {
  TC_ENFORCE(tctx_ == nullptr,
             "setHostId: must be called before the context connects");
  hostId_ = std::move(hostId);
}

std::shared_ptr<const Topology> Context::topology() const {
  std::lock_guard<std::mutex> guard(topoMu_);
  return topology_;
}

std::string Context::scopedStoreKey(const std::string& suffix) const {
  if (groupTag_.empty()) {
    return "tpucoll/" + suffix;
  }
  return "tpucoll/" + groupTag_ + "/" + suffix;
}

void Context::installTopology(std::shared_ptr<const Topology> topo) {
  {
    std::lock_guard<std::mutex> guard(topoMu_);
    topology_ = topo;
  }
  if (tctx_ != nullptr && topo != nullptr) {
    // Shm-reachability mask: the payload plane only negotiates between
    // ranks the topology co-hosts. With real machines this is what the
    // per-connection same-IP check would conclude anyway; with a
    // TPUCOLL_HOST_ID override it is what SIMULATES the multi-host
    // wiring (cross-"host" pairs stay on TCP).
    std::vector<char> allowed(size_, 0);
    for (int r = 0; r < size_; r++) {
      allowed[r] = topo->sameHost(rank_, r) ? 1 : 0;
    }
    tctx_->setShmPeers(std::move(allowed));
  }
}

void Context::discoverTopology() {
  TC_ENFORCE(store_ != nullptr, "discoverTopology: no store");
  const std::string fp = hostFingerprint(hostId_);
  const std::string base = "tc/topo/";
  store_->set(base + std::to_string(rank_),
              Store::Buf(fp.begin(), fp.end()));
  std::vector<std::string> fps(size_);
  fps[rank_] = fp;
  std::vector<std::string> keys;
  std::vector<int> order;
  for (int j = 0; j < size_; j++) {
    if (j != rank_) {
      keys.push_back(base + std::to_string(j));
      order.push_back(j);
    }
  }
  auto blobs = store_->multiGet(keys, timeout_);
  for (size_t i = 0; i < order.size(); i++) {
    fps[order[i]].assign(blobs[i].begin(), blobs[i].end());
  }
  installTopology(
      std::make_shared<const Topology>(buildTopology(rank_, fps)));
}

namespace {

// Deterministic fault-plane domain for a split group: any collision-
// resistant pure function of the tag works (chaos determinism needs
// same-tag => same-domain across runs and ranks, not global uniqueness).
// Root stays 0; async lanes use parentDomain + lane + 1 (engine.cc), so
// split domains start far above the root's lane range.
int domainFromGroupTag(const std::string& tag) {
  uint64_t h = 0xcbf29ce484222325ULL;
  for (char c : tag) {
    h = (h ^ static_cast<uint8_t>(c)) * 0x100000001b3ULL;
  }
  return static_cast<int>(h % 1000000000ULL) + 1000;
}

}  // namespace

void Context::applyGroupTag(const std::string& tag) {
  groupTag_ = tag;
  if (tag.empty()) {
    return;
  }
  setFaultDomain(domainFromGroupTag(tag));
  flightrec_.setGroupTag(tag.c_str());
  metrics_.setGroup(tag);
}

uint64_t Context::nextSplitGeneration(uint32_t tag) {
  std::lock_guard<std::mutex> guard(splitGenMu_);
  return ++splitGens_[tag];
}

void Context::connectFullMesh(std::shared_ptr<Store> store,
                              std::shared_ptr<transport::Device> device) {
  TC_ENFORCE(tctx_ == nullptr, "context already connected");
  // Before the mesh comes up, so connect_refuse rules cover the
  // bootstrap handshakes too. Malformed files throw (never silently
  // run un-faulted against an operator's explicit schedule).
  fault::maybeLoadEnvFile();
  FlightRecorder::maybeInstallFromEnv();
  MetricsOp mop(&metrics_, MetricOp::kConnect, 0);
  store_ = std::move(store);
  device_ = std::move(device);
  // Load any TPUCOLL_TUNING_FILE before the transport comes up: its
  // transport hints (channel count / stripe threshold) configure the
  // mesh being created, not just the next fork.
  maybeLoadTuningFile();
  maybeLoadScheduleFile();
  tctx_ = std::make_unique<transport::Context>(device_, rank_, size_);
  tctx_->setInstrumentation(&tracer_, &metrics_, &flightrec_);
  tctx_->setFaultDomain(faultDomain_);
  applyTransportHints();
  const boot::BootOptions bopts = boot::optionsFromEnv();
  if (bopts.mode == boot::Mode::kLazy) {
    // Lazy bootstrap (docs/bootstrap.md): one leader-relayed rendezvous
    // replaces BOTH store choreographies of the full-mesh path — the
    // tc/topo fingerprint exchange of discoverTopology() AND the
    // O(N^2) pair-id table of connectFullMesh() — because the relayed
    // payload carries fingerprint and address blob together. Only the
    // topology-selected eager pairs are dialed here; everything else is
    // broker-dialed on first use.
    boot::RendezvousStats stats;
    const std::string fp = hostFingerprint(hostId_);
    const auto rr = boot::relayedRendezvous(*store_, rank_, size_, fp,
                                            tctx_->lazyAddressBlob(),
                                            bopts.shards, timeout_, &stats);
    installTopology(
        std::make_shared<const Topology>(buildTopology(rank_, rr.fingerprints)));
    std::vector<transport::SockAddr> addrs(size_);
    for (int r = 0; r < size_; r++) {
      transport::Context::parseLazyAddressBlob(rr.payloads[r],
                                               tctx_->channels(), &addrs[r]);
    }
    const auto topo = topology();
    tctx_->enableLazy(rr.meshId, std::move(addrs),
                      boot::eagerPeers(bopts, *topo), bopts.maxPairs, timeout_);
    tctx_->dialEager(timeout_);
    metrics_.recordBootRendezvous(true, stats.publishUs, stats.topoUs,
                                  stats.exchangeUs,
                                  static_cast<uint64_t>(stats.storeOps),
                                  static_cast<uint64_t>(stats.storeBytes));
    return;
  }
  // Fingerprint exchange BEFORE the mesh connects: the resulting
  // co-host mask decides which pairs may negotiate the shm plane.
  discoverTopology();
  tctx_->connectFullMesh(*store_, timeout_);
}

void Context::forkFrom(Context& parent, uint32_t tag) {
  TC_ENFORCE(tctx_ == nullptr, "context already connected");
  TC_ENFORCE_EQ(rank_, parent.rank(), "fork must keep the parent rank");
  TC_ENFORCE_EQ(size_, parent.size(), "fork must keep the parent size");
  TC_ENFORCE(parent.tctx_ != nullptr, "parent context not connected");
  device_ = parent.device_;
  hostId_ = parent.hostId_;
  fault::maybeLoadEnvFile();
  FlightRecorder::maybeInstallFromEnv();
  MetricsOp mop(&metrics_, MetricOp::kConnect, 0);
  maybeLoadTuningFile();
  maybeLoadScheduleFile();
  tctx_ = std::make_unique<transport::Context>(device_, rank_, size_);
  tctx_->setInstrumentation(&tracer_, &metrics_, &flightrec_);
  tctx_->setFaultDomain(faultDomain_);
  applyTransportHints();
  // Same ranks, same machines: the fork inherits the parent's topology
  // (and so its shm-reachability mask) without store traffic.
  installTopology(parent.topology());
  auto blob = tctx_->prepareFullMesh();

  // Exchange blob lengths, then the blobs themselves, over the parent.
  std::vector<uint64_t> lens(size_);
  uint64_t myLen = blob.size();
  {
    AllgatherOptions opts;
    opts.context = &parent;
    opts.tag = tag;
    opts.input = &myLen;
    opts.output = lens.data();
    opts.count = 1;
    opts.dtype = DataType::kUint64;
    allgather(opts);
  }
  std::vector<size_t> counts(lens.begin(), lens.end());
  size_t total = 0;
  for (size_t c : counts) {
    total += c;
  }
  std::vector<uint8_t> all(total);
  {
    AllgathervOptions opts;
    opts.context = &parent;
    opts.tag = tag + 1;
    opts.input = blob.data();
    opts.output = all.data();
    opts.counts = counts;
    opts.dtype = DataType::kUint8;
    allgatherv(opts);
  }
  std::vector<std::vector<uint8_t>> blobs(size_);
  size_t off = 0;
  for (int j = 0; j < size_; j++) {
    blobs[j].assign(all.begin() + off, all.begin() + off + counts[j]);
    off += counts[j];
  }
  tctx_->connectWithBlobs(blobs, timeout_);
}

std::string Context::metricsJson(bool drain) {
  // The broker pair counts are live transport state, not accumulating
  // counters; refresh the "boot" gauges so every snapshot reflects the
  // pair table as of this call (the eviction-cap soak asserts on them).
  if (tctx_ != nullptr && tctx_->lazyEnabled()) {
    uint64_t connected = 0;
    uint64_t evicted = 0;
    uint64_t inbound = 0;
    uint64_t dials = 0;
    tctx_->lazyPairStats(&connected, &evicted, &inbound, &dials);
    metrics_.recordBootPairs(connected, inbound, evicted, dials);
  }
  return metrics_.toJson(rank_, drain);
}

void Context::setTuningTable(
    std::shared_ptr<const tuning::TuningTable> table) {
  {
    std::lock_guard<std::mutex> guard(tuningMu_);
    tuningTable_ = std::move(table);
  }
  // Cached plans embed the RESOLVED algorithm of their kAuto dispatch;
  // a new table may elect differently, so every plan is stale now.
  // (Outside tuningMu_: clear() drains buffers and must not nest under
  // the dispatch-path lock.)
  if (planCache_ != nullptr) {
    planCache_->clear();
  }
}

std::shared_ptr<const tuning::TuningTable> Context::tuningTable() const {
  std::lock_guard<std::mutex> guard(tuningMu_);
  return tuningTable_;
}

void Context::setScheduleTable(
    std::shared_ptr<const schedule::ScheduleTable> table) {
  // Verify + resolve BEFORE swapping: an invalid schedule throws here
  // and the previously installed plane stays in force untouched.
  std::shared_ptr<const schedule::InstalledSchedules> inst;
  if (table != nullptr) {
    inst = schedule::installSchedules(std::move(table), rank_, size_);
  }
  {
    std::lock_guard<std::mutex> guard(schedMu_);
    schedules_ = std::move(inst);
  }
  // Cached plans embed the resolved dispatch (an elected schedule keys
  // plans under its name hash); install/clear makes every plan stale.
  // (Outside schedMu_: clear() drains buffers and must not nest under
  // the dispatch-path lock.)
  if (planCache_ != nullptr) {
    planCache_->clear();
  }
}

std::shared_ptr<const schedule::InstalledSchedules> Context::schedules()
    const {
  std::lock_guard<std::mutex> guard(schedMu_);
  return schedules_;
}

// Feed an installed tuning table's transport hints (tuned channel count
// and stripe threshold) to the transport context about to connect. The
// env knobs win inside setChannelConfig, so an operator override is
// always possible; with no table or no hints the seed defaults hold.
void Context::applyTransportHints() {
  auto table = tuningTable();
  if (table == nullptr) {
    return;
  }
  const auto& hints = table->transportHints();
  if (hints.set()) {
    tctx_->setChannelConfig(hints.channels, hints.stripeBytes);
  }
}

void Context::maybeLoadTuningFile() {
  const char* path = envString("TPUCOLL_TUNING_FILE");
  if (path == nullptr) {
    return;
  }
  std::ifstream in(path, std::ios::binary);
  TC_ENFORCE(in.good(), "TPUCOLL_TUNING_FILE: cannot read ", path);
  std::ostringstream buf;
  buf << in.rdbuf();
  setTuningTable(std::make_shared<const tuning::TuningTable>(
      tuning::TuningTable::fromJson(buf.str())));
}

void Context::maybeLoadScheduleFile() {
  const char* path = envString("TPUCOLL_SCHEDULE_FILE");
  if (path == nullptr) {
    return;
  }
  std::ifstream in(path, std::ios::binary);
  TC_ENFORCE(in.good(), "TPUCOLL_SCHEDULE_FILE: cannot read ", path);
  std::ostringstream buf;
  buf << in.rdbuf();
  setScheduleTable(std::make_shared<const schedule::ScheduleTable>(
      schedule::ScheduleTable::fromJson(buf.str())));
}

uint64_t Context::nextSlot(uint32_t numToSkip) {
  // Relaxed: slot-range allocator — uniqueness only.
  uint32_t base =
      slotCounter_.fetch_add(numToSkip, std::memory_order_relaxed);
  return Slot::build(SlotPrefix::kUser, base).value();
}

std::unique_ptr<transport::UnboundBuffer> Context::createUnboundBuffer(
    void* ptr, size_t size) {
  TC_ENFORCE(tctx_ != nullptr, "context not connected");
  return tctx_->createUnboundBuffer(ptr, size);
}

void Context::fleetObsStart() {
  TC_ENFORCE(tctx_ != nullptr, "fleetObsStart: context not connected");
  std::lock_guard<std::mutex> guard(fleetObsMu_);
  if (fleetObs_ == nullptr) {
    fleetObs_ = std::make_unique<fleetobs::FleetObs>(this);
  }
  fleetObs_->start();
}

void Context::fleetObsStop() {
  std::lock_guard<std::mutex> guard(fleetObsMu_);
  if (fleetObs_ != nullptr) {
    fleetObs_->stop();
  }
}

bool Context::fleetObsRunning() const {
  std::lock_guard<std::mutex> guard(fleetObsMu_);
  return fleetObs_ != nullptr && fleetObs_->running();
}

void Context::fleetObsSetAux(const std::string& auxJson) {
  std::lock_guard<std::mutex> guard(fleetObsMu_);
  TC_ENFORCE(fleetObs_ != nullptr,
             "fleetObsSetAux: fleet observability plane never started");
  fleetObs_->setAux(auxJson);
}

std::string Context::fleetJson() {
  std::lock_guard<std::mutex> guard(fleetObsMu_);
  if (fleetObs_ != nullptr) {
    return fleetObs_->fleetJson();
  }
  std::ostringstream out;
  out << "{\"version\":1,\"kind\":\"fleet\",\"rank\":" << rank_
      << ",\"size\":" << size_
      << ",\"enabled\":false,\"role\":\"off\",\"hosts\":[],"
      << "\"coverage\":{\"expected\":" << size_
      << ",\"reported\":0,\"missing\":[";
  // Honest stub: nothing reported, so every rank is missing.
  for (int r = 0; r < size_; r++) {
    out << (r == 0 ? "" : ",") << r;
  }
  out << "]},\"note\":\"fleet observability plane not started\"}";
  return out.str();
}

void Context::close() {
  // Fleet observability plane first: its thread is mid-tick through the
  // transport about to be quiesced, and stopping it here (not at
  // destruction) means a posted relay recv never sees the mesh die.
  fleetObsStop();
  // Plans next: their registrations point into the transport about to
  // be quiesced, and a cached buffer's drain pass needs it alive.
  if (planCache_ != nullptr) {
    planCache_->clear();
  }
  // Parent mesh before the hier sub-communicators: a hierGroups() init
  // blocked in a parent collective holds hierMu_, and killing the
  // parent mesh is what unwinds it so the lock below can be taken.
  if (tctx_) {
    tctx_->close();
  }
  // Then the hier sub-communicators, so a hierarchical phase blocked on
  // a sub-mesh unwinds too (exactly like async lanes on shutdown).
  // hierMu_ is never held across the split bootstrap (hierGroups), so
  // this cannot block on a builder stuck in a store wait; hierClosed_
  // makes a build that FINISHES after this close tear its fresh
  // sub-meshes down immediately.
  std::lock_guard<std::mutex> guard(hierMu_);
  hierClosed_ = true;
  if (hierLeaders_ != nullptr) {
    hierLeaders_->close();
  }
  if (hierLocal_ != nullptr) {
    hierLocal_->close();
  }
}

Context::Scratch Context::acquireScratch(size_t minBytes) {
  {
    std::lock_guard<std::mutex> guard(scratchMu_);
    for (auto it = scratchPool_.begin(); it != scratchPool_.end(); ++it) {
      if (it->size() >= minBytes) {
        std::vector<char> buf = std::move(*it);
        scratchPool_.erase(it);
        return Scratch(this, std::move(buf));
      }
    }
  }
  return Scratch(this, std::vector<char>(minBytes));
}

Context::Scratch::~Scratch() {
  if (ctx_ != nullptr && !buf_.empty()) {
    std::lock_guard<std::mutex> guard(ctx_->scratchMu_);
    if (ctx_->scratchPool_.size() < 4) {
      ctx_->scratchPool_.push_back(std::move(buf_));
    }
  }
}

}  // namespace tpucoll
