#include "tpucoll/group/hier.h"

#include <algorithm>
#include <cstring>
#include <sstream>

#include "tpucoll/collectives/collectives.h"
#include "tpucoll/common/profile.h"
#include "tpucoll/context.h"
#include "tpucoll/group/topology.h"

namespace tpucoll {
namespace group {

using profile::Phase;
using profile::PhaseScope;

namespace {

// Subgroup-rank -> global-rank map for failure messages: a pair error
// inside a phase names the SUBGROUP peer, which is meaningless without
// this mapping.
std::string describeMembers(const std::vector<int>& members) {
  std::ostringstream os;
  os << "[";
  for (size_t i = 0; i < members.size(); i++) {
    os << (i == 0 ? "" : ",") << i << "->" << members[i];
  }
  os << "]";
  return os.str();
}

// Run `phase` against sub-context `sub`; a typed failure is rethrown —
// type preserved so the C ABI keeps its error-code mapping — naming the
// collective, the phase, the subgroup tag, and the subgroup->global
// rank map, so "pair to rank 1 failed" becomes attributable.
// `profPhase` charges the phase's wall time to the PARENT op's profiler
// accumulator (intra / inter / fanout); the nested sub-context
// collective additionally profiles its own pack/wire/reduce breakdown
// into the sub-context's profiler.
template <typename Fn>
void runPhase(const char* collective, const char* phaseName,
              Phase profPhase, Context* sub,
              const std::vector<int>& members, Fn&& phase) {
  PhaseScope profScope(profPhase);
  try {
    phase();
  } catch (const TimeoutException& e) {
    TC_THROW(TimeoutException, "hier ", collective, " [", phaseName,
             "] on subgroup '", sub->groupTag(), "' (subgroup ranks ",
             describeMembers(members), "): ", e.what());
  } catch (const AbortedException& e) {
    TC_THROW(AbortedException, "hier ", collective, " [", phaseName,
             "] on subgroup '", sub->groupTag(), "' (subgroup ranks ",
             describeMembers(members), "): ", e.what());
  } catch (const IoException& e) {
    TC_THROW(IoException, "hier ", collective, " [", phaseName,
             "] on subgroup '", sub->groupTag(), "' (subgroup ranks ",
             describeMembers(members), "): ", e.what());
  }
}

struct HierPlanes {
  Context* local;            // never null (size >= 1)
  Context* leaders;          // null on non-leaders
  std::vector<int> localMembers;    // local rank -> global rank
  std::vector<int> leaderMembers;   // leader rank -> global rank
  std::shared_ptr<const Topology> topo;
};

HierPlanes planes(Context* ctx) {
  HierPlanes p;
  ctx->hierGroups(&p.local, &p.leaders);
  p.topo = ctx->topology();
  TC_ENFORCE(p.local != nullptr && p.topo != nullptr,
             "hier: no topology/sub-groups");
  p.localMembers = p.topo->hosts[p.topo->hostIndex];
  for (const auto& h : p.topo->hosts) {
    p.leaderMembers.push_back(h.front());
  }
  return p;
}

// Global ranks in "grouped" order — concatenated by host, members
// ascending within each host. The leader-plane *v collectives exchange
// host-contiguous blocks, so payloads are staged in this order and
// permuted back at the end when global rank order differs.
std::vector<int> groupedRanks(const Topology& topo) {
  std::vector<int> out;
  for (const auto& h : topo.hosts) {
    out.insert(out.end(), h.begin(), h.end());
  }
  return out;
}

}  // namespace

bool hierEligible(Context* ctx) {
  auto topo = ctx->topology();
  return topo != nullptr && topo->nonFlat();
}

void hierAllreduce(Context* ctx, char* work, size_t count, DataType dtype,
                   ReduceOp op, ReduceFn customFn, uint32_t tag,
                   std::chrono::milliseconds timeout) {
  HierPlanes p = planes(ctx);
  const bool multiLocal = p.topo->localSize > 1;
  if (multiLocal) {
    // Reduce-to-leader (in place on the leader: reduce supports
    // input == output on root) — half the intra-host bytes of a local
    // allreduce, and only the leader needs the host sum before the
    // inter-host exchange. Internally the bandwidth tier IS a ring
    // reduce-scatter + chunk gather over the shm plane.
    runPhase("allreduce", "intra-host reduce", Phase::kIntra,
             p.local, p.localMembers,
             [&] {
      ReduceOptions o;
      o.context = p.local;
      o.tag = tag;
      o.timeout = timeout;
      o.input = work;
      o.output = p.topo->isLeader ? work : nullptr;
      o.count = count;
      o.dtype = dtype;
      o.op = op;
      o.customFn = customFn;
      o.root = 0;
      reduce(o);
    });
  }
  if (p.leaders != nullptr) {
    runPhase("allreduce", "inter-host exchange", Phase::kInter, p.leaders,
             p.leaderMembers, [&] {
      AllreduceOptions o;
      o.context = p.leaders;
      o.tag = tag;
      o.timeout = timeout;
      o.inputs = {work};
      o.outputs = {work};
      o.count = count;
      o.dtype = dtype;
      o.op = op;
      o.customFn = customFn;
      allreduce(o);
    });
  }
  if (multiLocal) {
    runPhase("allreduce", "intra-host broadcast", Phase::kFanout,
             p.local, p.localMembers,
             [&] {
      BroadcastOptions o;
      o.context = p.local;
      o.tag = tag;
      o.timeout = timeout;
      o.buffer = work;
      o.count = count;
      o.dtype = dtype;
      o.root = 0;  // the host leader is always local rank 0
      broadcast(o);
    });
  }
}

void hierReduceScatter(Context* ctx, const void* input, void* output,
                       const std::vector<size_t>& recvCounts,
                       DataType dtype, ReduceOp op, ReduceFn customFn,
                       uint32_t tag, std::chrono::milliseconds timeout) {
  HierPlanes p = planes(ctx);
  const Topology& topo = *p.topo;
  const size_t elsize = elementSize(dtype);
  size_t totalCount = 0;
  for (size_t c : recvCounts) {
    totalCount += c;
  }
  const std::vector<int> grouped = groupedRanks(topo);

  // Stage the input in host-grouped block order so the leader plane's
  // reduce_scatter hands each leader one CONTIGUOUS host block.
  std::vector<size_t> blockOff(recvCounts.size(), 0);
  {
    size_t off = 0;
    for (size_t r = 0; r < recvCounts.size(); r++) {
      blockOff[r] = off;
      off += recvCounts[r] * elsize;
    }
  }
  auto stage = ctx->acquireScratch(totalCount * elsize);
  {
    PhaseScope ps(Phase::kPack);
    size_t off = 0;
    for (int r : grouped) {
      const size_t len = recvCounts[r] * elsize;
      std::memcpy(stage.data() + off,
                  static_cast<const char*>(input) + blockOff[r], len);
      off += len;
    }
  }

  if (topo.localSize > 1) {
    // Reduce-to-leader (in place on the leader): only leaders feed the
    // inter-host reduce_scatter, so non-leaders need no host sum.
    runPhase("reduce_scatter", "intra-host reduce", Phase::kIntra, p.local,
             p.localMembers, [&] {
      ReduceOptions o;
      o.context = p.local;
      o.tag = tag;
      o.timeout = timeout;
      o.input = stage.data();
      o.output = topo.isLeader ? stage.data() : nullptr;
      o.count = totalCount;
      o.dtype = dtype;
      o.op = op;
      o.customFn = customFn;
      o.root = 0;
      reduce(o);
    });
  }

  // My host's block of the grouped layout.
  size_t hostCount = 0;
  for (int r : topo.hosts[topo.hostIndex]) {
    hostCount += recvCounts[r];
  }
  auto hostBlock = ctx->acquireScratch(hostCount * elsize);
  if (p.leaders != nullptr) {
    std::vector<size_t> perHost(topo.nHosts(), 0);
    for (int h = 0; h < topo.nHosts(); h++) {
      for (int r : topo.hosts[h]) {
        perHost[h] += recvCounts[r];
      }
    }
    runPhase("reduce_scatter", "inter-host exchange", Phase::kInter, p.leaders,
             p.leaderMembers, [&] {
      ReduceScatterOptions o;
      o.context = p.leaders;
      o.tag = tag;
      o.timeout = timeout;
      o.input = stage.data();
      o.output = hostBlock.data();
      o.recvCounts = perHost;
      o.dtype = dtype;
      o.op = op;
      o.customFn = customFn;
      reduceScatter(o);
    });
  }
  if (topo.localSize > 1) {
    runPhase("reduce_scatter", "intra-host broadcast", Phase::kFanout, p.local,
             p.localMembers, [&] {
      BroadcastOptions o;
      o.context = p.local;
      o.tag = tag;
      o.timeout = timeout;
      o.buffer = hostBlock.data();
      o.count = hostCount;
      o.dtype = dtype;
      o.root = 0;
      broadcast(o);
    });
  }
  // Slice my block out of the host block (members ascending, so my
  // offset is the counts of lower-ranked co-hosted members).
  size_t myOff = 0;
  for (int r : topo.hosts[topo.hostIndex]) {
    if (r == topo.rank) {
      break;
    }
    myOff += recvCounts[r] * elsize;
  }
  PhaseScope ps(Phase::kUnpack);
  std::memcpy(output, hostBlock.data() + myOff,
              recvCounts[topo.rank] * elsize);
}

void hierAllgather(Context* ctx, const void* input, void* output,
                   size_t count, DataType dtype, uint32_t tag,
                   std::chrono::milliseconds timeout) {
  HierPlanes p = planes(ctx);
  const Topology& topo = *p.topo;
  const size_t elsize = elementSize(dtype);
  const size_t rankBytes = count * elsize;
  const int size = static_cast<int>(topo.hostOf.size());
  const std::vector<int> grouped = groupedRanks(topo);
  if (input == nullptr) {
    // In-place form: the caller staged its block at rank offset.
    input = static_cast<const char*>(output) +
            size_t(topo.rank) * rankBytes;
  }

  auto localBuf = ctx->acquireScratch(topo.localSize * rankBytes);
  if (topo.localSize > 1) {
    runPhase("allgather", "intra-host allgather", Phase::kIntra,
             p.local, p.localMembers,
             [&] {
      AllgatherOptions o;
      o.context = p.local;
      o.tag = tag;
      o.timeout = timeout;
      o.input = input;
      o.output = localBuf.data();
      o.count = count;
      o.dtype = dtype;
      allgather(o);
    });
  } else {
    PhaseScope ps(Phase::kPack);
    std::memcpy(localBuf.data(), input, rankBytes);
  }

  auto groupedBuf = ctx->acquireScratch(size_t(size) * rankBytes);
  if (p.leaders != nullptr) {
    std::vector<size_t> perHost(topo.nHosts());
    for (int h = 0; h < topo.nHosts(); h++) {
      perHost[h] = topo.hosts[h].size() * count;
    }
    runPhase("allgather", "inter-host exchange", Phase::kInter, p.leaders,
             p.leaderMembers, [&] {
      AllgathervOptions o;
      o.context = p.leaders;
      o.tag = tag;
      o.timeout = timeout;
      o.input = localBuf.data();
      o.output = groupedBuf.data();
      o.counts = perHost;
      o.dtype = dtype;
      allgatherv(o);
    });
  }
  if (topo.localSize > 1) {
    runPhase("allgather", "intra-host broadcast", Phase::kFanout,
             p.local, p.localMembers,
             [&] {
      BroadcastOptions o;
      o.context = p.local;
      o.tag = tag;
      o.timeout = timeout;
      o.buffer = groupedBuf.data();
      o.count = size_t(size) * count;
      o.dtype = dtype;
      o.root = 0;
      broadcast(o);
    });
  }
  // Grouped order -> global rank order.
  PhaseScope ps(Phase::kUnpack);
  for (int g = 0; g < size; g++) {
    std::memcpy(static_cast<char*>(output) + size_t(grouped[g]) * rankBytes,
                groupedBuf.data() + size_t(g) * rankBytes, rankBytes);
  }
}

void hierBroadcast(Context* ctx, void* buffer, size_t count,
                   DataType dtype, int root, uint32_t tag,
                   std::chrono::milliseconds timeout) {
  HierPlanes p = planes(ctx);
  const Topology& topo = *p.topo;
  const int rootHost = topo.hostOf[root];
  const bool onRootHost = topo.hostIndex == rootHost;
  const bool rootIsLeader = topo.hosts[rootHost].front() == root;

  // Phase 1 (root's host, when the root is not its leader): local
  // broadcast FROM the root, delivering to the leader and co-hosted
  // ranks in one shm pass.
  if (onRootHost && !rootIsLeader && topo.localSize > 1) {
    runPhase("broadcast", "intra-host (root)", Phase::kIntra,
             p.local, p.localMembers,
             [&] {
      const auto& mine = topo.hosts[topo.hostIndex];
      const int rootLocal = static_cast<int>(
          std::find(mine.begin(), mine.end(), root) - mine.begin());
      BroadcastOptions o;
      o.context = p.local;
      o.tag = tag;
      o.timeout = timeout;
      o.buffer = buffer;
      o.count = count;
      o.dtype = dtype;
      o.root = rootLocal;
      broadcast(o);
    });
  }
  // Phase 2: leaders relay across hosts (root's host's leader is the
  // leader-plane root).
  if (p.leaders != nullptr) {
    runPhase("broadcast", "inter-host relay", Phase::kInter,
             p.leaders, p.leaderMembers,
             [&] {
      BroadcastOptions o;
      o.context = p.leaders;
      o.tag = tag;
      o.timeout = timeout;
      o.buffer = buffer;
      o.count = count;
      o.dtype = dtype;
      o.root = rootHost;  // host h's leader is leader-plane rank h
      broadcast(o);
    });
  }
  // Phase 3: every host whose members did not already receive in phase
  // 1 broadcasts from its leader.
  if (!(onRootHost && !rootIsLeader) && topo.localSize > 1) {
    runPhase("broadcast", "intra-host (leader)", Phase::kFanout,
             p.local, p.localMembers,
             [&] {
      BroadcastOptions o;
      o.context = p.local;
      o.tag = tag;
      o.timeout = timeout;
      o.buffer = buffer;
      o.count = count;
      o.dtype = dtype;
      o.root = 0;
      broadcast(o);
    });
  }
}

void hierBarrier(Context* ctx, uint32_t tag,
                 std::chrono::milliseconds timeout) {
  HierPlanes p = planes(ctx);
  // arrive (local) -> synchronize (leaders) -> release (local): the
  // second local barrier is what keeps a non-leader from exiting before
  // the inter-host round completed.
  if (p.topo->localSize > 1) {
    runPhase("barrier", "intra-host arrive", Phase::kIntra,
             p.local, p.localMembers, [&] {
      BarrierOptions o;
      o.context = p.local;
      o.tag = tag;
      o.timeout = timeout;
      barrier(o);
    });
  }
  if (p.leaders != nullptr) {
    runPhase("barrier", "inter-host", Phase::kInter,
             p.leaders, p.leaderMembers, [&] {
      BarrierOptions o;
      o.context = p.leaders;
      o.tag = tag;
      o.timeout = timeout;
      barrier(o);
    });
  }
  if (p.topo->localSize > 1) {
    runPhase("barrier", "intra-host release", Phase::kFanout,
             p.local, p.localMembers,
             [&] {
      BarrierOptions o;
      o.context = p.local;
      o.tag = tag;
      o.timeout = timeout;
      barrier(o);
    });
  }
}

}  // namespace group
}  // namespace tpucoll
