// Context::split — native communicator split (MPI_Comm_split semantics)
// plus the lazily-built hierarchical sub-communicators every kHier
// collective rides. Lives in group/ rather than context.cc because the
// exchange plumbing (store color exchange, parent-collective blob
// allgather) pulls in the collective layer, which context.cc must not.
#include <algorithm>
#include <cstring>
#include <utility>

#include "tpucoll/collectives/collectives.h"
#include "tpucoll/common/logging.h"
#include "tpucoll/context.h"
#include "tpucoll/fault/fault.h"
#include "tpucoll/group/topology.h"

namespace tpucoll {

namespace {

// Reserved parent tags for the hierGroups() internal splits. High in the
// 32-bit tag space next to the forkFrom default (0xFFFFFF0); each split
// consumes [tag, tag+2] on store-less parents.
constexpr uint32_t kHierLocalSplitTag = 0xFFFFE00u;
constexpr uint32_t kHierLeaderSplitTag = 0xFFFFE40u;

struct ColorKey {
  int64_t color;
  int64_t key;
};

std::string encodeColorKey(int color, int key) {
  return std::to_string(color) + ":" + std::to_string(key);
}

ColorKey decodeColorKey(const std::string& s, int fromRank) {
  const size_t sep = s.find(':');
  TC_ENFORCE(sep != std::string::npos, "split: malformed color record \"",
             s, "\" from rank ", fromRank);
  ColorKey ck;
  ck.color = std::strtoll(s.c_str(), nullptr, 10);
  ck.key = std::strtoll(s.c_str() + sep + 1, nullptr, 10);
  return ck;
}

}  // namespace

std::unique_ptr<Context> Context::split(int color, int key, uint32_t tag) {
  TC_ENFORCE(tctx_ != nullptr, "split: context not connected");
  const uint64_t gen = nextSplitGeneration(tag);

  // ---- 1. (color, key) exchange across the PARENT group -------------
  // Store-backed when a rendezvous store exists (keys scoped by the
  // context's group tag + the user tag + the per-tag generation: two
  // concurrent splits over one store use distinct tags and cannot
  // collide; sequential same-tag splits advance the generation instead
  // of re-reading stale keys). Store-less (forked) contexts exchange
  // over the parent's own collectives.
  std::vector<ColorKey> all(size_);
  const std::string scope = "split/" + std::to_string(tag) + "/" +
                            std::to_string(gen) + "/";
  if (store_ != nullptr) {
    const std::string mine = encodeColorKey(color, key);
    store_->set(scopedStoreKey(scope + "c" + std::to_string(rank_)),
                Store::Buf(mine.begin(), mine.end()));
    std::vector<std::string> keys;
    std::vector<int> order;
    for (int j = 0; j < size_; j++) {
      if (j == rank_) {
        all[j] = decodeColorKey(mine, j);
      } else {
        keys.push_back(scopedStoreKey(scope + "c" + std::to_string(j)));
        order.push_back(j);
      }
    }
    auto vals = store_->multiGet(keys, timeout_);
    for (size_t i = 0; i < order.size(); i++) {
      all[order[i]] = decodeColorKey(
          std::string(vals[i].begin(), vals[i].end()), order[i]);
    }
  } else {
    std::vector<int64_t> flat(size_t(size_) * 2);
    int64_t mine[2] = {color, key};
    AllgatherOptions opts;
    opts.context = this;
    opts.tag = tag;
    opts.input = mine;
    opts.output = flat.data();
    opts.count = 2;
    opts.dtype = DataType::kInt64;
    allgather(opts);
    for (int j = 0; j < size_; j++) {
      all[j] = ColorKey{flat[2 * j], flat[2 * j + 1]};
    }
  }

  // ---- 2. membership: my color's ranks, ordered by (key, rank) ------
  std::vector<int> members;
  for (int j = 0; j < size_; j++) {
    if (color >= 0 && all[j].color == color) {
      members.push_back(j);
    }
  }
  std::stable_sort(members.begin(), members.end(), [&](int a, int b) {
    return std::make_pair(all[a].key, int64_t(a)) <
           std::make_pair(all[b].key, int64_t(b));
  });
  const bool member = color >= 0;
  int newRank = -1;
  if (member) {
    newRank = static_cast<int>(
        std::find(members.begin(), members.end(), rank_) -
        members.begin());
  }

  // ---- 3. build + bootstrap the subset communicator -----------------
  // Non-members still participate in the store-less blob exchange below
  // (it runs over the full parent), then return null.
  const std::string childTag =
      (groupTag_.empty() ? std::string() : groupTag_ + "/") + "s" +
      std::to_string(tag) + "." + std::to_string(gen) + ".c" +
      std::to_string(color);
  std::unique_ptr<Context> child;
  if (member) {
    child = std::make_unique<Context>(newRank,
                                      static_cast<int>(members.size()));
    child->setTimeout(timeout_);
    child->hostId_ = hostId_;
    child->applyGroupTag(childTag);
  }

  if (store_ != nullptr) {
    if (!member) {
      return nullptr;
    }
    // The subset's mesh bootstraps through the normal store path in its
    // own scoped namespace — topology discovery (and so the shm mask)
    // re-runs among the members, which is exactly the subset result.
    auto prefix = std::make_shared<PrefixStore>(
        store_, scopedStoreKey(scope + "g" + std::to_string(color)));
    child->connectFullMesh(std::move(prefix), device_);
    return child;
  }

  // Store-less parent: blob exchange over the parent's collectives, the
  // forkFrom pattern widened to subsets (non-members contribute zero
  // bytes and discard the result).
  std::vector<uint8_t> blob;
  if (member) {
    child->device_ = device_;
    fault::maybeLoadEnvFile();
    FlightRecorder::maybeInstallFromEnv();
    child->maybeLoadTuningFile();
    child->tctx_ = std::make_unique<transport::Context>(
        device_, newRank, static_cast<int>(members.size()));
    child->tctx_->setInstrumentation(&child->tracer_, &child->metrics_,
                                     &child->flightrec_);
    child->tctx_->setFaultDomain(child->faultDomain_);
    child->applyTransportHints();
    auto parentTopo = topology();
    if (parentTopo != nullptr) {
      child->installTopology(std::make_shared<const Topology>(
          subsetTopology(*parentTopo, members, newRank)));
    }
    blob = child->tctx_->prepareFullMesh();
  }
  std::vector<uint64_t> lens(size_);
  uint64_t myLen = blob.size();
  {
    AllgatherOptions opts;
    opts.context = this;
    opts.tag = tag + 1;
    opts.input = &myLen;
    opts.output = lens.data();
    opts.count = 1;
    opts.dtype = DataType::kUint64;
    allgather(opts);
  }
  std::vector<size_t> counts(lens.begin(), lens.end());
  size_t total = 0;
  for (size_t c : counts) {
    total += c;
  }
  std::vector<uint8_t> allBlobs(total);
  {
    AllgathervOptions opts;
    opts.context = this;
    opts.tag = tag + 2;
    opts.input = blob.data();
    opts.output = allBlobs.data();
    opts.counts = counts;
    opts.dtype = DataType::kUint8;
    allgatherv(opts);
  }
  if (!member) {
    return nullptr;
  }
  std::vector<size_t> offsets(size_, 0);
  {
    size_t off = 0;
    for (int j = 0; j < size_; j++) {
      offsets[j] = off;
      off += counts[j];
    }
  }
  std::vector<std::vector<uint8_t>> memberBlobs(members.size());
  for (size_t m = 0; m < members.size(); m++) {
    const int parentRank = members[m];
    TC_ENFORCE(counts[parentRank] > 0, "split: member rank ", parentRank,
               " published no bootstrap blob");
    memberBlobs[m].assign(
        allBlobs.begin() + offsets[parentRank],
        allBlobs.begin() + offsets[parentRank] + counts[parentRank]);
  }
  child->tctx_->connectWithBlobs(memberBlobs, timeout_);
  return child;
}

std::unique_ptr<Context> Context::splitByHost(uint32_t tag) {
  auto topo = topology();
  TC_ENFORCE(topo != nullptr,
             "split_by_host: no topology (context not connected?)");
  return split(topo->hostIndex, rank_, tag);
}

void Context::hierGroups(Context** local, Context** leaders) {
  std::unique_lock<std::mutex> lk(hierMu_);
  // Single-flight WITHOUT holding hierMu_ across the split bootstrap:
  // the exchange can block for the full store/collective timeout, and
  // close() must be able to take hierMu_ meanwhile (a holder blocked in
  // a rendezvous-store wait is NOT unwound by closing the parent mesh).
  hierCv_.wait(lk, [&] { return !hierBuilding_; });
  if (!hierInit_) {
    hierBuilding_ = true;
    lk.unlock();
    std::unique_ptr<Context> localCtx;
    std::unique_ptr<Context> leaderCtx;
    try {
      auto topo = topology();
      TC_ENFORCE(topo != nullptr, "hierGroups: no topology");
      // Key = global rank, so the host leader (lowest member rank)
      // always lands on local rank 0 — the root every hier phase
      // broadcasts from.
      localCtx = split(topo->hostIndex, rank_, kHierLocalSplitTag);
      leaderCtx =
          split(topo->isLeader ? 0 : -1, rank_, kHierLeaderSplitTag);
    } catch (...) {
      lk.lock();
      hierBuilding_ = false;
      hierCv_.notify_all();
      throw;
    }
    lk.lock();
    hierLocal_ = std::move(localCtx);
    hierLeaders_ = std::move(leaderCtx);
    hierInit_ = true;
    hierBuilding_ = false;
    if (hierClosed_) {
      // close() ran while we were bootstrapping: honor it now so the
      // fresh sub-meshes don't outlive the closed parent.
      if (hierLeaders_ != nullptr) {
        hierLeaders_->close();
      }
      hierLocal_->close();
    }
    hierCv_.notify_all();
  }
  *local = hierLocal_.get();
  *leaders = hierLeaders_.get();
}

}  // namespace tpucoll
