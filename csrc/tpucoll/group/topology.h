// Host topology of a process group: which ranks share a machine.
//
// Discovered once per context at bootstrap — every rank publishes a host
// fingerprint (hostname + boot id, overridable for simulation and custom
// placement labels) through the rendezvous store, and all ranks derive
// the same ranks-per-host map, local rank/size, and per-host leader
// (lowest global rank). The result drives two things:
//  - the shm payload plane only negotiates between ranks whose
//    fingerprints match (transport::Context::setShmPeers), which is also
//    what lets tests simulate an H-host topology on one machine by
//    overriding the fingerprint per process (TPUCOLL_HOST_ID);
//  - the hierarchical collectives (group/hier.h) compose an intra-host
//    fast plane (shm) with an inter-host slow plane (TCP) among elected
//    leaders only — the HiCCL-style decomposition.
#pragma once

#include <string>
#include <vector>

namespace tpucoll {

struct Topology {
  // Host fingerprints in host-index order; hosts are numbered by their
  // lowest member rank, so host 0 always contains global rank 0.
  std::vector<std::string> fingerprints;
  // hosts[h] = member global ranks of host h, ascending.
  std::vector<std::vector<int>> hosts;
  // hostOf[r] = host index of global rank r.
  std::vector<int> hostOf;

  int rank{0};        // this rank
  int hostIndex{0};   // this rank's host
  int localRank{0};   // index within hosts[hostIndex]
  int localSize{1};
  int leader{0};      // global rank of this host's leader (lowest member)
  bool isLeader{true};

  int nHosts() const { return static_cast<int>(hosts.size()); }
  int maxLocalSize() const;
  // True when the hierarchy has both planes to exploit: more than one
  // host AND more than one rank on some host. Flat topologies dispatch
  // hierarchical requests back to the flat schedules.
  bool nonFlat() const { return nHosts() > 1 && maxLocalSize() > 1; }
  // True when rank a and rank b share a host (shm-reachability modulo
  // TPUCOLL_SHM and segment-creation success).
  bool sameHost(int a, int b) const { return hostOf[a] == hostOf[b]; }

  std::string toJson() const;
};

// Build from per-rank fingerprints (index = global rank).
Topology buildTopology(int rank,
                       const std::vector<std::string>& fingerprints);

// Topology of a subset communicator: `members` are parent global ranks
// of the subgroup in NEW-rank order; the result is renumbered 0..n-1.
Topology subsetTopology(const Topology& parent,
                        const std::vector<int>& members, int newRank);

// This process's host fingerprint: `override` (Context::setHostId) wins,
// then TPUCOLL_HOST_ID, then "<hostname>/<boot-id>". The boot id makes
// hostname collisions across machines (cloned images) harmless; the
// override is what lets one machine present as H simulated hosts.
std::string hostFingerprint(const std::string& override_);

}  // namespace tpucoll
