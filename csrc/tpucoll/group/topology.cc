#include "tpucoll/group/topology.h"

#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <map>
#include <sstream>

#include "tpucoll/common/env.h"
#include "tpucoll/common/json.h"
#include "tpucoll/common/logging.h"

namespace tpucoll {

int Topology::maxLocalSize() const {
  size_t m = 1;
  for (const auto& h : hosts) {
    m = std::max(m, h.size());
  }
  return static_cast<int>(m);
}

std::string Topology::toJson() const {
  std::ostringstream out;
  out << "{\"rank\":" << rank << ",\"host_index\":" << hostIndex
      << ",\"local_rank\":" << localRank << ",\"local_size\":" << localSize
      << ",\"leader\":" << leader
      << ",\"is_leader\":" << (isLeader ? "true" : "false")
      << ",\"n_hosts\":" << nHosts()
      << ",\"non_flat\":" << (nonFlat() ? "true" : "false") << ",\"hosts\":[";
  for (size_t h = 0; h < hosts.size(); h++) {
    out << (h == 0 ? "" : ",") << "{\"fingerprint\":";
    appendJsonString(out, fingerprints[h]);
    out << ",\"ranks\":[";
    for (size_t i = 0; i < hosts[h].size(); i++) {
      out << (i == 0 ? "" : ",") << hosts[h][i];
    }
    out << "]}";
  }
  out << "]}";
  return out.str();
}

Topology buildTopology(int rank,
                       const std::vector<std::string>& fingerprints) {
  TC_ENFORCE(!fingerprints.empty(), "topology: no fingerprints");
  TC_ENFORCE(rank >= 0 && rank < static_cast<int>(fingerprints.size()),
             "topology: rank out of range");
  Topology topo;
  topo.fingerprints.clear();
  topo.rank = rank;
  topo.hostOf.assign(fingerprints.size(), -1);
  // Hosts numbered by first-appearing (= lowest) member rank, so the
  // numbering is deterministic across ranks and host 0 holds rank 0.
  std::map<std::string, int> index;
  for (size_t r = 0; r < fingerprints.size(); r++) {
    auto it = index.find(fingerprints[r]);
    int h;
    if (it == index.end()) {
      h = static_cast<int>(topo.hosts.size());
      index.emplace(fingerprints[r], h);
      topo.hosts.emplace_back();
      topo.fingerprints.push_back(fingerprints[r]);
    } else {
      h = it->second;
    }
    topo.hostOf[r] = h;
    topo.hosts[h].push_back(static_cast<int>(r));
  }
  topo.hostIndex = topo.hostOf[rank];
  const auto& mine = topo.hosts[topo.hostIndex];
  topo.localSize = static_cast<int>(mine.size());
  topo.localRank = static_cast<int>(
      std::find(mine.begin(), mine.end(), rank) - mine.begin());
  topo.leader = mine.front();
  topo.isLeader = topo.leader == rank;
  return topo;
}

Topology subsetTopology(const Topology& parent,
                        const std::vector<int>& members, int newRank) {
  std::vector<std::string> fps;
  fps.reserve(members.size());
  for (int m : members) {
    TC_ENFORCE(m >= 0 && m < static_cast<int>(parent.hostOf.size()),
               "subsetTopology: member rank ", m, " out of range");
    fps.push_back(parent.fingerprints[parent.hostOf[m]]);
  }
  return buildTopology(newRank, fps);
}

std::string hostFingerprint(const std::string& override_) {
  if (!override_.empty()) {
    return override_;
  }
  const char* env = envString("TPUCOLL_HOST_ID");
  if (env != nullptr) {
    return env;
  }
  char host[256] = {0};
  if (gethostname(host, sizeof(host) - 1) != 0) {
    snprintf(host, sizeof(host), "unknown-host");
  }
  std::string fp(host);
  // The boot id disambiguates cloned hostnames; best-effort (containers
  // may hide /proc) — the hostname alone still works for common setups.
  FILE* f = fopen("/proc/sys/kernel/random/boot_id", "r");
  if (f != nullptr) {
    char boot[64] = {0};
    if (fgets(boot, sizeof(boot), f) != nullptr) {
      // strip trailing newline
      for (char* p = boot; *p != '\0'; p++) {
        if (*p == '\n') {
          *p = '\0';
          break;
        }
      }
      fp += "/";
      fp += boot;
    }
    fclose(f);
  }
  return fp;
}

}  // namespace tpucoll
