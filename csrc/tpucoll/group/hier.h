// Topology-aware hierarchical collectives (AllreduceAlgorithm::kHier and
// friends): compose the intra-host fast plane (shm rings between
// co-hosted ranks) with the inter-host slow plane (TCP between elected
// leaders) instead of running one flat schedule over the mixed fabric.
//
// Shape (the HiCCL decomposition; docs/topology.md):
//   allreduce       intra-host reduce to the leader (in place on the
//                   leader; the bandwidth tier is a ring RS + chunk
//                   gather over shm) -> leader-only allreduce across
//                   hosts -> intra-host broadcast from the leader
//   reduce_scatter  stage host-grouped -> intra-host reduce to the
//                   leader -> leader reduce_scatter with per-host block
//                   counts -> intra-host broadcast of the host block ->
//                   local slice copy
//   allgather       intra-host allgather -> leader allgatherv of host
//                   blocks -> intra-host broadcast -> global-rank
//                   permutation
//   broadcast       root's host: local broadcast from root; leaders
//                   relay across hosts; other hosts: local broadcast
//   barrier         local barrier -> leader barrier -> local barrier
//
// With L ranks/host and H hosts the slow plane moves 2(H-1)/H of the
// payload once per HOST (leaders only) instead of once per rank —
// independent of L, which is the entire point.
//
// Every phase is an ordinary collective on a split sub-communicator
// (Context::hierGroups), so the plan cache, tuning tables, metrics,
// flight recorder, and fault plane all apply per sub-group for free.
//
// Precision/ordering contract: the reduction ORDER differs from the flat
// schedules (local partials combine before any cross-host term), so
// floating-point results are deterministic and identical across ranks,
// but not bitwise-equal to the flat ring's result. Same class of
// contract as the algorithm choice itself (docs/topology.md).
#pragma once

#include <chrono>
#include <cstddef>
#include <vector>

#include "tpucoll/math.h"
#include "tpucoll/types.h"

namespace tpucoll {

class Context;

namespace group {

// True when the topology has both planes to exploit (>1 host AND >1
// rank on some host). The dispatchers fall back to the flat schedules
// otherwise, so kHier is always safe to request.
bool hierEligible(Context* ctx);

void hierAllreduce(Context* ctx, char* work, size_t count, DataType dtype,
                   ReduceOp op, ReduceFn customFn, uint32_t tag,
                   std::chrono::milliseconds timeout);

void hierReduceScatter(Context* ctx, const void* input, void* output,
                       const std::vector<size_t>& recvCounts,
                       DataType dtype, ReduceOp op, ReduceFn customFn,
                       uint32_t tag, std::chrono::milliseconds timeout);

void hierAllgather(Context* ctx, const void* input, void* output,
                   size_t count, DataType dtype, uint32_t tag,
                   std::chrono::milliseconds timeout);

void hierBroadcast(Context* ctx, void* buffer, size_t count,
                   DataType dtype, int root, uint32_t tag,
                   std::chrono::milliseconds timeout);

void hierBarrier(Context* ctx, uint32_t tag,
                 std::chrono::milliseconds timeout);

}  // namespace group
}  // namespace tpucoll
