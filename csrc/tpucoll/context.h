// Top-level process-group context: rank/size identity, default timeout, slot
// allocation, and ownership of the transport mesh (reference contract:
// gloo/context.h:27-65 + gloo/rendezvous/context.cc:25-35). All collective
// state lives here — there is no global state anywhere in the library.
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "tpucoll/common/flightrec.h"
#include "tpucoll/common/metrics.h"
#include "tpucoll/common/profile.h"
#include "tpucoll/common/span.h"
#include "tpucoll/common/tracer.h"
#include "tpucoll/group/topology.h"
#include "tpucoll/rendezvous/store.h"
#include "tpucoll/transport/context.h"
#include "tpucoll/transport/device.h"

namespace tpucoll {

namespace tuning {
class TuningTable;
}  // namespace tuning

namespace schedule {
class ScheduleTable;
struct InstalledSchedules;
}  // namespace schedule

namespace plan {
class PlanCache;
}  // namespace plan

namespace fleetobs {
class FleetObs;
}  // namespace fleetobs

class Context;

namespace elastic {
class ElasticAgent;
// See elastic/elastic.h — declared here so Context can befriend it.
std::unique_ptr<Context> buildEpochContext(
    std::shared_ptr<Store> store, std::shared_ptr<transport::Device> device,
    int newRank, int newSize, uint64_t epoch, const std::string& hostId,
    std::shared_ptr<const tuning::TuningTable> table,
    std::chrono::milliseconds timeout);
}  // namespace elastic

class Context {
 public:
  static constexpr std::chrono::milliseconds kDefaultTimeout =
      std::chrono::milliseconds(30000);

  Context(int rank, int size);
  ~Context();

  int rank() const { return rank_; }
  int size() const { return size_; }

  std::chrono::milliseconds getTimeout() const { return timeout_; }
  void setTimeout(std::chrono::milliseconds timeout) { timeout_ = timeout; }

  // Fault-plane identity (fault.h) applied to the transport mesh when it
  // is created: set BEFORE connectFullMesh/forkFrom so even the
  // bootstrap traffic (connect_refuse rules, fork-time failures) is
  // keyed to this domain rather than the parent's. 0 — the default — is
  // the root domain; async-engine lanes carry lane + 1.
  void setFaultDomain(int domain) { faultDomain_ = domain; }
  int faultDomain() const { return faultDomain_; }

  // Host-fingerprint override for topology discovery (group/topology.h):
  // set BEFORE connect. Empty — the default — falls back to
  // TPUCOLL_HOST_ID, then "<hostname>/<boot-id>". Two ranks whose
  // fingerprints match are treated as co-hosted: the shm payload plane
  // negotiates between them, split_by_host() groups them, and the
  // hierarchical collectives put them on the same intra-host plane —
  // which is exactly how tests simulate an H-host topology on one
  // machine (give each process a distinct fake id).
  void setHostId(std::string hostId);
  const std::string& hostId() const { return hostId_; }

  // Host topology discovered at bootstrap (connectFullMesh exchanges
  // fingerprints through the store; forked contexts inherit the
  // parent's; split contexts carry the member subset). Null only for a
  // context that has not connected yet.
  std::shared_ptr<const Topology> topology() const;

  // Group tag namespace of this communicator: "" for a bootstrap (root)
  // context, "s<tag>.<gen>.c<color>" path segments for split
  // sub-communicators (nested splits append). Scopes every rendezvous
  // Store key written after bootstrap (tuning elections, split color
  // exchanges), the flight-recorder dump filenames, the metrics
  // snapshot's "group" field, and the fault-plane domain — so two
  // concurrent splits over one store can never collide and a subgroup's
  // post-mortem artifacts never clobber the parent's.
  const std::string& groupTag() const { return groupTag_; }
  // "tpucoll/<groupTag>/<suffix>" (or "tpucoll/<suffix>" at the root):
  // the ONE spelling of post-bootstrap store keys.
  std::string scopedStoreKey(const std::string& suffix) const;

  // ---- process-group split (group/split.cc) ----
  // MPI_Comm_split semantics: a COLLECTIVE over this context — every
  // rank must call concurrently with the same `tag`. Ranks passing the
  // same non-negative `color` form a subset communicator with fresh
  // contiguous ranks ordered by (key, parent rank); a negative color
  // opts out and yields nullptr. The child is a full Context: own
  // members-only mesh (pairs between members only), own tag/slot
  // namespace, own plan cache / metrics / flight recorder / fault
  // domain, own store namespace (nested splits and tuning elections
  // work), topology = the member subset.
  //
  // Exchange plumbing: the color exchange and the member mesh bootstrap
  // ride the rendezvous store when this context has one (keys scoped by
  // groupTag + `tag` + a per-tag generation, so sequential same-tag
  // splits and concurrent distinct-tag splits never collide); forked
  // store-less contexts exchange over this context's own collectives
  // instead, consuming parent tags [tag, tag+2].
  std::unique_ptr<Context> split(int color, int key, uint32_t tag = 0);
  // Convenience: color = host index from the discovered topology — the
  // intra-host communicator native hierarchical collectives ride.
  std::unique_ptr<Context> splitByHost(uint32_t tag = 0);

  // Lazily-created hierarchical sub-communicators (first kHier
  // collective, or explicit): `local` spans this host's ranks, `leaders`
  // one leader per host (null on non-leaders). Creation is a collective
  // over this context (reserved split tags); single-flight per context.
  void hierGroups(Context** local, Context** leaders);

  // ---- elastic membership plane (elastic/elastic.h) ----
  // Build THE successor communicator this group continues as in
  // `epoch` after a membership change: `members` lists the surviving
  // ranks of THIS context (ascending; this rank must be listed), the
  // child takes fresh contiguous ranks in that order, bootstraps a
  // members-only mesh under the epoch-scoped store namespace
  // ("tpucoll/elastic/e<epoch>/mesh/..."), carries group tag
  // "e<epoch>" (epoch-tagged flight recorder, metrics and fault
  // domain), and inherits the installed tuning table, host id and
  // timeout. Requires a store-backed context; every member must call
  // with the same arguments. ElasticAgent drives this machinery
  // automatically (lease-detected membership); defined in
  // elastic/elastic.cc.
  std::unique_ptr<Context> rebuild(const std::vector<int>& members,
                                   uint64_t epoch);

  // Bootstrap the full mesh over a rendezvous store. Call once.
  void connectFullMesh(std::shared_ptr<Store> store,
                       std::shared_ptr<transport::Device> device);

  // Bootstrap by riding an already-connected context: fresh pairs are
  // created on the parent's device and the address blobs are exchanged
  // with the parent's own collectives — no store traffic (reference
  // ContextFactory, gloo/rendezvous/context.cc:37-162). `tag` namespaces
  // the bootstrap exchange on the parent; it must not collide with
  // concurrently running parent collectives.
  void forkFrom(Context& parent, uint32_t tag = 0xFFFFFF0u);

  // Monotonic slot allocator for application point-to-point messaging under
  // the kUser prefix; collectives namespace themselves by (prefix, tag).
  uint64_t nextSlot(uint32_t numToSkip = 1);

  std::unique_ptr<transport::UnboundBuffer> createUnboundBuffer(void* ptr,
                                                               size_t size);

  // Reusable staging memory for collective schedules. Fresh allocations pay
  // 4KiB-page first-touch faults inside the receive path (the kernel zeroes
  // pages under read()), which dominates large-payload rings; the pool keeps
  // pages warm across calls. Thread-safe; concurrent collectives each get
  // their own buffer.
  class Scratch {
   public:
    Scratch(Context* ctx, std::vector<char> buf)
        : ctx_(ctx), buf_(std::move(buf)) {}
    Scratch(Scratch&& o) noexcept : ctx_(o.ctx_), buf_(std::move(o.buf_)) {
      o.ctx_ = nullptr;  // moved-from dtor returns nothing to the pool
    }
    Scratch(const Scratch&) = delete;
    Scratch& operator=(const Scratch&) = delete;
    Scratch& operator=(Scratch&&) = delete;
    ~Scratch();
    char* data() { return buf_.data(); }
    size_t size() const { return buf_.size(); }

   private:
    friend class Context;
    Context* ctx_;
    std::vector<char> buf_;
  };
  Scratch acquireScratch(size_t minBytes);

  transport::Context* transport() const { return tctx_.get(); }

  // Persistent collective plans (collectives/plan.h): LRU of pre-created
  // UnboundBuffers + scratch arenas + memoized schedules keyed by the
  // repeated collective's full identity, so the steady-state replay of
  // training traffic performs zero allocations and zero registrations.
  // Invalidation: close()/destruction and setTuningTable() drop every
  // plan (the latter because kAuto keys embed the resolved algorithm).
  plan::PlanCache& planCache() { return *planCache_; }

  // First-class tracing (capability the reference lacks): start(), run
  // collectives, then dump Chrome trace-event JSON via traceJson().
  Tracer& tracer() { return tracer_; }

  // Metrics registry (counters + latency histograms + watchdog state).
  // Enabled by default; per-op cost is a few relaxed atomic adds, and a
  // single relaxed load when disabled.
  Metrics& metrics() { return metrics_; }

  // Always-on flight recorder (common/flightrec.h): bounded lock-free
  // ring of every collective/p2p op this context issued, dumped to JSON
  // on stall / transport failure / fatal signal / request. There is no
  // off switch — the whole point is that the record exists when the
  // process dies unexpectedly.
  FlightRecorder& flightrec() { return flightrec_; }

  // Phase-level collective profiler (common/profile.h): per-op
  // pack/post/wire_wait/reduce/unpack breakdowns in a bounded ring
  // keyed by the flight recorder's cseq, plus aggregate phase
  // histograms flushed into the metrics registry. On by default
  // (TPUCOLL_PROFILE=0 disables; off costs one relaxed load per op).
  profile::Profiler& profiler() { return profiler_; }

  // Causal span recorder (common/span.h): per-phase-INSTANCE spans —
  // {cseq, id, kind, peer, slot, bytes, t0, t1} — in a bounded ring
  // beside the profiler's, the raw material critpath.py merges across
  // ranks into the op's causal graph. Opt-in (TPUCOLL_SPANS=1; off
  // costs one relaxed load per op + one thread-local read per phase).
  span::Recorder& spans() { return spanrec_; }

  // Structured JSON snapshot of the registry; `drain` resets counters.
  std::string metricsJson(bool drain);

  // ---- in-band fleet observability plane (common/fleetobs.h) ----
  // Start the hierarchical telemetry fold for this rank's topology role
  // (member -> host leader -> rank 0). Requires a connected context;
  // no-op under TPUCOLL_FLEETOBS=0 or when already running.
  void fleetObsStart();
  // Stop and join the aggregation thread; close()/destruction call this
  // before the transport quiesces. Safe when never started.
  void fleetObsStop();
  bool fleetObsRunning() const;
  // JSON object merged into this rank's report as "aux" (e.g. the
  // elastic agent's lease status fed from Python). Throws EnforceError
  // when the plane was never started or the document is malformed.
  void fleetObsSetAux(const std::string& auxJson);
  // Rank 0: latest merged fleet document (telemetry /fleet route).
  // Other ranks / plane off: a valid-JSON stub saying so.
  std::string fleetJson();

  // JSON snapshot of the profiler's per-op phase-breakdown ring
  // (non-draining, like the flight recorder).
  std::string profileJson() { return profiler_.toJson(); }

  // JSON snapshot of the causal span ring (non-draining).
  std::string spansJson() { return spanrec_.toJson(); }

  // ---- collective autotuning plane (tuning/tuning_table.h) ----
  // Installed measured tuning table consulted by every kAuto dispatch;
  // null (the default) falls back to the historical compile-time
  // thresholds. MUST be byte-identical across ranks (see tuning.h
  // determinism contract) — install via tuning::tune() or from one
  // shared serialized table, never from per-rank measurements.
  // Reads take a mutex, not an atomic: dispatch happens once per
  // collective call (a multi-microsecond operation), not per segment.
  void setTuningTable(std::shared_ptr<const tuning::TuningTable> table);
  std::shared_ptr<const tuning::TuningTable> tuningTable() const;

  // ---- collective schedule plane (schedule/ir.h) ----
  // Install a schedule table: every schedule matching this context's
  // world size is statically VERIFIED (schedule/verifier.h — installing
  // an incorrect schedule throws, nothing is swapped) and resolved for
  // this rank; elected cells then take precedence over every other
  // kAuto dispatch tier. Null clears. Same all-ranks-identical contract
  // as the tuning table, and the same invalidation: cached plans embed
  // the resolved dispatch, so install/clear drops every plan.
  void setScheduleTable(std::shared_ptr<const schedule::ScheduleTable> table);
  // The installed (verified + resolved) plane; null when none.
  std::shared_ptr<const schedule::InstalledSchedules> schedules() const;

  // Monotonic generation counter namespacing each tune() election's
  // store keys. All ranks call tune() the same number of times (it is a
  // collective), so the generation agrees without store traffic.
  uint64_t nextTuneGeneration() {
    // Relaxed: generation-id allocator — uniqueness only.
    return tuneGen_.fetch_add(1, std::memory_order_relaxed) + 1;
  }

  // Rendezvous store this context bootstrapped over; null for forked
  // contexts (they exchange through the parent instead).
  Store* store() const { return store_.get(); }

  void close();

 private:
  // The elastic agent builds epoch-successor contexts from scratch
  // (joiners have no prior Context to call rebuild() on) and needs the
  // same pre-connect hooks rebuild() uses (hostId_, applyGroupTag).
  friend class elastic::ElasticAgent;
  friend std::unique_ptr<Context> elastic::buildEpochContext(
      std::shared_ptr<Store>, std::shared_ptr<transport::Device>, int, int,
      uint64_t, const std::string&,
      std::shared_ptr<const tuning::TuningTable>, std::chrono::milliseconds);

  // Exchange host fingerprints through the store and install the
  // resulting Topology + shm-reachability mask on the transport (must
  // run after tctx_ exists, before it connects).
  void discoverTopology();
  // Install `topo` and hand the co-host mask to tctx_ (when present).
  void installTopology(std::shared_ptr<const Topology> topo);
  // Stamp this context's group identity across the post-mortem planes:
  // fault domain (deterministic hash of the tag), flight-recorder dump
  // tag, metrics "group" field. Called before the mesh exists.
  void applyGroupTag(const std::string& tag);
  // Per-(user tag) split generation: same-tag splits are issued in the
  // same order on every rank (split is a collective), so the counter
  // agrees without store traffic; distinct tags stay independent so
  // CONCURRENT splits (which must use distinct tags) cannot race the
  // counter into rank-divergent generations.
  uint64_t nextSplitGeneration(uint32_t tag);

  // TPUCOLL_TUNING_FILE hook: load + install a serialized table at
  // connect/fork (before the transport mesh is created, so its
  // transport hints configure THIS mesh), letting a deployment pin its
  // measured table without touching application code. Malformed files
  // throw (never silently run untuned against an operator's explicit
  // instruction).
  void maybeLoadTuningFile();
  // TPUCOLL_SCHEDULE_FILE hook: load + verify + install a serialized
  // schedule table at connect/fork. Malformed or unverifiable files
  // throw loudly (never silently drop an operator's elected schedules).
  void maybeLoadScheduleFile();
  // Hand an installed table's tuned channel/stripe knobs to tctx_
  // before it connects (env still wins; see transport::Context::
  // setChannelConfig).
  void applyTransportHints();

  const int rank_;
  const int size_;
  std::chrono::milliseconds timeout_{kDefaultTimeout};
  int faultDomain_{0};
  std::string hostId_;
  std::string groupTag_;
  std::atomic<uint32_t> slotCounter_{0};
  std::atomic<uint64_t> tuneGen_{0};
  mutable std::mutex tuningMu_;
  std::shared_ptr<const tuning::TuningTable> tuningTable_;
  mutable std::mutex schedMu_;
  std::shared_ptr<const schedule::InstalledSchedules> schedules_;
  mutable std::mutex topoMu_;
  std::shared_ptr<const Topology> topology_;
  std::mutex splitGenMu_;
  std::map<uint32_t, uint64_t> splitGens_;
  // Hierarchical sub-communicators (hierGroups); created single-flight
  // WITHOUT holding hierMu_ across the (blocking) split bootstrap —
  // hierBuilding_ + hierCv_ serialize builders, hierClosed_ records a
  // close() that raced the build. Torn down by close()/~Context.
  std::mutex hierMu_;
  std::condition_variable hierCv_;
  bool hierInit_{false};
  bool hierBuilding_{false};
  bool hierClosed_{false};
  std::unique_ptr<Context> hierLocal_;
  std::unique_ptr<Context> hierLeaders_;
  std::shared_ptr<Store> store_;
  std::shared_ptr<transport::Device> device_;
  std::unique_ptr<transport::Context> tctx_;
  std::unique_ptr<plan::PlanCache> planCache_;
  // Guarded by fleetObsMu_; stopped/reset explicitly before tctx_ dies
  // (its wire buffers unregister against the live transport).
  mutable std::mutex fleetObsMu_;
  std::unique_ptr<fleetobs::FleetObs> fleetObs_;

  std::mutex scratchMu_;
  std::vector<std::vector<char>> scratchPool_;
  Tracer tracer_;
  Metrics metrics_;
  // After metrics_: the profiler flushes phase histograms into the
  // registry, so it must be constructed after and destroyed before it
  // (the span recorder only reads the registry's group tag, but keeps
  // the same ordering discipline).
  profile::Profiler profiler_;
  span::Recorder spanrec_;
  FlightRecorder flightrec_;
};

}  // namespace tpucoll
