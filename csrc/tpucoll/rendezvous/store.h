// tpucoll rendezvous: key/value store interface used to bootstrap process
// groups.
//
// Matches the reference contract (gloo/rendezvous/store.h:25-74 and
// gloo/common/store.h:20-53): set/get with blocking waits and timeouts, an
// existence check, plus the "v2" batched operations (multi_get/multi_set,
// atomic add) that cut bootstrap round trips from O(n^2) to O(n) store calls.
#pragma once

#include <chrono>
#include <cstdint>
#include <memory>
#include <string>
#include <vector>

#include "tpucoll/common/logging.h"

namespace tpucoll {

class Store {
 public:
  using Buf = std::vector<uint8_t>;
  static constexpr std::chrono::milliseconds kDefaultTimeout =
      std::chrono::milliseconds(30000);

  virtual ~Store() = default;

  virtual void set(const std::string& key, const Buf& value) = 0;

  // Blocks until `key` exists, then returns its value. Throws
  // TimeoutException if the deadline passes first.
  virtual Buf get(const std::string& key,
                  std::chrono::milliseconds timeout = kDefaultTimeout) = 0;

  // Non-blocking: true iff every key currently exists.
  virtual bool check(const std::vector<std::string>& keys) = 0;

  // Blocks until all keys exist.
  virtual void wait(const std::vector<std::string>& keys,
                    std::chrono::milliseconds timeout = kDefaultTimeout);

  // Atomically add `delta` to an integer-valued key (creating it at 0) and
  // return the new value. Used for rank counting and store-side barriers.
  virtual int64_t add(const std::string& key, int64_t delta) = 0;

  // Batched variants; the base implementations loop, subclasses with a
  // batched wire protocol (TCPStore) override them.
  virtual std::vector<Buf> multiGet(
      const std::vector<std::string>& keys,
      std::chrono::milliseconds timeout = kDefaultTimeout);
  virtual void multiSet(const std::vector<std::string>& keys,
                        const std::vector<Buf>& values);

  // Remove `key`; true when it existed. A waiter blocked on a deleted
  // key simply keeps waiting — deletion is for namespace hygiene (lease
  // reaping, retired rebuild/epoch namespaces), not signalling.
  virtual bool deleteKey(const std::string& key) = 0;

  // Keys currently present that start with `prefix` (relative to this
  // store's namespace), in unspecified order. Snapshot semantics only:
  // keys created or deleted concurrently may or may not appear.
  virtual std::vector<std::string> listKeys(const std::string& prefix) = 0;
};

// Decorator that namespaces every key, so independent contexts can share one
// physical store (reference: gloo/rendezvous/prefix_store.cc:13-40).
class PrefixStore : public Store {
 public:
  PrefixStore(std::shared_ptr<Store> base, std::string prefix);

  void set(const std::string& key, const Buf& value) override;
  Buf get(const std::string& key, std::chrono::milliseconds timeout) override;
  bool check(const std::vector<std::string>& keys) override;
  int64_t add(const std::string& key, int64_t delta) override;
  std::vector<Buf> multiGet(const std::vector<std::string>& keys,
                            std::chrono::milliseconds timeout) override;
  void multiSet(const std::vector<std::string>& keys,
                const std::vector<Buf>& values) override;
  bool deleteKey(const std::string& key) override;
  // Qualifies the prefix, then strips this store's own namespace from
  // the results, so listing through a PrefixStore stack yields keys
  // usable with the same stack's get/delete.
  std::vector<std::string> listKeys(const std::string& prefix) override;

 private:
  std::string qualify(const std::string& key) const;
  std::shared_ptr<Store> base_;
  std::string prefix_;
};

}  // namespace tpucoll
