#include "tpucoll/rendezvous/store.h"

#include <memory>
#include <thread>

namespace tpucoll {

void Store::wait(const std::vector<std::string>& keys,
                 std::chrono::milliseconds timeout) {
  const auto deadline = std::chrono::steady_clock::now() + timeout;
  while (!check(keys)) {
    if (std::chrono::steady_clock::now() >= deadline) {
      TC_THROW(TimeoutException, "store wait timed out after ",
               timeout.count(), "ms waiting for ", keys.size(), " key(s)");
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(2));
  }
}

std::vector<Store::Buf> Store::multiGet(const std::vector<std::string>& keys,
                                        std::chrono::milliseconds timeout) {
  const auto deadline = std::chrono::steady_clock::now() + timeout;
  std::vector<Buf> out;
  out.reserve(keys.size());
  for (const auto& key : keys) {
    auto now = std::chrono::steady_clock::now();
    auto remaining = std::chrono::duration_cast<std::chrono::milliseconds>(
        deadline - now);
    if (remaining.count() <= 0) {
      TC_THROW(TimeoutException, "store multiGet timed out");
    }
    out.push_back(get(key, remaining));
  }
  return out;
}

void Store::multiSet(const std::vector<std::string>& keys,
                     const std::vector<Buf>& values) {
  TC_ENFORCE_EQ(keys.size(), values.size());
  for (size_t i = 0; i < keys.size(); i++) {
    set(keys[i], values[i]);
  }
}

PrefixStore::PrefixStore(std::shared_ptr<Store> base, std::string prefix)
    : base_(std::move(base)), prefix_(std::move(prefix)) {}

std::string PrefixStore::qualify(const std::string& key) const {
  return prefix_ + "/" + key;
}

void PrefixStore::set(const std::string& key, const Buf& value) {
  base_->set(qualify(key), value);
}

Store::Buf PrefixStore::get(const std::string& key,
                            std::chrono::milliseconds timeout) {
  return base_->get(qualify(key), timeout);
}

bool PrefixStore::check(const std::vector<std::string>& keys) {
  std::vector<std::string> qualified;
  qualified.reserve(keys.size());
  for (const auto& key : keys) {
    qualified.push_back(qualify(key));
  }
  return base_->check(qualified);
}

int64_t PrefixStore::add(const std::string& key, int64_t delta) {
  return base_->add(qualify(key), delta);
}

std::vector<Store::Buf> PrefixStore::multiGet(
    const std::vector<std::string>& keys, std::chrono::milliseconds timeout) {
  std::vector<std::string> qualified;
  qualified.reserve(keys.size());
  for (const auto& key : keys) {
    qualified.push_back(qualify(key));
  }
  return base_->multiGet(qualified, timeout);
}

void PrefixStore::multiSet(const std::vector<std::string>& keys,
                           const std::vector<Buf>& values) {
  std::vector<std::string> qualified;
  qualified.reserve(keys.size());
  for (const auto& key : keys) {
    qualified.push_back(qualify(key));
  }
  base_->multiSet(qualified, values);
}

bool PrefixStore::deleteKey(const std::string& key) {
  return base_->deleteKey(qualify(key));
}

std::vector<std::string> PrefixStore::listKeys(const std::string& prefix) {
  const std::string scope = prefix_ + "/";
  std::vector<std::string> out;
  for (auto& key : base_->listKeys(qualify(prefix))) {
    // base_->listKeys only returns keys under qualify(prefix), which
    // itself starts with scope; the strip can therefore never miss.
    TC_ENFORCE_EQ(key.compare(0, scope.size(), scope), 0,
                  "PrefixStore::listKeys: base returned unscoped key '",
                  key, "'");
    out.push_back(key.substr(scope.size()));
  }
  return out;
}

}  // namespace tpucoll
