// Store over a shared filesystem: one file per key, written atomically via
// tmp+rename, waits by polling. Works across processes and across hosts that
// share a filesystem (reference: gloo/rendezvous/file_store.cc:31-90).
//
// Layout: <path>/tc_<fnv64(key)>. Each file embeds the full key so a hash
// collision is detected rather than silently cross-matched. Atomic add() is
// serialized with flock on a per-key lock file.
#pragma once

#include <string>

#include "tpucoll/rendezvous/store.h"

namespace tpucoll {

class FileStore : public Store {
 public:
  explicit FileStore(std::string path);

  void set(const std::string& key, const Buf& value) override;
  Buf get(const std::string& key, std::chrono::milliseconds timeout) override;
  bool check(const std::vector<std::string>& keys) override;
  int64_t add(const std::string& key, int64_t delta) override;
  bool deleteKey(const std::string& key) override;
  // Scans the directory and reads each file's embedded key (the hashed
  // filenames carry no prefix structure) — O(keys), for hygiene paths
  // (lease reaping, retired namespaces), not hot paths.
  std::vector<std::string> listKeys(const std::string& prefix) override;

 private:
  std::string fileFor(const std::string& key) const;
  // Returns false if the key file does not exist yet.
  bool tryRead(const std::string& key, Buf* out) const;
  void writeAtomic(const std::string& key, const Buf& value);

  std::string path_;
};

}  // namespace tpucoll
