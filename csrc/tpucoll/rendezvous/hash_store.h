// In-process store backed by a mutex-protected map; the default rendezvous
// for multi-rank-in-one-process tests (reference: gloo/rendezvous/
// hash_store.cc:14-52). Waits are condition-variable based, not polling.
#pragma once

#include <condition_variable>
#include <mutex>
#include <unordered_map>

#include "tpucoll/rendezvous/store.h"

namespace tpucoll {

class HashStore : public Store {
 public:
  void set(const std::string& key, const Buf& value) override;
  Buf get(const std::string& key, std::chrono::milliseconds timeout) override;
  bool check(const std::vector<std::string>& keys) override;
  int64_t add(const std::string& key, int64_t delta) override;
  bool deleteKey(const std::string& key) override;
  std::vector<std::string> listKeys(const std::string& prefix) override;

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  std::unordered_map<std::string, Buf> map_;
};

}  // namespace tpucoll
