// TCPStore: a self-contained key/value rendezvous service.
//
// Replaces the reference's RedisStore (gloo/rendezvous/redis_store.cc) with
// a dependency-free server any rank (conventionally rank 0) can host —
// the pattern modern frameworks bootstrap from. Implements the full Store
// contract including blocking waits (server-side, no client polling),
// atomic counters, and batched multiGet (the store-v2 batching the
// reference gates behind GLOO_ENABLE_STORE_V2_API).
//
// Wire protocol (all integers little-endian):
//   request:  [u8 op][u32 nkeys] then per key [u32 klen][key bytes],
//             then op-specific payload
//   response: [u8 status][u32 nvals] then per val [u64 vlen][bytes]
// Ops: kSet(1, 1 key + 1 val), kTryGet(2), kWaitGet(3, payload u64
// timeout_ms), kAdd(4, payload i64 delta -> returns 8-byte value),
// kCheck(5, n keys -> status 0 iff all exist), kMultiGet(6, n keys with
// u64 timeout_ms payload), kDelete(7, 1 key -> 1 val: 1 byte 0/1
// existed), kList(8, 1 key = prefix -> n vals, one key string each).
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "tpucoll/rendezvous/store.h"

namespace tpucoll {

class TcpStoreServer {
 public:
  // Binds host:port (port 0 = ephemeral; read back via port()).
  explicit TcpStoreServer(const std::string& host, uint16_t port = 0);
  ~TcpStoreServer();

  uint16_t port() const { return port_; }

 private:
  void acceptLoop();
  void serveClient(int fd);

  int listenFd_{-1};
  uint16_t port_{0};
  std::atomic<bool> stop_{false};
  std::thread acceptThread_;
  std::mutex threadsMu_;
  std::vector<std::thread> clientThreads_;
  std::vector<int> clientFds_;  // guarded by threadsMu_

  std::mutex mu_;
  std::condition_variable cv_;
  // Ordered so kList serves a prefix as a lower_bound range scan
  // (O(log n + matches)) instead of walking every key under the lock —
  // the elastic monitor and the boot plane list on their poll cadence,
  // and a large-N namespace made the full scan the server's hot loop.
  std::map<std::string, Store::Buf> map_;
};

class TcpStore : public Store {
 public:
  TcpStore(const std::string& host, uint16_t port);
  ~TcpStore() override;

  void set(const std::string& key, const Buf& value) override;
  Buf get(const std::string& key, std::chrono::milliseconds timeout) override;
  bool check(const std::vector<std::string>& keys) override;
  int64_t add(const std::string& key, int64_t delta) override;
  std::vector<Buf> multiGet(const std::vector<std::string>& keys,
                            std::chrono::milliseconds timeout) override;
  bool deleteKey(const std::string& key) override;
  std::vector<std::string> listKeys(const std::string& prefix) override;

 private:
  // One request/response round trip (client socket is serialized).
  std::pair<uint8_t, std::vector<Buf>> roundTrip(
      uint8_t op, const std::vector<std::string>& keys,
      const std::vector<Buf>& payload);

  std::mutex mu_;
  int fd_{-1};
};

}  // namespace tpucoll
