#include "tpucoll/rendezvous/hash_store.h"

#include <cstring>

namespace tpucoll {

void HashStore::set(const std::string& key, const Buf& value) {
  {
    std::lock_guard<std::mutex> guard(mu_);
    map_[key] = value;
  }
  cv_.notify_all();
}

Store::Buf HashStore::get(const std::string& key,
                          std::chrono::milliseconds timeout) {
  std::unique_lock<std::mutex> lock(mu_);
  auto pred = [&] { return map_.find(key) != map_.end(); };
  if (!cv_.wait_for(lock, timeout, pred)) {
    TC_THROW(TimeoutException, "HashStore::get timed out on key '", key, "'");
  }
  return map_[key];
}

bool HashStore::check(const std::vector<std::string>& keys) {
  std::lock_guard<std::mutex> guard(mu_);
  for (const auto& key : keys) {
    if (map_.find(key) == map_.end()) {
      return false;
    }
  }
  return true;
}

bool HashStore::deleteKey(const std::string& key) {
  std::lock_guard<std::mutex> guard(mu_);
  return map_.erase(key) > 0;
}

std::vector<std::string> HashStore::listKeys(const std::string& prefix) {
  std::lock_guard<std::mutex> guard(mu_);
  std::vector<std::string> out;
  for (const auto& kv : map_) {
    if (kv.first.compare(0, prefix.size(), prefix) == 0) {
      out.push_back(kv.first);
    }
  }
  return out;
}

int64_t HashStore::add(const std::string& key, int64_t delta) {
  int64_t result;
  {
    std::lock_guard<std::mutex> guard(mu_);
    int64_t current = 0;
    auto it = map_.find(key);
    if (it != map_.end()) {
      TC_ENFORCE_EQ(it->second.size(), sizeof(int64_t),
                    "add() on non-counter key '", key, "'");
      std::memcpy(&current, it->second.data(), sizeof(int64_t));
    }
    result = current + delta;
    Buf buf(sizeof(int64_t));
    std::memcpy(buf.data(), &result, sizeof(int64_t));
    map_[key] = std::move(buf);
  }
  cv_.notify_all();
  return result;
}

}  // namespace tpucoll
