#include "tpucoll/rendezvous/file_store.h"

#include <dirent.h>
#include <fcntl.h>
#include <sys/file.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <cstdio>
#include <cstring>
#include <thread>

namespace tpucoll {

namespace {

uint64_t fnv64(const std::string& s) {
  uint64_t h = 0xcbf29ce484222325ull;
  for (unsigned char c : s) {
    h ^= c;
    h *= 0x100000001b3ull;
  }
  return h;
}

std::string readAll(int fd) {
  std::string out;
  char buf[4096];
  ssize_t n;
  while ((n = read(fd, buf, sizeof(buf))) > 0) {
    out.append(buf, static_cast<size_t>(n));
  }
  TC_ENFORCE_GE(n, 0, "read failed: ", strerror(errno));
  return out;
}

// Key-in-filename encoding ("tk_" scheme): [A-Za-z0-9_-] pass through,
// everything else (including '.' and '%') percent-escapes, so a listing
// recovers every key from readdir alone — no per-file open — and the
// ".tmp." / ".lock" suffixes writeAtomic/add append can never collide
// with an encoded key (no encoded name contains '.').
bool safeNameChar(unsigned char c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9') || c == '_' || c == '-';
}

std::string escapeKey(const std::string& key) {
  static const char* hex = "0123456789ABCDEF";
  std::string out;
  out.reserve(key.size());
  for (unsigned char c : key) {
    if (safeNameChar(c)) {
      out.push_back(static_cast<char>(c));
    } else {
      out.push_back('%');
      out.push_back(hex[c >> 4]);
      out.push_back(hex[c & 0xF]);
    }
  }
  return out;
}

int hexVal(char c) {
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'A' && c <= 'F') return c - 'A' + 10;
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  return -1;
}

// Inverse of escapeKey; false on a malformed escape (foreign file).
bool unescapeKey(const std::string& name, std::string* key) {
  key->clear();
  key->reserve(name.size());
  for (size_t i = 0; i < name.size(); i++) {
    if (name[i] != '%') {
      key->push_back(name[i]);
      continue;
    }
    if (i + 2 >= name.size()) {
      return false;
    }
    const int hi = hexVal(name[i + 1]);
    const int lo = hexVal(name[i + 2]);
    if (hi < 0 || lo < 0) {
      return false;
    }
    key->push_back(static_cast<char>((hi << 4) | lo));
    i += 2;
  }
  return true;
}

// Escaped names longer than this fall back to the legacy fnv64-hashed
// scheme ("tc_"), keeping well under the 255-byte filename limit.
constexpr size_t kMaxEscapedName = 200;

}  // namespace

FileStore::FileStore(std::string path) : path_(std::move(path)) {
  // Best-effort create; races with sibling ranks are fine.
  mkdir(path_.c_str(), 0777);
  struct stat st;
  TC_ENFORCE(stat(path_.c_str(), &st) == 0 && S_ISDIR(st.st_mode),
             "FileStore path is not a directory: ", path_);
}

std::string FileStore::fileFor(const std::string& key) const {
  // Key-in-filename ("tk_") so listKeys is a pure readdir + name
  // filter; very long keys keep the legacy hashed ("tc_") scheme, whose
  // listing path must open the file and read the [keyLen][key] header.
  std::string esc = escapeKey(key);
  if (esc.size() <= kMaxEscapedName) {
    return path_ + "/tk_" + esc;
  }
  char name[32];
  snprintf(name, sizeof(name), "tc_%016llx",
           static_cast<unsigned long long>(fnv64(key)));
  return path_ + "/" + name;
}

void FileStore::writeAtomic(const std::string& key, const Buf& value) {
  const std::string target = fileFor(key);
  const std::string tmp =
      target + ".tmp." + std::to_string(getpid()) + "." +
      std::to_string(reinterpret_cast<uintptr_t>(&value) & 0xffff);
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0666);
  TC_ENFORCE_GE(fd, 0, "open failed for ", tmp, ": ", strerror(errno));
  uint32_t keyLen = static_cast<uint32_t>(key.size());
  bool ok = write(fd, &keyLen, sizeof(keyLen)) == sizeof(keyLen) &&
            write(fd, key.data(), key.size()) ==
                static_cast<ssize_t>(key.size()) &&
            (value.empty() ||
             write(fd, value.data(), value.size()) ==
                 static_cast<ssize_t>(value.size()));
  close(fd);
  TC_ENFORCE(ok, "short write to ", tmp);
  TC_ENFORCE(rename(tmp.c_str(), target.c_str()) == 0, "rename failed: ",
             strerror(errno));
}

bool FileStore::tryRead(const std::string& key, Buf* out) const {
  int fd = open(fileFor(key).c_str(), O_RDONLY);
  if (fd < 0) {
    TC_ENFORCE_EQ(errno, ENOENT, "open failed: ", strerror(errno));
    return false;
  }
  std::string raw = readAll(fd);
  close(fd);
  TC_ENFORCE_GE(raw.size(), sizeof(uint32_t), "corrupt store file for ", key);
  uint32_t keyLen;
  std::memcpy(&keyLen, raw.data(), sizeof(keyLen));
  TC_ENFORCE_GE(raw.size(), sizeof(uint32_t) + keyLen, "corrupt store file");
  std::string storedKey = raw.substr(sizeof(uint32_t), keyLen);
  TC_ENFORCE_EQ(storedKey, key, "FileStore key hash collision");
  if (out != nullptr) {
    const char* data = raw.data() + sizeof(uint32_t) + keyLen;
    out->assign(data, data + raw.size() - sizeof(uint32_t) - keyLen);
  }
  return true;
}

void FileStore::set(const std::string& key, const Buf& value) {
  writeAtomic(key, value);
}

Store::Buf FileStore::get(const std::string& key,
                          std::chrono::milliseconds timeout) {
  const auto deadline = std::chrono::steady_clock::now() + timeout;
  Buf out;
  while (!tryRead(key, &out)) {
    if (std::chrono::steady_clock::now() >= deadline) {
      TC_THROW(TimeoutException, "FileStore::get timed out on key '", key,
               "'");
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  return out;
}

bool FileStore::check(const std::vector<std::string>& keys) {
  for (const auto& key : keys) {
    if (!tryRead(key, nullptr)) {
      return false;
    }
  }
  return true;
}

bool FileStore::deleteKey(const std::string& key) {
  // Verify the stored key before unlinking: under an fnv64 collision the
  // file belongs to a DIFFERENT key and must survive.
  if (!tryRead(key, nullptr)) {
    return false;
  }
  const std::string target = fileFor(key);
  unlink((target + ".lock").c_str());  // add()'s lock file, if any
  if (unlink(target.c_str()) != 0) {
    TC_ENFORCE_EQ(errno, ENOENT, "unlink failed for ", target, ": ",
                  strerror(errno));
    return false;  // lost a delete race; the key is gone either way
  }
  return true;
}

std::vector<std::string> FileStore::listKeys(const std::string& prefix) {
  std::vector<std::string> out;
  DIR* dir = opendir(path_.c_str());
  TC_ENFORCE(dir != nullptr, "opendir failed for ", path_, ": ",
             strerror(errno));
  struct dirent* ent;
  while ((ent = readdir(dir)) != nullptr) {
    const std::string name(ent->d_name);
    if (name.find(".tmp.") != std::string::npos ||
        (name.size() >= 5 &&
         name.compare(name.size() - 5, 5, ".lock") == 0)) {
      continue;
    }
    // Fast path: "tk_" names carry the escaped key — the listing costs
    // one readdir total, zero opens (the elastic monitor and the boot
    // plane list on their poll cadence; under large N the per-file open
    // of the hashed scheme dominated the whole poll).
    if (name.compare(0, 3, "tk_") == 0) {
      std::string key;
      if (unescapeKey(name.substr(3), &key) &&
          key.compare(0, prefix.size(), prefix) == 0) {
        out.push_back(std::move(key));
      }
      continue;
    }
    if (name.compare(0, 3, "tc_") != 0) {
      continue;
    }
    int fd = open((path_ + "/" + name).c_str(), O_RDONLY);
    if (fd < 0) {
      continue;  // deleted between readdir and open
    }
    // Read ONLY the [keyLen][key] header — a listing must not re-read
    // every value body (epoch namespaces hold multi-KB mesh blobs, and
    // the elastic monitor lists queues on its poll cadence).
    uint32_t keyLen = 0;
    std::string key;
    bool ok = read(fd, &keyLen, sizeof(keyLen)) ==
                  static_cast<ssize_t>(sizeof(keyLen)) &&
              keyLen <= (1u << 20);
    if (ok) {
      key.resize(keyLen);
      ok = keyLen == 0 ||
           read(fd, &key[0], keyLen) == static_cast<ssize_t>(keyLen);
    }
    close(fd);
    if (!ok) {
      continue;  // torn writer (set() renames atomically; be tolerant)
    }
    if (key.compare(0, prefix.size(), prefix) == 0) {
      out.push_back(std::move(key));
    }
  }
  closedir(dir);
  return out;
}

int64_t FileStore::add(const std::string& key, int64_t delta) {
  const std::string lockPath = fileFor(key) + ".lock";
  int lockFd = open(lockPath.c_str(), O_WRONLY | O_CREAT, 0666);
  TC_ENFORCE_GE(lockFd, 0, "open lock failed: ", strerror(errno));
  TC_ENFORCE(flock(lockFd, LOCK_EX) == 0, "flock failed: ", strerror(errno));
  int64_t result = delta;
  Buf current;
  if (tryRead(key, &current)) {
    TC_ENFORCE_EQ(current.size(), sizeof(int64_t), "add() on non-counter key");
    int64_t value;
    std::memcpy(&value, current.data(), sizeof(value));
    result = value + delta;
  }
  Buf buf(sizeof(int64_t));
  std::memcpy(buf.data(), &result, sizeof(result));
  writeAtomic(key, buf);
  flock(lockFd, LOCK_UN);
  close(lockFd);
  return result;
}

}  // namespace tpucoll
