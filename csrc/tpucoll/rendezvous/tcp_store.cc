#include "tpucoll/rendezvous/tcp_store.h"

#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>

#include "tpucoll/transport/address.h"
#include "tpucoll/transport/socket.h"

namespace tpucoll {

namespace {

enum Op : uint8_t {
  kSet = 1,
  kTryGet = 2,
  kWaitGet = 3,
  kAdd = 4,
  kCheck = 5,
  kMultiGet = 6,
  kDelete = 7,
  kList = 8,
};

enum Status : uint8_t {
  kOk = 0,
  kMissing = 1,
  kTimeout = 2,
  kBadRequest = 3,
};

bool readFull(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  size_t got = 0;
  while (got < n) {
    ssize_t rv = read(fd, p + got, n - got);
    if (rv == 0) {
      return false;
    }
    if (rv < 0) {
      if (errno == EINTR) {
        continue;
      }
      return false;
    }
    got += static_cast<size_t>(rv);
  }
  return true;
}

bool writeFull(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  size_t sent = 0;
  while (sent < n) {
    // MSG_NOSIGNAL: a dead peer must surface as an error, not SIGPIPE.
    ssize_t rv = send(fd, p + sent, n - sent, MSG_NOSIGNAL);
    if (rv < 0) {
      if (errno == EINTR) {
        continue;
      }
      return false;
    }
    sent += static_cast<size_t>(rv);
  }
  return true;
}

template <typename T>
bool readValue(int fd, T* v) {
  return readFull(fd, v, sizeof(T));
}

bool readBlob(int fd, std::vector<uint8_t>* out, uint64_t maxLen = 1 << 30) {
  uint64_t len;
  if (!readValue(fd, &len) || len > maxLen) {
    return false;
  }
  out->resize(len);
  return len == 0 || readFull(fd, out->data(), len);
}

bool writeResponse(int fd, uint8_t status,
                   const std::vector<Store::Buf>& vals) {
  std::string out;
  out.push_back(static_cast<char>(status));
  uint32_t n = static_cast<uint32_t>(vals.size());
  out.append(reinterpret_cast<char*>(&n), 4);
  for (const auto& v : vals) {
    uint64_t len = v.size();
    out.append(reinterpret_cast<char*>(&len), 8);
    out.append(reinterpret_cast<const char*>(v.data()), v.size());
  }
  return writeFull(fd, out.data(), out.size());
}

}  // namespace

TcpStoreServer::TcpStoreServer(const std::string& host, uint16_t port) {
  auto addr = transport::resolve(host, port);
  listenFd_ = socket(addr.sa()->sa_family, SOCK_STREAM | SOCK_CLOEXEC, 0);
  TC_ENFORCE_GE(listenFd_, 0, "socket: ", strerror(errno));
  transport::setReuseAddr(listenFd_);
  TC_ENFORCE_EQ(bind(listenFd_, addr.sa(), addr.len), 0,
                "TcpStoreServer bind: ", strerror(errno));
  TC_ENFORCE_EQ(listen(listenFd_, 512), 0, "listen: ", strerror(errno));
  transport::SockAddr bound;
  bound.len = sizeof(bound.ss);
  getsockname(listenFd_, bound.sa(), &bound.len);
  if (bound.sa()->sa_family == AF_INET) {
    port_ = ntohs(reinterpret_cast<sockaddr_in*>(&bound.ss)->sin_port);
  } else {
    port_ = ntohs(reinterpret_cast<sockaddr_in6*>(&bound.ss)->sin6_port);
  }
  acceptThread_ = std::thread([this] { acceptLoop(); });
}

TcpStoreServer::~TcpStoreServer() {
  // Relaxed: pure exit flag — the dtor's thread join (not this
  // store) is the synchronization point for the loop's effects.
  stop_.store(true, std::memory_order_relaxed);
  // Unblock accept() and any server-side waits.
  ::shutdown(listenFd_, SHUT_RDWR);
  cv_.notify_all();
  acceptThread_.join();
  ::close(listenFd_);
  std::vector<std::thread> threads;
  {
    std::lock_guard<std::mutex> guard(threadsMu_);
    threads.swap(clientThreads_);
    // Client handler threads may be blocked in read() on connections their
    // clients still hold open; shut the sockets down so the joins return.
    for (int fd : clientFds_) {
      ::shutdown(fd, SHUT_RDWR);
    }
    clientFds_.clear();
  }
  for (auto& t : threads) {
    t.join();
  }
}

void TcpStoreServer::acceptLoop() {
  while (!stop_.load(std::memory_order_relaxed)) {
    int fd = accept4(listenFd_, nullptr, nullptr, SOCK_CLOEXEC);
    if (fd < 0) {
      if (errno == EINTR) {
        continue;
      }
      return;  // listener shut down
    }
    transport::setNoDelay(fd);
    std::lock_guard<std::mutex> guard(threadsMu_);
    clientFds_.push_back(fd);
    clientThreads_.emplace_back([this, fd] { serveClient(fd); });
  }
}

void TcpStoreServer::serveClient(int fd) {
  while (!stop_.load(std::memory_order_relaxed)) {
    uint8_t op;
    uint32_t nkeys;
    if (!readValue(fd, &op) || !readValue(fd, &nkeys) || nkeys > 65536) {
      break;
    }
    std::vector<std::string> keys(nkeys);
    bool ok = true;
    for (auto& key : keys) {
      uint32_t klen;
      if (!readValue(fd, &klen) || klen > (1u << 20)) {
        ok = false;
        break;
      }
      key.resize(klen);
      if (klen > 0 && !readFull(fd, key.data(), klen)) {
        ok = false;
        break;
      }
    }
    if (!ok) {
      break;
    }

    switch (op) {
      case kSet: {
        std::vector<uint8_t> val;
        if (nkeys != 1 || !readBlob(fd, &val)) {
          ok = false;
          break;
        }
        {
          std::lock_guard<std::mutex> guard(mu_);
          map_[keys[0]] = std::move(val);
        }
        cv_.notify_all();
        ok = writeResponse(fd, kOk, {});
        break;
      }
      case kTryGet: {
        if (nkeys != 1) {
          writeResponse(fd, kBadRequest, {});
          ok = false;
          break;
        }
        std::lock_guard<std::mutex> guard(mu_);
        auto it = map_.find(keys[0]);
        if (it == map_.end()) {
          ok = writeResponse(fd, kMissing, {});
        } else {
          ok = writeResponse(fd, kOk, {it->second});
        }
        break;
      }
      case kWaitGet:
      case kMultiGet: {
        uint64_t timeoutMs;
        // kWaitGet is single-key; kMultiGet accepts zero keys (a size-1
        // bootstrap legitimately asks for nothing).
        if ((op == kWaitGet && nkeys != 1) || !readValue(fd, &timeoutMs)) {
          ok = false;
          break;
        }
        auto deadline = std::chrono::steady_clock::now() +
                        std::chrono::milliseconds(timeoutMs);
        std::unique_lock<std::mutex> lock(mu_);
        bool all = cv_.wait_until(lock, deadline, [&] {
          if (stop_.load(std::memory_order_relaxed)) {
            return true;
          }
          for (const auto& key : keys) {
            if (map_.find(key) == map_.end()) {
              return false;
            }
          }
          return true;
        });
        if (!all || stop_.load(std::memory_order_relaxed)) {
          lock.unlock();
          ok = writeResponse(fd, kTimeout, {});
        } else {
          std::vector<Store::Buf> vals;
          vals.reserve(keys.size());
          for (const auto& key : keys) {
            vals.push_back(map_[key]);
          }
          lock.unlock();
          ok = writeResponse(fd, kOk, vals);
        }
        break;
      }
      case kAdd: {
        int64_t delta;
        if (nkeys != 1 || !readValue(fd, &delta)) {
          ok = false;
          break;
        }
        int64_t result;
        {
          std::lock_guard<std::mutex> guard(mu_);
          int64_t current = 0;
          auto it = map_.find(keys[0]);
          if (it != map_.end() && it->second.size() == sizeof(int64_t)) {
            std::memcpy(&current, it->second.data(), sizeof(current));
          }
          result = current + delta;
          Store::Buf buf(sizeof(result));
          std::memcpy(buf.data(), &result, sizeof(result));
          map_[keys[0]] = std::move(buf);
        }
        cv_.notify_all();
        Store::Buf out(sizeof(result));
        std::memcpy(out.data(), &result, sizeof(result));
        ok = writeResponse(fd, kOk, {out});
        break;
      }
      case kDelete: {
        if (nkeys != 1) {
          writeResponse(fd, kBadRequest, {});
          ok = false;
          break;
        }
        bool existed;
        {
          std::lock_guard<std::mutex> guard(mu_);
          existed = map_.erase(keys[0]) > 0;
        }
        ok = writeResponse(fd, kOk, {Store::Buf{existed ? uint8_t(1)
                                                        : uint8_t(0)}});
        break;
      }
      case kList: {
        if (nkeys != 1) {
          writeResponse(fd, kBadRequest, {});
          ok = false;
          break;
        }
        std::vector<Store::Buf> vals;
        {
          std::lock_guard<std::mutex> guard(mu_);
          const std::string& prefix = keys[0];
          // Ordered map: jump to the first candidate and stop at the
          // first key past the prefix range — never a full-namespace
          // walk under the serving lock.
          for (auto it = map_.lower_bound(prefix);
               it != map_.end() &&
               it->first.compare(0, prefix.size(), prefix) == 0;
               ++it) {
            vals.emplace_back(it->first.begin(), it->first.end());
          }
        }
        ok = writeResponse(fd, kOk, vals);
        break;
      }
      case kCheck: {
        bool all = true;
        {
          std::lock_guard<std::mutex> guard(mu_);
          for (const auto& key : keys) {
            if (map_.find(key) == map_.end()) {
              all = false;
              break;
            }
          }
        }
        ok = writeResponse(fd, all ? kOk : kMissing, {});
        break;
      }
      default:
        writeResponse(fd, kBadRequest, {});
        ok = false;
    }
    if (!ok) {
      break;
    }
  }
  // Drop our registration before closing: the destructor must never
  // shutdown() an fd number the kernel may have reused.
  {
    std::lock_guard<std::mutex> guard(threadsMu_);
    for (auto it = clientFds_.begin(); it != clientFds_.end(); ++it) {
      if (*it == fd) {
        clientFds_.erase(it);
        break;
      }
    }
  }
  ::close(fd);
}

// ---- client ----

TcpStore::TcpStore(const std::string& host, uint16_t port) {
  auto addr = transport::resolve(host, port);
  // Bounded retry: the server (typically rank 0) may come up after us.
  // Each attempt uses a FRESH socket — a socket whose connect failed is
  // in an unspecified state, and retrying connect(2) on it is exactly
  // what yields the sporadic ECONNABORTED ("software caused connection
  // abort") that used to kill a rank out of the bootstrap race.
  // ECONNABORTED/ECONNRESET are themselves transient during server
  // startup and retry like ECONNREFUSED.
  const auto deadline =
      std::chrono::steady_clock::now() + std::chrono::seconds(30);
  while (true) {
    fd_ = socket(addr.sa()->sa_family, SOCK_STREAM | SOCK_CLOEXEC, 0);
    TC_ENFORCE_GE(fd_, 0, "socket: ", strerror(errno));
    if (connect(fd_, addr.sa(), addr.len) == 0) {
      break;
    }
    const int err = errno;
    ::close(fd_);
    fd_ = -1;
    if (err != ECONNREFUSED && err != ECONNABORTED && err != ECONNRESET &&
        err != EINTR) {
      TC_THROW(IoException, "TcpStore connect to ", addr.str(), ": ",
               strerror(err));
    }
    if (std::chrono::steady_clock::now() >= deadline) {
      TC_THROW(TimeoutException, "TcpStore connect to ", addr.str(),
               " timed out");
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
  transport::setNoDelay(fd_);
}

TcpStore::~TcpStore() {
  if (fd_ >= 0) {
    ::close(fd_);
  }
}

std::pair<uint8_t, std::vector<Store::Buf>> TcpStore::roundTrip(
    uint8_t op, const std::vector<std::string>& keys,
    const std::vector<Buf>& payload) {
  std::lock_guard<std::mutex> guard(mu_);
  std::string req;
  req.push_back(static_cast<char>(op));
  uint32_t nkeys = static_cast<uint32_t>(keys.size());
  req.append(reinterpret_cast<char*>(&nkeys), 4);
  for (const auto& key : keys) {
    uint32_t klen = static_cast<uint32_t>(key.size());
    req.append(reinterpret_cast<char*>(&klen), 4);
    req.append(key);
  }
  for (const auto& p : payload) {
    req.append(reinterpret_cast<const char*>(p.data()), p.size());
  }
  TC_ENFORCE(writeFull(fd_, req.data(), req.size()),
             "TcpStore request failed: ", strerror(errno));
  uint8_t status;
  uint32_t nvals;
  if (!readValue(fd_, &status) || !readValue(fd_, &nvals)) {
    TC_THROW(IoException, "TcpStore connection lost");
  }
  std::vector<Buf> vals(nvals);
  for (auto& v : vals) {
    if (!readBlob(fd_, &v)) {
      TC_THROW(IoException, "TcpStore connection lost mid-response");
    }
  }
  return {status, std::move(vals)};
}

namespace {
Store::Buf packU64(uint64_t v) {
  Store::Buf buf(8);
  std::memcpy(buf.data(), &v, 8);
  return buf;
}
}  // namespace

void TcpStore::set(const std::string& key, const Buf& value) {
  Buf payload(8 + value.size());
  uint64_t len = value.size();
  std::memcpy(payload.data(), &len, 8);
  std::memcpy(payload.data() + 8, value.data(), value.size());
  auto [status, vals] = roundTrip(kSet, {key}, {payload});
  TC_ENFORCE_EQ(int(status), int(kOk), "TcpStore set failed");
}

Store::Buf TcpStore::get(const std::string& key,
                         std::chrono::milliseconds timeout) {
  auto [status, vals] =
      roundTrip(kWaitGet, {key}, {packU64(timeout.count())});
  if (status == kTimeout) {
    TC_THROW(TimeoutException, "TcpStore::get timed out on key '", key, "'");
  }
  TC_ENFORCE_EQ(int(status), int(kOk), "TcpStore get failed");
  TC_ENFORCE_EQ(vals.size(), size_t(1));
  return vals[0];
}

bool TcpStore::check(const std::vector<std::string>& keys) {
  auto [status, vals] = roundTrip(kCheck, keys, {});
  return status == kOk;
}

int64_t TcpStore::add(const std::string& key, int64_t delta) {
  Buf payload(8);
  std::memcpy(payload.data(), &delta, 8);
  auto [status, vals] = roundTrip(kAdd, {key}, {payload});
  TC_ENFORCE_EQ(int(status), int(kOk), "TcpStore add failed");
  TC_ENFORCE_EQ(vals.size(), size_t(1));
  int64_t result;
  std::memcpy(&result, vals[0].data(), 8);
  return result;
}

bool TcpStore::deleteKey(const std::string& key) {
  auto [status, vals] = roundTrip(kDelete, {key}, {});
  TC_ENFORCE_EQ(int(status), int(kOk), "TcpStore delete failed");
  TC_ENFORCE_EQ(vals.size(), size_t(1));
  return !vals[0].empty() && vals[0][0] != 0;
}

std::vector<std::string> TcpStore::listKeys(const std::string& prefix) {
  auto [status, vals] = roundTrip(kList, {prefix}, {});
  TC_ENFORCE_EQ(int(status), int(kOk), "TcpStore list failed");
  std::vector<std::string> out;
  out.reserve(vals.size());
  for (const auto& v : vals) {
    out.emplace_back(v.begin(), v.end());
  }
  return out;
}

std::vector<Store::Buf> TcpStore::multiGet(
    const std::vector<std::string>& keys,
    std::chrono::milliseconds timeout) {
  auto [status, vals] =
      roundTrip(kMultiGet, keys, {packU64(timeout.count())});
  if (status == kTimeout) {
    TC_THROW(TimeoutException, "TcpStore::multiGet timed out");
  }
  TC_ENFORCE_EQ(int(status), int(kOk), "TcpStore multiGet failed");
  return vals;
}

}  // namespace tpucoll
