#include "tpucoll/transport/device.h"

#include "tpucoll/common/logging.h"
#include "tpucoll/common/sysinfo.h"

namespace tpucoll {
namespace transport {

Device::Device(const DeviceAttr& attr)
    : loop_(makeLoop(attr.busyPoll, attr.engine)), authKey_(attr.authKey),
      encrypt_(attr.encrypt) {
  if (!attr.keyring.empty()) {
    TC_ENFORCE(authKey_.empty(),
               "auth_key and keyring are mutually exclusive tiers");
    keyring_ = Keyring::parse(attr.keyring);
  }
  TC_ENFORCE(!encrypt_ || !authKey_.empty() || keyring_.valid(),
             "encrypt=true requires an auth key or keyring (the AEAD "
             "keys are derived from the handshake)");
  std::string host = attr.hostname;
  if (!attr.iface.empty()) {
    host = addressForInterface(attr.iface);
    TC_ENFORCE(!host.empty(), "interface ", attr.iface,
               " has no usable address");
  }
  SockAddr bindAddr = resolve(host, attr.port);
  listener_ = std::make_unique<Listener>(loop_.get(), bindAddr, authKey_,
                                         keyring_, encrypt_);
}

std::string Device::str() const {
  std::string s = "tcp://" + listener_->address().str();
  const std::string iface = interfaceForAddress(listener_->address().sa());
  if (!iface.empty()) {
    s += " (" + iface;
    const int speed = interfaceSpeedMbps(iface);
    if (speed > 0) {
      s += ", " + std::to_string(speed) + " Mb/s";
    }
    const std::string pci = interfacePciBusId(iface);
    if (!pci.empty()) {
      s += ", pci " + pci;  // NUMA placement hint (ref device.h:42-47)
    }
    s += ")";
  }
  s += " [";
  s += loop_->engineName();
  s += "]";
  return s;
}

}  // namespace transport
}  // namespace tpucoll
