#include "tpucoll/transport/device.h"

namespace tpucoll {
namespace transport {

Device::Device(const DeviceAttr& attr) {
  SockAddr bindAddr = resolve(attr.hostname, attr.port);
  listener_ = std::make_unique<Listener>(&loop_, bindAddr);
}

std::string Device::str() const {
  return "tcp://" + listener_->address().str();
}

}  // namespace transport
}  // namespace tpucoll
