#include "tpucoll/transport/device.h"

#include "tpucoll/boot/lazy_id.h"
#include "tpucoll/common/env.h"
#include "tpucoll/common/logging.h"
#include "tpucoll/common/sysinfo.h"
#include "tpucoll/transport/context.h"

namespace tpucoll {
namespace transport {

namespace {

// Loop-pool size: attr wins, else TPUCOLL_LOOP_THREADS (strict parse,
// common/env.h), else 1 — the seed's single-thread data plane. Capped
// well below any sane host so a typo cannot spawn hundreds of threads.
constexpr long kMaxLoops = 64;

int resolveNumLoops(int attrLoops) {
  if (attrLoops > 0) {
    TC_ENFORCE(attrLoops <= kMaxLoops, "DeviceAttr.numLoops must be <= ",
               kMaxLoops, ", got ", attrLoops);
    return attrLoops;
  }
  return static_cast<int>(envCount("TPUCOLL_LOOP_THREADS", 1, 1, kMaxLoops));
}

}  // namespace

Device::Device(const DeviceAttr& attr)
    : authKey_(attr.authKey), encrypt_(attr.encrypt) {
  // Validate lazily-read global knobs before the loop threads exist:
  // loop threads log, and encrypting pairs consult the AVX-512 kill
  // switch from AEAD calls on the loop thread — a malformed value
  // throwing inside a function-local static init there would terminate
  // the process (or livelock the level-triggered loop) instead of
  // surfacing as a typed error from this (wrapped) ctor. Validating
  // here also makes TPUCOLL_NO_AVX512 uniformly strict: the lazy read
  // is short-circuited away on hosts without AVX-512.
  logThreshold();
  envFlag("TPUCOLL_NO_AVX512", false);
  const int numLoops = resolveNumLoops(attr.numLoops);
  loops_.reserve(numLoops);
  for (int i = 0; i < numLoops; i++) {
    loops_.push_back(makeLoop(attr.busyPoll, attr.engine));
  }
  if (!attr.keyring.empty()) {
    TC_ENFORCE(authKey_.empty(),
               "auth_key and keyring are mutually exclusive tiers");
    keyring_ = Keyring::parse(attr.keyring);
  }
  TC_ENFORCE(!encrypt_ || !authKey_.empty() || keyring_.valid(),
             "encrypt=true requires an auth key or keyring (the AEAD "
             "keys are derived from the handshake)");
  std::string host = attr.hostname;
  if (!attr.iface.empty()) {
    host = addressForInterface(attr.iface);
    TC_ENFORCE(!host.empty(), "interface ", attr.iface,
               " has no usable address");
  }
  SockAddr bindAddr = resolve(host, attr.port);
  // The listener stays on loop 0 regardless of pool size: accepts and
  // handshakes are rare, and a fixed home keeps routing simple.
  listener_ = std::make_unique<Listener>(loops_[0].get(), bindAddr, authKey_,
                                         keyring_, encrypt_);
}

void Device::registerLazyMesh(uint32_t meshId, Context* ctx) {
  {
    std::lock_guard<std::mutex> guard(lazyMu_);
    auto it = lazyMeshes_.find(meshId);
    TC_ENFORCE(it == lazyMeshes_.end() || it->second == ctx,
               "lazy mesh id collision: ", meshId);
    lazyMeshes_[meshId] = ctx;
  }
  listener_->setUnclaimedHook(
      [this](uint64_t pairId) { onUnclaimedLazy(pairId); });
  // An eager peer may have dialed in while this mesh was still parsing
  // rendezvous blobs; those connections parked unclaimed and must be
  // routed now that the mesh can accept them.
  listener_->replayUnclaimed();
}

void Device::unregisterLazyMesh(uint32_t meshId) {
  std::lock_guard<std::mutex> guard(lazyMu_);
  lazyMeshes_.erase(meshId);
}

void Device::onUnclaimedLazy(uint64_t pairId) {
  const boot::LazyIdParts parts = boot::parseLazyPairId(pairId);
  Context* ctx = nullptr;
  {
    std::lock_guard<std::mutex> guard(lazyMu_);
    auto it = lazyMeshes_.find(parts.meshId);
    if (it != lazyMeshes_.end()) {
      ctx = it->second;
    }
  }
  if (ctx == nullptr) {
    // No registered mesh (context already closed, or a stale dialer):
    // leave the connection parked; the listener reaps it at teardown.
    TC_WARN("unclaimed lazy connection for unknown mesh ", parts.meshId,
            " (initiator rank ", parts.initiator, ")");
    return;
  }
  ctx->acceptLazyInbound(pairId);
}

std::string Device::str() const {
  std::string s = "tcp://" + listener_->address().str();
  const std::string iface = interfaceForAddress(listener_->address().sa());
  if (!iface.empty()) {
    s += " (" + iface;
    const int speed = interfaceSpeedMbps(iface);
    if (speed > 0) {
      s += ", " + std::to_string(speed) + " Mb/s";
    }
    const std::string pci = interfacePciBusId(iface);
    if (!pci.empty()) {
      s += ", pci " + pci;  // NUMA placement hint (ref device.h:42-47)
    }
    s += ")";
  }
  s += " [";
  s += loops_[0]->engineName();
  if (loops_.size() > 1) {
    s += " x" + std::to_string(loops_.size());
  }
  s += "]";
  return s;
}

}  // namespace transport
}  // namespace tpucoll
