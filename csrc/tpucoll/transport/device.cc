#include "tpucoll/transport/device.h"

namespace tpucoll {
namespace transport {

Device::Device(const DeviceAttr& attr) : authKey_(attr.authKey) {
  SockAddr bindAddr = resolve(attr.hostname, attr.port);
  listener_ = std::make_unique<Listener>(&loop_, bindAddr, authKey_);
}

std::string Device::str() const {
  return "tcp://" + listener_->address().str();
}

}  // namespace transport
}  // namespace tpucoll
