// UnboundBuffer: a registered memory region from which tagged sends are
// issued and into which tagged receives land. Supports recv-from-any,
// per-operation timeouts, and abortable waits.
//
// Contract parity with the reference's transport::UnboundBuffer
// (gloo/transport/unbound_buffer.h:36-153): send/recv are async; waitSend/
// waitRecv are the only blocking points; waits return false when aborted;
// transport failures surface as IoException; destruction drains in-flight
// operations so the region can never be written after free.
#pragma once

#include <chrono>
#include <condition_variable>
#include <cstddef>
#include <deque>
#include <mutex>
#include <vector>

#include "tpucoll/common/logging.h"

namespace tpucoll {
namespace transport {

class Context;

// Accumulator signature for fused receive-reduce (layout-compatible with
// tpucoll::ReduceFn, math.h:18): fn(acc, in, n) combines n elements of
// `in` into `acc`.
using RecvReduceFn = void (*)(void* acc, const void* in, size_t n);

// Ceiling on the element size a recvReduce may use: the shm receive path
// keeps a carry buffer this large for ring spans that split an element.
// Sized for the largest q8 wire unit (4-byte scale + TPUCOLL_Q8_BLOCK
// int8 codes at its 2048 maximum, math.h) — the widest "element" any
// typed fused receive currently folds; plain dtype reductions stay <= 32.
// A static_assert in collectives_q8.cc ties this to kQ8MaxBlockElems so
// the two cannot drift apart silently.
constexpr size_t kMaxCombineElsize = 2052;

class UnboundBuffer {
 public:
  UnboundBuffer(Context* context, void* ptr, size_t size);
  ~UnboundBuffer();

  UnboundBuffer(const UnboundBuffer&) = delete;
  UnboundBuffer& operator=(const UnboundBuffer&) = delete;

  void* ptr() const { return ptr_; }
  size_t size() const { return size_; }
  // Owning transport context (observability hooks live there).
  Context* transportContext() const { return context_; }

  // Async send of [offset, offset+nbytes) to dstRank under `slot`.
  // nbytes == SIZE_MAX means "rest of the buffer".
  void send(int dstRank, uint64_t slot, size_t offset = 0,
            size_t nbytes = SIZE_MAX);

  // Async recv into [offset, offset+nbytes) from srcRank under `slot`.
  void recv(int srcRank, uint64_t slot, size_t offset = 0,
            size_t nbytes = SIZE_MAX);

  // Recv-from-any: first matching arrival from any rank in srcRanks wins.
  void recv(const std::vector<int>& srcRanks, uint64_t slot,
            size_t offset = 0, size_t nbytes = SIZE_MAX);

  // Fused receive-reduce: like recv, but the incoming payload is COMBINED
  // into [offset, offset+nbytes) with `fn(acc, in, n)` instead of
  // overwriting it. Where the transport stages payloads anyway (shm ring,
  // stash, self-send) the combine runs straight from the staging memory,
  // eliminating the copy-out pass a recv-into-scratch schedule pays; the
  // byte-stream TCP path stages internally so the accumulator is never
  // clobbered by partial reads. The reference has no equivalent — its
  // schedules always recv into scratch and reduce afterwards
  // (gloo/allreduce.cc:284-299); this is the single-core/bandwidth win of
  // owning the receive path. `fn` runs on the transport's loop thread (or
  // the poster's thread on stash/self-send hits), so it must be
  // thread-safe and must not block; nbytes must be a multiple of elsize
  // (elsize <= kMaxCombineElsize).
  void recvReduce(int srcRank, uint64_t slot, RecvReduceFn fn, size_t elsize,
                  size_t offset = 0, size_t nbytes = SIZE_MAX);

  // Typed variant: the wire carries `wireElsize`-byte elements while the
  // accumulator advances by `accElsize` per element — fn converts as it
  // folds (e.g. bf16 wire into a float32 accumulator, fn = decode+add;
  // fn may also ignore the accumulator's prior value to express a pure
  // decode-into-place). `offset`/`accElsize` address THIS buffer (the
  // accumulator); `wireNbytes` is the incoming message size and must
  // match the sender's. recvReduce == the wireElsize == accElsize case.
  void recvReduceTyped(int srcRank, uint64_t slot, RecvReduceFn fn,
                       size_t wireElsize, size_t accElsize, size_t offset,
                       size_t wireNbytes);

  // ---- one-sided put/get (reference: transport/unbound_buffer.h:128-153
  // + remote_key.h; DCN analog of the device plane's Pallas remote DMA) --

  // Export this buffer as a one-sided target. The serialized key is
  // exchangeable over any channel (typically allgathered); peers put/get
  // against it with no posted operation on this side. The registration
  // lives until this buffer is destroyed.
  std::string getRemoteKey();

  // One-sided write: local [offset, offset+nbytes) into the remote region
  // [roffset, ...). Completion via waitSend; the target posts nothing.
  // notify=true additionally completes a waitRecv on the exporting buffer
  // when the payload lands — the reference's BOUND-buffer contract
  // (one-sided write into registered memory + arrival notification,
  // gloo/transport/buffer.h:16-41).
  void put(const std::string& remoteKey, size_t offset, size_t roffset,
           size_t nbytes, bool notify = false);

  // One-sided read: remote region [roffset, roffset+nbytes) into local
  // [offset, ...). Completion via waitRecv (the region bytes arrive as a
  // normal message on `slot`, which must be unused by other traffic with
  // that peer).
  void get(const std::string& remoteKey, uint64_t slot, size_t offset,
           size_t roffset, size_t nbytes);

  // Wait for one send to complete. Returns false if aborted. Throws
  // TimeoutException past the deadline, IoException on transport failure.
  bool waitSend(std::chrono::milliseconds timeout);
  // Wait for one recv to complete; *srcRank (if non-null) receives the
  // source. Same failure contract as waitSend.
  bool waitRecv(int* srcRank, std::chrono::milliseconds timeout);
  // waitRecv that also reports WHICH message landed: *slot (if non-null)
  // receives the completed message's slot. With several recvs
  // outstanding on one buffer, completion order follows the wire, not
  // the posting order (striped and non-striped messages ride different
  // channel sets), so consumers that act per-message — the pipelined
  // wire rings' decode-on-arrival — key off the slot instead of
  // assuming FIFO.
  bool waitRecvSlot(int* srcRank, uint64_t* slot,
                    std::chrono::milliseconds timeout);
  // Wait for one notify-put arrival into this buffer's exported region
  // (bound-buffer waitRecv analog). Kept on a SEPARATE queue from posted
  // receives so one-sided arrivals can never satisfy — or be satisfied
  // by — a tagged recv. Honors abortWaitRecv.
  bool waitPutArrival(int* srcRank, std::chrono::milliseconds timeout);

  // Unblock current and future waiters (they return false) until the abort
  // flag is cleared by the next send/recv post.
  void abortWaitSend();
  void abortWaitRecv();

  // --- completion callbacks (Context / Pair internals) ---
  void onSendComplete();
  void onRecvComplete(int srcRank, uint64_t slot);
  // Notify-put arrival: queues a waitRecv completion WITHOUT pending-recv
  // accounting (no recv was posted; the peer wrote one-sidedly).
  void onRegionPutArrived(int srcRank);
  // Error paths decrement the matching pending count so destruction can
  // always account for every operation exactly once.
  void onSendError(const std::string& message);
  void onRecvError(const std::string& message);
  void addPendingSend();
  void addPendingRecv();
  void cancelPendingSend();
  void cancelPendingRecv();

 private:
  // Blocking-wait core: condvar sleep, or a spin when the device is in
  // sync/busy-poll mode. When the context's watchdog threshold is set and
  // the wait exceeds it, `onStall(waitedUs)` fires ONCE with the buffer
  // lock released (lock order is context -> buffer), then the wait
  // continues to its normal deadline.
  template <typename Pred, typename OnStall>
  bool waitFor(std::unique_lock<std::mutex>& lock, Pred pred,
               std::chrono::milliseconds timeout, OnStall onStall);

  Context* const context_;
  void* const ptr_;
  const size_t size_;
  uint64_t regionToken_{0};  // nonzero once exported via getRemoteKey

  std::mutex mu_;
  std::condition_variable cv_;
  struct RecvDone {
    int srcRank;
    uint64_t slot;
  };

  int pendingSends_{0};
  int pendingRecvs_{0};
  int completedSends_{0};
  std::deque<RecvDone> completedRecvs_;
  std::deque<int> putArrivals_;  // notify-put sources (separate contract)
  bool abortSend_{false};
  bool abortRecv_{false};
  std::string error_;
  bool failed_{false};
};

}  // namespace transport
}  // namespace tpucoll
