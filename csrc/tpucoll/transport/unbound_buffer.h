// UnboundBuffer: a registered memory region from which tagged sends are
// issued and into which tagged receives land. Supports recv-from-any,
// per-operation timeouts, and abortable waits.
//
// Contract parity with the reference's transport::UnboundBuffer
// (gloo/transport/unbound_buffer.h:36-153): send/recv are async; waitSend/
// waitRecv are the only blocking points; waits return false when aborted;
// transport failures surface as IoException; destruction drains in-flight
// operations so the region can never be written after free.
#pragma once

#include <chrono>
#include <condition_variable>
#include <cstddef>
#include <deque>
#include <mutex>
#include <vector>

#include "tpucoll/common/logging.h"

namespace tpucoll {
namespace transport {

class Context;

class UnboundBuffer {
 public:
  UnboundBuffer(Context* context, void* ptr, size_t size);
  ~UnboundBuffer();

  UnboundBuffer(const UnboundBuffer&) = delete;
  UnboundBuffer& operator=(const UnboundBuffer&) = delete;

  void* ptr() const { return ptr_; }
  size_t size() const { return size_; }

  // Async send of [offset, offset+nbytes) to dstRank under `slot`.
  // nbytes == SIZE_MAX means "rest of the buffer".
  void send(int dstRank, uint64_t slot, size_t offset = 0,
            size_t nbytes = SIZE_MAX);

  // Async recv into [offset, offset+nbytes) from srcRank under `slot`.
  void recv(int srcRank, uint64_t slot, size_t offset = 0,
            size_t nbytes = SIZE_MAX);

  // Recv-from-any: first matching arrival from any rank in srcRanks wins.
  void recv(const std::vector<int>& srcRanks, uint64_t slot,
            size_t offset = 0, size_t nbytes = SIZE_MAX);

  // Wait for one send to complete. Returns false if aborted. Throws
  // TimeoutException past the deadline, IoException on transport failure.
  bool waitSend(std::chrono::milliseconds timeout);
  // Wait for one recv to complete; *srcRank (if non-null) receives the
  // source. Same failure contract as waitSend.
  bool waitRecv(int* srcRank, std::chrono::milliseconds timeout);

  // Unblock current and future waiters (they return false) until the abort
  // flag is cleared by the next send/recv post.
  void abortWaitSend();
  void abortWaitRecv();

  // --- completion callbacks (Context / Pair internals) ---
  void onSendComplete();
  void onRecvComplete(int srcRank);
  // Error paths decrement the matching pending count so destruction can
  // always account for every operation exactly once.
  void onSendError(const std::string& message);
  void onRecvError(const std::string& message);
  void addPendingSend();
  void addPendingRecv();
  void cancelPendingSend();
  void cancelPendingRecv();

 private:
  Context* const context_;
  void* const ptr_;
  const size_t size_;

  std::mutex mu_;
  std::condition_variable cv_;
  int pendingSends_{0};
  int pendingRecvs_{0};
  int completedSends_{0};
  std::deque<int> completedRecvs_;
  bool abortSend_{false};
  bool abortRecv_{false};
  std::string error_;
  bool failed_{false};
};

}  // namespace transport
}  // namespace tpucoll
