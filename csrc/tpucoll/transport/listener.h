// Listener: one listening socket per transport device, shared by every pair
// and every context on that device. Inbound connections announce the pair
// they belong to with a 16-byte hello; the listener routes the socket to the
// expecting Pair, or parks it until the Pair registers (reference analog:
// gloo/transport/tcp/listener.h:50-72 seq-number routing).
#pragma once

#include <cstdint>
#include <functional>
#include <string>
#include <list>
#include <memory>
#include <mutex>
#include <unordered_map>

#include "tpucoll/common/keyring.h"
#include "tpucoll/transport/address.h"
#include "tpucoll/transport/loop.h"
#include "tpucoll/transport/shm.h"
#include "tpucoll/transport/wire.h"

namespace tpucoll {
namespace transport {

class Pair;
class PendingConn;

class Listener : public Handler {
 public:
  // `authKey` and `keyring` are stored by reference: the owning Device
  // outlives the Listener (device.h member order).
  Listener(Loop* loop, const SockAddr& bindAddr, const std::string& authKey,
           const Keyring& keyring, bool encrypt);
  ~Listener() override;

  const SockAddr& address() const { return addr_; }

  // Route the inbound connection carrying `pairId` to `pair` (immediately if
  // it already arrived and was parked).
  void expect(uint64_t pairId, Pair* pair);
  void unexpect(uint64_t pairId);

  // Lazy-dial hook (boot plane): invoked — outside the listener lock, on
  // the listener's loop thread — when a fully-handshaked connection
  // carrying a lazy-namespace pair id (boot/lazy_id.h bit 63) parks with
  // no expecting pair. The hook (Device's lazy-mesh registry) builds the
  // accepting Pair on demand and calls expect(), which picks the parked
  // fd right back up. At most one hook; set before any lazy traffic.
  void setUnclaimedHook(std::function<void(uint64_t)> hook) {
    std::lock_guard<std::mutex> guard(mu_);
    unclaimedHook_ = std::move(hook);
  }

  // Re-fire the unclaimed hook for lazy-namespace connections that
  // parked BEFORE their mesh registered: an eager dialer can reach this
  // listener while the local rank is still parsing rendezvous blobs, in
  // which case finishPending's hook pass found no (or the wrong) mesh
  // and the fd would otherwise stay parked forever. Called by the
  // device's lazy-mesh registry after each registration, from the
  // registering thread.
  void replayUnclaimed();

  void handleEvents(uint32_t events) override;

  // PendingConn completion (loop thread). Destroys `conn`. `keys` carries
  // the connection's AEAD keys when the device encrypts; `shm` the accepted
  // same-host payload segment (listener side), if any. keys is BY VALUE:
  // callers pass the dying PendingConn's member, which this function frees
  // before handing the keys on. `authedRank` is the rank the keyring tier
  // authenticated (-1 on the PSK/plain tiers): routing additionally
  // enforces it equals the expecting pair's peer rank, so K[a,b] lets its
  // holder speak only AS a or b — not claim a third identity.
  void finishPending(PendingConn* conn, bool ok, uint64_t pairId, int fd,
                     ConnKeys keys, int32_t authedRank = -1,
                     std::unique_ptr<ShmSegment> shm = nullptr);

 private:
  Loop* const loop_;
  int fd_{-1};
  SockAddr addr_;
  const std::string& authKey_;
  const Keyring& keyring_;
  const bool encrypt_;

  struct Parked {
    int fd;
    ConnKeys keys;
    int32_t authedRank;
    std::unique_ptr<ShmSegment> shm;
  };

  std::mutex mu_;
  bool shuttingDown_{false};
  std::unordered_map<uint64_t, Pair*> expected_;
  std::unordered_map<uint64_t, Parked> parked_;
  std::list<std::unique_ptr<PendingConn>> pending_;
  std::function<void(uint64_t)> unclaimedHook_;
};

}  // namespace transport
}  // namespace tpucoll
