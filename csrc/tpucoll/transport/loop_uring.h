// io_uring event engine (see loop.h for the engine contract and why the
// poll mode is oneshot-rearm). Raw syscall implementation — the image has
// no liburing — against <linux/io_uring.h>: one ring per device, POLL_ADD
// oneshot per registered fd, re-armed after every dispatch so handlers
// keep level-triggered semantics. This is the TPU build's answer to the
// reference's alternative-event-engine tier (gloo/transport/uv/*, libuv):
// instead of carrying a second portability library, carry the kernel's
// own modern interface behind the same Loop contract.
#pragma once

#include <memory>

#include "tpucoll/transport/loop.h"

namespace tpucoll {
namespace transport {

// True when the running kernel/sandbox lets us set up an io_uring.
bool uringAvailable();

// Throws EnforceError when unavailable.
std::unique_ptr<Loop> makeUringLoop(bool busyPoll);

}  // namespace transport
}  // namespace tpucoll
