// Shared-memory payload plane for same-host pairs.
//
// The reference exposes intra-host awareness (gloo/transport/pair.h:79-100
// localRank) but never exploits it; NCCL-class backends do, with a SHM
// transport between co-located ranks. Here the TCP stream stays the control
// plane (headers, ordering, matching, failure detection all unchanged) while
// large payloads move through a pair-private shared-memory segment holding
// one lock-free SPSC byte ring per direction. One memcpy in (sender), one
// memcpy out (receiver loop thread) — no syscalls, no socket buffers, no
// kernel wakeups on the bulk path.
//
// Negotiated during the connect handshake: the initiator creates the
// segment and offers its name when both socket endpoints share an IP; the
// listener accepts iff it can open and validate the segment (random 128-bit
// names plus a magic/pairId stamp make cross-host or cross-namespace
// acceptance impossible — it simply fails to open and the pair falls back
// to plain TCP payloads).
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>

namespace tpucoll {
namespace transport {

// Process-wide configuration (read once). TPUCOLL_SHM=0 disables the
// plane entirely; TPUCOLL_SHM_RING sizes each direction's ring (default
// 8 MiB, clamped to [64 KiB, 1 GiB] — the window listeners accept);
// TPUCOLL_SHM_THRESHOLD sets the payload size at and above which messages
// ride the ring instead of the socket (default 32 KiB, min 1 — the
// small-message latency path stays on the eager TCP protocol, which needs
// no chunk round trips).
bool shmEnabled();
uint64_t shmRingBytesConfig();
uint64_t shmThresholdBytes();

// One direction of the segment: a single-producer single-consumer byte ring.
// head = total bytes produced, tail = total bytes consumed (both monotonic;
// the difference is the fill level). The producer owns head, the consumer
// owns tail; each reads the other's counter with acquire ordering so the
// data memcpy is visible before the counter that publishes it.
struct ShmRing {
  std::atomic<uint64_t>* head{nullptr};
  std::atomic<uint64_t>* tail{nullptr};
  char* data{nullptr};
  uint64_t cap{0};

  uint64_t freeBytes() const {
    return cap - (head->load(std::memory_order_relaxed) -
                  tail->load(std::memory_order_acquire));
  }
  uint64_t usedBytes() const {
    return head->load(std::memory_order_acquire) -
           tail->load(std::memory_order_relaxed);
  }
  // Producer: copy up to n bytes in (bounded by free space); returns the
  // number written. Handles wraparound with a split memcpy.
  uint64_t write(const char* src, uint64_t n);
  // Consumer: stream n bytes (which the producer has published — the caller
  // learned the count from a chunk-announce message) through fn as one or
  // two contiguous spans, then advance tail. fn(ptr, len, offsetInMessage)
  // returns false to abort (tail still advances; the pair is dying anyway).
  template <typename Fn>
  bool consume(uint64_t n, Fn&& fn) {
    const uint64_t t = tail->load(std::memory_order_relaxed);
    const uint64_t off = t % cap;
    const uint64_t first = n < cap - off ? n : cap - off;
    bool ok = fn(data + off, first, uint64_t(0));
    if (ok && n > first) {
      ok = fn(data, n - first, first);
    }
    tail->store(t + n, std::memory_order_release);
    return ok;
  }
};

class ShmSegment {
 public:
  ~ShmSegment();

  // Initiator: create a fresh segment with two rings of ringBytes each,
  // stamped with pairId. Throws IoException on failure.
  static std::unique_ptr<ShmSegment> create(uint64_t pairId,
                                            uint64_t ringBytes);
  // Listener: open and validate an offered segment. Returns nullptr on any
  // mismatch or failure (the caller then rejects the offer).
  static std::unique_ptr<ShmSegment> open(const std::string& name,
                                          uint64_t pairId,
                                          uint64_t ringBytes);

  const std::string& name() const { return name_; }
  uint64_t ringBytes() const { return ringBytes_; }
  // Drop the filesystem name; the mappings keep the memory alive. Called by
  // the initiator as soon as the peer has the segment open (or on failure).
  void unlinkName();

  // dir 0: initiator -> listener; dir 1: listener -> initiator.
  ShmRing ring(int dir) const;

 private:
  ShmSegment() = default;

  std::string name_;
  bool linked_{false};  // name still present in /dev/shm (we created it)
  void* base_{nullptr};
  size_t mapBytes_{0};
  uint64_t ringBytes_{0};
};

}  // namespace transport
}  // namespace tpucoll
