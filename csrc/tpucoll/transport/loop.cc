#include "tpucoll/transport/loop.h"

#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <unistd.h>

#include <cerrno>
#include <cstdlib>
#include <cstring>

#include "tpucoll/common/logging.h"
#include "tpucoll/common/env.h"
#include "tpucoll/transport/loop_uring.h"

namespace tpucoll {
namespace transport {

namespace {
constexpr int kMaxEvents = 64;
}

// ---- Loop: data-path defaults (readiness-only engines) ----

void Loop::addData(int, Handler*) {
  TC_THROW(EnforceError, "engine '", engineName(),
           "' has no submission data path");
}
void Loop::asyncRecv(int, void*, size_t) {
  TC_THROW(EnforceError, "engine '", engineName(),
           "' has no submission data path");
}
void Loop::asyncSend(int, const iovec*, int) {
  TC_THROW(EnforceError, "engine '", engineName(),
           "' has no submission data path");
}

// ---- LoopBase: thread + wakeup + deferral + tick barrier ----

LoopBase::LoopBase(bool busyPoll) : busyPoll_(busyPoll) {
  wakeFd_ = eventfd(0, EFD_CLOEXEC | EFD_NONBLOCK);
  TC_ENFORCE_GE(wakeFd_, 0, "eventfd: ", strerror(errno));
}

LoopBase::~LoopBase() {
  // Engines stopped the thread in their own dtor (their run() uses
  // engine state destroyed before base members); this is the backstop.
  stopThread();
  ::close(wakeFd_);
}

void LoopBase::startThread() {
  thread_ = std::thread([this] { run(); });
}

void LoopBase::stopThread() {
  if (joined_ || !thread_.joinable()) {
    return;
  }
  // Relaxed: exit flag — wake() makes every sleeper re-check, and
  // the join below is the synchronization point for loop effects.
  stop_.store(true, std::memory_order_relaxed);
  wake();
  thread_.join();
  joined_ = true;
  std::lock_guard<std::mutex> guard(mu_);
  tick_ += 2;  // release any barrier() waiters at shutdown
  cv_.notify_all();
}

void LoopBase::wake() {
  uint64_t one = 1;
  ssize_t n = write(wakeFd_, &one, sizeof(one));
  (void)n;
}

void LoopBase::defer(std::function<void()> fn) {
  {
    std::lock_guard<std::mutex> guard(mu_);
    deferred_.push_back(std::move(fn));
  }
  wake();
}

void LoopBase::barrier() {
  if (onLoopThread()) {
    return;
  }
  uint64_t target;
  {
    std::lock_guard<std::mutex> guard(mu_);
    target = tick_ + 1;
  }
  wake();
  std::unique_lock<std::mutex> lock(mu_);
  cv_.wait(lock, [&] { return tick_ >= target || stop_.load(std::memory_order_relaxed); });
}

bool LoopBase::onLoopThread() const {
  return std::this_thread::get_id() == thread_.get_id();
}

void LoopBase::endOfBatch() {
  std::vector<std::function<void()>> fns;
  {
    std::lock_guard<std::mutex> guard(mu_);
    tick_++;
    fns.swap(deferred_);
  }
  cv_.notify_all();
  for (auto& fn : fns) {
    fn();
  }
}

// ---- EpollLoop ----

EpollLoop::EpollLoop(bool busyPoll) : LoopBase(busyPoll) {
  epollFd_ = epoll_create1(EPOLL_CLOEXEC);
  TC_ENFORCE_GE(epollFd_, 0, "epoll_create1: ", strerror(errno));
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.ptr = nullptr;  // nullptr marks the wake fd
  TC_ENFORCE_EQ(epoll_ctl(epollFd_, EPOLL_CTL_ADD, wakeFd_, &ev), 0);
  startThread();
}

EpollLoop::~EpollLoop() {
  stopThread();
  ::close(epollFd_);
}

void EpollLoop::add(int fd, uint32_t events, Handler* handler) {
  epoll_event ev{};
  ev.events = events;
  ev.data.ptr = handler;
  TC_ENFORCE_EQ(epoll_ctl(epollFd_, EPOLL_CTL_ADD, fd, &ev), 0,
                "epoll add: ", strerror(errno));
}

void EpollLoop::mod(int fd, uint32_t events, Handler* handler) {
  epoll_event ev{};
  ev.events = events;
  ev.data.ptr = handler;
  TC_ENFORCE_EQ(epoll_ctl(epollFd_, EPOLL_CTL_MOD, fd, &ev), 0,
                "epoll mod: ", strerror(errno));
}

void EpollLoop::del(int fd) {
  epoll_event ev{};
  int rv = epoll_ctl(epollFd_, EPOLL_CTL_DEL, fd, &ev);
  if (rv != 0) {
    TC_ENFORCE_EQ(errno, ENOENT, "epoll del: ", strerror(errno));
  }
  // Tick barrier: once the loop completes the current dispatch batch, no
  // stale event for fd can be pending.
  barrier();
}

void EpollLoop::run() {
  epoll_event events[kMaxEvents];
  while (!stop_.load(std::memory_order_relaxed)) {
    // Busy-poll mode never sleeps in the kernel: epoll_wait(0) returns
    // immediately and the pause keeps the spin hyperthread-friendly.
    int n = epoll_wait(epollFd_, events, kMaxEvents, busyPoll_ ? 0 : 100);
    if (n < 0) {
      TC_ENFORCE_EQ(errno, EINTR, "epoll_wait: ", strerror(errno));
      continue;
    }
    if (n == 0 && busyPoll_) {
#if defined(__x86_64__) || defined(__i386__)
      __builtin_ia32_pause();
#endif
      // Yield between empty polls: on a dedicated core this is nearly
      // free; on an oversubscribed host it keeps spinners from starving
      // the threads that would produce their events. Skipping
      // endOfBatch() here is safe per its contract (wakeFd_ is watched).
      std::this_thread::yield();
      continue;
    }
    for (int i = 0; i < n; i++) {
      if (events[i].data.ptr == nullptr) {
        uint64_t drain;
        while (read(wakeFd_, &drain, sizeof(drain)) > 0) {
        }
        continue;
      }
      try {
        static_cast<Handler*>(events[i].data.ptr)
            ->handleEvents(events[i].events);
      } catch (const std::exception& e) {
        // Handlers convert expected failures into pair errors themselves; an
        // exception reaching here is a bug, but killing the whole process
        // (std::terminate off a std::thread) would take every rank down.
        TC_ERROR("unhandled exception on event loop thread: ", e.what());
      }
    }
    endOfBatch();
  }
}

std::unique_ptr<Loop> makeLoop(bool busyPoll, const std::string& engine) {
  std::string e = engine;
  if (e.empty()) {
    // Strict choice (common/env.h): a misspelled engine must not
    // silently fall back to epoll and invalidate an A/B measurement.
    e = envChoice("TPUCOLL_ENGINE", "auto", {"auto", "epoll", "uring"});
  }
  if (e == "auto" || e == "epoll" || e.empty()) {
    return std::make_unique<EpollLoop>(busyPoll);
  }
  if (e == "uring") {
    // Explicit request: fail loudly if the kernel/sandbox lacks io_uring
    // instead of silently running a different engine.
    return makeUringLoop(busyPoll);
  }
  TC_THROW(EnforceError, "unknown event engine (want epoll|uring|auto): ",
           e);
}

}  // namespace transport
}  // namespace tpucoll
