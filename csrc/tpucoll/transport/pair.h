// Pair: one bidirectional point-to-point channel between this process and a
// peer rank, multiplexing all slot-tagged messages over a single TCP stream.
//
// Contract parity with the reference pair state machine (gloo/transport/tcp/
// pair.h:87-92, pair.cc) — connect/close lifecycle, async sends with inline
// fast path, error fan-out to pending operations — but with the eager wire
// protocol of wire.h instead of the notify/ready handshake, and with receive
// matching delegated to transport::Context.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "tpucoll/fault/fault.h"
#include "tpucoll/transport/address.h"
#include "tpucoll/transport/loop.h"
#include "tpucoll/transport/shm.h"
#include "tpucoll/transport/unbound_buffer.h"
#include "tpucoll/transport/wire.h"

namespace tpucoll {
namespace transport {

class Context;
class Listener;

// Shared completion state for one striped logical send (TPUCOLL_CHANNELS
// > 1). The logical operation resolves EXACTLY ONCE, when the LAST
// stripe resolves (wire-completed or errored) — never earlier: an early
// onSendError would zero the buffer's pending-send count while sibling
// stripes on other channel pairs are still transmitting from its
// memory, letting ~UnboundBuffer free bytes a loop thread is reading
// (use-after-free). The last resolver delivers onSendError when ANY
// stripe failed (first recorded message wins) and onSendComplete
// otherwise; striped sends are never cancelled (cancelQueuedSends skips
// them — a sibling may already be on the wire, and shipping a partial
// message would hang the receiver's reassembly). Stripes live on
// different Pair objects, so the state is atomics + one cold-path mutex.
struct StripeTx {
  explicit StripeTx(int n) : remaining(n) {}
  std::atomic<int> remaining;  // unresolved stripes
  std::atomic<bool> failed{false};
  std::mutex errMu;
  std::string error;  // first failure message (errMu)

  void recordError(const std::string& msg) {
    std::lock_guard<std::mutex> guard(errMu);
    if (!failed.load(std::memory_order_relaxed)) {
      error = msg;
      failed.store(true, std::memory_order_relaxed);
    }
  }
};

class Pair : public Handler {
 public:
  enum class State : int {
    kInitializing = 0,
    kConnected = 2,
    kFailed = 3,
    kClosed = 4,
  };

  // `channel` is this connection's data-channel index within the logical
  // pair (0 = the primary connection, which alone carries control
  // traffic, sub-threshold messages, and the shm plane; >= 1 = an extra
  // stripe lane with its own handshake/encryption state, ideally on its
  // own loop). `loopIndex` names `loop` within the device pool for the
  // per-loop progress metrics.
  Pair(Context* context, Loop* loop, int selfRank, int peerRank,
       uint64_t localPairId, int channel = 0, int loopIndex = 0);
  ~Pair() override;

  uint64_t localPairId() const { return localPairId_; }
  int peerRank() const { return peerRank_; }
  int channel() const { return channel_; }

  // Initiator path (blocking, user thread): TCP connect to the peer's
  // listener and write the hello routing this connection to `remotePairId`.
  // Retries retryable failures (peer not accepting yet, reset mid-
  // handshake) with backoff until the deadline, emitting a structured
  // ConnectDebugData record per attempt (common/debug.h); set
  // TPUCOLL_DISABLE_CONNECTION_RETRIES to fail on the first error
  // (reference: GLOO_DISABLE_CONNECTION_RETRIES).
  void connect(const SockAddr& remote, uint64_t remotePairId,
               std::chrono::milliseconds timeout);

  // Listener path: register interest in an inbound connection carrying our
  // localPairId; the listener hands us the fd once the hello arrives.
  void expectViaListener(Listener* listener);

  void waitConnected(std::chrono::milliseconds timeout);

  // Async send; data must stay valid until the matching waitSend completes.
  void send(UnboundBuffer* ubuf, uint64_t slot, const char* data,
            size_t nbytes);

  // One stripe of a striped logical message (wire.h kStripe): this
  // channel's contiguous [data, data+nbytes) share of a `total`-byte
  // message split over `count` channels. `st` is the shared completion
  // state; `seqLow` tags all stripes of one message (reassembly
  // disambiguation). Only transport::Context calls this, once per
  // channel, in channel order.
  void sendStripe(UnboundBuffer* ubuf, uint64_t slot, const char* data,
                  size_t nbytes, uint64_t total, uint8_t count,
                  uint8_t seqLow, std::shared_ptr<StripeTx> st);

  // One-sided write into the peer's registered region (kPut framing).
  // notify: the target's exporting buffer gets a waitRecv completion on
  // arrival (bound-buffer semantics). `st` carries the shared completion
  // state when the put is one stripe of a striped logical put.
  void sendPut(UnboundBuffer* ubuf, uint64_t token, uint64_t roffset,
               const char* data, size_t nbytes, bool notify = false,
               std::shared_ptr<StripeTx> st = nullptr);

  // Enqueue a message whose payload the op itself owns (get requests and
  // get responses): no completion callback, safe from any thread.
  void sendOwned(WireHeader header, std::vector<char> payload);

  // Remove queued sends for `ubuf` that have not started hitting the wire;
  // returns how many were dropped. A partially-written front op cannot be
  // cancelled (removing it would corrupt the stream framing).
  int cancelQueuedSends(UnboundBuffer* ubuf);
  // True if any tx op (including a partially-written one) references ubuf.
  bool hasInflightSend(UnboundBuffer* ubuf);
  // Watchdog introspection: slot of the first queued/in-flight tx op that
  // references ubuf. Returns false when none does.
  bool sendSlotFor(UnboundBuffer* ubuf, uint64_t* slot);

  // Graceful close; pending operations fail. Idempotent, thread-safe.
  // `grace` bounds the goodbye/EOF drain (the default matches the
  // historical close behavior; the lazy broker evicts with a shorter
  // grace so a slow peer cannot stall the dial that triggered eviction).
  void close(std::chrono::milliseconds grace = std::chrono::milliseconds(2000));

  // ---- lazy broker hooks (transport::Context, boot plane) ----
  // Marks a peer-initiated connection accepted on demand via the lazy
  // pair-id namespace. Such a pair is rx-only (dual simplex: each side
  // sends only on connections it dialed), and on receiving the peer's
  // goodbye it answers with its own immediately — the evicting side's
  // close() then completes without waiting out its grace, and this
  // side's EOF tears down orderly. Set before connect/expect.
  void setLazyInbound() { lazyInbound_ = true; }
  // True once the pair tore down (failed or closed) — the broker drops
  // such pairs from its tables on the next scan.
  bool defunct() const {
    State s = state_.load(std::memory_order_acquire);
    return s == State::kFailed || s == State::kClosed;
  }
  // Eviction gate: connected with nothing queued or on the wire.
  bool idleForEvict();

  // Hard-fail the pair from a user thread (see Context::
  // failPairsWithInflightSend).
  void failFromUser(const std::string& message) { fail(message); }

  void handleEvents(uint32_t events) override;
  // Submission data path (uring engine): completion of an asyncRecv/
  // asyncSend posted by this pair. Loop thread.
  void handleIoComplete(bool isRecv, int32_t res) override;

  // Called by the listener (loop thread) when our inbound connection is up.
  // `keys` carries the connection's AEAD keys on encrypted devices; `shm`
  // the negotiated same-host payload segment (nullptr: TCP payloads), with
  // `shmInitiator` selecting this side's ring directions.
  void assumeConnected(int fd, const ConnKeys& keys = ConnKeys{},
                       std::unique_ptr<ShmSegment> shm = nullptr,
                       bool shmInitiator = false);

  // One-line tx/flow-control state for Context::debugDump (any thread).
  std::string debugState();

  // Shared-memory payload plane introspection (any thread).
  bool shmActive() const { return shmActive_.load(std::memory_order_relaxed); }
  uint64_t shmTxBytes() const {
    return shmTxBytes_.load(std::memory_order_relaxed);
  }
  uint64_t shmRxBytes() const {
    return shmRxBytes_.load(std::memory_order_relaxed);
  }

  // Receiver-side flow control (called by Context under its own lock):
  // pause stops reading this pair's socket so TCP backpressure throttles a
  // runaway sender; resume re-arms EPOLLIN. Safe from any thread.
  void pauseReading();
  void resumeReading();

 private:
  struct TxOp {
    WireHeader header;
    size_t headerSent{0};
    UnboundBuffer* ubuf;
    const char* data;
    size_t nbytes;
    size_t dataSent{0};
    // Striped logical send: completion routes through the shared state
    // (last stripe in wins) instead of completing ubuf directly.
    std::shared_ptr<StripeTx> stripe;
    // Encrypted framing: one sealed frame at a time (header frame, then
    // payload frames of kEncFrameBytes), built lazily when the op FIRST
    // starts transmitting so cancelled queued sends never consume a tx
    // sequence number (a consumed-but-unsent seq would desynchronize the
    // receiver's nonce counter). Framing bounds the staging buffer and
    // overlaps sealing with socket writes.
    std::vector<char> cipher;   // current frame (ciphertext + tag)
    size_t cipherSent{0};
    bool headerSealed{false};
    size_t sealOffset{0};       // payload bytes sealed so far
    // Self-owned payload (get requests/responses): `data` points into it.
    std::vector<char> ownedData;
    // Shared-memory payload plane (wire.h kShm*): the payload moves
    // through the pair's shm ring; the socket carries only the announce
    // header and per-chunk headers.
    bool viaShm{false};
    bool announceDone{false};       // announce header fully on the wire
    uint64_t shmWritten{0};         // payload bytes copied into the ring
    uint64_t shmAnnounced{0};       // payload bytes covered by chunk headers
    bool creditReqSent{false};      // a kShmCreditReq is out for this stall
    int64_t creditReqUs{0};         // when it went out (link RTT probe)
    WireHeader chunkHeader{};       // current chunk header (plain path)
    size_t chunkHeaderSent{0};
    bool chunkInFlight{false};
  };

  // Outcome of trying to advance the front shm op (mu_ held).
  enum class ShmTxStatus { kDone, kSocketFull, kRingBlocked, kError };

  // A finished tx op's completion routing: direct (ubuf) or through the
  // striped-send shared state. Built under mu_, delivered without it.
  struct TxDone {
    UnboundBuffer* ubuf;
    std::shared_ptr<StripeTx> stripe;
  };
  static void deliverSendComplete(const TxDone& d);
  static void deliverSendError(const TxDone& d, const std::string& msg);
  // Last-resolution outcome delivery for a striped send (see StripeTx).
  static void finalizeStripe(const TxDone& d);

  // Which tx cursor an in-flight data-path send advances on completion.
  // Each socket-write site in the flush functions is one site; the
  // completion replays exactly the cursor arithmetic the synchronous
  // path would have applied after its send() returned.
  enum class TxSite : uint8_t {
    kCtrl,             // ctrlSent_
    kFrontHeader,      // tx_.front().headerSent (plain shm announce)
    kFrontChunkHeader, // tx_.front().chunkHeaderSent (plain shm chunk)
    kFrontCipher,      // tx_.front().cipherSent (any sealed frame)
    kFrontPlain,       // tx_.front() header+data sendmsg split
  };

  // The socket-write primitive behind every flush site. Readiness mode:
  // sendmsg/send directly (EINTR retried; EAGAIN reported). Data-path
  // mode: submit ONE sendmsg SQE for the iovec (at most one in flight),
  // record `site`, and report EAGAIN — the flush stops exactly as if
  // the socket were full, and the completion advances the cursors and
  // re-runs it. mu_ held.
  ssize_t txWrite(TxSite site, const iovec* iov, int iovcnt);
  // Apply `n` sent bytes to the cursors of the in-flight site (mu_ held).
  void txAdvanceInFlight(size_t n);

  // Data-path rx driver (loop thread unless noted).
  struct RxWant {
    char* ptr;
    size_t len;
  };
  RxWant rxWant();  // next bytes the rx state machine needs
  enum class RxStep { kMore, kStop };
  // Post-read processing shared by readLoop (readiness) and
  // handleIoComplete (data path): advance the state machine by n
  // received bytes.
  RxStep processRxBytes(size_t n, size_t* consumed);
  RxStep processHeader(size_t* consumed);  // header complete: dispatch
  void onRxEof();                          // peer closed (read returned 0)
  // Post the next recv if connected, unposted, and not paused at a
  // message boundary. Requires mu_ held; rxPosted_ is the latch that
  // keeps any other thread from posting while the loop thread still
  // owns the rx cursors (cleared only at its repost decision points).
  void maybePostRecvLocked();

  // Write queued ops until EAGAIN or empty; requires mu_ held. Completed
  // ops' buffers are appended to `completed` (callbacks run without mu_).
  void flushTx(std::vector<TxDone>* completed);
  // Advance the front (shm) op: announce header, ring writes, chunk
  // headers, credit requests. mu_ held.
  ShmTxStatus flushShmFront(TxOp* op, std::vector<TxDone>* completed);
  // Drain the control channel (credits/credit requests), which preempts
  // the data stream only at wire-message boundaries. Returns false when
  // the socket is full or an error was recorded. mu_ held.
  bool flushCtrl();
  bool streamAtBoundary() const;  // mu_ held
  void queueCtrl(Opcode opcode);  // mu_ held; caller flushes + updates mask
  // Shared enqueue path behind send/sendPut/sendOwned (acquires mu_).
  void enqueue(TxOp op);
  // Fault-injection cold paths (fault/fault.h): send/sendPut delegate
  // here when a schedule is armed, keeping the disarmed hot path at
  // exactly one predictable check.
  void sendFaulted(UnboundBuffer* ubuf, uint64_t slot, const char* data,
                   size_t nbytes);
  void sendPutFaulted(UnboundBuffer* ubuf, uint64_t token,
                      uint64_t roffset, const char* data, size_t nbytes,
                      bool notify, std::shared_ptr<StripeTx> st);
  // Mutate the op per the fired decision (corrupt/truncate), or veto
  // the enqueue entirely (kill — the pair is already failed when this
  // returns false).
  bool applyTxFault(const fault::TxDecision& fd, TxOp* op);
  // Post-enqueue fault tail: duplicate copy / sever after truncation.
  void finishTxFault(const fault::TxDecision& fd,
                     const WireHeader& cleanHeader, const char* data,
                     size_t nbytes);
  // One connection attempt: TCP connect + hello + (optional) PSK
  // handshake; throws on failure. Fills *localAddr once bound.
  void connectAttempt(const SockAddr& remote, uint64_t remotePairId,
                      std::chrono::steady_clock::time_point deadline,
                      std::string* localAddr);
  // Seal the next frame (header, then payload chunks) into op->cipher,
  // consuming one tx seq each (mu_ held).
  void sealHeaderFrame(TxOp* op);
  void sealPayloadFrame(TxOp* op);
  void updateEpollMask();  // mu_ held
  void readLoop();         // loop thread only
  // Consume a fully received message (loop thread).
  void finishMessage();
  // Transition to kFailed, release resources, fan error out. Safe from any
  // thread; idempotent.
  void fail(const std::string& message);
  void teardown(State target, const std::string& message, bool notifyContext);

  Context* const context_;
  Loop* const loop_;
  const int selfRank_;
  const int peerRank_;
  const uint64_t localPairId_;
  const int channel_;    // data-channel index within the logical pair
  const int loopIndex_;  // loop_'s index in the device pool (metrics)
  // Engine-selected I/O mode: submission data path (uring) vs readiness
  // + direct syscalls (epoll). Fixed at construction.
  const bool dataPath_;

  // Ordering protocol (tools/check explicit-atomics): connect publishes
  // keys_/shm rings/fd_ with release stores of state_/everConnected_;
  // lock-free fast paths pair them with acquire loads. fd_ reads off
  // the hot path are relaxed — the fd number itself is the data.
  std::atomic<State> state_{State::kInitializing};
  std::atomic<bool> everConnected_{false};
  Listener* expectedAt_{nullptr};
  bool closing_{false};      // goodbye enqueued (mu_)
  bool peerGoodbye_{false};  // peer announced orderly departure (mu_)
  bool rxPaused_{false};     // stash backpressure engaged (mu_)
  bool lazyInbound_{false};  // broker-accepted rx-only pair (pre-connect)

  std::mutex mu_;
  std::condition_variable cv_;
  // Atomic: written during teardown (under mu_) while the loop thread's
  // read path inspects it without the pair lock. The close() sequencing
  // (state flip + loop tick barrier before ::close) provides the actual
  // lifetime guarantee; atomicity just keeps the access well-defined.
  std::atomic<int> fd_{-1};
  uint32_t epollMask_{0};
  std::deque<TxOp> tx_;
  std::string error_;
  std::string pendingTxError_;  // set by flushTx (mu_ held), drained by caller
  // Data-path state (mu_): one in-flight sendmsg SQE + its cursor site;
  // one in-flight recv SQE flag (flipped under mu_, cursors loop-thread).
  bool txInFlight_{false};
  TxSite txSite_{TxSite::kCtrl};
  bool rxPosted_{false};
  UnboundBuffer* rxUbuf_{nullptr};  // guarded by mu_ (cross-thread on fail)

  // Connection cipher state. keys_ is written once before the pair is
  // CONNECTED (handshake thread) and only read afterwards; the seq
  // counters live on their owning threads (tx under mu_, rx on the loop
  // thread).
  ConnKeys keys_;
  uint64_t txSeq_{0};
  uint64_t rxSeq_{0};

  // ---- shared-memory payload plane ----
  std::unique_ptr<ShmSegment> shm_;  // set before CONNECTED, freed in dtor
  ShmRing shmTx_;
  ShmRing shmRx_;
  std::atomic<bool> shmActive_{false};
  std::atomic<uint64_t> shmTxBytes_{0};
  std::atomic<uint64_t> shmRxBytes_{0};
  // tx-side flow control (mu_): front op stalled on ring space, waiting
  // for a kShmCredit wakeup.
  bool txRingBlocked_{false};
  // Control channel (mu_): queued credit/credit-request opcodes plus the
  // one currently hitting the wire (raw header, or sealed frame).
  std::deque<Opcode> ctrlQ_;
  char ctrlBuf_[sizeof(WireHeader) + kAeadTagBytes];
  size_t ctrlLen_{0};
  size_t ctrlSent_{0};


  // rx state, loop thread only
  enum class RxMode { kDirect, kStash, kPut, kGetReq, kStripe };
  WireHeader rxHeader_{};
  size_t rxHeaderRead_{0};
  bool rxInPayload_{false};
  char* rxDest_{nullptr};
  std::vector<char> rxStashData_;
  RxMode rxMode_{RxMode::kDirect};
  // Fused receive-reduce over the byte-stream path: payload (incl.
  // ciphertext) stages in rxCombineStage_ so partial reads never clobber
  // the accumulator; at message completion rxCombine_ folds the staging
  // into rxFinalDest_ (the posted recvReduce destination). The stage is
  // grow-only (kept across messages): fused TCP traffic must not pay a
  // malloc + zero-fill per message.
  //
  // Encrypted connections instead fold FRAME-BY-FRAME (rxFoldInline_):
  // each kEncFrameBytes frame's plaintext is combined into the
  // accumulator right after its AEAD tag verifies, while it is still
  // cache-hot — the whole-message fold at completion would re-read the
  // stage cold, one full memory traversal per byte (measured on the
  // 16 MiB encrypted-allreduce A/B, BASELINE.md r5). Only verified
  // plaintext is ever folded; a tampered later frame poisons the pair
  // and the pending op errors out with the accumulator partially
  // updated — same contents-undefined-on-error contract as every other
  // failed in-place collective.
  RecvReduceFn rxCombine_{nullptr};
  size_t rxCombineElsize_{0};     // wire bytes per element
  size_t rxCombineAccElsize_{0};  // accumulator bytes per element
  char* rxFinalDest_{nullptr};
  bool rxFoldInline_{false};
  std::vector<char> rxCombineStage_;
  size_t rxPayloadRead_{0};  // progress within the current frame
  size_t rxPlainDone_{0};    // completed (verified) payload bytes
  // Encrypted rx staging: ciphertext header+tag, and the payload tag that
  // trails the in-place payload ciphertext.
  uint8_t rxHeaderCipher_[sizeof(WireHeader) + kAeadTagBytes];
  uint8_t rxPayloadTag_[kAeadTagBytes];

  // rx-side shm message state (loop thread only): set by a kShmData/kShmPut
  // announce, advanced by kShmChunk, cleared at message completion.
  bool shmRxActive_{false};
  RxMode shmRxMode_{RxMode::kDirect};
  WireHeader shmRxHeader_{};   // the announce header (slot/aux/flags)
  char* shmRxDest_{nullptr};   // direct: user memory; stash: shmRxStash_
  std::vector<char> shmRxStash_;
  uint64_t shmRxTotal_{0};
  uint64_t shmRxDone_{0};
  // Fused receive-reduce from the shm ring: spans are combined into the
  // destination straight out of shared memory (no staging copy at all —
  // the whole point of recvReduce). Ring wrap and chunk caps can split an
  // element across spans; the carry buffer bridges those bytes.
  RecvReduceFn shmRxCombine_{nullptr};
  size_t shmRxCombineElsize_{0};     // wire bytes per element
  size_t shmRxCombineAccElsize_{0};  // accumulator bytes per element
  // Over-aligned: the carry is fed to typed reduce kernels as a 1-element
  // span, so it must satisfy the strictest alignment any kernel wants
  // (kMaxCombineElsize itself is no longer a power of two — it is sized
  // for q8 wire units — so the alignment is pinned at a cache line).
  alignas(64) uint8_t shmRxCarry_[kMaxCombineElsize];
  size_t shmRxCarryLen_{0};

  // Combine one in-order span of the active shm message (handles
  // element-straddling span boundaries via shmRxCarry_). `msgOff` is the
  // span's byte offset within the WIRE message; the accumulator address
  // for wire element i is shmRxDest_ + i * shmRxCombineAccElsize_.
  void combineShmSpan(uint64_t msgOff, const char* src, size_t len);

  // Reassembly handle of the stripe currently landing (RxMode::kStripe;
  // loop thread only) and its channel index echo.
  uint64_t rxStripeEntry_{0};

  // Stamp this pair's last-progress timestamp (the watchdog's liveness
  // signal), the per-channel byte counters, and the per-loop progress
  // stamp in the metrics registry. Called wherever payload or wire bytes
  // actually move; `tx` picks the byte-counter direction.
  void touchProgress(bool tx, size_t bytes);
};

}  // namespace transport
}  // namespace tpucoll
