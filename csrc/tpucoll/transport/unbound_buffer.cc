#include "tpucoll/transport/unbound_buffer.h"

#include <cstring>
#include <thread>

#include "tpucoll/transport/context.h"
#include "tpucoll/transport/device.h"
#include "tpucoll/transport/wire.h"

namespace tpucoll {
namespace transport {

UnboundBuffer::UnboundBuffer(Context* context, void* ptr, size_t size)
    : context_(context), ptr_(ptr), size_(size) {
  TC_ENFORCE(ptr != nullptr || size == 0, "null buffer with nonzero size");
}

UnboundBuffer::~UnboundBuffer() {
  // Revoke the one-sided registration first: later puts/gets against the
  // region miss (peer contract violation), and in-flight ones already
  // copied under the region lock.
  if (regionToken_ != 0) {
    context_->unregisterRegion(regionToken_);
  }
  // Cancel operations that have not touched the wire yet, then drain
  // whatever is still in flight: the loop thread may hold raw pointers into
  // our memory until each op completes or the owning pair fails.
  context_->cancelRecvsFor(this);
  context_->cancelSendsFor(this);
  auto done = [&] { return pendingSends_ == 0 && pendingRecvs_ == 0; };
  {
    std::unique_lock<std::mutex> lock(mu_);
    if (cv_.wait_for(lock, std::chrono::seconds(5), done)) {
      return;
    }
  }
  // A partially-written send to a stalled peer is the only way to get here;
  // poison those pairs (clears their tx queues and errors us) rather than
  // blocking destruction forever.
  context_->failPairsWithInflightSend(this);
  std::unique_lock<std::mutex> lock(mu_);
  cv_.wait(lock, done);
}

void UnboundBuffer::send(int dstRank, uint64_t slot, size_t offset,
                         size_t nbytes) {
  if (nbytes == SIZE_MAX) {
    TC_ENFORCE_LE(offset, size_);
    nbytes = size_ - offset;
  }
  TC_ENFORCE_LE(offset + nbytes, size_, "send out of bounds");
  {
    std::lock_guard<std::mutex> guard(mu_);
    abortSend_ = false;
  }
  context_->postSend(this, dstRank, slot,
                     static_cast<char*>(ptr_) + offset, nbytes);
}

void UnboundBuffer::recv(int srcRank, uint64_t slot, size_t offset,
                         size_t nbytes) {
  recv(std::vector<int>{srcRank}, slot, offset, nbytes);
}

void UnboundBuffer::recv(const std::vector<int>& srcRanks, uint64_t slot,
                         size_t offset, size_t nbytes) {
  if (nbytes == SIZE_MAX) {
    TC_ENFORCE_LE(offset, size_);
    nbytes = size_ - offset;
  }
  TC_ENFORCE_LE(offset + nbytes, size_, "recv out of bounds");
  TC_ENFORCE_GT(srcRanks.size(), size_t(0), "empty source rank list");
  {
    std::lock_guard<std::mutex> guard(mu_);
    abortRecv_ = false;
  }
  context_->postRecv(this, srcRanks, slot,
                     static_cast<char*>(ptr_) + offset, nbytes);
}

void UnboundBuffer::recvReduce(int srcRank, uint64_t slot, RecvReduceFn fn,
                               size_t elsize, size_t offset, size_t nbytes) {
  if (nbytes == SIZE_MAX) {
    TC_ENFORCE_LE(offset, size_);
    nbytes = size_ - offset;
  }
  recvReduceTyped(srcRank, slot, fn, elsize, elsize, offset, nbytes);
}

void UnboundBuffer::recvReduceTyped(int srcRank, uint64_t slot,
                                    RecvReduceFn fn, size_t wireElsize,
                                    size_t accElsize, size_t offset,
                                    size_t wireNbytes) {
  TC_ENFORCE(fn != nullptr, "recvReduce: null reduce fn");
  TC_ENFORCE(wireElsize > 0 && wireElsize <= kMaxCombineElsize,
             "recvReduce: wire element size ", wireElsize, " out of range");
  TC_ENFORCE(accElsize > 0, "recvReduce: bad accumulator element size");
  TC_ENFORCE_EQ(wireNbytes % wireElsize, size_t(0),
                "recvReduce: payload not a whole number of elements");
  const size_t accBytes = wireNbytes / wireElsize * accElsize;
  TC_ENFORCE(offset <= size_ && accBytes <= size_ - offset,
             "recvReduce: accumulator range out of bounds");
  {
    std::lock_guard<std::mutex> guard(mu_);
    abortRecv_ = false;
  }
  context_->postRecv(this, std::vector<int>{srcRank}, slot,
                     static_cast<char*>(ptr_) + offset, wireNbytes, fn,
                     wireElsize, accElsize);
}

namespace {

WireRemoteKey parseRemoteKey(const std::string& blob) {
  TC_ENFORCE_EQ(blob.size(), sizeof(WireRemoteKey), "bad remote key size");
  WireRemoteKey key;
  std::memcpy(&key, blob.data(), sizeof(key));
  TC_ENFORCE_EQ(key.magic, kRemoteKeyMagic, "bad remote key magic");
  return key;
}

}  // namespace

std::string UnboundBuffer::getRemoteKey() {
  if (regionToken_ == 0) {
    regionToken_ =
        context_->registerRegion(static_cast<char*>(ptr_), size_, this);
  }
  WireRemoteKey key{kRemoteKeyMagic, context_->rank(), regionToken_, size_};
  return std::string(reinterpret_cast<const char*>(&key), sizeof(key));
}

void UnboundBuffer::put(const std::string& remoteKey, size_t offset,
                        size_t roffset, size_t nbytes, bool notify) {
  const WireRemoteKey key = parseRemoteKey(remoteKey);
  TC_ENFORCE(key.rank >= 0 && key.rank < context_->size(),
             "remote key rank ", key.rank, " outside group of ",
             context_->size());
  TC_ENFORCE_LE(offset, size_, "put local offset out of bounds");
  TC_ENFORCE_LE(nbytes, size_ - offset, "put out of local bounds");
  TC_ENFORCE_LE(roffset, key.size, "put remote offset out of bounds");
  TC_ENFORCE_LE(nbytes, key.size - roffset, "put out of remote bounds");
  {
    std::lock_guard<std::mutex> guard(mu_);
    abortSend_ = false;
  }
  context_->postPut(this, key.rank, key.token, roffset,
                    static_cast<char*>(ptr_) + offset, nbytes, notify);
}

void UnboundBuffer::get(const std::string& remoteKey, uint64_t slot,
                        size_t offset, size_t roffset, size_t nbytes) {
  const WireRemoteKey key = parseRemoteKey(remoteKey);
  TC_ENFORCE(key.rank >= 0 && key.rank < context_->size(),
             "remote key rank ", key.rank, " outside group of ",
             context_->size());
  TC_ENFORCE_LE(offset, size_, "get local offset out of bounds");
  TC_ENFORCE_LE(nbytes, size_ - offset, "get out of local bounds");
  TC_ENFORCE_LE(roffset, key.size, "get remote offset out of bounds");
  TC_ENFORCE_LE(nbytes, key.size - roffset, "get out of remote bounds");
  // Issue the request first: if it throws, nothing is left pending. A
  // response can never be lost to the ordering — early arrivals stash
  // until the recv below posts (the eager protocol's normal path).
  context_->postGetRequest(key.rank, slot, key.token, roffset, nbytes);
  recv(key.rank, slot, offset, nbytes);
}

template <typename Pred, typename OnStall>
bool UnboundBuffer::waitFor(std::unique_lock<std::mutex>& lock, Pred pred,
                            std::chrono::milliseconds timeout,
                            OnStall onStall) {
  Metrics* metrics = context_->metrics();
  const int64_t watchdogUs =
      metrics != nullptr ? metrics->watchdogUs() : 0;
  const auto start = std::chrono::steady_clock::now();
  const auto deadline = start + timeout;
  bool reported = false;
  auto maybeReport = [&](std::chrono::steady_clock::time_point now) {
    if (reported || watchdogUs <= 0 ||
        now - start < std::chrono::microseconds(watchdogUs)) {
      return;
    }
    reported = true;
    const int64_t waitedUs =
        std::chrono::duration_cast<std::chrono::microseconds>(now - start)
            .count();
    // Released: reportStall takes the transport-context lock, and the
    // established order is context -> buffer.
    lock.unlock();
    onStall(waitedUs);
    lock.lock();
  };
  if (!context_->device()->busyPoll()) {
    if (watchdogUs <= 0) {
      return cv_.wait_for(lock, timeout, pred);
    }
    const auto stallAt = start + std::chrono::microseconds(watchdogUs);
    while (!pred()) {
      const auto next =
          (!reported && stallAt < deadline) ? stallAt : deadline;
      if (cv_.wait_until(lock, next, pred)) {
        return true;
      }
      const auto now = std::chrono::steady_clock::now();
      if (now >= deadline) {
        return pred();
      }
      maybeReport(now);
    }
    return true;
  }
  // Sync/busy-poll mode: spin instead of sleeping — the completion comes
  // from the (also spinning) loop thread, so the round trip avoids two
  // kernel wakeups.
  while (!pred()) {
    lock.unlock();
#if defined(__x86_64__) || defined(__i386__)
    __builtin_ia32_pause();
#endif
    std::this_thread::yield();
    const auto now = std::chrono::steady_clock::now();
    const bool expired = now >= deadline;
    lock.lock();
    if (expired) {
      return pred();
    }
    maybeReport(now);
  }
  return true;
}

bool UnboundBuffer::waitSend(std::chrono::milliseconds timeout) {
  std::unique_lock<std::mutex> lock(mu_);
  auto pred = [&] { return completedSends_ > 0 || abortSend_ || failed_; };
  auto onStall = [this](int64_t waitedUs) {
    context_->reportStall(this, /*isSend=*/true, waitedUs);
  };
  if (!waitFor(lock, pred, timeout, onStall)) {
    TC_THROW(TimeoutException, "waitSend timed out after ", timeout.count(),
             "ms");
  }
  if (failed_ && completedSends_ == 0) {
    TC_THROW(IoException, error_);
  }
  if (abortSend_ && completedSends_ == 0) {
    return false;
  }
  TC_ENFORCE_GT(completedSends_, 0);
  completedSends_--;
  return true;
}

bool UnboundBuffer::waitRecv(int* srcRank, std::chrono::milliseconds timeout) {
  return waitRecvSlot(srcRank, nullptr, timeout);
}

bool UnboundBuffer::waitRecvSlot(int* srcRank, uint64_t* slot,
                                 std::chrono::milliseconds timeout) {
  // One relaxed load when metrics are off; timestamps only when on.
  Metrics* metrics = context_->metrics();
  const bool measured = metrics != nullptr && metrics->enabled();
  const int64_t startUs = measured ? Tracer::nowUs() : 0;
  std::unique_lock<std::mutex> lock(mu_);
  auto pred = [&] {
    return !completedRecvs_.empty() || abortRecv_ || failed_;
  };
  auto onStall = [this](int64_t waitedUs) {
    context_->reportStall(this, /*isSend=*/false, waitedUs);
  };
  if (!waitFor(lock, pred, timeout, onStall)) {
    TC_THROW(TimeoutException, "waitRecv timed out after ", timeout.count(),
             "ms");
  }
  if (failed_ && completedRecvs_.empty()) {
    TC_THROW(IoException, error_);
  }
  if (abortRecv_ && completedRecvs_.empty()) {
    return false;
  }
  TC_ENFORCE(!completedRecvs_.empty());
  const RecvDone done = completedRecvs_.front();
  const int src = done.srcRank;
  if (srcRank != nullptr) {
    *srcRank = src;
  }
  if (slot != nullptr) {
    *slot = done.slot;
  }
  completedRecvs_.pop_front();
  if (measured) {
    // Per-peer wait latency: the "which link is slow" histogram.
    metrics->recordRecvWait(src, Tracer::nowUs() - startUs);
  }
  return true;
}

bool UnboundBuffer::waitPutArrival(int* srcRank,
                                   std::chrono::milliseconds timeout) {
  std::unique_lock<std::mutex> lock(mu_);
  auto pred = [&] {
    return !putArrivals_.empty() || abortRecv_ || failed_;
  };
  auto onStall = [this](int64_t waitedUs) {
    context_->reportStall(this, /*isSend=*/false, waitedUs);
  };
  if (!waitFor(lock, pred, timeout, onStall)) {
    TC_THROW(TimeoutException, "waitPutArrival timed out after ",
             timeout.count(), "ms");
  }
  if (failed_ && putArrivals_.empty()) {
    TC_THROW(IoException, error_);
  }
  if (abortRecv_ && putArrivals_.empty()) {
    return false;
  }
  if (srcRank != nullptr) {
    *srcRank = putArrivals_.front();
  }
  putArrivals_.pop_front();
  return true;
}

void UnboundBuffer::abortWaitSend() {
  {
    std::lock_guard<std::mutex> guard(mu_);
    abortSend_ = true;
    cv_.notify_all();
  }
}

void UnboundBuffer::abortWaitRecv() {
  {
    std::lock_guard<std::mutex> guard(mu_);
    abortRecv_ = true;
    cv_.notify_all();
  }
}

void UnboundBuffer::onSendComplete() {
  {
    std::lock_guard<std::mutex> guard(mu_);
    pendingSends_--;
    completedSends_++;
    cv_.notify_all();
  }
}

void UnboundBuffer::onRegionPutArrived(int srcRank) {
  std::lock_guard<std::mutex> guard(mu_);
  putArrivals_.push_back(srcRank);
  cv_.notify_all();
}

void UnboundBuffer::onRecvComplete(int srcRank, uint64_t slot) {
  {
    std::lock_guard<std::mutex> guard(mu_);
    pendingRecvs_--;
    completedRecvs_.push_back(RecvDone{srcRank, slot});
    cv_.notify_all();
  }
}

void UnboundBuffer::onSendError(const std::string& message) {
  {
    std::lock_guard<std::mutex> guard(mu_);
    pendingSends_--;
    failed_ = true;
    error_ = message;
    cv_.notify_all();
  }
}

void UnboundBuffer::onRecvError(const std::string& message) {
  {
    std::lock_guard<std::mutex> guard(mu_);
    pendingRecvs_--;
    failed_ = true;
    error_ = message;
    cv_.notify_all();
  }
}

void UnboundBuffer::addPendingSend() {
  std::lock_guard<std::mutex> guard(mu_);
  pendingSends_++;
}

void UnboundBuffer::addPendingRecv() {
  std::lock_guard<std::mutex> guard(mu_);
  pendingRecvs_++;
}

void UnboundBuffer::cancelPendingSend() {
  {
    std::lock_guard<std::mutex> guard(mu_);
    pendingSends_--;
    cv_.notify_all();
  }
}

void UnboundBuffer::cancelPendingRecv() {
  {
    std::lock_guard<std::mutex> guard(mu_);
    pendingRecvs_--;
    cv_.notify_all();
  }
}

}  // namespace transport
}  // namespace tpucoll
