// Event-loop engines: one I/O dispatch thread per transport device. All
// async socket I/O dispatch happens on that thread; user threads only
// enqueue work and block on condition variables (the reference's design
// point, gloo/transport/tcp/loop.cc:103-220).
//
// Two engines implement the same contract:
//  - EpollLoop: epoll + eventfd wakeup + tick-barrier unregister (the
//    flagship, default).
//  - UringLoop (loop_uring.h): io_uring with oneshot poll re-armed after
//    every dispatch — re-arming re-checks readiness, which preserves the
//    LEVEL-TRIGGERED semantics the pair's read budget depends on
//    (pair.cc kReadBudget stops mid-stream and relies on re-notification).
//    This is the modern-Linux answer to the reference's alternative
//    event-engine tier (gloo/transport/uv, libuv on epoll's behalf).
// Selection: DeviceAttr.engine or TPUCOLL_ENGINE = epoll|uring|auto.
#pragma once

#include <sys/uio.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace tpucoll {
namespace transport {

class Handler {
 public:
  virtual ~Handler() = default;
  virtual void handleEvents(uint32_t events) = 0;
  // Data-path completion (engines where hasDataPath(); loop thread):
  // result of an asyncRecv/asyncSend — bytes transferred, 0 = EOF (recv),
  // negative = -errno. Default: readiness-only handlers never see it.
  virtual void handleIoComplete(bool isRecv, int32_t res) {
    (void)isRecv;
    (void)res;
  }
};

class Loop {
 public:
  virtual ~Loop() = default;

  // Register fd. `events` is an EPOLL* mask (engines translate). The
  // handler must outlive the registration. Level-triggered semantics:
  // a handler that returns with the fd still ready is re-notified.
  virtual void add(int fd, uint32_t events, Handler* handler) = 0;
  virtual void mod(int fd, uint32_t events, Handler* handler) = 0;

  // Remove fd. On return it is guaranteed no handler dispatch for this fd
  // is in flight (unless called from the loop thread itself, where that is
  // trivially true).
  virtual void del(int fd) = 0;

  // busyPoll: spin instead of sleeping in the kernel — the reference's
  // sync/busy-poll latency mode (gloo tcp/pair.cc:505 MSG_DONTWAIT),
  // traded CPU-for-latency at the device level because one loop thread
  // owns all sockets.
  virtual bool busyPoll() const = 0;

  // Run fn on the loop thread at the next tick.
  virtual void defer(std::function<void()> fn) = 0;

  // Wait until the loop has completed the current dispatch batch (no-op on
  // the loop thread). After it returns, no handler invocation that started
  // before the call is still in flight.
  virtual void barrier() = 0;

  virtual bool onLoopThread() const = 0;

  // "epoll" or "uring" (introspection / tests).
  virtual const char* engineName() const = 0;

  // Cumulative submission statistics since construction. For the uring
  // engine: `enters` = io_uring_enter syscalls, `sqes` = SQEs submitted
  // (I/O ops + polls + cancels), `cqes` = completions drained. The
  // sqes/enters ratio is the batching evidence: readiness engines pay
  // >=1 syscall per I/O op by construction, so a ratio > 1 can only
  // come from batched submission. Epoll engine reports zeros.
  struct EngineStats {
    uint64_t enters{0};
    uint64_t sqes{0};
    uint64_t cqes{0};
  };
  virtual EngineStats engineStats() const { return {}; }

  // ---- submission data path (uring engine) ----
  // hasDataPath(): the engine executes socket I/O from submitted ops
  // (batched SQEs, one io_uring_enter per dispatch batch) instead of
  // readiness + caller syscalls. Registered via addData (no poll is
  // armed); completions arrive at handler->handleIoComplete; del()
  // cancels outstanding ops and returns only once the kernel is done
  // with their buffers. At most ONE outstanding op per direction per fd;
  // buffers must stay valid until completion or del(fd).
  virtual bool hasDataPath() const { return false; }
  virtual void addData(int fd, Handler* handler);
  virtual void asyncRecv(int fd, void* buf, size_t len);
  virtual void asyncSend(int fd, const iovec* iov, int iovcnt);
};

// Engine factory. `engine`: "epoll", "uring", "auto", or "" (= TPUCOLL_ENGINE
// env if set, else auto). auto = epoll (the soaked default); an explicit
// "uring" throws if io_uring is unavailable (seccomp, old kernel) rather
// than silently running a different engine.
std::unique_ptr<Loop> makeLoop(bool busyPoll, const std::string& engine = "");

// Machinery both engines share: the dispatch thread, eventfd wakeup, the
// deferred-fn queue, and the tick barrier that backs the del() "no
// dispatch in flight" contract. The tick protocol is the subtle part of
// that contract — it lives HERE, once. Engines implement waitAndDispatch
// (block for events, dispatch handlers, return) and call startThread()
// at the end of their constructor; endOfBatch() runs after every
// dispatch batch.
class LoopBase : public Loop {
 public:
  explicit LoopBase(bool busyPoll);
  ~LoopBase() override;  // engines must call stopThread() in their dtor

  bool busyPoll() const override { return busyPoll_; }
  void defer(std::function<void()> fn) override;
  void barrier() override;
  bool onLoopThread() const override;

 protected:
  void startThread();
  void stopThread();  // idempotent: join the loop thread, release waiters
  // Write the wake eventfd (any thread). Engines watch wakeFd_ their own
  // way and must drain it when it fires.
  void wake();
  // tick_++/notify + run deferred fns. Engines call this after every
  // dispatch batch. Skipping it on EMPTY busy-poll spins is safe iff the
  // engine watches wakeFd_: barrier()/defer() write the eventfd first,
  // so any waiter forces a non-empty batch.
  void endOfBatch();

  int wakeFd_{-1};
  const bool busyPoll_;
  std::atomic<bool> stop_{false};
  std::mutex mu_;  // engines may extend its protection to their own state
  std::condition_variable cv_;

 private:
  // Engine body: block for events (or spin when busyPoll), dispatch
  // handlers, call endOfBatch() per batch; return when stop_ is set.
  virtual void run() = 0;

  std::thread thread_;
  bool joined_{false};
  uint64_t tick_{0};
  std::vector<std::function<void()>> deferred_;
};

class EpollLoop : public LoopBase {
 public:
  explicit EpollLoop(bool busyPoll = false);
  ~EpollLoop() override;

  void add(int fd, uint32_t events, Handler* handler) override;
  void mod(int fd, uint32_t events, Handler* handler) override;
  void del(int fd) override;
  const char* engineName() const override { return "epoll"; }

 private:
  void run() override;

  int epollFd_{-1};
};

}  // namespace transport
}  // namespace tpucoll
