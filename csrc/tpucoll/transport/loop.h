// Single epoll event-loop thread per transport device. All async socket I/O
// dispatch happens on this thread; user threads only enqueue work and block
// on condition variables (the reference's design point, gloo/transport/tcp/
// loop.cc:103-220, rebuilt with an eventfd wakeup and a tick-barrier
// unregister instead of deferred-function handshakes).
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace tpucoll {
namespace transport {

class Handler {
 public:
  virtual ~Handler() = default;
  virtual void handleEvents(uint32_t events) = 0;
};

class Loop {
 public:
  // busyPoll: spin on epoll_wait(0) instead of sleeping in the kernel —
  // the reference's sync/busy-poll latency mode (gloo tcp/pair.cc:505
  // MSG_DONTWAIT), traded CPU-for-latency at the device level here
  // because one loop thread owns all sockets.
  explicit Loop(bool busyPoll = false);
  ~Loop();

  // Register fd with the epoll set. `events` is an EPOLL* mask. The handler
  // must outlive the registration.
  void add(int fd, uint32_t events, Handler* handler);
  void mod(int fd, uint32_t events, Handler* handler);

  // Remove fd. On return it is guaranteed no handler dispatch for this fd is
  // in flight (unless called from the loop thread itself, where that is
  // trivially true). The barrier is a loop-generation tick: the caller waits
  // until the loop has passed through epoll_wait at least once more.
  void del(int fd);

  bool busyPoll() const { return busyPoll_; }

  // Run fn on the loop thread at the next tick.
  void defer(std::function<void()> fn);

  // Wait until the loop has completed the current dispatch batch (no-op on
  // the loop thread). After it returns, no handler invocation that started
  // before the call is still in flight.
  void barrier();

  bool onLoopThread() const;

 private:
  void run();
  void wake();

  int epollFd_{-1};
  int wakeFd_{-1};
  std::thread thread_;
  const bool busyPoll_;
  std::atomic<bool> stop_{false};

  std::mutex mu_;
  std::condition_variable cv_;
  uint64_t tick_{0};
  std::vector<std::function<void()>> deferred_;
};

}  // namespace transport
}  // namespace tpucoll
