#include "tpucoll/transport/pair.h"

#include <netinet/in.h>
#include <poll.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <cstring>

#include <thread>

#include "tpucoll/common/debug.h"
#include "tpucoll/common/env.h"
#include "tpucoll/common/hmac.h"
#include "tpucoll/fault/fault.h"
#include "tpucoll/transport/context.h"
#include "tpucoll/transport/device.h"
#include "tpucoll/transport/listener.h"
#include "tpucoll/transport/socket.h"

namespace tpucoll {
namespace transport {

namespace {

// Typed handshake failures so the retry loop classifies robustly instead
// of substring-matching error text.
struct AuthRejected : IoException {
  using IoException::IoException;
};
struct HandshakeEof : IoException {
  using IoException::IoException;
};

// Same-host detection for the shm payload plane: the connected socket's
// local and peer IPs are equal exactly when both endpoints live on this
// machine (loopback, or a connection to the host's own address — the only
// way peer IP == my IP). False negatives (multi-homed exotica) merely skip
// the fast path; false positives are impossible.
bool sameHostFd(int fd) {
  sockaddr_storage a{}, b{};
  socklen_t alen = sizeof(a), blen = sizeof(b);
  if (getsockname(fd, reinterpret_cast<sockaddr*>(&a), &alen) != 0 ||
      getpeername(fd, reinterpret_cast<sockaddr*>(&b), &blen) != 0 ||
      a.ss_family != b.ss_family) {
    return false;
  }
  if (a.ss_family == AF_INET) {
    return reinterpret_cast<sockaddr_in*>(&a)->sin_addr.s_addr ==
           reinterpret_cast<sockaddr_in*>(&b)->sin_addr.s_addr;
  }
  if (a.ss_family == AF_INET6) {
    return std::memcmp(&reinterpret_cast<sockaddr_in6*>(&a)->sin6_addr,
                       &reinterpret_cast<sockaddr_in6*>(&b)->sin6_addr,
                       sizeof(in6_addr)) == 0;
  }
  return false;
}

}  // namespace

Pair::Pair(Context* context, Loop* loop, int selfRank, int peerRank,
           uint64_t localPairId, int channel, int loopIndex)
    : context_(context),
      loop_(loop),
      selfRank_(selfRank),
      peerRank_(peerRank),
      localPairId_(localPairId),
      channel_(channel),
      loopIndex_(loopIndex),
      dataPath_(loop->hasDataPath()) {}

// Striped-send completion routing (see pair.h StripeTx): a plain op
// completes its buffer directly; a stripe op only records its outcome,
// and the LAST stripe to resolve delivers the single logical
// completion/error. Deferring the error to the last resolution is
// load-bearing: it keeps the buffer's pending-send count nonzero while
// any sibling stripe still transmits from the buffer's memory, so
// ~UnboundBuffer cannot free bytes a loop thread is reading.
void Pair::finalizeStripe(const TxDone& d) {
  if (d.ubuf == nullptr) {
    return;
  }
  if (d.stripe->failed.load(std::memory_order_acquire)) {
    std::string msg;
    {
      std::lock_guard<std::mutex> guard(d.stripe->errMu);
      msg = d.stripe->error;
    }
    d.ubuf->onSendError(msg);
  } else {
    d.ubuf->onSendComplete();
  }
}

void Pair::deliverSendComplete(const TxDone& d) {
  if (d.stripe == nullptr) {
    if (d.ubuf != nullptr) {
      d.ubuf->onSendComplete();
    }
    return;
  }
  // Acq-rel: the finalizing decrement must observe the other
  // channels' writes; each decrement publishes its own.
  if (d.stripe->remaining.fetch_sub(1, std::memory_order_acq_rel) == 1) {
    finalizeStripe(d);
  }
}

void Pair::deliverSendError(const TxDone& d, const std::string& msg) {
  if (d.stripe == nullptr) {
    if (d.ubuf != nullptr) {
      d.ubuf->onSendError(msg);
    }
    return;
  }
  d.stripe->recordError(msg);
  if (d.stripe->remaining.fetch_sub(1, std::memory_order_acq_rel) == 1) {
    finalizeStripe(d);
  }
}

Pair::~Pair() {
  close();
  // A teardown started on the loop thread (EOF, tx error) may still be
  // executing after close() early-returns; quiesce before freeing members.
  loop_->barrier();
}

void Pair::connect(const SockAddr& remote, uint64_t remotePairId,
                   std::chrono::milliseconds timeout) {
  static constexpr std::chrono::milliseconds kBackoff{50};
  // Clean EOF mid-handshake is ambiguous: a peer restarting during
  // bootstrap (retryable) or a permanent auth/encryption tier mismatch
  // (terminal). Bounded retries resolve the ambiguity without burning
  // the whole deadline on a misconfiguration.
  static constexpr int kMaxEofRetries = 3;
  const auto deadline = std::chrono::steady_clock::now() + timeout;
  // Strict flag (common/env.h): historically "any set value" meant
  // disabled, so =0 disabled retries too; now only 0/1 parse.
  const bool retriesDisabled =
      envFlag("TPUCOLL_DISABLE_CONNECTION_RETRIES", false);
  int attempt = 0;
  int eofAttempts = 0;
  while (true) {
    attempt++;
    ConnectDebugData d;
    d.selfRank = selfRank_;
    d.peerRank = peerRank_;
    d.remote = remote.str();
    d.attempt = attempt;
    try {
      const int64_t handshakeStartUs = Tracer::nowUs();
      connectAttempt(remote, remotePairId, deadline, &d.local);
      if (Metrics* m = context_->metrics()) {
        // Seed the link RTT estimate with the successful handshake
        // duration — an upper bound (a few protocol round trips) that
        // the shm credit plane refines where active. Failed attempts
        // never sample: they time the peer's boot, not the wire.
        m->recordLinkRtt(peerRank_, Tracer::nowUs() - handshakeStartUs);
      }
      d.ok = true;
      logConnectAttempt(d);
      return;
    } catch (const TimeoutException&) {
      d.error = "timed out";
      logConnectAttempt(d);
      throw;
    } catch (const AuthRejected& e) {
      // A live peer refuted the tag: terminal, retrying a wrong key is
      // noise.
      d.error = e.what();
      logConnectAttempt(d);
      throw;
    } catch (const HandshakeEof& e) {
      d.error = e.what();
      eofAttempts++;
      d.willRetry = !retriesDisabled && eofAttempts <= kMaxEofRetries &&
                    std::chrono::steady_clock::now() + kBackoff < deadline;
      logConnectAttempt(d);
      if (!d.willRetry) {
        throw;
      }
      if (Metrics* m = context_->metrics()) {
        m->recordRetry();
      }
      std::this_thread::sleep_for(kBackoff);
    } catch (const IoException& e) {
      // Refused/reset/poll errors: the peer is still coming up; retry
      // until the deadline.
      d.error = e.what();
      d.willRetry = !retriesDisabled &&
                    std::chrono::steady_clock::now() + kBackoff < deadline;
      logConnectAttempt(d);
      if (!d.willRetry) {
        throw;
      }
      if (Metrics* m = context_->metrics()) {
        m->recordRetry();
      }
      std::this_thread::sleep_for(kBackoff);
    }
  }
}

void Pair::connectAttempt(const SockAddr& remote, uint64_t remotePairId,
                          std::chrono::steady_clock::time_point deadline,
                          std::string* localAddr) {
  if (fault::armed()) {
    // A fired connect_refuse rule throws a retryable IoException here,
    // driving the same backoff/classification path a real refused or
    // reset handshake takes.
    fault::onConnect(selfRank_, peerRank_, context_->metrics(),
                     context_->tracer(), context_->faultDomain());
  }
  int fd = socket(remote.sa()->sa_family, SOCK_STREAM | SOCK_CLOEXEC, 0);
  TC_ENFORCE_GE(fd, 0, errnoString("socket"));
  setNonBlocking(fd);

  int rv = ::connect(fd, remote.sa(), remote.len);
  if (rv != 0 && errno != EINPROGRESS) {
    ::close(fd);
    TC_THROW(IoException, "connect to rank ", peerRank_, " at ", remote.str(),
             ": ", strerror(errno));
  }
  if (rv != 0) {
    // Await writability = connection established (or refused). Retry EINTR
    // against the remaining deadline; a real poll error is an IoException,
    // not a timeout.
    while (true) {
      pollfd pfd{fd, POLLOUT, 0};
      auto remaining = std::chrono::duration_cast<std::chrono::milliseconds>(
          deadline - std::chrono::steady_clock::now());
      int prv = poll(&pfd, 1, static_cast<int>(std::max<int64_t>(
                                  remaining.count(), 0)));
      if (prv > 0) {
        break;
      }
      if (prv == 0) {
        ::close(fd);
        TC_THROW(TimeoutException, "connect to rank ", peerRank_, " at ",
                 remote.str(), " timed out");
      }
      if (errno == EINTR) {
        continue;
      }
      int savedErrno = errno;
      ::close(fd);
      TC_THROW(IoException, "connect to rank ", peerRank_, " at ",
               remote.str(), ": poll: ", strerror(savedErrno));
    }
    int soErr = 0;
    socklen_t soLen = sizeof(soErr);
    getsockopt(fd, SOL_SOCKET, SO_ERROR, &soErr, &soLen);
    if (soErr != 0) {
      ::close(fd);
      TC_THROW(IoException, "connect to rank ", peerRank_, " at ",
               remote.str(), ": ", strerror(soErr));
    }
  }
  setNoDelay(fd);
  {
    SockAddr local;
    local.len = sizeof(local.ss);
    if (getsockname(fd, local.sa(), &local.len) == 0) {
      *localAddr = local.str();
    }
  }

  const std::string& authKey = context_->device()->authKey();
  auto writeAll = [&](const void* buf, size_t len, const char* what) {
    const char* p = static_cast<const char*>(buf);
    size_t sent = 0;
    while (sent < len) {
      ssize_t n = ::send(fd, p + sent, len - sent, MSG_NOSIGNAL);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          // Bound by the same handshake deadline readAll honors: a peer
          // that accepts but never drains must not stall connect forever.
          pollfd pfd{fd, POLLOUT, 0};
          int prv = poll(&pfd, 1, static_cast<int>(std::max<int64_t>(
              std::chrono::duration_cast<std::chrono::milliseconds>(
                  deadline - std::chrono::steady_clock::now()).count(), 0)));
          if (prv == 0) {
            ::close(fd);
            TC_THROW(TimeoutException, what, ": handshake write to rank ",
                     peerRank_, " timed out");
          }
          if (prv < 0 && errno != EINTR) {
            int savedErrno = errno;
            ::close(fd);
            TC_THROW(IoException, what, ": handshake poll: ",
                     strerror(savedErrno));
          }
          continue;
        }
        if (errno == EINTR) {
          continue;
        }
        ::close(fd);
        TC_THROW(IoException, what, " write to rank ", peerRank_, ": ",
                 strerror(errno));
      }
      sent += static_cast<size_t>(n);
    }
  };
  auto readAll = [&](void* buf, size_t len, const char* what) {
    char* p = static_cast<char*>(buf);
    size_t got = 0;
    while (got < len) {
      ssize_t n = ::recv(fd, p + got, len - got, 0);
      if (n == 0) {
        ::close(fd);
        TC_THROW(HandshakeEof, what, ": rank ", peerRank_,
                 " closed the connection during the handshake "
                 "(restarting peer, or auth/encryption tier mismatch)");
      }
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          pollfd pfd{fd, POLLIN, 0};
          int prv = poll(&pfd, 1, static_cast<int>(std::max<int64_t>(
              std::chrono::duration_cast<std::chrono::milliseconds>(
                  deadline - std::chrono::steady_clock::now()).count(), 0)));
          if (prv == 0) {
            ::close(fd);
            TC_THROW(TimeoutException, what, ": handshake with rank ",
                     peerRank_, " timed out");
          }
          if (prv < 0 && errno != EINTR) {
            int savedErrno = errno;
            ::close(fd);
            TC_THROW(IoException, what, ": handshake poll: ",
                     strerror(savedErrno));
          }
          continue;
        }
        if (errno == EINTR) {
          continue;
        }
        ::close(fd);
        TC_THROW(IoException, what, ": ", strerror(errno));
      }
      got += static_cast<size_t>(n);
    }
  };

  // Route this connection to the peer's expecting Pair; with a pre-shared
  // key or per-rank keyring, run the mutual challenge/response of wire.h
  // on top (and, when the device encrypts, derive the connection's AEAD
  // keys from it). When both endpoints share an IP, also offer the
  // shared-memory payload plane.
  const bool encrypt = context_->device()->encrypt();
  const Keyring& keyring = context_->device()->keyring();
  const bool ringTier = keyring.valid();
  // Extra data channels never negotiate shm: the shm plane lives on the
  // primary connection, and a pair whose payloads ride the shm ring
  // bypasses striping entirely. The topology mask (setShmPeers) gates
  // on top of the socket-level same-host probe, so a simulated
  // multi-host layout (TPUCOLL_HOST_ID) keeps its cross-"host" pairs on
  // TCP even though every process shares one machine.
  const bool offerShm = channel_ == 0 && shmEnabled() && sameHostFd(fd) &&
                        context_->shmPeerAllowed(peerRank_);
  const uint32_t magic =
      ringTier ? (encrypt ? kHelloRingEncMagic : kHelloRingMagic)
      : authKey.empty() ? kHelloMagic
      : encrypt         ? kHelloAuthEncMagic
                        : kHelloAuthMagic;
  WireHello hello{magic, offerShm ? kHelloFlagShmOffer : 0, remotePairId};
  writeAll(&hello, sizeof(hello), "hello");
  ConnKeys keys;
  if (ringTier || !authKey.empty()) {
    // Keyring tier: announce our identity, authenticate with the
    // pairwise key K[selfRank, peerRank] that exactly the two legitimate
    // endpoints hold, and bind both identities into the transcript. The
    // listener verifies possession AND (at routing) that the claimed
    // rank matches the slot, so a leaked keyring speaks only as its own
    // rank (common/keyring.h threat model; reference analog: per-process
    // TLS identity, gloo/transport/tcp/tls/context.h:25-42).
    std::string ringKey;
    if (ringTier) {
      try {
        TC_ENFORCE_EQ(keyring.rank(), selfRank_,
                      "keyring was derived for a different rank");
        ringKey = keyring.keyFor(peerRank_);
      } catch (...) {
        ::close(fd);  // every throw path here must release the socket
        throw;
      }
      const uint32_t self = static_cast<uint32_t>(selfRank_);
      writeAll(&self, sizeof(self), "rank intro");
    }
    const std::string& key = ringTier ? ringKey : authKey;
    uint8_t nonceI[kAuthNonceBytes];
    randomBytes(nonceI, sizeof(nonceI));
    writeAll(nonceI, sizeof(nonceI), "auth nonce");

    uint8_t reply[kAuthNonceBytes + kAuthMacBytes];
    readAll(reply, sizeof(reply), "auth challenge");
    auto transcript = [&](const char* role) {
      std::string msg(role);
      msg.append(reinterpret_cast<const char*>(&remotePairId),
                 sizeof(remotePairId));
      if (ringTier) {
        const int32_t self = selfRank_;
        const int32_t peer = peerRank_;
        msg.append(reinterpret_cast<const char*>(&self), sizeof(self));
        msg.append(reinterpret_cast<const char*>(&peer), sizeof(peer));
      }
      msg.append(reinterpret_cast<const char*>(nonceI), kAuthNonceBytes);
      msg.append(reinterpret_cast<const char*>(reply), kAuthNonceBytes);
      return hmacSha256(key.data(), key.size(), msg.data(), msg.size());
    };
    auto srvExpect = transcript("srv");
    if (!macEqual(reply + kAuthNonceBytes, srvExpect.data(),
                  kAuthMacBytes)) {
      ::close(fd);
      TC_THROW(AuthRejected, "rank ", peerRank_,
               " failed authentication (bad server tag)");
    }
    auto cliMac = transcript("cli");
    writeAll(cliMac.data(), cliMac.size(), "auth tag");
    if (encrypt) {
      keys = deriveConnKeys(key, remotePairId, nonceI, reply,
                            /*initiator=*/true);
    }
  }
  std::unique_ptr<ShmSegment> shmSeg;
  if (offerShm) {
    // The hello promised an offer, so one is always sent; a failed segment
    // creation degenerates to a zero-length name the listener rejects. Any
    // throw below closes the fd and the local unique_ptr unlinks + unmaps.
    try {
      shmSeg = ShmSegment::create(remotePairId, shmRingBytesConfig());
    } catch (const IoException& e) {
      TC_WARN("shm segment creation failed, using TCP payloads: ", e.what());
    }
    WireShmOffer offer{kShmOfferMagic,
                       shmSeg ? static_cast<uint32_t>(shmSeg->name().size())
                              : 0,
                       shmSeg ? shmSeg->ringBytes() : 0};
    writeAll(&offer, sizeof(offer), "shm offer");
    if (shmSeg) {
      writeAll(shmSeg->name().data(), shmSeg->name().size(), "shm name");
    }
    uint8_t verdict = kShmReject;
    readAll(&verdict, sizeof(verdict), "shm verdict");
    if (shmSeg) {
      // The peer either has the segment open or refused it; the filesystem
      // name has served its purpose either way.
      shmSeg->unlinkName();
    }
    if (verdict != kShmAccept) {
      shmSeg.reset();
    }
  }
  assumeConnected(fd, keys, std::move(shmSeg), /*shmInitiator=*/true);
}

void Pair::expectViaListener(Listener* listener) {
  expectedAt_ = listener;
  listener->expect(localPairId_, this);
}

void Pair::assumeConnected(int fd, const ConnKeys& keys,
                           std::unique_ptr<ShmSegment> shm,
                           bool shmInitiator) {
  setNonBlocking(fd);
  setBufferSizes(fd, 4 << 20);
  bool accepted = false;
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (state_.load(std::memory_order_acquire) == State::kInitializing) {
      if (shm != nullptr) {
        shm_ = std::move(shm);
        shmTx_ = shm_->ring(shmInitiator ? 0 : 1);
        shmRx_ = shm_->ring(shmInitiator ? 1 : 0);
        shmActive_.store(true, std::memory_order_relaxed);
        TC_DEBUG("rank ", selfRank_, ": shm payload plane to rank ",
                 peerRank_, " (", shm_->ringBytes() >> 20, " MiB/dir)");
      }
      keys_ = keys;
      // Release: connecting publishes the fields set above (keys_,
      // shm rings, fd_) to lock-free acquire-loads of state_/fd_.
      fd_.store(fd, std::memory_order_release);
      epollMask_ = EPOLLIN;
      everConnected_.store(true, std::memory_order_release);
      state_.store(State::kConnected, std::memory_order_release);
      if (dataPath_) {
        // Submission mode: no readiness poll; register for completions
        // and post the first header recv. Safe off the loop thread: no
        // op is outstanding yet, so the rx cursors are quiescent.
        loop_->addData(fd, this);
        maybePostRecvLocked();
      } else {
        loop_->add(fd, EPOLLIN, this);
      }
      accepted = true;
    }
  }
  if (!accepted) {
    ::close(fd);  // pair was closed while the connection was in flight
    return;
  }
  cv_.notify_all();
}

void Pair::waitConnected(std::chrono::milliseconds timeout) {
  std::unique_lock<std::mutex> lock(mu_);
  auto pred = [&] { return state_.load(std::memory_order_acquire) != State::kInitializing; };
  if (!cv_.wait_for(lock, timeout, pred)) {
    TC_THROW(TimeoutException, "rank ", selfRank_,
             ": timed out connecting pair to rank ", peerRank_);
  }
  State s = state_.load(std::memory_order_acquire);
  if (s != State::kConnected && !everConnected_.load(std::memory_order_acquire)) {
    TC_THROW(IoException, "pair to rank ", peerRank_, " failed: ", error_);
  }
  // A pair that connected and already saw the peer depart counts as
  // connected: everything the peer sent is staged in the context stash, so
  // receive-only schedules against it still complete.
}

// Apply a fault decision to an outbound message: one shared slow path
// behind the armed() gate in send/sendPut. Returns false when the
// message must not be enqueued at all (kill). A truncated op keeps its
// claimed header.nbytes but transmits only truncateToBytes; the caller
// then fails the pair so the receiver observes EOF mid-message. A
// corrupted op keeps its real length but carries a poisoned magic (on
// encrypted connections the corrupt header is sealed normally, so the
// frame authenticates and the receiver still hits the magic check —
// "protocol violation from rank N" on every tier).
bool Pair::applyTxFault(const fault::TxDecision& fd, TxOp* op) {
  if (fd.kill) {
    fail(fault::killMessage(peerRank_));
    return false;  // enqueue would throw; the caller raises instead
  }
  if (fd.corrupt) {
    op->header.magic ^= fault::kCorruptMagicMask;
  }
  if (fd.truncate) {
    op->nbytes = fd.truncateToBytes;
    // Truncation is a byte-stream fault: keep it off the shm plane,
    // where announced chunk totals (not EOF) delimit the message and a
    // short payload would park the receiver on the ring instead of
    // failing loudly.
    if (op->viaShm) {
      op->viaShm = false;
      op->header.opcode = static_cast<uint8_t>(
          op->header.opcode == static_cast<uint8_t>(Opcode::kShmPut)
              ? Opcode::kPut
              : Opcode::kData);
    }
  }
  return true;
}

// Post-enqueue fault tail: emit the duplicate copy and/or sever the
// stream after a truncated message was flushed.
void Pair::finishTxFault(const fault::TxDecision& fd,
                         const WireHeader& cleanHeader, const char* data,
                         size_t nbytes) {
  if (fd.duplicate) {
    try {
      sendOwned(cleanHeader, std::vector<char>(data, data + nbytes));
    } catch (const std::exception&) {
      // Pair failed/closing between the two enqueues: the dup fault
      // degenerates to a no-op, never to a new error.
    }
  }
  if (fd.truncate) {
    fail(fault::truncateMessage(peerRank_));
  }
}

void Pair::send(UnboundBuffer* ubuf, uint64_t slot, const char* data,
                size_t nbytes) {
  if (__builtin_expect(fault::armed(), 0)) {
    // Cold, self-contained: the disarmed hot path pays exactly this one
    // predictable check (fault.h cost contract), nothing else.
    sendFaulted(ubuf, slot, data, nbytes);
    return;
  }
  const bool viaShm = shmActive_.load(std::memory_order_relaxed) &&
                      nbytes >= shmThresholdBytes();
  TxOp op;
  op.header = WireHeader{
      kMsgMagic,
      static_cast<uint8_t>(viaShm ? Opcode::kShmData : Opcode::kData),
      0, {0, 0}, slot, nbytes, 0};
  op.ubuf = ubuf;
  op.data = data;
  op.nbytes = nbytes;
  op.viaShm = viaShm;
  enqueue(std::move(op));
}

void Pair::sendFaulted(UnboundBuffer* ubuf, uint64_t slot,
                       const char* data, size_t nbytes) {
  fault::TxDecision fd = fault::onTxMessage(
      selfRank_, peerRank_, static_cast<uint8_t>(Opcode::kData), slot,
      nbytes, context_->metrics(), context_->tracer(), channel_,
      context_->faultDomain());
  const bool viaShm = shmActive_.load(std::memory_order_relaxed) &&
                      nbytes >= shmThresholdBytes();
  TxOp op;
  op.header = WireHeader{
      kMsgMagic,
      static_cast<uint8_t>(viaShm ? Opcode::kShmData : Opcode::kData),
      0, {0, 0}, slot, nbytes, 0};
  op.ubuf = ubuf;
  op.data = data;
  op.nbytes = nbytes;
  op.viaShm = viaShm;
  if (!applyTxFault(fd, &op)) {
    TC_THROW(IoException, "send to rank ", peerRank_, ": ",
             fault::killMessage(peerRank_));
  }
  enqueue(std::move(op));
  if (fd.duplicate || fd.truncate) {
    WireHeader clean{kMsgMagic, static_cast<uint8_t>(Opcode::kData),
                     0, {0, 0}, slot, nbytes, 0};
    finishTxFault(fd, clean, data, nbytes);
  }
}

// One stripe of a striped logical message. The header is fully
// self-describing (wire.h kStripe): the receiver reassembles from
// (slot, seqLow, total, count, index) alone, so sender and receiver
// need no out-of-band channel agreement beyond the connection count.
void Pair::sendStripe(UnboundBuffer* ubuf, uint64_t slot, const char* data,
                      size_t nbytes, uint64_t total, uint8_t count,
                      uint8_t seqLow, std::shared_ptr<StripeTx> st) {
  TxOp op;
  op.header = WireHeader{kMsgMagic, static_cast<uint8_t>(Opcode::kStripe),
                         seqLow,
                         {static_cast<uint8_t>(channel_), count},
                         slot, nbytes, total};
  op.ubuf = ubuf;
  op.data = data;
  op.nbytes = nbytes;
  op.stripe = std::move(st);
  if (__builtin_expect(fault::armed(), 0)) {
    // Stripes match fault rules as DATA traffic (the opcode schedules
    // name), with per-(rule, rank, channel) state keeping each
    // channel's firing sequence deterministic. `dup` is counted in the
    // report but not materialized: a duplicated stripe would violate
    // reassembly's exactly-once-per-(message, channel) contract
    // (docs/faults.md).
    fault::TxDecision fd = fault::onTxMessage(
        selfRank_, peerRank_, static_cast<uint8_t>(Opcode::kData), slot,
        nbytes, context_->metrics(), context_->tracer(), channel_,
        context_->faultDomain());
    if (!applyTxFault(fd, &op)) {
      TC_THROW(IoException, "send to rank ", peerRank_, ": ",
               fault::killMessage(peerRank_));
    }
    enqueue(std::move(op));
    if (fd.truncate) {
      // finishTxFault is deliberately not used here: its dup arm would
      // materialize a second stripe; only the post-flush sever applies.
      fail(fault::truncateMessage(peerRank_));
    }
    return;
  }
  enqueue(std::move(op));
}

void Pair::sendPut(UnboundBuffer* ubuf, uint64_t token, uint64_t roffset,
                   const char* data, size_t nbytes, bool notify,
                   std::shared_ptr<StripeTx> st) {
  if (__builtin_expect(fault::armed(), 0)) {
    sendPutFaulted(ubuf, token, roffset, data, nbytes, notify,
                   std::move(st));
    return;
  }
  const bool viaShm = shmActive_.load(std::memory_order_relaxed) &&
                      nbytes >= shmThresholdBytes();
  TxOp op;
  op.header = WireHeader{
      kMsgMagic,
      static_cast<uint8_t>(viaShm ? Opcode::kShmPut : Opcode::kPut),
      notify ? kPutFlagNotify : uint8_t(0), {0, 0},
      token, nbytes, roffset};
  op.ubuf = ubuf;
  op.data = data;
  op.nbytes = nbytes;
  op.viaShm = viaShm;
  op.stripe = std::move(st);
  enqueue(std::move(op));
}

void Pair::sendPutFaulted(UnboundBuffer* ubuf, uint64_t token,
                          uint64_t roffset, const char* data,
                          size_t nbytes, bool notify,
                          std::shared_ptr<StripeTx> st) {
  fault::TxDecision fd = fault::onTxMessage(
      selfRank_, peerRank_, static_cast<uint8_t>(Opcode::kPut), token,
      nbytes, context_->metrics(), context_->tracer(), channel_,
      context_->faultDomain());
  const bool viaShm = shmActive_.load(std::memory_order_relaxed) &&
                      nbytes >= shmThresholdBytes();
  TxOp op;
  op.header = WireHeader{
      kMsgMagic,
      static_cast<uint8_t>(viaShm ? Opcode::kShmPut : Opcode::kPut),
      notify ? kPutFlagNotify : uint8_t(0), {0, 0},
      token, nbytes, roffset};
  op.ubuf = ubuf;
  op.data = data;
  op.nbytes = nbytes;
  op.viaShm = viaShm;
  op.stripe = std::move(st);
  if (!applyTxFault(fd, &op)) {
    TC_THROW(IoException, "put to rank ", peerRank_, ": ",
             fault::killMessage(peerRank_));
  }
  enqueue(std::move(op));
  if (fd.duplicate || fd.truncate) {
    // A duplicated put re-writes the same bytes at the same offset —
    // idempotent for the DATA. The notification is not idempotent (each
    // notify-put completes one wait_put), so the duplicate always goes
    // out notify-less: dup perturbs the wire, never the app's
    // synchronization count.
    WireHeader clean{kMsgMagic, static_cast<uint8_t>(Opcode::kPut),
                     0, {0, 0}, token, nbytes, roffset};
    finishTxFault(fd, clean, data, nbytes);
  }
}

void Pair::sendOwned(WireHeader header, std::vector<char> payload) {
  TxOp op;
  op.header = header;
  op.ubuf = nullptr;
  op.nbytes = payload.size();
  // Large one-sided get responses (plain data messages with an op-owned
  // payload) take the shm fast path like any other bulk payload.
  if (header.opcode == static_cast<uint8_t>(Opcode::kData) &&
      shmActive_.load(std::memory_order_relaxed) &&
      payload.size() >= shmThresholdBytes()) {
    op.header.opcode = static_cast<uint8_t>(Opcode::kShmData);
    op.viaShm = true;
  }
  op.ownedData = std::move(payload);
  op.data = nullptr;  // fixed up after the move into the queue
  enqueue(std::move(op));
}

void Pair::touchProgress(bool tx, size_t bytes) {
  if (Metrics* m = context_->metrics()) {
    const int64_t now = Tracer::nowUs();
    m->touchProgress(peerRank_, now);
    m->touchLoop(loopIndex_, now);
    if (tx) {
      m->recordChannelTx(channel_, bytes);
    } else {
      m->recordChannelRx(channel_, bytes);
    }
    // Link-level split of the same movement: per-(peer, channel) bytes
    // plus the windowed EWMA bandwidth fold (fleet observability
    // plane). Same gate as the counters above — one relaxed load when
    // metrics are off.
    m->recordLink(peerRank_, channel_, tx, bytes, now);
  }
  if (FlightRecorder* fr = context_->flightrec()) {
    // Every payload/header byte moving through a pair funnels here —
    // including each stripe of a striped message on its own channel
    // pair — so the flight recorder's enqueued -> started transition
    // fires on the first progress of ANY stripe (one relaxed store,
    // and only on the first progress).
    fr->markTransportProgress();
  }
}

void Pair::enqueue(TxOp op) {
  std::vector<TxDone> completed;
  std::string txError;
  const size_t nbytes = op.nbytes;
  {
    std::lock_guard<std::mutex> guard(mu_);
    State s = state_.load(std::memory_order_acquire);
    if (s != State::kConnected || closing_) {
      TC_THROW(IoException, "send to rank ", peerRank_, ": pair ",
               s == State::kFailed ? error_
               : closing_          ? "is closing"
                                   : "is not connected");
    }
    tx_.push_back(std::move(op));
    if (tx_.back().data == nullptr && !tx_.back().ownedData.empty()) {
      // Owned payloads must point into the queued op (deque elements are
      // stable), not the moved-from local.
      tx_.back().data = tx_.back().ownedData.data();
    }
    if (tx_.size() == 1) {
      // Inline fast path: try to push the bytes out right here, skipping a
      // loop-thread wakeup when the socket has room (the common case).
      flushTx(&completed);
      if (state_.load(std::memory_order_acquire) == State::kConnected && !tx_.empty()) {
        updateEpollMask();
      }
    } else {
      updateEpollMask();
    }
    txError = pendingTxError_;
    pendingTxError_.clear();
  }
  if (Metrics* m = context_->metrics()) {
    m->recordSent(peerRank_, nbytes);
    // Post count for the link plane: enqueue intent, distinct from the
    // sentMsgs completion count (a growing gap is a backed-up link).
    m->recordLinkPost(peerRank_);
  }
  for (auto& d : completed) {
    deliverSendComplete(d);
  }
  if (!txError.empty()) {
    fail(txError);
  }
}

int Pair::cancelQueuedSends(UnboundBuffer* ubuf) {
  int removed = 0;      // LOGICAL sends released (pendingSend units)
  int removedWire = 0;  // wire messages dropped (metrics units)
  uint64_t removedBytes = 0;
  {
    std::lock_guard<std::mutex> guard(mu_);
    for (auto it = tx_.begin(); it != tx_.end();) {
      // txInFlight_: a submitted SQE references the front op's memory
      // even before any byte is confirmed — it must not be freed under
      // the kernel.
      const bool started =
          it == tx_.begin() &&
          (it->headerSent > 0 || it->headerSealed || txInFlight_);
      // Stripe ops are NEVER cancelled: a sibling stripe on another
      // channel pair may already be on the wire, and removing this one
      // would ship a partial message the receiver's reassembly waits on
      // forever. They resolve through wire completion or through
      // failPairsWithInflightSend failing this pair (hasInflightSend
      // sees the queued op), whose teardown errors the shared state.
      if (it->ubuf == ubuf && !started && it->stripe == nullptr) {
        removedBytes += it->nbytes;
        removedWire++;
        removed++;
        it = tx_.erase(it);
      } else {
        ++it;
      }
    }
  }
  if (removedWire > 0) {
    if (Metrics* m = context_->metrics()) {
      m->uncountSent(peerRank_, removedWire, removedBytes);
    }
  }
  return removed;
}

bool Pair::hasInflightSend(UnboundBuffer* ubuf) {
  std::lock_guard<std::mutex> guard(mu_);
  for (const auto& op : tx_) {
    if (op.ubuf == ubuf) {
      return true;
    }
  }
  return false;
}

bool Pair::sendSlotFor(UnboundBuffer* ubuf, uint64_t* slot) {
  std::lock_guard<std::mutex> guard(mu_);
  for (const auto& op : tx_) {
    if (op.ubuf == ubuf) {
      *slot = op.header.slot;
      return true;
    }
  }
  return false;
}

bool Pair::streamAtBoundary() const {
  if (tx_.empty()) {
    return true;
  }
  const TxOp& op = tx_.front();
  if (op.viaShm && op.announceDone) {
    // Between chunk headers of an shm message is a wire-message boundary:
    // control messages may preempt here (they carry no ordering).
    return !op.chunkInFlight;
  }
  return op.headerSent == 0 && !op.headerSealed;
}

void Pair::queueCtrl(Opcode opcode) { ctrlQ_.push_back(opcode); }

bool Pair::flushCtrl() {
  while (true) {
    if (ctrlSent_ < ctrlLen_) {
      iovec iov{ctrlBuf_ + ctrlSent_, ctrlLen_ - ctrlSent_};
      ssize_t n = txWrite(TxSite::kCtrl, &iov, 1);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          return false;
        }
        pendingTxError_ = errnoString("send");
        return false;
      }
      ctrlSent_ += static_cast<size_t>(n);
      continue;
    }
    if (ctrlQ_.empty() || !streamAtBoundary()) {
      return true;
    }
    WireHeader h{kMsgMagic, static_cast<uint8_t>(ctrlQ_.front()),
                 0, {0, 0}, 0, 0, 0};
    ctrlQ_.pop_front();
    if (keys_.encrypted) {
      uint8_t* p = reinterpret_cast<uint8_t*>(ctrlBuf_);
      aeadSeal(keys_.tx, txSeq_++, nullptr, 0,
               reinterpret_cast<const uint8_t*>(&h), sizeof(h), p,
               p + sizeof(h));
      ctrlLen_ = sizeof(WireHeader) + kAeadTagBytes;
    } else {
      std::memcpy(ctrlBuf_, &h, sizeof(h));
      ctrlLen_ = sizeof(WireHeader);
    }
    ctrlSent_ = 0;
  }
}

Pair::ShmTxStatus Pair::flushShmFront(TxOp* op,
                                      std::vector<TxDone>* completed) {
  // Sends a small header's bytes; returns kDone / kSocketFull / kError.
  auto pushBytes = [&](TxSite site, const char* base, size_t len,
                       size_t* sent) -> ShmTxStatus {
    while (*sent < len) {
      iovec iov{const_cast<char*>(base) + *sent, len - *sent};
      ssize_t n = txWrite(site, &iov, 1);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          return ShmTxStatus::kSocketFull;
        }
        pendingTxError_ = errnoString("send");
        return ShmTxStatus::kError;
      }
      *sent += static_cast<size_t>(n);
    }
    return ShmTxStatus::kDone;
  };

  if (!op->announceDone) {
    ShmTxStatus st;
    if (keys_.encrypted) {
      if (!op->headerSealed) {
        sealHeaderFrame(op);
      }
      st = pushBytes(TxSite::kFrontCipher, op->cipher.data(),
                     op->cipher.size(), &op->cipherSent);
    } else {
      st = pushBytes(TxSite::kFrontHeader,
                     reinterpret_cast<const char*>(&op->header),
                     sizeof(WireHeader), &op->headerSent);
    }
    if (st != ShmTxStatus::kDone) {
      return st;
    }
    op->announceDone = true;
  }

  // Chunks are capped at a quarter ring so the receiver starts draining
  // while later chunks are still being written (sender-copy / receiver-copy
  // overlap); a full-ring chunk would serialize the two memcpys.
  const uint64_t maxChunk =
      std::max<uint64_t>(shmTx_.cap / 4, uint64_t(64) << 10);
  while (true) {
    if (op->chunkInFlight) {
      ShmTxStatus st;
      if (keys_.encrypted) {
        st = pushBytes(TxSite::kFrontCipher, op->cipher.data(),
                       op->cipher.size(), &op->cipherSent);
      } else {
        st = pushBytes(TxSite::kFrontChunkHeader,
                       reinterpret_cast<const char*>(&op->chunkHeader),
                       sizeof(WireHeader), &op->chunkHeaderSent);
      }
      if (st != ShmTxStatus::kDone) {
        return st;
      }
      op->chunkInFlight = false;
    }
    if (op->shmAnnounced == op->nbytes) {
      completed->push_back(TxDone{op->ubuf, op->stripe});
      tx_.pop_front();  // op is dangling from here
      return ShmTxStatus::kDone;
    }
    const uint64_t want =
        std::min<uint64_t>(op->nbytes - op->shmWritten, maxChunk);
    const uint64_t w = shmTx_.write(op->data + op->shmWritten, want);
    if (w == 0) {
      // Ring full with nothing in flight to piggyback on: ask for an
      // explicit wakeup. By FIFO the receiver has consumed every chunk
      // announced before the request by the time it reads it, so its
      // credit always signals real space.
      if (!op->creditReqSent) {
        queueCtrl(Opcode::kShmCreditReq);
        op->creditReqSent = true;
        // Stamp the request so the matching kShmCredit grant yields a
        // link RTT sample (fleet observability plane).
        op->creditReqUs = Tracer::nowUs();
      }
      txRingBlocked_ = true;
      return ShmTxStatus::kRingBlocked;
    }
    op->creditReqSent = false;  // progress: a future stall re-requests
    op->shmWritten += w;
    shmTxBytes_.fetch_add(w, std::memory_order_relaxed);
    op->chunkHeader = WireHeader{kMsgMagic,
                                 static_cast<uint8_t>(Opcode::kShmChunk),
                                 0, {0, 0}, 0,
                                 op->shmWritten - op->shmAnnounced, 0};
    op->shmAnnounced = op->shmWritten;
    op->chunkHeaderSent = 0;
    if (keys_.encrypted) {
      op->cipher.resize(sizeof(WireHeader) + kAeadTagBytes);
      op->cipherSent = 0;
      uint8_t* p = reinterpret_cast<uint8_t*>(op->cipher.data());
      aeadSeal(keys_.tx, txSeq_++, nullptr, 0,
               reinterpret_cast<const uint8_t*>(&op->chunkHeader),
               sizeof(WireHeader), p, p + sizeof(WireHeader));
    }
    op->chunkInFlight = true;
  }
}

void Pair::flushTx(std::vector<TxDone>* completed) {
  if (fd_.load(std::memory_order_relaxed) < 0) {
    return;
  }
  while (true) {
    // The control channel first: finish any in-flight credit frame, then
    // emit queued ones whenever the data stream sits at a boundary.
    if (!flushCtrl()) {
      return;
    }
    if (tx_.empty()) {
      return;
    }
    if (tx_.front().viaShm) {
      ShmTxStatus st = flushShmFront(&tx_.front(), completed);
      if (st == ShmTxStatus::kDone) {
        continue;
      }
      if (st == ShmTxStatus::kRingBlocked) {
        flushCtrl();  // push the credit request out before parking
      }
      return;
    }
    TxOp& op = tx_.front();
    if (keys_.encrypted) {
      if (op.cipherSent == op.cipher.size()) {
        if (!op.headerSealed) {
          sealHeaderFrame(&op);
        } else if (op.sealOffset < op.nbytes) {
          sealPayloadFrame(&op);
        } else {
          completed->push_back(TxDone{op.ubuf, op.stripe});
          tx_.pop_front();
          continue;
        }
      }
      iovec civ{op.cipher.data() + op.cipherSent,
                op.cipher.size() - op.cipherSent};
      ssize_t n = txWrite(TxSite::kFrontCipher, &civ, 1);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          break;
        }
        pendingTxError_ = errnoString("send");
        return;
      }
      op.cipherSent += static_cast<size_t>(n);
      if (op.cipherSent == op.cipher.size() && op.headerSealed &&
          op.sealOffset == op.nbytes) {
        completed->push_back(TxDone{op.ubuf, op.stripe});
        tx_.pop_front();
      }
      continue;
    }
    iovec iov[2];
    int iovcnt = 0;
    if (op.headerSent < sizeof(WireHeader)) {
      iov[iovcnt].iov_base =
          reinterpret_cast<char*>(&op.header) + op.headerSent;
      iov[iovcnt].iov_len = sizeof(WireHeader) - op.headerSent;
      iovcnt++;
    }
    if (op.dataSent < op.nbytes) {
      iov[iovcnt].iov_base = const_cast<char*>(op.data) + op.dataSent;
      iov[iovcnt].iov_len = op.nbytes - op.dataSent;
      iovcnt++;
    }
    ssize_t n = 0;
    if (iovcnt > 0) {
      n = txWrite(TxSite::kFrontPlain, iov, iovcnt);
    }
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        break;
      }
      pendingTxError_ = errnoString("send");
      return;
    }
    size_t adv = static_cast<size_t>(n);
    size_t headerRemaining = sizeof(WireHeader) - op.headerSent;
    size_t take = std::min(adv, headerRemaining);
    op.headerSent += take;
    adv -= take;
    op.dataSent += adv;
    if (op.headerSent == sizeof(WireHeader) && op.dataSent == op.nbytes) {
      completed->push_back(TxDone{op.ubuf, op.stripe});
      tx_.pop_front();
    }
  }
}

void Pair::sealHeaderFrame(TxOp* op) {
  op->cipher.resize(sizeof(WireHeader) + kAeadTagBytes);
  op->cipherSent = 0;
  uint8_t* p = reinterpret_cast<uint8_t*>(op->cipher.data());
  aeadSeal(keys_.tx, txSeq_++, nullptr, 0,
           reinterpret_cast<const uint8_t*>(&op->header),
           sizeof(WireHeader), p, p + sizeof(WireHeader));
  op->headerSealed = true;
}

void Pair::sealPayloadFrame(TxOp* op) {
  const size_t chunk =
      std::min(kEncFrameBytes, op->nbytes - op->sealOffset);
  op->cipher.resize(chunk + kAeadTagBytes);
  op->cipherSent = 0;
  uint8_t* p = reinterpret_cast<uint8_t*>(op->cipher.data());
  aeadSeal(keys_.tx, txSeq_++, nullptr, 0,
           reinterpret_cast<const uint8_t*>(op->data + op->sealOffset),
           chunk, p, p + chunk);
  op->sealOffset += chunk;
}

// The socket-write primitive behind every flush site (see pair.h).
ssize_t Pair::txWrite(TxSite site, const iovec* iov, int iovcnt) {
  if (!dataPath_) {
    for (;;) {
      ssize_t n;
      if (iovcnt == 1) {
        n = ::send(fd_.load(std::memory_order_relaxed), iov[0].iov_base,
                   iov[0].iov_len, MSG_NOSIGNAL);
      } else {
        msghdr msg{};
        msg.msg_iov = const_cast<iovec*>(iov);
        msg.msg_iovlen = static_cast<size_t>(iovcnt);
        // MSG_NOSIGNAL: broken pipes become errors, never SIGPIPE.
        n = sendmsg(fd_.load(std::memory_order_relaxed), &msg,
                    MSG_NOSIGNAL);
      }
      if (n < 0 && errno == EINTR) {
        continue;
      }
      if (n > 0) {
        touchProgress(/*tx=*/true, static_cast<size_t>(n));
      }
      return n;
    }
  }
  // Data path: one sendmsg SQE in flight at a time. Reporting EAGAIN
  // makes every flush function stop exactly as on a full socket; the
  // completion advances the cursors (txAdvanceInFlight) and re-runs it.
  if (txInFlight_) {
    errno = EAGAIN;
    return -1;
  }
  loop_->asyncSend(fd_.load(std::memory_order_relaxed), iov, iovcnt);
  txInFlight_ = true;
  txSite_ = site;
  errno = EAGAIN;
  return -1;
}

void Pair::txAdvanceInFlight(size_t n) {
  if (n > 0) {
    touchProgress(/*tx=*/true, n);
  }
  switch (txSite_) {
    case TxSite::kCtrl:
      ctrlSent_ += n;
      return;
    case TxSite::kFrontHeader:
      tx_.front().headerSent += n;
      return;
    case TxSite::kFrontChunkHeader:
      tx_.front().chunkHeaderSent += n;
      return;
    case TxSite::kFrontCipher:
      tx_.front().cipherSent += n;
      return;
    case TxSite::kFrontPlain: {
      // The synchronous path's header/data split arithmetic.
      TxOp& op = tx_.front();
      size_t adv = n;
      const size_t take =
          std::min(adv, sizeof(WireHeader) - op.headerSent);
      op.headerSent += take;
      adv -= take;
      op.dataSent += adv;
      return;
    }
  }
}

void Pair::updateEpollMask() {
  if (dataPath_) {
    return;  // submissions replace readiness; nothing to arm
  }
  if (fd_.load(std::memory_order_relaxed) < 0 ||
      state_.load(std::memory_order_acquire) != State::kConnected) {
    return;
  }
  // EPOLLOUT only when socket progress is possible: a front op parked on
  // ring space has no bytes to write (its wakeup is the peer's credit),
  // but pending control frames always count.
  const bool txWants = ctrlSent_ < ctrlLen_ || !ctrlQ_.empty() ||
                       (!tx_.empty() && !txRingBlocked_);
  uint32_t desired = (rxPaused_ ? 0u : uint32_t(EPOLLIN)) |
                     (txWants ? uint32_t(EPOLLOUT) : 0u);
  if (desired != epollMask_) {
    loop_->mod(fd_.load(std::memory_order_relaxed), desired, this);
    epollMask_ = desired;
  }
}

void Pair::handleEvents(uint32_t events) {
  if (state_.load(std::memory_order_acquire) != State::kConnected) {
    return;
  }
  if (events & (EPOLLIN | EPOLLERR | EPOLLHUP)) {
    readLoop();
  }
  if (state_.load(std::memory_order_acquire) != State::kConnected) {
    return;
  }
  if (events & EPOLLOUT) {
    std::vector<TxDone> completed;
    std::string txError;
    {
      std::lock_guard<std::mutex> guard(mu_);
      flushTx(&completed);
      if (state_.load(std::memory_order_acquire) == State::kConnected) {
        updateEpollMask();
      }
      txError = pendingTxError_;
      pendingTxError_.clear();
    }
    cv_.notify_all();  // close() may be waiting for the tx queue to drain
    for (auto& d : completed) {
      deliverSendComplete(d);
    }
    if (!txError.empty()) {
      fail(txError);
    }
  }
}

Pair::RxWant Pair::rxWant() {
  if (!rxInPayload_) {
    const bool enc = keys_.encrypted;
    const size_t hdrWant =
        enc ? sizeof(rxHeaderCipher_) : sizeof(WireHeader);
    char* hp = enc ? reinterpret_cast<char*>(rxHeaderCipher_)
                   : reinterpret_cast<char*>(&rxHeader_);
    return {hp + rxHeaderRead_, hdrWant - rxHeaderRead_};
  }
  // Encrypted connections append a 16-byte tag after each payload frame's
  // ciphertext; the ciphertext itself lands in the final destination
  // (user memory or stash) and is decrypted in place once complete. The
  // destination is surfaced to the application only after the tag
  // verifies, so a tamperer can at worst poison the pair.
  const bool enc = keys_.encrypted;
  const size_t frameLen =
      enc ? std::min(kEncFrameBytes, rxHeader_.nbytes - rxPlainDone_)
          : rxHeader_.nbytes;
  const size_t frameTotal = frameLen + (enc ? kAeadTagBytes : 0);
  if (rxPayloadRead_ < frameLen) {
    return {rxDest_ + rxPlainDone_ + rxPayloadRead_,
            frameLen - rxPayloadRead_};
  }
  return {reinterpret_cast<char*>(rxPayloadTag_) +
              (rxPayloadRead_ - frameLen),
          frameTotal - rxPayloadRead_};
}

void Pair::onRxEof() {
  if (rxInPayload_) {
    fail(detail::strCat("connection to rank ", peerRank_,
                        " closed mid-message"));
    return;
  }
  bool orderly;
  {
    std::lock_guard<std::mutex> guard(mu_);
    orderly = peerGoodbye_;
  }
  if (orderly) {
    teardown(State::kClosed,
             detail::strCat("rank ", peerRank_, " left the group"),
             /*notifyContext=*/true);
  } else {
    fail(detail::strCat("connection to rank ", peerRank_,
                        " closed by peer unexpectedly"));
  }
}

Pair::RxStep Pair::processRxBytes(size_t n, size_t* consumed) {
  if (n > 0) {
    touchProgress(/*tx=*/false, n);
  }
  if (!rxInPayload_) {
    const bool enc = keys_.encrypted;
    const size_t hdrWant =
        enc ? sizeof(rxHeaderCipher_) : sizeof(WireHeader);
    rxHeaderRead_ += n;
    *consumed += n;
    if (rxHeaderRead_ < hdrWant) {
      return RxStep::kMore;
    }
    return processHeader(consumed);
  }
  const bool enc = keys_.encrypted;
  const size_t frameLen =
      enc ? std::min(kEncFrameBytes, rxHeader_.nbytes - rxPlainDone_)
          : rxHeader_.nbytes;
  const size_t frameTotal = frameLen + (enc ? kAeadTagBytes : 0);
  rxPayloadRead_ += n;
  *consumed += n;
  if (rxPayloadRead_ == frameTotal) {
    if (enc) {
      if (!aeadOpen(keys_.rx, rxSeq_++, nullptr, 0,
                    reinterpret_cast<uint8_t*>(rxDest_ + rxPlainDone_),
                    frameLen,
                    reinterpret_cast<uint8_t*>(rxDest_ + rxPlainDone_),
                    rxPayloadTag_)) {
        fail(detail::strCat("message authentication failed from rank ",
                            peerRank_));
        return RxStep::kStop;
      }
      if (rxFoldInline_ && rxCombine_ != nullptr) {
        // Fold this frame's just-verified plaintext into the
        // accumulator while it is still cache-hot (saves the cold
        // whole-stage re-read at finishMessage). frameLen is a
        // multiple of the element size: every non-final frame is
        // kEncFrameBytes (checked aligned when rxFoldInline_ was
        // set) and the final frame is nbytes minus a multiple of it,
        // with nbytes itself element-aligned by matchIncoming. The
        // accumulator offset is in ELEMENTS times ITS elsize — wire
        // and accumulator strides differ for typed recvReduce.
        const size_t elemsDone = rxPlainDone_ / rxCombineElsize_;
        rxCombine_(rxFinalDest_ + elemsDone * rxCombineAccElsize_,
                   rxDest_ + rxPlainDone_, frameLen / rxCombineElsize_);
      }
      rxPlainDone_ += frameLen;
      rxPayloadRead_ = 0;
      if (rxPlainDone_ < rxHeader_.nbytes) {
        return RxStep::kMore;  // more frames of this message
      }
    }
    finishMessage();
  }
  return RxStep::kMore;
}

// Header complete: decrypt/validate it and dispatch on the opcode. This
// is the former readLoop dispatch block, shared verbatim by both engines
// (kMore == the old `continue`, kStop == the old `return`).
Pair::RxStep Pair::processHeader(size_t* consumed) {
  const bool enc = keys_.encrypted;
  if (enc && !aeadOpen(keys_.rx, rxSeq_++, nullptr, 0, rxHeaderCipher_,
                       sizeof(WireHeader),
                       reinterpret_cast<uint8_t*>(&rxHeader_),
                       rxHeaderCipher_ + sizeof(WireHeader))) {
    fail(detail::strCat("message authentication failed from rank ",
                        peerRank_));
    return RxStep::kStop;
  }
  if (rxHeader_.magic != kMsgMagic) {
    fail(detail::strCat("protocol violation from rank ", peerRank_));
    return RxStep::kStop;
  }
  if (rxHeader_.opcode == static_cast<uint8_t>(Opcode::kGoodbye)) {
    {
      std::lock_guard<std::mutex> guard(mu_);
      peerGoodbye_ = true;
      if (lazyInbound_ && !closing_ &&
          state_.load(std::memory_order_acquire) == State::kConnected) {
        // Eviction handshake: answer the broker's goodbye at once so
        // its close() returns without waiting out the grace, then let
        // the EOF that follows tear this side down orderly.
        closing_ = true;
        TxOp op;
        op.header = WireHeader{kMsgMagic,
                               static_cast<uint8_t>(Opcode::kGoodbye),
                               0, {0, 0}, 0, 0};
        op.ubuf = nullptr;
        op.data = nullptr;
        op.nbytes = 0;
        tx_.push_back(op);
        std::vector<TxDone> completed;
        flushTx(&completed);  // goodbye carries no ubuf: nothing completes
        updateEpollMask();
        pendingTxError_.clear();
      }
    }
    cv_.notify_all();
    rxHeaderRead_ = 0;
    return RxStep::kMore;
  }
  // ---- shared-memory payload plane ----
  if (rxHeader_.opcode == static_cast<uint8_t>(Opcode::kShmCredit) ||
      rxHeader_.opcode == static_cast<uint8_t>(Opcode::kShmCreditReq)) {
    const bool isGrant =
        rxHeader_.opcode == static_cast<uint8_t>(Opcode::kShmCredit);
    std::vector<TxDone> completed;
    std::string txError;
    {
      std::lock_guard<std::mutex> guard(mu_);
      if (isGrant) {
        txRingBlocked_ = false;
        if (!tx_.empty() && tx_.front().viaShm) {
          TxOp& front = tx_.front();
          if (front.creditReqSent && front.creditReqUs != 0) {
            // Request -> grant round trip: the cheapest in-band RTT
            // probe this transport has (control header both ways, no
            // payload). Relaxed-atomic EWMA update, safe under mu_.
            if (Metrics* m = context_->metrics()) {
              m->recordLinkRtt(peerRank_,
                               Tracer::nowUs() - front.creditReqUs);
            }
            front.creditReqUs = 0;
          }
          front.creditReqSent = false;
        }
      } else {
        queueCtrl(Opcode::kShmCredit);
      }
      flushTx(&completed);
      if (state_.load(std::memory_order_acquire) == State::kConnected) {
        updateEpollMask();
      }
      txError = pendingTxError_;
      pendingTxError_.clear();
    }
    cv_.notify_all();
    for (auto& d : completed) {
      deliverSendComplete(d);
    }
    if (!txError.empty()) {
      fail(txError);
      return RxStep::kStop;
    }
    rxHeaderRead_ = 0;
    return RxStep::kMore;
  }
  if (shmRxActive_ &&
      rxHeader_.opcode != static_cast<uint8_t>(Opcode::kShmChunk)) {
    // The sender's FIFO guarantees chunk announcements are contiguous;
    // anything else mid-message is a protocol violation.
    fail(detail::strCat("message interleaved with shm chunks from rank ",
                        peerRank_));
    return RxStep::kStop;
  }
  if (rxHeader_.opcode == static_cast<uint8_t>(Opcode::kShmData) ||
      rxHeader_.opcode == static_cast<uint8_t>(Opcode::kShmPut)) {
    if (!shmActive_.load(std::memory_order_relaxed)) {
      fail(detail::strCat("shm message without a negotiated segment "
                          "from rank ", peerRank_));
      return RxStep::kStop;
    }
    const size_t nbytes = rxHeader_.nbytes;
    if (rxHeader_.opcode == static_cast<uint8_t>(Opcode::kShmPut)) {
      if (nbytes == 0) {
        if (!context_->writeRegion(rxHeader_.slot, rxHeader_.aux,
                                   nullptr, 0,
                                   rxHeader_.flags & kPutFlagNotify,
                                   peerRank_)) {
          fail(detail::strCat("one-sided put outside registered region "
                              "from rank ", peerRank_));
          return RxStep::kStop;
        }
        rxHeaderRead_ = 0;
        return RxStep::kMore;
      }
      shmRxActive_ = true;
      shmRxHeader_ = rxHeader_;
      shmRxTotal_ = nbytes;
      shmRxDone_ = 0;
      shmRxMode_ = RxMode::kPut;
      shmRxDest_ = nullptr;
      rxHeaderRead_ = 0;
      return RxStep::kMore;
    }
    Context::Match match;
    try {
      match = context_->matchIncoming(peerRank_, rxHeader_.slot, nbytes);
    } catch (const std::exception& e) {
      fail(detail::strCat("receive matching failed: ", e.what()));
      return RxStep::kStop;
    }
    if (nbytes == 0) {
      if (match.direct) {
        match.ubuf->onRecvComplete(peerRank_, rxHeader_.slot);
      } else {
        context_->stashArrived(peerRank_, rxHeader_.slot, {});
      }
      rxHeaderRead_ = 0;
      return RxStep::kMore;
    }
    shmRxActive_ = true;
    shmRxHeader_ = rxHeader_;
    shmRxTotal_ = nbytes;
    shmRxDone_ = 0;
    if (match.direct) {
      shmRxMode_ = RxMode::kDirect;
      shmRxDest_ = match.dest;
      shmRxCombine_ = match.combine;
      shmRxCombineElsize_ = match.combineElsize;
      shmRxCombineAccElsize_ = match.combineAccElsize;
      shmRxCarryLen_ = 0;
      std::lock_guard<std::mutex> guard(mu_);
      rxUbuf_ = match.ubuf;
    } else {
      shmRxMode_ = RxMode::kStash;
      shmRxStash_.resize(nbytes);
      shmRxDest_ = shmRxStash_.data();
      shmRxCombine_ = nullptr;
    }
    rxHeaderRead_ = 0;
    return RxStep::kMore;
  }
  if (rxHeader_.opcode == static_cast<uint8_t>(Opcode::kShmChunk)) {
    if (!shmRxActive_) {
      fail(detail::strCat("shm chunk without an announced message "
                          "from rank ", peerRank_));
      return RxStep::kStop;
    }
    const uint64_t chunk = rxHeader_.nbytes;
    if (chunk == 0 || chunk > shmRxTotal_ - shmRxDone_ ||
        chunk > shmRx_.usedBytes()) {
      fail(detail::strCat("shm chunk exceeds announced message or ring "
                          "contents from rank ", peerRank_));
      return RxStep::kStop;
    }
    bool ok = true;
    if (shmRxMode_ == RxMode::kPut) {
      // Ring spans land straight in the registered region (validated
      // per span under the context lock) — no staging copy.
      const uint64_t base = shmRxHeader_.aux + shmRxDone_;
      ok = shmRx_.consume(
          chunk, [&](const char* p, uint64_t len, uint64_t off) {
            return context_->writeRegion(shmRxHeader_.slot, base + off,
                                         p, len, false, peerRank_);
          });
    } else if (shmRxCombine_ != nullptr) {
      // Fused receive-reduce: fold ring spans into the destination in
      // place of the staging memcpy — the payload is touched exactly
      // once on this side.
      const uint64_t base = shmRxDone_;
      shmRx_.consume(chunk,
                     [&](const char* p, uint64_t len, uint64_t off) {
                       combineShmSpan(base + off, p, len);
                       return true;
                     });
    } else {
      char* dst = shmRxDest_ + shmRxDone_;
      shmRx_.consume(chunk,
                     [&](const char* p, uint64_t len, uint64_t off) {
                       std::memcpy(dst + off, p, len);
                       return true;
                     });
    }
    if (!ok) {
      fail(detail::strCat("one-sided put outside registered region "
                          "from rank ", peerRank_));
      return RxStep::kStop;
    }
    shmRxDone_ += chunk;
    shmRxBytes_.fetch_add(chunk, std::memory_order_relaxed);
    touchProgress(/*tx=*/false, chunk);
    *consumed += chunk;
    // Eager credit after draining a big chunk: the sender throttles on
    // ring space, and this lets it refill while we keep consuming.
    if (chunk * 8 >= shmRx_.cap) {
      std::vector<TxDone> completed;
      std::string txError;
      {
        std::lock_guard<std::mutex> guard(mu_);
        queueCtrl(Opcode::kShmCredit);
        flushTx(&completed);
        if (state_.load(std::memory_order_acquire) == State::kConnected) {
          updateEpollMask();
        }
        txError = pendingTxError_;
        pendingTxError_.clear();
      }
      cv_.notify_all();  // close() may be waiting on tx_ draining
      for (auto& d : completed) {
        deliverSendComplete(d);
      }
      if (!txError.empty()) {
        fail(txError);
        return RxStep::kStop;
      }
    }
    if (shmRxDone_ == shmRxTotal_) {
      shmRxActive_ = false;
      shmRxCombine_ = nullptr;  // carry is empty: nbytes % elsize == 0
      if (Metrics* m = context_->metrics()) {
        m->recordRecvd(peerRank_, shmRxTotal_);
      }
      switch (shmRxMode_) {
        case RxMode::kDirect: {
          UnboundBuffer* b = nullptr;
          {
            std::lock_guard<std::mutex> guard(mu_);
            b = rxUbuf_;
            rxUbuf_ = nullptr;
          }
          if (b != nullptr) {
            b->onRecvComplete(peerRank_, shmRxHeader_.slot);
          }
          break;
        }
        case RxMode::kStash:
          try {
            context_->stashArrived(peerRank_, shmRxHeader_.slot,
                                   std::move(shmRxStash_));
          } catch (const std::exception& e) {
            fail(detail::strCat("receive matching failed: ", e.what()));
            return RxStep::kStop;
          }
          shmRxStash_ = std::vector<char>();
          break;
        case RxMode::kPut:
          if (shmRxHeader_.flags & kPutFlagNotify) {
            // Zero-byte notify write: completes the exporting buffer's
            // waitRecv now that every chunk has landed.
            if (!context_->writeRegion(shmRxHeader_.slot,
                                       shmRxHeader_.aux, nullptr, 0,
                                       true, peerRank_)) {
              fail(detail::strCat("one-sided put outside registered "
                                  "region from rank ", peerRank_));
              return RxStep::kStop;
            }
          }
          break;
        default:
          break;
      }
    }
    rxHeaderRead_ = 0;
    return RxStep::kMore;
  }
  if (rxHeader_.opcode == static_cast<uint8_t>(Opcode::kStripe)) {
    // One contiguous stripe of a striped logical message: the context
    // hands back where this channel's share lands (user memory at the
    // stripe offset, or a reassembly/stage buffer) and an entry handle
    // the completion reports into. The span re-derivation doubles as
    // the protocol check — a header whose nbytes disagrees with the
    // deterministic split is a violation, not a different layout.
    const uint32_t count = rxHeader_.reserved[1];
    const uint32_t index = rxHeader_.reserved[0];
    const uint64_t total = rxHeader_.aux;
    if (count < 2 || count > kMaxStripeChannels || index >= count ||
        total < count ||
        rxHeader_.nbytes != stripeSpan(total, count, index)) {
      fail(detail::strCat("malformed stripe header from rank ", peerRank_));
      return RxStep::kStop;
    }
    Context::StripeMatch sm;
    try {
      sm = context_->stripeIncoming(peerRank_, rxHeader_.slot,
                                    rxHeader_.flags, total, count, index);
    } catch (const std::exception& e) {
      fail(detail::strCat("receive matching failed: ", e.what()));
      return RxStep::kStop;
    }
    rxInPayload_ = true;
    rxPayloadRead_ = 0;
    rxPlainDone_ = 0;
    rxMode_ = RxMode::kStripe;
    rxCombine_ = nullptr;
    rxFoldInline_ = false;
    rxDest_ = sm.dest;
    rxStripeEntry_ = sm.entry;
    return RxStep::kMore;
  }
  if (rxHeader_.opcode == static_cast<uint8_t>(Opcode::kPut)) {
    // One-sided write: payload staged then copied into the registered
    // region under the context lock (re-validated there, so a region
    // torn down mid-flight cannot be scribbled on).
    const size_t nbytes = rxHeader_.nbytes;
    if (nbytes == 0) {
      // Zero-byte puts still validate the token/offset: the same
      // contract violation must not pass or fail based on length.
      if (!context_->writeRegion(rxHeader_.slot, rxHeader_.aux,
                                 nullptr, 0,
                                 rxHeader_.flags & kPutFlagNotify,
                                 peerRank_)) {
        fail(detail::strCat("one-sided put outside registered region "
                            "from rank ", peerRank_));
        return RxStep::kStop;
      }
      rxHeaderRead_ = 0;
      return RxStep::kMore;
    }
    rxInPayload_ = true;
    rxPayloadRead_ = 0;
    rxPlainDone_ = 0;
    rxMode_ = RxMode::kPut;
    rxStashData_.resize(nbytes);
    rxDest_ = rxStashData_.data();
    return RxStep::kMore;
  }
  if (rxHeader_.opcode == static_cast<uint8_t>(Opcode::kGetReq)) {
    if (rxHeader_.nbytes != sizeof(WireGetReq)) {
      fail(detail::strCat("malformed get request from rank ",
                          peerRank_));
      return RxStep::kStop;
    }
    rxInPayload_ = true;
    rxPayloadRead_ = 0;
    rxPlainDone_ = 0;
    rxMode_ = RxMode::kGetReq;
    rxStashData_.resize(sizeof(WireGetReq));
    rxDest_ = rxStashData_.data();
    return RxStep::kMore;
  }
  if (rxHeader_.opcode != static_cast<uint8_t>(Opcode::kData)) {
    fail(detail::strCat("protocol violation from rank ", peerRank_));
    return RxStep::kStop;
  }
  const size_t nbytes = rxHeader_.nbytes;
  Context::Match match;
  try {
    match = context_->matchIncoming(peerRank_, rxHeader_.slot, nbytes);
  } catch (const std::exception& e) {
    // e.g. posted-size mismatch: an application-level contract violation
    // (inconsistent counts across ranks). Poison this pair instead of
    // unwinding through the event loop.
    fail(detail::strCat("receive matching failed: ", e.what()));
    return RxStep::kStop;
  }
  if (nbytes == 0) {
    if (match.direct) {
      match.ubuf->onRecvComplete(peerRank_, rxHeader_.slot);
    } else {
      context_->stashArrived(peerRank_, rxHeader_.slot, {});
    }
    rxHeaderRead_ = 0;
    return RxStep::kMore;
  }
  rxInPayload_ = true;
  rxPayloadRead_ = 0;
  rxPlainDone_ = 0;
  if (match.direct) {
    rxMode_ = RxMode::kDirect;
    rxCombine_ = match.combine;
    rxCombineElsize_ = match.combineElsize;
    rxCombineAccElsize_ = match.combineAccElsize != 0
                              ? match.combineAccElsize
                              : match.combineElsize;
    if (match.combine != nullptr) {
      // recvReduce over the byte stream: partial reads (and in-place
      // ciphertext) must never touch the accumulator, so the payload
      // stages first. Plaintext connections fold the stage at message
      // completion; encrypted ones fold per verified frame (see
      // rxFoldInline_ in pair.h) when frames are element-aligned —
      // kEncFrameBytes is 4-KiB-aligned, so only exotic custom-fn
      // element sizes fall back to the completion fold. Typed
      // recvReduce (wire elsize != accumulator elsize, e.g. the
      // bf16-wire ring) folds at ELEMENT offsets — each side scaled by
      // its own elsize.
      rxFinalDest_ = match.dest;
      rxFoldInline_ = keys_.encrypted &&
                      kEncFrameBytes % match.combineElsize == 0;
      if (rxCombineStage_.size() < nbytes) {
        rxCombineStage_.resize(nbytes);
      }
      rxDest_ = rxCombineStage_.data();
    } else {
      rxDest_ = match.dest;
    }
    std::lock_guard<std::mutex> guard(mu_);
    rxUbuf_ = match.ubuf;
  } else {
    rxMode_ = RxMode::kStash;
    rxStashData_.resize(nbytes);
    rxDest_ = rxStashData_.data();
  }
  return RxStep::kMore;
}

void Pair::maybePostRecvLocked() {
  if (!dataPath_ || rxPosted_ ||
      fd_.load(std::memory_order_relaxed) < 0 ||
      state_.load(std::memory_order_acquire) != State::kConnected) {
    return;
  }
  if (rxPaused_ && !rxInPayload_) {
    return;  // boundary pause; resumeReading reposts
  }
  RxWant w = rxWant();
  loop_->asyncRecv(fd_.load(std::memory_order_relaxed), w.ptr, w.len);
  rxPosted_ = true;
}

void Pair::handleIoComplete(bool isRecv, int32_t res) {
  if (isRecv) {
    // rxPosted_ stays set while this thread still owns the rx cursors:
    // it is the latch that keeps resumeReading() (app thread) from
    // posting a recv computed from cursors processRxBytes is mutating
    // lock-free below. Clear it only at the repost decision points,
    // under mu_, in the same critical section as the repost check.
    if (state_.load(std::memory_order_acquire) != State::kConnected) {
      std::lock_guard<std::mutex> guard(mu_);
      rxPosted_ = false;
      return;
    }
    if (res == 0) {
      {
        std::lock_guard<std::mutex> guard(mu_);
        rxPosted_ = false;
      }
      onRxEof();
      return;
    }
    if (res < 0) {
      if (res == -EAGAIN || res == -EINTR) {
        // Spurious wake on a pre-5.7 kernel: cursors untouched; repost.
        std::lock_guard<std::mutex> guard(mu_);
        rxPosted_ = false;
        maybePostRecvLocked();
        return;
      }
      if (res == -ECANCELED) {
        std::lock_guard<std::mutex> guard(mu_);
        rxPosted_ = false;
        return;  // teardown owns the wind-down
      }
      {
        std::lock_guard<std::mutex> guard(mu_);
        rxPosted_ = false;
      }
      errno = -res;
      fail(errnoString("recv"));
      return;
    }
    size_t consumed = 0;
    RxStep step = RxStep::kStop;
    try {
      step = processRxBytes(static_cast<size_t>(res), &consumed);
    } catch (...) {
      // Unlatch before propagating: a wedged-true rxPosted_ would
      // silently stop this pair from ever receiving again.
      std::lock_guard<std::mutex> guard(mu_);
      rxPosted_ = false;
      throw;
    }
    {
      std::lock_guard<std::mutex> guard(mu_);
      rxPosted_ = false;
      if (step != RxStep::kStop) {
        maybePostRecvLocked();
      }
    }
    return;
  }

  // Send completion: apply the confirmed byte count to the in-flight
  // site's cursors, then resume the flush — the submission-mode mirror
  // of handleEvents' EPOLLOUT arm.
  std::vector<TxDone> completed;
  std::string txError;
  {
    std::lock_guard<std::mutex> guard(mu_);
    txInFlight_ = false;
    if (state_.load(std::memory_order_acquire) != State::kConnected) {
      return;
    }
    if (res < 0) {
      if (res != -EAGAIN && res != -EINTR && res != -ECANCELED) {
        errno = -res;
        pendingTxError_ = errnoString("send");
      }
      // -EAGAIN/-EINTR: zero progress; flushTx resubmits the same bytes.
    } else {
      txAdvanceInFlight(static_cast<size_t>(res));
    }
    if (res != -ECANCELED && pendingTxError_.empty()) {
      flushTx(&completed);
    }
    txError = pendingTxError_;
    pendingTxError_.clear();
  }
  cv_.notify_all();  // close() may be waiting for the tx queue to drain
  for (auto& d : completed) {
    deliverSendComplete(d);
  }
  if (!txError.empty()) {
    fail(txError);
  }
}

void Pair::readLoop() {
  // Fairness/backpressure budget: a sender that keeps the socket full
  // could otherwise pin the loop thread in this loop forever (EAGAIN
  // never comes), starving sibling pairs and making pauseReading
  // ineffective — the epoll mask only matters once we return to the
  // loop. Level-triggered epoll re-fires if data remains.
  constexpr size_t kReadBudget = 8u << 20;
  size_t consumed = 0;
  while (state_.load(std::memory_order_acquire) == State::kConnected) {
    if (consumed >= kReadBudget) {
      return;
    }
    {
      std::lock_guard<std::mutex> guard(mu_);
      if (rxPaused_ && !rxInPayload_) {
        // Stop at a message boundary; remaining bytes stay in the socket
        // until the context resumes us.
        return;
      }
    }
    RxWant w = rxWant();
    ssize_t n = read(fd_.load(std::memory_order_relaxed), w.ptr,
                     w.len);
    if (n == 0) {
      onRxEof();
      return;
    }
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        return;
      }
      if (errno == EINTR) {
        continue;
      }
      fail(errnoString("recv"));
      return;
    }
    if (processRxBytes(static_cast<size_t>(n), &consumed) ==
        RxStep::kStop) {
      return;
    }
  }
}

void Pair::combineShmSpan(uint64_t msgOff, const char* src, size_t len) {
  const size_t el = shmRxCombineElsize_;
  const size_t accEl = shmRxCombineAccElsize_;
  // Accumulator address of the wire element containing byte `pos`.
  auto accAt = [&](uint64_t pos) {
    return shmRxDest_ + (pos / el) * accEl;
  };
  size_t head = 0;
  if (shmRxCarryLen_ > 0) {
    // Finish the element a previous span split. Its wire position starts
    // shmRxCarryLen_ bytes before this span's first byte.
    head = std::min(len, el - shmRxCarryLen_);
    std::memcpy(shmRxCarry_ + shmRxCarryLen_, src, head);
    shmRxCarryLen_ += head;
    if (shmRxCarryLen_ < el) {
      return;  // still mid-element (tiny span)
    }
    shmRxCombine_(accAt(msgOff + head - el), shmRxCarry_, 1);
    shmRxCarryLen_ = 0;
  }
  const size_t mid = (len - head) / el * el;
  if (mid > 0) {
    // The ring is a plain byte ring: after odd-length traffic a span can
    // start at any byte, but the reduce kernels dereference typed
    // pointers. Feed them `src` only when it satisfies the element type's
    // alignment (the largest power of two dividing elsize, the strictest
    // requirement a type of that size can have); otherwise fold through a
    // small aligned bounce so typed loads never see a misaligned address.
    // (The accumulator is the caller's own element-offset buffer — its
    // alignment is the caller's contract, exactly as on the scratch
    // schedule.)
    const size_t req = std::min(el & (~el + 1), size_t(16));
    if (reinterpret_cast<uintptr_t>(src + head) % req == 0) {
      shmRxCombine_(accAt(msgOff + head), src + head, mid / el);
    } else {
      alignas(64) char bounce[8192];
      const size_t step = sizeof(bounce) / el * el;
      for (size_t pos = 0; pos < mid; pos += step) {
        const size_t n = std::min(step, mid - pos);
        std::memcpy(bounce, src + head + pos, n);
        shmRxCombine_(accAt(msgOff + head + pos), bounce, n / el);
      }
    }
  }
  const size_t tail = len - head - mid;
  if (tail > 0) {
    std::memcpy(shmRxCarry_, src + head + mid, tail);
    shmRxCarryLen_ = tail;
  }
}

void Pair::finishMessage() {
  if (Metrics* m = context_->metrics()) {
    m->recordRecvd(peerRank_, rxHeader_.nbytes);
  }
  switch (rxMode_) {
    case RxMode::kStash:
      try {
        context_->stashArrived(peerRank_, rxHeader_.slot,
                               std::move(rxStashData_));
      } catch (const std::exception& e) {
        fail(detail::strCat("receive matching failed: ", e.what()));
        return;
      }
      rxStashData_ = std::vector<char>();
      break;
    case RxMode::kDirect: {
      if (rxCombine_ != nullptr) {
        if (!rxFoldInline_) {
          rxCombine_(rxFinalDest_, rxCombineStage_.data(),
                     rxHeader_.nbytes / rxCombineElsize_);
        }
        rxCombine_ = nullptr;  // stage keeps its capacity for the next one
        rxFoldInline_ = false;
      }
      UnboundBuffer* b = nullptr;
      {
        std::lock_guard<std::mutex> guard(mu_);
        b = rxUbuf_;
        rxUbuf_ = nullptr;
      }
      if (b != nullptr) {
        b->onRecvComplete(peerRank_, rxHeader_.slot);
      }
      break;
    }
    case RxMode::kPut:
      if (!context_->writeRegion(rxHeader_.slot, rxHeader_.aux,
                                 rxStashData_.data(), rxStashData_.size(),
                                 rxHeader_.flags & kPutFlagNotify,
                                 peerRank_)) {
        // Unknown token or out-of-bounds: a peer contract violation
        // (bounds are validated sender-side against the RemoteKey, so
        // only a stale key or a buggy/malicious peer lands here).
        fail(detail::strCat("one-sided put outside registered region "
                            "from rank ", peerRank_));
        return;
      }
      rxStashData_ = std::vector<char>();
      break;
    case RxMode::kStripe:
      try {
        context_->stripeLanded(peerRank_, rxStripeEntry_,
                               rxHeader_.reserved[0]);
      } catch (const std::exception& e) {
        fail(detail::strCat("receive matching failed: ", e.what()));
        return;
      }
      rxStripeEntry_ = 0;
      break;
    case RxMode::kGetReq: {
      WireGetReq req;
      std::memcpy(&req, rxStashData_.data(), sizeof(req));
      std::vector<char> data;
      if (!context_->readRegion(req.token, req.roffset, req.nbytes,
                                &data)) {
        fail(detail::strCat("one-sided get outside registered region "
                            "from rank ", peerRank_));
        return;
      }
      // Respond with a plain data message on the requester's slot; the
      // bytes were copied out under the region lock, so the response
      // cannot race the exporting buffer's teardown.
      WireHeader header{kMsgMagic, static_cast<uint8_t>(Opcode::kData),
                        0, {0, 0}, rxHeader_.slot, data.size(), 0};
      try {
        sendOwned(header, std::move(data));
      } catch (const std::exception&) {
        // Pair already closing/failed: the requester's posted recv gets
        // the pair error through the normal fan-out; nothing to unwind
        // through the event loop here.
      }
      break;
    }
  }
  rxMode_ = RxMode::kDirect;
  rxInPayload_ = false;
  rxHeaderRead_ = 0;
  rxDest_ = nullptr;
}

std::string Pair::debugState() {
  std::lock_guard<std::mutex> guard(mu_);
  std::string s = "txq=" + std::to_string(tx_.size());
  if (shmActive_.load(std::memory_order_relaxed)) {
    s += " shm[tx=" +
         std::to_string(
             shmTxBytes_.load(std::memory_order_relaxed) >> 10) +
         "KB rx=" +
         std::to_string(
             shmRxBytes_.load(std::memory_order_relaxed) >> 10) +
         "KB";
    if (txRingBlocked_) {
      s += " RING-BLOCKED";  // waiting on a kShmCredit wakeup
    }
    if (!ctrlQ_.empty() || ctrlSent_ < ctrlLen_) {
      s += " ctrl=" + std::to_string(ctrlQ_.size());
    }
    s += "]";
  }
  return s;
}

void Pair::pauseReading() {
  std::lock_guard<std::mutex> guard(mu_);
  if (!rxPaused_) {
    rxPaused_ = true;
    updateEpollMask();
  }
}

void Pair::resumeReading() {
  std::lock_guard<std::mutex> guard(mu_);
  if (rxPaused_) {
    rxPaused_ = false;
    updateEpollMask();
    // Data path: the pause parked the rx driver at a message boundary
    // with no recv outstanding, so the cursors are quiescent and posting
    // from this thread is safe.
    maybePostRecvLocked();
  }
}

void Pair::fail(const std::string& message) {
  teardown(State::kFailed, message, /*notifyContext=*/true);
}

bool Pair::idleForEvict() {
  std::lock_guard<std::mutex> guard(mu_);
  return state_.load(std::memory_order_acquire) == State::kConnected &&
         tx_.empty() && !txInFlight_ && ctrlQ_.empty() && !closing_;
}

void Pair::close(std::chrono::milliseconds grace) {
  // Graceful departure: flush queued sends, announce goodbye, half-close the
  // write side, then keep reading until the peer's EOF. Draining prevents
  // the kernel from sending an RST (which would flush the peer's receive
  // queue and lose delivered-but-unread payloads) when ranks reach teardown
  // at different times.
  const std::chrono::milliseconds kGrace = grace;
  std::vector<TxDone> completed;
  {
    std::unique_lock<std::mutex> lock(mu_);
    if (state_.load(std::memory_order_acquire) == State::kConnected && !closing_) {
      closing_ = true;
      TxOp op;
      op.header = WireHeader{kMsgMagic,
                             static_cast<uint8_t>(Opcode::kGoodbye),
                             0, {0, 0}, 0, 0};
      op.ubuf = nullptr;
      op.data = nullptr;
      op.nbytes = 0;
      tx_.push_back(op);
      flushTx(&completed);
      updateEpollMask();
      pendingTxError_.clear();
      const auto deadline = std::chrono::steady_clock::now() + kGrace;
      cv_.wait_until(lock, deadline, [&] {
        return tx_.empty() || state_.load(std::memory_order_acquire) != State::kConnected;
      });
      const int sfd = fd_.load(std::memory_order_relaxed);
      if (sfd >= 0) {
        ::shutdown(sfd, SHUT_WR);
      }
      cv_.wait_until(lock, deadline, [&] {
        return peerGoodbye_ || state_.load(std::memory_order_acquire) != State::kConnected;
      });
    }
  }
  for (auto& d : completed) {
    deliverSendComplete(d);
  }
  teardown(State::kClosed, "pair closed", /*notifyContext=*/false);
}

void Pair::teardown(State target, const std::string& message,
                    bool notifyContext) {
  std::vector<TxDone> sends;
  UnboundBuffer* rxb = nullptr;
  int fd = -1;
  {
    std::lock_guard<std::mutex> guard(mu_);
    State s = state_.load(std::memory_order_acquire);
    if (s == State::kFailed || s == State::kClosed) {
      return;
    }
    state_.store(target, std::memory_order_release);
    error_ = message;
    fd = fd_.load(std::memory_order_relaxed);
    fd_.store(-1, std::memory_order_release);
  }
  cv_.notify_all();
  if (expectedAt_ != nullptr) {
    expectedAt_->unexpect(localPairId_);
  }
  if (fd >= 0) {
    // del() barriers on the loop tick AND (data path) cancels + drains
    // any outstanding recv/send SQEs: after it returns no dispatch — and
    // no kernel DMA — touches this fd, the tx op buffers, or the rx
    // destination memory. Only then is it safe to free the tx queue and
    // fail the buffers below.
    loop_->del(fd);
    ::close(fd);
  }
  {
    std::lock_guard<std::mutex> guard(mu_);
    for (auto& op : tx_) {
      sends.push_back(TxDone{op.ubuf, op.stripe});
    }
    tx_.clear();
    txInFlight_ = false;
    txRingBlocked_ = false;
    ctrlQ_.clear();
    ctrlLen_ = 0;
    ctrlSent_ = 0;
    rxb = rxUbuf_;
    rxUbuf_ = nullptr;
  }
  for (auto& d : sends) {
    deliverSendError(d, message);
  }
  if (rxb != nullptr) {
    rxb->onRecvError(message);
  }
  if (notifyContext) {
    context_->onPairError(peerRank_, message,
                          /*orderly=*/target == State::kClosed, channel_);
  }
}

}  // namespace transport
}  // namespace tpucoll
