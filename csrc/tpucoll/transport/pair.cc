#include "tpucoll/transport/pair.h"

#include <poll.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <cstring>

#include <thread>

#include "tpucoll/common/debug.h"
#include "tpucoll/common/hmac.h"
#include "tpucoll/transport/context.h"
#include "tpucoll/transport/device.h"
#include "tpucoll/transport/listener.h"
#include "tpucoll/transport/socket.h"

namespace tpucoll {
namespace transport {

namespace {

// Typed handshake failures so the retry loop classifies robustly instead
// of substring-matching error text.
struct AuthRejected : IoException {
  using IoException::IoException;
};
struct HandshakeEof : IoException {
  using IoException::IoException;
};

}  // namespace

Pair::Pair(Context* context, Loop* loop, int selfRank, int peerRank,
           uint64_t localPairId)
    : context_(context),
      loop_(loop),
      selfRank_(selfRank),
      peerRank_(peerRank),
      localPairId_(localPairId) {}

Pair::~Pair() {
  close();
  // A teardown started on the loop thread (EOF, tx error) may still be
  // executing after close() early-returns; quiesce before freeing members.
  loop_->barrier();
}

void Pair::connect(const SockAddr& remote, uint64_t remotePairId,
                   std::chrono::milliseconds timeout) {
  static constexpr std::chrono::milliseconds kBackoff{50};
  // Clean EOF mid-handshake is ambiguous: a peer restarting during
  // bootstrap (retryable) or a permanent auth/encryption tier mismatch
  // (terminal). Bounded retries resolve the ambiguity without burning
  // the whole deadline on a misconfiguration.
  static constexpr int kMaxEofRetries = 3;
  const auto deadline = std::chrono::steady_clock::now() + timeout;
  const bool retriesDisabled =
      std::getenv("TPUCOLL_DISABLE_CONNECTION_RETRIES") != nullptr;
  int attempt = 0;
  int eofAttempts = 0;
  while (true) {
    attempt++;
    ConnectDebugData d;
    d.selfRank = selfRank_;
    d.peerRank = peerRank_;
    d.remote = remote.str();
    d.attempt = attempt;
    try {
      connectAttempt(remote, remotePairId, deadline, &d.local);
      d.ok = true;
      logConnectAttempt(d);
      return;
    } catch (const TimeoutException&) {
      d.error = "timed out";
      logConnectAttempt(d);
      throw;
    } catch (const AuthRejected& e) {
      // A live peer refuted the tag: terminal, retrying a wrong key is
      // noise.
      d.error = e.what();
      logConnectAttempt(d);
      throw;
    } catch (const HandshakeEof& e) {
      d.error = e.what();
      eofAttempts++;
      d.willRetry = !retriesDisabled && eofAttempts <= kMaxEofRetries &&
                    std::chrono::steady_clock::now() + kBackoff < deadline;
      logConnectAttempt(d);
      if (!d.willRetry) {
        throw;
      }
      std::this_thread::sleep_for(kBackoff);
    } catch (const IoException& e) {
      // Refused/reset/poll errors: the peer is still coming up; retry
      // until the deadline.
      d.error = e.what();
      d.willRetry = !retriesDisabled &&
                    std::chrono::steady_clock::now() + kBackoff < deadline;
      logConnectAttempt(d);
      if (!d.willRetry) {
        throw;
      }
      std::this_thread::sleep_for(kBackoff);
    }
  }
}

void Pair::connectAttempt(const SockAddr& remote, uint64_t remotePairId,
                          std::chrono::steady_clock::time_point deadline,
                          std::string* localAddr) {
  int fd = socket(remote.sa()->sa_family, SOCK_STREAM | SOCK_CLOEXEC, 0);
  TC_ENFORCE_GE(fd, 0, errnoString("socket"));
  setNonBlocking(fd);

  int rv = ::connect(fd, remote.sa(), remote.len);
  if (rv != 0 && errno != EINPROGRESS) {
    ::close(fd);
    TC_THROW(IoException, "connect to rank ", peerRank_, " at ", remote.str(),
             ": ", strerror(errno));
  }
  if (rv != 0) {
    // Await writability = connection established (or refused). Retry EINTR
    // against the remaining deadline; a real poll error is an IoException,
    // not a timeout.
    while (true) {
      pollfd pfd{fd, POLLOUT, 0};
      auto remaining = std::chrono::duration_cast<std::chrono::milliseconds>(
          deadline - std::chrono::steady_clock::now());
      int prv = poll(&pfd, 1, static_cast<int>(std::max<int64_t>(
                                  remaining.count(), 0)));
      if (prv > 0) {
        break;
      }
      if (prv == 0) {
        ::close(fd);
        TC_THROW(TimeoutException, "connect to rank ", peerRank_, " at ",
                 remote.str(), " timed out");
      }
      if (errno == EINTR) {
        continue;
      }
      int savedErrno = errno;
      ::close(fd);
      TC_THROW(IoException, "connect to rank ", peerRank_, " at ",
               remote.str(), ": poll: ", strerror(savedErrno));
    }
    int soErr = 0;
    socklen_t soLen = sizeof(soErr);
    getsockopt(fd, SOL_SOCKET, SO_ERROR, &soErr, &soLen);
    if (soErr != 0) {
      ::close(fd);
      TC_THROW(IoException, "connect to rank ", peerRank_, " at ",
               remote.str(), ": ", strerror(soErr));
    }
  }
  setNoDelay(fd);
  {
    SockAddr local;
    local.len = sizeof(local.ss);
    if (getsockname(fd, local.sa(), &local.len) == 0) {
      *localAddr = local.str();
    }
  }

  const std::string& authKey = context_->device()->authKey();
  auto writeAll = [&](const void* buf, size_t len, const char* what) {
    const char* p = static_cast<const char*>(buf);
    size_t sent = 0;
    while (sent < len) {
      ssize_t n = ::send(fd, p + sent, len - sent, MSG_NOSIGNAL);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          // Bound by the same handshake deadline readAll honors: a peer
          // that accepts but never drains must not stall connect forever.
          pollfd pfd{fd, POLLOUT, 0};
          int prv = poll(&pfd, 1, static_cast<int>(std::max<int64_t>(
              std::chrono::duration_cast<std::chrono::milliseconds>(
                  deadline - std::chrono::steady_clock::now()).count(), 0)));
          if (prv == 0) {
            ::close(fd);
            TC_THROW(TimeoutException, what, ": handshake write to rank ",
                     peerRank_, " timed out");
          }
          if (prv < 0 && errno != EINTR) {
            int savedErrno = errno;
            ::close(fd);
            TC_THROW(IoException, what, ": handshake poll: ",
                     strerror(savedErrno));
          }
          continue;
        }
        if (errno == EINTR) {
          continue;
        }
        ::close(fd);
        TC_THROW(IoException, what, " write to rank ", peerRank_, ": ",
                 strerror(errno));
      }
      sent += static_cast<size_t>(n);
    }
  };
  auto readAll = [&](void* buf, size_t len, const char* what) {
    char* p = static_cast<char*>(buf);
    size_t got = 0;
    while (got < len) {
      ssize_t n = ::recv(fd, p + got, len - got, 0);
      if (n == 0) {
        ::close(fd);
        TC_THROW(HandshakeEof, what, ": rank ", peerRank_,
                 " closed the connection during the handshake "
                 "(restarting peer, or auth/encryption tier mismatch)");
      }
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          pollfd pfd{fd, POLLIN, 0};
          int prv = poll(&pfd, 1, static_cast<int>(std::max<int64_t>(
              std::chrono::duration_cast<std::chrono::milliseconds>(
                  deadline - std::chrono::steady_clock::now()).count(), 0)));
          if (prv == 0) {
            ::close(fd);
            TC_THROW(TimeoutException, what, ": handshake with rank ",
                     peerRank_, " timed out");
          }
          if (prv < 0 && errno != EINTR) {
            int savedErrno = errno;
            ::close(fd);
            TC_THROW(IoException, what, ": handshake poll: ",
                     strerror(savedErrno));
          }
          continue;
        }
        if (errno == EINTR) {
          continue;
        }
        ::close(fd);
        TC_THROW(IoException, what, ": ", strerror(errno));
      }
      got += static_cast<size_t>(n);
    }
  };

  // Route this connection to the peer's expecting Pair; with a pre-shared
  // key, run the mutual challenge/response of wire.h on top (and, when the
  // device encrypts, derive the connection's AEAD keys from it).
  const bool encrypt = context_->device()->encrypt();
  WireHello hello{authKey.empty() ? kHelloMagic
                  : encrypt       ? kHelloAuthEncMagic
                                  : kHelloAuthMagic,
                  0, remotePairId};
  writeAll(&hello, sizeof(hello), "hello");
  ConnKeys keys;
  if (!authKey.empty()) {
    uint8_t nonceI[kAuthNonceBytes];
    randomBytes(nonceI, sizeof(nonceI));
    writeAll(nonceI, sizeof(nonceI), "auth nonce");

    uint8_t reply[kAuthNonceBytes + kAuthMacBytes];
    readAll(reply, sizeof(reply), "auth challenge");
    auto transcript = [&](const char* role) {
      std::string msg(role);
      msg.append(reinterpret_cast<const char*>(&remotePairId),
                 sizeof(remotePairId));
      msg.append(reinterpret_cast<const char*>(nonceI), kAuthNonceBytes);
      msg.append(reinterpret_cast<const char*>(reply), kAuthNonceBytes);
      return hmacSha256(authKey.data(), authKey.size(), msg.data(),
                        msg.size());
    };
    auto srvExpect = transcript("srv");
    if (!macEqual(reply + kAuthNonceBytes, srvExpect.data(),
                  kAuthMacBytes)) {
      ::close(fd);
      TC_THROW(AuthRejected, "rank ", peerRank_,
               " failed authentication (bad server tag)");
    }
    auto cliMac = transcript("cli");
    writeAll(cliMac.data(), cliMac.size(), "auth tag");
    if (encrypt) {
      keys = deriveConnKeys(authKey, remotePairId, nonceI, reply,
                            /*initiator=*/true);
    }
  }
  assumeConnected(fd, keys);
}

void Pair::expectViaListener(Listener* listener) {
  expectedAt_ = listener;
  listener->expect(localPairId_, this);
}

void Pair::assumeConnected(int fd, const ConnKeys& keys) {
  setNonBlocking(fd);
  setBufferSizes(fd, 4 << 20);
  bool accepted = false;
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (state_.load() == State::kInitializing) {
      keys_ = keys;
      fd_ = fd;
      epollMask_ = EPOLLIN;
      everConnected_.store(true);
      state_.store(State::kConnected);
      loop_->add(fd, EPOLLIN, this);
      accepted = true;
    }
  }
  if (!accepted) {
    ::close(fd);  // pair was closed while the connection was in flight
    return;
  }
  cv_.notify_all();
}

void Pair::waitConnected(std::chrono::milliseconds timeout) {
  std::unique_lock<std::mutex> lock(mu_);
  auto pred = [&] { return state_.load() != State::kInitializing; };
  if (!cv_.wait_for(lock, timeout, pred)) {
    TC_THROW(TimeoutException, "rank ", selfRank_,
             ": timed out connecting pair to rank ", peerRank_);
  }
  State s = state_.load();
  if (s != State::kConnected && !everConnected_.load()) {
    TC_THROW(IoException, "pair to rank ", peerRank_, " failed: ", error_);
  }
  // A pair that connected and already saw the peer depart counts as
  // connected: everything the peer sent is staged in the context stash, so
  // receive-only schedules against it still complete.
}

void Pair::send(UnboundBuffer* ubuf, uint64_t slot, const char* data,
                size_t nbytes) {
  TxOp op;
  op.header = WireHeader{kMsgMagic, static_cast<uint8_t>(Opcode::kData),
                         0, {0, 0}, slot, nbytes};
  op.ubuf = ubuf;
  op.data = data;
  op.nbytes = nbytes;
  enqueue(std::move(op));
}

void Pair::sendPut(UnboundBuffer* ubuf, uint64_t token, uint64_t roffset,
                   const char* data, size_t nbytes, bool notify) {
  TxOp op;
  op.header = WireHeader{kMsgMagic, static_cast<uint8_t>(Opcode::kPut),
                         notify ? kPutFlagNotify : uint8_t(0), {0, 0},
                         token, nbytes, roffset};
  op.ubuf = ubuf;
  op.data = data;
  op.nbytes = nbytes;
  enqueue(std::move(op));
}

void Pair::sendOwned(WireHeader header, std::vector<char> payload) {
  TxOp op;
  op.header = header;
  op.ubuf = nullptr;
  op.nbytes = payload.size();
  op.ownedData = std::move(payload);
  op.data = nullptr;  // fixed up after the move into the queue
  enqueue(std::move(op));
}

void Pair::enqueue(TxOp op) {
  std::vector<UnboundBuffer*> completed;
  std::string txError;
  {
    std::lock_guard<std::mutex> guard(mu_);
    State s = state_.load();
    if (s != State::kConnected || closing_) {
      TC_THROW(IoException, "send to rank ", peerRank_, ": pair ",
               s == State::kFailed ? error_
               : closing_          ? "is closing"
                                   : "is not connected");
    }
    tx_.push_back(std::move(op));
    if (tx_.back().data == nullptr && !tx_.back().ownedData.empty()) {
      // Owned payloads must point into the queued op (deque elements are
      // stable), not the moved-from local.
      tx_.back().data = tx_.back().ownedData.data();
    }
    if (tx_.size() == 1) {
      // Inline fast path: try to push the bytes out right here, skipping a
      // loop-thread wakeup when the socket has room (the common case).
      flushTx(&completed);
      if (state_.load() == State::kConnected && !tx_.empty()) {
        updateEpollMask();
      }
    } else {
      updateEpollMask();
    }
    txError = pendingTxError_;
    pendingTxError_.clear();
  }
  for (auto* b : completed) {
    if (b != nullptr) {
      b->onSendComplete();
    }
  }
  if (!txError.empty()) {
    fail(txError);
  }
}

int Pair::cancelQueuedSends(UnboundBuffer* ubuf) {
  std::lock_guard<std::mutex> guard(mu_);
  int removed = 0;
  for (auto it = tx_.begin(); it != tx_.end();) {
    const bool started =
        it == tx_.begin() && (it->headerSent > 0 || it->headerSealed);
    if (it->ubuf == ubuf && !started) {
      it = tx_.erase(it);
      removed++;
    } else {
      ++it;
    }
  }
  return removed;
}

bool Pair::hasInflightSend(UnboundBuffer* ubuf) {
  std::lock_guard<std::mutex> guard(mu_);
  for (const auto& op : tx_) {
    if (op.ubuf == ubuf) {
      return true;
    }
  }
  return false;
}

void Pair::flushTx(std::vector<UnboundBuffer*>* completed) {
  if (fd_ < 0) {
    return;
  }
  while (!tx_.empty()) {
    TxOp& op = tx_.front();
    if (keys_.encrypted) {
      if (op.cipherSent == op.cipher.size()) {
        if (!op.headerSealed) {
          sealHeaderFrame(&op);
        } else if (op.sealOffset < op.nbytes) {
          sealPayloadFrame(&op);
        } else {
          completed->push_back(op.ubuf);
          tx_.pop_front();
          continue;
        }
      }
      ssize_t n = ::send(fd_, op.cipher.data() + op.cipherSent,
                         op.cipher.size() - op.cipherSent, MSG_NOSIGNAL);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          break;
        }
        if (errno == EINTR) {
          continue;
        }
        pendingTxError_ = errnoString("send");
        return;
      }
      op.cipherSent += static_cast<size_t>(n);
      if (op.cipherSent == op.cipher.size() && op.headerSealed &&
          op.sealOffset == op.nbytes) {
        completed->push_back(op.ubuf);
        tx_.pop_front();
      }
      continue;
    }
    iovec iov[2];
    int iovcnt = 0;
    if (op.headerSent < sizeof(WireHeader)) {
      iov[iovcnt].iov_base =
          reinterpret_cast<char*>(&op.header) + op.headerSent;
      iov[iovcnt].iov_len = sizeof(WireHeader) - op.headerSent;
      iovcnt++;
    }
    if (op.dataSent < op.nbytes) {
      iov[iovcnt].iov_base = const_cast<char*>(op.data) + op.dataSent;
      iov[iovcnt].iov_len = op.nbytes - op.dataSent;
      iovcnt++;
    }
    ssize_t n = 0;
    if (iovcnt > 0) {
      msghdr msg{};
      msg.msg_iov = iov;
      msg.msg_iovlen = iovcnt;
      // MSG_NOSIGNAL: broken pipes become errors, never SIGPIPE.
      n = sendmsg(fd_, &msg, MSG_NOSIGNAL);
    }
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        break;
      }
      if (errno == EINTR) {
        continue;
      }
      pendingTxError_ = errnoString("send");
      return;
    }
    size_t adv = static_cast<size_t>(n);
    size_t headerRemaining = sizeof(WireHeader) - op.headerSent;
    size_t take = std::min(adv, headerRemaining);
    op.headerSent += take;
    adv -= take;
    op.dataSent += adv;
    if (op.headerSent == sizeof(WireHeader) && op.dataSent == op.nbytes) {
      completed->push_back(op.ubuf);
      tx_.pop_front();
    }
  }
}

void Pair::sealHeaderFrame(TxOp* op) {
  op->cipher.resize(sizeof(WireHeader) + kAeadTagBytes);
  op->cipherSent = 0;
  uint8_t* p = reinterpret_cast<uint8_t*>(op->cipher.data());
  aeadSeal(keys_.tx, txSeq_++, nullptr, 0,
           reinterpret_cast<const uint8_t*>(&op->header),
           sizeof(WireHeader), p, p + sizeof(WireHeader));
  op->headerSealed = true;
}

void Pair::sealPayloadFrame(TxOp* op) {
  const size_t chunk =
      std::min(kEncFrameBytes, op->nbytes - op->sealOffset);
  op->cipher.resize(chunk + kAeadTagBytes);
  op->cipherSent = 0;
  uint8_t* p = reinterpret_cast<uint8_t*>(op->cipher.data());
  aeadSeal(keys_.tx, txSeq_++, nullptr, 0,
           reinterpret_cast<const uint8_t*>(op->data + op->sealOffset),
           chunk, p, p + chunk);
  op->sealOffset += chunk;
}

void Pair::updateEpollMask() {
  if (fd_ < 0 || state_.load() != State::kConnected) {
    return;
  }
  uint32_t desired = (rxPaused_ ? 0u : uint32_t(EPOLLIN)) |
                     (tx_.empty() ? 0u : uint32_t(EPOLLOUT));
  if (desired != epollMask_) {
    loop_->mod(fd_, desired, this);
    epollMask_ = desired;
  }
}

void Pair::handleEvents(uint32_t events) {
  if (state_.load() != State::kConnected) {
    return;
  }
  if (events & (EPOLLIN | EPOLLERR | EPOLLHUP)) {
    readLoop();
  }
  if (state_.load() != State::kConnected) {
    return;
  }
  if (events & EPOLLOUT) {
    std::vector<UnboundBuffer*> completed;
    std::string txError;
    {
      std::lock_guard<std::mutex> guard(mu_);
      flushTx(&completed);
      if (state_.load() == State::kConnected) {
        updateEpollMask();
      }
      txError = pendingTxError_;
      pendingTxError_.clear();
    }
    cv_.notify_all();  // close() may be waiting for the tx queue to drain
    for (auto* b : completed) {
      if (b != nullptr) {
        b->onSendComplete();
      }
    }
    if (!txError.empty()) {
      fail(txError);
    }
  }
}

void Pair::readLoop() {
  // Fairness/backpressure budget: a sender that keeps the socket full
  // could otherwise pin the loop thread in this loop forever (EAGAIN
  // never comes), starving sibling pairs and making pauseReading
  // ineffective — the epoll mask only matters once we return to the
  // loop. Level-triggered epoll re-fires if data remains.
  constexpr size_t kReadBudget = 8u << 20;
  size_t consumed = 0;
  while (state_.load() == State::kConnected) {
    if (consumed >= kReadBudget) {
      return;
    }
    {
      std::lock_guard<std::mutex> guard(mu_);
      if (rxPaused_ && !rxInPayload_) {
        // Stop at a message boundary; remaining bytes stay in the socket
        // until the context resumes us.
        return;
      }
    }
    if (!rxInPayload_) {
      const bool enc = keys_.encrypted;
      const size_t hdrWant =
          enc ? sizeof(rxHeaderCipher_) : sizeof(WireHeader);
      char* hp = enc ? reinterpret_cast<char*>(rxHeaderCipher_)
                     : reinterpret_cast<char*>(&rxHeader_);
      ssize_t n = read(fd_, hp + rxHeaderRead_, hdrWant - rxHeaderRead_);
      if (n == 0) {
        bool orderly;
        {
          std::lock_guard<std::mutex> guard(mu_);
          orderly = peerGoodbye_;
        }
        if (orderly) {
          teardown(State::kClosed,
                   detail::strCat("rank ", peerRank_, " left the group"),
                   /*notifyContext=*/true);
        } else {
          fail(detail::strCat("connection to rank ", peerRank_,
                              " closed by peer unexpectedly"));
        }
        return;
      }
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          return;
        }
        if (errno == EINTR) {
          continue;
        }
        fail(errnoString("recv"));
        return;
      }
      rxHeaderRead_ += static_cast<size_t>(n);
      consumed += static_cast<size_t>(n);
      if (rxHeaderRead_ < hdrWant) {
        continue;
      }
      if (enc && !aeadOpen(keys_.rx, rxSeq_++, nullptr, 0, rxHeaderCipher_,
                           sizeof(WireHeader),
                           reinterpret_cast<uint8_t*>(&rxHeader_),
                           rxHeaderCipher_ + sizeof(WireHeader))) {
        fail(detail::strCat("message authentication failed from rank ",
                            peerRank_));
        return;
      }
      if (rxHeader_.magic != kMsgMagic) {
        fail(detail::strCat("protocol violation from rank ", peerRank_));
        return;
      }
      if (rxHeader_.opcode == static_cast<uint8_t>(Opcode::kGoodbye)) {
        {
          std::lock_guard<std::mutex> guard(mu_);
          peerGoodbye_ = true;
        }
        cv_.notify_all();
        rxHeaderRead_ = 0;
        continue;
      }
      if (rxHeader_.opcode == static_cast<uint8_t>(Opcode::kPut)) {
        // One-sided write: payload staged then copied into the registered
        // region under the context lock (re-validated there, so a region
        // torn down mid-flight cannot be scribbled on).
        const size_t nbytes = rxHeader_.nbytes;
        if (nbytes == 0) {
          // Zero-byte puts still validate the token/offset: the same
          // contract violation must not pass or fail based on length.
          if (!context_->writeRegion(rxHeader_.slot, rxHeader_.aux,
                                     nullptr, 0,
                                     rxHeader_.flags & kPutFlagNotify,
                                     peerRank_)) {
            fail(detail::strCat("one-sided put outside registered region "
                                "from rank ", peerRank_));
            return;
          }
          rxHeaderRead_ = 0;
          continue;
        }
        rxInPayload_ = true;
        rxPayloadRead_ = 0;
        rxPlainDone_ = 0;
        rxMode_ = RxMode::kPut;
        rxStashData_.resize(nbytes);
        rxDest_ = rxStashData_.data();
        continue;
      }
      if (rxHeader_.opcode == static_cast<uint8_t>(Opcode::kGetReq)) {
        if (rxHeader_.nbytes != sizeof(WireGetReq)) {
          fail(detail::strCat("malformed get request from rank ",
                              peerRank_));
          return;
        }
        rxInPayload_ = true;
        rxPayloadRead_ = 0;
        rxPlainDone_ = 0;
        rxMode_ = RxMode::kGetReq;
        rxStashData_.resize(sizeof(WireGetReq));
        rxDest_ = rxStashData_.data();
        continue;
      }
      if (rxHeader_.opcode != static_cast<uint8_t>(Opcode::kData)) {
        fail(detail::strCat("protocol violation from rank ", peerRank_));
        return;
      }
      const size_t nbytes = rxHeader_.nbytes;
      Context::Match match;
      try {
        match = context_->matchIncoming(peerRank_, rxHeader_.slot, nbytes);
      } catch (const std::exception& e) {
        // e.g. posted-size mismatch: an application-level contract violation
        // (inconsistent counts across ranks). Poison this pair instead of
        // unwinding through the event loop.
        fail(detail::strCat("receive matching failed: ", e.what()));
        return;
      }
      if (nbytes == 0) {
        if (match.direct) {
          match.ubuf->onRecvComplete(peerRank_);
        } else {
          context_->stashArrived(peerRank_, rxHeader_.slot, {});
        }
        rxHeaderRead_ = 0;
        continue;
      }
      rxInPayload_ = true;
      rxPayloadRead_ = 0;
      rxPlainDone_ = 0;
      if (match.direct) {
        rxMode_ = RxMode::kDirect;
        rxDest_ = match.dest;
        std::lock_guard<std::mutex> guard(mu_);
        rxUbuf_ = match.ubuf;
      } else {
        rxMode_ = RxMode::kStash;
        rxStashData_.resize(nbytes);
        rxDest_ = rxStashData_.data();
      }
    } else {
      // Encrypted connections append a 16-byte tag after the payload
      // ciphertext; the ciphertext itself lands in the final destination
      // (user memory or stash) and is decrypted in place once complete.
      // The destination is surfaced to the application only after the
      // tag verifies, so a tamperer can at worst poison the pair.
      const bool enc = keys_.encrypted;
      const size_t frameLen =
          enc ? std::min(kEncFrameBytes, rxHeader_.nbytes - rxPlainDone_)
              : rxHeader_.nbytes;
      const size_t frameTotal = frameLen + (enc ? kAeadTagBytes : 0);
      char* dst;
      size_t want;
      if (rxPayloadRead_ < frameLen) {
        dst = rxDest_ + rxPlainDone_ + rxPayloadRead_;
        want = frameLen - rxPayloadRead_;
      } else {
        dst = reinterpret_cast<char*>(rxPayloadTag_) +
              (rxPayloadRead_ - frameLen);
        want = frameTotal - rxPayloadRead_;
      }
      ssize_t n = read(fd_, dst, want);
      if (n == 0) {
        fail(detail::strCat("connection to rank ", peerRank_,
                            " closed mid-message"));
        return;
      }
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          return;
        }
        if (errno == EINTR) {
          continue;
        }
        fail(errnoString("recv"));
        return;
      }
      rxPayloadRead_ += static_cast<size_t>(n);
      consumed += static_cast<size_t>(n);
      if (rxPayloadRead_ == frameTotal) {
        if (enc) {
          if (!aeadOpen(keys_.rx, rxSeq_++, nullptr, 0,
                        reinterpret_cast<uint8_t*>(rxDest_ + rxPlainDone_),
                        frameLen,
                        reinterpret_cast<uint8_t*>(rxDest_ + rxPlainDone_),
                        rxPayloadTag_)) {
            fail(detail::strCat(
                "message authentication failed from rank ", peerRank_));
            return;
          }
          rxPlainDone_ += frameLen;
          rxPayloadRead_ = 0;
          if (rxPlainDone_ < rxHeader_.nbytes) {
            continue;  // more frames of this message
          }
        }
        finishMessage();
      }
    }
  }
}

void Pair::finishMessage() {
  switch (rxMode_) {
    case RxMode::kStash:
      try {
        context_->stashArrived(peerRank_, rxHeader_.slot,
                               std::move(rxStashData_));
      } catch (const std::exception& e) {
        fail(detail::strCat("receive matching failed: ", e.what()));
        return;
      }
      rxStashData_ = std::vector<char>();
      break;
    case RxMode::kDirect: {
      UnboundBuffer* b = nullptr;
      {
        std::lock_guard<std::mutex> guard(mu_);
        b = rxUbuf_;
        rxUbuf_ = nullptr;
      }
      if (b != nullptr) {
        b->onRecvComplete(peerRank_);
      }
      break;
    }
    case RxMode::kPut:
      if (!context_->writeRegion(rxHeader_.slot, rxHeader_.aux,
                                 rxStashData_.data(), rxStashData_.size(),
                                 rxHeader_.flags & kPutFlagNotify,
                                 peerRank_)) {
        // Unknown token or out-of-bounds: a peer contract violation
        // (bounds are validated sender-side against the RemoteKey, so
        // only a stale key or a buggy/malicious peer lands here).
        fail(detail::strCat("one-sided put outside registered region "
                            "from rank ", peerRank_));
        return;
      }
      rxStashData_ = std::vector<char>();
      break;
    case RxMode::kGetReq: {
      WireGetReq req;
      std::memcpy(&req, rxStashData_.data(), sizeof(req));
      std::vector<char> data;
      if (!context_->readRegion(req.token, req.roffset, req.nbytes,
                                &data)) {
        fail(detail::strCat("one-sided get outside registered region "
                            "from rank ", peerRank_));
        return;
      }
      // Respond with a plain data message on the requester's slot; the
      // bytes were copied out under the region lock, so the response
      // cannot race the exporting buffer's teardown.
      WireHeader header{kMsgMagic, static_cast<uint8_t>(Opcode::kData),
                        0, {0, 0}, rxHeader_.slot, data.size(), 0};
      try {
        sendOwned(header, std::move(data));
      } catch (const std::exception&) {
        // Pair already closing/failed: the requester's posted recv gets
        // the pair error through the normal fan-out; nothing to unwind
        // through the event loop here.
      }
      break;
    }
  }
  rxMode_ = RxMode::kDirect;
  rxInPayload_ = false;
  rxHeaderRead_ = 0;
  rxDest_ = nullptr;
}

void Pair::pauseReading() {
  std::lock_guard<std::mutex> guard(mu_);
  if (!rxPaused_) {
    rxPaused_ = true;
    updateEpollMask();
  }
}

void Pair::resumeReading() {
  std::lock_guard<std::mutex> guard(mu_);
  if (rxPaused_) {
    rxPaused_ = false;
    updateEpollMask();
  }
}

void Pair::fail(const std::string& message) {
  teardown(State::kFailed, message, /*notifyContext=*/true);
}

void Pair::close() {
  // Graceful departure: flush queued sends, announce goodbye, half-close the
  // write side, then keep reading until the peer's EOF. Draining prevents
  // the kernel from sending an RST (which would flush the peer's receive
  // queue and lose delivered-but-unread payloads) when ranks reach teardown
  // at different times.
  static constexpr std::chrono::milliseconds kGrace{2000};
  std::vector<UnboundBuffer*> completed;
  {
    std::unique_lock<std::mutex> lock(mu_);
    if (state_.load() == State::kConnected && !closing_) {
      closing_ = true;
      TxOp op;
      op.header = WireHeader{kMsgMagic,
                             static_cast<uint8_t>(Opcode::kGoodbye),
                             0, {0, 0}, 0, 0};
      op.ubuf = nullptr;
      op.data = nullptr;
      op.nbytes = 0;
      tx_.push_back(op);
      flushTx(&completed);
      updateEpollMask();
      pendingTxError_.clear();
      const auto deadline = std::chrono::steady_clock::now() + kGrace;
      cv_.wait_until(lock, deadline, [&] {
        return tx_.empty() || state_.load() != State::kConnected;
      });
      if (fd_ >= 0) {
        ::shutdown(fd_, SHUT_WR);
      }
      cv_.wait_until(lock, deadline, [&] {
        return peerGoodbye_ || state_.load() != State::kConnected;
      });
    }
  }
  for (auto* b : completed) {
    if (b != nullptr) {
      b->onSendComplete();
    }
  }
  teardown(State::kClosed, "pair closed", /*notifyContext=*/false);
}

void Pair::teardown(State target, const std::string& message,
                    bool notifyContext) {
  std::vector<UnboundBuffer*> sends;
  UnboundBuffer* rxb = nullptr;
  int fd = -1;
  {
    std::lock_guard<std::mutex> guard(mu_);
    State s = state_.load();
    if (s == State::kFailed || s == State::kClosed) {
      return;
    }
    state_.store(target);
    error_ = message;
    for (auto& op : tx_) {
      sends.push_back(op.ubuf);
    }
    tx_.clear();
    fd = fd_;
    fd_ = -1;
    rxb = rxUbuf_;
    rxUbuf_ = nullptr;
  }
  cv_.notify_all();
  if (expectedAt_ != nullptr) {
    expectedAt_->unexpect(localPairId_);
  }
  if (fd >= 0) {
    // del() barriers on the loop tick: after it returns no dispatch touches
    // this fd or the rx destination memory, so failing the buffers below
    // cannot race an in-flight read into user memory.
    loop_->del(fd);
    ::close(fd);
  }
  for (auto* b : sends) {
    if (b != nullptr) {
      b->onSendError(message);
    }
  }
  if (rxb != nullptr) {
    rxb->onRecvError(message);
  }
  if (notifyContext) {
    context_->onPairError(peerRank_, message);
  }
}

}  // namespace transport
}  // namespace tpucoll
