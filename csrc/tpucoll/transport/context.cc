#include "tpucoll/transport/context.h"

#include "tpucoll/transport/wire.h"

#include <algorithm>
#include <cstdlib>
#include <cstring>

#include "tpucoll/boot/lazy_id.h"
#include "tpucoll/common/env.h"
#include "tpucoll/transport/device.h"
#include "tpucoll/transport/pair.h"

namespace tpucoll {
namespace transport {

namespace {

std::string rankKey(int rank) { return "tc/rank/" + std::to_string(rank); }

// Rank blob: [u32 numRanks][u32 addrLen][addr][u64 pairId * numRanks].
// With TPUCOLL_CHANNELS > 1 a channel extension follows:
// [u32 kBlobChannelsMagic][u32 channels][u64 channelId * numRanks*(C-1)]
// (channel-major per peer: ids[j*(C-1) + (c-1)] routes channel c of the
// pair toward peer j). A single-channel context emits the seed's exact
// byte layout, and a channel-count mismatch between ranks fails the
// bootstrap loudly instead of hanging the mesh.
constexpr uint32_t kBlobChannelsMagic = 0x7C01100A;

// Lazy address blob (enableLazy bootstrap):
// [u32 magic][u32 channels][u32 addrLen][addr]. No per-peer pair ids —
// the lazy id codec (boot/lazy_id.h) derives routing ids from
// (mesh, generation, initiator, target, channel) deterministically, so
// the rendezvous exchange carries O(1) bytes per rank instead of O(n).
constexpr uint32_t kLazyBlobMagic = 0x7C0B0071;
// Eviction close grace: the victim's remote side is an rx-only lazy
// inbound pair that replies to the goodbye immediately, so the
// handshake completes in a round trip, not a drain.
constexpr std::chrono::milliseconds kEvictGrace(250);

std::vector<uint8_t> packRankBlob(int numRanks, const SockAddr& addr,
                                  const std::vector<uint64_t>& pairIds,
                                  int channels,
                                  const std::vector<uint64_t>& channelIds) {
  auto addrBytes = addr.serialize();
  std::vector<uint8_t> blob;
  blob.reserve(8 + addrBytes.size() + 8 * pairIds.size() +
               (channels > 1 ? 8 + 8 * channelIds.size() : 0));
  uint32_t n = static_cast<uint32_t>(numRanks);
  uint32_t alen = static_cast<uint32_t>(addrBytes.size());
  blob.insert(blob.end(), reinterpret_cast<uint8_t*>(&n),
              reinterpret_cast<uint8_t*>(&n) + 4);
  blob.insert(blob.end(), reinterpret_cast<uint8_t*>(&alen),
              reinterpret_cast<uint8_t*>(&alen) + 4);
  blob.insert(blob.end(), addrBytes.begin(), addrBytes.end());
  blob.insert(blob.end(),
              reinterpret_cast<const uint8_t*>(pairIds.data()),
              reinterpret_cast<const uint8_t*>(pairIds.data()) +
                  8 * pairIds.size());
  if (channels > 1) {
    uint32_t magic = kBlobChannelsMagic;
    uint32_t c = static_cast<uint32_t>(channels);
    blob.insert(blob.end(), reinterpret_cast<uint8_t*>(&magic),
                reinterpret_cast<uint8_t*>(&magic) + 4);
    blob.insert(blob.end(), reinterpret_cast<uint8_t*>(&c),
                reinterpret_cast<uint8_t*>(&c) + 4);
    blob.insert(blob.end(),
                reinterpret_cast<const uint8_t*>(channelIds.data()),
                reinterpret_cast<const uint8_t*>(channelIds.data()) +
                    8 * channelIds.size());
  }
  return blob;
}

void unpackRankBlob(const std::vector<uint8_t>& blob, int expectRanks,
                    int expectChannels, SockAddr* addr,
                    std::vector<uint64_t>* pairIds,
                    std::vector<uint64_t>* channelIds) {
  TC_ENFORCE_GE(blob.size(), size_t(8), "rank blob too short");
  uint32_t n, alen;
  std::memcpy(&n, blob.data(), 4);
  std::memcpy(&alen, blob.data() + 4, 4);
  TC_ENFORCE_EQ(int(n), expectRanks, "rank blob size mismatch");
  TC_ENFORCE_GE(blob.size(), size_t(8) + alen + size_t(8) * n,
                "rank blob truncated");
  *addr = SockAddr::deserialize(blob.data() + 8, alen);
  pairIds->resize(n);
  std::memcpy(pairIds->data(), blob.data() + 8 + alen, size_t(8) * n);
  size_t off = 8 + alen + size_t(8) * n;
  channelIds->clear();
  if (blob.size() > off) {
    TC_ENFORCE_GE(blob.size(), off + 8, "rank blob truncated");
    uint32_t magic, peerChannels;
    std::memcpy(&magic, blob.data() + off, 4);
    std::memcpy(&peerChannels, blob.data() + off + 4, 4);
    TC_ENFORCE_EQ(magic, kBlobChannelsMagic, "rank blob corrupt");
    TC_ENFORCE_EQ(int(peerChannels), expectChannels,
                  "TPUCOLL_CHANNELS mismatch across ranks: peer uses ",
                  peerChannels, ", this rank uses ", expectChannels);
    const size_t want = size_t(8) * n * (peerChannels - 1);
    TC_ENFORCE_GE(blob.size(), off + 8 + want, "rank blob truncated");
    channelIds->resize(n * (peerChannels - 1));
    std::memcpy(channelIds->data(), blob.data() + off + 8, want);
  } else {
    TC_ENFORCE_EQ(expectChannels, 1,
                  "TPUCOLL_CHANNELS mismatch across ranks: peer uses 1, "
                  "this rank uses ", expectChannels);
  }
}

}  // namespace

Context::Context(std::shared_ptr<Device> device, int rank, int size)
    : device_(std::move(device)), rank_(rank), size_(size) {
  TC_ENFORCE(rank >= 0 && rank < size, "bad rank ", rank, " for size ", size);
  pairs_.resize(size);
  channelPairs_.resize(size);
  pairErrors_.resize(size);
  stashBytes_.resize(size, 0);
  rxPaused_.resize(size, 0);
  stripeStageBytes_.resize(size, 0);
  stripePausedMask_.resize(size, 0);
  // Strict parses (common/env.h): malformed knobs throw here, at context
  // construction, instead of silently running with a default.
  stashHighWater_ =
      std::max<size_t>(envBytes("TPUCOLL_MAX_STASH_BYTES", 64u << 20),
                       1u << 20);
  const long envCh =
      envCount("TPUCOLL_CHANNELS", 0, 1, kMaxStripeChannels);
  if (envCh > 0) {
    channels_ = static_cast<int>(envCh);
    channelsFromEnv_ = true;
  }
  const uint64_t envStripe = envBytes("TPUCOLL_STRIPE_BYTES", 0);
  if (envStripe > 0) {
    // Floor keeps every stripe non-empty and the per-stripe header
    // overhead negligible.
    stripeBytes_ = std::max<uint64_t>(envStripe, 4096);
    stripeBytesFromEnv_ = true;
  }
}

void Context::setChannelConfig(int channels, uint64_t stripeBytes) {
  for (const auto& p : pairs_) {
    TC_ENFORCE(p == nullptr,
               "setChannelConfig must run before the mesh is created");
  }
  if (!channelsFromEnv_ && channels > 0) {
    TC_ENFORCE(channels <= static_cast<int>(kMaxStripeChannels),
               "channels must be in [1, ", kMaxStripeChannels, "], got ",
               channels);
    channels_ = channels;
  }
  if (!stripeBytesFromEnv_ && stripeBytes > 0) {
    stripeBytes_ = std::max<uint64_t>(stripeBytes, 4096);
  }
}

Context::~Context() {
  close();
  // Loop-thread teardowns may still reference this context (onPairError /
  // matchIncoming / stripeIncoming); pairs shard across the whole loop
  // pool, so quiesce EVERY loop before members are freed.
  device_->barrierAllLoops();
  graveyard_.clear();
  inboundPairs_.clear();
  channelPairs_.clear();
  pairs_.clear();
}

std::vector<uint8_t> Context::prepareFullMesh() {
  std::vector<uint64_t> pairIds(size_, 0);
  std::vector<uint64_t> channelIds(
      channels_ > 1 ? size_t(size_) * (channels_ - 1) : 0, 0);
  for (int j = 0; j < size_; j++) {
    if (j == rank_) {
      continue;
    }
    // Round-robin loop sharding: channel c of the pair toward peer j
    // lands on loop (j*C + c) % numLoops, so with numLoops >= channels
    // every channel of one logical pair progresses on a distinct loop
    // thread.
    const uint64_t key0 = uint64_t(j) * channels_;
    pairs_[j] = std::make_unique<Pair>(this, device_->loopFor(key0), rank_,
                                       j, device_->nextPairId(), 0,
                                       device_->loopIndexFor(key0));
    pairIds[j] = pairs_[j]->localPairId();
    channelPairs_[j].clear();
    for (int c = 1; c < channels_; c++) {
      const uint64_t key = key0 + c;
      channelPairs_[j].push_back(std::make_unique<Pair>(
          this, device_->loopFor(key), rank_, j, device_->nextPairId(), c,
          device_->loopIndexFor(key)));
      channelIds[size_t(j) * (channels_ - 1) + (c - 1)] =
          channelPairs_[j].back()->localPairId();
    }
  }
  // Lower rank listens, higher rank initiates: register expectations first
  // so an early initiator finds a parked or expected pair either way.
  for (int j = rank_ + 1; j < size_; j++) {
    pairs_[j]->expectViaListener(device_->listener());
    for (auto& cp : channelPairs_[j]) {
      cp->expectViaListener(device_->listener());
    }
  }
  return packRankBlob(size_, device_->address(), pairIds, channels_,
                      channelIds);
}

void Context::connectWithBlobs(
    const std::vector<std::vector<uint8_t>>& blobs,
    std::chrono::milliseconds timeout) {
  TC_ENFORCE_EQ(blobs.size(), static_cast<size_t>(size_));
  // Parse EVERY peer's blob up front, once: a configuration mismatch
  // (e.g. disagreeing TPUCOLL_CHANNELS) must fail loudly on every
  // rank — not just on the ranks that need the blob for an outbound
  // connection (the others would time out waiting for a peer that
  // already aborted) — and the connect loop below reuses the parses.
  std::vector<SockAddr> peerAddrs(size_);
  std::vector<std::vector<uint64_t>> peerPairIds(size_);
  std::vector<std::vector<uint64_t>> peerChannelIds(size_);
  for (int j = 0; j < size_; j++) {
    if (j == rank_) {
      continue;
    }
    unpackRankBlob(blobs[j], size_, channels_, &peerAddrs[j],
                   &peerPairIds[j], &peerChannelIds[j]);
  }
  // Connect only toward lower ranks; higher ranks initiate to us. Every
  // data channel is its own connection with its own handshake (and, on
  // encrypted devices, its own derived AEAD keys).
  for (int j = 0; j < rank_; j++) {
    pairs_[j]->connect(peerAddrs[j], peerPairIds[j][rank_], timeout);
    for (int c = 1; c < channels_; c++) {
      channelPairs_[j][c - 1]->connect(
          peerAddrs[j],
          peerChannelIds[j][size_t(rank_) * (channels_ - 1) + (c - 1)],
          timeout);
    }
  }
  for (int j = 0; j < size_; j++) {
    if (j != rank_) {
      pairs_[j]->waitConnected(timeout);
      for (auto& cp : channelPairs_[j]) {
        cp->waitConnected(timeout);
      }
    }
  }
  TC_DEBUG("rank ", rank_, ": full mesh of ", size_, " connected via ",
           device_->str(), " (", channels_, " channel(s)/pair, stripe >= ",
           stripeBytes_, " bytes)");
}

void Context::connectFullMesh(Store& store,
                              std::chrono::milliseconds timeout) {
  auto myBlob = prepareFullMesh();
  store.set(rankKey(rank_), myBlob);

  std::vector<std::string> keys;
  for (int j = 0; j < size_; j++) {
    if (j != rank_) {
      keys.push_back(rankKey(j));
    }
  }
  auto peerBlobs = store.multiGet(keys, timeout);
  std::vector<std::vector<uint8_t>> blobs(size_);
  size_t idx = 0;
  for (int j = 0; j < size_; j++) {
    blobs[j] = (j == rank_) ? myBlob : std::move(peerBlobs[idx++]);
  }
  connectWithBlobs(blobs, timeout);
}

std::unique_ptr<UnboundBuffer> Context::createUnboundBuffer(void* ptr,
                                                            size_t size) {
  // Registration counter the plan cache's steady-state contract keys
  // on: a warm planned loop must hold this at a zero delta.
  if (metrics_ != nullptr) {
    metrics_->recordUbufCreate();
  }
  return std::make_unique<UnboundBuffer>(this, ptr, size);
}

uint64_t Context::registerRegion(char* ptr, size_t size,
                                 UnboundBuffer* owner) {
  std::lock_guard<std::mutex> guard(mu_);
  const uint64_t token = nextRegionToken_++;
  regions_[token] = Region{ptr, size, owner};
  return token;
}

void Context::unregisterRegion(uint64_t token) {
  std::lock_guard<std::mutex> guard(mu_);
  regions_.erase(token);
}

bool Context::readRegion(uint64_t token, uint64_t roffset, uint64_t nbytes,
                         std::vector<char>* out) {
  std::lock_guard<std::mutex> guard(mu_);
  auto it = regions_.find(token);
  if (it == regions_.end() || roffset > it->second.size ||
      nbytes > it->second.size - roffset) {
    return false;
  }
  out->assign(it->second.ptr + roffset, it->second.ptr + roffset + nbytes);
  return true;
}

bool Context::writeRegion(uint64_t token, uint64_t roffset,
                          const char* data, size_t nbytes, bool notify,
                          int srcRank) {
  std::lock_guard<std::mutex> guard(mu_);
  auto it = regions_.find(token);
  if (it == regions_.end() || roffset > it->second.size ||
      nbytes > it->second.size - roffset) {
    return false;
  }
  std::memcpy(it->second.ptr + roffset, data, nbytes);
  if (notify && it->second.owner != nullptr) {
    // Under mu_ by design (see header): ~UnboundBuffer unregisters under
    // this same mutex first, so no notification can outlive the owner.
    // onRegionPutArrived skips pending-recv accounting — nothing was
    // posted for a one-sided arrival.
    it->second.owner->onRegionPutArrived(srcRank);
  }
  return true;
}

namespace {

// Shared failure tail of the striped fan-outs: nothing was enqueued ->
// plain cancel (the single-channel contract: a throwing post leaves the
// buffer clean and reusable); otherwise mark the logical op failed,
// resolve the never-enqueued stripes, and let the LAST resolution
// (possibly a sibling's wire completion on another loop) deliver the
// single onSendError — never before the buffer's memory is quiescent.
void resolveAbortedStripes(UnboundBuffer* buf,
                           const std::shared_ptr<StripeTx>& st,
                           int enqueued, int channels, const char* what) {
  if (enqueued == 0) {
    buf->cancelPendingSend();
    return;
  }
  st->recordError(detail::strCat("striped ", what,
                                 " aborted: a data channel refused "
                                 "the stripe"));
  const int missing = channels - enqueued;
  // Acq-rel: the final decrementer completes the stripe and must
  // observe every sibling channel's writes (error strings, landed
  // payload); siblings' decrements must publish them.
  if (st->remaining.fetch_sub(missing, std::memory_order_acq_rel) ==
      missing) {
    // Copy under errMu: a sibling stripe's failure may be recording
    // concurrently.
    std::string msg;
    {
      std::lock_guard<std::mutex> guard(st->errMu);
      msg = st->error;
    }
    buf->onSendError(msg);
  }
}

}  // namespace

void Context::postPut(UnboundBuffer* buf, int dstRank, uint64_t token,
                      uint64_t roffset, char* data, size_t nbytes,
                      bool notify) {
  TC_ENFORCE(dstRank >= 0 && dstRank < size_, "bad destination rank ",
             dstRank);
  if (dstRank == rank_) {
    // Local put: straight into the registered region (one memcpy under
    // the region lock, no staging copy).
    buf->addPendingSend();
    if (!writeRegion(token, roffset, data, nbytes, notify, rank_)) {
      buf->cancelPendingSend();
      TC_THROW(EnforceError, "local put outside the registered region");
    }
    buf->onSendComplete();
    return;
  }
  buf->addPendingSend();
  Pair* pair = nullptr;
  bool pinned = false;
  {
    std::unique_lock<std::mutex> lock(mu_);
    if (closed_ || !pairErrors_[dstRank].empty()) {
      buf->cancelPendingSend();
      TC_THROW(IoException, "put to rank ", dstRank, ": ",
               closed_ ? "context closed" : pairErrors_[dstRank].c_str());
    }
    try {
      pair = outboundForLocked(dstRank, lock, &pinned);
    } catch (...) {
      buf->cancelPendingSend();
      throw;
    }
    TC_ENFORCE(pair != nullptr, "no pair for rank ", dstRank);
  }
  try {
    // Non-notify puts stripe like sends (each stripe is an independent
    // one-sided write of a disjoint range — no receiver-side reassembly
    // needed). Notify puts stay whole: the arrival notification must fire
    // after ALL bytes land, and cross-channel arrival order is undefined.
    if (channels_ > 1 && !notify && nbytes >= stripeBytes_ &&
        nbytes >= static_cast<size_t>(channels_) && !pair->shmActive()) {
      buf->cancelPendingSend();  // postPutStriped re-adds exactly once
      postPutStriped(buf, dstRank, token, roffset, data, nbytes);
    } else {
      try {
        pair->sendPut(buf, token, roffset, data, nbytes, notify);
      } catch (...) {
        buf->cancelPendingSend();
        throw;
      }
    }
  } catch (...) {
    if (pinned) {
      unpinLazy(dstRank);
    }
    throw;
  }
  if (pinned) {
    unpinLazy(dstRank);
  }
}

void Context::postPutStriped(UnboundBuffer* buf, int dstRank,
                             uint64_t token, uint64_t roffset, char* data,
                             size_t nbytes) {
  buf->addPendingSend();
  auto st = std::make_shared<StripeTx>(channels_);
  int enqueued = 0;
  try {
    for (int c = 0; c < channels_; c++) {
      const uint64_t off = stripeOffset(nbytes, channels_, c);
      const uint64_t span = stripeSpan(nbytes, channels_, c);
      pairFor(dstRank, c)->sendPut(buf, token, roffset + off, data + off,
                                   span, /*notify=*/false, st);
      enqueued++;
    }
  } catch (...) {
    resolveAbortedStripes(buf, st, enqueued, channels_, "put");
    throw;
  }
}

void Context::postGetRequest(int dstRank, uint64_t respSlot, uint64_t token,
                             uint64_t roffset, size_t nbytes) {
  TC_ENFORCE(dstRank >= 0 && dstRank < size_, "bad source rank ", dstRank);
  if (dstRank == rank_) {
    // Local get: read the region, then deliver through the shared
    // stash/posted matcher like any self-sourced message.
    std::vector<char> data;
    TC_ENFORCE(readRegion(token, roffset, nbytes, &data),
               "local get outside the registered region");
    stashArrived(rank_, respSlot, std::move(data));
    return;
  }
  Pair* pair = nullptr;
  bool pinned = false;
  {
    std::unique_lock<std::mutex> lock(mu_);
    if (closed_ || !pairErrors_[dstRank].empty()) {
      TC_THROW(IoException, "get from rank ", dstRank, ": ",
               closed_ ? "context closed" : pairErrors_[dstRank].c_str());
    }
    pair = outboundForLocked(dstRank, lock, &pinned);
    TC_ENFORCE(pair != nullptr, "no pair for rank ", dstRank);
  }
  WireGetReq req{token, roffset, nbytes};
  std::vector<char> payload(sizeof(req));
  std::memcpy(payload.data(), &req, sizeof(req));
  WireHeader header{kMsgMagic, static_cast<uint8_t>(Opcode::kGetReq),
                    0, {0, 0}, respSlot, sizeof(req), 0};
  try {
    pair->sendOwned(header, std::move(payload));
  } catch (...) {
    if (pinned) {
      unpinLazy(dstRank);
    }
    throw;
  }
  if (pinned) {
    unpinLazy(dstRank);
  }
}

void Context::close() {
  bool wasLazy;
  uint32_t meshId;
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (closed_) {
      return;
    }
    closed_ = true;
    wasLazy = lazy_;
    meshId = meshId_;
  }
  if (wasLazy) {
    // Stop routing new broker-dialed inbound connections here before the
    // pair tables start draining.
    device_->unregisterLazyMesh(meshId);
  }
  // Snapshot the pair tables under mu_ and close outside it (Pair::close
  // blocks on loop barriers that themselves take mu_ via onPairError).
  // With the lazy broker the tables mutate at any time — loop threads
  // quiet-drop entries into the graveyard and app threads install dials —
  // so the pre-lazy lock-free walk here was a use-after-free against a
  // concurrent graveyard reallocation. Every entry snapshotted stays
  // alive: closed_ (set above) makes dials refuse under mu_, and the only
  // destroyer — the dial-time graveyard reap — first unlinks its victims
  // from graveyard_ while holding mu_, so it can never free a pair this
  // snapshot saw. Quiet drops only MOVE pairs between tables, which the
  // raw-pointer snapshot is indifferent to.
  std::vector<Pair*> toClose;
  {
    std::lock_guard<std::mutex> guard(mu_);
    for (auto& pair : pairs_) {
      if (pair) {
        toClose.push_back(pair.get());
      }
    }
    for (auto& cps : channelPairs_) {
      for (auto& cp : cps) {
        if (cp) {
          toClose.push_back(cp.get());
        }
      }
    }
    for (auto& ips : inboundPairs_) {
      for (auto& ip : ips) {
        if (ip) {
          toClose.push_back(ip.get());
        }
      }
    }
    for (auto& g : graveyard_) {
      if (g) {
        toClose.push_back(g.get());  // defunct entries no-op on close
      }
    }
  }
  for (Pair* pair : toClose) {
    pair->close();
  }
  // Fail receives that will now never complete — posted ones and those
  // claimed by an in-flight stripe reassembly.
  std::vector<UnboundBuffer*> victims;
  {
    std::lock_guard<std::mutex> guard(mu_);
    for (auto& pr : posted_) {
      victims.push_back(pr.ubuf);
    }
    posted_.clear();
    // Every pair (all channels) was closed above — teardown del()s the
    // fd with a loop-tick barrier — so no channel rx still writes into
    // any reassembly buffer and everything can be reaped.
    for (int r = 0; r < size_; r++) {
      dropStripesLocked(r, "context closed", /*channel=*/-1,
                        /*allQuiesced=*/true, &victims);
    }
    stashed_.clear();
    std::fill(stashBytes_.begin(), stashBytes_.end(), 0);
    std::fill(stripeStageBytes_.begin(), stripeStageBytes_.end(), 0);
    std::fill(stripePausedMask_.begin(), stripePausedMask_.end(), 0);
  }
  for (auto* b : victims) {
    b->onRecvError("context closed");
  }
}

std::list<Context::PostedRecv>::iterator Context::findPosted(int srcRank,
                                                             uint64_t slot,
                                                             size_t nbytes) {
  for (auto it = posted_.begin(); it != posted_.end(); ++it) {
    if (it->slot == slot && it->allowed[srcRank]) {
      TC_ENFORCE_EQ(it->nbytes, nbytes,
                    "message size mismatch on slot ", slot, " from rank ",
                    srcRank, ": posted ", it->nbytes, " incoming ", nbytes);
      return it;
    }
  }
  return posted_.end();
}

void Context::landPayload(char* dest, RecvReduceFn combine,
                          size_t combineElsize, const char* data,
                          size_t nbytes) {
  if (nbytes == 0) {
    // Zero-byte payloads (barrier-style slots) may carry data ==
    // nullptr; memcpy with a null pointer is UB even when n == 0.
    return;
  }
  if (combine != nullptr) {
    combine(dest, data, nbytes / combineElsize);
  } else {
    std::memcpy(dest, data, nbytes);
  }
}

void Context::landPayload(const PostedRecv& pr, const char* data,
                          size_t nbytes) {
  landPayload(pr.dest, pr.combine, pr.combineElsize, data, nbytes);
}

void Context::postSend(UnboundBuffer* buf, int dstRank, uint64_t slot,
                       char* data, size_t nbytes) {
  TC_ENFORCE(dstRank >= 0 && dstRank < size_, "bad destination rank ",
             dstRank);
  buf->addPendingSend();
  if (dstRank == rank_) {
    // Self-send: deliver through the matcher immediately. The payload is
    // copied eagerly so the sender may reuse its buffer after waitSend.
    UnboundBuffer* rbuf = nullptr;
    {
      std::lock_guard<std::mutex> guard(mu_);
      auto it = findPosted(rank_, slot, nbytes);
      if (it != posted_.end()) {
        landPayload(*it, data, nbytes);
        rbuf = it->ubuf;
        posted_.erase(it);
      } else {
        stashed_.push_back(
            Stash{rank_, slot, std::vector<char>(data, data + nbytes)});
      }
    }
    if (rbuf != nullptr) {
      rbuf->onRecvComplete(rank_, slot);
    }
    buf->onSendComplete();
    return;
  }
  Pair* pair = nullptr;
  bool pinned = false;
  {
    std::unique_lock<std::mutex> lock(mu_);
    if (closed_) {
      buf->cancelPendingSend();
      TC_THROW(IoException, "send on closed context");
    }
    if (!pairErrors_[dstRank].empty()) {
      buf->cancelPendingSend();
      TC_THROW(IoException, "send to failed rank ", dstRank, ": ",
               pairErrors_[dstRank]);
    }
    try {
      pair = outboundForLocked(dstRank, lock, &pinned);
    } catch (...) {
      buf->cancelPendingSend();
      throw;
    }
    TC_ENFORCE(pair != nullptr, "no pair for rank ", dstRank);
  }
  try {
    // Stripe large payloads across the pair's data channels (perf path:
    // TCP stack work, stash memcpys, and per-connection encryption then
    // run concurrently on several loop threads). The shm plane already
    // sidesteps the TCP serialization for same-host peers, so an shm
    // pair keeps the single-connection path.
    if (channels_ > 1 && nbytes >= stripeBytes_ &&
        nbytes >= static_cast<size_t>(channels_) && !pair->shmActive()) {
      buf->cancelPendingSend();  // postSendStriped re-adds exactly once
      postSendStriped(buf, dstRank, slot, data, nbytes);
    } else {
      try {
        pair->send(buf, slot, data, nbytes);
      } catch (...) {
        buf->cancelPendingSend();
        throw;
      }
    }
  } catch (...) {
    if (pinned) {
      unpinLazy(dstRank);
    }
    throw;
  }
  if (pinned) {
    unpinLazy(dstRank);
  }
}

void Context::postSendStriped(UnboundBuffer* buf, int dstRank,
                              uint64_t slot, char* data, size_t nbytes) {
  buf->addPendingSend();
  auto st = std::make_shared<StripeTx>(channels_);
  // Relaxed: per-pair wire tag allocator — uniqueness only.
  const uint8_t seqLow = static_cast<uint8_t>(
      stripeSeq_.fetch_add(1, std::memory_order_relaxed));
  int enqueued = 0;
  try {
    for (int c = 0; c < channels_; c++) {
      const uint64_t off = stripeOffset(nbytes, channels_, c);
      const uint64_t span = stripeSpan(nbytes, channels_, c);
      pairFor(dstRank, c)->sendStripe(
          buf, slot, data + off, span, nbytes,
          static_cast<uint8_t>(channels_), seqLow, st);
      enqueued++;
    }
  } catch (...) {
    resolveAbortedStripes(buf, st, enqueued, channels_, "send");
    throw;
  }
}

void Context::postRecv(UnboundBuffer* buf, const std::vector<int>& srcRanks,
                       uint64_t slot, char* dest, size_t nbytes,
                       RecvReduceFn combine, size_t combineElsize,
                       size_t combineAccElsize) {
  if (combineAccElsize == 0) {
    combineAccElsize = combineElsize;
  }
  buf->addPendingRecv();
  bool fromStash = false;
  int stashSrc = -1;
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (closed_) {
      buf->cancelPendingRecv();
      TC_THROW(IoException, "recv on closed context");
    }
    std::vector<char> allowed(size_, 0);
    int liveAllowed = 0;
    for (int r : srcRanks) {
      TC_ENFORCE(r >= 0 && r < size_, "bad source rank ", r);
      allowed[r] = 1;
      if (pairErrors_[r].empty()) {
        liveAllowed++;
      }
    }
    // Earliest matching early-arrival wins (FIFO fairness across sources).
    // The stash is consulted before the liveness check: data a peer
    // delivered before departing is still consumable.
    for (auto it = stashed_.begin(); it != stashed_.end(); ++it) {
      if (it->slot == slot && allowed[it->srcRank]) {
        TC_ENFORCE_EQ(it->data.size(), nbytes,
                      "stashed message size mismatch on slot ", slot);
        landPayload(dest, combine, combineElsize, it->data.data(), nbytes);
        stashSrc = it->srcRank;
        if (stashSrc != rank_) {
          stashBytes_[stashSrc] -= it->data.size();
        }
        stashed_.erase(it);
        fromStash = true;
        break;
      }
    }
    // Backpressure release policy: if this recv drained from the stash,
    // resume its source only once the stash falls below the low watermark
    // (an unconditional resume would refill faster than one-per-recv
    // drains, growing the stash without bound). If the recv could NOT be
    // satisfied locally, the wanted message is still on the wire: resume
    // every admissible paused source so it can arrive — it is the oldest
    // in-stream, so it lands in this posted recv before the flood stashes.
    if (fromStash) {
      if (stashSrc != rank_ && rxPaused_[stashSrc] &&
          hasAnyPairLocked(stashSrc) &&
          stashBytes_[stashSrc] < stashHighWater_ / 2) {
        rxPaused_[stashSrc] = 0;
        resumePeerLocked(stashSrc);  // under mu_: see stashArrived
      }
    } else {
      for (int r : srcRanks) {
        if (rxPaused_[r] && hasAnyPairLocked(r)) {
          rxPaused_[r] = 0;
          resumePeerLocked(r);
        }
      }
    }
    if (!fromStash && liveAllowed == 0) {
      buf->cancelPendingRecv();
      TC_THROW(IoException, "recv: all source ranks failed (first error: ",
               pairErrors_[srcRanks[0]], ")");
    }
    if (!fromStash) {
      posted_.push_back(PostedRecv{buf, slot, dest, nbytes,
                                   std::move(allowed), combine,
                                   combineElsize, combineAccElsize});
    }
  }
  if (fromStash) {
    buf->onRecvComplete(stashSrc, slot);
  }
}

void Context::cancelRecvsFor(UnboundBuffer* buf) {
  int cancelled = 0;
  {
    std::lock_guard<std::mutex> guard(mu_);
    for (auto it = posted_.begin(); it != posted_.end();) {
      if (it->ubuf == buf) {
        it = posted_.erase(it);
        cancelled++;
      } else {
        ++it;
      }
    }
  }
  for (int i = 0; i < cancelled; i++) {
    buf->cancelPendingRecv();
  }
}

int Context::cancelSendsFor(UnboundBuffer* buf) {
  // Only plain (non-striped) queued sends are cancellable, and those
  // live exclusively on the primary pairs: striped ops are pinned in
  // their queues (cancelQueuedSends skips them — a sibling stripe may
  // already be on the wire, and shipping a partial message would hang
  // the receiver's reassembly) and resolve via wire completion or via
  // failPairsWithInflightSend failing their pair.
  int cancelled = 0;
  for (auto& pair : pairs_) {
    if (pair) {
      cancelled += pair->cancelQueuedSends(buf);
    }
  }
  for (int i = 0; i < cancelled; i++) {
    buf->cancelPendingSend();
  }
  return cancelled;
}

void Context::failPairsWithInflightSend(UnboundBuffer* buf) {
  for (auto& pair : pairs_) {
    if (pair && pair->hasInflightSend(buf)) {
      pair->failFromUser(
          "send dropped: buffer destroyed while payload was in flight");
    }
  }
  for (auto& cps : channelPairs_) {
    for (auto& cp : cps) {
      if (cp && cp->hasInflightSend(buf)) {
        cp->failFromUser(
            "send dropped: buffer destroyed while payload was in flight");
      }
    }
  }
  // Receive analog for stripe reassembly: a recv claimed by an entry in
  // stripes_ left posted_ (so cancelRecvsFor cannot see it) and only
  // completes when the remaining stripes land. If the buffer is being
  // destroyed while such an entry is open, fail the source's channel
  // pairs — their teardown drops/poisons the entry and errors the
  // claimed recv, unblocking the destructor.
  std::vector<int> stripeSrcs;
  {
    std::lock_guard<std::mutex> guard(mu_);
    for (const auto& e : stripes_) {
      if (e.ubuf == buf) {
        stripeSrcs.push_back(e.srcRank);
      }
    }
  }
  for (int src : stripeSrcs) {
    if (pairs_[src]) {
      pairs_[src]->failFromUser(
          "recv dropped: buffer destroyed while stripes were in flight");
    }
    for (auto& cp : channelPairs_[src]) {
      if (cp) {
        cp->failFromUser(
            "recv dropped: buffer destroyed while stripes were in flight");
      }
    }
    if (lazy_) {
      // The stripes actually arrive on the peer's dialed connections.
      for (auto& ip : inboundPairs_[src]) {
        if (ip) {
          ip->failFromUser(
              "recv dropped: buffer destroyed while stripes were in flight");
        }
      }
    }
  }
}

void Context::pausePeerLocked(int rank) {
  // Backpressure must cover every channel: a striped flood arrives on
  // all of them, and pausing only the primary would let the stripes
  // keep filling the reassembly list. In lazy mode the peer's payload
  // traffic arrives on its dialed (our inbound) connections, so those
  // must pause too.
  if (pairs_[rank]) {
    pairs_[rank]->pauseReading();
  }
  for (auto& cp : channelPairs_[rank]) {
    if (cp) {
      cp->pauseReading();
    }
  }
  if (lazy_) {
    for (auto& ip : inboundPairs_[rank]) {
      if (ip) {
        ip->pauseReading();
      }
    }
  }
}

void Context::resumePeerLocked(int rank) {
  if (pairs_[rank]) {
    pairs_[rank]->resumeReading();
  }
  for (auto& cp : channelPairs_[rank]) {
    if (cp) {
      cp->resumeReading();
    }
  }
  if (lazy_) {
    for (auto& ip : inboundPairs_[rank]) {
      if (ip) {
        ip->resumeReading();
      }
    }
  }
  // A full-peer resume also lifts any stage-backpressure pauses
  // (resumeReading is idempotent; the mask must not go stale).
  stripePausedMask_[rank] = 0;
}

void Context::accountStageLocked(int srcRank, size_t bytes) {
  stripeStageBytes_[srcRank] += bytes;
  maybePauseAheadChannelsLocked(srcRank);
}

void Context::maybePauseAheadChannelsLocked(int srcRank) {
  if (stripeStageBytes_[srcRank] <= stashHighWater_ || srcRank == rank_ ||
      rxPaused_[srcRank] || !hasAnyPairLocked(srcRank)) {
    return;
  }
  // A channel is "ahead" when every open entry from this source already
  // has its stripe fully landed: pausing it cannot block any open
  // entry's completion. At least one channel always stays readable —
  // every open entry has an unlanded stripe, and its channel fails the
  // landedMask test — so the stage bytes keep draining and the pause is
  // guaranteed to lift at the low watermark.
  uint32_t ahead = (channels_ >= 32)
                       ? ~uint32_t(0)
                       : ((uint32_t(1) << channels_) - 1);
  for (const auto& e : stripes_) {
    if (e.srcRank == srcRank) {
      ahead &= e.landedMask;
    }
  }
  ahead &= ~stripePausedMask_[srcRank];
  if (ahead == 0) {
    return;
  }
  if (stripePausedMask_[srcRank] == 0 && metrics_ != nullptr) {
    metrics_->recordStashPause(srcRank);
  }
  for (int c = 0; c < channels_; c++) {
    if (ahead & (uint32_t(1) << c)) {
      Pair* p = pairFor(srcRank, c);
      if (p != nullptr) {
        p->pauseReading();
      }
      if (lazy_ && static_cast<size_t>(c) < inboundPairs_[srcRank].size() &&
          inboundPairs_[srcRank][c]) {
        inboundPairs_[srcRank][c]->pauseReading();
      }
      stripePausedMask_[srcRank] |= uint32_t(1) << c;
    }
  }
}

void Context::releaseStageLocked(int srcRank, size_t bytes) {
  stripeStageBytes_[srcRank] -= bytes;
  if (stripePausedMask_[srcRank] != 0 && !rxPaused_[srcRank] &&
      stripeStageBytes_[srcRank] < stashHighWater_ / 2) {
    const uint32_t mask = stripePausedMask_[srcRank];
    stripePausedMask_[srcRank] = 0;
    for (int c = 0; c < channels_; c++) {
      if ((mask & (uint32_t(1) << c)) == 0) {
        continue;
      }
      if (pairFor(srcRank, c) != nullptr) {
        pairFor(srcRank, c)->resumeReading();
      }
      if (lazy_ && static_cast<size_t>(c) < inboundPairs_[srcRank].size() &&
          inboundPairs_[srcRank][c]) {
        inboundPairs_[srcRank][c]->resumeReading();
      }
    }
  }
}

Context::StripeMatch Context::stripeIncoming(int srcRank, uint64_t slot,
                                             uint8_t seqLow, uint64_t total,
                                             uint32_t count,
                                             uint32_t index) {
  const uint32_t bit = 1u << index;
  std::vector<char> stage;  // sized OUTSIDE mu_ when a stage is needed
  for (;;) {
    std::unique_lock<std::mutex> guard(mu_);
    for (auto& e : stripes_) {
      if (e.srcRank == srcRank && e.slot == slot && e.seqLow == seqLow &&
          e.total == total && e.count == count &&
          (e.arrivedMask & bit) == 0) {
        // Oldest key-matching entry this channel has not yet fed. The
        // bit check also covers the 8-bit seq tag wrapping under extreme
        // channel skew (256 same-key messages in flight): the wrapped
        // message simply opens a fresh entry below, and per-channel FIFO
        // keeps oldest-without-bit the correct home for every stripe.
        e.arrivedMask |= bit;
        char* base =
            (e.direct && e.combine == nullptr) ? e.dest : e.buf.data();
        return {base + stripeOffset(total, count, index), e.id};
      }
    }
    // First stripe of this message: claim a posted receive exactly like
    // matchIncoming would (throws on size mismatch), or start a stash
    // reassembly. Entry creation order tracks logical-message order per
    // (src, slot) — a later message's first stripe can only arrive after
    // its channel delivered every earlier message's stripe, and this
    // channel's delivery completes its install before the next header is
    // read, even across the allocation gap below — so claims observe the
    // same FIFO the single-connection path has.
    //
    // A source that already failed can never complete a NEWLY OPENED
    // message: at least one of its channels is gone, and any message
    // whose full stripe set made it out completed through entries opened
    // before the failure (per-channel FIFO), so a set opened now is
    // permanently short. Sink the payload into a born-dead entry —
    // claiming a posted receive here would strand a buffer another live
    // source could still serve — reaped by stripeLanded's dead path.
    const bool bornDead = !pairErrors_[srcRank].empty();
    auto it = bornDead ? posted_.end() : findPosted(srcRank, slot, total);
    const bool needStage =
        it == posted_.end() || it->combine != nullptr;
    if (needStage && stage.size() != total) {
      // The (possibly multi-MiB, zero-filling) stage allocation must not
      // run under mu_ — it would stall every other channel's matching
      // and all post/stash accounting. Drop the lock, size it, rescan: a
      // sibling stripe may have installed the entry meanwhile (then the
      // match above wins and this allocation is discarded), and the
      // posted claim is re-resolved fresh after relocking.
      guard.unlock();
      stage.resize(total);
      continue;
    }
    StripeEntry e;
    e.id = nextStripeEntry_++;
    e.srcRank = srcRank;
    e.slot = slot;
    e.seqLow = seqLow;
    e.total = total;
    e.count = count;
    e.arrivedMask = bit;
    if (bornDead) {
      e.dead = true;
      e.error = pairErrors_[srcRank];
    }
    if (it != posted_.end()) {
      e.direct = true;
      e.ubuf = it->ubuf;
      e.dest = it->dest;
      e.combine = it->combine;
      e.combineElsize = it->combineElsize;
      if (e.combine != nullptr) {
        // Fused recvReduce: byte-offset stripes may split an element
        // across channels, so stripes stage here and the fold runs once,
        // at completion, over the whole message.
        e.buf = std::move(stage);
      }
      posted_.erase(it);
    } else {
      e.buf = std::move(stage);
    }
    stripes_.push_back(std::move(e));
    StripeEntry& ne = stripes_.back();
    if (!ne.direct) {
      // Unmatched stage: counts against the in-flight reassembly
      // watermark (a claimed recv's stage is bounded by what the app
      // posted and is not counted). Accounted AFTER the push so the
      // pause scan sees this entry — its unlanded stripe keeps the
      // delivering channel readable.
      accountStageLocked(srcRank, total);
    }
    char* base =
        (ne.direct && ne.combine == nullptr) ? ne.dest : ne.buf.data();
    return {base + stripeOffset(total, count, index), ne.id};
  }
}

void Context::stripeLanded(int srcRank, uint64_t entry, uint32_t index) {
  UnboundBuffer* rbuf = nullptr;
  UnboundBuffer* errBuf = nullptr;
  std::string errMsg;
  std::vector<char> stashPayload;
  uint64_t slot = 0;
  bool toStash = false;
  char* foldDest = nullptr;
  RecvReduceFn foldFn = nullptr;
  size_t foldElsize = 0;
  uint64_t foldTotal = 0;
  {
    std::lock_guard<std::mutex> guard(mu_);
    auto it = stripes_.begin();
    while (it != stripes_.end() && it->id != entry) {
      ++it;
    }
    if (it == stripes_.end()) {
      return;  // reaped after a quiesced failure / close
    }
    it->landedMask |= 1u << index;
    const uint32_t full = (1u << it->count) - 1;
    if (it->dead) {
      // A dead entry can NEVER complete successfully: any entry still
      // in the list when its source failed was incomplete (a complete
      // one is erased at its last stripeLanded, under this same mu_),
      // and dropStripesLocked force-marks the dead channel's half-read
      // stripe as landed — its byte range is a hole, not data. Reap
      // with the deferred error once no sibling still writes.
      if (it->landedMask == it->arrivedMask) {
        errBuf = it->ubuf;
        errMsg = it->error;
        if (!it->direct) {
          releaseStageLocked(srcRank, it->total);
        }
        stripes_.erase(it);
      }
    } else if (it->landedMask == full) {
      // Every stripe landed: deliver. The (possibly multi-MiB)
      // recvReduce fold runs OUTSIDE mu_ — the entry is off the list,
      // so nothing else references its stage.
      if (it->direct) {
        if (it->combine != nullptr) {
          foldDest = it->dest;
          foldFn = it->combine;
          foldElsize = it->combineElsize;
          foldTotal = it->total;
          stashPayload = std::move(it->buf);  // the stage to fold from
        }
        rbuf = it->ubuf;
        slot = it->slot;
      } else {
        toStash = true;
        slot = it->slot;
        stashPayload = std::move(it->buf);
        releaseStageLocked(srcRank, it->total);
      }
      stripes_.erase(it);
    } else {
      // Entry stays open: this channel may have just become "ahead" of
      // every open entry — re-evaluate the stage backpressure.
      maybePauseAheadChannelsLocked(srcRank);
    }
  }
  if (foldFn != nullptr) {
    landPayload(foldDest, foldFn, foldElsize, stashPayload.data(),
                foldTotal);
  }
  if (rbuf != nullptr) {
    rbuf->onRecvComplete(srcRank, slot);
  }
  if (errBuf != nullptr) {
    errBuf->onRecvError(errMsg);
  }
  if (toStash) {
    // The normal race-closing stash path: re-checks posted receives,
    // accounts watermarks, and pauses the peer when flooded.
    stashArrived(srcRank, slot, std::move(stashPayload));
  }
}

void Context::dropStripesLocked(int rank, const std::string& message,
                                int channel, bool allQuiesced,
                                std::vector<UnboundBuffer*>* victims) {
  for (auto it = stripes_.begin(); it != stripes_.end();) {
    if (it->srcRank != rank) {
      ++it;
      continue;
    }
    if (channel >= 0) {
      // The failing channel's rx is quiesced (teardown del'd its fd
      // behind the loop barrier before notifying), so its half-read
      // stripe — if any — is abandoned, not in flight.
      const uint32_t bit = 1u << channel;
      if ((it->arrivedMask & bit) != 0) {
        it->landedMask |= bit;
      }
    }
    if (!it->dead) {
      it->dead = true;
      it->error = message;
    }
    if (allQuiesced || it->landedMask == it->arrivedMask) {
      // No channel still writes into this entry: reap now.
      if (it->ubuf != nullptr) {
        victims->push_back(it->ubuf);
      }
      if (!it->direct) {
        releaseStageLocked(rank, it->total);
      }
      it = stripes_.erase(it);
    } else {
      // A sibling channel is mid-payload; the last stripeLanded reaps.
      ++it;
    }
  }
}

Context::Match Context::matchIncoming(int srcRank, uint64_t slot,
                                      size_t nbytes) {
  std::lock_guard<std::mutex> guard(mu_);
  auto it = findPosted(srcRank, slot, nbytes);
  if (it == posted_.end()) {
    return Match{};
  }
  Match m{true, it->ubuf, it->dest, it->combine, it->combineElsize,
          it->combineAccElsize};
  posted_.erase(it);
  return m;
}

void Context::stashArrived(int srcRank, uint64_t slot,
                           std::vector<char> data) {
  UnboundBuffer* rbuf = nullptr;
  int src = srcRank;
  {
    std::lock_guard<std::mutex> guard(mu_);
    // A matching recv may have been posted while the payload was in flight;
    // prefer delivering straight into it.
    auto it = findPosted(srcRank, slot, data.size());
    if (it != posted_.end()) {
      landPayload(*it, data.data(), data.size());
      rbuf = it->ubuf;
      posted_.erase(it);
    } else {
      stashBytes_[srcRank] += data.size();
      // Pause at the high watermark — but never while a posted receive
      // still admits this source: that receive's message is somewhere
      // behind the stashed traffic, and pausing would starve it (one
      // message trickling per unrelated postRecv under concurrent tags).
      bool postedWantsSrc = false;
      for (const auto& pr : posted_) {
        if (pr.allowed[srcRank]) {
          postedWantsSrc = true;
          break;
        }
      }
      if (srcRank != rank_ && !postedWantsSrc &&
          stashBytes_[srcRank] > stashHighWater_ && !rxPaused_[srcRank] &&
          hasAnyPairLocked(srcRank)) {
        rxPaused_[srcRank] = 1;
        // Under mu_: the flag and the pair's epoll state must change
        // atomically with respect to postRecv's resume path (ctx -> pair
        // lock order, same as close()). Covers every data channel.
        pausePeerLocked(srcRank);
        if (metrics_ != nullptr) {
          metrics_->recordStashPause(srcRank);
        }
      }
      stashed_.push_back(Stash{srcRank, slot, std::move(data)});
    }
  }
  if (rbuf != nullptr) {
    rbuf->onRecvComplete(src, slot);
  }
}

void Context::shmStats(uint64_t* txBytes, uint64_t* rxBytes,
                       int* activePairs) {
  uint64_t tx = 0, rx = 0;
  int active = 0;
  std::lock_guard<std::mutex> guard(mu_);
  for (auto& pair : pairs_) {
    if (pair) {
      tx += pair->shmTxBytes();
      rx += pair->shmRxBytes();
      active += pair->shmActive() ? 1 : 0;
    }
  }
  // Lazy mode: payloads from a peer arrive on its dialed (our inbound)
  // connections; count their ring traffic too.
  for (auto& ips : inboundPairs_) {
    for (auto& ip : ips) {
      if (ip) {
        tx += ip->shmTxBytes();
        rx += ip->shmRxBytes();
        active += ip->shmActive() ? 1 : 0;
      }
    }
  }
  *txBytes = tx;
  *rxBytes = rx;
  *activePairs = active;
}

bool Context::peerUsesShm(int rank) {
  if (rank == rank_) {
    return true;  // self-sends combine from the stash / matcher directly
  }
  std::lock_guard<std::mutex> guard(mu_);
  if (rank < 0 || rank >= size_) {
    return false;
  }
  // Lazy mode: "payloads from `rank` arrive through shm" is a property
  // of the peer's dialed connection — our inbound pair.
  if (lazy_) {
    for (auto& ip : inboundPairs_[rank]) {
      if (ip && ip->shmActive()) {
        return true;
      }
    }
  }
  return pairs_[rank] != nullptr && pairs_[rank]->shmActive();
}

void Context::reportStall(UnboundBuffer* buf, bool isSend,
                          int64_t waitedUs) {
  if (metrics_ == nullptr) {
    return;
  }
  Metrics::Stall stall;
  stall.isSend = isSend;
  stall.waitedUs = waitedUs;
  stall.atUs = Tracer::nowUs();
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (isSend) {
      for (auto& pair : pairs_) {
        uint64_t slot = 0;
        if (pair && pair->sendSlotFor(buf, &slot)) {
          stall.peer = pair->peerRank();
          stall.slot = slot;
          break;
        }
      }
      if (stall.peer < 0) {
        for (auto& cps : channelPairs_) {
          uint64_t slot = 0;
          for (auto& cp : cps) {
            if (cp && cp->sendSlotFor(buf, &slot)) {
              stall.peer = cp->peerRank();
              stall.slot = slot;
              break;
            }
          }
          if (stall.peer >= 0) {
            break;
          }
        }
      }
    } else {
      // A receive claimed by stripe reassembly left posted_ at its
      // first stripe; the watchdog's blame must keep naming the
      // peer/slot it is stuck on.
      for (const auto& e : stripes_) {
        if (e.ubuf == buf) {
          stall.peer = e.srcRank;
          stall.slot = e.slot;
          break;
        }
      }
      for (const auto& pr : posted_) {
        if (stall.peer >= 0 || pr.ubuf != buf) {
          continue;
        }
        stall.slot = pr.slot;
        int only = -1;
        int admitted = 0;
        for (int r = 0; r < size_; r++) {
          if (pr.allowed[r]) {
            only = r;
            admitted++;
          }
        }
        // Recv-from-any stays peer=-1: no single culprit to name.
        stall.peer = admitted == 1 ? only : -1;
        break;
      }
    }
  }
  if (stall.peer >= 0) {
    stall.peerLastProgressUs = metrics_->lastProgressUs(stall.peer);
  }
  metrics_->recordStall(stall);
  if (flightrec_ != nullptr) {
    // Post-mortem evidence while the stall is live: what THIS rank has
    // issued so far and which peer it is blocked on. No-op unless
    // TPUCOLL_FLIGHTREC_DIR is set.
    flightrec_->autoDump("stall", stall.peer);
  }
}

void Context::debugDump() {
  std::lock_guard<std::mutex> guard(mu_);
  std::string s = "rank " + std::to_string(rank_) + ": posted=[";
  for (auto& pr : posted_) {
    s += "(slot=" + std::to_string(pr.slot & 0xFFFFFF) + ",allow=";
    for (int r = 0; r < size_; r++) s += pr.allowed[r] ? std::to_string(r) : "";
    s += ") ";
  }
  s += "] stash={";
  for (int r = 0; r < size_; r++) {
    s += std::to_string(r) + ":" + std::to_string(stashBytes_[r] >> 10) +
         "KB" + (rxPaused_[r] ? "*PAUSED" : "") + " ";
  }
  s += "} stashedCount=" + std::to_string(stashed_.size());
  s += " stripes=" + std::to_string(stripes_.size());
  s += " pairs={";
  for (int r = 0; r < size_; r++) {
    if (pairs_[r]) {
      s += std::to_string(r) + ":[" + pairs_[r]->debugState() + "] ";
      for (size_t c = 0; c < channelPairs_[r].size(); c++) {
        if (channelPairs_[r][c]) {
          s += std::to_string(r) + ".ch" + std::to_string(c + 1) + ":[" +
               channelPairs_[r][c]->debugState() + "] ";
        }
      }
    }
  }
  s += "}";
  if (lazy_) {
    size_t in = 0;
    for (const auto& ips : inboundPairs_) {
      for (const auto& ip : ips) {
        in += ip ? 1 : 0;
      }
    }
    s += " lazy{out=" + std::to_string(lazyOutboundCount_) +
         " in=" + std::to_string(in) +
         // relaxed: debug-dump counters, no ordering against pair state
         " dials=" +
         std::to_string(lazyDials_.load(std::memory_order_relaxed)) +
         " evicted=" +
         std::to_string(lazyEvictions_.load(std::memory_order_relaxed)) +
         " graveyard=" + std::to_string(graveyard_.size()) + "}";
  }
  fprintf(stderr, "%s\n", s.c_str());
}

void Context::onPairError(int rank, const std::string& message,
                          bool orderly, int channel) {
  if (lazy_ && orderly) {
    // Lazy plane: an orderly goodbye is the peer evicting one direction
    // (or closing cleanly), not a death. Reap the defunct connections
    // quietly — pairErrors_ stays clear so a future send simply
    // re-dials, and posted receives stay live (the peer can reconnect
    // and deliver; context close still fails them).
    std::vector<UnboundBuffer*> victims;
    {
      std::lock_guard<std::mutex> guard(mu_);
      if (lazyPinned_[rank] == 0 && !dialing_[rank]) {
        quietDropLocked(rank);
      }
      dropStripesLocked(rank, message, channel, /*allQuiesced=*/false,
                        &victims);
    }
    for (auto* b : victims) {
      b->onRecvError(message);
    }
    return;
  }
  if (metrics_ != nullptr && !orderly) {
    // Failure evidence for recovery tooling: even when the watchdog
    // never fired (a SIGKILL'd peer surfaces via EOF in milliseconds),
    // the metrics snapshot names which peer's link died first.
    metrics_->recordPeerFailure(rank, message);
  }
  if (flightrec_ != nullptr && !orderly) {
    flightrec_->autoDump("transport_failure", rank);
  }
  std::vector<UnboundBuffer*> victims;
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (pairErrors_[rank].empty()) {
      pairErrors_[rank] = message;
    }
    // A failed channel strands any reassembly waiting on its stripes;
    // the logical pair is keyed by rank, so one channel's death poisons
    // the peer for sends (pairErrors_) and fails claimed receives here
    // (deferred while a sibling channel is still mid-payload).
    dropStripesLocked(rank, message, channel, /*allQuiesced=*/false,
                      &victims);
    for (auto it = posted_.begin(); it != posted_.end();) {
      bool anyLive = false;
      if (it->allowed[rank]) {
        // A recv-from-any can still be satisfied by another live source
        // (everything a departed peer sent was delivered before its EOF,
        // so its data cannot be pending). Fail only when no admissible
        // source remains.
        for (int r = 0; r < size_; r++) {
          if (it->allowed[r] && pairErrors_[r].empty()) {
            anyLive = true;
            break;
          }
        }
      } else {
        anyLive = true;
      }
      if (!anyLive) {
        victims.push_back(it->ubuf);
        it = posted_.erase(it);
      } else {
        ++it;
      }
    }
  }
  for (auto* b : victims) {
    b->onRecvError(message);
  }
}

// ---- lazy connection plane --------------------------------------------

std::vector<uint8_t> Context::lazyAddressBlob() const {
  auto addrBytes = device_->address().serialize();
  std::vector<uint8_t> blob(12 + addrBytes.size());
  const uint32_t magic = kLazyBlobMagic;
  const uint32_t ch = static_cast<uint32_t>(channels_);
  const uint32_t alen = static_cast<uint32_t>(addrBytes.size());
  std::memcpy(blob.data(), &magic, 4);
  std::memcpy(blob.data() + 4, &ch, 4);
  std::memcpy(blob.data() + 8, &alen, 4);
  std::memcpy(blob.data() + 12, addrBytes.data(), addrBytes.size());
  return blob;
}

void Context::parseLazyAddressBlob(const std::vector<uint8_t>& blob,
                                   int expectChannels, SockAddr* addr) {
  TC_ENFORCE_GE(blob.size(), size_t(12), "lazy address blob too short");
  uint32_t magic, ch, alen;
  std::memcpy(&magic, blob.data(), 4);
  std::memcpy(&ch, blob.data() + 4, 4);
  std::memcpy(&alen, blob.data() + 8, 4);
  TC_ENFORCE_EQ(magic, kLazyBlobMagic, "lazy address blob corrupt");
  TC_ENFORCE_EQ(int(ch), expectChannels,
                "TPUCOLL_CHANNELS mismatch across ranks: peer uses ", ch,
                ", this rank uses ", expectChannels);
  TC_ENFORCE_GE(blob.size(), size_t(12) + alen,
                "lazy address blob truncated");
  *addr = SockAddr::deserialize(blob.data() + 12, alen);
}

void Context::enableLazy(uint64_t meshId, std::vector<SockAddr> peerAddrs,
                         std::vector<char> eager, int maxPairs,
                         std::chrono::milliseconds dialTimeout) {
  TC_ENFORCE_EQ(peerAddrs.size(), static_cast<size_t>(size_),
                "peer address table size mismatch");
  TC_ENFORCE_EQ(eager.size(), static_cast<size_t>(size_),
                "eager mask size mismatch");
  TC_ENFORCE(size_ <= static_cast<int>(boot::kLazyMaxRanks),
             "lazy broker supports up to ", boot::kLazyMaxRanks,
             " ranks, got ", size_);
  {
    std::lock_guard<std::mutex> guard(mu_);
    TC_ENFORCE(!lazy_, "enableLazy called twice");
    for (const auto& p : pairs_) {
      TC_ENFORCE(p == nullptr,
                 "enableLazy must run before the mesh is created");
    }
    lazy_ = true;
    meshId_ = static_cast<uint32_t>(meshId) & boot::kLazyMeshMask;
    maxLazyPairs_ = maxPairs;
    lazyDialTimeout_ = dialTimeout;
    lazyPeerAddrs_ = std::move(peerAddrs);
    lazyEager_ = std::move(eager);
    dialGen_.assign(size_, 0);
    dialing_.assign(size_, 0);
    lazyPinned_.assign(size_, 0);
    lazyLastUse_.assign(size_, 0);
    inboundPairs_.resize(size_);
    for (auto& v : inboundPairs_) {
      v.resize(channels_);
    }
  }
  device_->registerLazyMesh(meshId_, this);
  TC_DEBUG("rank ", rank_, ": lazy broker armed (mesh ", meshId_,
           ", cap ", maxLazyPairs_, ", ", channels_, " channel(s)/pair)");
}

void Context::dialEager(std::chrono::milliseconds timeout) {
  std::unique_lock<std::mutex> lock(mu_);
  TC_ENFORCE(lazy_, "dialEager requires enableLazy");
  lazyDialTimeout_ = timeout;
  for (int r = 0; r < size_; r++) {
    if (r != rank_ && lazyEager_[r] && pairs_[r] == nullptr) {
      ensureOutboundLocked(r, lock);
    }
  }
}

void Context::acceptLazyInbound(uint64_t pairId) {
  const boot::LazyIdParts p = boot::parseLazyPairId(pairId);
  if (p.target != rank_ || p.initiator < 0 || p.initiator >= size_ ||
      p.initiator == rank_ || p.channel < 0 || p.channel >= channels_) {
    TC_WARN("rank ", rank_, ": ignoring lazy connection with bad id "
            "(initiator ", p.initiator, ", target ", p.target, ", channel ",
            p.channel, ")");
    return;
  }
  Pair* fresh = nullptr;
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (closed_ || !lazy_) {
      return;
    }
    auto& slot = inboundPairs_[p.initiator][p.channel];
    if (slot && slot->localPairId() == pairId && !slot->defunct()) {
      return;  // duplicate hook firing for an already-claimed connection
    }
    if (slot) {
      // A stale generation we have not yet seen EOF from: its own
      // teardown reaps it in place; only the table slot moves.
      graveyard_.push_back(std::move(slot));
    }
    const uint64_t key =
        uint64_t(p.initiator) * channels_ + uint64_t(p.channel);
    slot = std::make_unique<Pair>(this, device_->loopFor(key), rank_,
                                  p.initiator, pairId, p.channel,
                                  device_->loopIndexFor(key));
    slot->setLazyInbound();
    fresh = slot.get();
  }
  // Outside mu_: expect() may assume the parked connection inline, which
  // starts rx on this loop thread (matchIncoming re-enters mu_).
  fresh->expectViaListener(device_->listener());
}

void Context::lazyPairStats(uint64_t* connected, uint64_t* evicted,
                            uint64_t* inbound, uint64_t* dials) {
  std::lock_guard<std::mutex> guard(mu_);
  uint64_t out = 0, in = 0;
  for (int r = 0; r < size_; r++) {
    out += pairs_[r] ? 1 : 0;
  }
  for (const auto& ips : inboundPairs_) {
    for (const auto& ip : ips) {
      in += (ip && !ip->defunct()) ? 1 : 0;
    }
  }
  *connected = out;
  *inbound = in;
  *evicted = lazyEvictions_.load(std::memory_order_relaxed);
  *dials = lazyDials_.load(std::memory_order_relaxed);
}

Pair* Context::outboundForLocked(int dstRank,
                                 std::unique_lock<std::mutex>& lock,
                                 bool* pinned) {
  Pair* pair = pairs_[dstRank].get();
  if (!lazy_) {
    return pair;
  }
  if (pair != nullptr && pair->defunct() && lazyPinned_[dstRank] == 0) {
    // The peer's whole context left orderly between ops and the quiet
    // drop was deferred (rank was pinned at the time); reap now and
    // fall through to a fresh dial.
    quietDropLocked(dstRank);
    pair = nullptr;
  }
  if (pair == nullptr) {
    pair = ensureOutboundLocked(dstRank, lock);
  }
  lazyLastUse_[dstRank] = ++lazyUseTick_;
  lazyPinned_[dstRank]++;
  *pinned = true;
  return pair;
}

Pair* Context::ensureOutboundLocked(int dstRank,
                                    std::unique_lock<std::mutex>& lock) {
  for (;;) {
    if (closed_) {
      TC_THROW(IoException, "send on closed context");
    }
    if (!pairErrors_[dstRank].empty()) {
      TC_THROW(IoException, "send to failed rank ", dstRank, ": ",
               pairErrors_[dstRank]);
    }
    if (pairs_[dstRank] != nullptr) {
      return pairs_[dstRank].get();
    }
    if (dialing_[dstRank]) {
      dialCv_.wait(lock);
      continue;
    }
    dialing_[dstRank] = 1;
    // Make room under the cap first, and piggyback the graveyard reap on
    // the loop barrier the eviction close needs anyway. Only entries
    // observed defunct BEFORE the barrier are freed: their teardown
    // provably completed once every loop has ticked.
    std::vector<std::unique_ptr<Pair>> evicted;
    evictForCapLocked(&evicted);
    std::vector<std::unique_ptr<Pair>> reap;
    for (auto& g : graveyard_) {
      if (g->defunct()) {
        reap.push_back(std::move(g));
      }
    }
    graveyard_.erase(
        std::remove(graveyard_.begin(), graveyard_.end(), nullptr),
        graveyard_.end());
    const uint32_t gen = dialGen_[dstRank]++;
    const std::chrono::milliseconds timeout = lazyDialTimeout_;
    lock.unlock();
    for (auto& v : evicted) {
      v->close(kEvictGrace);
    }
    if (!evicted.empty() || !reap.empty()) {
      device_->barrierAllLoops();
      evicted.clear();
      reap.clear();
    }
    std::vector<std::unique_ptr<Pair>> fresh(channels_);
    std::string err;
    try {
      for (int c = 0; c < channels_; c++) {
        const uint64_t key = uint64_t(dstRank) * channels_ + c;
        // The deterministic id doubles as local id and remote routing
        // id: the acceptor derives (mesh, initiator, channel) from it
        // with no per-peer id exchange at rendezvous time.
        const uint64_t id =
            boot::makeLazyPairId(meshId_, gen, rank_, dstRank, c);
        fresh[c] = std::make_unique<Pair>(this, device_->loopFor(key),
                                          rank_, dstRank, id, c,
                                          device_->loopIndexFor(key));
        fresh[c]->connect(lazyPeerAddrs_[dstRank], id, timeout);
      }
      for (auto& f : fresh) {
        f->waitConnected(timeout);
      }
    } catch (const std::exception& e) {
      err = e.what();
    }
    lock.lock();
    if (err.empty() && closed_) {
      err = "context closed during lazy dial";
    }
    if (err.empty() && !pairErrors_[dstRank].empty()) {
      err = pairErrors_[dstRank];
    }
    if (!err.empty()) {
      dialing_[dstRank] = 0;
      dialCv_.notify_all();
      lock.unlock();
      for (auto& f : fresh) {
        if (f) {
          f->close(std::chrono::milliseconds(0));
        }
      }
      device_->barrierAllLoops();
      fresh.clear();
      lock.lock();
      TC_THROW(IoException, "lazy dial to rank ", dstRank, " failed: ",
               err);
    }
    lazyDials_.fetch_add(1, std::memory_order_relaxed);
    // Install; anything stale from a prior generation moves to the
    // graveyard (its own EOF teardown reaps it in place).
    if (pairs_[dstRank]) {
      graveyard_.push_back(std::move(pairs_[dstRank]));
    }
    for (auto& cp : channelPairs_[dstRank]) {
      if (cp) {
        graveyard_.push_back(std::move(cp));
      }
    }
    channelPairs_[dstRank].clear();
    pairs_[dstRank] = std::move(fresh[0]);
    for (int c = 1; c < channels_; c++) {
      channelPairs_[dstRank].push_back(std::move(fresh[c]));
    }
    if (!lazyEager_[dstRank]) {
      lazyOutboundCount_++;
    }
    dialing_[dstRank] = 0;
    dialCv_.notify_all();
    return pairs_[dstRank].get();
  }
}

void Context::evictForCapLocked(std::vector<std::unique_ptr<Pair>>* victims) {
  if (!lazy_ || maxLazyPairs_ <= 0) {
    return;
  }
  while (lazyOutboundCount_ >= maxLazyPairs_) {
    int victim = -1;
    uint64_t oldest = ~uint64_t(0);
    for (int r = 0; r < size_; r++) {
      if (r == rank_ || !pairs_[r] || lazyEager_[r] || dialing_[r] ||
          lazyPinned_[r] != 0) {
        continue;
      }
      if (lazyLastUse_[r] < oldest && logicalPairIdleLocked(r)) {
        oldest = lazyLastUse_[r];
        victim = r;
      }
    }
    if (victim < 0) {
      // Every broker pair is pinned or mid-op: exceed the cap under
      // load rather than deadlock; the next dial trims back down.
      return;
    }
    victims->push_back(std::move(pairs_[victim]));
    for (auto& cp : channelPairs_[victim]) {
      if (cp) {
        victims->push_back(std::move(cp));
      }
    }
    channelPairs_[victim].clear();
    lazyOutboundCount_--;
    lazyEvictions_.fetch_add(1, std::memory_order_relaxed);
  }
}

bool Context::logicalPairIdleLocked(int rank) {
  if (!pairs_[rank]->idleForEvict()) {
    return false;
  }
  for (auto& cp : channelPairs_[rank]) {
    if (cp && !cp->idleForEvict()) {
      return false;
    }
  }
  return true;
}

void Context::unpinLazy(int rank) {
  std::lock_guard<std::mutex> guard(mu_);
  lazyPinned_[rank]--;
}

bool Context::hasAnyPairLocked(int rank) {
  if (pairs_[rank]) {
    return true;
  }
  if (!lazy_) {
    return false;
  }
  for (const auto& ip : inboundPairs_[rank]) {
    if (ip) {
      return true;
    }
  }
  return false;
}

void Context::quietDropLocked(int rank) {
  bool outDead = pairs_[rank] != nullptr && pairs_[rank]->defunct();
  for (const auto& cp : channelPairs_[rank]) {
    outDead = outDead || (cp && cp->defunct());
  }
  if (outDead) {
    // One dead component retires the whole logical outbound pair: the
    // peer only closes this direction when its context goes away, so
    // the siblings are dying too and a redial replaces all channels.
    if (pairs_[rank]) {
      if (!lazyEager_[rank] && lazyOutboundCount_ > 0) {
        lazyOutboundCount_--;
      }
      graveyard_.push_back(std::move(pairs_[rank]));
    }
    for (auto& cp : channelPairs_[rank]) {
      if (cp) {
        graveyard_.push_back(std::move(cp));
      }
    }
    channelPairs_[rank].clear();
  }
  for (auto& ip : inboundPairs_[rank]) {
    if (ip && ip->defunct()) {
      graveyard_.push_back(std::move(ip));
    }
  }
}

}  // namespace transport
}  // namespace tpucoll
