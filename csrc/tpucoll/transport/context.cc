#include "tpucoll/transport/context.h"

#include "tpucoll/transport/wire.h"

#include <algorithm>
#include <cstdlib>
#include <cstring>

#include "tpucoll/transport/device.h"
#include "tpucoll/transport/pair.h"

namespace tpucoll {
namespace transport {

namespace {

std::string rankKey(int rank) { return "tc/rank/" + std::to_string(rank); }

// Rank blob: [u32 numRanks][u32 addrLen][addr][u64 pairId * numRanks].
std::vector<uint8_t> packRankBlob(int numRanks, const SockAddr& addr,
                                  const std::vector<uint64_t>& pairIds) {
  auto addrBytes = addr.serialize();
  std::vector<uint8_t> blob;
  blob.reserve(8 + addrBytes.size() + 8 * pairIds.size());
  uint32_t n = static_cast<uint32_t>(numRanks);
  uint32_t alen = static_cast<uint32_t>(addrBytes.size());
  blob.insert(blob.end(), reinterpret_cast<uint8_t*>(&n),
              reinterpret_cast<uint8_t*>(&n) + 4);
  blob.insert(blob.end(), reinterpret_cast<uint8_t*>(&alen),
              reinterpret_cast<uint8_t*>(&alen) + 4);
  blob.insert(blob.end(), addrBytes.begin(), addrBytes.end());
  blob.insert(blob.end(),
              reinterpret_cast<const uint8_t*>(pairIds.data()),
              reinterpret_cast<const uint8_t*>(pairIds.data()) +
                  8 * pairIds.size());
  return blob;
}

void unpackRankBlob(const std::vector<uint8_t>& blob, int expectRanks,
                    SockAddr* addr, std::vector<uint64_t>* pairIds) {
  TC_ENFORCE_GE(blob.size(), size_t(8), "rank blob too short");
  uint32_t n, alen;
  std::memcpy(&n, blob.data(), 4);
  std::memcpy(&alen, blob.data() + 4, 4);
  TC_ENFORCE_EQ(int(n), expectRanks, "rank blob size mismatch");
  TC_ENFORCE_GE(blob.size(), size_t(8) + alen + size_t(8) * n,
                "rank blob truncated");
  *addr = SockAddr::deserialize(blob.data() + 8, alen);
  pairIds->resize(n);
  std::memcpy(pairIds->data(), blob.data() + 8 + alen, size_t(8) * n);
}

}  // namespace

Context::Context(std::shared_ptr<Device> device, int rank, int size)
    : device_(std::move(device)), rank_(rank), size_(size) {
  TC_ENFORCE(rank >= 0 && rank < size, "bad rank ", rank, " for size ", size);
  pairs_.resize(size);
  pairErrors_.resize(size);
  stashBytes_.resize(size, 0);
  rxPaused_.resize(size, 0);
  stashHighWater_ = 64u << 20;
  if (const char* env = std::getenv("TPUCOLL_MAX_STASH_BYTES")) {
    stashHighWater_ = std::max<size_t>(std::atoll(env), 1u << 20);
  }
}

Context::~Context() {
  close();
  // Loop-thread teardowns may still reference this context (onPairError /
  // matchIncoming); quiesce before members are freed.
  device_->loop()->barrier();
  pairs_.clear();
}

std::vector<uint8_t> Context::prepareFullMesh() {
  std::vector<uint64_t> pairIds(size_, 0);
  for (int j = 0; j < size_; j++) {
    if (j == rank_) {
      continue;
    }
    pairs_[j] = std::make_unique<Pair>(this, device_->loop(), rank_, j,
                                       device_->nextPairId());
    pairIds[j] = pairs_[j]->localPairId();
  }
  // Lower rank listens, higher rank initiates: register expectations first
  // so an early initiator finds a parked or expected pair either way.
  for (int j = rank_ + 1; j < size_; j++) {
    pairs_[j]->expectViaListener(device_->listener());
  }
  return packRankBlob(size_, device_->address(), pairIds);
}

void Context::connectWithBlobs(
    const std::vector<std::vector<uint8_t>>& blobs,
    std::chrono::milliseconds timeout) {
  TC_ENFORCE_EQ(blobs.size(), static_cast<size_t>(size_));
  // Connect only toward lower ranks; higher ranks initiate to us.
  for (int j = 0; j < rank_; j++) {
    SockAddr addr;
    std::vector<uint64_t> peerPairIds;
    unpackRankBlob(blobs[j], size_, &addr, &peerPairIds);
    pairs_[j]->connect(addr, peerPairIds[rank_], timeout);
  }
  for (int j = 0; j < size_; j++) {
    if (j != rank_) {
      pairs_[j]->waitConnected(timeout);
    }
  }
  TC_DEBUG("rank ", rank_, ": full mesh of ", size_, " connected via ",
           device_->str());
}

void Context::connectFullMesh(Store& store,
                              std::chrono::milliseconds timeout) {
  auto myBlob = prepareFullMesh();
  store.set(rankKey(rank_), myBlob);

  std::vector<std::string> keys;
  for (int j = 0; j < size_; j++) {
    if (j != rank_) {
      keys.push_back(rankKey(j));
    }
  }
  auto peerBlobs = store.multiGet(keys, timeout);
  std::vector<std::vector<uint8_t>> blobs(size_);
  size_t idx = 0;
  for (int j = 0; j < size_; j++) {
    blobs[j] = (j == rank_) ? myBlob : std::move(peerBlobs[idx++]);
  }
  connectWithBlobs(blobs, timeout);
}

std::unique_ptr<UnboundBuffer> Context::createUnboundBuffer(void* ptr,
                                                            size_t size) {
  return std::make_unique<UnboundBuffer>(this, ptr, size);
}

uint64_t Context::registerRegion(char* ptr, size_t size,
                                 UnboundBuffer* owner) {
  std::lock_guard<std::mutex> guard(mu_);
  const uint64_t token = nextRegionToken_++;
  regions_[token] = Region{ptr, size, owner};
  return token;
}

void Context::unregisterRegion(uint64_t token) {
  std::lock_guard<std::mutex> guard(mu_);
  regions_.erase(token);
}

bool Context::readRegion(uint64_t token, uint64_t roffset, uint64_t nbytes,
                         std::vector<char>* out) {
  std::lock_guard<std::mutex> guard(mu_);
  auto it = regions_.find(token);
  if (it == regions_.end() || roffset > it->second.size ||
      nbytes > it->second.size - roffset) {
    return false;
  }
  out->assign(it->second.ptr + roffset, it->second.ptr + roffset + nbytes);
  return true;
}

bool Context::writeRegion(uint64_t token, uint64_t roffset,
                          const char* data, size_t nbytes, bool notify,
                          int srcRank) {
  std::lock_guard<std::mutex> guard(mu_);
  auto it = regions_.find(token);
  if (it == regions_.end() || roffset > it->second.size ||
      nbytes > it->second.size - roffset) {
    return false;
  }
  std::memcpy(it->second.ptr + roffset, data, nbytes);
  if (notify && it->second.owner != nullptr) {
    // Under mu_ by design (see header): ~UnboundBuffer unregisters under
    // this same mutex first, so no notification can outlive the owner.
    // onRegionPutArrived skips pending-recv accounting — nothing was
    // posted for a one-sided arrival.
    it->second.owner->onRegionPutArrived(srcRank);
  }
  return true;
}

void Context::postPut(UnboundBuffer* buf, int dstRank, uint64_t token,
                      uint64_t roffset, char* data, size_t nbytes,
                      bool notify) {
  TC_ENFORCE(dstRank >= 0 && dstRank < size_, "bad destination rank ",
             dstRank);
  if (dstRank == rank_) {
    // Local put: straight into the registered region (one memcpy under
    // the region lock, no staging copy).
    buf->addPendingSend();
    if (!writeRegion(token, roffset, data, nbytes, notify, rank_)) {
      buf->cancelPendingSend();
      TC_THROW(EnforceError, "local put outside the registered region");
    }
    buf->onSendComplete();
    return;
  }
  buf->addPendingSend();
  Pair* pair = nullptr;
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (closed_ || !pairErrors_[dstRank].empty()) {
      buf->cancelPendingSend();
      TC_THROW(IoException, "put to rank ", dstRank, ": ",
               closed_ ? "context closed" : pairErrors_[dstRank].c_str());
    }
    pair = pairs_[dstRank].get();
    TC_ENFORCE(pair != nullptr, "no pair for rank ", dstRank);
  }
  try {
    pair->sendPut(buf, token, roffset, data, nbytes, notify);
  } catch (...) {
    buf->cancelPendingSend();
    throw;
  }
}

void Context::postGetRequest(int dstRank, uint64_t respSlot, uint64_t token,
                             uint64_t roffset, size_t nbytes) {
  TC_ENFORCE(dstRank >= 0 && dstRank < size_, "bad source rank ", dstRank);
  if (dstRank == rank_) {
    // Local get: read the region, then deliver through the shared
    // stash/posted matcher like any self-sourced message.
    std::vector<char> data;
    TC_ENFORCE(readRegion(token, roffset, nbytes, &data),
               "local get outside the registered region");
    stashArrived(rank_, respSlot, std::move(data));
    return;
  }
  Pair* pair = nullptr;
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (closed_ || !pairErrors_[dstRank].empty()) {
      TC_THROW(IoException, "get from rank ", dstRank, ": ",
               closed_ ? "context closed" : pairErrors_[dstRank].c_str());
    }
    pair = pairs_[dstRank].get();
    TC_ENFORCE(pair != nullptr, "no pair for rank ", dstRank);
  }
  WireGetReq req{token, roffset, nbytes};
  std::vector<char> payload(sizeof(req));
  std::memcpy(payload.data(), &req, sizeof(req));
  WireHeader header{kMsgMagic, static_cast<uint8_t>(Opcode::kGetReq),
                    0, {0, 0}, respSlot, sizeof(req), 0};
  pair->sendOwned(header, std::move(payload));
}

void Context::close() {
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (closed_) {
      return;
    }
    closed_ = true;
  }
  for (auto& pair : pairs_) {
    if (pair) {
      pair->close();
    }
  }
  // Fail receives that will now never complete.
  std::vector<UnboundBuffer*> victims;
  {
    std::lock_guard<std::mutex> guard(mu_);
    for (auto& pr : posted_) {
      victims.push_back(pr.ubuf);
    }
    posted_.clear();
    stashed_.clear();
    std::fill(stashBytes_.begin(), stashBytes_.end(), 0);
  }
  for (auto* b : victims) {
    b->onRecvError("context closed");
  }
}

std::list<Context::PostedRecv>::iterator Context::findPosted(int srcRank,
                                                             uint64_t slot,
                                                             size_t nbytes) {
  for (auto it = posted_.begin(); it != posted_.end(); ++it) {
    if (it->slot == slot && it->allowed[srcRank]) {
      TC_ENFORCE_EQ(it->nbytes, nbytes,
                    "message size mismatch on slot ", slot, " from rank ",
                    srcRank, ": posted ", it->nbytes, " incoming ", nbytes);
      return it;
    }
  }
  return posted_.end();
}

void Context::landPayload(char* dest, RecvReduceFn combine,
                          size_t combineElsize, const char* data,
                          size_t nbytes) {
  if (combine != nullptr) {
    combine(dest, data, nbytes / combineElsize);
  } else {
    std::memcpy(dest, data, nbytes);
  }
}

void Context::landPayload(const PostedRecv& pr, const char* data,
                          size_t nbytes) {
  landPayload(pr.dest, pr.combine, pr.combineElsize, data, nbytes);
}

void Context::postSend(UnboundBuffer* buf, int dstRank, uint64_t slot,
                       char* data, size_t nbytes) {
  TC_ENFORCE(dstRank >= 0 && dstRank < size_, "bad destination rank ",
             dstRank);
  buf->addPendingSend();
  if (dstRank == rank_) {
    // Self-send: deliver through the matcher immediately. The payload is
    // copied eagerly so the sender may reuse its buffer after waitSend.
    UnboundBuffer* rbuf = nullptr;
    {
      std::lock_guard<std::mutex> guard(mu_);
      auto it = findPosted(rank_, slot, nbytes);
      if (it != posted_.end()) {
        landPayload(*it, data, nbytes);
        rbuf = it->ubuf;
        posted_.erase(it);
      } else {
        stashed_.push_back(
            Stash{rank_, slot, std::vector<char>(data, data + nbytes)});
      }
    }
    if (rbuf != nullptr) {
      rbuf->onRecvComplete(rank_);
    }
    buf->onSendComplete();
    return;
  }
  Pair* pair = nullptr;
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (closed_) {
      buf->cancelPendingSend();
      TC_THROW(IoException, "send on closed context");
    }
    if (!pairErrors_[dstRank].empty()) {
      buf->cancelPendingSend();
      TC_THROW(IoException, "send to failed rank ", dstRank, ": ",
               pairErrors_[dstRank]);
    }
    pair = pairs_[dstRank].get();
    TC_ENFORCE(pair != nullptr, "no pair for rank ", dstRank);
  }
  try {
    pair->send(buf, slot, data, nbytes);
  } catch (...) {
    buf->cancelPendingSend();
    throw;
  }
}

void Context::postRecv(UnboundBuffer* buf, const std::vector<int>& srcRanks,
                       uint64_t slot, char* dest, size_t nbytes,
                       RecvReduceFn combine, size_t combineElsize,
                       size_t combineAccElsize) {
  if (combineAccElsize == 0) {
    combineAccElsize = combineElsize;
  }
  buf->addPendingRecv();
  bool fromStash = false;
  int stashSrc = -1;
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (closed_) {
      buf->cancelPendingRecv();
      TC_THROW(IoException, "recv on closed context");
    }
    std::vector<char> allowed(size_, 0);
    int liveAllowed = 0;
    for (int r : srcRanks) {
      TC_ENFORCE(r >= 0 && r < size_, "bad source rank ", r);
      allowed[r] = 1;
      if (pairErrors_[r].empty()) {
        liveAllowed++;
      }
    }
    // Earliest matching early-arrival wins (FIFO fairness across sources).
    // The stash is consulted before the liveness check: data a peer
    // delivered before departing is still consumable.
    for (auto it = stashed_.begin(); it != stashed_.end(); ++it) {
      if (it->slot == slot && allowed[it->srcRank]) {
        TC_ENFORCE_EQ(it->data.size(), nbytes,
                      "stashed message size mismatch on slot ", slot);
        landPayload(dest, combine, combineElsize, it->data.data(), nbytes);
        stashSrc = it->srcRank;
        if (stashSrc != rank_) {
          stashBytes_[stashSrc] -= it->data.size();
        }
        stashed_.erase(it);
        fromStash = true;
        break;
      }
    }
    // Backpressure release policy: if this recv drained from the stash,
    // resume its source only once the stash falls below the low watermark
    // (an unconditional resume would refill faster than one-per-recv
    // drains, growing the stash without bound). If the recv could NOT be
    // satisfied locally, the wanted message is still on the wire: resume
    // every admissible paused source so it can arrive — it is the oldest
    // in-stream, so it lands in this posted recv before the flood stashes.
    if (fromStash) {
      if (stashSrc != rank_ && rxPaused_[stashSrc] && pairs_[stashSrc] &&
          stashBytes_[stashSrc] < stashHighWater_ / 2) {
        rxPaused_[stashSrc] = 0;
        pairs_[stashSrc]->resumeReading();  // under mu_: see stashArrived
      }
    } else {
      for (int r : srcRanks) {
        if (rxPaused_[r] && pairs_[r]) {
          rxPaused_[r] = 0;
          pairs_[r]->resumeReading();
        }
      }
    }
    if (!fromStash && liveAllowed == 0) {
      buf->cancelPendingRecv();
      TC_THROW(IoException, "recv: all source ranks failed (first error: ",
               pairErrors_[srcRanks[0]], ")");
    }
    if (!fromStash) {
      posted_.push_back(PostedRecv{buf, slot, dest, nbytes,
                                   std::move(allowed), combine,
                                   combineElsize, combineAccElsize});
    }
  }
  if (fromStash) {
    buf->onRecvComplete(stashSrc);
  }
}

void Context::cancelRecvsFor(UnboundBuffer* buf) {
  int cancelled = 0;
  {
    std::lock_guard<std::mutex> guard(mu_);
    for (auto it = posted_.begin(); it != posted_.end();) {
      if (it->ubuf == buf) {
        it = posted_.erase(it);
        cancelled++;
      } else {
        ++it;
      }
    }
  }
  for (int i = 0; i < cancelled; i++) {
    buf->cancelPendingRecv();
  }
}

int Context::cancelSendsFor(UnboundBuffer* buf) {
  int cancelled = 0;
  for (auto& pair : pairs_) {
    if (pair) {
      cancelled += pair->cancelQueuedSends(buf);
    }
  }
  for (int i = 0; i < cancelled; i++) {
    buf->cancelPendingSend();
  }
  return cancelled;
}

void Context::failPairsWithInflightSend(UnboundBuffer* buf) {
  for (auto& pair : pairs_) {
    if (pair && pair->hasInflightSend(buf)) {
      pair->failFromUser(
          "send dropped: buffer destroyed while payload was in flight");
    }
  }
}

Context::Match Context::matchIncoming(int srcRank, uint64_t slot,
                                      size_t nbytes) {
  std::lock_guard<std::mutex> guard(mu_);
  auto it = findPosted(srcRank, slot, nbytes);
  if (it == posted_.end()) {
    return Match{};
  }
  Match m{true, it->ubuf, it->dest, it->combine, it->combineElsize,
          it->combineAccElsize};
  posted_.erase(it);
  return m;
}

void Context::stashArrived(int srcRank, uint64_t slot,
                           std::vector<char> data) {
  UnboundBuffer* rbuf = nullptr;
  int src = srcRank;
  {
    std::lock_guard<std::mutex> guard(mu_);
    // A matching recv may have been posted while the payload was in flight;
    // prefer delivering straight into it.
    auto it = findPosted(srcRank, slot, data.size());
    if (it != posted_.end()) {
      landPayload(*it, data.data(), data.size());
      rbuf = it->ubuf;
      posted_.erase(it);
    } else {
      stashBytes_[srcRank] += data.size();
      // Pause at the high watermark — but never while a posted receive
      // still admits this source: that receive's message is somewhere
      // behind the stashed traffic, and pausing would starve it (one
      // message trickling per unrelated postRecv under concurrent tags).
      bool postedWantsSrc = false;
      for (const auto& pr : posted_) {
        if (pr.allowed[srcRank]) {
          postedWantsSrc = true;
          break;
        }
      }
      if (srcRank != rank_ && !postedWantsSrc &&
          stashBytes_[srcRank] > stashHighWater_ && !rxPaused_[srcRank] &&
          pairs_[srcRank]) {
        rxPaused_[srcRank] = 1;
        // Under mu_: the flag and the pair's epoll state must change
        // atomically with respect to postRecv's resume path (ctx -> pair
        // lock order, same as close()).
        pairs_[srcRank]->pauseReading();
        if (metrics_ != nullptr) {
          metrics_->recordStashPause(srcRank);
        }
      }
      stashed_.push_back(Stash{srcRank, slot, std::move(data)});
    }
  }
  if (rbuf != nullptr) {
    rbuf->onRecvComplete(src);
  }
}

void Context::shmStats(uint64_t* txBytes, uint64_t* rxBytes,
                       int* activePairs) {
  uint64_t tx = 0, rx = 0;
  int active = 0;
  std::lock_guard<std::mutex> guard(mu_);
  for (auto& pair : pairs_) {
    if (pair) {
      tx += pair->shmTxBytes();
      rx += pair->shmRxBytes();
      active += pair->shmActive() ? 1 : 0;
    }
  }
  *txBytes = tx;
  *rxBytes = rx;
  *activePairs = active;
}

bool Context::peerUsesShm(int rank) {
  if (rank == rank_) {
    return true;  // self-sends combine from the stash / matcher directly
  }
  std::lock_guard<std::mutex> guard(mu_);
  if (rank < 0 || rank >= size_ || !pairs_[rank]) {
    return false;
  }
  return pairs_[rank]->shmActive();
}

void Context::reportStall(UnboundBuffer* buf, bool isSend,
                          int64_t waitedUs) {
  if (metrics_ == nullptr) {
    return;
  }
  Metrics::Stall stall;
  stall.isSend = isSend;
  stall.waitedUs = waitedUs;
  stall.atUs = Tracer::nowUs();
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (isSend) {
      for (auto& pair : pairs_) {
        uint64_t slot = 0;
        if (pair && pair->sendSlotFor(buf, &slot)) {
          stall.peer = pair->peerRank();
          stall.slot = slot;
          break;
        }
      }
    } else {
      for (const auto& pr : posted_) {
        if (pr.ubuf != buf) {
          continue;
        }
        stall.slot = pr.slot;
        int only = -1;
        int admitted = 0;
        for (int r = 0; r < size_; r++) {
          if (pr.allowed[r]) {
            only = r;
            admitted++;
          }
        }
        // Recv-from-any stays peer=-1: no single culprit to name.
        stall.peer = admitted == 1 ? only : -1;
        break;
      }
    }
  }
  if (stall.peer >= 0) {
    stall.peerLastProgressUs = metrics_->lastProgressUs(stall.peer);
  }
  metrics_->recordStall(stall);
  if (flightrec_ != nullptr) {
    // Post-mortem evidence while the stall is live: what THIS rank has
    // issued so far and which peer it is blocked on. No-op unless
    // TPUCOLL_FLIGHTREC_DIR is set.
    flightrec_->autoDump("stall", stall.peer);
  }
}

void Context::debugDump() {
  std::lock_guard<std::mutex> guard(mu_);
  std::string s = "rank " + std::to_string(rank_) + ": posted=[";
  for (auto& pr : posted_) {
    s += "(slot=" + std::to_string(pr.slot & 0xFFFFFF) + ",allow=";
    for (int r = 0; r < size_; r++) s += pr.allowed[r] ? std::to_string(r) : "";
    s += ") ";
  }
  s += "] stash={";
  for (int r = 0; r < size_; r++) {
    s += std::to_string(r) + ":" + std::to_string(stashBytes_[r] >> 10) +
         "KB" + (rxPaused_[r] ? "*PAUSED" : "") + " ";
  }
  s += "} stashedCount=" + std::to_string(stashed_.size());
  s += " pairs={";
  for (int r = 0; r < size_; r++) {
    if (pairs_[r]) {
      s += std::to_string(r) + ":[" + pairs_[r]->debugState() + "] ";
    }
  }
  s += "}";
  fprintf(stderr, "%s\n", s.c_str());
}

void Context::onPairError(int rank, const std::string& message,
                          bool orderly) {
  if (metrics_ != nullptr && !orderly) {
    // Failure evidence for recovery tooling: even when the watchdog
    // never fired (a SIGKILL'd peer surfaces via EOF in milliseconds),
    // the metrics snapshot names which peer's link died first.
    metrics_->recordPeerFailure(rank, message);
  }
  if (flightrec_ != nullptr && !orderly) {
    flightrec_->autoDump("transport_failure", rank);
  }
  std::vector<UnboundBuffer*> victims;
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (pairErrors_[rank].empty()) {
      pairErrors_[rank] = message;
    }
    for (auto it = posted_.begin(); it != posted_.end();) {
      bool anyLive = false;
      if (it->allowed[rank]) {
        // A recv-from-any can still be satisfied by another live source
        // (everything a departed peer sent was delivered before its EOF,
        // so its data cannot be pending). Fail only when no admissible
        // source remains.
        for (int r = 0; r < size_; r++) {
          if (it->allowed[r] && pairErrors_[r].empty()) {
            anyLive = true;
            break;
          }
        }
      } else {
        anyLive = true;
      }
      if (!anyLive) {
        victims.push_back(it->ubuf);
        it = posted_.erase(it);
      } else {
        ++it;
      }
    }
  }
  for (auto* b : victims) {
    b->onRecvError(message);
  }
}

}  // namespace transport
}  // namespace tpucoll
