#include "tpucoll/transport/listener.h"

#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>

#include <poll.h>

#include <array>

#include "tpucoll/common/hmac.h"
#include "tpucoll/common/logging.h"
#include "tpucoll/transport/pair.h"
#include "tpucoll/transport/socket.h"
#include "tpucoll/transport/wire.h"

namespace tpucoll {
namespace transport {

// Reads the hello preamble off a fresh inbound connection — and, when the
// device requires authentication, runs the listener side of the PSK (or
// per-rank keyring) challenge/response (see wire.h) — then hands the fd
// back to the listener for routing.
class PendingConn : public Handler {
 public:
  PendingConn(Listener* listener, int fd, const std::string& authKey,
              const Keyring& keyring, bool encrypt)
      : listener_(listener), fd_(fd), authKey_(authKey), keyring_(keyring),
        encrypt_(encrypt) {}

  int fd() const { return fd_; }

  void handleEvents(uint32_t /*events*/) override {
    while (true) {
      const size_t want = phase_ == Phase::kHello      ? sizeof(WireHello)
                          : phase_ == Phase::kRankIntro ? sizeof(uint32_t)
                          : phase_ == Phase::kNonce    ? kAuthNonceBytes
                          : phase_ == Phase::kShmOffer ? sizeof(WireShmOffer)
                          : phase_ == Phase::kShmName  ? size_t(offer_.nameLen)
                                                       : kAuthMacBytes;
      ssize_t n = read(fd_, buf_ + got_, want - got_);
      if (n == 0) {
        listener_->finishPending(this, false, 0, fd_, ConnKeys{});
        return;
      }
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          return;
        }
        if (errno == EINTR) {
          continue;
        }
        listener_->finishPending(this, false, 0, fd_, ConnKeys{});
        return;
      }
      got_ += static_cast<size_t>(n);
      if (got_ < want) {
        continue;
      }
      got_ = 0;
      switch (phase_) {
        case Phase::kHello: {
          WireHello hello;
          std::memcpy(&hello, buf_, sizeof(hello));
          pairId_ = hello.pairId;
          shmOffered_ = (hello.reserved & kHelloFlagShmOffer) != 0;
          const bool wantAuth = !authKey_.empty();
          const bool wantRing = keyring_.valid();
          if (hello.magic == kHelloMagic && !wantAuth && !wantRing) {
            if (shmOffered_) {
              phase_ = Phase::kShmOffer;
              break;
            }
            listener_->finishPending(this, true, pairId_, fd_, ConnKeys{});
            return;
          }
          // The hello must match this device's (auth tier, encrypt) pair
          // exactly: plain vs PSK vs keyring vs encrypted mismatches (in
          // either direction) and garbage are all rejected.
          const uint32_t want =
              wantRing ? (encrypt_ ? kHelloRingEncMagic : kHelloRingMagic)
                       : (encrypt_ ? kHelloAuthEncMagic : kHelloAuthMagic);
          if (hello.magic != want || !(wantAuth || wantRing)) {
            listener_->finishPending(this, false, 0, fd_, ConnKeys{});
            return;
          }
          phase_ = wantRing ? Phase::kRankIntro : Phase::kNonce;
          break;
        }
        case Phase::kRankIntro: {
          uint32_t claimed;
          std::memcpy(&claimed, buf_, sizeof(claimed));
          if (claimed >= static_cast<uint32_t>(keyring_.size()) ||
              static_cast<int32_t>(claimed) == keyring_.rank()) {
            TC_WARN("rejecting inbound connection: bad claimed rank ",
                    claimed);
            listener_->finishPending(this, false, 0, fd_, ConnKeys{});
            return;
          }
          claimedRank_ = static_cast<int32_t>(claimed);
          key_ = keyring_.keyFor(claimedRank_);
          phase_ = Phase::kNonce;
          break;
        }
        case Phase::kNonce: {
          std::memcpy(nonceI_, buf_, kAuthNonceBytes);
          randomBytes(nonceL_, kAuthNonceBytes);
          // Challenge response: nonceL || HMAC(key, "srv"||id||nI||nL).
          auto mac = transcriptMac("srv");
          uint8_t out[kAuthNonceBytes + kAuthMacBytes];
          std::memcpy(out, nonceL_, kAuthNonceBytes);
          std::memcpy(out + kAuthNonceBytes, mac.data(), kAuthMacBytes);
          if (!writeFullNoSig(fd_, out, sizeof(out))) {
            listener_->finishPending(this, false, 0, fd_, ConnKeys{});
            return;
          }
          phase_ = Phase::kClientMac;
          break;
        }
        case Phase::kClientMac: {
          auto expect = transcriptMac("cli");
          const bool ok = macEqual(reinterpret_cast<uint8_t*>(buf_),
                                   expect.data(), kAuthMacBytes);
          if (!ok) {
            TC_WARN("rejecting inbound connection: bad auth tag");
            listener_->finishPending(this, false, 0, fd_, ConnKeys{});
            return;
          }
          if (encrypt_) {
            keys_ = deriveConnKeys(connKey(), pairId_, nonceI_, nonceL_,
                                   /*initiator=*/false);
          }
          if (shmOffered_) {
            phase_ = Phase::kShmOffer;
            break;
          }
          listener_->finishPending(this, true, pairId_, fd_, keys_,
                                   claimedRank_);
          return;
        }
        case Phase::kShmOffer: {
          std::memcpy(&offer_, buf_, sizeof(offer_));
          if (offer_.magic != kShmOfferMagic ||
              offer_.nameLen > sizeof(buf_)) {
            listener_->finishPending(this, false, 0, fd_, ConnKeys{});
            return;
          }
          if (offer_.nameLen == 0) {
            // The initiator failed to create a segment; acknowledge the
            // fallback so both sides use TCP payloads.
            uint8_t verdict = kShmReject;
            if (!writeFullNoSig(fd_, &verdict, 1)) {
              listener_->finishPending(this, false, 0, fd_, ConnKeys{});
              return;
            }
            listener_->finishPending(this, true, pairId_, fd_, keys_,
                                     claimedRank_);
            return;
          }
          phase_ = Phase::kShmName;
          break;
        }
        case Phase::kShmName: {
          // Accept iff the segment opens and validates (magic, pairId,
          // size) — which can only happen on the initiator's host, in the
          // same IPC namespace, under the same user. Everything else
          // degrades to TCP payloads, never to an error.
          std::unique_ptr<ShmSegment> seg;
          const bool sane = shmEnabled() &&
                            offer_.ringBytes >= (64 << 10) &&
                            offer_.ringBytes <= (uint64_t(1) << 30);
          if (sane) {
            seg = ShmSegment::open(std::string(buf_, offer_.nameLen),
                                   pairId_, offer_.ringBytes);
          }
          uint8_t verdict = seg ? kShmAccept : kShmReject;
          if (!writeFullNoSig(fd_, &verdict, 1)) {
            listener_->finishPending(this, false, 0, fd_, ConnKeys{});
            return;
          }
          listener_->finishPending(this, true, pairId_, fd_, keys_,
                                   claimedRank_, std::move(seg));
          return;
        }
      }
    }
  }

 private:
  enum class Phase {
    kHello, kRankIntro, kNonce, kClientMac, kShmOffer, kShmName
  };

  // The HMAC/HKDF key for this connection: the pairwise K[self, claimed]
  // on the keyring tier, the mesh PSK otherwise.
  const std::string& connKey() const {
    return claimedRank_ >= 0 ? key_ : authKey_;
  }

  std::array<uint8_t, 32> transcriptMac(const char* role) const {
    std::string msg(role);
    msg.append(reinterpret_cast<const char*>(&pairId_), sizeof(pairId_));
    if (claimedRank_ >= 0) {
      // Keyring tier: both identities enter the transcript, so the MAC
      // binds WHO is talking to WHOM, not just possession of a key.
      const int32_t self = keyring_.rank();
      msg.append(reinterpret_cast<const char*>(&claimedRank_),
                 sizeof(claimedRank_));
      msg.append(reinterpret_cast<const char*>(&self), sizeof(self));
    }
    msg.append(reinterpret_cast<const char*>(nonceI_), kAuthNonceBytes);
    msg.append(reinterpret_cast<const char*>(nonceL_), kAuthNonceBytes);
    const std::string& key = connKey();
    return hmacSha256(key.data(), key.size(), msg.data(), msg.size());
  }

  static bool writeFullNoSig(int fd, const void* buf, size_t n) {
    const char* p = static_cast<const char*>(buf);
    size_t sent = 0;
    while (sent < n) {
      ssize_t rv = ::send(fd, p + sent, n - sent, MSG_NOSIGNAL);
      if (rv < 0) {
        if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK) {
          // Handshake frames are tiny; a fresh socket accepts them. EAGAIN
          // here is pathological — retry briefly via blocking poll.
          pollfd pfd{fd, POLLOUT, 0};
          poll(&pfd, 1, 1000);
          continue;
        }
        return false;
      }
      sent += static_cast<size_t>(rv);
    }
    return true;
  }

  Listener* const listener_;
  const int fd_;
  const std::string& authKey_;
  const Keyring& keyring_;
  const bool encrypt_;
  Phase phase_{Phase::kHello};
  uint64_t pairId_{0};
  int32_t claimedRank_{-1};  // keyring tier: the authenticated peer rank
  std::string key_;          // keyring tier: K[self, claimedRank_]
  uint8_t nonceI_[kAuthNonceBytes];
  uint8_t nonceL_[kAuthNonceBytes];
  bool shmOffered_{false};
  WireShmOffer offer_{};
  ConnKeys keys_;
  char buf_[256];  // fits the largest phase read (shm segment name)
  size_t got_{0};
};

Listener::Listener(Loop* loop, const SockAddr& bindAddr,
                   const std::string& authKey, const Keyring& keyring,
                   bool encrypt)
    : loop_(loop), authKey_(authKey), keyring_(keyring),
      encrypt_(encrypt) {
  fd_ = socket(bindAddr.sa()->sa_family, SOCK_STREAM | SOCK_CLOEXEC, 0);
  TC_ENFORCE_GE(fd_, 0, errnoString("socket"));
  setReuseAddr(fd_);
  TC_ENFORCE_EQ(bind(fd_, bindAddr.sa(), bindAddr.len), 0,
                errnoString("bind"), " at ", bindAddr.str());
  TC_ENFORCE_EQ(listen(fd_, 4096), 0, errnoString("listen"));
  addr_.len = sizeof(addr_.ss);
  TC_ENFORCE_EQ(getsockname(fd_, addr_.sa(), &addr_.len), 0,
                errnoString("getsockname"));
  setNonBlocking(fd_);
  loop_->add(fd_, EPOLLIN, this);
}

Listener::~Listener() {
  loop_->del(fd_);
  ::close(fd_);
  // Stop concurrent finishPending from routing/erasing while we tear down,
  // then quiesce each half-open connection before closing it.
  std::list<std::unique_ptr<PendingConn>> leftovers;
  {
    std::lock_guard<std::mutex> guard(mu_);
    shuttingDown_ = true;
    leftovers.swap(pending_);
  }
  for (auto& conn : leftovers) {
    loop_->del(conn->fd());  // barriers: no in-flight dispatch afterwards
    ::close(conn->fd());
  }
  for (auto& kv : parked_) {
    ::close(kv.second.fd);
  }
}

void Listener::handleEvents(uint32_t /*events*/) {
  while (true) {
    int fd = accept4(fd_, nullptr, nullptr, SOCK_NONBLOCK | SOCK_CLOEXEC);
    if (fd < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        return;
      }
      if (errno == EINTR) {
        continue;
      }
      TC_WARN("accept failed: ", strerror(errno));
      return;
    }
    setNoDelay(fd);
    auto conn = std::make_unique<PendingConn>(this, fd, authKey_, keyring_,
                                              encrypt_);
    PendingConn* raw = conn.get();
    {
      std::lock_guard<std::mutex> guard(mu_);
      pending_.push_back(std::move(conn));
    }
    loop_->add(fd, EPOLLIN, raw);
  }
}

void Listener::finishPending(PendingConn* conn, bool ok, uint64_t pairId,
                             int fd, ConnKeys keys, int32_t authedRank,
                             std::unique_ptr<ShmSegment> shm) {
  Pair* target = nullptr;
  std::function<void(uint64_t)> unclaimedHook;
  {
    std::lock_guard<std::mutex> guard(mu_);
    if (shuttingDown_) {
      return;  // the destructor owns this connection now
    }
    loop_->del(fd);  // loop thread: immediate
    for (auto it = pending_.begin(); it != pending_.end(); ++it) {
      if (it->get() == conn) {
        pending_.erase(it);
        break;
      }
    }
    if (ok) {
      auto it = expected_.find(pairId);
      if (it != expected_.end()) {
        // Keyring tier: the connection proved possession of
        // K[self, authedRank]; it may only land on the pair built for
        // exactly that peer. A legitimate rank a replaying its own key
        // against a slot expecting rank b dies here.
        if (authedRank >= 0 && it->second->peerRank() != authedRank) {
          TC_WARN("rejecting inbound connection: authenticated as rank ",
                  authedRank, " but pair ", pairId, " expects rank ",
                  it->second->peerRank());
          ok = false;
        } else {
          target = it->second;
          expected_.erase(it);
        }
      } else {
        auto old = parked_.find(pairId);
        if (old != parked_.end()) {
          // An earlier fully-handshaked connection for the same pairId
          // (initiator retry, or a credential holder reconnecting) is
          // superseded; close it rather than leak the fd.
          ::close(old->second.fd);
          parked_.erase(old);
        }
        parked_[pairId] = Parked{fd, keys, authedRank, std::move(shm)};
        if ((pairId & (uint64_t(1) << 63)) != 0) {
          unclaimedHook = unclaimedHook_;  // copy under mu_ (replay races)
        }
      }
    }
  }
  if (!ok) {
    ::close(fd);
    return;
  }
  if (target != nullptr) {
    target->assumeConnected(fd, keys, std::move(shm),
                            /*shmInitiator=*/false);
  } else if (unclaimedHook != nullptr) {
    // Broker-dialed connection with no pair yet: ask the lazy-mesh
    // registry to materialize the accepting side. The hook re-enters
    // expect(), which claims the parked fd above.
    unclaimedHook(pairId);
  }
}

void Listener::replayUnclaimed() {
  std::function<void(uint64_t)> hook;
  std::vector<uint64_t> ids;
  {
    std::lock_guard<std::mutex> guard(mu_);
    hook = unclaimedHook_;
    for (const auto& kv : parked_) {
      if ((kv.first & (uint64_t(1) << 63)) != 0) {
        ids.push_back(kv.first);
      }
    }
  }
  if (hook == nullptr) {
    return;
  }
  // Outside mu_: the hook re-enters expect(). A connection claimed
  // between the snapshot and the call is fine — the accepting context
  // treats an already-materialized pair id as a duplicate and returns.
  for (uint64_t id : ids) {
    hook(id);
  }
}

void Listener::expect(uint64_t pairId, Pair* pair) {
  int fd = -1;
  ConnKeys keys;
  std::unique_ptr<ShmSegment> shm;
  {
    std::lock_guard<std::mutex> guard(mu_);
    auto it = parked_.find(pairId);
    if (it != parked_.end()) {
      const int32_t authedRank = it->second.authedRank;
      if (authedRank >= 0 && pair->peerRank() != authedRank) {
        // Same identity-vs-slot check as finishPending, for connections
        // that arrived before the pair registered. Drop the parked fd;
        // the pair keeps waiting (and times out) rather than accepting
        // a mismatched identity.
        TC_WARN("dropping parked connection: authenticated as rank ",
                authedRank, " but pair ", pairId, " expects rank ",
                pair->peerRank());
        ::close(it->second.fd);
        parked_.erase(it);
        expected_[pairId] = pair;
      } else {
        fd = it->second.fd;
        keys = it->second.keys;
        shm = std::move(it->second.shm);
        parked_.erase(it);
      }
    } else {
      expected_[pairId] = pair;
    }
  }
  if (fd >= 0) {
    pair->assumeConnected(fd, keys, std::move(shm), /*shmInitiator=*/false);
  }
}

void Listener::unexpect(uint64_t pairId) {
  std::lock_guard<std::mutex> guard(mu_);
  expected_.erase(pairId);
}

}  // namespace transport
}  // namespace tpucoll
