// Device: the per-process transport endpoint. Owns the epoll loop thread and
// the shared listener; hands out process-unique pair routing ids (reference
// analog: gloo/transport/tcp/device.cc plus its Loop/Listener ownership).
// Multiple contexts can share one device; their pairs never cross-match
// because pair ids are globally unique within the device.
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "tpucoll/common/keyring.h"
#include "tpucoll/transport/address.h"
#include "tpucoll/transport/listener.h"
#include "tpucoll/transport/loop.h"

namespace tpucoll {
namespace transport {

class Context;

struct DeviceAttr {
  // Hostname or IP to bind and advertise. Loopback default suits
  // single-host tests; multi-host deployments pass the DCN hostname.
  std::string hostname{"127.0.0.1"};
  // Bind by interface NAME instead (reference: gloo tcp/attr.h iface):
  // when non-empty, the interface's first address overrides hostname.
  std::string iface;
  uint16_t port{0};  // 0 = ephemeral
  // Non-empty: require the PSK handshake on every inbound and outbound
  // connection (mutual HMAC-SHA256 authentication; see wire.h).
  std::string authKey;
  // Per-rank identity tier (common/keyring.h): a serialized keyring
  // ("tcring1:...") of pairwise keys. Mutually exclusive with authKey;
  // connections then authenticate with K[selfRank, peerRank], and a
  // leaked keyring impersonates one rank, not the whole mesh.
  std::string keyring;
  // Encrypt the data plane: per-connection ChaCha20-Poly1305 keys derived
  // from the handshake (requires authKey or keyring). Both sides of
  // every connection must agree — a plaintext peer is rejected at hello.
  bool encrypt{false};
  // Sync/busy-poll latency mode (reference: tcp setSync + MSG_DONTWAIT
  // busy-poll, gloo tcp/pair.cc:505): the loop thread spins on
  // epoll_wait(0) and blocking waits spin instead of sleeping on their
  // condition variables. Burns a core for the sub-10us regime.
  bool busyPoll{false};
  // Event engine: "epoll" | "uring" | "auto" | "" ("" = TPUCOLL_ENGINE env
  // if set, else auto). See loop.h / loop_uring.h.
  std::string engine;
  // Event-loop thread pool size. 0 = TPUCOLL_LOOP_THREADS env (strict
  // parse) if set, else 1 — the seed's single-thread behavior. The
  // listener always lives on loop 0; pairs (and their extra data
  // channels) are sharded round-robin across the pool so TCP stack
  // work, stash memcpys, and per-connection encryption spread over
  // cores instead of single-streaming on one.
  int numLoops{0};
};

class Device {
 public:
  explicit Device(const DeviceAttr& attr);

  // Loop 0: the listener's loop and the legacy single-loop accessor.
  Loop* loop() { return loops_[0].get(); }
  // Round-robin shard for pair/channel `key` (stable for a given key).
  Loop* loopFor(uint64_t key) { return loops_[key % loops_.size()].get(); }
  int loopIndexFor(uint64_t key) const {
    return static_cast<int>(key % loops_.size());
  }
  int numLoops() const { return static_cast<int>(loops_.size()); }
  // Quiesce every loop in the pool (teardown barriers must cover all
  // loops once pairs shard across them).
  void barrierAllLoops() {
    for (auto& l : loops_) {
      l->barrier();
    }
  }
  Listener* listener() { return listener_.get(); }
  const SockAddr& address() const { return listener_->address(); }
  uint64_t nextPairId() {
    // Relaxed: uniqueness is all that is needed from an id
    // allocator; nothing is published through it.
    return pairId_.fetch_add(1, std::memory_order_relaxed);
  }
  const std::string& authKey() const { return authKey_; }
  const Keyring& keyring() const { return keyring_; }
  bool encrypt() const { return encrypt_; }
  bool busyPoll() const { return loops_[0]->busyPoll(); }
  std::string str() const;

  // ---- lazy-mesh registry (boot plane) ----
  // A context in lazy-connect mode registers under its rendezvous mesh
  // id; the listener's unclaimed hook then routes broker-dialed inbound
  // connections (lazy-namespace pair ids, boot/lazy_id.h) to that
  // context's acceptLazyInbound. Register before any lazy peer can
  // dial, unregister in Context::close() — the context stays alive
  // through its destructor's barrierAllLoops(), which drains any hook
  // still running on loop 0.
  void registerLazyMesh(uint32_t meshId, Context* ctx);
  void unregisterLazyMesh(uint32_t meshId);

 private:
  void onUnclaimedLazy(uint64_t pairId);
  // Declared first: destroyed last. loops_[0] hosts the listener; the
  // rest are the data-channel shards.
  std::vector<std::unique_ptr<Loop>> loops_;
  // Declared before listener_: the listener holds references to the
  // key material, so it must be destroyed first (reverse declaration
  // order) and constructed after.
  std::string authKey_;
  Keyring keyring_;
  bool encrypt_{false};
  std::unique_ptr<Listener> listener_;
  std::atomic<uint64_t> pairId_{1};
  std::mutex lazyMu_;
  std::unordered_map<uint32_t, Context*> lazyMeshes_;
};

}  // namespace transport
}  // namespace tpucoll
