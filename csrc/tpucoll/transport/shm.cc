#include "tpucoll/transport/shm.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdlib>
#include <cstring>

#include "tpucoll/common/env.h"
#include "tpucoll/common/hmac.h"
#include "tpucoll/common/logging.h"

namespace tpucoll {
namespace transport {

bool shmEnabled() {
  // Strict flag (common/env.h): historically any non-"0" value meant
  // enabled, so TPUCOLL_SHM=false silently kept shm ON; now only 0/1
  // parse and anything else throws at the first same-host config read.
  static const bool v = envFlag("TPUCOLL_SHM", true);
  return v;
}

uint64_t shmRingBytesConfig() {
  // Strict parse (common/env.h): "8MB" or "-1" throws instead of silently
  // running with a default-sized ring.
  static const uint64_t v = [] {
    const uint64_t b = envBytes("TPUCOLL_SHM_RING", 0);
    if (b == 0) {
      return uint64_t(8) << 20;
    }
    // Clamp into the window listeners accept (listener.cc sanity check);
    // an out-of-window value would otherwise create-and-offer a segment
    // every connect only to be rejected into TCP fallback each time.
    const uint64_t lo = 64 << 10, hi = uint64_t(1) << 30;
    return b < lo ? lo : b > hi ? hi : b;
  }();
  return v;
}

uint64_t shmThresholdBytes() {
  static const uint64_t v = [] {
    const uint64_t b = envBytes("TPUCOLL_SHM_THRESHOLD", 0);
    return b >= 1 ? b : uint64_t(32) << 10;
  }();
  return v;
}

namespace {

constexpr uint32_t kShmSegMagic = 0x7C011006;
constexpr uint32_t kShmSegVersion = 1;

// Header page layout. Counters live on their own cache lines so the
// producer's head stores never false-share with the consumer's tail stores
// (each wrapped in an alignas struct — aligning the bare array would only
// align its start, leaving head and tail 8 bytes apart on one line).
struct PaddedCounter {
  alignas(64) std::atomic<uint64_t> v;
};
struct SegHdr {
  uint32_t magic;
  uint32_t version;
  uint64_t pairId;
  uint64_t ringBytes;
  PaddedCounter counters[4];  // head0, tail0, head1, tail1
};
constexpr size_t kHdrBytes = 4096;
static_assert(sizeof(SegHdr) <= kHdrBytes, "segment header fits one page");

size_t mapSize(uint64_t ringBytes) { return kHdrBytes + 2 * ringBytes; }

}  // namespace

uint64_t ShmRing::write(const char* src, uint64_t n) {
  const uint64_t h = head->load(std::memory_order_relaxed);
  const uint64_t free = cap - (h - tail->load(std::memory_order_acquire));
  if (n > free) {
    n = free;
  }
  if (n == 0) {
    return 0;
  }
  const uint64_t off = h % cap;
  const uint64_t first = n < cap - off ? n : cap - off;
  std::memcpy(data + off, src, first);
  if (n > first) {
    std::memcpy(data, src + first, n - first);
  }
  head->store(h + n, std::memory_order_release);
  return n;
}

std::unique_ptr<ShmSegment> ShmSegment::create(uint64_t pairId,
                                               uint64_t ringBytes) {
  uint8_t rnd[16];
  randomBytes(rnd, sizeof(rnd));
  char name[64];
  // 128 random bits: collision with a concurrently chosen name is
  // impossible in practice, and a stale segment can never be confused for
  // ours (O_EXCL below).
  snprintf(name, sizeof(name),
           "/tpucoll-%02x%02x%02x%02x%02x%02x%02x%02x"
           "%02x%02x%02x%02x%02x%02x%02x%02x",
           rnd[0], rnd[1], rnd[2], rnd[3], rnd[4], rnd[5], rnd[6], rnd[7],
           rnd[8], rnd[9], rnd[10], rnd[11], rnd[12], rnd[13], rnd[14],
           rnd[15]);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) {
    TC_THROW(IoException, "shm_open(", name, "): ", strerror(errno));
  }
  const size_t bytes = mapSize(ringBytes);
  if (ftruncate(fd, static_cast<off_t>(bytes)) != 0) {
    int savedErrno = errno;
    ::close(fd);
    shm_unlink(name);
    TC_THROW(IoException, "ftruncate(", name, ", ", bytes,
             "): ", strerror(savedErrno));
  }
  void* base = mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd,
                    0);
  ::close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    TC_THROW(IoException, "mmap(", name, "): ", strerror(errno));
  }
  auto* hdr = new (base) SegHdr();
  hdr->pairId = pairId;
  hdr->ringBytes = ringBytes;
  for (auto& c : hdr->counters) {
    c.v.store(0, std::memory_order_relaxed);
  }
  hdr->version = kShmSegVersion;
  // Magic last: an opener that wins a (theoretical) race sees either no
  // magic or a fully initialized header.
  reinterpret_cast<std::atomic<uint32_t>*>(&hdr->magic)
      ->store(kShmSegMagic, std::memory_order_release);

  auto seg = std::unique_ptr<ShmSegment>(new ShmSegment());
  seg->name_ = name;
  seg->linked_ = true;
  seg->base_ = base;
  seg->mapBytes_ = bytes;
  seg->ringBytes_ = ringBytes;
  return seg;
}

std::unique_ptr<ShmSegment> ShmSegment::open(const std::string& name,
                                             uint64_t pairId,
                                             uint64_t ringBytes) {
  if (name.empty() || name[0] != '/' || name.size() > 255) {
    return nullptr;
  }
  int fd = shm_open(name.c_str(), O_RDWR, 0);
  if (fd < 0) {
    return nullptr;  // different host / IPC namespace, or already gone
  }
  const size_t bytes = mapSize(ringBytes);
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size != static_cast<off_t>(bytes)) {
    ::close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd,
                    0);
  ::close(fd);
  if (base == MAP_FAILED) {
    return nullptr;
  }
  auto* hdr = static_cast<SegHdr*>(base);
  if (reinterpret_cast<std::atomic<uint32_t>*>(&hdr->magic)
              ->load(std::memory_order_acquire) != kShmSegMagic ||
      hdr->version != kShmSegVersion || hdr->pairId != pairId ||
      hdr->ringBytes != ringBytes) {
    munmap(base, bytes);
    return nullptr;
  }
  auto seg = std::unique_ptr<ShmSegment>(new ShmSegment());
  seg->name_ = name;
  seg->base_ = base;
  seg->mapBytes_ = bytes;
  seg->ringBytes_ = ringBytes;
  return seg;
}

void ShmSegment::unlinkName() {
  if (linked_) {
    shm_unlink(name_.c_str());
    linked_ = false;
  }
}

ShmRing ShmSegment::ring(int dir) const {
  auto* hdr = static_cast<SegHdr*>(base_);
  ShmRing r;
  r.head = &hdr->counters[dir * 2].v;
  r.tail = &hdr->counters[dir * 2 + 1].v;
  r.data = static_cast<char*>(base_) + kHdrBytes + dir * ringBytes_;
  r.cap = ringBytes_;
  return r;
}

ShmSegment::~ShmSegment() {
  unlinkName();
  if (base_ != nullptr) {
    munmap(base_, mapBytes_);
  }
}

}  // namespace transport
}  // namespace tpucoll
