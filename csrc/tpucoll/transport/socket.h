// Small socket option helpers shared by pair/listener/device.
#pragma once

#include <string>

namespace tpucoll {
namespace transport {

void setNonBlocking(int fd);
void setNoDelay(int fd);
void setReuseAddr(int fd);
std::string errnoString(const char* what);

}  // namespace transport
}  // namespace tpucoll
