// Small socket option helpers shared by pair/listener/device.
#pragma once

#include <string>

namespace tpucoll {
namespace transport {

void setNonBlocking(int fd);
void setNoDelay(int fd);
void setReuseAddr(int fd);
// Large buffers keep bulk collective segments flowing with fewer
// syscall/wakeup round trips (reference analog: SO_SNDBUF autotuning in
// gloo/transport/tcp/pair.cc:860-872).
void setBufferSizes(int fd, int bytes);
std::string errnoString(const char* what);

}  // namespace transport
}  // namespace tpucoll
