#include "tpucoll/transport/address.h"

#include <arpa/inet.h>
#include <netdb.h>

#include <cstring>

#include "tpucoll/common/logging.h"

namespace tpucoll {
namespace transport {

std::string SockAddr::str() const {
  char host[NI_MAXHOST];
  char port[NI_MAXSERV];
  int rv = getnameinfo(sa(), len, host, sizeof(host), port, sizeof(port),
                       NI_NUMERICHOST | NI_NUMERICSERV);
  if (rv != 0) {
    return "<unresolvable>";
  }
  return std::string(host) + ":" + port;
}

std::vector<uint8_t> SockAddr::serialize() const {
  std::vector<uint8_t> out(sizeof(socklen_t) + len);
  std::memcpy(out.data(), &len, sizeof(socklen_t));
  std::memcpy(out.data() + sizeof(socklen_t), &ss, len);
  return out;
}

SockAddr SockAddr::deserialize(const uint8_t* data, size_t size) {
  SockAddr addr;
  TC_ENFORCE_GE(size, sizeof(socklen_t), "address blob too short");
  std::memcpy(&addr.len, data, sizeof(socklen_t));
  TC_ENFORCE_LE(sizeof(socklen_t) + addr.len, size, "address blob truncated");
  TC_ENFORCE_LE(addr.len, socklen_t(sizeof(sockaddr_storage)));
  std::memcpy(&addr.ss, data + sizeof(socklen_t), addr.len);
  return addr;
}

SockAddr resolve(const std::string& hostname, uint16_t port) {
  addrinfo hints{};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  hints.ai_flags = AI_PASSIVE | AI_NUMERICSERV;
  addrinfo* result = nullptr;
  const std::string portStr = std::to_string(port);
  int rv = getaddrinfo(hostname.empty() ? nullptr : hostname.c_str(),
                       portStr.c_str(), &hints, &result);
  TC_ENFORCE_EQ(rv, 0, "getaddrinfo(", hostname, "): ", gai_strerror(rv));
  SockAddr addr;
  // Prefer IPv4 for loopback friendliness; fall back to first result.
  addrinfo* chosen = result;
  for (addrinfo* ai = result; ai != nullptr; ai = ai->ai_next) {
    if (ai->ai_family == AF_INET) {
      chosen = ai;
      break;
    }
  }
  addr.len = static_cast<socklen_t>(chosen->ai_addrlen);
  std::memcpy(&addr.ss, chosen->ai_addr, chosen->ai_addrlen);
  freeaddrinfo(result);
  return addr;
}

}  // namespace transport
}  // namespace tpucoll
