// Transport context: owns the full mesh of pairs for one process group and
// centralizes receive matching.
//
// Replaces the reference's per-slot tally/mutator machinery
// (gloo/transport/tcp/context.cc, gloo/transport/context.h:111-298) with a
// single matcher: a FIFO list of posted receives plus an arrival-ordered
// stash of early messages. Recv-from-any falls out naturally: a posted
// receive carries the set of admissible source ranks and the first matching
// arrival claims it. Self-sends short-circuit through the same matcher.
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <list>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "tpucoll/transport/wire.h"

#include "tpucoll/common/flightrec.h"
#include "tpucoll/common/logging.h"
#include "tpucoll/common/metrics.h"
#include "tpucoll/common/tracer.h"
#include "tpucoll/rendezvous/store.h"
#include "tpucoll/transport/address.h"
#include "tpucoll/transport/unbound_buffer.h"

namespace tpucoll {
namespace transport {

class Device;
class Pair;

class Context {
 public:
  Context(std::shared_ptr<Device> device, int rank, int size);
  ~Context();

  int rank() const { return rank_; }
  int size() const { return size_; }
  Device* device() const { return device_.get(); }

  // ---- multi-channel striping configuration ----
  // Effective knobs resolve as: TPUCOLL_CHANNELS / TPUCOLL_STRIPE_BYTES
  // env (strict parse, common/env.h) > setChannelConfig (the tuning
  // plane's hook) > defaults (1 channel -- the seed's wire behavior --
  // and 1 MiB). Must be called before the mesh is created; channel
  // count must agree across ranks (the bootstrap blob carries it and
  // connect fails loudly on a mismatch).
  void setChannelConfig(int channels, uint64_t stripeBytes);
  int channels() const { return channels_; }
  uint64_t stripeThresholdBytes() const { return stripeBytes_; }

  // Topology gate for the shm payload plane (group/topology.h): when
  // set — before the mesh connects — a pair only OFFERS shm to peers
  // the mask co-hosts. The per-connection same-IP probe still applies
  // on top; this is what keeps a simulated multi-host topology
  // (TPUCOLL_HOST_ID overrides) honest by pinning cross-"host" pairs
  // to TCP. Unset (the default, and the standalone-transport case)
  // allows every peer, the pre-topology behavior.
  void setShmPeers(std::vector<char> allowed) {
    shmPeers_ = std::move(allowed);
  }
  bool shmPeerAllowed(int rank) const {
    return shmPeers_.empty() ||
           (rank >= 0 && rank < static_cast<int>(shmPeers_.size()) &&
            shmPeers_[rank] != 0);
  }

  // Fault-plane identity of this mesh (fault.h): 0 — the default — is
  // the root/parent domain; async-engine lane contexts carry lane + 1 so
  // each lane's serial op stream draws from its own deterministic
  // per-(rule, rank, channel, domain) fault state. Set once right after
  // the mesh is created, before any traffic.
  void setFaultDomain(int domain) { faultDomain_ = domain; }
  int faultDomain() const { return faultDomain_; }

  // Store-based bootstrap: publish one blob per rank (address + per-peer
  // pair routing ids — O(n) store traffic per rank, O(n^2) total), then
  // connect the full mesh. Higher rank initiates, lower rank listens.
  void connectFullMesh(Store& store, std::chrono::milliseconds timeout);

  // Store-free bootstrap: create this context's pairs and return the rank
  // blob; then connect against all ranks' blobs (exchanged by the caller,
  // e.g. over an already-connected parent context — the reference's
  // ContextFactory pattern, gloo/rendezvous/context.cc:37-162).
  std::vector<uint8_t> prepareFullMesh();
  void connectWithBlobs(const std::vector<std::vector<uint8_t>>& blobs,
                        std::chrono::milliseconds timeout);

  // ---- lazy connection plane (boot/, docs/bootstrap.md) ----
  // Dual-simplex broker: each rank SENDS only on connections it dialed
  // (pairs_/channelPairs_); peer-dialed connections land in a separate
  // inbound table used for receive only. Receive matching is already
  // context-level (posted_/stashed_ keyed by source rank), so a posted
  // recv never needs a dialed pair, and two ranks dialing each other
  // concurrently never race over one connection slot.
  //
  // This rank's address payload for the rendezvous exchange:
  // [u32 magic][u32 channels][u32 addrLen][addr].
  std::vector<uint8_t> lazyAddressBlob() const;
  static void parseLazyAddressBlob(const std::vector<uint8_t>& blob,
                                   int expectChannels, SockAddr* addr);
  // Switch this context to lazy mode (instead of prepareFullMesh +
  // connect*): store the full address table, register with the device's
  // lazy-mesh registry under `meshId` (truncated to the id codec's mesh
  // bits), and arm the broker. `eager` marks peers dialEager() connects
  // up front (pinned, never evicted); everything else is dialed on
  // first use, capped at `maxPairs` broker-dialed logical pairs
  // (0 = unbounded) with LRU eviction of idle ones.
  void enableLazy(uint64_t meshId, std::vector<SockAddr> peerAddrs,
                  std::vector<char> eager, int maxPairs,
                  std::chrono::milliseconds dialTimeout);
  void dialEager(std::chrono::milliseconds timeout);
  // Device hook (listener loop thread): a broker-dialed inbound
  // connection arrived for this mesh; materialize its rx-only pair.
  void acceptLazyInbound(uint64_t pairId);
  bool lazyEnabled() const { return lazy_; }
  // Broker counters (metrics "boot" family): currently connected
  // outbound logical pairs (eager + broker-dialed), lifetime evictions,
  // currently live inbound connections, lifetime broker dials.
  void lazyPairStats(uint64_t* connected, uint64_t* evicted,
                     uint64_t* inbound, uint64_t* dials);

  std::unique_ptr<UnboundBuffer> createUnboundBuffer(void* ptr, size_t size);

  // ---- one-sided registered regions (RemoteKey put/get) ----
  // Register [ptr, ptr+size) as a one-sided target; returns the token a
  // serialized RemoteKey carries. Peers may then put into / get from the
  // region with no posted operation on this side; notify-puts complete a
  // waitRecv on `owner`.
  uint64_t registerRegion(char* ptr, size_t size, UnboundBuffer* owner);
  void unregisterRegion(uint64_t token);
  // Loop thread: validate + copy bytes out of a region (get). Empty
  // optional-like: returns false when the token is unknown or the range
  // is out of bounds.
  bool readRegion(uint64_t token, uint64_t roffset, uint64_t nbytes,
                  std::vector<char>* out);
  // Loop thread: validate + copy bytes into a region (put). Returns false
  // on unknown token / out-of-bounds (the caller poisons the pair). With
  // notify, the owner's waitRecv completes (srcRank reported); the
  // callback runs under mu_, which makes unregisterRegion a barrier: once
  // it returns no further notification can touch the owner.
  bool writeRegion(uint64_t token, uint64_t roffset, const char* data,
                   size_t nbytes, bool notify = false, int srcRank = -1);

  // Graceful teardown: closes all pairs; pending operations fail with
  // IoException. Idempotent.
  void close();

  // ---- internal API (UnboundBuffer / Pair) ----
  void postSend(UnboundBuffer* buf, int dstRank, uint64_t slot, char* data,
                size_t nbytes);
  // One-sided write: local bytes -> peer's registered region (token,
  // roffset). Completion via buf->waitSend; nothing happens peer-side.
  void postPut(UnboundBuffer* buf, int dstRank, uint64_t token,
               uint64_t roffset, char* data, size_t nbytes,
               bool notify = false);
  // One-sided read: request region bytes from dstRank; they arrive as a
  // normal message on respSlot (buf must have a recv posted for it).
  void postGetRequest(int dstRank, uint64_t respSlot, uint64_t token,
                      uint64_t roffset, size_t nbytes);
  // With `combine` set, arriving payload is reduced into `dest` via
  // combine(dest, payload, nbytes / combineElsize) instead of copied
  // (UnboundBuffer::recvReduce); staged paths combine from staging
  // memory. combineAccElsize (0 = combineElsize) is the accumulator's
  // per-element stride when the wire carries a different dtype
  // (recvReduceTyped).
  void postRecv(UnboundBuffer* buf, const std::vector<int>& srcRanks,
                uint64_t slot, char* dest, size_t nbytes,
                RecvReduceFn combine = nullptr, size_t combineElsize = 0,
                size_t combineAccElsize = 0);
  void cancelRecvsFor(UnboundBuffer* buf);
  // Drop queued (not yet on the wire) sends referencing buf; returns count.
  int cancelSendsFor(UnboundBuffer* buf);
  // Last-resort unblocking for ~UnboundBuffer: fail any pair that still has
  // an in-flight (partially written) send referencing buf.
  void failPairsWithInflightSend(UnboundBuffer* buf);

  // Loop thread, on a fresh message header: claim a destination for it.
  struct Match {
    bool direct{false};  // true: land payload at `dest` and complete `ubuf`
    UnboundBuffer* ubuf{nullptr};
    char* dest{nullptr};
    RecvReduceFn combine{nullptr};  // non-null: reduce into dest, don't copy
    size_t combineElsize{0};        // wire bytes per element
    size_t combineAccElsize{0};     // accumulator bytes per element
  };
  Match matchIncoming(int srcRank, uint64_t slot, size_t nbytes);

  // Loop thread, when a stashed payload has fully arrived. Re-checks posted
  // receives to close the race with a recv posted mid-payload.
  void stashArrived(int srcRank, uint64_t slot, std::vector<char> data);

  // ---- stripe reassembly (multi-channel receive path) ----
  // Loop thread of any channel pair, on a fresh kStripe header: claim
  // (or join) the reassembly entry for the message this stripe belongs
  // to, and return where the stripe's payload lands. The first stripe
  // of a message claims a posted receive exactly like matchIncoming
  // (and allocates a reassembly buffer when none is posted or when the
  // receive is a fused recvReduce, whose fold must wait for the whole
  // message). Throws on size mismatch or protocol violations (the pair
  // poisons itself).
  struct StripeMatch {
    char* dest;      // stripe payload destination (already offset)
    uint64_t entry;  // reassembly entry handle for stripeLanded
  };
  StripeMatch stripeIncoming(int srcRank, uint64_t slot, uint8_t seqLow,
                             uint64_t total, uint32_t count,
                             uint32_t index);
  // Loop thread, when a stripe's payload has fully (and, on encrypted
  // channels, verified) landed. Completes the logical message when it
  // was the last stripe: direct receives complete their buffer (folding
  // the stage for recvReduce), unmatched messages enter the stash via
  // the normal stashArrived race-closing path.
  void stripeLanded(int srcRank, uint64_t entry, uint32_t index);

  // A pair failed: poison posted receives that could match it and record the
  // error for future sends. `orderly` marks a goodbye-announced departure
  // (still poisons, but is not blamed in the metrics transport-failure
  // record — clean shutdown skew is not a death). `channel` is the data
  // channel of the failing connection (-1 = unknown): by the time a
  // pair's teardown notifies, its own rx is quiesced (fd del'd with the
  // loop barrier), so that channel's half-read stripe — if any — can be
  // safely abandoned while sibling channels may still be mid-payload.
  void onPairError(int rank, const std::string& message,
                   bool orderly = false, int channel = -1);
  void debugDump();

  // Shared-memory payload-plane stats summed over pairs: ring bytes sent /
  // received and how many pairs negotiated the plane (any thread).
  void shmStats(uint64_t* txBytes, uint64_t* rxBytes, int* activePairs);

  // True when payloads from `rank` arrive through an shm ring (or are
  // local self-sends) — i.e. when a fused recvReduce combines straight
  // from staging memory with no loss of reduce/I-O overlap. Schedules use
  // this to pick fused vs scratch receives per source (any thread).
  bool peerUsesShm(int rank);

  // ---- observability ----
  // Borrowed from the owning tpucoll::Context (which outlives this
  // object); all may be null for standalone transport use (C++ unit
  // tests). Set once before connect, read from data-path threads.
  void setInstrumentation(Tracer* tracer, Metrics* metrics,
                          FlightRecorder* flightrec = nullptr) {
    tracer_ = tracer;
    metrics_ = metrics;
    flightrec_ = flightrec;
  }
  Tracer* tracer() const { return tracer_; }
  Metrics* metrics() const { return metrics_; }
  FlightRecorder* flightrec() const { return flightrec_; }

  // Straggler watchdog: called by a blocking wait (UnboundBuffer) that
  // has made no progress past the watchdog threshold. Figures out which
  // peer/slot `buf` is blocked on from the pending-operation table
  // (posted receives / per-pair tx queues), logs it, and records the
  // stall in the metrics registry. The caller must NOT hold the buffer
  // lock (lock order is context -> buffer).
  void reportStall(UnboundBuffer* buf, bool isSend, int64_t waitedUs);

 private:
  struct PostedRecv {
    UnboundBuffer* ubuf;
    uint64_t slot;
    char* dest;
    size_t nbytes;
    std::vector<char> allowed;  // indexed by rank
    RecvReduceFn combine;       // non-null: reduce arrivals into dest
    size_t combineElsize;       // wire bytes per element
    size_t combineAccElsize;    // accumulator bytes per element
  };
  // Land `data` at `dest`: reduce when a combine fn is set, plain copy
  // otherwise. Single definition of delivery semantics for every staged
  // path (self-send, stash-hit, stashArrived race).
  static void landPayload(char* dest, RecvReduceFn combine,
                          size_t combineElsize, const char* data,
                          size_t nbytes);
  static void landPayload(const PostedRecv& pr, const char* data,
                          size_t nbytes);
  struct Stash {
    int srcRank;
    uint64_t slot;
    std::vector<char> data;
  };

  // Deliver a local or stashed payload into a posted recv (mu_ held).
  // Returns the matched entry or posted_.end().
  std::list<PostedRecv>::iterator findPosted(int srcRank, uint64_t slot,
                                             size_t nbytes);

  // Striped fan-out behind postSend/postPut (channels_ > 1, payload at
  // or above the stripe threshold, shm inactive for the peer).
  void postSendStriped(UnboundBuffer* buf, int dstRank, uint64_t slot,
                       char* data, size_t nbytes);
  void postPutStriped(UnboundBuffer* buf, int dstRank, uint64_t token,
                      uint64_t roffset, char* data, size_t nbytes);
  // Channel c of the logical pair to `rank` (c == 0: the primary pair).
  // May return null in lazy mode (pair not dialed / quiet-dropped).
  Pair* pairFor(int rank, int c) {
    if (c == 0) {
      return pairs_[rank].get();
    }
    auto& cps = channelPairs_[rank];
    return static_cast<size_t>(c - 1) < cps.size() ? cps[c - 1].get()
                                                   : nullptr;
  }
  // Lazy broker internals (mu_ held on entry/exit; ensureOutboundLocked
  // drops the lock around the blocking dial and eviction close).
  // outboundForLocked is the shared send-side lookup: full-mesh it is a
  // plain table read; lazy it re-dials quiet-dropped peers, touches the
  // LRU clock, and pins the pair (sets *pinned) across the caller's
  // use-outside-mu_ window so the broker cannot evict or reap it.
  Pair* outboundForLocked(int dstRank, std::unique_lock<std::mutex>& lock,
                          bool* pinned);
  Pair* ensureOutboundLocked(int dstRank, std::unique_lock<std::mutex>& lock);
  void evictForCapLocked(std::vector<std::unique_ptr<Pair>>* victims);
  bool logicalPairIdleLocked(int rank);
  void unpinLazy(int rank);
  // Any live connection to/from `rank` (outbound or lazy inbound)?
  // Gates the stash-backpressure pause/resume paths, which in lazy mode
  // must cover peer-dialed rx connections.
  bool hasAnyPairLocked(int rank);
  // Orderly lazy departure (peer evicted its dialed connection, or left
  // cleanly): move this rank's DEFUNCT pairs to the graveyard without
  // poisoning pairErrors_ — a future send simply re-dials. Healthy
  // connections in the other direction are left untouched.
  void quietDropLocked(int rank);
  // Stash backpressure across every channel of a peer (mu_ held).
  void pausePeerLocked(int rank);
  void resumePeerLocked(int rank);
  // Backpressure for IN-FLIGHT reassembly stages (mu_ held): unmatched
  // striped messages allocate their full `total` before completion, so
  // under channel skew a fast channel can open stages far ahead of a
  // laggard. Crossing the stash high watermark pauses only the channels
  // that are "ahead" — fully landed on every open entry from the source
  // — so no open entry's completion is ever blocked and the stage bytes
  // are guaranteed to keep draining (release below resumes them at the
  // low watermark).
  void accountStageLocked(int srcRank, size_t bytes);
  void maybePauseAheadChannelsLocked(int srcRank);
  void releaseStageLocked(int srcRank, size_t bytes);
  // Poison in-flight reassemblies from `rank` (pair failure / close):
  // entries with no stripe mid-payload are reaped immediately (their
  // claimed buffers appended to `victims` for the caller to fail
  // OUTSIDE mu_); entries a sibling channel is still writing into are
  // marked dead and reaped by the last stripeLanded. `channel` >= 0
  // abandons that (quiesced) channel's own half-read stripe;
  // `allQuiesced` (close(): every pair already torn down) force-reaps
  // everything. mu_ held.
  void dropStripesLocked(int rank, const std::string& message, int channel,
                         bool allQuiesced,
                         std::vector<UnboundBuffer*>* victims);

  // One in-flight striped message's reassembly state (mu_). Lifetime
  // rule: an entry (and so `buf`, which channel loop threads write into
  // WITHOUT mu_ between stripeIncoming and stripeLanded) may only be
  // freed once every arrived stripe has landed or its channel's rx is
  // provably quiesced — a peer failure therefore marks entries `dead`
  // and defers the reap to the last in-flight stripe instead of
  // freeing memory under a sibling channel's read.
  struct StripeEntry {
    uint64_t id;
    int srcRank;
    uint64_t slot;
    uint8_t seqLow;
    uint64_t total;
    uint32_t count;
    uint32_t arrivedMask{0};  // stripes whose header was matched
    uint32_t landedMask{0};   // stripes whose payload fully landed
    bool direct{false};       // claimed a posted recv at creation
    bool dead{false};         // source rank failed; reap when quiescent
    std::string error;        // failure message for the deferred ubuf error
    UnboundBuffer* ubuf{nullptr};
    char* dest{nullptr};            // posted destination (direct)
    RecvReduceFn combine{nullptr};  // non-null: fold buf into dest at end
    size_t combineElsize{0};
    std::vector<char> buf;  // stash payload, or recvReduce stage
  };

  const std::shared_ptr<Device> device_;
  const int rank_;
  const int size_;
  int channels_{1};
  int faultDomain_{0};
  // Per-peer shm eligibility (setShmPeers); empty = all allowed.
  std::vector<char> shmPeers_;
  uint64_t stripeBytes_{uint64_t(1) << 20};
  bool channelsFromEnv_{false};
  bool stripeBytesFromEnv_{false};
  // Tags all stripes of one logical message (low byte travels in the
  // header flags) so back-to-back same-slot messages reassemble
  // unambiguously.
  std::atomic<uint64_t> stripeSeq_{0};
  Tracer* tracer_{nullptr};
  Metrics* metrics_{nullptr};
  FlightRecorder* flightrec_{nullptr};

  std::mutex mu_;
  std::vector<std::unique_ptr<Pair>> pairs_;
  // channelPairs_[rank] holds channels 1..channels_-1 to that peer
  // (channel 0 is pairs_[rank]); empty when channels_ == 1.
  std::vector<std::vector<std::unique_ptr<Pair>>> channelPairs_;
  std::list<StripeEntry> stripes_;  // in-flight reassemblies (mu_)
  uint64_t nextStripeEntry_{1};
  std::list<PostedRecv> posted_;
  std::deque<Stash> stashed_;
  std::vector<std::string> pairErrors_;
  // Stash backpressure (mu_): bytes staged per source rank. Crossing the
  // high watermark pauses that pair's socket (TCP throttles the sender);
  // posting a receive that admits the rank resumes it — posted receives
  // bypass the stash, so progress is always possible.
  std::vector<size_t> stashBytes_;
  std::vector<char> rxPaused_;
  // In-flight unmatched reassembly stages per source (mu_); see
  // accountStageLocked. stripePausedMask_ names channels paused by that
  // mechanism (bit c = channel c), cleared by any full-peer resume.
  std::vector<size_t> stripeStageBytes_;
  std::vector<uint32_t> stripePausedMask_;
  size_t stashHighWater_;
  bool closed_{false};

  // One-sided region registry (mu_). Tokens are never reused, so a stale
  // RemoteKey can only miss, not alias a new region.
  struct Region {
    char* ptr;
    size_t size;
    UnboundBuffer* owner;
  };
  std::unordered_map<uint64_t, Region> regions_;
  uint64_t nextRegionToken_{1};

  // ---- lazy broker state (mu_ unless noted) ----
  bool lazy_{false};
  uint32_t meshId_{0};  // codec-truncated rendezvous mesh id
  int maxLazyPairs_{0};  // broker-dialed logical pair cap (0 = unbounded)
  std::chrono::milliseconds lazyDialTimeout_{std::chrono::milliseconds(30000)};
  std::vector<SockAddr> lazyPeerAddrs_;
  std::vector<char> lazyEager_;      // pinned topology pairs, never evicted
  std::vector<uint32_t> dialGen_;    // per-peer redial generation (id codec)
  std::vector<char> dialing_;        // a thread is mid-dial to this peer
  std::vector<int> lazyPinned_;      // ops between lookup and enqueue
  std::vector<uint64_t> lazyLastUse_;  // LRU clock value per peer
  uint64_t lazyUseTick_{0};
  int lazyOutboundCount_{0};  // broker-dialed (non-eager) logical pairs
  // inboundPairs_[rank][channel]: peer-dialed rx-only connections.
  std::vector<std::vector<std::unique_ptr<Pair>>> inboundPairs_;
  // Defunct pairs awaiting a safe destruction point (a Pair cannot be
  // destroyed inside its own teardown callback; reaped under the loop
  // barrier in close()/~Context).
  std::vector<std::unique_ptr<Pair>> graveyard_;
  std::condition_variable dialCv_;
  std::atomic<uint64_t> lazyDials_{0};
  std::atomic<uint64_t> lazyEvictions_{0};
};

}  // namespace transport
}  // namespace tpucoll
