#include "tpucoll/transport/loop_uring.h"

#include <linux/io_uring.h>
#include <poll.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <cstring>
#include <thread>
#include <unordered_map>

#include "tpucoll/common/logging.h"

namespace tpucoll {
namespace transport {

namespace {

int sysIoUringSetup(unsigned entries, io_uring_params* p) {
  return static_cast<int>(syscall(__NR_io_uring_setup, entries, p));
}

int sysIoUringEnter(int fd, unsigned toSubmit, unsigned minComplete,
                    unsigned flags) {
  return static_cast<int>(syscall(__NR_io_uring_enter, fd, toSubmit,
                                  minComplete, flags, nullptr, 0));
}

// user_data encoding: fd in the high 32 bits, registration generation in
// the low 32. Generations disambiguate stale completions after del/re-add
// of the same fd (fds are reused by the kernel immediately).
uint64_t encodeUd(int fd, uint32_t gen) {
  return (uint64_t(uint32_t(fd)) << 32) | gen;
}
int udFd(uint64_t ud) { return int(uint32_t(ud >> 32)); }
uint32_t udGen(uint64_t ud) { return uint32_t(ud); }

// POLL_REMOVE completions carry this marker so the dispatch loop drops
// them without a table lookup (fd slot 0xFFFFFFFF is never a real fd).
constexpr uint64_t kRemoveUd = ~uint64_t(0);

// SQ depth: submission is immediate after every prep batch (max 2 SQEs),
// so this never fills. CQ depth: every registered fd keeps one oneshot
// poll in flight, so outstanding CQEs scale with the device's fd count
// (pairs x contexts sharing one device) — ask for a deep CQ up front
// (IORING_SETUP_CQSIZE, 64 KiB of ring) and additionally survive
// overflow via FEAT_NODROP + the -EBUSY retry in submitLocked.
constexpr unsigned kSqEntries = 256;
constexpr unsigned kCqEntries = 4096;

}  // namespace

class UringLoop : public LoopBase {
 public:
  explicit UringLoop(bool busyPoll) : LoopBase(busyPoll) {
    io_uring_params p;
    std::memset(&p, 0, sizeof(p));
    p.flags = IORING_SETUP_CQSIZE;
    p.cq_entries = kCqEntries;
    ringFd_ = sysIoUringSetup(kSqEntries, &p);
    TC_ENFORCE_GE(ringFd_, 0, "io_uring_setup: ", strerror(errno),
                  " (TPUCOLL_ENGINE=epoll to use the epoll engine)");

    // Map the rings. With FEAT_SINGLE_MMAP the SQ and CQ rings share one
    // mapping; otherwise they are separate.
    sqLen_ = p.sq_off.array + p.sq_entries * sizeof(unsigned);
    cqLen_ = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
    const bool single = (p.features & IORING_FEAT_SINGLE_MMAP) != 0;
    if (single) {
      sqLen_ = cqLen_ = std::max(sqLen_, cqLen_);
    }
    sqPtr_ = mmap(nullptr, sqLen_, PROT_READ | PROT_WRITE,
                  MAP_SHARED | MAP_POPULATE, ringFd_, IORING_OFF_SQ_RING);
    TC_ENFORCE(sqPtr_ != MAP_FAILED, "io_uring sq mmap: ", strerror(errno));
    if (single) {
      cqPtr_ = sqPtr_;
    } else {
      cqPtr_ = mmap(nullptr, cqLen_, PROT_READ | PROT_WRITE,
                    MAP_SHARED | MAP_POPULATE, ringFd_, IORING_OFF_CQ_RING);
      TC_ENFORCE(cqPtr_ != MAP_FAILED, "io_uring cq mmap: ",
                 strerror(errno));
    }
    sqeLen_ = p.sq_entries * sizeof(io_uring_sqe);
    sqes_ = static_cast<io_uring_sqe*>(
        mmap(nullptr, sqeLen_, PROT_READ | PROT_WRITE,
             MAP_SHARED | MAP_POPULATE, ringFd_, IORING_OFF_SQES));
    TC_ENFORCE(sqes_ != MAP_FAILED, "io_uring sqe mmap: ", strerror(errno));

    auto* sq = static_cast<char*>(sqPtr_);
    sqHead_ = reinterpret_cast<unsigned*>(sq + p.sq_off.head);
    sqTail_ = reinterpret_cast<unsigned*>(sq + p.sq_off.tail);
    sqMask_ = *reinterpret_cast<unsigned*>(sq + p.sq_off.ring_mask);
    sqArray_ = reinterpret_cast<unsigned*>(sq + p.sq_off.array);
    auto* cq = static_cast<char*>(cqPtr_);
    cqHead_ = reinterpret_cast<unsigned*>(cq + p.cq_off.head);
    cqTail_ = reinterpret_cast<unsigned*>(cq + p.cq_off.tail);
    cqMask_ = *reinterpret_cast<unsigned*>(cq + p.cq_off.ring_mask);
    cqes_ = reinterpret_cast<io_uring_cqe*>(cq + p.cq_off.cqes);

    {
      std::lock_guard<std::mutex> guard(mu_);
      armWakeLocked();
    }
    startThread();
  }

  ~UringLoop() override {
    stopThread();
    if (cqPtr_ != sqPtr_ && cqPtr_ != nullptr) {
      munmap(cqPtr_, cqLen_);
    }
    if (sqPtr_ != nullptr) {
      munmap(sqPtr_, sqLen_);
    }
    if (sqes_ != nullptr) {
      munmap(sqes_, sqeLen_);
    }
    ::close(ringFd_);
  }

  void add(int fd, uint32_t events, Handler* handler) override {
    std::lock_guard<std::mutex> guard(mu_);
    Reg& reg = regs_[fd];
    reg.handler = handler;
    reg.events = events;
    reg.gen = nextGen_++;
    reg.armed = true;
    armLocked(fd, reg);
    submitLocked();
  }

  void mod(int fd, uint32_t events, Handler* handler) override {
    std::lock_guard<std::mutex> guard(mu_);
    auto it = regs_.find(fd);
    TC_ENFORCE(it != regs_.end(), "uring mod: fd not registered");
    Reg& reg = it->second;
    reg.handler = handler;
    reg.events = events;
    if (reg.armed) {
      // Cancel the in-flight poll and re-arm with the new mask under a
      // fresh generation (the stale completion, ready or cancelled, is
      // dropped by the generation check).
      removeLocked(fd, reg.gen);
      reg.gen = nextGen_++;
      armLocked(fd, reg);
    }
    // !armed: the fd is mid-dispatch on the loop thread; the post-dispatch
    // re-arm picks up the new mask.
    submitLocked();
  }

  void del(int fd) override {
    {
      std::lock_guard<std::mutex> guard(mu_);
      auto it = regs_.find(fd);
      if (it != regs_.end()) {
        if (it->second.armed) {
          removeLocked(fd, it->second.gen);
          submitLocked();
        }
        regs_.erase(it);
      }
    }
    // Tick barrier: once the loop completes the current dispatch batch, no
    // stale completion for fd can still be dispatching.
    barrier();
  }

  const char* engineName() const override { return "uring"; }

 private:
  struct Reg {
    Handler* handler{nullptr};
    uint32_t events{0};
    uint32_t gen{0};
    bool armed{false};
  };

  // --- SQ production (mu_ held) ---

  io_uring_sqe* sqeLocked() {
    // Submission is immediate after every prep batch, and batches are at
    // most 2 entries (remove + add), so the SQ cannot fill.
    const unsigned head =
        __atomic_load_n(sqHead_, __ATOMIC_ACQUIRE);
    const unsigned tail = sqTailLocal_;
    TC_ENFORCE(tail - head < kSqEntries, "io_uring SQ overflow");
    io_uring_sqe* sqe = &sqes_[tail & sqMask_];
    std::memset(sqe, 0, sizeof(*sqe));
    sqArray_[tail & sqMask_] = tail & sqMask_;
    sqTailLocal_ = tail + 1;
    pending_++;
    return sqe;
  }

  void armLocked(int fd, const Reg& reg) {
    io_uring_sqe* sqe = sqeLocked();
    sqe->opcode = IORING_OP_POLL_ADD;
    sqe->fd = fd;
    // EPOLL* and POLL* share values for IN/OUT/ERR/HUP/RDHUP; pass through.
    sqe->poll32_events = reg.events | POLLERR | POLLHUP;
    sqe->user_data = encodeUd(fd, reg.gen);
  }

  void removeLocked(int fd, uint32_t gen) {
    io_uring_sqe* sqe = sqeLocked();
    sqe->opcode = IORING_OP_POLL_REMOVE;
    sqe->addr = encodeUd(fd, gen);
    sqe->user_data = kRemoveUd;
  }

  void armWakeLocked() {
    io_uring_sqe* sqe = sqeLocked();
    sqe->opcode = IORING_OP_POLL_ADD;
    sqe->fd = wakeFd_;
    sqe->poll32_events = POLLIN;
    sqe->user_data = encodeUd(wakeFd_, 0);  // gen 0 = the wake poll
    submitLocked();
  }

  void submitLocked() {
    if (pending_ == 0) {
      return;
    }
    __atomic_store_n(sqTail_, sqTailLocal_, __ATOMIC_RELEASE);
    const unsigned n = pending_;
    pending_ = 0;
    for (;;) {
      int rv = sysIoUringEnter(ringFd_, n, 0, 0);
      if (rv >= 0) {
        return;
      }
      if (errno == EINTR) {
        continue;
      }
      if (errno == EBUSY) {
        // CQ is saturated (FEAT_NODROP backlog): the loop thread drains
        // it without taking mu_, so yielding here makes progress even
        // though we hold the lock. Bounded in practice by the CQ depth.
        std::this_thread::yield();
        continue;
      }
      TC_THROW(EnforceError, "io_uring_enter(submit): ", strerror(errno));
    }
  }

  // --- CQ consumption (loop thread only) ---

  void run() override {
    struct Completion {
      uint64_t ud;
      int32_t res;
    };
    std::vector<Completion> batch;
    while (!stop_.load()) {
      // Drain available completions (sole consumer: plain head, acquire
      // tail).
      batch.clear();
      unsigned head = *cqHead_;
      const unsigned tail = __atomic_load_n(cqTail_, __ATOMIC_ACQUIRE);
      for (; head != tail; head++) {
        const io_uring_cqe& cqe = cqes_[head & cqMask_];
        batch.push_back({cqe.user_data, cqe.res});
      }
      __atomic_store_n(cqHead_, head, __ATOMIC_RELEASE);

      if (batch.empty()) {
        if (busyPoll_) {
#if defined(__x86_64__) || defined(__i386__)
          __builtin_ia32_pause();
#endif
          // Same contract as EpollLoop: barrier()/defer() write the wake
          // eventfd first, so skipping endOfBatch() on empty spins cannot
          // strand a waiter.
          std::this_thread::yield();
          continue;
        }
        int rv = sysIoUringEnter(ringFd_, 0, 1, IORING_ENTER_GETEVENTS);
        if (rv < 0 && errno != EINTR && errno != EBUSY) {
          TC_ERROR("io_uring_enter(wait): ", strerror(errno));
        }
        continue;  // re-drain
      }

      for (const Completion& c : batch) {
        if (c.ud == kRemoveUd) {
          continue;  // POLL_REMOVE ack
        }
        const int fd = udFd(c.ud);
        const uint32_t gen = udGen(c.ud);
        if (fd == wakeFd_ && gen == 0) {
          uint64_t drain;
          while (read(wakeFd_, &drain, sizeof(drain)) > 0) {
          }
          std::lock_guard<std::mutex> guard(mu_);
          armWakeLocked();
          continue;
        }
        Handler* handler = nullptr;
        {
          std::lock_guard<std::mutex> guard(mu_);
          auto it = regs_.find(fd);
          if (it == regs_.end() || it->second.gen != gen) {
            continue;  // stale: removed or re-registered since
          }
          it->second.armed = false;
          handler = it->second.handler;
        }
        // Same-generation ECANCELED should not happen (mod() bumps the
        // generation before cancelling), but if it does, skip the dispatch
        // and fall through to the re-arm so the fd cannot go silent.
        if (c.res != -ECANCELED) {
          const uint32_t events =
              c.res > 0 ? uint32_t(c.res) : uint32_t(EPOLLERR);
          try {
            handler->handleEvents(events);
          } catch (const std::exception& e) {
            // Same contract as EpollLoop: handlers own expected failures.
            TC_ERROR("unhandled exception on uring loop thread: ", e.what());
          }
        }
        // Oneshot re-arm AFTER dispatch: POLL_ADD reports current
        // readiness immediately, so un-drained data (read budget) fires
        // again right away — level-triggered semantics.
        {
          std::lock_guard<std::mutex> guard(mu_);
          auto it = regs_.find(fd);
          if (it != regs_.end() && it->second.gen == gen &&
              !it->second.armed) {
            it->second.armed = true;
            armLocked(fd, it->second);
            submitLocked();
          }
        }
      }

      endOfBatch();
    }
  }

  int ringFd_{-1};
  void* sqPtr_{nullptr};
  void* cqPtr_{nullptr};
  size_t sqLen_{0}, cqLen_{0}, sqeLen_{0};
  io_uring_sqe* sqes_{nullptr};
  unsigned* sqHead_{nullptr};
  unsigned* sqTail_{nullptr};
  unsigned sqMask_{0};
  unsigned* sqArray_{nullptr};
  unsigned* cqHead_{nullptr};
  unsigned* cqTail_{nullptr};
  unsigned cqMask_{0};
  io_uring_cqe* cqes_{nullptr};

  unsigned sqTailLocal_{0};  // mu_ held for writes
  unsigned pending_{0};
  std::unordered_map<int, Reg> regs_;
  uint32_t nextGen_{1};  // gen 0 is reserved for the wake poll
};

bool uringAvailable() {
  static const bool ok = [] {
    io_uring_params p;
    std::memset(&p, 0, sizeof(p));
    int fd = sysIoUringSetup(2, &p);
    if (fd < 0) {
      return false;
    }
    ::close(fd);
    return true;
  }();
  return ok;
}

std::unique_ptr<Loop> makeUringLoop(bool busyPoll) {
  return std::make_unique<UringLoop>(busyPoll);
}

}  // namespace transport
}  // namespace tpucoll
