#include "tpucoll/transport/loop_uring.h"

#include <linux/io_uring.h>
#include <poll.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <cstring>
#include <deque>
#include <thread>
#include <unordered_map>

#include "tpucoll/common/logging.h"

namespace tpucoll {
namespace transport {

namespace {

int sysIoUringSetup(unsigned entries, io_uring_params* p) {
  return static_cast<int>(syscall(__NR_io_uring_setup, entries, p));
}

int sysIoUringEnter(int fd, unsigned toSubmit, unsigned minComplete,
                    unsigned flags) {
  return static_cast<int>(syscall(__NR_io_uring_enter, fd, toSubmit,
                                  minComplete, flags, nullptr, 0));
}

// user_data encoding: fd in the high 32 bits, then a 2-bit op kind, then
// a 30-bit registration generation. Generations disambiguate stale
// completions after del/re-add of the same fd (fds are reused by the
// kernel immediately); the kind routes the completion (readiness poll vs
// data-path recv/send).
enum UdKind : uint32_t { kKindPoll = 0, kKindRecv = 1, kKindSend = 2 };
constexpr uint32_t kGenBits = 30;
constexpr uint32_t kGenMask = (1u << kGenBits) - 1;

uint64_t encodeUd(int fd, UdKind kind, uint32_t gen) {
  return (uint64_t(uint32_t(fd)) << 32) | (uint64_t(kind) << kGenBits) |
         (gen & kGenMask);
}
int udFd(uint64_t ud) { return int(uint32_t(ud >> 32)); }
UdKind udKind(uint64_t ud) {
  return UdKind((uint32_t(ud) >> kGenBits) & 0x3);
}
uint32_t udGen(uint64_t ud) { return uint32_t(ud) & kGenMask; }

// POLL_REMOVE / ASYNC_CANCEL completions carry this marker so the
// dispatch loop drops them without a table lookup (fd slot 0xFFFFFFFF is
// never a real fd).
constexpr uint64_t kRemoveUd = ~uint64_t(0);

// SQ depth: producers submit (or the loop thread flushes) after every
// prep batch; sqeLocked() force-flushes if a batch ever reaches the ring
// size. CQ depth: every registered fd keeps at most one oneshot poll and
// two data ops in flight, so outstanding CQEs scale with the device's fd
// count (pairs x contexts sharing one device) — ask for a deep CQ up
// front (IORING_SETUP_CQSIZE) and additionally survive overflow via
// FEAT_NODROP (enforced at setup) + the -EBUSY handling in enterSubmit.
constexpr unsigned kSqEntries = 256;
constexpr unsigned kCqEntries = 4096;

}  // namespace

class UringLoop : public LoopBase {
 public:
  explicit UringLoop(bool busyPoll) : LoopBase(busyPoll) {
    io_uring_params p;
    std::memset(&p, 0, sizeof(p));
    p.flags = IORING_SETUP_CQSIZE;
    p.cq_entries = kCqEntries;
    ringFd_ = sysIoUringSetup(kSqEntries, &p);
    TC_ENFORCE_GE(ringFd_, 0, "io_uring_setup: ", strerror(errno),
                  " (TPUCOLL_ENGINE=epoll to use the epoll engine)");
    // Overflow survival (and del()'s drain loop) depend on the kernel
    // never dropping completions. 5.5+ (FEAT_NODROP) is also the floor
    // for the data-path opcodes (OP_RECV/OP_SENDMSG are 5.6).
    TC_ENFORCE((p.features & IORING_FEAT_NODROP) != 0,
               "io_uring lacks IORING_FEAT_NODROP (kernel too old); "
               "TPUCOLL_ENGINE=epoll to use the epoll engine");

    // Map the rings. With FEAT_SINGLE_MMAP the SQ and CQ rings share one
    // mapping; otherwise they are separate.
    sqLen_ = p.sq_off.array + p.sq_entries * sizeof(unsigned);
    cqLen_ = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
    const bool single = (p.features & IORING_FEAT_SINGLE_MMAP) != 0;
    if (single) {
      sqLen_ = cqLen_ = std::max(sqLen_, cqLen_);
    }
    sqPtr_ = mmap(nullptr, sqLen_, PROT_READ | PROT_WRITE,
                  MAP_SHARED | MAP_POPULATE, ringFd_, IORING_OFF_SQ_RING);
    TC_ENFORCE(sqPtr_ != MAP_FAILED, "io_uring sq mmap: ", strerror(errno));
    if (single) {
      cqPtr_ = sqPtr_;
    } else {
      cqPtr_ = mmap(nullptr, cqLen_, PROT_READ | PROT_WRITE,
                    MAP_SHARED | MAP_POPULATE, ringFd_, IORING_OFF_CQ_RING);
      TC_ENFORCE(cqPtr_ != MAP_FAILED, "io_uring cq mmap: ",
                 strerror(errno));
    }
    sqeLen_ = p.sq_entries * sizeof(io_uring_sqe);
    sqes_ = static_cast<io_uring_sqe*>(
        mmap(nullptr, sqeLen_, PROT_READ | PROT_WRITE,
             MAP_SHARED | MAP_POPULATE, ringFd_, IORING_OFF_SQES));
    TC_ENFORCE(sqes_ != MAP_FAILED, "io_uring sqe mmap: ", strerror(errno));

    auto* sq = static_cast<char*>(sqPtr_);
    sqHead_ = reinterpret_cast<unsigned*>(sq + p.sq_off.head);
    sqTail_ = reinterpret_cast<unsigned*>(sq + p.sq_off.tail);
    sqMask_ = *reinterpret_cast<unsigned*>(sq + p.sq_off.ring_mask);
    sqArray_ = reinterpret_cast<unsigned*>(sq + p.sq_off.array);
    auto* cq = static_cast<char*>(cqPtr_);
    cqHead_ = reinterpret_cast<unsigned*>(cq + p.cq_off.head);
    cqTail_ = reinterpret_cast<unsigned*>(cq + p.cq_off.tail);
    cqMask_ = *reinterpret_cast<unsigned*>(cq + p.cq_off.ring_mask);
    cqes_ = reinterpret_cast<io_uring_cqe*>(cq + p.cq_off.cqes);

    {
      std::lock_guard<std::mutex> guard(mu_);
      armWakeLocked();
      flushLocked();
    }
    startThread();
  }

  ~UringLoop() override {
    stopThread();
    if (cqPtr_ != sqPtr_ && cqPtr_ != nullptr) {
      munmap(cqPtr_, cqLen_);
    }
    if (sqPtr_ != nullptr) {
      munmap(sqPtr_, sqLen_);
    }
    if (sqes_ != nullptr) {
      munmap(sqes_, sqeLen_);
    }
    ::close(ringFd_);
  }

  void add(int fd, uint32_t events, Handler* handler) override {
    std::lock_guard<std::mutex> guard(mu_);
    Reg& reg = regs_[fd];
    reg.handler = handler;
    reg.events = events;
    reg.gen = nextGenLocked();
    reg.armed = true;
    reg.dataMode = false;
    armLocked(fd, reg);
    flushLocked();
  }

  void mod(int fd, uint32_t events, Handler* handler) override {
    std::lock_guard<std::mutex> guard(mu_);
    auto it = regs_.find(fd);
    TC_ENFORCE(it != regs_.end(), "uring mod: fd not registered");
    Reg& reg = it->second;
    reg.handler = handler;
    reg.events = events;
    if (reg.armed) {
      // Cancel the in-flight poll and re-arm with the new mask under a
      // fresh generation (the stale completion, ready or cancelled, is
      // dropped by the generation check).
      removeLocked(fd, reg.gen);
      reg.gen = nextGenLocked();
      armLocked(fd, reg);
    }
    // !armed: the fd is mid-dispatch on the loop thread; the post-dispatch
    // re-arm picks up the new mask.
    flushLocked();
  }

  void del(int fd) override {
    {
      std::unique_lock<std::mutex> lock(mu_);
      auto it = regs_.find(fd);
      if (it == regs_.end()) {
        return;
      }
      Reg& reg = it->second;
      reg.dying = true;
      if (reg.armed) {
        removeLocked(fd, reg.gen);
      }
      // Cancel outstanding data ops and WAIT for their terminal
      // completions: the kernel may be mid-copy into/out of the caller's
      // buffers, and the del() contract is "no dispatch AND no kernel
      // access to op memory after return".
      if (reg.recvOut) {
        cancelLocked(encodeUd(fd, kKindRecv, reg.gen));
      }
      if (reg.sendOut) {
        cancelLocked(encodeUd(fd, kKindSend, reg.gen));
      }
      flushLocked(/*force=*/true);
      if (reg.recvOut || reg.sendOut) {
        if (onLoopThread()) {
          drainFdOpsOnLoopThread(lock, fd);
        } else {
          dataCv_.wait(lock, [&] {
            auto i2 = regs_.find(fd);
            return i2 == regs_.end() ||
                   (!i2->second.recvOut && !i2->second.sendOut);
          });
        }
      }
      regs_.erase(fd);
    }
    // Tick barrier: once the loop completes the current dispatch batch,
    // no completion for fd — stale poll event OR data-path
    // handleIoComplete (whose recvOut/sendOut were cleared at dispatch,
    // BEFORE the handler ran) — can still be executing. Without this,
    // del() could return mid-handler and the caller would free buffers
    // the handler is still writing. No-op when called from the loop
    // thread itself (the in-flight handler is this call stack).
    barrier();
  }

  const char* engineName() const override { return "uring"; }

  EngineStats engineStats() const override {
    EngineStats s;
    s.enters = statEnters_.load(std::memory_order_relaxed);
    s.sqes = statSqes_.load(std::memory_order_relaxed);
    s.cqes = statCqes_.load(std::memory_order_relaxed);
    return s;
  }

  // ---- submission data path ----

  bool hasDataPath() const override { return true; }

  void addData(int fd, Handler* handler) override {
    std::lock_guard<std::mutex> guard(mu_);
    Reg& reg = regs_[fd];
    reg.handler = handler;
    reg.gen = nextGenLocked();
    reg.dataMode = true;
    reg.armed = false;
  }

  void asyncRecv(int fd, void* buf, size_t len) override {
    std::lock_guard<std::mutex> guard(mu_);
    auto it = regs_.find(fd);
    TC_ENFORCE(it != regs_.end() && it->second.dataMode && !it->second.dying,
               "uring asyncRecv: fd not in data mode");
    Reg& reg = it->second;
    TC_ENFORCE(!reg.recvOut, "uring asyncRecv: recv already outstanding");
    io_uring_sqe* sqe = sqeLocked();
    sqe->opcode = IORING_OP_RECV;
    sqe->fd = fd;
    sqe->addr = reinterpret_cast<uint64_t>(buf);
    sqe->len = static_cast<uint32_t>(len);
    sqe->user_data = encodeUd(fd, kKindRecv, reg.gen);
    reg.recvOut = true;
    flushLocked();
  }

  void asyncSend(int fd, const iovec* iov, int iovcnt) override {
    std::lock_guard<std::mutex> guard(mu_);
    auto it = regs_.find(fd);
    TC_ENFORCE(it != regs_.end() && it->second.dataMode && !it->second.dying,
               "uring asyncSend: fd not in data mode");
    Reg& reg = it->second;
    TC_ENFORCE(!reg.sendOut, "uring asyncSend: send already outstanding");
    TC_ENFORCE(iovcnt > 0 && iovcnt <= kTxIovMax,
               "uring asyncSend: bad iovcnt");
    // The msghdr/iovec must stay valid until the kernel consumes the
    // SQE (and with ASYNC they must live until completion): copy into
    // registration-owned storage.
    for (int i = 0; i < iovcnt; i++) {
      reg.txIov[i] = iov[i];
    }
    std::memset(&reg.txMsg, 0, sizeof(reg.txMsg));
    reg.txMsg.msg_iov = reg.txIov;
    reg.txMsg.msg_iovlen = static_cast<size_t>(iovcnt);
    io_uring_sqe* sqe = sqeLocked();
    sqe->opcode = IORING_OP_SENDMSG;
    sqe->fd = fd;
    sqe->addr = reinterpret_cast<uint64_t>(&reg.txMsg);
    sqe->len = 1;
    sqe->msg_flags = MSG_NOSIGNAL;
    sqe->user_data = encodeUd(fd, kKindSend, reg.gen);
    reg.sendOut = true;
    flushLocked();
  }

 private:
  static constexpr int kTxIovMax = 4;

  struct Reg {
    Handler* handler{nullptr};
    uint32_t events{0};
    uint32_t gen{0};
    bool armed{false};     // readiness poll in flight
    bool dataMode{false};  // addData registration (no poll)
    bool dying{false};     // del() in progress: drop completions
    bool recvOut{false};   // data-path ops in flight
    bool sendOut{false};
    msghdr txMsg{};
    iovec txIov[kTxIovMax];
  };

  struct Completion {
    uint64_t ud;
    int32_t res;
  };

  uint32_t nextGenLocked() { return nextGen_++ & kGenMask; }

  // --- SQ production (mu_ held) ---

  io_uring_sqe* sqeLocked() {
    const unsigned head = __atomic_load_n(sqHead_, __ATOMIC_ACQUIRE);
    if (sqTailLocal_ - head >= kSqEntries) {
      // A lazy loop-thread batch filled the ring: flush it now.
      flushLocked(/*force=*/true);
    }
    const unsigned tail = sqTailLocal_;
    io_uring_sqe* sqe = &sqes_[tail & sqMask_];
    std::memset(sqe, 0, sizeof(*sqe));
    sqArray_[tail & sqMask_] = tail & sqMask_;
    sqTailLocal_ = tail + 1;
    pending_++;
    return sqe;
  }

  void armLocked(int fd, const Reg& reg) {
    io_uring_sqe* sqe = sqeLocked();
    sqe->opcode = IORING_OP_POLL_ADD;
    sqe->fd = fd;
    // EPOLL* and POLL* share values for IN/OUT/ERR/HUP/RDHUP; pass through.
    sqe->poll32_events = reg.events | POLLERR | POLLHUP;
    sqe->user_data = encodeUd(fd, kKindPoll, reg.gen);
  }

  void removeLocked(int fd, uint32_t gen) {
    io_uring_sqe* sqe = sqeLocked();
    sqe->opcode = IORING_OP_POLL_REMOVE;
    sqe->addr = encodeUd(fd, kKindPoll, gen);
    sqe->user_data = kRemoveUd;
  }

  void cancelLocked(uint64_t targetUd) {
    io_uring_sqe* sqe = sqeLocked();
    sqe->opcode = IORING_OP_ASYNC_CANCEL;
    sqe->addr = targetUd;
    sqe->user_data = kRemoveUd;
  }

  void armWakeLocked() {
    io_uring_sqe* sqe = sqeLocked();
    sqe->opcode = IORING_OP_POLL_ADD;
    sqe->fd = wakeFd_;
    sqe->poll32_events = POLLIN;
    sqe->user_data = encodeUd(wakeFd_, kKindPoll, 0);  // gen 0 = wake poll
  }

  // Publish prepped SQEs. On the loop thread submission is LAZY by
  // default — the whole dispatch batch's SQEs ride the single
  // io_uring_enter that also waits for the next completions. Any other
  // thread must enter immediately (the doorbell that starts the I/O).
  void flushLocked(bool force = false) {
    if (pending_ == 0) {
      return;
    }
    __atomic_store_n(sqTail_, sqTailLocal_, __ATOMIC_RELEASE);
    if (!force && onLoopThread()) {
      return;  // run() submits with its wait-enter
    }
    const unsigned n = pending_;
    pending_ = 0;
    enterSubmit(n);
  }

  void enterSubmit(unsigned n) {
    // mu_ held (ALL CQ consumption happens under mu_, so draining here
    // is safe from any thread). EBUSY = CQ saturated (FEAT_NODROP
    // backlog): free CQ space into the spill queue — yielding alone
    // would deadlock on the loop thread (sole dispatcher waiting on
    // itself) and stall other threads against a blocked loop.
    bool spilled = false;
    while (n > 0) {
      statEnters_.fetch_add(1, std::memory_order_relaxed);
      int rv = sysIoUringEnter(ringFd_, n, 0, 0);
      if (rv >= 0) {
        statSqes_.fetch_add(std::min(n, unsigned(rv)),
                            std::memory_order_relaxed);
        // Partial submission is possible (e.g. CQ filled mid-batch):
        // keep going until every prepped SQE is consumed — dropping one
        // loses an I/O forever.
        n -= std::min(n, unsigned(rv));
        continue;
      }
      if (errno == EINTR) {
        continue;
      }
      if (errno == EBUSY) {
        if (drainCqLocked() == 0) {
          std::this_thread::yield();
        } else {
          spilled = true;
        }
        continue;
      }
      TC_THROW(EnforceError, "io_uring_enter(submit): ", strerror(errno));
    }
    if (spilled && !onLoopThread()) {
      wake();  // the loop may be blocked in GETEVENTS on a CQ we emptied
    }
  }

  // --- CQ consumption ---

  // Drain available CQEs into the dispatch queue; returns how many.
  // mu_ held — the queue (not a thread-local batch) is THE holding area
  // for undispatched completions, so del() can always find an op's
  // terminal completion no matter which thread drained it.
  unsigned drainCqLocked() {
    unsigned head = *cqHead_;
    const unsigned tail = __atomic_load_n(cqTail_, __ATOMIC_ACQUIRE);
    unsigned n = 0;
    for (; head != tail; head++, n++) {
      const io_uring_cqe& cqe = cqes_[head & cqMask_];
      dispatchQ_.push_back({cqe.user_data, cqe.res});
    }
    __atomic_store_n(cqHead_, head, __ATOMIC_RELEASE);
    statCqes_.fetch_add(n, std::memory_order_relaxed);
    return n;
  }

  // del() on the loop thread: consume CQEs inline until fd's data ops
  // have terminally completed; everything else spills to the next batch.
  void drainFdOpsOnLoopThread(std::unique_lock<std::mutex>& lock, int fd) {
    for (;;) {
      Reg& reg = regs_.at(fd);
      if (!reg.recvOut && !reg.sendOut) {
        break;
      }
      // This fd's terminal completions may ALREADY sit in the dispatch
      // queue — drained but not yet dispatched (this thread IS the
      // dispatcher, and it is here, inside a handler). Waiting for a
      // fresh CQE while the needed one sits queued would block forever.
      // Consume ours from the queue first; only then wait for new ones.
      bool found = false;
      for (auto it = dispatchQ_.begin(); it != dispatchQ_.end();) {
        if (it->ud != kRemoveUd && udFd(it->ud) == fd &&
            udKind(it->ud) != kKindPoll && udGen(it->ud) == reg.gen) {
          clearOutstandingLocked(reg, udKind(it->ud));
          it = dispatchQ_.erase(it);
          found = true;
        } else {
          ++it;
        }
      }
      if (found) {
        continue;
      }
      if (drainCqLocked() == 0) {
        lock.unlock();
        statEnters_.fetch_add(1, std::memory_order_relaxed);
        int rv = sysIoUringEnter(ringFd_, 0, 1, IORING_ENTER_GETEVENTS);
        if (rv < 0 && errno != EINTR && errno != EBUSY) {
          TC_ERROR("io_uring_enter(del wait): ", strerror(errno));
        }
        lock.lock();
      }
    }
  }

  void clearOutstandingLocked(Reg& reg, UdKind kind) {
    if (kind == kKindRecv) {
      reg.recvOut = false;
    } else if (kind == kKindSend) {
      reg.sendOut = false;
    }
    dataCv_.notify_all();
  }

  void run() override {
    bool dispatched = false;
    // Relaxed: exit flag; the wake eventfd write makes the loop
    // re-check, and join is the real synchronization point.
    while (!stop_.load(std::memory_order_relaxed)) {
      Completion c{};
      bool have = false;
      {
        std::lock_guard<std::mutex> guard(mu_);
        if (dispatchQ_.empty()) {
          drainCqLocked();
        }
        if (!dispatchQ_.empty()) {
          c = dispatchQ_.front();
          dispatchQ_.pop_front();
          have = true;
        }
      }

      if (!have) {
        if (dispatched) {
          dispatched = false;
          endOfBatch();
          continue;  // the batch may have deferred work producing CQEs
        }
        if (busyPoll_) {
          // Spinning: publish + submit any lazily-prepped SQEs first.
          {
            std::lock_guard<std::mutex> guard(mu_);
            flushLocked(/*force=*/true);
          }
#if defined(__x86_64__) || defined(__i386__)
          __builtin_ia32_pause();
#endif
          // Same contract as EpollLoop: barrier()/defer() write the wake
          // eventfd first, so skipping endOfBatch() on empty spins cannot
          // strand a waiter.
          std::this_thread::yield();
          continue;
        }
        // THE steady-state syscall: one enter submits the entire batch
        // of prepped SQEs and waits for the next completion.
        unsigned n = 0;
        {
          std::lock_guard<std::mutex> guard(mu_);
          if (pending_ > 0) {
            __atomic_store_n(sqTail_, sqTailLocal_, __ATOMIC_RELEASE);
            n = pending_;
            pending_ = 0;
          }
        }
        statEnters_.fetch_add(1, std::memory_order_relaxed);
        int rv = sysIoUringEnter(ringFd_, n, 1, IORING_ENTER_GETEVENTS);
        if (rv >= 0) {
          statSqes_.fetch_add(std::min(n, unsigned(rv)),
                              std::memory_order_relaxed);
          n -= std::min(n, unsigned(rv));
        }
        if (n > 0) {
          // EBUSY/EINTR/partial consumption left unsubmitted SQEs in the
          // ring; push them through or the I/Os they carry never start.
          std::lock_guard<std::mutex> guard(mu_);
          enterSubmit(n);
        }
        if (rv < 0 && errno != EINTR && errno != EBUSY) {
          TC_ERROR("io_uring_enter(wait): ", strerror(errno));
        }
        continue;  // re-drain
      }

      dispatched = true;
      if (c.ud == kRemoveUd) {
        continue;  // POLL_REMOVE / ASYNC_CANCEL ack
      }
      const int fd = udFd(c.ud);
      const UdKind kind = udKind(c.ud);
      const uint32_t gen = udGen(c.ud);
      if (fd == wakeFd_ && kind == kKindPoll && gen == 0) {
        uint64_t drain;
        while (read(wakeFd_, &drain, sizeof(drain)) > 0) {
        }
        std::lock_guard<std::mutex> guard(mu_);
        armWakeLocked();
        continue;
      }

      if (kind != kKindPoll) {
        // Data-path completion.
        Handler* handler = nullptr;
        {
          std::lock_guard<std::mutex> guard(mu_);
          auto it = regs_.find(fd);
          if (it == regs_.end() || it->second.gen != gen) {
            continue;  // stale: removed or re-registered since
          }
          clearOutstandingLocked(it->second, kind);
          if (it->second.dying) {
            continue;  // del() in progress; it owns the wind-down
          }
          handler = it->second.handler;
        }
        try {
          handler->handleIoComplete(kind == kKindRecv, c.res);
        } catch (const std::exception& e) {
          TC_ERROR("unhandled exception on uring loop thread: ", e.what());
        }
        continue;
      }

      Handler* handler = nullptr;
      {
        std::lock_guard<std::mutex> guard(mu_);
        auto it = regs_.find(fd);
        if (it == regs_.end() || it->second.gen != gen) {
          continue;  // stale: removed or re-registered since
        }
        it->second.armed = false;
        handler = it->second.handler;
      }
      // Same-generation ECANCELED should not happen (mod() bumps the
      // generation before cancelling), but if it does, skip the dispatch
      // and fall through to the re-arm so the fd cannot go silent.
      if (c.res != -ECANCELED) {
        const uint32_t events =
            c.res > 0 ? uint32_t(c.res) : uint32_t(EPOLLERR);
        try {
          handler->handleEvents(events);
        } catch (const std::exception& e) {
          // Same contract as EpollLoop: handlers own expected failures.
          TC_ERROR("unhandled exception on uring loop thread: ", e.what());
        }
      }
      // Oneshot re-arm AFTER dispatch: POLL_ADD reports current
      // readiness immediately, so un-drained data (read budget) fires
      // again right away — level-triggered semantics.
      {
        std::lock_guard<std::mutex> guard(mu_);
        auto it = regs_.find(fd);
        if (it != regs_.end() && it->second.gen == gen &&
            !it->second.armed && !it->second.dataMode) {
          it->second.armed = true;
          armLocked(fd, it->second);
          flushLocked();
        }
      }
    }
  }

  int ringFd_{-1};
  void* sqPtr_{nullptr};
  void* cqPtr_{nullptr};
  size_t sqLen_{0}, cqLen_{0}, sqeLen_{0};
  io_uring_sqe* sqes_{nullptr};
  unsigned* sqHead_{nullptr};
  unsigned* sqTail_{nullptr};
  unsigned sqMask_{0};
  unsigned* sqArray_{nullptr};
  unsigned* cqHead_{nullptr};
  unsigned* cqTail_{nullptr};
  unsigned cqMask_{0};
  io_uring_cqe* cqes_{nullptr};

  unsigned sqTailLocal_{0};  // mu_ held for writes
  unsigned pending_{0};
  std::unordered_map<int, Reg> regs_;
  std::deque<Completion> dispatchQ_;  // drained, undispatched; mu_ held
  std::condition_variable dataCv_;  // del() waits for data-op drains
  uint32_t nextGen_{1};  // gen 0 is reserved for the wake poll

  // engineStats() counters; relaxed — observability only.
  std::atomic<uint64_t> statEnters_{0};
  std::atomic<uint64_t> statSqes_{0};
  std::atomic<uint64_t> statCqes_{0};
};

bool uringAvailable() {
  static const bool ok = [] {
    io_uring_params p;
    std::memset(&p, 0, sizeof(p));
    int fd = sysIoUringSetup(2, &p);
    if (fd < 0) {
      return false;
    }
    const bool nodrop = (p.features & IORING_FEAT_NODROP) != 0;
    ::close(fd);
    return nodrop;
  }();
  return ok;
}

std::unique_ptr<Loop> makeUringLoop(bool busyPoll) {
  return std::make_unique<UringLoop>(busyPoll);
}

}  // namespace transport
}  // namespace tpucoll
