// Wire protocol for the tpucoll host (DCN/TCP) data plane.
//
// Original "eager + stash" design: a message is a fixed header followed
// immediately by its payload. The receiver matches the (source, slot) against
// posted receives and either lands the payload directly in user memory or
// stashes it until a matching receive is posted. This replaces the
// reference's four-opcode notify/ready handshake (gloo/transport/tcp/
// pair.h:53-83) with a single-opcode protocol: one fewer round trip per
// message, at the cost of bounded receiver-side staging for early arrivals —
// the right trade for collective schedules that keep only a few segments in
// flight.
#pragma once

#include <cstdint>
#include <cstring>
#include <string>

#include "tpucoll/common/crypto.h"

namespace tpucoll {
namespace transport {

constexpr uint32_t kMsgMagic = 0x7C011001;
constexpr uint32_t kHelloMagic = 0x7C011002;
// PSK-authenticated hello: the 16-byte hello with this magic is followed
// by a mutual HMAC-SHA256 challenge/response —
//   initiator: nonceI[16]
//   listener:  nonceL[16] || HMAC(key, "srv" || pairId || nonceI || nonceL)
//   initiator: HMAC(key, "cli" || pairId || nonceI || nonceL)
// Either side drops the connection on a tag mismatch, so only holders of
// the pre-shared key can join the mesh. NOTE: this magic provides JOIN
// AUTHENTICATION ONLY — post-handshake traffic is plaintext with no
// integrity protection. Untrusted networks want kHelloAuthEncMagic.
constexpr uint32_t kHelloAuthMagic = 0x7C011003;
// Same handshake, then the connection switches to encrypted framing (the
// reference TLS tier's confidentiality+integrity, gloo/transport/tcp/
// tls/pair.cc): per-connection ChaCha20-Poly1305 keys derived via
// HKDF-SHA256 from the PSK and the handshake transcript. Every wire
// message becomes sealed(header)+tag, then sealed(payload)+tag when a
// payload follows; each seal consumes one per-direction sequence number
// (the AEAD nonce), so reordering/replay/tampering all fail the tag and
// poison the pair with an IoException.
constexpr uint32_t kHelloAuthEncMagic = 0x7C011004;
// Per-rank identity tier (common/keyring.h): the same mutual
// challenge/response, but keyed with the PAIRWISE key K[a,b] that only
// ranks a and b hold, so a leaked keyring impersonates one rank, not
// the fleet (the reference's per-process TLS identity property,
// gloo/transport/tcp/tls/context.h:25-42). The 16-byte hello is
// followed by le32(initiatorRank) before the nonce exchange; BOTH
// ranks enter the transcript, and the listener additionally enforces
// at routing time that the authenticated rank matches the rank the
// expecting pair was built for — possession of K[a,b] lets you speak
// only as a to b and b to a.
constexpr uint32_t kHelloRingMagic = 0x7C011008;
constexpr uint32_t kHelloRingEncMagic = 0x7C011009;

constexpr size_t kAuthNonceBytes = 16;
constexpr size_t kAuthMacBytes = 32;
// Encrypted payloads are sealed in frames of at most this many plaintext
// bytes (each frame = ciphertext + 16-byte tag, one sequence number): it
// bounds the sender's staging buffer, pipelines sealing with the socket
// writes, and lets the receiver verify/deliver progressively. Both sides
// derive the frame walk from the header's nbytes, so the size is part of
// the wire protocol.
constexpr size_t kEncFrameBytes = 256 * 1024;

// Per-connection directional AEAD keys (encrypted == false for plaintext
// connections; tx/rx then unused).
struct ConnKeys {
  bool encrypted{false};
  AeadKey tx{};
  AeadKey rx{};
};

// Derive the two directional keys from the PSK and the full handshake
// transcript (pairId and both nonces), so a replayed transcript or a
// different pair yields different keys.
inline ConnKeys deriveConnKeys(const std::string& psk, uint64_t pairId,
                               const uint8_t* nonceI, const uint8_t* nonceL,
                               bool initiator) {
  ConnKeys keys;
  keys.encrypted = true;
  uint8_t salt[sizeof(pairId) + 2 * kAuthNonceBytes];
  std::memcpy(salt, &pairId, sizeof(pairId));
  std::memcpy(salt + sizeof(pairId), nonceI, kAuthNonceBytes);
  std::memcpy(salt + sizeof(pairId) + kAuthNonceBytes, nonceL,
              kAuthNonceBytes);
  uint8_t okm[2 * kAeadKeyBytes];
  static constexpr char kInfo[] = "tpucoll-wire-v1";
  hkdfSha256(psk.data(), psk.size(), salt, sizeof(salt), kInfo,
             sizeof(kInfo) - 1, okm, sizeof(okm));
  // okm[0:32] keys initiator->listener, okm[32:64] listener->initiator.
  std::memcpy((initiator ? keys.tx : keys.rx).bytes, okm, kAeadKeyBytes);
  std::memcpy((initiator ? keys.rx : keys.tx).bytes, okm + kAeadKeyBytes,
              kAeadKeyBytes);
  return keys;
}

enum class Opcode : uint8_t {
  kData = 1,
  // Announces an orderly departure. Sent by close() before the write side is
  // shut down; a peer that sees EOF *without* a preceding goodbye knows the
  // remote died unexpectedly (fast failure detection), while EOF after
  // goodbye is a clean group teardown. The goodbye/half-close/drain dance
  // also guarantees no in-flight payload is lost to a TCP reset when ranks
  // finish a collective at different times.
  kGoodbye = 2,
  // One-sided write into a registered region (reference capability:
  // transport/unbound_buffer.h:134-141 put over ibverbs RDMA_WRITE).
  // slot = region token, aux = remote offset; the payload lands directly
  // in the target's registered memory with NO posted receive and no
  // target-side completion — bounds are validated against the
  // registration and violations poison the pair.
  kPut = 3,
  // One-sided read request (reference: unbound_buffer.h:143-152 get over
  // RDMA_READ). slot = the requester's response slot; the 24-byte payload
  // is {u64 token, u64 roffset, u64 nbytes}. The target responds with a
  // normal kData message carrying region bytes on the response slot, so
  // the response rides the ordinary matching path.
  kGetReq = 4,
  // ---- shared-memory payload plane (shm.h; same-host pairs only) ----
  // These opcodes carry NO socket payload: the payload bytes move through
  // the pair's shared-memory ring, and the TCP stream carries only the
  // framing — so ordering, matching, timeouts, and failure detection are
  // exactly the TCP protocol's. On encrypted connections the headers are
  // sealed as usual while ring bytes stay plaintext: the ring never
  // crosses the network and the segment is a 0600 same-user mapping, so
  // the wire threat model (on-path attacker) does not reach it.
  //
  // Announces a message whose payload will arrive through the ring.
  // Fields exactly as kData (slot, nbytes = total payload bytes); for
  // kShmPut as kPut (slot = region token, aux = remote offset, flags
  // kPutFlagNotify). Chunk announcements follow contiguously (the sender's
  // FIFO guarantees no other data-bearing message interleaves).
  kShmData = 5,
  kShmPut = 6,
  // Announces that nbytes MORE payload bytes of the current shm message
  // are in the ring (written before this header was sent, so they are
  // visible to the receiver by the time it reads the header).
  kShmChunk = 7,
  // Flow control. kShmCreditReq: the sender's ring is full and it has
  // nothing in flight to piggyback on; the receiver — which by FIFO has
  // consumed every previously announced chunk by the time it reads this —
  // replies kShmCredit. kShmCredit: pure wakeup, also sent eagerly after
  // consuming a large chunk so the sender refills while the receiver
  // drains (pipelining). Both are idempotent and carry no ordering
  // semantics, which is why they alone may preempt the tx queue at
  // message boundaries.
  kShmCreditReq = 8,
  kShmCredit = 9,
  // ---- multi-channel striping (TPUCOLL_CHANNELS > 1 only) ----
  // One contiguous stripe of a large kData message, carried on data
  // channel `reserved[0]` of the logical pair. Striping is fully
  // self-describing so the receiver needs no out-of-band agreement:
  //   slot        = the message's slot (as kData)
  //   nbytes      = THIS stripe's payload bytes (drives rx framing,
  //                 incl. the encrypted frame walk — and must equal
  //                 stripeSpan(aux, reserved[1], reserved[0]))
  //   aux         = TOTAL message bytes (what receive matching uses)
  //   reserved[0] = stripe/channel index, reserved[1] = stripe count
  //   flags       = low 8 bits of the sender's per-pair stripe sequence
  //                 (all stripes of one message carry the same value;
  //                 disambiguates back-to-back same-slot messages during
  //                 reassembly)
  // The split is deterministic — derived from byte counts alone
  // (stripeSpan/stripeOffset below), never from runtime state — so two
  // runs stripe identically and the fault plane stays reproducible.
  // A striped message completes (receive matching, waitRecv, flight-
  // recorder completion) only when every stripe has landed; transport
  // progress of ANY stripe counts as the op having started.
  kStripe = 10,
};

// Upper bound on data channels per logical pair (TPUCOLL_CHANNELS):
// stripe count/index travel in one-byte header fields and reassembly
// tracks arrival in a 32-bit mask, but the practical ceiling is NIC
// queues x cores, not the encoding.
constexpr uint32_t kMaxStripeChannels = 8;

// Deterministic contiguous stripe split: stripe `idx` of a `total`-byte
// message over `count` channels. Balanced to within one byte; every
// stripe is non-empty whenever total >= count (the stripe threshold is
// far above any sane channel count).
inline uint64_t stripeSpan(uint64_t total, uint32_t count, uint32_t idx) {
  const uint64_t base = total / count;
  const uint64_t rem = total % count;
  return base + (idx < rem ? 1 : 0);
}
inline uint64_t stripeOffset(uint64_t total, uint32_t count, uint32_t idx) {
  const uint64_t base = total / count;
  const uint64_t rem = total % count;
  return idx * base + (idx < rem ? idx : rem);
}

// WireHello.reserved bits.
constexpr uint32_t kHelloFlagShmOffer = 1;  // shm offer follows handshake

// The shm offer the initiator sends after the (possibly authenticated)
// handshake: {this struct}{name bytes}. The listener replies one byte,
// kShmAccept or kShmReject; on reject both sides fall back to TCP
// payloads. A tampered or corrupted offer can only cause a reject or an
// open() failure — never a wrong mapping (segments are stamped with the
// pairId and found by unguessable random name).
#pragma pack(push, 1)
struct WireShmOffer {
  uint32_t magic;  // kShmOfferMagic
  uint32_t nameLen;
  uint64_t ringBytes;
};
#pragma pack(pop)
constexpr uint32_t kShmOfferMagic = 0x7C011007;
constexpr uint8_t kShmAccept = 1;
constexpr uint8_t kShmReject = 0;
static_assert(sizeof(WireShmOffer) == 16, "shm offer must be packed");

// WireHeader.flags bits (valid for kPut):
//   bit 0: notify — complete a waitRecv on the target's exporting buffer
//   when the payload lands (the reference's BOUND-buffer contract:
//   one-sided write into pre-registered memory with an arrival
//   notification, gloo/transport/buffer.h:16-41 waitRecv).
constexpr uint8_t kPutFlagNotify = 1;

#pragma pack(push, 1)
struct WireHeader {
  uint32_t magic;
  uint8_t opcode;
  uint8_t flags;
  uint8_t reserved[2];
  uint64_t slot;
  uint64_t nbytes;
  uint64_t aux;  // kPut: remote offset; others: 0
};

// Payload of a kGetReq message.
struct WireGetReq {
  uint64_t token;
  uint64_t roffset;
  uint64_t nbytes;
};

// Serialized RemoteKey: the byte-exchangeable descriptor of a registered
// region (reference: transport/remote_key.h:8-18 {rank, size} plus the
// transport-specific addressing — here a per-context token).
struct WireRemoteKey {
  uint32_t magic;  // kRemoteKeyMagic
  int32_t rank;
  uint64_t token;
  uint64_t size;
};
constexpr uint32_t kRemoteKeyMagic = 0x7C011005;

// First bytes an initiator writes after TCP connect: routes the fresh
// connection to the listener-side Pair expecting it.
struct WireHello {
  uint32_t magic;
  uint32_t reserved;
  uint64_t pairId;
};
#pragma pack(pop)

static_assert(sizeof(WireHeader) == 32, "wire header must be packed");
static_assert(sizeof(WireHello) == 16, "wire hello must be packed");
static_assert(sizeof(WireGetReq) == 24, "get request must be packed");
static_assert(sizeof(WireRemoteKey) == 24, "remote key must be packed");

}  // namespace transport
}  // namespace tpucoll
