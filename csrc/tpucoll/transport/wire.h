// Wire protocol for the tpucoll host (DCN/TCP) data plane.
//
// Original "eager + stash" design: a message is a fixed header followed
// immediately by its payload. The receiver matches the (source, slot) against
// posted receives and either lands the payload directly in user memory or
// stashes it until a matching receive is posted. This replaces the
// reference's four-opcode notify/ready handshake (gloo/transport/tcp/
// pair.h:53-83) with a single-opcode protocol: one fewer round trip per
// message, at the cost of bounded receiver-side staging for early arrivals —
// the right trade for collective schedules that keep only a few segments in
// flight.
#pragma once

#include <cstdint>

namespace tpucoll {
namespace transport {

constexpr uint32_t kMsgMagic = 0x7C011001;
constexpr uint32_t kHelloMagic = 0x7C011002;
// PSK-authenticated hello (the TLS-tier analog): the 16-byte hello with
// this magic is followed by a mutual HMAC-SHA256 challenge/response —
//   initiator: nonceI[16]
//   listener:  nonceL[16] || HMAC(key, "srv" || pairId || nonceI || nonceL)
//   initiator: HMAC(key, "cli" || pairId || nonceI || nonceL)
// Either side drops the connection on a tag mismatch, so only holders of
// the pre-shared key can join the mesh.
constexpr uint32_t kHelloAuthMagic = 0x7C011003;

constexpr size_t kAuthNonceBytes = 16;
constexpr size_t kAuthMacBytes = 32;

enum class Opcode : uint8_t {
  kData = 1,
  // Announces an orderly departure. Sent by close() before the write side is
  // shut down; a peer that sees EOF *without* a preceding goodbye knows the
  // remote died unexpectedly (fast failure detection), while EOF after
  // goodbye is a clean group teardown. The goodbye/half-close/drain dance
  // also guarantees no in-flight payload is lost to a TCP reset when ranks
  // finish a collective at different times.
  kGoodbye = 2,
};

#pragma pack(push, 1)
struct WireHeader {
  uint32_t magic;
  uint8_t opcode;
  uint8_t reserved[3];
  uint64_t slot;
  uint64_t nbytes;
};

// First bytes an initiator writes after TCP connect: routes the fresh
// connection to the listener-side Pair expecting it.
struct WireHello {
  uint32_t magic;
  uint32_t reserved;
  uint64_t pairId;
};
#pragma pack(pop)

static_assert(sizeof(WireHeader) == 24, "wire header must be packed");
static_assert(sizeof(WireHello) == 16, "wire hello must be packed");

}  // namespace transport
}  // namespace tpucoll
