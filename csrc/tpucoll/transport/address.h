// Transport addresses: a resolved socket address that can be serialized into
// a rendezvous store and reconstructed by peers (reference contract:
// gloo/transport/address.h + gloo/transport/tcp/address.h:25-58; here the
// pair-routing id travels separately in the rank blob, not in the address).
#pragma once

#include <netinet/in.h>
#include <sys/socket.h>

#include <cstdint>
#include <string>
#include <vector>

namespace tpucoll {
namespace transport {

struct SockAddr {
  sockaddr_storage ss{};
  socklen_t len{0};

  std::string str() const;

  std::vector<uint8_t> serialize() const;
  static SockAddr deserialize(const uint8_t* data, size_t size);

  const sockaddr* sa() const {
    return reinterpret_cast<const sockaddr*>(&ss);
  }
  sockaddr* sa() { return reinterpret_cast<sockaddr*>(&ss); }
};

// Resolve hostname (or dotted quad) to a bindable/connectable address with
// the given port (0 = ephemeral).
SockAddr resolve(const std::string& hostname, uint16_t port);

}  // namespace transport
}  // namespace tpucoll
