#include "tpucoll/transport/socket.h"

#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>

#include <cerrno>
#include <cstring>

#include "tpucoll/common/logging.h"

namespace tpucoll {
namespace transport {

void setNonBlocking(int fd) {
  int flags = fcntl(fd, F_GETFL);
  TC_ENFORCE_GE(flags, 0, "fcntl(F_GETFL): ", strerror(errno));
  TC_ENFORCE_EQ(fcntl(fd, F_SETFL, flags | O_NONBLOCK), 0,
                "fcntl(F_SETFL): ", strerror(errno));
}

// Socket tuning is best-effort (a refused option is not fatal), but a
// silently un-tuned socket shows up only as mysterious throughput or
// latency loss — warn so debug output names the failed option.
void setNoDelay(int fd) {
  int on = 1;
  if (setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &on, sizeof(on)) != 0) {
    TC_WARN("setsockopt(TCP_NODELAY) failed on fd ", fd, ": ",
            strerror(errno));
  }
}

void setReuseAddr(int fd) {
  int on = 1;
  if (setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &on, sizeof(on)) != 0) {
    TC_WARN("setsockopt(SO_REUSEADDR) failed on fd ", fd, ": ",
            strerror(errno));
  }
}

void setBufferSizes(int fd, int bytes) {
  if (setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &bytes, sizeof(bytes)) != 0) {
    TC_WARN("setsockopt(SO_SNDBUF, ", bytes, ") failed on fd ", fd, ": ",
            strerror(errno));
  }
  if (setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bytes, sizeof(bytes)) != 0) {
    TC_WARN("setsockopt(SO_RCVBUF, ", bytes, ") failed on fd ", fd, ": ",
            strerror(errno));
  }
}

std::string errnoString(const char* what) {
  return std::string(what) + ": " + strerror(errno);
}

}  // namespace transport
}  // namespace tpucoll
