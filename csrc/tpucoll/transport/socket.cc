#include "tpucoll/transport/socket.h"

#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>

#include <cerrno>
#include <cstring>

#include "tpucoll/common/logging.h"

namespace tpucoll {
namespace transport {

void setNonBlocking(int fd) {
  int flags = fcntl(fd, F_GETFL);
  TC_ENFORCE_GE(flags, 0, "fcntl(F_GETFL): ", strerror(errno));
  TC_ENFORCE_EQ(fcntl(fd, F_SETFL, flags | O_NONBLOCK), 0,
                "fcntl(F_SETFL): ", strerror(errno));
}

void setNoDelay(int fd) {
  int on = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &on, sizeof(on));
}

void setReuseAddr(int fd) {
  int on = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &on, sizeof(on));
}

void setBufferSizes(int fd, int bytes) {
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &bytes, sizeof(bytes));
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bytes, sizeof(bytes));
}

std::string errnoString(const char* what) {
  return std::string(what) + ": " + strerror(errno);
}

}  // namespace transport
}  // namespace tpucoll
