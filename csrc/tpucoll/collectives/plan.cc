#include "tpucoll/collectives/plan.h"

#include <exception>

#include "tpucoll/common/env.h"
#include "tpucoll/common/metrics.h"

namespace tpucoll {
namespace plan {

transport::UnboundBuffer* Plan::userBuf(size_t idx, void* ptr,
                                        size_t nbytes) {
  if (users_.size() <= idx) {
    users_.resize(idx + 1);
  }
  UserSlot& slot = users_[idx];
  const uintptr_t p = reinterpret_cast<uintptr_t>(ptr);
  if (slot.buf != nullptr && slot.ptr == p && slot.nbytes == nbytes) {
    return slot.buf.get();
  }
  // Drop the stale registration BEFORE creating the replacement so its
  // cancel+drain pass can never see the new buffer's pending ops.
  slot.buf.reset();
  slot.buf = ctx_->createUnboundBuffer(ptr, nbytes);
  slot.ptr = p;
  slot.nbytes = nbytes;
  return slot.buf.get();
}

char* Plan::scratch(size_t idx, size_t minBytes) {
  return scratch(idx, minBytes, nullptr);
}

char* Plan::scratch(size_t idx, size_t minBytes, bool* fresh) {
  if (stages_.size() <= idx) {
    stages_.resize(idx + 1);
  }
  StageSlot& slot = stages_[idx];
  if (cached_) {
    char* data = slot.arena.require(minBytes);
    if (slot.arena.grewOnLastRequire()) {
      slot.buf.reset();  // any registration points at the old block
    }
    if (fresh != nullptr) {
      *fresh = slot.arena.grewOnLastRequire();
    }
    return data;
  }
  // Transient: the Context scratch pool (warm pages across calls, the
  // pre-plan behavior), one acquisition per call per slot.
  if (!slot.pooled.has_value() || slot.pooled->size() < minBytes) {
    slot.buf.reset();
    slot.pooled.emplace(ctx_->acquireScratch(minBytes));
  }
  if (fresh != nullptr) {
    *fresh = true;  // pool pages rotate between calls: never trust them
  }
  return slot.pooled->data();
}

Plan::Stage Plan::stage(size_t idx, size_t minBytes) {
  char* data = scratch(idx, minBytes);
  StageSlot& slot = stages_[idx];
  if (slot.buf == nullptr) {
    slot.buf = ctx_->createUnboundBuffer(
        data, cached_ ? slot.arena.capacity() : slot.pooled->size());
  }
  return Stage{data, slot.buf.get()};
}

const std::vector<collectives_detail::SegSpan>& Plan::segments(
    size_t blockBytes, size_t elsize) {
  // elsize is constant for a plan (it is derived from the key's dtype),
  // so blockBytes alone keys the memo.
  for (const auto& entry : segs_) {
    if (entry.first == blockBytes) {
      return entry.second;
    }
  }
  segs_.emplace_back(blockBytes,
                     collectives_detail::segmentize(blockBytes, elsize));
  return segs_.back().second;
}

PlanCache::PlanCache(Context* ctx)
    : ctx_(ctx),
      // Read per-cache (not function-static): bench.py's A/B arms and
      // the tests toggle the knobs between Context constructions.
      enabled_(envFlag("TPUCOLL_PLAN_CACHE", true)),
      capacity_(static_cast<size_t>(
          envCount("TPUCOLL_PLAN_LRU", 64, 1, 1 << 20))) {}

std::shared_ptr<Plan> PlanCache::acquire(const PlanKey& key) {
  if (!enabled_) {
    return nullptr;
  }
  Metrics& metrics = ctx_->metrics();
  // Evicted entries destroy OUTSIDE mu_ (after this scope): ~Plan runs
  // ~UnboundBuffer, which takes transport mutexes and can block on a
  // drain — a concurrent acquire on another thread must not wait on
  // that. Same discipline as clear().
  Lru dropped;
  std::shared_ptr<Plan> plan;
  {
    std::lock_guard<std::mutex> guard(mu_);
    auto it = map_.find(key);
    if (it != map_.end()) {
      plan = it->second->plan;
      bool expected = false;
      if (!plan->inUse_.compare_exchange_strong(
              // Acquire on success: the previous release's writes to
              // the plan's slots must be visible to this call.
              expected, true, std::memory_order_acquire,
              std::memory_order_relaxed)) {
        // Same-key concurrency (an API-contract violation upstream):
        // degrade to a transient plan rather than sharing live buffers.
        return nullptr;
      }
      lru_.splice(lru_.begin(), lru_, it->second);
      metrics.recordPlanHit();
      return plan;
    }
    plan = std::make_shared<Plan>(ctx_, /*cached=*/true);
    plan->key_ = key;
    // Relaxed: the plan is not yet visible to any other thread.
    plan->inUse_.store(true, std::memory_order_relaxed);
    lru_.push_front(Entry{key, plan});
    map_[key] = lru_.begin();
    metrics.recordPlanMiss();
    // Evict past capacity, oldest first, skipping in-use entries
    // (their callers hold live buffers; they die on release instead).
    uint64_t evicted = 0;
    auto tail = lru_.end();
    while (map_.size() > capacity_ && tail != lru_.begin()) {
      --tail;
      // Relaxed: a stale "in use" read just defers this eviction.
      if (tail->plan->inUse_.load(std::memory_order_relaxed)) {
        continue;
      }
      map_.erase(tail->key);
      dropped.splice(dropped.begin(), lru_, tail);
      tail = lru_.end();
      // Restart the walk: splice invalidated the erased position's
      // neighborhood bookkeeping; the list is tiny (capacity_+1).
      evicted++;
    }
    if (evicted > 0) {
      metrics.recordPlanEvictions(evicted);
    }
  }
  return plan;
}

void PlanCache::release(const std::shared_ptr<Plan>& plan, bool poisoned) {
  if (plan == nullptr) {
    return;
  }
  if (poisoned) {
    std::lock_guard<std::mutex> guard(mu_);
    auto it = map_.find(plan->key_);
    // Guard against the entry having been cleared/evicted and the key
    // reused by a FRESH plan while this call was in flight.
    if (it != map_.end() && it->second->plan == plan) {
      lru_.erase(it->second);
      map_.erase(it);
    }
  }
  // Release: publish this call's slot writes to the next acquirer.
  plan->inUse_.store(false, std::memory_order_release);
  // If the entry was dropped (poison, clear, eviction) the caller's
  // shared_ptr is the last ref; the Plan's buffers drain in ~Plan.
}

void PlanCache::clear() {
  Lru dropped;
  {
    std::lock_guard<std::mutex> guard(mu_);
    map_.clear();
    dropped.swap(lru_);
  }
  // Entries destroy OUTSIDE the lock: ~UnboundBuffer takes transport
  // mutexes and can block draining in-flight ops.
}

size_t PlanCache::size() const {
  std::lock_guard<std::mutex> guard(mu_);
  return map_.size();
}

PlanHandle::PlanHandle(Context* ctx, const PlanKey& key) {
  PlanCache& cache = ctx->planCache();
  plan_ = cache.acquire(key);
  if (plan_ != nullptr) {
    cache_ = &cache;
    exceptionsAtEntry_ = std::uncaught_exceptions();
  } else {
    plan_ = std::make_shared<Plan>(ctx, /*cached=*/false);
  }
}

PlanHandle::~PlanHandle() {
  if (cache_ != nullptr) {
    // Baseline comparison (not a plain >0 check): a collective issued
    // from a destructor during unwinding must not poison its plan.
    const bool poisoned =
        std::uncaught_exceptions() > exceptionsAtEntry_;
    cache_->release(plan_, poisoned);
  }
}

}  // namespace plan
}  // namespace tpucoll
