// Recursive halving-doubling allreduce (Rabenseifner's algorithm over
// block windows).
//
// Reduce-scatter by recursive vector halving: at each round a rank and its
// partner (rank XOR mask, mask from P/2 down to 1) exchange complementary
// halves of the current block window and reduce the half they keep. After
// log2(P) rounds each rank's window is exactly its own block, fully
// reduced — the window bookkeeping lands block r on rank r directly, with
// no bit-reversal pass (contrast reference reduce_scatter.h:21-329).
// Allgather by recursive doubling reverses the walk, windows merging with
// their siblings until every rank holds the full vector.
//
// Non-power-of-2 group sizes use a binary-blocks decomposition (behavior
// parity with gloo/allreduce_halving_doubling.h:39-64 initBinaryBlocks,
// re-derived for this build's in-order window walk): P is split into
// power-of-2 blocks by its binary representation, larger blocks at lower
// ranks. Each block reduce-scatters internally over the full vector, then
// partial windows flow up the block chain smallest -> largest (each rank's
// inter-block traffic is proportional to its window, unlike the fold,
// where 2*rem ranks exchange the whole vector twice). The fully reduced
// windows flow back down the chain, and each block allgathers internally.
// The fold path is kept as TPUCOLL_HD_NP2=fold for small payloads where
// its fewer messages can win.
#include <cstdlib>
#include <cstring>

#include "tpucoll/collectives/algorithms.h"
#include "tpucoll/collectives/detail.h"
#include "tpucoll/collectives/plan.h"
#include "tpucoll/common/profile.h"
#include "tpucoll/tuning/dispatch.h"

namespace tpucoll {
namespace algorithms {

using collectives_detail::Blocks;
using collectives_detail::evenBlocks;
using collectives_detail::largestPow2AtMost;
using collectives_detail::fuseRecvReduce;
using plan::LazyStage;
using profile::Phase;
using profile::PhaseScope;

namespace {

// Slot-delta bases keep every phase's tags disjoint (Slot::offset is
// bounds-checked against the 24-bit delta field, types.h).
constexpr uint64_t kRsBase = 0x1000;
constexpr uint64_t kFwdBase = 0x2000;
constexpr uint64_t kBwdBase = 0x3000;
constexpr uint64_t kAgBase = 0x4000;
constexpr uint64_t kRedistBase = 0x5000;
constexpr uint64_t kFoldBase = 0;
constexpr uint64_t kUnfoldSlot = 1 << 20;

}  // namespace

void hdFoldAllreduce(Context* ctx, plan::Plan& plan, char* work,
                     size_t count, size_t elsize, ReduceFn fn, Slot slot,
                     std::chrono::milliseconds timeout, bool fuseOk) {
  const int rank = ctx->rank();
  const int size = ctx->size();
  const size_t nbytes = count * elsize;
  const int pow2 = static_cast<int>(largestPow2AtMost(size));
  const int rem = size - pow2;

  auto* workBuf = plan.userBuf(0, work, nbytes);
  // Fused receive-reduce (single policy: collectives_detail::
  // fuseRecvReduce): every receive-with-reduce in this walk targets a
  // range disjoint from any concurrently sent range, so partner partials
  // may be combined into `work` by the transport. The decision is per
  // partner (they change each round); scratch materializes lazily, only
  // if some round falls back.
  auto canFuse = [&](int src) {
    return fuseRecvReduce(ctx, fuseOk, elsize, src);
  };
  LazyStage stage(plan, 1, nbytes);

  // Fold: the first 2*rem ranks pair (even, odd); odds contribute their
  // vector to their even partner and sit out the exchange.
  uint64_t round = kFoldBase;
  int vrank;
  if (rank < 2 * rem) {
    if (rank % 2 == 1) {
      {
        PhaseScope ps(Phase::kPost, rank - 1, slot.offset(round).value(),
                      nbytes);
        workBuf->send(rank - 1, slot.offset(round).value(), 0, nbytes);
      }
      PhaseScope ps(Phase::kWireWait);
      workBuf->waitSend(timeout);
      vrank = -1;
    } else {
      if (canFuse(rank + 1)) {
        {
          PhaseScope ps(Phase::kPost);
          workBuf->recvReduce(rank + 1, slot.offset(round).value(), fn,
                              elsize, 0, nbytes);
        }
        PhaseScope ps(Phase::kWireWait, rank + 1,
                      slot.offset(round).value(), nbytes);
        workBuf->waitRecv(nullptr, timeout);
      } else {
        {
          PhaseScope ps(Phase::kPost);
          stage.buf()->recv(rank + 1, slot.offset(round).value(), 0,
                            nbytes);
        }
        {
          PhaseScope ps(Phase::kWireWait, rank + 1,
                        slot.offset(round).value(), nbytes);
          stage.buf()->waitRecv(nullptr, timeout);
        }
        PhaseScope ps(Phase::kReduce);
        fn(work, stage.data(), count);
      }
      vrank = rank / 2;
    }
  } else {
    vrank = rank - rem;
  }
  round++;
  auto physical = [&](int v) { return v < rem ? 2 * v : v + rem; };

  if (vrank >= 0 && pow2 > 1) {
    const Blocks& blocks =
        plan.blocks(0, [&] { return evenBlocks(count, pow2, elsize); });
    auto rangeOff = [&](int first) { return blocks.offset[first]; };
    auto rangeBytes = [&](int first, int n) {
      return blocks.rangeBytes(first, n);
    };

    // --- reduce-scatter: recursive vector halving ---
    int winStart = 0;
    int winCount = pow2;
    for (int mask = pow2 / 2; mask >= 1; mask >>= 1, round++) {
      const int partner = physical(vrank ^ mask);
      const int half = winCount / 2;
      const bool keepLower = (vrank & mask) == 0;
      const int keepStart = keepLower ? winStart : winStart + half;
      const int sendStart = keepLower ? winStart + half : winStart;
      const uint64_t s = slot.offset(round).value();
      const bool fused = canFuse(partner);
      {
        PhaseScope ps(Phase::kPost);
        if (fused) {
          // Combined into the kept range on arrival; the sent half is
          // disjoint, so the in-flight send never reads combined bytes.
          workBuf->recvReduce(partner, s, fn, elsize, rangeOff(keepStart),
                              rangeBytes(keepStart, half));
        } else {
          // Receive into the scratch mirror at the kept range's own
          // offsets.
          stage.buf()->recv(partner, s, rangeOff(keepStart),
                            rangeBytes(keepStart, half));
        }
      }
      {
        PhaseScope ps(Phase::kPost, partner, s,
                      rangeBytes(sendStart, half));
        workBuf->send(partner, s, rangeOff(sendStart),
                      rangeBytes(sendStart, half));
      }
      if (fused) {
        PhaseScope ps(Phase::kWireWait, partner, s,
                      rangeBytes(keepStart, half));
        workBuf->waitRecv(nullptr, timeout);
      } else {
        {
          PhaseScope ps(Phase::kWireWait, partner, s,
                        rangeBytes(keepStart, half));
          stage.buf()->waitRecv(nullptr, timeout);
        }
        if (rangeBytes(keepStart, half) > 0) {
          PhaseScope ps(Phase::kReduce);
          fn(work + rangeOff(keepStart), stage.data() + rangeOff(keepStart),
             rangeBytes(keepStart, half) / elsize);
        }
      }
      {
        PhaseScope ps(Phase::kWireWait);
        workBuf->waitSend(timeout);
      }
      winStart = keepStart;
      winCount = half;
    }

    // --- allgather: recursive doubling (receives land in place) ---
    for (int mask = 1; mask < pow2; mask <<= 1, round++) {
      const int partner = physical(vrank ^ mask);
      const int partnerStart = winStart ^ winCount;  // sibling window
      const uint64_t s = slot.offset(round).value();
      {
        PhaseScope ps(Phase::kPost);
        workBuf->recv(partner, s, rangeOff(partnerStart),
                      rangeBytes(partnerStart, winCount));
      }
      {
        PhaseScope ps(Phase::kPost, partner, s,
                      rangeBytes(winStart, winCount));
        workBuf->send(partner, s, rangeOff(winStart),
                      rangeBytes(winStart, winCount));
      }
      {
        PhaseScope ps(Phase::kWireWait, partner, s,
                      rangeBytes(partnerStart, winCount));
        workBuf->waitRecv(nullptr, timeout);
      }
      PhaseScope ps(Phase::kWireWait);
      workBuf->waitSend(timeout);
      winStart = std::min(winStart, partnerStart);
      winCount *= 2;
    }
  }

  // Unfold: even partners push the final vector back to the odd ranks.
  // A distinct sub-slot avoids any overlap with exchange rounds.
  const uint64_t finalSlot = slot.offset(kUnfoldSlot).value();
  if (rank < 2 * rem) {
    if (rank % 2 == 1) {
      {
        PhaseScope ps(Phase::kPost);
        workBuf->recv(rank - 1, finalSlot, 0, nbytes);
      }
      PhaseScope ps(Phase::kWireWait, rank - 1, finalSlot, nbytes);
      workBuf->waitRecv(nullptr, timeout);
    } else {
      {
        PhaseScope ps(Phase::kPost, rank + 1, finalSlot, nbytes);
        workBuf->send(rank + 1, finalSlot, 0, nbytes);
      }
      PhaseScope ps(Phase::kWireWait);
      workBuf->waitSend(timeout);
    }
  }
}

void hdBinaryBlocksAllreduce(Context* ctx, plan::Plan& plan, char* work,
                             size_t count, size_t elsize, ReduceFn fn,
                             Slot slot, std::chrono::milliseconds timeout,
                             bool fuseOk) {
  const int rank = ctx->rank();
  const int size = ctx->size();
  const size_t nbytes = count * elsize;

  // Binary-blocks layout: one block per set bit of P, larger blocks at
  // lower ranks (so blocks[0] is the largest, at rank offset 0).
  std::vector<int> bsize, boff;
  for (int bit = 30, off = 0; bit >= 0; bit--) {
    if (size & (1 << bit)) {
      bsize.push_back(1 << bit);
      boff.push_back(off);
      off += 1 << bit;
    }
  }
  const int k = static_cast<int>(bsize.size());
  int b = k - 1;
  while (boff[b] > rank) {
    b--;
  }
  const int r = rank - boff[b];   // rank within my block
  const int B = bsize[b];         // my block's size
  const int Bmax = bsize[0];

  // All windows are unions of "atoms": the vector split Bmax ways. Every
  // block size divides Bmax, so window boundaries align across blocks.
  const Blocks& atoms =
      plan.blocks(0, [&] { return evenBlocks(count, Bmax, elsize); });
  auto atomOff = [&](int first) { return atoms.offset[first]; };
  auto atomBytes = [&](int first, int n) { return atoms.rangeBytes(first, n); };

  auto* workBuf = plan.userBuf(0, work, nbytes);
  // Fused receive-reduce (single policy: collectives_detail::
  // fuseRecvReduce; disjoint kept/sent ranges make direct combining
  // safe). Scratch only materializes if a partner falls back.
  auto canFuse = [&](int src) {
    return fuseRecvReduce(ctx, fuseOk, elsize, src);
  };
  LazyStage stage(plan, 1, nbytes);

  // --- intra-block reduce-scatter: recursive vector halving ---
  // The window walk lands atoms [r*Bmax/B, (r+1)*Bmax/B) on block rank r.
  int winStart = 0;
  int winCount = Bmax;
  int step = 0;
  for (int mask = B / 2; mask >= 1; mask >>= 1, step++) {
    const int partner = boff[b] + (r ^ mask);
    const int half = winCount / 2;
    const bool keepLower = (r & mask) == 0;
    const int keepStart = keepLower ? winStart : winStart + half;
    const int sendStart = keepLower ? winStart + half : winStart;
    const uint64_t s = slot.offset(kRsBase + step).value();
    const bool fused = canFuse(partner);
    {
      PhaseScope ps(Phase::kPost);
      if (fused) {
        workBuf->recvReduce(partner, s, fn, elsize, atomOff(keepStart),
                            atomBytes(keepStart, half));
      } else {
        stage.buf()->recv(partner, s, atomOff(keepStart),
                          atomBytes(keepStart, half));
      }
      workBuf->send(partner, s, atomOff(sendStart),
                    atomBytes(sendStart, half));
    }
    if (fused) {
      PhaseScope ps(Phase::kWireWait);
      workBuf->waitRecv(nullptr, timeout);
    } else {
      {
        PhaseScope ps(Phase::kWireWait);
        stage.buf()->waitRecv(nullptr, timeout);
      }
      if (atomBytes(keepStart, half) > 0) {
        PhaseScope ps(Phase::kReduce);
        fn(work + atomOff(keepStart), stage.data() + atomOff(keepStart),
           atomBytes(keepStart, half) / elsize);
      }
    }
    {
      PhaseScope ps(Phase::kWireWait);
      workBuf->waitSend(timeout);
    }
    winStart = keepStart;
    winCount = half;
  }

  // --- inter-block chain, forward leg (smallest -> largest) ---
  // Exchange e joins blocks e (larger side) and e+1 (smaller side); the
  // smaller side's windows are unions of the larger side's, so each
  // smaller rank scatters pieces while each larger rank receives exactly
  // its own window. The chain serializes naturally: a block cannot send
  // partials up before it has absorbed the block below it.
  if (b + 1 < k) {  // I am the larger side of exchange b.
    const int ratio = B / bsize[b + 1];
    const int peer = boff[b + 1] + r / ratio;
    const uint64_t s = slot.offset(kFwdBase + b).value();
    if (canFuse(peer)) {
      // No send is in flight on this side of the exchange; the partial
      // combines into the window in place.
      {
        PhaseScope ps(Phase::kPost);
        workBuf->recvReduce(peer, s, fn, elsize, atomOff(winStart),
                            atomBytes(winStart, winCount));
      }
      PhaseScope ps(Phase::kWireWait);
      workBuf->waitRecv(nullptr, timeout);
    } else {
      {
        PhaseScope ps(Phase::kPost);
        stage.buf()->recv(peer, s, atomOff(winStart),
                          atomBytes(winStart, winCount));
      }
      {
        PhaseScope ps(Phase::kWireWait);
        stage.buf()->waitRecv(nullptr, timeout);
      }
      if (atomBytes(winStart, winCount) > 0) {
        PhaseScope ps(Phase::kReduce);
        fn(work + atomOff(winStart), stage.data() + atomOff(winStart),
           atomBytes(winStart, winCount) / elsize);
      }
    }
  }
  if (b > 0) {  // I am the smaller side of exchange b-1.
    const int ratioUp = bsize[b - 1] / B;
    const int Aup = Bmax / bsize[b - 1];  // atoms per larger-side window
    const uint64_t fwd = slot.offset(kFwdBase + b - 1).value();
    const uint64_t bwd = slot.offset(kBwdBase + b - 1).value();
    {
      PhaseScope ps(Phase::kPost);
      for (int j = 0; j < ratioUp; j++) {
        const int rUp = r * ratioUp + j;
        workBuf->send(boff[b - 1] + rUp, fwd, atomOff(rUp * Aup),
                      atomBytes(rUp * Aup, Aup));
      }
    }
    {
      PhaseScope ps(Phase::kWireWait);
      for (int j = 0; j < ratioUp; j++) {
        workBuf->waitSend(timeout);
      }
    }
    // --- backward leg: fully reduced pieces come back in place ---
    {
      PhaseScope ps(Phase::kPost);
      for (int j = 0; j < ratioUp; j++) {
        const int rUp = r * ratioUp + j;
        workBuf->recv(boff[b - 1] + rUp, bwd, atomOff(rUp * Aup),
                      atomBytes(rUp * Aup, Aup));
      }
    }
    PhaseScope ps(Phase::kWireWait);
    for (int j = 0; j < ratioUp; j++) {
      workBuf->waitRecv(nullptr, timeout);
    }
  }
  if (b + 1 < k) {  // Backward leg toward the block below me.
    const int ratio = B / bsize[b + 1];
    const int peer = boff[b + 1] + r / ratio;
    const uint64_t s = slot.offset(kBwdBase + b).value();
    {
      PhaseScope ps(Phase::kPost);
      workBuf->send(peer, s, atomOff(winStart),
                    atomBytes(winStart, winCount));
    }
    PhaseScope ps(Phase::kWireWait);
    workBuf->waitSend(timeout);
  }

  // --- intra-block allgather: recursive doubling ---
  step = 0;
  for (int mask = 1; mask < B; mask <<= 1, step++) {
    const int partner = boff[b] + (r ^ mask);
    const int partnerStart = winStart ^ winCount;  // sibling window
    const uint64_t s = slot.offset(kAgBase + step).value();
    {
      PhaseScope ps(Phase::kPost);
      workBuf->recv(partner, s, atomOff(partnerStart),
                    atomBytes(partnerStart, winCount));
      workBuf->send(partner, s, atomOff(winStart),
                    atomBytes(winStart, winCount));
    }
    PhaseScope ps(Phase::kWireWait);
    workBuf->waitRecv(nullptr, timeout);
    workBuf->waitSend(timeout);
    winStart = std::min(winStart, partnerStart);
    winCount *= 2;
  }
}

void hdReduceScatter(Context* ctx, plan::Plan& plan, char* work,
                     transport::UnboundBuffer* workBuf,
                     const Blocks& blocks, ReduceFn fn, size_t elsize,
                     Slot slot, std::chrono::milliseconds timeout,
                     bool fuseOk) {
  const int rank = ctx->rank();
  const int size = ctx->size();
  const size_t nbytes =
      blocks.offset[size - 1] + blocks.bytes[size - 1];
  const int pow2 = static_cast<int>(largestPow2AtMost(size));
  const int rem = size - pow2;

  auto canFuse = [&](int src) {
    return fuseRecvReduce(ctx, fuseOk, elsize, src);
  };
  LazyStage stage(plan, 1, nbytes);

  // Fold (non-power-of-2 only): odd ranks of the first 2*rem contribute
  // their whole vector to their even partner and rejoin for the
  // redistribution at the end.
  int vrank;
  if (rank < 2 * rem) {
    if (rank % 2 == 1) {
      {
        PhaseScope ps(Phase::kPost);
        workBuf->send(rank - 1, slot.offset(kFoldBase).value(), 0, nbytes);
      }
      PhaseScope ps(Phase::kWireWait);
      workBuf->waitSend(timeout);
      vrank = -1;
    } else {
      if (canFuse(rank + 1)) {
        {
          PhaseScope ps(Phase::kPost);
          workBuf->recvReduce(rank + 1, slot.offset(kFoldBase).value(),
                              fn, elsize, 0, nbytes);
        }
        PhaseScope ps(Phase::kWireWait);
        workBuf->waitRecv(nullptr, timeout);
      } else {
        {
          PhaseScope ps(Phase::kPost);
          stage.buf()->recv(rank + 1, slot.offset(kFoldBase).value(), 0,
                            nbytes);
        }
        {
          PhaseScope ps(Phase::kWireWait);
          stage.buf()->waitRecv(nullptr, timeout);
        }
        if (nbytes > 0) {
          PhaseScope ps(Phase::kReduce);
          fn(work, stage.data(), nbytes / elsize);
        }
      }
      vrank = rank / 2;
    }
  } else {
    vrank = rank - rem;
  }
  auto physical = [&](int v) { return v < rem ? 2 * v : v + rem; };

  // Recursive vector halving over windows of RESULT blocks (size of
  // them, arbitrary byte counts). Floor splits: both partners compute
  // half = c/2 from the shared window, so uneven windows stay in
  // lockstep; the upper window takes the extra block. Window byte
  // ranges are contiguous, so each round is one transfer.
  int pendingSends = 0;
  int winStart = 0;
  int winCount = size;
  if (vrank >= 0) {
    int step = 0;
    for (int mask = pow2 / 2; mask >= 1; mask >>= 1, step++) {
      const int half = winCount / 2;
      const int partner = physical(vrank ^ mask);
      const bool keepLower = (vrank & mask) == 0;
      const int keepStart = keepLower ? winStart : winStart + half;
      const int keepCount = keepLower ? half : winCount - half;
      const int sendStart = keepLower ? winStart + half : winStart;
      const int sendCount = winCount - keepCount;
      const uint64_t s = slot.offset(kRsBase + step).value();
      const size_t keepBytes = blocks.rangeBytes(keepStart, keepCount);
      const bool fused = canFuse(partner);
      {
        PhaseScope ps(Phase::kPost);
        if (fused) {
          workBuf->recvReduce(partner, s, fn, elsize,
                              blocks.offset[keepStart], keepBytes);
        } else {
          stage.buf()->recv(partner, s, blocks.offset[keepStart],
                            keepBytes);
        }
        workBuf->send(partner, s, blocks.offset[sendStart],
                      blocks.rangeBytes(sendStart, sendCount));
      }
      if (fused) {
        PhaseScope ps(Phase::kWireWait);
        workBuf->waitRecv(nullptr, timeout);
      } else {
        {
          PhaseScope ps(Phase::kWireWait);
          stage.buf()->waitRecv(nullptr, timeout);
        }
        if (keepBytes > 0) {
          PhaseScope ps(Phase::kReduce);
          fn(work + blocks.offset[keepStart],
             stage.data() + blocks.offset[keepStart], keepBytes / elsize);
        }
      }
      // Send completions are deferred to the end of the call: every
      // round's sent range is disjoint from all later combine targets
      // (each round's keep window excludes what was sent), so in-flight
      // data is never rewritten and the blocking wait would only add
      // log2(P) stalls to a latency-bound path.
      pendingSends++;
      winStart = keepStart;
      winCount = keepCount;
    }
  }

  // Redistribution: power-of-2 groups land window == {block vrank ==
  // block rank} and this phase is empty. Otherwise each participant
  // ships the foreign blocks in its window to their real ranks, and
  // every rank whose block ended elsewhere (including folded-out odd
  // ranks) receives it. ownerOf replays the deterministic window walk.
  auto ownerOf = [&](int j) {
    int v = 0, s = 0, c = size;
    for (int mask = pow2 / 2; mask >= 1; mask >>= 1) {
      const int half = c / 2;
      if (j < s + half) {
        c = half;
      } else {
        v |= mask;
        s += half;
        c -= half;
      }
    }
    return v;
  };
  if (vrank >= 0) {
    PhaseScope ps(Phase::kPost);
    for (int j = winStart; j < winStart + winCount; j++) {
      if (j == rank || blocks.bytes[j] == 0) {
        continue;
      }
      workBuf->send(j, slot.offset(kRedistBase + uint64_t(j)).value(),
                    blocks.offset[j], blocks.bytes[j]);
      pendingSends++;
    }
  }
  const int owner = physical(ownerOf(rank));
  if (owner != rank && blocks.bytes[rank] > 0) {
    {
      PhaseScope ps(Phase::kPost);
      workBuf->recv(owner,
                    slot.offset(kRedistBase + uint64_t(rank)).value(),
                    blocks.offset[rank], blocks.bytes[rank]);
    }
    PhaseScope ps(Phase::kWireWait);
    workBuf->waitRecv(nullptr, timeout);
  }
  PhaseScope ps(Phase::kWireWait);
  for (int i = 0; i < pendingSends; i++) {
    workBuf->waitSend(timeout);
  }
}

void directReduceScatter(Context* ctx, plan::Plan& plan, char* work,
                         transport::UnboundBuffer* workBuf,
                         const Blocks& blocks, ReduceFn fn, size_t elsize,
                         Slot slot, std::chrono::milliseconds timeout,
                         bool fuseOk) {
  const int rank = ctx->rank();
  const int size = ctx->size();

  // One latency round: ship this rank's copy of block j straight to
  // rank j, all P-1 transfers concurrently in flight.
  int sends = 0;
  {
    PhaseScope ps(Phase::kPost);
    for (int j = 0; j < size; j++) {
      if (j == rank || blocks.bytes[j] == 0) {
        continue;
      }
      workBuf->send(j, slot.offset(uint64_t(j)).value(), blocks.offset[j],
                    blocks.bytes[j]);
      sends++;
    }
  }
  // P-1 partials land in this rank's block. The combines are serialized
  // (one outstanding recvReduce at a time): combine-on-arrival may run
  // on the loop thread or, for stash hits, on this thread — two
  // outstanding posts into the SAME range could race their accumulates.
  // Serial posting keeps the zero-copy combine and still overlaps the
  // wire time: senders fired already, later arrivals wait in the stash.
  if (blocks.bytes[rank] > 0) {
    LazyStage stage(plan, 1, blocks.bytes[rank]);
    for (int s = 0; s < size; s++) {
      if (s == rank) {
        continue;
      }
      if (fuseRecvReduce(ctx, fuseOk, elsize, s)) {
        {
          PhaseScope ps(Phase::kPost);
          workBuf->recvReduce(s, slot.offset(uint64_t(rank)).value(), fn,
                              elsize, blocks.offset[rank],
                              blocks.bytes[rank]);
        }
        PhaseScope ps(Phase::kWireWait);
        workBuf->waitRecv(nullptr, timeout);
      } else {
        {
          PhaseScope ps(Phase::kPost);
          stage.buf()->recv(s, slot.offset(uint64_t(rank)).value(), 0,
                            blocks.bytes[rank]);
        }
        {
          PhaseScope ps(Phase::kWireWait);
          stage.buf()->waitRecv(nullptr, timeout);
        }
        PhaseScope ps(Phase::kReduce);
        fn(work + blocks.offset[rank], stage.data(),
           blocks.bytes[rank] / elsize);
      }
    }
  }
  PhaseScope ps(Phase::kWireWait);
  for (int i = 0; i < sends; i++) {
    workBuf->waitSend(timeout);
  }
}

// Recursive doubling: log2(P) rounds; round k exchanges the FULL
// running vector with partner = rank ^ (1 << k) and folds it in. Half
// the rounds of the halving-doubling pair (no allgather phase), at
// full-vector bytes per round — the alpha-dominated tiny-payload tier.
// Send and receive ranges overlap (both are the whole vector), so the
// receive always stages: folding into `work` while the concurrent send
// still reads it would race.
//
// Non-power-of-2 groups use the standard pre/post fold (Rabenseifner's
// small-message variant): with p2 the largest power of 2 <= P and
// rem = P - p2, the first 2*rem ranks pair up — each odd "extra" ships
// its whole vector to the even survivor below it, sits out the log
// rounds, and receives the finished result. At the tiny payloads this
// tier serves the two fold messages cost ~1 alpha each, keeping total
// latency at log2(p2)+2 rounds vs fold-HD's 2*log2(p2)+2 — the same
// 2x round advantage the pow-2 path measures (BASELINE.md).
//
// Bitwise identity across ranks: survivors enter the log rounds with
// subgroup-identical values; at each round both partners compute
// fn(X, Y) / fn(Y, X) over identical operand bits, and IEEE addition
// (and min/max) is commutative, so every merged group stays bitwise
// identical by induction. Extras receive those exact bits.
void recursiveDoublingAllreduce(Context* ctx, plan::Plan& plan,
                                char* work, size_t count, size_t elsize,
                                ReduceFn fn, Slot slot,
                                std::chrono::milliseconds timeout) {
  const int rank = ctx->rank();
  const int size = ctx->size();
  int p2 = 1;
  while (p2 * 2 <= size) {
    p2 *= 2;
  }
  const int rem = size - p2;
  const size_t nbytes = count * elsize;
  auto* workBuf = plan.userBuf(0, work, nbytes);
  // Slot layout: offset 0 = pre-fold, 1 = result return, 2+k = round k.
  const bool extra = rank < 2 * rem && (rank & 1) != 0;
  const bool paired = rank < 2 * rem && (rank & 1) == 0;
  if (extra) {
    // Extras never touch scratch — keep their path allocation-free.
    {
      PhaseScope ps(Phase::kPost, rank - 1, slot.offset(0).value(), nbytes);
      workBuf->send(rank - 1, slot.offset(0).value(), 0, nbytes);
    }
    {
      PhaseScope ps(Phase::kWireWait);
      workBuf->waitSend(timeout);
    }
    {
      PhaseScope ps(Phase::kPost);
      workBuf->recv(rank - 1, slot.offset(1).value(), 0, nbytes);
    }
    PhaseScope ps(Phase::kWireWait, rank - 1, slot.offset(1).value(), nbytes);
    workBuf->waitRecv(nullptr, timeout);
    return;
  }
  // Receive staging (send/recv ranges overlap — both are the whole
  // vector — so the receive can never fold in place): plan-staged, so
  // the repeated tiny-payload call this tier serves replays with no
  // allocation and no registration. This was the last per-op
  // std::vector<char> scratch in the allreduce family.
  auto st = plan.stage(1, nbytes);
  char* scratch = st.data;
  transport::UnboundBuffer* scratchBuf = st.buf;
  if (paired) {
    {
      PhaseScope ps(Phase::kPost);
      scratchBuf->recv(rank + 1, slot.offset(0).value(), 0, nbytes);
    }
    {
      PhaseScope ps(Phase::kWireWait, rank + 1, slot.offset(0).value(),
                    nbytes);
      scratchBuf->waitRecv(nullptr, timeout);
    }
    PhaseScope ps(Phase::kReduce);
    fn(work, scratch, count);
  }
  // Survivors renumber into a dense [0, p2) space for the XOR walk.
  const int rdRank = paired ? rank / 2 : rank - rem;
  uint64_t round = 0;
  for (int k = 1; k < p2; k <<= 1, round++) {
    const int rdPartner = rdRank ^ k;
    const int partner = rdPartner < rem ? 2 * rdPartner : rdPartner + rem;
    {
      PhaseScope ps(Phase::kPost);
      scratchBuf->recv(partner, slot.offset(2 + round).value(), 0, nbytes);
    }
    {
      PhaseScope ps(Phase::kPost, partner, slot.offset(2 + round).value(),
                    nbytes);
      workBuf->send(partner, slot.offset(2 + round).value(), 0, nbytes);
    }
    {
      PhaseScope ps(Phase::kWireWait);
      workBuf->waitSend(timeout);
    }
    {
      PhaseScope ps(Phase::kWireWait, partner,
                    slot.offset(2 + round).value(), nbytes);
      scratchBuf->waitRecv(nullptr, timeout);
    }
    PhaseScope ps(Phase::kReduce);
    fn(work, scratch, count);
  }
  if (paired) {
    {
      PhaseScope ps(Phase::kPost, rank + 1, slot.offset(1).value(), nbytes);
      workBuf->send(rank + 1, slot.offset(1).value(), 0, nbytes);
    }
    PhaseScope ps(Phase::kWireWait);
    workBuf->waitSend(timeout);
  }
}

void halvingDoublingAllreduce(Context* ctx, plan::Plan& plan, char* work,
                              size_t count, size_t elsize, ReduceFn fn,
                              Slot slot, std::chrono::milliseconds timeout,
                              bool fuseOk) {
  const int size = ctx->size();
  const bool pow2 = (size & (size - 1)) == 0;
  if (pow2) {
    // Power-of-2 groups: binary-blocks degenerates to the same single-
    // block walk; route through the fold path (rem == 0, no fold step).
    hdFoldAllreduce(ctx, plan, work, count, elsize, fn, slot, timeout,
                    fuseOk);
    return;
  }
  // Non-power-of-2 strategy. Loopback-measured crossover (BASELINE.md,
  // P=6): fold's fewer messages win while per-message overhead dominates;
  // binary-blocks' proportional byte work wins once payloads are large.
  // TPUCOLL_HD_NP2=blocks|fold forces either; otherwise the installed
  // tuning table's measured hd_fold/hd_blocks curves decide when both
  // arms were swept on this deployment, and the TPUCOLL_HD_NP2_CROSSOVER
  // byte threshold is the untuned fallback.
  bool useBlocks;
  const char* env =
      envChoice("TPUCOLL_HD_NP2", "auto", {"blocks", "fold", "auto"});
  if (std::strcmp(env, "blocks") == 0) {
    useBlocks = true;
  } else if (std::strcmp(env, "fold") == 0) {
    useBlocks = false;
  } else if (auto tuned = tuning::tableHdUseBlocks(ctx, count * elsize)) {
    useBlocks = *tuned;
  } else {
    static const size_t crossover = collectives_detail::envBytes(
        "TPUCOLL_HD_NP2_CROSSOVER", 1 << 20);
    useBlocks = count * elsize >= crossover;
  }
  if (useBlocks) {
    hdBinaryBlocksAllreduce(ctx, plan, work, count, elsize, fn, slot,
                            timeout, fuseOk);
  } else {
    hdFoldAllreduce(ctx, plan, work, count, elsize, fn, slot, timeout,
                    fuseOk);
  }
}

}  // namespace algorithms
}  // namespace tpucoll
