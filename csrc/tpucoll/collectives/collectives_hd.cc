// Recursive halving-doubling allreduce (Rabenseifner's algorithm over
// block windows).
//
// Reduce-scatter by recursive vector halving: at each round a rank and its
// partner (rank XOR mask, mask from P/2 down to 1) exchange complementary
// halves of the current block window and reduce the half they keep. After
// log2(P) rounds each rank's window is exactly its own block, fully
// reduced — the window bookkeeping lands block r on rank r directly, with
// no bit-reversal pass (contrast reference reduce_scatter.h:21-329).
// Allgather by recursive doubling reverses the walk, windows merging with
// their siblings until every rank holds the full vector.
#include <cstring>

#include "tpucoll/collectives/algorithms.h"
#include "tpucoll/collectives/detail.h"

namespace tpucoll {
namespace algorithms {

using collectives_detail::Blocks;
using collectives_detail::evenBlocks;
using collectives_detail::largestPow2AtMost;

void halvingDoublingAllreduce(Context* ctx, char* work, size_t count,
                              size_t elsize, ReduceFn fn, Slot slot,
                              std::chrono::milliseconds timeout) {
  const int rank = ctx->rank();
  const int size = ctx->size();
  const size_t nbytes = count * elsize;
  const int pow2 = static_cast<int>(largestPow2AtMost(size));
  const int rem = size - pow2;

  auto workBuf = ctx->createUnboundBuffer(work, nbytes);
  auto scratch = ctx->acquireScratch(nbytes);
  char* tmp = scratch.data();
  auto tmpBuf = ctx->createUnboundBuffer(tmp, nbytes);

  // Fold: the first 2*rem ranks pair (even, odd); odds contribute their
  // vector to their even partner and sit out the exchange.
  uint64_t round = 0;
  int vrank;
  if (rank < 2 * rem) {
    if (rank % 2 == 1) {
      workBuf->send(rank - 1, slot.offset(round).value(), 0, nbytes);
      workBuf->waitSend(timeout);
      vrank = -1;
    } else {
      tmpBuf->recv(rank + 1, slot.offset(round).value(), 0, nbytes);
      tmpBuf->waitRecv(nullptr, timeout);
      fn(work, tmp, count);
      vrank = rank / 2;
    }
  } else {
    vrank = rank - rem;
  }
  round++;
  auto physical = [&](int v) { return v < rem ? 2 * v : v + rem; };

  if (vrank >= 0 && pow2 > 1) {
    Blocks blocks = evenBlocks(count, pow2, elsize);
    auto rangeOff = [&](int first) { return blocks.offset[first]; };
    auto rangeBytes = [&](int first, int n) {
      return blocks.rangeBytes(first, n);
    };

    // --- reduce-scatter: recursive vector halving ---
    int winStart = 0;
    int winCount = pow2;
    for (int mask = pow2 / 2; mask >= 1; mask >>= 1, round++) {
      const int partner = physical(vrank ^ mask);
      const int half = winCount / 2;
      const bool keepLower = (vrank & mask) == 0;
      const int keepStart = keepLower ? winStart : winStart + half;
      const int sendStart = keepLower ? winStart + half : winStart;
      const uint64_t s = slot.offset(round).value();
      // Receive into the scratch mirror at the kept range's own offsets.
      tmpBuf->recv(partner, s, rangeOff(keepStart),
                   rangeBytes(keepStart, half));
      workBuf->send(partner, s, rangeOff(sendStart),
                    rangeBytes(sendStart, half));
      tmpBuf->waitRecv(nullptr, timeout);
      if (rangeBytes(keepStart, half) > 0) {
        fn(work + rangeOff(keepStart), tmp + rangeOff(keepStart),
           rangeBytes(keepStart, half) / elsize);
      }
      workBuf->waitSend(timeout);
      winStart = keepStart;
      winCount = half;
    }

    // --- allgather: recursive doubling (receives land in place) ---
    for (int mask = 1; mask < pow2; mask <<= 1, round++) {
      const int partner = physical(vrank ^ mask);
      const int partnerStart = winStart ^ winCount;  // sibling window
      const uint64_t s = slot.offset(round).value();
      workBuf->recv(partner, s, rangeOff(partnerStart),
                    rangeBytes(partnerStart, winCount));
      workBuf->send(partner, s, rangeOff(winStart),
                    rangeBytes(winStart, winCount));
      workBuf->waitRecv(nullptr, timeout);
      workBuf->waitSend(timeout);
      winStart = std::min(winStart, partnerStart);
      winCount *= 2;
    }
  }

  // Unfold: even partners push the final vector back to the odd ranks.
  // A distinct sub-slot avoids any overlap with exchange rounds.
  const uint64_t finalSlot = slot.offset(1 << 20).value();
  if (rank < 2 * rem) {
    if (rank % 2 == 1) {
      workBuf->recv(rank - 1, finalSlot, 0, nbytes);
      workBuf->waitRecv(nullptr, timeout);
    } else {
      workBuf->send(rank + 1, finalSlot, 0, nbytes);
      workBuf->waitSend(timeout);
    }
  }
}

}  // namespace algorithms
}  // namespace tpucoll
