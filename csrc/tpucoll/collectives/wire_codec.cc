#include "tpucoll/collectives/wire_codec.h"

#include <algorithm>

#include "tpucoll/common/codec_pool.h"

namespace tpucoll {
namespace algorithms {

namespace {

// ---- bf16 adapters (unit = one element, no scale header) ----

void bf16Encode(const float* src, uint8_t* dst, size_t n) {
  f32StreamToBf16(src, reinterpret_cast<uint16_t*>(dst), n);
}

void bf16Decode(const uint8_t* src, float* dst, size_t n) {
  bf16StreamToF32(reinterpret_cast<const uint16_t*>(src), dst, n);
}

void bf16Accumulate(float* acc, const uint8_t* src, size_t n) {
  bf16StreamAccumulate(acc, reinterpret_cast<const uint16_t*>(src), n);
}

size_t bf16Wire(size_t n) { return n * sizeof(uint16_t); }

void bf16FusedAccumulate(void* acc, const void* in, size_t n) {
  bf16StreamAccumulate(static_cast<float*>(acc),
                       static_cast<const uint16_t*>(in), n);
}

void bf16FusedDecode(void* acc, const void* in, size_t n) {
  bf16StreamToF32(static_cast<const uint16_t*>(in),
                  static_cast<float*>(acc), n);
}

// ---- q8 adapters (block size process-global, like the codec) ----

void q8Encode(const float* src, uint8_t* dst, size_t n) {
  f32StreamToQ8(src, dst, n, q8BlockElems());
}

void q8Decode(const uint8_t* src, float* dst, size_t n) {
  q8StreamToF32(src, dst, n, q8BlockElems());
}

void q8Accumulate(float* acc, const uint8_t* src, size_t n) {
  q8StreamAccumulate(acc, src, n, q8BlockElems());
}

size_t q8Wire(size_t n) { return q8WireBytes(n, q8BlockElems()); }

void q8FusedAccumulate(void* acc, const void* in, size_t nUnits) {
  const size_t block = q8BlockElems();
  q8StreamAccumulate(static_cast<float*>(acc),
                     static_cast<const uint8_t*>(in), nUnits * block,
                     block);
}

// ---- q4 adapters ----

void q4Encode(const float* src, uint8_t* dst, size_t n) {
  f32StreamToQ4(src, dst, n, q4BlockElems());
}

void q4Decode(const uint8_t* src, float* dst, size_t n) {
  q4StreamToF32(src, dst, n, q4BlockElems());
}

void q4Accumulate(float* acc, const uint8_t* src, size_t n) {
  q4StreamAccumulate(acc, src, n, q4BlockElems());
}

size_t q4Wire(size_t n) { return q4WireBytes(n, q4BlockElems()); }

void q4FusedAccumulate(void* acc, const void* in, size_t nUnits) {
  const size_t block = q4BlockElems();
  q4StreamAccumulate(static_cast<float*>(acc),
                     static_cast<const uint8_t*>(in), nUnits * block,
                     block);
}

}  // namespace

const WireCodec& bf16WireCodec() {
  static const WireCodec c = [] {
    WireCodec w;
    w.kind = kWireCodecBf16;
    w.name = "bf16";
    w.unitElems = 1;
    w.unitBytes = sizeof(uint16_t);
    w.exactReencode = true;
    w.encode = bf16Encode;
    w.decode = bf16Decode;
    w.accumulate = bf16Accumulate;
    w.wire = bf16Wire;
    w.fusedAccumulate = bf16FusedAccumulate;
    w.fusedDecode = bf16FusedDecode;
    return w;
  }();
  return c;
}

const WireCodec& q8WireCodec() {
  static const WireCodec c = [] {
    WireCodec w;
    w.kind = kWireCodecQ8;
    w.name = "q8";
    w.unitElems = q8BlockElems();
    w.unitBytes = q8UnitBytes(q8BlockElems());
    w.exactReencode = false;
    w.encode = q8Encode;
    w.decode = q8Decode;
    w.accumulate = q8Accumulate;
    w.wire = q8Wire;
    w.fusedAccumulate = q8FusedAccumulate;
    w.fusedDecode = nullptr;  // q8 re-encode double-rounds: never fuse AG
    return w;
  }();
  return c;
}

const WireCodec& q4WireCodec() {
  static const WireCodec c = [] {
    WireCodec w;
    w.kind = kWireCodecQ4;
    w.name = "q4";
    w.unitElems = q4BlockElems();
    w.unitBytes = q4UnitBytes(q4BlockElems());
    w.exactReencode = false;
    w.encode = q4Encode;
    w.decode = q4Decode;
    w.accumulate = q4Accumulate;
    w.wire = q4Wire;
    w.fusedAccumulate = q4FusedAccumulate;
    w.fusedDecode = nullptr;
    return w;
  }();
  return c;
}

size_t subSpans(const WireCodec& codec, size_t n, int depth, SubSpan* out) {
  const size_t units = codec.unitsOf(n);
  const size_t count = std::max<size_t>(
      1, std::min<size_t>(static_cast<size_t>(depth), units));
  for (size_t k = 0; k < count; k++) {
    const size_t u0 = units * k / count;
    const size_t u1 = units * (k + 1) / count;
    SubSpan& s = out[k];
    s.elemOff = u0 * codec.unitElems;
    const size_t elemEnd = std::min(u1 * codec.unitElems, n);
    s.elems = elemEnd - s.elemOff;
    s.wireOff = u0 * codec.unitBytes;
    s.wireBytes = codec.wire(s.elems);
  }
  return count;
}

namespace {

// Unit-aligned shard walk shared by the three sharded kernels: fn gets
// (elemOff, elems, wireOff) per shard.
template <typename Fn>
void forEachShard(const WireCodec& codec, size_t n, size_t shards,
                  const Fn& fn) {
  const size_t units = codec.unitsOf(n);
  const size_t count = std::max<size_t>(1, std::min(shards, units));
  if (count <= 1) {
    fn(size_t(0), n, size_t(0));
    return;
  }
  codec::CodecPool::instance().parallelFor(count, [&](size_t k) {
    const size_t u0 = units * k / count;
    const size_t u1 = units * (k + 1) / count;
    const size_t elemOff = u0 * codec.unitElems;
    const size_t elemEnd = std::min(u1 * codec.unitElems, n);
    fn(elemOff, elemEnd - elemOff, u0 * codec.unitBytes);
  });
}

}  // namespace

void wireEncode(const WireCodec& codec, const float* src, uint8_t* dst,
                size_t n, size_t shards, float* res, float* tmp) {
  if (res == nullptr) {
    forEachShard(codec, n, shards,
                 [&](size_t eo, size_t ne, size_t wo) {
                   codec.encode(src + eo, dst + wo, ne);
                 });
    return;
  }
  // Error feedback, per shard: t = src + res; encode t; the residual
  // array doubles as the decode scratch, then flips to t - decode(t).
  // Mul-free elementwise passes — deterministic for any shard count.
  forEachShard(codec, n, shards, [&](size_t eo, size_t ne, size_t wo) {
    float* t = tmp + eo;
    float* r = res + eo;
    const float* s = src + eo;
    for (size_t i = 0; i < ne; i++) {
      t[i] = s[i] + r[i];
    }
    codec.encode(t, dst + wo, ne);
    codec.decode(dst + wo, r, ne);
    for (size_t i = 0; i < ne; i++) {
      r[i] = t[i] - r[i];
    }
  });
}

void wireDecode(const WireCodec& codec, const uint8_t* src, float* dst,
                size_t n, size_t shards) {
  forEachShard(codec, n, shards, [&](size_t eo, size_t ne, size_t wo) {
    codec.decode(src + wo, dst + eo, ne);
  });
}

void wireAccumulate(const WireCodec& codec, float* acc, const uint8_t* src,
                    size_t n, size_t shards) {
  forEachShard(codec, n, shards, [&](size_t eo, size_t ne, size_t wo) {
    codec.accumulate(acc + eo, src + wo, ne);
  });
}

}  // namespace algorithms
}  // namespace tpucoll
