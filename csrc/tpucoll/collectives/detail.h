// Shared block/segment bookkeeping for collective schedules.
#pragma once

#include <algorithm>
#include <cerrno>
#include <cstddef>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <optional>
#include <vector>

#include "tpucoll/common/env.h"
#include "tpucoll/context.h"

namespace tpucoll {
namespace collectives_detail {

struct Blocks {
  std::vector<size_t> bytes;   // per-block byte size
  std::vector<size_t> offset;  // per-block byte offset

  // Bytes of the contiguous range covering blocks [first, first+n).
  size_t rangeBytes(size_t first, size_t n) const {
    size_t total = 0;
    for (size_t i = first; i < first + n; i++) {
      total += bytes[i];
    }
    return total;
  }
};

inline Blocks evenBlocks(size_t count, int size, size_t elsize) {
  Blocks b;
  b.bytes.resize(size);
  b.offset.resize(size);
  const size_t base = count / size;
  const size_t rem = count % size;
  size_t off = 0;
  for (int i = 0; i < size; i++) {
    const size_t elems = base + (static_cast<size_t>(i) < rem ? 1 : 0);
    b.bytes[i] = elems * elsize;
    b.offset[i] = off;
    off += b.bytes[i];
  }
  return b;
}

inline Blocks countBlocks(const std::vector<size_t>& counts, size_t elsize) {
  Blocks b;
  b.bytes.resize(counts.size());
  b.offset.resize(counts.size());
  size_t off = 0;
  for (size_t i = 0; i < counts.size(); i++) {
    b.bytes[i] = counts[i] * elsize;
    b.offset[i] = off;
    off += b.bytes[i];
  }
  return b;
}

struct SegSpan {
  size_t offset;  // within the block
  size_t nbytes;
};

// Pipelining granularity for ring schedules (see collectives_ring.cc).
constexpr size_t kMaxSegmentBytes = 4 << 20;

// Fused receive-reduce (UnboundBuffer::recvReduce) policy for builtin
// reductions. Default (auto): fuse only when the source pair delivers
// payloads through an shm ring — there the combine replaces the ring
// copy-out outright, a strict win; on byte-stream TCP pairs fusing would
// move the reduction onto the loop thread and lose the reduce/socket-I-O
// overlap the scratch schedule (the reference's shape, gloo/allreduce.cc:
// 284-299) gets for free, so auto keeps scratch there.
// TPUCOLL_RECV_REDUCE=0 forces scratch everywhere; =1 forces fused
// everywhere (A/B measurement on any transport). Anything else (but
// ""/"auto") throws: a misspelled knob must not silently run the wrong
// arm of an A/B experiment.
enum class RecvReduceMode { kOff, kAuto, kForce };

inline RecvReduceMode recvReduceMode() {
  static const RecvReduceMode mode = [] {
    const char* v =
        envChoice("TPUCOLL_RECV_REDUCE", "auto", {"0", "1", "auto"});
    if (std::strcmp(v, "0") == 0) {
      return RecvReduceMode::kOff;
    }
    if (std::strcmp(v, "1") == 0) {
      return RecvReduceMode::kForce;
    }
    return RecvReduceMode::kAuto;
  }();
  return mode;
}

// Strict byte-count env knob — hoisted to common/env.h so the transport
// layer shares the same contract; this alias keeps the many schedule
// call sites unchanged. Call sites cache the result in a function-local
// static: these gate hot schedule decisions.
using ::tpucoll::envBytes;

// THE fuse-eligibility predicate — single definition so every schedule
// applies the same policy. `fuseOk` = the reduction is a builtin (safe on
// the transport's loop thread).
inline bool fuseRecvReduce(Context* ctx, bool fuseOk, size_t elsize,
                           int srcRank) {
  const auto mode = recvReduceMode();
  return fuseOk && mode != RecvReduceMode::kOff &&
         elsize <= transport::kMaxCombineElsize &&
         (mode == RecvReduceMode::kForce ||
          ctx->transport()->peerUsesShm(srcRank));
}

// (The lazily-materialized pooled scratch that used to live here —
// LazyScratch — became plan::LazyStage: the same first-touch contract,
// now backed by the persistent plan's arena so a repeated collective
// reuses the registration instead of re-creating it. See plan.h.)

inline std::vector<SegSpan> segmentize(size_t blockBytes, size_t elsize) {
  size_t segBytes = std::max(kMaxSegmentBytes / elsize * elsize, elsize);
  std::vector<SegSpan> segs;
  size_t off = 0;
  while (off < blockBytes) {
    size_t n = std::min(segBytes, blockBytes - off);
    segs.push_back(SegSpan{off, n});
    off += n;
  }
  if (segs.empty()) {
    segs.push_back(SegSpan{0, 0});  // zero-byte block still needs a message
  }
  return segs;
}

inline uint64_t largestPow2AtMost(uint64_t n) {
  uint64_t p = 1;
  while (p * 2 <= n) {
    p *= 2;
  }
  return p;
}

}  // namespace collectives_detail
}  // namespace tpucoll
