// Internal: allreduce algorithm implementations operating on a prepared
// work buffer (inputs already locally reduced into it). Selected via
// AllreduceOptions::algorithm.
#pragma once

#include <chrono>

#include "tpucoll/context.h"
#include "tpucoll/math.h"
#include "tpucoll/types.h"

namespace tpucoll {
namespace algorithms {

// Bandwidth-optimal ring (reduce-scatter + allgather), segment-pipelined.
// fuseOk: fn is a builtin (loop-thread-safe) reduction, so the reduce-
// scatter phase may use the transport's fused recvReduce path.
void ringAllreduce(Context* ctx, char* work, size_t count, size_t elsize,
                   ReduceFn fn, Slot slot,
                   std::chrono::milliseconds timeout, bool fuseOk);

// Recursive-halving/recursive-doubling (Rabenseifner) allreduce:
// 2*log2(P) rounds, latency-optimal for small payloads. Non-power-of-2
// group sizes use a binary-blocks decomposition (reference analog:
// gloo/allreduce_halving_doubling.h:39-64) giving every rank work
// proportional to its window; TPUCOLL_HD_NP2=fold selects the simpler
// fold variant (first 2r odd ranks fold into their even partners, at the
// cost of two extra full-vector hops on those ranks).
void halvingDoublingAllreduce(Context* ctx, char* work, size_t count,
                              size_t elsize, ReduceFn fn, Slot slot,
                              std::chrono::milliseconds timeout,
                              bool fuseOk);

// Mixed-radix grouped-hypercube (bcube) allreduce: log-depth like
// halving-doubling but with configurable group fan-out per step; exact
// schedule for any P via prime factorization (reference analog:
// gloo/allreduce_bcube.h).
void bcubeAllreduce(Context* ctx, char* work, size_t count, size_t elsize,
                    ReduceFn fn, Slot slot,
                    std::chrono::milliseconds timeout, bool fuseOk);

// Ring allreduce with bfloat16 wire compression (float32 payloads).
void bf16WireRingAllreduce(Context* ctx, char* work, size_t count, Slot slot,
                           std::chrono::milliseconds timeout);

}  // namespace algorithms
}  // namespace tpucoll
