// Internal: allreduce algorithm implementations operating on a prepared
// work buffer (inputs already locally reduced into it). Selected via
// AllreduceOptions::algorithm.
#pragma once

#include <chrono>

#include "tpucoll/collectives/detail.h"
#include "tpucoll/collectives/plan.h"
#include "tpucoll/context.h"
#include "tpucoll/math.h"
#include "tpucoll/types.h"

namespace tpucoll {
namespace algorithms {

// Bandwidth-optimal ring (reduce-scatter + allgather), segment-pipelined.
// fuseOk: fn is a builtin (loop-thread-safe) reduction, so the reduce-
// scatter phase may use the transport's fused recvReduce path.
void ringAllreduce(Context* ctx, plan::Plan& plan, char* work,
                   size_t count, size_t elsize, ReduceFn fn, Slot slot,
                   std::chrono::milliseconds timeout, bool fuseOk);

// Recursive-halving/recursive-doubling (Rabenseifner) allreduce:
// 2*log2(P) rounds, latency-optimal for small payloads. Non-power-of-2
// group sizes use a binary-blocks decomposition (reference analog:
// gloo/allreduce_halving_doubling.h:39-64) giving every rank work
// proportional to its window; TPUCOLL_HD_NP2=fold selects the simpler
// fold variant (first 2r odd ranks fold into their even partners, at the
// cost of two extra full-vector hops on those ranks).
// Recursive doubling: each round exchanges the FULL running vector with
// partner rank^k and folds it in; non-power-of-2 sizes take a pre/post
// fold (odd ranks of the first 2*(P-p2) ship their vector to the even
// survivor, sit out the rounds, and receive the result). Commutative
// IEEE addition makes the result bitwise identical across ranks.
void recursiveDoublingAllreduce(Context* ctx, plan::Plan& plan,
                                char* work, size_t count, size_t elsize,
                                ReduceFn fn, Slot slot,
                                std::chrono::milliseconds timeout);

void halvingDoublingAllreduce(Context* ctx, plan::Plan& plan, char* work,
                              size_t count, size_t elsize, ReduceFn fn,
                              Slot slot, std::chrono::milliseconds timeout,
                              bool fuseOk);

// The two halving-doubling non-power-of-2 strategies as directly callable
// arms (AllreduceAlgorithm::kHdFold / kHdBlocks; halvingDoublingAllreduce
// dispatches between them). Both are valid for ANY group size — on
// power-of-2 groups they run the identical single-block walk.
void hdFoldAllreduce(Context* ctx, plan::Plan& plan, char* work,
                     size_t count, size_t elsize, ReduceFn fn, Slot slot,
                     std::chrono::milliseconds timeout, bool fuseOk);
void hdBinaryBlocksAllreduce(Context* ctx, plan::Plan& plan, char* work,
                             size_t count, size_t elsize, ReduceFn fn,
                             Slot slot, std::chrono::milliseconds timeout,
                             bool fuseOk);

// Mixed-radix grouped-hypercube (bcube) allreduce: log-depth like
// halving-doubling but with configurable group fan-out per step; exact
// schedule for any P via prime factorization (reference analog:
// gloo/allreduce_bcube.h).
void bcubeAllreduce(Context* ctx, plan::Plan& plan, char* work,
                    size_t count, size_t elsize, ReduceFn fn, Slot slot,
                    std::chrono::milliseconds timeout, bool fuseOk);

// Ring allreduce with bfloat16 wire compression (float32 payloads).
void bf16WireRingAllreduce(Context* ctx, plan::Plan& plan, char* work,
                           size_t count, Slot slot,
                           std::chrono::milliseconds timeout);

// Ring allreduce with the int8 block-quantized wire codec (float32 sum
// payloads; math.h q8 stream layout, TPUCOLL_Q8_BLOCK block size).
// Accumulation stays float32; every reduce-scatter hop re-quantizes, and
// the allgather phase forwards the owner's final quantized stream
// verbatim so all ranks decode bit-identical results.
void q8WireRingAllreduce(Context* ctx, plan::Plan& plan, char* work,
                         size_t count, Slot slot,
                         std::chrono::milliseconds timeout);

// Ring reduce-scatter over the same q8 wire (startShift -1: rank r ends
// owning reduced block r of `blocks`, full-precision float32 — only the
// wire hops are quantized).
void q8WireRingReduceScatter(Context* ctx, plan::Plan& plan, char* work,
                             transport::UnboundBuffer* workBuf,
                             const collectives_detail::Blocks& blocks,
                             Slot slot, std::chrono::milliseconds timeout);

// Ring allreduce / reduce-scatter over the int4 packed-nibble wire
// codec (float32 sum; math.h q4 stream layout, TPUCOLL_Q4_BLOCK block
// size). ~8x fewer wire bytes than float32 at max|block|/14 per-element
// precision; the allgather forwards verbatim, so results stay
// bit-identical across ranks. Opt-in / tuner-elected only.
void q4WireRingAllreduce(Context* ctx, plan::Plan& plan, char* work,
                         size_t count, Slot slot,
                         std::chrono::milliseconds timeout);
void q4WireRingReduceScatter(Context* ctx, plan::Plan& plan, char* work,
                             transport::UnboundBuffer* workBuf,
                             const collectives_detail::Blocks& blocks,
                             Slot slot, std::chrono::milliseconds timeout);

// Log-latency reduce-scatter by recursive vector halving (contract of
// reference gloo/reduce_scatter.h:21-329, re-derived for the in-order
// window walk): log2(P) rounds over windows of the caller's per-rank
// result blocks (arbitrary recvCounts; floor splits keep partners in
// lockstep on uneven counts). Power-of-2 groups land block r on rank r
// directly; otherwise odd ranks of the first 2*rem fold into their even
// partner and a final redistribution ships each owned block to its real
// rank. `work` is reduced in place; afterwards block `rank` (at
// blocks.offset[rank]) is this rank's fully reduced result.
void hdReduceScatter(Context* ctx, plan::Plan& plan, char* work,
                     transport::UnboundBuffer* workBuf,
                     const collectives_detail::Blocks& blocks, ReduceFn fn,
                     size_t elsize, Slot slot,
                     std::chrono::milliseconds timeout, bool fuseOk);

// One-round reduce-scatter for tiny payloads: every rank ships its copy
// of block j straight to rank j (P-1 concurrent transfers) and combines
// the P-1 partials that land in its own block. Single network round —
// beats both ring (P-1 rounds) and recursive halving (log2 P) when the
// payload is latency-bound. No reference analog (its smallest-payload
// path is still halving-doubling); same tier as the repo's direct
// allgather (TPUCOLL_ALLGATHER_DIRECT_MAX).
void directReduceScatter(Context* ctx, plan::Plan& plan, char* work,
                         transport::UnboundBuffer* workBuf,
                         const collectives_detail::Blocks& blocks,
                         ReduceFn fn, size_t elsize, Slot slot,
                         std::chrono::milliseconds timeout, bool fuseOk);

}  // namespace algorithms
}  // namespace tpucoll
