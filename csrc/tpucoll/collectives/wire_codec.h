// Wire-codec descriptors: the lossy wire formats (math.h bf16/q8/q4
// streams) as data, so one pipelined ring engine (wire_ring.cc) serves
// every codec instead of each codec hand-rolling its own schedule.
//
// A codec's stream is a sequence of independent UNITS (q8/q4: one scale
// header + one block of codes; bf16: a single element). Everything the
// engine needs reduces to unit geometry plus four kernels:
//
//   - unit independence makes SHARDING exact: encoding units [a, b) and
//     [b, c) separately and concatenating equals the serial walk
//     byte-for-byte, for any split — the codec pool's byte-identity
//     contract (wireEncode/wireDecode/wireAccumulate below);
//   - the same boundaries split a ring hop into TPUCOLL_CODEC_PIPELINE
//     sub-blocks (subSpans) that encode/transmit/decode independently —
//     the pipelined hop's wire protocol;
//   - error feedback (wireEncode with a residual) folds the previous
//     call's quantization error into the next encode and captures the
//     new error, per element, before the bytes hit the wire.
//
// Precision/consensus contracts stay per-codec (docs/errors.md); this
// header only fixes the geometry and kernel surface.
#pragma once

#include <cstddef>
#include <cstdint>

#include "tpucoll/math.h"
#include "tpucoll/transport/unbound_buffer.h"

namespace tpucoll {
namespace algorithms {

// Stable codec ids (capi sharded-codec surface + tuner labels).
constexpr int kWireCodecBf16 = 0;
constexpr int kWireCodecQ8 = 1;
constexpr int kWireCodecQ4 = 2;

struct WireCodec {
  int kind{0};             // kWireCodec*
  const char* name{""};    // "bf16" / "q8" / "q4"
  size_t unitElems{1};     // float32 elements per full unit
  size_t unitBytes{2};     // wire bytes per full unit
  // encode(decode(encode(x))) == encode(x): true only for bf16, where a
  // decoded value re-rounds to the same wire bytes. Gates the fused
  // allgather arm (re-encode forwarding); q8/q4 must forward verbatim.
  bool exactReencode{false};

  // Stream kernels over n elements (serial; sharding wraps them).
  void (*encode)(const float* src, uint8_t* dst, size_t n){nullptr};
  void (*decode)(const uint8_t* src, float* dst, size_t n){nullptr};
  void (*accumulate)(float* acc, const uint8_t* src, size_t n){nullptr};
  // Total wire bytes for an n-element stream (ragged tail included).
  size_t (*wire)(size_t n){nullptr};

  // RecvReduceFn-shaped adapters for the typed fused receive: `in` is n
  // whole units, acc the float32 accumulator (wire elsize = unitBytes,
  // acc elsize = unitElems * 4). fusedDecode is only set when
  // exactReencode holds (the fused-allgather decode-in-place arm).
  transport::RecvReduceFn fusedAccumulate{nullptr};
  transport::RecvReduceFn fusedDecode{nullptr};

  size_t unitsOf(size_t n) const {
    return (n + unitElems - 1) / unitElems;
  }
};

// Process-wide descriptors (q8/q4 bind the resolved TPUCOLL_Q8_BLOCK /
// TPUCOLL_Q4_BLOCK once, like the codecs themselves).
const WireCodec& bf16WireCodec();
const WireCodec& q8WireCodec();
const WireCodec& q4WireCodec();

// One pipelined sub-block of an n-element hop stream: a unit-aligned
// contiguous span. Sub boundaries are derived from (n, depth) alone, so
// sender and receiver always agree on the per-message geometry.
struct SubSpan {
  size_t elemOff{0};    // first element of the span
  size_t elems{0};      // elements in the span
  size_t wireOff{0};    // byte offset of the span inside the stream
  size_t wireBytes{0};  // wire bytes of the span
};

constexpr int kMaxPipelineDepth = 32;  // TPUCOLL_CODEC_PIPELINE ceiling

// Split an n-element stream into at most `depth` unit-aligned spans
// (fewer when the stream has fewer units; exactly one for n == 0).
// Returns the span count; `out` must hold kMaxPipelineDepth entries.
size_t subSpans(const WireCodec& codec, size_t n, int depth, SubSpan* out);

// Sharded stream kernels: run the serial kernel over `shards` unit-
// aligned pieces on the codec pool. Output is byte-identical to the
// serial walk for ANY shard count (unit independence; disjoint dst
// ranges) — unit-tested via the capi sharded surface.
//
// wireEncode optionally applies error feedback: with res != nullptr
// (and tmp, a caller-provided n-float scratch), each element encodes
// t = src + res and the new residual res = t - decode(encode(t)) is
// captured in place. res/tmp slices shard with the stream.
void wireEncode(const WireCodec& codec, const float* src, uint8_t* dst,
                size_t n, size_t shards, float* res = nullptr,
                float* tmp = nullptr);
void wireDecode(const WireCodec& codec, const uint8_t* src, float* dst,
                size_t n, size_t shards);
void wireAccumulate(const WireCodec& codec, float* acc, const uint8_t* src,
                    size_t n, size_t shards);

}  // namespace algorithms
}  // namespace tpucoll
