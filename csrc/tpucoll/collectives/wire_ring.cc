// Pipelined wire-codec ring engine (see wire_ring.h for the contract).
//
// Hop anatomy at pipeline depth D (both ring phases):
//
//   post   all <= D sub-recvs up front (slot delta = hop base + j, so
//          the receiver can identify arrivals in ANY order);
//   encode sub j on the codec pool; the WORKER posts the send the
//          moment its encode finishes (in sub order, via the hop's
//          send sequencer), so the op thread never blocks on encode
//          before a send — sub j+1 encodes while sub j is on the wire
//          and the op thread is already draining arrivals;
//   drain  staged arrivals by slot (waitRecvSlot) and hand each sub to
//          the pool for decode/accumulate on arrival;
//   join   decode tickets, fused arrivals, encode tickets, and the D
//          sends before the next hop (the rx/tx parity regions flip
//          per hop, so a full per-hop drain is what makes their reuse
//          safe).
//
// Phase attribution follows the work, not the schedule: with pool
// workers, the op thread's pack bucket holds only the residual
// (non-overlapped) encode join — the codec itself runs while the op
// thread sits in wire_wait — and sends posted by workers are invisible
// to the op-thread span stream (the pair-level wire telemetry still
// counts them). With no workers (TPUCOLL_CODEC_THREADS=1, the default)
// every kernel runs inline on the op thread under its honest phase
// scope, including the staged decode/accumulate fallback.
//
// Fused receives keep working per sub-block: a sub whose element count
// is whole units rides recvReduceTyped straight into the float32
// accumulator on the transport thread (one fewer staging pass AND the
// fold leaves the caller's profile entirely); ragged tails stage.
//
// Consensus: unchanged from the per-codec rings. The allgather forwards
// received wire bytes verbatim for inexact codecs (q8/q4) — now
// directly from the rx stage, with no copy into tx — and re-encodes
// decoded values only where that roundtrip is exact (bf16). Error
// feedback touches origin encodes only, so forwarded streams are
// byte-identical with EF on or off.
#include <cstring>
#include <exception>
#include <mutex>

#include "tpucoll/collectives/wire_ring.h"
#include "tpucoll/common/codec_pool.h"
#include "tpucoll/common/env.h"
#include "tpucoll/common/profile.h"

namespace tpucoll {
namespace algorithms {

using collectives_detail::Blocks;
using collectives_detail::evenBlocks;
using profile::Phase;
using profile::PhaseScope;

bool wireErrorFeedback() {
  static const bool on = envFlag("TPUCOLL_WIRE_EF", true);
  return on;
}

namespace {

// Per-hop sub-block geometry (unit-aligned; identical on both ends of
// the wire because it derives from the hop's element count alone).
struct HopGeom {
  SubSpan spans[kMaxPipelineDepth];
  size_t n{0};
};

HopGeom hopGeom(const WireCodec& codec, size_t elems, int depth) {
  HopGeom g;
  g.n = subSpans(codec, elems, depth, g.spans);
  return g;
}

// Encode state shared by both phases: residual/scratch slices resolved
// per send block.
struct EfState {
  float* res{nullptr};  // count floats, plan-persistent (slot 3)
  float* tmp{nullptr};  // maxBlockElems floats, per-call scratch (slot 4)
};

// In-order send sequencer for one hop's worker-posted sends: whichever
// worker closes the lowest-index gap posts every consecutive ready sub.
// Sends therefore hit the pair in sub order no matter which encode
// finishes first — fault-injection draws and the wire telemetry see ONE
// deterministic tx order per pair, run to run. Lives on the op thread's
// stack; the per-hop encode-ticket join keeps every job from outliving
// the frame.
struct HopTx {
  std::mutex mu;
  size_t next{0};
  size_t n{0};
  std::exception_ptr err;  // first failed send; later subs stop posting
  bool ready[kMaxPipelineDepth] = {};
  SubSpan spans[kMaxPipelineDepth];
  uint64_t slots[kMaxPipelineDepth];
  transport::UnboundBuffer* buf{nullptr};
  size_t base{0};
  int right{-1};

  void complete(size_t j) {
    std::lock_guard<std::mutex> guard(mu);
    ready[j] = true;
    while (next < n && ready[next]) {
      const SubSpan& ss = spans[next];
      if (err == nullptr) {
        try {
          buf->send(right, slots[next], base + ss.wireOff, ss.wireBytes);
        } catch (...) {
          // Pool jobs must not throw (a worker-thread escape is
          // std::terminate): latch the pair's error for the op
          // thread's encode join to rethrow.
          err = std::current_exception();
        }
      }
      next++;
    }
  }
};

// Encode the hop's stream into txSeg and send each sub as soon as its
// encode finishes. With pool workers (and depth > 1) each sub is one
// async encode(+adopt)+send job — the worker posts the send through
// `htx`, the op thread keeps going, and the returned ticket count is
// joined at hop end. Otherwise the subs run synchronously on the op
// thread: at depth 1 the single sub shards across the pool lanes
// (maximum lanes on one stream), at depth > 1 with no workers each sub
// encodes and ships in turn (same wire bytes, honest serial phases).
// `adopt` != nullptr additionally decodes each encoded sub back into
// place (the allgather owner's roundtrip; may alias `src`).
size_t encodeAndSend(const WireCodec& codec, const HopGeom& sg,
                     const float* src, float* res, float* tmp,
                     float* adopt, uint8_t* txSeg,
                     transport::UnboundBuffer* txBuf, size_t txBase,
                     int right, Slot slot, uint64_t hopBase, int depth,
                     HopTx* htx, codec::CodecPool::Ticket* tickets) {
  codec::CodecPool& pool = codec::CodecPool::instance();
  const size_t lanes = static_cast<size_t>(codec::codecThreads());
  if (depth <= 1 || sg.n <= 1 || pool.workers() == 0) {
    for (size_t j = 0; j < sg.n; j++) {
      const SubSpan& ss = sg.spans[j];
      {
        PhaseScope ps(Phase::kPack);
        wireEncode(codec, src + ss.elemOff, txSeg + ss.wireOff, ss.elems,
                   lanes, res != nullptr ? res + ss.elemOff : nullptr,
                   tmp);
        if (adopt != nullptr) {
          wireDecode(codec, txSeg + ss.wireOff, adopt + ss.elemOff,
                     ss.elems, lanes);
        }
      }
      const uint64_t s = slot.offset(hopBase + j).value();
      PhaseScope ps(Phase::kPost, right, s, ss.wireBytes);
      txBuf->send(right, s, txBase + ss.wireOff, ss.wireBytes);
    }
    return 0;
  }
  htx->n = sg.n;
  htx->buf = txBuf;
  htx->base = txBase;
  htx->right = right;
  for (size_t j = 0; j < sg.n; j++) {
    htx->spans[j] = sg.spans[j];
    htx->slots[j] = slot.offset(hopBase + j).value();
  }
  for (size_t j = 0; j < sg.n; j++) {
    const SubSpan ss = sg.spans[j];  // by value: the job may outlive j
    tickets[j] = pool.submit([&codec, ss, j, src, res, tmp, adopt, txSeg,
                              htx] {
      wireEncode(codec, src + ss.elemOff, txSeg + ss.wireOff, ss.elems,
                 /*shards=*/1, res != nullptr ? res + ss.elemOff : nullptr,
                 tmp != nullptr ? tmp + ss.elemOff : nullptr);
      if (adopt != nullptr) {
        wireDecode(codec, txSeg + ss.wireOff, adopt + ss.elemOff, ss.elems,
                   /*shards=*/1);
      }
      htx->complete(j);
    });
  }
  return sg.n;
}

// Join a hop's async encode tickets. join() is the happy path: the
// residual (non-overlapped) encode time is all that stays on the op
// thread's pack bucket — the sends were already posted by the workers,
// in sub order — and a send failure latched in the sequencer rethrows
// here, BEFORE the caller blocks on send completions that were never
// posted. The destructor is the unwind net: the jobs reference this
// frame's HopTx and scratch, so no exception (a dead peer surfacing in
// drainHop) may leak them past the frame.
struct EncodeJoin {
  const codec::CodecPool::Ticket* tickets;
  HopTx* htx;
  size_t n{0};

  void join() {
    if (n != 0) {
      codec::CodecPool& pool = codec::CodecPool::instance();
      PhaseScope ps(Phase::kPack);
      for (size_t j = 0; j < n; j++) {
        pool.wait(tickets[j]);
      }
      n = 0;
    }
    // All jobs finished (pool.wait ordered us after complete()), so the
    // latch is stable without the sequencer mutex.
    if (htx->err != nullptr) {
      std::rethrow_exception(htx->err);
    }
  }

  ~EncodeJoin() {
    codec::CodecPool& pool = codec::CodecPool::instance();
    for (size_t j = 0; j < n; j++) {
      pool.wait(tickets[j]);
    }
  }
};

// Drain `nStaged` staged sub-arrivals by slot, dispatching each to
// `perSub(j)` the moment it lands (decode-on-arrival); then join the
// issued tickets under `joinPhase` and reap `nFused` fused arrivals.
template <typename PerSub>
void drainHop(transport::UnboundBuffer* rxBuf,
              transport::UnboundBuffer* workBuf, size_t nStaged,
              size_t nFused, size_t nSubs, Slot slot, uint64_t hopBase,
              int left, std::chrono::milliseconds timeout, Phase joinPhase,
              const PerSub& perSub) {
  codec::CodecPool& pool = codec::CodecPool::instance();
  codec::CodecPool::Ticket tickets[kMaxPipelineDepth] = {};
  // Unwind net: a decode job captures `perSub` — this frame — so a
  // throwing wait below (peer death mid-hop) must join issued jobs
  // before unwinding.
  struct Join {
    codec::CodecPool& pool;
    const codec::CodecPool::Ticket* tickets;
    size_t n{0};
    ~Join() {
      for (size_t i = 0; i < n; i++) {
        pool.wait(tickets[i]);
      }
    }
  } join{pool, tickets};
  const uint64_t base = slot.offset(hopBase).value();
  for (size_t i = 0; i < nStaged; i++) {
    uint64_t landed = 0;
    {
      PhaseScope ps(Phase::kWireWait, left, base, 0);
      rxBuf->waitRecvSlot(nullptr, &landed, timeout);
    }
    const uint64_t j = landed - base;
    TC_ENFORCE_LT(j, static_cast<uint64_t>(nSubs),
                  "wire ring: arrival outside the hop's slot window");
    if (pool.workers() == 0) {
      // No pool: the kernel runs inline right here — attribute it to
      // the join phase it would otherwise have been waited under.
      PhaseScope ps(joinPhase);
      perSub(static_cast<size_t>(j));
    } else {
      tickets[i] =
          pool.submit([&perSub, j] { perSub(static_cast<size_t>(j)); });
      join.n = i + 1;
    }
  }
  for (size_t i = 0; i < nFused; i++) {
    PhaseScope ps(Phase::kWireWait, left, base, 0);
    workBuf->waitRecv(nullptr, timeout);
  }
  PhaseScope ps(joinPhase);
  for (size_t i = 0; i < join.n; i++) {
    pool.wait(tickets[i]);
  }
  join.n = 0;
}

// Ring reduce-scatter phase with pipelined quantized hops. Identical
// block walk to the per-codec rings: after P-1 steps rank r owns block
// (r + 1 + startShift) mod P fully reduced in float32. startShift 0
// feeds the allreduce allgather; -1 lands block r on rank r.
void wireRingRsPhase(Context* ctx, const WireCodec& codec, float* work,
                     const Blocks& blocks, Slot slot, int startShift,
                     std::chrono::milliseconds timeout,
                     transport::UnboundBuffer* workBuf,
                     plan::LazyStage& rxStage, uint8_t* tx,
                     transport::UnboundBuffer* txBuf, size_t wireBlock,
                     const EfState& ef) {
  const int rank = ctx->rank();
  const int size = ctx->size();
  const int right = (rank + 1) % size;
  const int left = (rank - 1 + size) % size;
  const int steps = size - 1;
  const int depth = codec::codecPipelineDepth();

  auto blockElems = [&](int b) { return blocks.bytes[b] / sizeof(float); };
  auto blockStart = [&](int b) {
    return blocks.offset[b] / sizeof(float);
  };

  // Fuse-eligibility of the source pair, resolved once; unit alignment
  // is checked per sub-block.
  const bool pairFuse =
      codec.fusedAccumulate != nullptr &&
      collectives_detail::fuseRecvReduce(ctx, /*fuseOk=*/true,
                                         codec.unitBytes, left);

  for (int step = 0; step < steps; step++) {
    const int sendBlock = (rank + startShift - step + 2 * size) % size;
    const int recvBlock = (rank + startShift - step - 1 + 2 * size) % size;
    const int parity = step % 2;
    const uint64_t hopBase = static_cast<uint64_t>(step) * depth;
    const HopGeom sg = hopGeom(codec, blockElems(sendBlock), depth);
    const HopGeom rg = hopGeom(codec, blockElems(recvBlock), depth);

    // Post every sub-recv before sending: arrivals complete in wire
    // order, not posting order, and the decode keys off the slot.
    size_t nFused = 0;
    size_t nStaged = 0;
    {
      PhaseScope ps(Phase::kPost);
      for (size_t j = 0; j < rg.n; j++) {
        const SubSpan& ss = rg.spans[j];
        const uint64_t s = slot.offset(hopBase + j).value();
        const bool fuse = pairFuse && ss.elems > 0 &&
                          ss.elems % codec.unitElems == 0;
        if (fuse) {
          workBuf->recvReduceTyped(
              left, s, codec.fusedAccumulate, codec.unitBytes,
              codec.unitElems * sizeof(float),
              (blockStart(recvBlock) + ss.elemOff) * sizeof(float),
              ss.wireBytes);
          nFused++;
        } else {
          rxStage.buf()->recv(left, s,
                              static_cast<size_t>(parity) * wireBlock +
                                  ss.wireOff,
                              ss.wireBytes);
          nStaged++;
        }
      }
    }

    HopTx htx;
    codec::CodecPool::Ticket txTickets[kMaxPipelineDepth] = {};
    EncodeJoin txJoin{txTickets, &htx};
    txJoin.n = encodeAndSend(
        codec, sg, work + blockStart(sendBlock),
        ef.res != nullptr ? ef.res + blockStart(sendBlock) : nullptr,
        ef.tmp, /*adopt=*/nullptr,
        tx + static_cast<size_t>(parity) * wireBlock, txBuf,
        static_cast<size_t>(parity) * wireBlock, right, slot, hopBase,
        depth, &htx, txTickets);

    const uint8_t* rxSeg =
        nStaged != 0 ? reinterpret_cast<const uint8_t*>(rxStage.data()) +
                           static_cast<size_t>(parity) * wireBlock
                     : nullptr;
    float* acc = work + blockStart(recvBlock);
    drainHop(rxStage.buf(), workBuf, nStaged, nFused, rg.n, slot, hopBase,
             left, timeout, Phase::kReduce, [&](size_t j) {
               const SubSpan& ss = rg.spans[j];
               wireAccumulate(codec, acc + ss.elemOff, rxSeg + ss.wireOff,
                              ss.elems,
                              depth <= 1
                                  ? static_cast<size_t>(
                                        codec::codecThreads())
                                  : 1);
             });

    txJoin.join();
    PhaseScope ps(Phase::kWireWait);
    for (size_t j = 0; j < sg.n; j++) {
      txBuf->waitSend(timeout);
    }
  }
}

// Allgather phase: rank r owns reduced block (r+1). The owner encodes
// its block ONCE (the call's only origin encode in this phase — error
// feedback applies) and adopts the decoded values; every later hop
// forwards the received stream verbatim straight from the rx stage
// (inexact codecs) or re-encodes the adopted values (exact roundtrip
// codecs on fused pairs), so all ranks decode bit-identical bytes.
void wireRingAgPhase(Context* ctx, const WireCodec& codec, float* work,
                     const Blocks& blocks, Slot slot,
                     std::chrono::milliseconds timeout,
                     transport::UnboundBuffer* workBuf,
                     plan::LazyStage& rxStage, uint8_t* tx,
                     transport::UnboundBuffer* txBuf, size_t wireBlock,
                     const EfState& ef) {
  const int rank = ctx->rank();
  const int size = ctx->size();
  const int right = (rank + 1) % size;
  const int left = (rank - 1 + size) % size;
  const int steps = size - 1;
  const int depth = codec::codecPipelineDepth();

  auto blockElems = [&](int b) { return blocks.bytes[b] / sizeof(float); };
  auto blockStart = [&](int b) {
    return blocks.offset[b] / sizeof(float);
  };

  // The fused-decode arm needs an exact re-encode for the forward leg;
  // fusedDecode is only populated on codecs where that holds (bf16).
  const bool pairFuse =
      codec.fusedDecode != nullptr && codec.exactReencode &&
      collectives_detail::fuseRecvReduce(ctx, /*fuseOk=*/true,
                                         codec.unitBytes, left);

  // Owner role: quantize own block into tx parity 0 and adopt the
  // decoded values (consensus: every rank holds decode(stream)). With
  // ring steps the encode folds into step 0 below — per sub, as
  // encode+adopt+send jobs — so the first hop's wire time absorbs it;
  // only a single-rank group runs it here.
  const int own = (rank + 1) % size;
  if (steps == 0) {
    PhaseScope ps(Phase::kPack);
    wireEncode(codec, work + blockStart(own), tx, blockElems(own),
               static_cast<size_t>(codec::codecThreads()),
               ef.res != nullptr ? ef.res + blockStart(own) : nullptr,
               ef.tmp);
    wireDecode(codec, tx, work + blockStart(own), blockElems(own),
               static_cast<size_t>(codec::codecThreads()));
    return;
  }

  const uint64_t agBase = static_cast<uint64_t>(steps) * depth;
  for (int step = 0; step < steps; step++) {
    const int sendBlock = (rank + 1 - step + 2 * size) % size;
    const int recvBlock = (rank - step + 2 * size) % size;
    const int parity = step % 2;
    const uint64_t hopBase = agBase + static_cast<uint64_t>(step) * depth;
    const HopGeom sg = hopGeom(codec, blockElems(sendBlock), depth);
    const HopGeom rg = hopGeom(codec, blockElems(recvBlock), depth);

    size_t nFused = 0;
    size_t nStaged = 0;
    {
      PhaseScope ps(Phase::kPost);
      for (size_t j = 0; j < rg.n; j++) {
        const SubSpan& ss = rg.spans[j];
        const uint64_t s = slot.offset(hopBase + j).value();
        const bool fuse = pairFuse && ss.elems > 0 &&
                          ss.elems % codec.unitElems == 0;
        if (fuse) {
          workBuf->recvReduceTyped(
              left, s, codec.fusedDecode, codec.unitBytes,
              codec.unitElems * sizeof(float),
              (blockStart(recvBlock) + ss.elemOff) * sizeof(float),
              ss.wireBytes);
          nFused++;
        } else {
          rxStage.buf()->recv(left, s,
                              static_cast<size_t>(parity) * wireBlock +
                                  ss.wireOff,
                              ss.wireBytes);
          nStaged++;
        }
      }
    }

    HopTx htx;
    codec::CodecPool::Ticket txTickets[kMaxPipelineDepth] = {};
    EncodeJoin txJoin{txTickets, &htx};
    if (step == 0) {
      // Owner encode: quantize own block (the call's only origin
      // encode in this phase — error feedback applies), adopt the
      // decoded values, and ship each sub as it finishes.
      txJoin.n = encodeAndSend(
          codec, sg, work + blockStart(own),
          ef.res != nullptr ? ef.res + blockStart(own) : nullptr, ef.tmp,
          /*adopt=*/work + blockStart(own), tx, txBuf, /*txBase=*/0,
          right, slot, hopBase, depth, &htx, txTickets);
    } else if (pairFuse) {
      // Fused pairs consumed last hop's stream in the transport;
      // re-encode the adopted values (exact, so the forwarded bytes
      // match the verbatim stream bit-for-bit). No residual: a forward
      // re-encode is not an origin encode.
      txJoin.n = encodeAndSend(
          codec, sg, work + blockStart(sendBlock),
          /*res=*/nullptr, /*tmp=*/nullptr,
          /*adopt=*/nullptr,
          tx + static_cast<size_t>(parity) * wireBlock, txBuf,
          static_cast<size_t>(parity) * wireBlock, right, slot, hopBase,
          depth, &htx, txTickets);
    } else {
      // Forward the bytes received last hop verbatim, directly from
      // the rx stage's previous parity region — the per-hop send drain
      // below is what keeps that region stable while it ships.
      const size_t prev = static_cast<size_t>((step - 1) % 2) * wireBlock;
      for (size_t j = 0; j < sg.n; j++) {
        const SubSpan& ss = sg.spans[j];
        const uint64_t s = slot.offset(hopBase + j).value();
        PhaseScope ps(Phase::kPost, right, s, ss.wireBytes);
        rxStage.buf()->send(right, s, prev + ss.wireOff, ss.wireBytes);
      }
    }

    const uint8_t* rxSeg =
        nStaged != 0 ? reinterpret_cast<const uint8_t*>(rxStage.data()) +
                           static_cast<size_t>(parity) * wireBlock
                     : nullptr;
    float* dst = work + blockStart(recvBlock);
    drainHop(rxStage.buf(), workBuf, nStaged, nFused, rg.n, slot, hopBase,
             left, timeout, Phase::kUnpack, [&](size_t j) {
               const SubSpan& ss = rg.spans[j];
               wireDecode(codec, rxSeg + ss.wireOff, dst + ss.elemOff,
                          ss.elems,
                          depth <= 1
                              ? static_cast<size_t>(codec::codecThreads())
                              : 1);
             });

    txJoin.join();
    PhaseScope ps(Phase::kWireWait);
    for (size_t j = 0; j < sg.n; j++) {
      const bool fromRx = step != 0 && !pairFuse;
      (fromRx ? rxStage.buf() : txBuf)->waitSend(timeout);
    }
  }
}

size_t maxStreamBlock(const WireCodec& codec, const Blocks& blocks,
                      size_t* maxElemsOut) {
  size_t maxElems = 0;
  for (size_t b : blocks.bytes) {
    maxElems = std::max(maxElems, b / sizeof(float));
  }
  if (maxElemsOut != nullptr) {
    *maxElemsOut = maxElems;
  }
  return std::max(codec.wire(maxElems), size_t(1));
}

EfState efState(plan::Plan& plan, size_t count, size_t maxBlockElems) {
  EfState ef;
  if (!wireErrorFeedback() || count == 0) {
    return ef;
  }
  bool fresh = false;
  ef.res = reinterpret_cast<float*>(
      plan.scratch(3, count * sizeof(float), &fresh));
  if (fresh) {
    std::memset(ef.res, 0, count * sizeof(float));
  }
  ef.tmp = reinterpret_cast<float*>(
      plan.scratch(4, std::max(maxBlockElems, size_t(1)) * sizeof(float)));
  return ef;
}

}  // namespace

void wireRingAllreduce(Context* ctx, plan::Plan& plan,
                       const WireCodec& codec, char* workBytes,
                       size_t count, Slot slot,
                       std::chrono::milliseconds timeout) {
  float* work = reinterpret_cast<float*>(workBytes);
  const Blocks& blocks = plan.blocks(
      0, [&] { return evenBlocks(count, ctx->size(), sizeof(float)); });
  size_t maxBlockElems = 0;
  const size_t wireBlock = maxStreamBlock(codec, blocks, &maxBlockElems);

  // Wire staging: tx double-buffered (a sent stream must stay valid
  // until its waitSend); rx double-buffered, lazily acquired (untouched
  // when every hop fuses). All plan-backed: warm arena + registration
  // on the steady-state replay.
  auto txStage = plan.stage(1, 2 * wireBlock);
  uint8_t* tx = reinterpret_cast<uint8_t*>(txStage.data);
  plan::LazyStage rxStage(plan, 2, 2 * wireBlock);
  auto* workBuf = plan.userBuf(0, work, count * sizeof(float));
  const EfState ef = efState(plan, count, maxBlockElems);

  wireRingRsPhase(ctx, codec, work, blocks, slot, /*startShift=*/0,
                  timeout, workBuf, rxStage, tx, txStage.buf, wireBlock,
                  ef);
  wireRingAgPhase(ctx, codec, work, blocks, slot, timeout, workBuf,
                  rxStage, tx, txStage.buf, wireBlock, ef);
}

void wireRingReduceScatter(Context* ctx, plan::Plan& plan,
                           const WireCodec& codec, char* workBytes,
                           transport::UnboundBuffer* workBuf,
                           const Blocks& blocks, Slot slot,
                           std::chrono::milliseconds timeout) {
  float* work = reinterpret_cast<float*>(workBytes);
  size_t maxBlockElems = 0;
  const size_t wireBlock = maxStreamBlock(codec, blocks, &maxBlockElems);
  size_t count = 0;
  for (size_t b : blocks.bytes) {
    count += b / sizeof(float);
  }
  // Stage slots 0/1 here: the entry's work copy owns slot 2
  // (kStageRsWork in collectives_ring.cc), and these plans never meet
  // the binomial/ring staging (different algorithm keys).
  auto txStage = plan.stage(0, 2 * wireBlock);
  uint8_t* tx = reinterpret_cast<uint8_t*>(txStage.data);
  plan::LazyStage rxStage(plan, 1, 2 * wireBlock);
  const EfState ef = efState(plan, count, maxBlockElems);
  wireRingRsPhase(ctx, codec, work, blocks, slot, /*startShift=*/-1,
                  timeout, workBuf, rxStage, tx, txStage.buf, wireBlock,
                  ef);
}

}  // namespace algorithms
}  // namespace tpucoll
