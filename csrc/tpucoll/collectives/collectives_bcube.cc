// Bcube (grouped hypercube) allreduce, generalized to mixed radix.
//
// The reference's AllreduceBcube (gloo/allreduce_bcube.h:68-264) factors
// the group into base-B hypercube stages; this build generalizes the idea
// to an arbitrary factorization P = G_0 * G_1 * ... * G_{k-1} (prime
// factors by default), so every rank count gets an exact schedule — no
// power-of-B restriction and no fold step.
//
// Reduce-scatter phase, step s: ranks sharing all mixed-radix digits
// except digit s form a group of G_s members. The current block window
// splits into G_s parts; each member keeps the part indexed by its own
// digit, sends part j to member j, and reduces the G_s - 1 contributions
// it receives (staged per sender, reduced in arrival order) into its kept
// part. After k steps each rank holds one fully reduced block. The
// allgather phase replays the steps in reverse with in-place receives.
//
// Latency is sum(G_s - 1) messages per phase over k steps versus the
// ring's P - 1; bandwidth matches the ring's optimal 2N(P-1)/P.
#include <cstring>
#include <unordered_map>

#include "tpucoll/collectives/algorithms.h"
#include "tpucoll/collectives/detail.h"
#include "tpucoll/collectives/plan.h"
#include "tpucoll/common/profile.h"

namespace tpucoll {
namespace algorithms {

using collectives_detail::Blocks;
using collectives_detail::evenBlocks;
using profile::Phase;
using profile::PhaseScope;

namespace {

std::vector<int> primeFactors(int n) {
  std::vector<int> factors;
  for (int p = 2; p * p <= n; p++) {
    while (n % p == 0) {
      factors.push_back(p);
      n /= p;
    }
  }
  if (n > 1) {
    factors.push_back(n);
  }
  return factors;
}

}  // namespace

void bcubeAllreduce(Context* ctx, plan::Plan& plan, char* work,
                    size_t count, size_t elsize, ReduceFn fn, Slot slot,
                    std::chrono::milliseconds timeout, bool fuseOk) {
  const int rank = ctx->rank();
  const int size = ctx->size();
  const size_t nbytes = count * elsize;
  const std::vector<int> radices = primeFactors(size);
  const int numSteps = static_cast<int>(radices.size());

  const Blocks& blocks =
      plan.blocks(0, [&] { return evenBlocks(count, size, elsize); });
  auto rangeOff = [&](int first) { return blocks.offset[first]; };
  auto rangeBytes = [&](int first, int n) {
    return blocks.rangeBytes(first, n);
  };

  auto* workBuf = plan.userBuf(0, work, nbytes);
  // Fused receive-reduce applies to RADIX-2 steps only: with one sender
  // the kept part is written by exactly one combine stream, disjoint from
  // the part being sent. Steps with g > 2 have g-1 senders all reducing
  // into the SAME kept part; fusing those would let a stash-hit combine
  // (poster's thread) race a loop-thread combine, so they stay on the
  // arrival-ordered scratch schedule. (P = 2^k therefore fuses fully.)
  auto canFuse = [&](int src) {
    return collectives_detail::fuseRecvReduce(ctx, fuseOk, elsize, src);
  };
  // Per-sender staging can need up to winCount * ceil(count/size) elements
  // at a step (uneven blocks make one part slightly larger than the
  // window's average); nbytes + size*elsize safely covers every step.
  // Lazily acquired: an all-radix-2 fused run never touches it.
  plan::LazyStage stage(plan, 1, nbytes + size * elsize);

  // Mixed-radix digits of this rank: rank = sum(digit_s * stride_s).
  std::vector<int> stride(numSteps), digit(numSteps);
  {
    int acc = 1;
    for (int s = 0; s < numSteps; s++) {
      stride[s] = acc;
      digit[s] = (rank / acc) % radices[s];
      acc *= radices[s];
    }
  }
  auto member = [&](int s, int j) {
    return rank + (j - digit[s]) * stride[s];
  };

  // (step, senderDigit, phase) -> unique sub-slot.
  int maxRadix = 2;
  for (int g : radices) {
    maxRadix = std::max(maxRadix, g);
  }
  auto stepSlot = [&](int phase, int s, int j) {
    return slot
        .offset(uint64_t(phase * numSteps + s) * maxRadix + uint64_t(j))
        .value();
  };

  // --- reduce-scatter: window narrows by G_s each step ---
  int winStart = 0;
  int winCount = size;
  std::vector<int> winStartAt(numSteps), winCountAt(numSteps);
  for (int s = 0; s < numSteps; s++) {
    const int g = radices[s];
    const int part = winCount / g;
    winStartAt[s] = winStart;
    winCountAt[s] = winCount;
    const int myPartStart = winStart + digit[s] * part;
    const size_t partBytes = rangeBytes(myPartStart, part);

    // Sends: part j of the window goes to group member j.
    for (int j = 0; j < g; j++) {
      if (j == digit[s]) {
        continue;
      }
      const int partStart = winStart + j * part;
      PhaseScope ps(Phase::kPost, member(s, j), stepSlot(0, s, digit[s]),
                    rangeBytes(partStart, part));
      workBuf->send(member(s, j), stepSlot(0, s, digit[s]),
                    rangeOff(partStart), rangeBytes(partStart, part));
    }
    const bool fused =
        g == 2 && canFuse(member(s, 1 - digit[s]));  // single sender
    if (fused) {
      {
        PhaseScope ps(Phase::kPost);
        workBuf->recvReduce(member(s, 1 - digit[s]),
                            stepSlot(0, s, 1 - digit[s]), fn, elsize,
                            rangeOff(myPartStart), partBytes);
      }
      PhaseScope ps(Phase::kWireWait, member(s, 1 - digit[s]),
                    stepSlot(0, s, 1 - digit[s]), partBytes);
      workBuf->waitRecv(nullptr, timeout);
    } else {
      // Receives: each sender's contribution to MY part, staged per sender
      // (slot j at scratch offset j * partBytes) so concurrent arrivals
      // never share memory; reduced in arrival order via the source rank.
      std::unordered_map<int, int> senderDigit;  // src rank -> j
      {
        PhaseScope ps(Phase::kPost);
        for (int j = 0; j < g; j++) {
          if (j == digit[s]) {
            continue;
          }
          senderDigit[member(s, j)] = j;
          stage.buf()->recv(member(s, j), stepSlot(0, s, j),
                            size_t(j) * partBytes, partBytes);
        }
      }
      for (int n = 0; n < g - 1; n++) {
        int src = -1;
        {
          PhaseScope ps(Phase::kWireWait);
          stage.buf()->waitRecv(&src, timeout);
        }
        const int j = senderDigit.at(src);
        if (partBytes > 0) {
          PhaseScope ps(Phase::kReduce);
          fn(work + rangeOff(myPartStart),
             stage.data() + size_t(j) * partBytes, partBytes / elsize);
        }
      }
    }
    {
      PhaseScope ps(Phase::kWireWait);
      for (int n = 0; n < g - 1; n++) {
        workBuf->waitSend(timeout);
      }
    }
    winStart = myPartStart;
    winCount = part;
  }

  // --- allgather: replay steps in reverse, windows merge G_s-fold ---
  for (int s = numSteps - 1; s >= 0; s--) {
    const int g = radices[s];
    const int stepWinStart = winStartAt[s];
    const int part = winCountAt[s] / g;
    // My current window is part digit[s] of the step-s window; send it to
    // every group member and receive their parts in place.
    for (int j = 0; j < g; j++) {
      if (j == digit[s]) {
        continue;
      }
      PhaseScope ps(Phase::kPost, member(s, j), stepSlot(1, s, digit[s]),
                    rangeBytes(winStart, winCount));
      workBuf->send(member(s, j), stepSlot(1, s, digit[s]),
                    rangeOff(winStart), rangeBytes(winStart, winCount));
    }
    {
      PhaseScope ps(Phase::kPost);
      for (int j = 0; j < g; j++) {
        if (j == digit[s]) {
          continue;
        }
        const int partStart = stepWinStart + j * part;
        workBuf->recv(member(s, j), stepSlot(1, s, j), rangeOff(partStart),
                      rangeBytes(partStart, part));
      }
    }
    if (g == 2) {
      // Radix-2 step: exactly one sender, so the arrival is attributable.
      const int j = 1 - digit[s];
      const int partStart = stepWinStart + j * part;
      PhaseScope ps(Phase::kWireWait, member(s, j), stepSlot(1, s, j),
                    rangeBytes(partStart, part));
      workBuf->waitRecv(nullptr, timeout);
    } else {
      PhaseScope ps(Phase::kWireWait);
      for (int n = 0; n < g - 1; n++) {
        workBuf->waitRecv(nullptr, timeout);
      }
    }
    {
      PhaseScope ps(Phase::kWireWait);
      for (int n = 0; n < g - 1; n++) {
        workBuf->waitSend(timeout);
      }
    }
    winStart = stepWinStart;
    winCount = winCountAt[s];
  }
}

}  // namespace algorithms
}  // namespace tpucoll
