// Ring collectives with int4 block-quantized wire compression for
// float32 sum payloads — the aggressive end of the lossy-wire family
// (~8x fewer bytes than float32, ~2x fewer than q8).
//
// Wire format (math.h): consecutive units of [float32 scale]
// [ceil(B/2) packed nibble bytes], B = TPUCOLL_Q4_BLOCK (default 256).
// Codes are biased nibbles (clip(round(x/scale), -7, 7) + 8), element i
// in byte i/2 — even index low nibble, odd index high; a dangling odd
// tail leaves the high nibble zero.
//
// Precision contract (docs/algorithms.md + docs/errors.md):
//  - accumulation stays float32; only wire hops quantize, at
//    |x - decode(x)| <= max|block| / 14 per element per hop — ~18x
//    coarser than q8, which is why the tuner elects this arm only
//    where measurement proves it wins and kAuto never does;
//  - error feedback (TPUCOLL_WIRE_EF, wire_ring.h) folds each origin
//    encode's error into the next call — at 4 bits it is what keeps
//    the repeated-reduction error bounded instead of biased;
//  - the allgather forwards the owner's stream verbatim (like q8, the
//    scale roundtrip double-rounds), so results are bit-identical on
//    every rank;
//  - float32 + sum only; TPUCOLL_Q4_BLOCK and TPUCOLL_CODEC_PIPELINE
//    must match on every rank.
//
// The schedule itself lives in wire_ring.cc (one pipelined engine for
// every codec); this file binds it to the q4 descriptor.
#include "tpucoll/collectives/algorithms.h"
#include "tpucoll/collectives/wire_ring.h"

namespace tpucoll {
namespace algorithms {

// Same compile-time pin as q8: the fused arm's recvReduceTyped element
// is one whole q4 unit (scale + packed codes).
static_assert(transport::kMaxCombineElsize >=
                  kQ4ScaleBytes + (kQ4MaxBlockElems + 1) / 2,
              "q4 wire units must fit the transport combine ceiling "
              "(raise kMaxCombineElsize alongside kQ4MaxBlockElems)");

void q4WireRingAllreduce(Context* ctx, plan::Plan& plan, char* workBytes,
                         size_t count, Slot slot,
                         std::chrono::milliseconds timeout) {
  wireRingAllreduce(ctx, plan, q4WireCodec(), workBytes, count, slot,
                    timeout);
}

void q4WireRingReduceScatter(Context* ctx, plan::Plan& plan,
                             char* workBytes,
                             transport::UnboundBuffer* workBuf,
                             const collectives_detail::Blocks& blocks,
                             Slot slot,
                             std::chrono::milliseconds timeout) {
  wireRingReduceScatter(ctx, plan, q4WireCodec(), workBytes, workBuf,
                        blocks, slot, timeout);
}

}  // namespace algorithms
}  // namespace tpucoll
