// New-style collective API: free functions over options structs, untyped at
// the schedule level (a ReduceFn is fetched once per call). Mirrors the
// reference's function+options surface (e.g. gloo/allreduce.h:193,
// gloo/broadcast.h, gloo/alltoallv.h) with the same semantics:
//  - every collective on a context that may run concurrently with another
//    must use a distinct tag;
//  - all ranks must pass identical (count, dtype, op, tag);
//  - timeouts default to the context timeout; failures throw IoException.
//
// Algorithms (original schedules, validated against the complexity notes in
// reference docs/algorithms.md):
//   barrier          dissemination, ceil(log2 P) rounds
//   broadcast        binomial tree over virtual ranks rooted at `root`
//   allreduce        ring reduce-scatter + ring allgather (bandwidth-optimal)
//   reduce           binomial reduction tree to root
//   gather(v)        direct sends to root
//   scatter          direct sends from root
//   allgather(v)     ring
//   alltoall(v)      rotated pairwise exchange
//   reduce_scatter   ring reduce-scatter with per-rank counts
#pragma once

#include <chrono>
#include <cstddef>
#include <vector>

#include "tpucoll/context.h"
#include "tpucoll/math.h"
#include "tpucoll/types.h"

namespace tpucoll {

struct CollectiveOptions {
  Context* context = nullptr;
  uint32_t tag = 0;
  // Zero means "use the context default".
  std::chrono::milliseconds timeout{0};
};

// Hierarchical arm for the schedules without an algorithm-family of
// their own (barrier/broadcast/allgather): kAuto is the flat schedule;
// kHier composes the intra-host shm plane with a leader-only inter-host
// exchange over the context's split sub-groups (group/hier.h) and
// degrades to the flat schedule on a flat topology.
enum class HierDispatch : uint8_t {
  kAuto = 0,
  kHier = 1,
};

struct BarrierOptions : CollectiveOptions {
  HierDispatch algorithm = HierDispatch::kAuto;
};
void barrier(BarrierOptions& opts);

struct BroadcastOptions : CollectiveOptions {
  void* buffer = nullptr;  // in on root, out elsewhere
  size_t count = 0;
  DataType dtype = DataType::kFloat32;
  int root = 0;
  HierDispatch algorithm = HierDispatch::kAuto;
};
void broadcast(BroadcastOptions& opts);

enum class AllreduceAlgorithm : uint8_t {
  // Ring for bandwidth-bound payloads, halving-doubling for latency-bound
  // ones, matching the reference's RING/BCUBE split (gloo/allreduce.h:
  // 38-42) with an automatic default. kAuto consults the context's
  // installed tuning table first (tuning/tuning_table.h: measured
  // per-deployment crossovers) and falls back to the compile-time
  // thresholds below when no table is loaded.
  kAuto = 0,
  kRing = 1,
  kHalvingDoubling = 2,
  kBcube = 3,
  // bfloat16 wire compression (float32 payloads only): halves bytes on
  // the wire; accumulation stays float32; all ranks receive identical
  // results. Opt-in — see collectives_compressed.cc for the precision
  // contract.
  kRingBf16Wire = 4,
  // Recursive doubling: log2(P) full-vector exchange rounds (vs the
  // halving-doubling pair's 2 log2 P) — the alpha-dominated tiny-payload
  // tier. Non-power-of-2 groups take a pre/post fold: odd ranks of the
  // first 2*(P-p2) fold into their even partners, sit out the rounds,
  // and receive the result. Crossover: TPUCOLL_ALLREDUCE_RD_MAX.
  kRecursiveDoubling = 5,
  // The two non-power-of-2 halving-doubling sub-variants as first-class
  // choices (kHalvingDoubling picks between them by TPUCOLL_HD_NP2 /
  // installed tuning table): the pre/post fold, and the binary-blocks
  // decomposition. On power-of-2 groups both degenerate to the same
  // single-block walk. Exposed so the tuner can sweep each arm and a
  // tuned table can elect one directly.
  kHdFold = 6,
  kHdBlocks = 7,
  // int8 block-quantized wire compression (float32 payloads only):
  // ~4x fewer wire bytes than float32 (~2x vs bf16-wire) at ~2.4
  // decimal digits of per-block precision; accumulation stays float32;
  // all ranks receive identical results (the allgather phase forwards
  // the final quantized stream verbatim). Opt-in — see
  // collectives_q8.cc for the precision contract and TPUCOLL_Q8_BLOCK.
  kRingQ8Wire = 8,
  // kAuto that is ADDITIONALLY allowed to elect the lossy wire codecs
  // (bf16/q8) from the installed tuning table — the caller's explicit
  // opt-in to reduced wire precision on float32 sum allreduces. For any
  // other (dtype, op, customFn) shape, or when no wire arm measures
  // faster, it dispatches exactly like kAuto. Untuned fallback: the
  // bandwidth tier (payloads past TPUCOLL_ALLREDUCE_HD_MAX) rides
  // kRingQ8Wire, the latency tiers stay lossless.
  kAutoLossyWire = 9,
  // Topology-aware hierarchical composition (group/hier.h): intra-host
  // allreduce over the shm plane, leader-only exchange across hosts,
  // intra-host broadcast. Electable by kAuto from a tuned table when
  // the topology is non-flat (TPUCOLL_HIER_AUTO gates the election);
  // explicit requests on a flat topology dispatch as kAuto. Reduction
  // ORDER differs from the flat schedules (docs/topology.md precision
  // contract); results stay identical across ranks.
  kHier = 10,
  // int4 packed-nibble wire compression (float32 payloads only): ~8x
  // fewer wire bytes than float32 at max|block|/14 per-element, per-hop
  // precision — aggressive enough that it is opt-in or tuner-elected
  // ONLY (kAutoLossyWire picks it solely from a measured table entry,
  // never as a fallback). Consensus matches q8: the allgather forwards
  // the owner's stream verbatim. See collectives_q4.cc for the
  // contract and TPUCOLL_Q4_BLOCK.
  kRingQ4Wire = 11,
};

struct AllreduceOptions : CollectiveOptions {
  // One or more local input buffers are reduced together first; the result
  // lands in every output buffer (multi-buffer form matches the reference's
  // multi-input allreduce used for one-process-per-host, N-accelerator
  // setups). inputs may alias outputs.
  std::vector<const void*> inputs;
  std::vector<void*> outputs;
  size_t count = 0;
  DataType dtype = DataType::kFloat32;
  ReduceOp op = ReduceOp::kSum;
  // Overrides `op` when set: an arbitrary commutative-associative
  // accumulate fn(acc, in, n_elems) (reference: gloo/allreduce.h:36 takes
  // any Func; gloo/algorithm.h:59-95 ReductionFunction CUSTOM). Not
  // compatible with the wire-compressed algorithms (kRingBf16Wire /
  // kRingQ8Wire reduce through their wire codecs).
  ReduceFn customFn = nullptr;
  AllreduceAlgorithm algorithm = AllreduceAlgorithm::kAuto;
};
void allreduce(AllreduceOptions& opts);

enum class ReduceAlgorithm : uint8_t {
  // Binomial tree for latency-bound payloads (log2 P rounds, but log2 P
  // full-size messages through the root's link); pipelined ring
  // reduce-scatter + direct chunk gather to root for bandwidth-bound
  // ones (~2N bytes per link total, the reference's only schedule:
  // gloo/reduce.cc:61-246). Crossover: the installed tuning table when
  // present, else TPUCOLL_REDUCE_BINOMIAL_MAX.
  kAuto = 0,
  kBinomial = 1,
  kRing = 2,
};

struct ReduceOptions : CollectiveOptions {
  const void* input = nullptr;
  void* output = nullptr;  // required on root only
  size_t count = 0;
  DataType dtype = DataType::kFloat32;
  ReduceOp op = ReduceOp::kSum;
  ReduceFn customFn = nullptr;  // overrides `op` when set
  int root = 0;
  ReduceAlgorithm algorithm = ReduceAlgorithm::kAuto;
};
void reduce(ReduceOptions& opts);

struct GatherOptions : CollectiveOptions {
  const void* input = nullptr;  // count elements on every rank
  void* output = nullptr;       // count * size elements on root
  size_t count = 0;
  DataType dtype = DataType::kFloat32;
  int root = 0;
};
void gather(GatherOptions& opts);

struct GathervOptions : CollectiveOptions {
  const void* input = nullptr;        // counts[rank] elements
  void* output = nullptr;             // sum(counts) elements on root
  std::vector<size_t> counts;         // per-rank element counts, all ranks
  DataType dtype = DataType::kFloat32;
  int root = 0;
};
void gatherv(GathervOptions& opts);

struct ScatterOptions : CollectiveOptions {
  const void* input = nullptr;  // count * size elements on root
  void* output = nullptr;       // count elements on every rank
  size_t count = 0;
  DataType dtype = DataType::kFloat32;
  int root = 0;
};
void scatter(ScatterOptions& opts);

struct AllgatherOptions : CollectiveOptions {
  const void* input = nullptr;  // count elements
  void* output = nullptr;       // count * size elements
  size_t count = 0;
  DataType dtype = DataType::kFloat32;
  HierDispatch algorithm = HierDispatch::kAuto;
};
void allgather(AllgatherOptions& opts);

struct AllgathervOptions : CollectiveOptions {
  const void* input = nullptr;   // counts[rank] elements
  void* output = nullptr;        // sum(counts) elements
  std::vector<size_t> counts;    // per-rank element counts
  DataType dtype = DataType::kFloat32;
};
void allgatherv(AllgathervOptions& opts);

struct AlltoallOptions : CollectiveOptions {
  const void* input = nullptr;  // count * size elements
  void* output = nullptr;       // count * size elements
  size_t count = 0;             // elements exchanged with EACH rank
  DataType dtype = DataType::kFloat32;
};
void alltoall(AlltoallOptions& opts);

struct AlltoallvOptions : CollectiveOptions {
  const void* input = nullptr;
  void* output = nullptr;
  // inCounts[j]: elements this rank sends to rank j (contiguous splits).
  // outCounts[j]: elements this rank receives from rank j.
  std::vector<size_t> inCounts;
  std::vector<size_t> outCounts;
  DataType dtype = DataType::kFloat32;
};
void alltoallv(AlltoallvOptions& opts);

enum class ReduceScatterAlgorithm : uint8_t {
  // Ring for bandwidth-bound payloads (P-1 uniform pipelined steps);
  // recursive vector halving (log2 P rounds, contract of reference
  // gloo/reduce_scatter.h) in the middle; single-round direct exchange
  // for tiny payloads. Crossovers: the installed tuning table when
  // present, else TPUCOLL_RS_DIRECT_MAX / TPUCOLL_RS_HD_MAX.
  kAuto = 0,
  kRing = 1,
  kHalvingDoubling = 2,
  kDirect = 3,
  // Ring reduce-scatter with the int8 block-quantized wire codec
  // (float32 sum only; opt-in, never auto-elected — the tuner measures
  // it so the table can report its headroom). Accumulation stays
  // float32; each rank's result block is the full-precision accumulator,
  // only the wire hops are quantized. Precision contract:
  // collectives_q8.cc.
  kRingQ8Wire = 4,
  // Hierarchical composition (group/hier.h): intra-host allreduce of
  // the staged vector, leader-only reduce_scatter of host-contiguous
  // blocks, intra-host broadcast + local slice. Electable by kAuto on a
  // non-flat topology from a tuned table; flat topologies dispatch as
  // kAuto.
  kHier = 5,
  // Ring reduce-scatter over the int4 packed-nibble wire codec
  // (float32 sum only; opt-in / tuner-measured, never auto-elected).
  // Precision contract: collectives_q4.cc.
  kRingQ4Wire = 6,
};

struct ReduceScatterOptions : CollectiveOptions {
  const void* input = nullptr;      // sum(recvCounts) elements
  void* output = nullptr;           // recvCounts[rank] elements
  std::vector<size_t> recvCounts;   // per-rank result block sizes
  DataType dtype = DataType::kFloat32;
  ReduceOp op = ReduceOp::kSum;
  ReduceFn customFn = nullptr;      // overrides `op` when set
  ReduceScatterAlgorithm algorithm = ReduceScatterAlgorithm::kAuto;
};
void reduceScatter(ReduceScatterOptions& opts);

namespace detail {
// Resolve the effective timeout for a collective call.
inline std::chrono::milliseconds effectiveTimeout(
    const CollectiveOptions& opts) {
  return opts.timeout.count() > 0 ? opts.timeout
                                  : opts.context->getTimeout();
}
}  // namespace detail

}  // namespace tpucoll
