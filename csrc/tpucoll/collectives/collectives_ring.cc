// Ring schedules: allgather(v), allreduce (reduce-scatter + allgather),
// reduce_scatter, and the binomial-tree reduce.
//
// Ring block bookkeeping: `count` elements are split into `size` blocks
// (allreduce) or taken from per-rank counts (v-variants / reduce_scatter).
// All rings send to rank+1 and receive from rank-1; per-step sub-slots keep
// pipelined messages on one pair from cross-matching.
#include <algorithm>
#include <cstring>
#include <vector>

#include "tpucoll/collectives/collectives.h"

namespace tpucoll {

namespace {

char* bytePtr(void* p) { return static_cast<char*>(p); }

struct Blocks {
  std::vector<size_t> bytes;    // per-block byte size
  std::vector<size_t> offset;   // per-block byte offset
};

Blocks evenBlocks(size_t count, int size, size_t elsize) {
  Blocks b;
  b.bytes.resize(size);
  b.offset.resize(size);
  const size_t base = count / size;
  const size_t rem = count % size;
  size_t off = 0;
  for (int i = 0; i < size; i++) {
    const size_t elems = base + (static_cast<size_t>(i) < rem ? 1 : 0);
    b.bytes[i] = elems * elsize;
    b.offset[i] = off;
    off += b.bytes[i];
  }
  return b;
}

Blocks countBlocks(const std::vector<size_t>& counts, size_t elsize) {
  Blocks b;
  b.bytes.resize(counts.size());
  b.offset.resize(counts.size());
  size_t off = 0;
  for (size_t i = 0; i < counts.size(); i++) {
    b.bytes[i] = counts[i] * elsize;
    b.offset[i] = off;
    off += b.bytes[i];
  }
  return b;
}

// Ring reduce-scatter over `work` (in place). After P-1 steps, rank r owns
// block (r + 1 + startShift) mod P fully reduced. startShift=0 feeds the
// allreduce allgather phase; startShift=-1 makes rank r own block r for the
// standalone reduce_scatter.
void ringReduceScatter(Context* ctx, char* work, const Blocks& blocks,
                       ReduceFn fn, size_t elsize, Slot slot,
                       uint64_t slotBase, int startShift,
                       std::chrono::milliseconds timeout,
                       transport::UnboundBuffer* workBuf) {
  const int rank = ctx->rank();
  const int size = ctx->size();
  size_t maxBlock = 0;
  for (size_t b : blocks.bytes) {
    maxBlock = std::max(maxBlock, b);
  }
  std::vector<char> tmp(maxBlock);
  auto tmpBuf = ctx->createUnboundBuffer(tmp.data(), tmp.size());
  const int right = (rank + 1) % size;
  const int left = (rank - 1 + size) % size;
  for (int step = 0; step < size - 1; step++) {
    const int sendBlock = (rank + startShift - step + 2 * size) % size;
    const int recvBlock = (rank + startShift - step - 1 + 2 * size) % size;
    const uint64_t s = slot.offset(slotBase + step).value();
    workBuf->send(right, s, blocks.offset[sendBlock],
                  blocks.bytes[sendBlock]);
    tmpBuf->recv(left, s, 0, blocks.bytes[recvBlock]);
    tmpBuf->waitRecv(nullptr, timeout);
    if (blocks.bytes[recvBlock] > 0) {
      fn(work + blocks.offset[recvBlock], tmp.data(),
         blocks.bytes[recvBlock] / elsize);
    }
    workBuf->waitSend(timeout);
  }
}

}  // namespace

// Ring allgather: block b travels P-1 hops; receives land in place in the
// output (reference schedule shape: gloo/allgather.cc:55-98).
void allgatherv(AllgathervOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "allgatherv: null context");
  const auto timeout = detail::effectiveTimeout(opts);
  const int rank = ctx->rank();
  const int size = ctx->size();
  TC_ENFORCE_EQ(opts.counts.size(), static_cast<size_t>(size));
  const size_t elsize = elementSize(opts.dtype);
  Blocks blocks = countBlocks(opts.counts, elsize);
  const size_t total = blocks.offset[size - 1] + blocks.bytes[size - 1];

  if (opts.input != nullptr) {
    std::memcpy(bytePtr(opts.output) + blocks.offset[rank], opts.input,
                blocks.bytes[rank]);
  }
  if (size == 1) {
    return;
  }

  Slot slot = Slot::build(SlotPrefix::kAllgather, opts.tag);
  auto out = ctx->createUnboundBuffer(opts.output, total);
  const int right = (rank + 1) % size;
  const int left = (rank - 1 + size) % size;
  for (int step = 0; step < size - 1; step++) {
    const int sendBlock = (rank - step + 2 * size) % size;
    const int recvBlock = (rank - step - 1 + 2 * size) % size;
    const uint64_t s = slot.offset(step).value();
    out->send(right, s, blocks.offset[sendBlock], blocks.bytes[sendBlock]);
    out->recv(left, s, blocks.offset[recvBlock], blocks.bytes[recvBlock]);
    out->waitRecv(nullptr, timeout);
    out->waitSend(timeout);
  }
}

void allgather(AllgatherOptions& opts) {
  AllgathervOptions v;
  static_cast<CollectiveOptions&>(v) = opts;
  v.input = opts.input;
  v.output = opts.output;
  v.counts.assign(opts.context->size(), opts.count);
  v.dtype = opts.dtype;
  allgatherv(v);
}

// Bandwidth-optimal ring allreduce (reference hot path: gloo/allreduce.cc:
// 147-392): local multi-input reduce, ring reduce-scatter, ring allgather,
// then fan the result to every output buffer.
void allreduce(AllreduceOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "allreduce: null context");
  TC_ENFORCE(!opts.inputs.empty() && !opts.outputs.empty(),
             "allreduce: need at least one input and output");
  const auto timeout = detail::effectiveTimeout(opts);
  const int rank = ctx->rank();
  const int size = ctx->size();
  const size_t elsize = elementSize(opts.dtype);
  const size_t nbytes = opts.count * elsize;
  ReduceFn fn = getReduceFn(opts.dtype, opts.op);

  // Local reduction of all inputs into outputs[0].
  char* work = bytePtr(opts.outputs[0]);
  if (work != opts.inputs[0]) {
    std::memcpy(work, opts.inputs[0], nbytes);
  }
  for (size_t i = 1; i < opts.inputs.size(); i++) {
    fn(work, opts.inputs[i], opts.count);
  }

  if (size > 1 && opts.count > 0) {
    Slot slot = Slot::build(SlotPrefix::kAllreduce, opts.tag);
    Blocks blocks = evenBlocks(opts.count, size, elsize);
    auto workBuf = ctx->createUnboundBuffer(work, nbytes);
    ringReduceScatter(ctx, work, blocks, fn, elsize, slot, 0, 0, timeout,
                      workBuf.get());
    // Allgather phase: rank r starts owning reduced block (r+1); the block
    // then rides the ring into place on every rank.
    const int right = (rank + 1) % size;
    const int left = (rank - 1 + size) % size;
    for (int step = 0; step < size - 1; step++) {
      const int sendBlock = (rank + 1 - step + 2 * size) % size;
      const int recvBlock = (rank - step + 2 * size) % size;
      const uint64_t s = slot.offset(size + step).value();
      workBuf->send(right, s, blocks.offset[sendBlock],
                    blocks.bytes[sendBlock]);
      workBuf->recv(left, s, blocks.offset[recvBlock],
                    blocks.bytes[recvBlock]);
      workBuf->waitRecv(nullptr, timeout);
      workBuf->waitSend(timeout);
    }
  }

  for (size_t i = 1; i < opts.outputs.size(); i++) {
    std::memcpy(opts.outputs[i], work, nbytes);
  }
}

// Binomial reduction tree: leaves push partials toward the root, halving the
// number of active ranks per round (log2 P latency steps).
void reduce(ReduceOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "reduce: null context");
  const auto timeout = detail::effectiveTimeout(opts);
  const int rank = ctx->rank();
  const int size = ctx->size();
  TC_ENFORCE(opts.root >= 0 && opts.root < size, "reduce: bad root");
  const size_t elsize = elementSize(opts.dtype);
  const size_t nbytes = opts.count * elsize;
  ReduceFn fn = getReduceFn(opts.dtype, opts.op);

  const bool isRoot = rank == opts.root;
  TC_ENFORCE(!isRoot || opts.output != nullptr, "reduce: root needs output");
  std::vector<char> scratch;
  char* result;
  if (isRoot) {
    result = bytePtr(opts.output);
  } else {
    scratch.resize(nbytes);
    result = scratch.data();
  }
  if (result != opts.input) {
    std::memcpy(result, opts.input, nbytes);
  }
  if (size == 1) {
    return;
  }

  Slot slot = Slot::build(SlotPrefix::kReduce, opts.tag);
  const int vrank = (rank - opts.root + size) % size;
  auto physical = [&](int v) { return (v + opts.root) % size; };
  auto resultBuf = ctx->createUnboundBuffer(result, nbytes);
  std::vector<char> tmp(nbytes);
  auto tmpBuf = ctx->createUnboundBuffer(tmp.data(), nbytes);

  int mask = 1;
  uint64_t round = 0;
  while (mask < size) {
    if (vrank & mask) {
      resultBuf->send(physical(vrank - mask), slot.offset(round).value(), 0,
                      nbytes);
      resultBuf->waitSend(timeout);
      break;
    }
    const int partner = vrank + mask;
    if (partner < size) {
      tmpBuf->recv(physical(partner), slot.offset(round).value(), 0, nbytes);
      tmpBuf->waitRecv(nullptr, timeout);
      fn(result, tmp.data(), opts.count);
    }
    mask <<= 1;
    round++;
  }
}

// Ring reduce-scatter with per-rank result blocks (reference analog:
// gloo/reduce_scatter.h halving-doubling; the ring keeps per-step traffic
// uniform and handles arbitrary recvCounts without bit-reversal reordering).
void reduceScatter(ReduceScatterOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "reduceScatter: null context");
  const auto timeout = detail::effectiveTimeout(opts);
  const int rank = ctx->rank();
  const int size = ctx->size();
  TC_ENFORCE_EQ(opts.recvCounts.size(), static_cast<size_t>(size));
  const size_t elsize = elementSize(opts.dtype);
  ReduceFn fn = getReduceFn(opts.dtype, opts.op);
  Blocks blocks = countBlocks(opts.recvCounts, elsize);
  const size_t total = blocks.offset[size - 1] + blocks.bytes[size - 1];

  if (size == 1) {
    std::memcpy(opts.output, opts.input, total);
    return;
  }

  // Work in a scratch copy so the caller's input stays intact.
  std::vector<char> work(total);
  std::memcpy(work.data(), opts.input, total);
  Slot slot = Slot::build(SlotPrefix::kReduceScatter, opts.tag);
  auto workBuf = ctx->createUnboundBuffer(work.data(), total);
  ringReduceScatter(ctx, work.data(), blocks, fn, elsize, slot, 0,
                    /*startShift=*/-1, timeout, workBuf.get());
  std::memcpy(opts.output, work.data() + blocks.offset[rank],
              blocks.bytes[rank]);
}

}  // namespace tpucoll
