// Ring schedules: allgather(v), allreduce (reduce-scatter + allgather),
// reduce_scatter, and the binomial-tree reduce.
//
// Ring block bookkeeping: `count` elements are split into `size` blocks
// (allreduce) or taken from per-rank counts (v-variants / reduce_scatter).
// All rings send to rank+1 and receive from rank-1; per-step sub-slots keep
// pipelined messages on one pair from cross-matching.
//
// Every public entry resolves its algorithm, then acquires a persistent
// plan (plan.h) keyed by the call's full identity: the plan owns the
// registered work/stage buffers and the memoized block/segment layout,
// so a repeated call replays with zero allocations and registrations.
#include <algorithm>
#include <cstring>
#include <optional>
#include <vector>

#include "tpucoll/collectives/algorithms.h"
#include "tpucoll/collectives/collectives.h"
#include "tpucoll/collectives/detail.h"
#include "tpucoll/collectives/plan.h"
#include "tpucoll/common/profile.h"
#include "tpucoll/group/hier.h"
#include "tpucoll/schedule/interpreter.h"
#include "tpucoll/tuning/dispatch.h"

namespace tpucoll {

using collectives_detail::Blocks;
using collectives_detail::countBlocks;
using collectives_detail::evenBlocks;
using collectives_detail::fuseRecvReduce;
using plan::LazyStage;
using plan::PlanHandle;
using plan::PlanKey;
using plan::PlanOp;
using profile::Phase;
using profile::PhaseScope;
using profile::ProfileOpScope;

namespace {

char* bytePtr(void* p) { return static_cast<char*>(p); }

// Plan stage-slot map for this file's schedules (indices are per-plan,
// and a plan is keyed by its resolved algorithm, so only slots used by
// ONE schedule may collide):
//   0  algorithm-internal staging (binomial reduce)
//   1  ring reduce-scatter double-buffered staging
//   2  reduce_scatter work copy (the caller's input stays intact)
//   3  reduce non-root result
constexpr size_t kStageBinomial = 0;
constexpr size_t kStageRingRs = 1;
constexpr size_t kStageRsWork = 2;
constexpr size_t kStageReduceResult = 3;

// PlanKey.algorithm sentinel for scheduled (IR-interpreted) dispatch;
// the schedule's identity rides in PlanKey.aux as an FNV-1a name hash.
// Native algorithm enums are tiny, so 0xFF can never collide.
constexpr uint8_t kScheduledAlgorithm = 0xFF;

uint64_t fnvName(const std::string& name) {
  uint64_t h = 1469598103934665603ull;
  for (char c : name) {
    h ^= static_cast<uint8_t>(c);
    h *= 1099511628211ull;
  }
  return h;
}

// Elected-schedule lookup for a kAuto dispatch. A schedule election
// names one exact (collective, world, dtype, size-bucket) cell — the
// most specific evidence the tuner can record — so it outranks both the
// tuning table and the compile-time fallback thresholds. Null when no
// plane is installed, no cell matches this call, the elected schedule
// was not resolvable for this world, or the program carries bf16-coded
// wire steps without the caller's lossy-wire opt-in (codedOk).
std::shared_ptr<const schedule::ResolvedProgram> electedSchedule(
    Context* ctx, const char* collective, DataType dtype, size_t nbytes,
    bool codedOk) {
  auto inst = ctx->schedules();
  if (inst == nullptr) {
    return nullptr;
  }
  const schedule::Schedule* sel = inst->table->elected(
      collective, ctx->size(), tuning::dataTypeName(dtype), nbytes);
  if (sel == nullptr) {
    return nullptr;
  }
  auto it = inst->programs.find(sel->name);
  if (it == inst->programs.end() || (it->second->hasCoded && !codedOk)) {
    return nullptr;
  }
  return it->second;
}

// Ring reduce-scatter over `work` (in place). After P-1 steps, rank r owns
// block (r + 1 + startShift) mod P fully reduced. startShift=0 feeds the
// allreduce allgather phase; startShift=-1 makes rank r own block r for the
// standalone reduce_scatter.
//
// Pipelining (the reference's key allreduce optimization, maxSegmentSize +
// two-in-flight at gloo/allreduce.cc:196-218, re-derived for the eager
// transport): block transfers are split into segments of at most
// kMaxSegmentBytes; receives are pre-posted TWO steps ahead into
// double-buffered staging so arriving payloads always land directly in
// their destination (never the stash), and each segment is reduced the
// moment it arrives, overlapping the VPU/AVX reduction with socket I/O of
// later segments.
// Slot span ringReduceScatter consumes starting at its slotBase: P-1
// steps of maxSegs segment slots each, rounded up to P*maxSegs. Any
// phase layered behind it on the same tag (allgather, gather-to-root)
// MUST derive its slot base from this helper, so a change to the RS
// slot schedule cannot silently collide with a follow-on phase.
uint64_t ringReduceScatterSlotSpan(plan::Plan& plan, const Blocks& blocks,
                                   size_t elsize) {
  size_t maxBlock = 0;
  for (size_t b : blocks.bytes) {
    maxBlock = std::max(maxBlock, b);
  }
  return uint64_t(blocks.bytes.size()) *
         plan.segments(maxBlock, elsize).size();
}

void ringReduceScatter(Context* ctx, plan::Plan& plan, char* work,
                       const Blocks& blocks, ReduceFn fn, size_t elsize,
                       Slot slot, uint64_t slotBase, int startShift,
                       std::chrono::milliseconds timeout,
                       transport::UnboundBuffer* workBuf, bool fuseOk) {
  const int rank = ctx->rank();
  const int size = ctx->size();
  size_t maxBlock = 0;
  for (size_t b : blocks.bytes) {
    maxBlock = std::max(maxBlock, b);
  }
  const size_t maxSegs = plan.segments(maxBlock, elsize).size();
  const int right = (rank + 1) % size;
  const int left = (rank - 1 + size) % size;
  // Fused receive-reduce: arrivals are combined into `work` by the
  // transport itself (straight out of the shm ring), so the schedule
  // needs no staging at all and each payload byte is touched once instead
  // of copy+reduce. Receives still pre-post two steps ahead; an in-flight
  // combined segment is always disjoint from the blocks being sent (recv
  // of step s writes block r-s-1 while sends read block r-s). Custom
  // reduce fns stay on the scratch path: they may not be safe on the
  // transport's loop thread (Python callbacks need the GIL). Fusing is
  // per-source: the ring only ever receives from `left`, so one check
  // picks the schedule (collectives_detail::fuseRecvReduce).
  const bool fuse = fuseRecvReduce(ctx, fuseOk, elsize, left);
  // Plan-backed staging, scratch path only (lazy: the fused path receives
  // straight into `work`): cached plans keep the pages AND the
  // registration warm across calls.
  LazyStage stage(plan, kStageRingRs, 2 * std::max(maxBlock, size_t(1)));
  const int steps = size - 1;

  auto sendBlockAt = [&](int step) {
    return (rank + startShift - step + 2 * size) % size;
  };
  auto recvBlockAt = [&](int step) {
    return (rank + startShift - step - 1 + 2 * size) % size;
  };
  auto segSlot = [&](int step, size_t seg) {
    return slot.offset(slotBase + uint64_t(step) * maxSegs + seg).value();
  };

  // Post all segment receives of `step`: fused, straight into the work
  // block (combined on arrival); scratch path, into staging half (step%2).
  auto postRecvsFor = [&](int step) {
    PhaseScope ps(Phase::kPost);
    const int rb = recvBlockAt(step);
    const auto& segs = plan.segments(blocks.bytes[rb], elsize);
    if (fuse) {
      for (size_t k = 0; k < segs.size(); k++) {
        workBuf->recvReduce(left, segSlot(step, k), fn, elsize,
                            blocks.offset[rb] + segs[k].offset,
                            segs[k].nbytes);
      }
      return;
    }
    const size_t base = (step % 2) * maxBlock;
    for (size_t k = 0; k < segs.size(); k++) {
      stage.buf()->recv(left, segSlot(step, k), base + segs[k].offset,
                        segs[k].nbytes);
    }
  };
  auto postSendsFor = [&](int step) {
    const size_t blockOff = blocks.offset[sendBlockAt(step)];
    const auto& segs =
        plan.segments(blocks.bytes[sendBlockAt(step)], elsize);
    for (size_t k = 0; k < segs.size(); k++) {
      // Annotated per segment: each send post is one causal span.
      PhaseScope ps(Phase::kPost, right, segSlot(step, k),
                    segs[k].nbytes);
      workBuf->send(right, segSlot(step, k), blockOff + segs[k].offset,
                    segs[k].nbytes);
    }
  };

  postRecvsFor(0);
  if (steps > 1) {
    postRecvsFor(1);
  }
  postSendsFor(0);

  for (int step = 0; step < steps; step++) {
    const int recvBlock = recvBlockAt(step);
    const size_t base = (step % 2) * maxBlock;
    const auto& segs = plan.segments(blocks.bytes[recvBlock], elsize);
    for (size_t k = 0; k < segs.size(); k++) {
      if (fuse) {
        // The combine already ran (loop thread / stash hit); the wait is
        // purely the completion count.
        PhaseScope ps(Phase::kWireWait, left, segSlot(step, k),
                      segs[k].nbytes);
        workBuf->waitRecv(nullptr, timeout);
        continue;
      }
      {
        PhaseScope ps(Phase::kWireWait, left, segSlot(step, k),
                      segs[k].nbytes);
        stage.buf()->waitRecv(nullptr, timeout);
      }
      // Segments on one pair complete in wire order, so segment k of this
      // step is the k-th completion.
      if (segs[k].nbytes > 0) {
        PhaseScope ps(Phase::kReduce);
        fn(work + blocks.offset[recvBlock] + segs[k].offset,
           stage.data() + base + segs[k].offset, segs[k].nbytes / elsize);
      }
    }
    // Drain this step's sends — counted from the SEND block's segment list,
    // which can differ from the recv block's when block sizes straddle a
    // segment boundary (e.g. evenBlocks remainders).
    const size_t sendSegCount =
        plan.segments(blocks.bytes[sendBlockAt(step)], elsize).size();
    {
      PhaseScope ps(Phase::kWireWait);
      for (size_t k = 0; k < sendSegCount; k++) {
        workBuf->waitSend(timeout);
      }
    }
    if (step + 2 < steps) {
      postRecvsFor(step + 2);  // staging half (step % 2) is free again
    }
    if (step + 1 < steps) {
      postSendsFor(step + 1);  // its block finished reducing just now
    }
  }
}

// Ring allgather phase over an in-place buffer: at step s, send block
// (rank + shift - s), receive block (rank + shift - s - 1) directly into
// place. All receives are pre-posted (each step writes a distinct block),
// the own/seed block is sent first, and every received segment is forwarded
// to the right neighbor the moment it arrives. shift=0 gathers each rank's
// own block (plain allgather); shift=+1 rides behind a reduce-scatter that
// left rank r owning reduced block r+1 (the allreduce second phase).
void ringAllgatherPhase(Context* ctx, plan::Plan& plan,
                        transport::UnboundBuffer* buf, const Blocks& blocks,
                        size_t elsize, Slot slot, uint64_t slotBase,
                        size_t maxSegs, int shift,
                        std::chrono::milliseconds timeout) {
  const int rank = ctx->rank();
  const int size = ctx->size();
  const int right = (rank + 1) % size;
  const int left = (rank - 1 + size) % size;
  const int steps = size - 1;
  auto blockAt = [&](int step) {
    return (rank + shift - step + 2 * size) % size;
  };
  auto segSlot = [&](int step, size_t seg) {
    return slot.offset(slotBase + uint64_t(step) * maxSegs + seg).value();
  };
  {
    PhaseScope ps(Phase::kPost);
    for (int step = 0; step < steps; step++) {
      const int recvBlock = blockAt(step + 1);  // == sendBlock(step) - 1
      const auto& segs = plan.segments(blocks.bytes[recvBlock], elsize);
      for (size_t k = 0; k < segs.size(); k++) {
        buf->recv(left, segSlot(step, k),
                  blocks.offset[recvBlock] + segs[k].offset,
                  segs[k].nbytes);
      }
    }
  }
  int pendingSends = 0;
  {
    const int sb = blockAt(0);
    const auto& segs = plan.segments(blocks.bytes[sb], elsize);
    for (size_t k = 0; k < segs.size(); k++) {
      PhaseScope ps(Phase::kPost, right, segSlot(0, k), segs[k].nbytes);
      buf->send(right, segSlot(0, k), blocks.offset[sb] + segs[k].offset,
                segs[k].nbytes);
      pendingSends++;
    }
  }
  for (int step = 0; step < steps; step++) {
    const int recvBlock = blockAt(step + 1);
    const auto& segs = plan.segments(blocks.bytes[recvBlock], elsize);
    for (size_t k = 0; k < segs.size(); k++) {
      {
        PhaseScope ps(Phase::kWireWait, left, segSlot(step, k),
                      segs[k].nbytes);
        buf->waitRecv(nullptr, timeout);
      }
      if (step + 1 < steps) {
        // This segment is exactly segment k of the next step's send block.
        PhaseScope ps(Phase::kPost, right, segSlot(step + 1, k),
                      segs[k].nbytes);
        buf->send(right, segSlot(step + 1, k),
                  blocks.offset[recvBlock] + segs[k].offset,
                  segs[k].nbytes);
        pendingSends++;
      }
    }
  }
  {
    PhaseScope ps(Phase::kWireWait);
    while (pendingSends-- > 0) {
      buf->waitSend(timeout);
    }
  }
}

}  // namespace

// Shared schedule behind allgather/allgatherv; instrumentation lives in
// the public entries so each op is attributed under its own name.
static void allgathervRun(AllgathervOptions& opts);

void allgatherv(AllgathervOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "allgatherv: null context");
  auto traceSpan = ctx->tracer().span("allgatherv");
  // Guarded: the counts-size enforce runs inside allgathervRun.
  const uint64_t myBytes =
      static_cast<size_t>(ctx->rank()) < opts.counts.size()
          ? opts.counts[ctx->rank()] * elementSize(opts.dtype)
          : 0;
  MetricsOp metricsOp(&ctx->metrics(), MetricOp::kAllgatherv, myBytes);
  // Fingerprint over the GROUP total: per-rank counts legitimately
  // differ on a matching allgatherv schedule, the counts vector (and so
  // its sum) must not.
  uint64_t totalCount = 0;
  for (size_t c : opts.counts) {
    totalCount += c;
  }
  FlightRecOp frOp(&ctx->flightrec(), "allgatherv", nullptr,
                   Slot::build(SlotPrefix::kAllgather, opts.tag).value(),
                   -1, myBytes, static_cast<uint8_t>(opts.dtype),
                   totalCount * elementSize(opts.dtype));
  ProfileOpScope profOp(&ctx->profiler(), "allgatherv", frOp.cseq(),
                        myBytes);
  span::OpScope spanOp(&ctx->spans(), "allgatherv", frOp.cseq());
  allgathervRun(opts);
}

void allgather(AllgatherOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "allgather: null context");
  auto traceSpan = ctx->tracer().span(
      "allgather", opts.count * elementSize(opts.dtype));
  MetricsOp metricsOp(&ctx->metrics(), MetricOp::kAllgather,
                      opts.count * elementSize(opts.dtype));
  FlightRecOp frOp(&ctx->flightrec(), "allgather", nullptr,
                   Slot::build(SlotPrefix::kAllgather, opts.tag).value(),
                   -1, opts.count * elementSize(opts.dtype),
                   static_cast<uint8_t>(opts.dtype));
  ProfileOpScope profOp(&ctx->profiler(), "allgather", frOp.cseq(),
                        opts.count * elementSize(opts.dtype));
  span::OpScope spanOp(&ctx->spans(), "allgather", frOp.cseq());
  if (opts.algorithm == HierDispatch::kHier && group::hierEligible(ctx) &&
      ctx->size() > 1 && opts.count > 0) {
    frOp.setAlgorithm("hier");
    profOp.setAlgorithm("hier");
    group::hierAllgather(ctx, opts.input, opts.output, opts.count,
                         opts.dtype, opts.tag,
                         detail::effectiveTimeout(opts));
    return;
  }
  if (ctx->size() > 1 && opts.count > 0 &&
      opts.algorithm != HierDispatch::kHier) {
    // Installed schedule plane first (see allreduce). Allgather
    // elections are bucketed by TOTAL output bytes — the quantity the
    // wire actually moves. Coded schedules never match: allgather has
    // no reduction to absorb bf16 rounding, so generators don't emit
    // them and electedSchedule's codedOk=false keeps it that way.
    const int size = ctx->size();
    const size_t elsize = elementSize(opts.dtype);
    const size_t total = opts.count * size_t(size) * elsize;
    if (auto prog = electedSchedule(ctx, "allgather", opts.dtype, total,
                                    /*codedOk=*/false)) {
      const char* lbl = schedule::internedLabel(prog->label);
      auto schedSpan = ctx->tracer().span("allgather", total, -1, lbl);
      frOp.setAlgorithm(lbl);
      profOp.setAlgorithm(lbl);
      const auto timeout = detail::effectiveTimeout(opts);
      Slot slot = Slot::build(SlotPrefix::kAllgather, opts.tag);
      char* out = bytePtr(opts.output);
      PlanKey key;
      key.opcode = static_cast<uint8_t>(PlanOp::kAllgatherv);
      key.algorithm = kScheduledAlgorithm;
      key.dtype = static_cast<uint8_t>(opts.dtype);
      key.tag = opts.tag;
      key.ptrA = reinterpret_cast<uintptr_t>(opts.input);
      key.ptrB = reinterpret_cast<uintptr_t>(opts.output);
      key.nbytes = total;
      key.aux = fnvName(prog->name);
      PlanHandle planh(ctx, key);
      if (opts.input != nullptr) {
        PhaseScope ps(Phase::kPack);
        std::memcpy(out + size_t(ctx->rank()) * opts.count * elsize,
                    opts.input, opts.count * elsize);
      }
      schedule::run(ctx, *planh, *prog, out, opts.count * size_t(size),
                    elsize, /*fn=*/nullptr, opts.dtype, slot, timeout);
      return;
    }
  }
  AllgathervOptions v;
  static_cast<CollectiveOptions&>(v) = opts;
  v.input = opts.input;
  v.output = opts.output;
  v.counts.assign(opts.context->size(), opts.count);
  v.dtype = opts.dtype;
  allgathervRun(v);
}

// Ring allgather: block b travels P-1 hops; receives land in place in the
// output (reference schedule shape: gloo/allgather.cc:55-98, with the
// pre-post + segment-forward pipeline of ringAllgatherPhase).
static void allgathervRun(AllgathervOptions& opts) {
  Context* ctx = opts.context;
  const auto timeout = detail::effectiveTimeout(opts);
  const int rank = ctx->rank();
  const int size = ctx->size();
  TC_ENFORCE_EQ(opts.counts.size(), static_cast<size_t>(size));
  const size_t elsize = elementSize(opts.dtype);
  size_t total = 0;
  for (size_t c : opts.counts) {
    total += c * elsize;
  }

  PlanKey key;
  key.opcode = static_cast<uint8_t>(PlanOp::kAllgatherv);
  key.dtype = static_cast<uint8_t>(opts.dtype);
  key.tag = opts.tag;
  key.ptrA = reinterpret_cast<uintptr_t>(opts.input);
  key.ptrB = reinterpret_cast<uintptr_t>(opts.output);
  key.nbytes = total;
  key.aux = plan::hashCounts(opts.counts);
  PlanHandle planh(ctx, key);
  const Blocks& blocks = planh->blocks(
      0, [&] { return countBlocks(opts.counts, elsize); });

  if (opts.input != nullptr) {
    PhaseScope ps(Phase::kPack);
    std::memcpy(bytePtr(opts.output) + blocks.offset[rank], opts.input,
                blocks.bytes[rank]);
  }
  if (size == 1) {
    return;
  }

  size_t maxBlock = 0;
  for (size_t b : blocks.bytes) {
    maxBlock = std::max(maxBlock, b);
  }
  Slot slot = Slot::build(SlotPrefix::kAllgather, opts.tag);
  auto* out = planh->userBuf(0, opts.output, total);

  // Small/medium payloads: direct exchange — every pair transfers
  // concurrently with no store-and-forward chain (measured ~2x faster
  // than the ring below the threshold; the ring wins for bulk payloads
  // where per-link balance matters). Loopback-tuned default; re-sweep on
  // real DCN via TPUCOLL_ALLGATHER_DIRECT_MAX (bytes of total non-local
  // traffic per rank; BASELINE.md documents the procedure).
  static const size_t directMax =
      collectives_detail::envBytes("TPUCOLL_ALLGATHER_DIRECT_MAX", 8u << 20);
  if (maxBlock * size_t(size - 1) <= directMax) {
    {
      PhaseScope ps(Phase::kPost);
      for (int i = 1; i < size; i++) {
        const int to = (rank + i) % size;
        const int from = (rank - i + size) % size;
        out->recv(from, slot.offset(0).value(), blocks.offset[from],
                  blocks.bytes[from]);
        out->send(to, slot.offset(0).value(), blocks.offset[rank],
                  blocks.bytes[rank]);
      }
    }
    PhaseScope ps(Phase::kWireWait);
    for (int i = 1; i < size; i++) {
      out->waitRecv(nullptr, timeout);
      out->waitSend(timeout);
    }
    return;
  }

  ringAllgatherPhase(ctx, *planh, out, blocks, elsize, slot, 0,
                     planh->segments(maxBlock, elsize).size(), /*shift=*/0,
                     timeout);
}

// Bandwidth-optimal ring allreduce (reference hot path: gloo/allreduce.cc:
// 147-392): local multi-input reduce, algorithm-specific exchange, then fan
// the result to every output buffer.
void allreduce(AllreduceOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "allreduce: null context");
  TC_ENFORCE(!opts.inputs.empty() && !opts.outputs.empty(),
             "allreduce: need at least one input and output");
  const auto timeout = detail::effectiveTimeout(opts);
  const int size = ctx->size();
  const size_t elsize = elementSize(opts.dtype);
  const size_t nbytes = opts.count * elsize;
  MetricsOp metricsOp(&ctx->metrics(), MetricOp::kAllreduce, nbytes);
  FlightRecOp frOp(&ctx->flightrec(), "allreduce", nullptr,
                   Slot::build(SlotPrefix::kAllreduce, opts.tag).value(),
                   -1, nbytes, static_cast<uint8_t>(opts.dtype));
  ProfileOpScope profOp(&ctx->profiler(), "allreduce", frOp.cseq(),
                        nbytes);
  span::OpScope spanOp(&ctx->spans(), "allreduce", frOp.cseq());
  ReduceFn fn = opts.customFn != nullptr
                  ? opts.customFn
                  : getReduceFn(opts.dtype, opts.op);

  // Local reduction of all inputs into outputs[0].
  char* work = bytePtr(opts.outputs[0]);
  {
    PhaseScope ps(Phase::kPack);
    if (work != opts.inputs[0]) {
      std::memcpy(work, opts.inputs[0], nbytes);
    }
    for (size_t i = 1; i < opts.inputs.size(); i++) {
      fn(work, opts.inputs[i], opts.count);
    }
  }

  TC_ENFORCE(opts.customFn == nullptr ||
                 (opts.algorithm != AllreduceAlgorithm::kRingBf16Wire &&
                  opts.algorithm != AllreduceAlgorithm::kRingQ8Wire &&
                  opts.algorithm != AllreduceAlgorithm::kRingQ4Wire),
             "allreduce: custom reduction functions are incompatible "
             "with the wire-compressed algorithms (they reduce through "
             "the wire codec)");

  if (size > 1 && opts.count > 0) {
    Slot slot = Slot::build(SlotPrefix::kAllreduce, opts.tag);
    AllreduceAlgorithm algo = opts.algorithm;
    // An explicit hierarchical request on a flat topology (single host,
    // or one rank per host) has no second plane to exploit; dispatch it
    // like kAuto so kHier is always safe to hardcode.
    if (algo == AllreduceAlgorithm::kHier && !group::hierEligible(ctx)) {
      algo = AllreduceAlgorithm::kAuto;
    }
    if (algo == AllreduceAlgorithm::kAutoLossyWire) {
      // The caller's explicit opt-in to lossy wire precision. Only the
      // float32 sum shape has wire codecs; anything else dispatches as
      // plain kAuto. Tuned contexts elect from measurement (wire arms
      // included); the untuned fallback routes the bandwidth tier to
      // the q8 ring — the caller asked for wire compression exactly
      // because the payload is bandwidth-bound.
      if (opts.dtype == DataType::kFloat32 && opts.op == ReduceOp::kSum &&
          opts.customFn == nullptr) {
        if (auto tuned =
                tuning::tableAllreduce(ctx, opts.dtype, nbytes,
                                       /*lossyWireOk=*/true)) {
          algo = *tuned;
        } else {
          static const size_t hdMaxLossy = collectives_detail::envBytes(
              "TPUCOLL_ALLREDUCE_HD_MAX", 1u << 20);
          algo = nbytes > hdMaxLossy ? AllreduceAlgorithm::kRingQ8Wire
                                     : AllreduceAlgorithm::kAuto;
        }
      } else {
        algo = AllreduceAlgorithm::kAuto;
      }
    }
    if (algo == AllreduceAlgorithm::kAuto && opts.customFn == nullptr) {
      // Installed schedule plane first: an election names one exact
      // (collective, world, dtype, bucket) cell, which is stronger
      // evidence than the tuning table's whole-curve crossovers.
      // Schedules carrying bf16-coded wire steps require the same
      // float32 + sum + kAutoLossyWire opt-in as the native wire arms.
      const bool codedOk =
          opts.algorithm == AllreduceAlgorithm::kAutoLossyWire &&
          opts.dtype == DataType::kFloat32 && opts.op == ReduceOp::kSum;
      if (auto prog = electedSchedule(ctx, "allreduce", opts.dtype, nbytes,
                                      codedOk)) {
        const char* lbl = schedule::internedLabel(prog->label);
        auto traceSpan = ctx->tracer().span("allreduce", nbytes, -1, lbl);
        frOp.setAlgorithm(lbl);
        profOp.setAlgorithm(lbl);
        PlanKey key;
        key.opcode = static_cast<uint8_t>(PlanOp::kAllreduce);
        key.algorithm = kScheduledAlgorithm;
        key.dtype = static_cast<uint8_t>(opts.dtype);
        key.op = static_cast<uint8_t>(opts.op);
        key.tag = opts.tag;
        key.ptrA = reinterpret_cast<uintptr_t>(work);
        key.nbytes = nbytes;
        key.aux = fnvName(prog->name);
        PlanHandle planh(ctx, key);
        schedule::run(ctx, *planh, *prog, work, opts.count, elsize, fn,
                      opts.dtype, slot, timeout);
        if (opts.outputs.size() > 1) {
          PhaseScope ps(Phase::kUnpack);
          for (size_t i = 1; i < opts.outputs.size(); i++) {
            std::memcpy(opts.outputs[i], work, nbytes);
          }
        }
        return;
      }
    }
    if (algo == AllreduceAlgorithm::kAuto) {
      // Measured tuning table first (tuning/dispatch.h: per-deployment
      // crossovers elected by tuning::tune and installed identically on
      // every rank), then the loopback-measured compile-time fallback
      // (BASELINE.md): recursive doubling (log2 P full-vector rounds;
      // non-power-of-2 groups take a pre/post fold) for the
      // alpha-dominated tiny tier, halving-doubling up to ~1 MiB, the
      // pipelined ring beyond. Re-sweep via bench.py --autotune, or move
      // the fallback thresholds with TPUCOLL_ALLREDUCE_RD_MAX /
      // TPUCOLL_ALLREDUCE_HD_MAX (bytes).
      if (auto tuned = tuning::tableAllreduce(ctx, opts.dtype, nbytes)) {
        algo = *tuned;
      } else {
        static const size_t rdMax = collectives_detail::envBytes(
            "TPUCOLL_ALLREDUCE_RD_MAX", 16u << 10);
        static const size_t hdMax = collectives_detail::envBytes(
            "TPUCOLL_ALLREDUCE_HD_MAX", 1u << 20);
        algo = nbytes <= rdMax ? AllreduceAlgorithm::kRecursiveDoubling
               : nbytes <= hdMax ? AllreduceAlgorithm::kHalvingDoubling
                                 : AllreduceAlgorithm::kRing;
      }
    }
    auto traceSpan = ctx->tracer().span(
        "allreduce", nbytes, -1, tuning::allreduceAlgorithmName(algo));
    frOp.setAlgorithm(tuning::allreduceAlgorithmName(algo));
    profOp.setAlgorithm(tuning::allreduceAlgorithmName(algo));
    if (algo == AllreduceAlgorithm::kHier) {
      // Hierarchical composition: every phase is an ordinary collective
      // on a split sub-context, each with its own plan cache — the
      // parent-level plan machinery below is deliberately skipped.
      group::hierAllreduce(ctx, work, opts.count, opts.dtype, opts.op,
                           opts.customFn, opts.tag, timeout);
      if (opts.outputs.size() > 1) {
        PhaseScope ps(Phase::kUnpack);
        for (size_t i = 1; i < opts.outputs.size(); i++) {
          std::memcpy(opts.outputs[i], work, nbytes);
        }
      }
      return;
    }
    // Persistent plan, keyed by the RESOLVED algorithm (a tuning-table
    // install clears the cache, so a stale kAuto choice cannot replay).
    // Custom reductions stay transient: the fn pointer's identity is
    // not stable across calls (Python rebuilds its trampoline).
    PlanKey key;
    key.opcode = static_cast<uint8_t>(PlanOp::kAllreduce);
    key.algorithm = static_cast<uint8_t>(algo);
    key.dtype = static_cast<uint8_t>(opts.dtype);
    key.op = static_cast<uint8_t>(opts.op);
    key.tag = opts.tag;
    key.ptrA = reinterpret_cast<uintptr_t>(work);
    key.nbytes = nbytes;
    PlanHandle planh = opts.customFn == nullptr ? PlanHandle(ctx, key)
                                                : PlanHandle(ctx);
    switch (algo) {
      case AllreduceAlgorithm::kRing:
        algorithms::ringAllreduce(ctx, *planh, work, opts.count, elsize,
                                  fn, slot, timeout,
                                  opts.customFn == nullptr);
        break;
      case AllreduceAlgorithm::kHalvingDoubling:
        algorithms::halvingDoublingAllreduce(ctx, *planh, work, opts.count,
                                             elsize, fn, slot, timeout,
                                             opts.customFn == nullptr);
        break;
      case AllreduceAlgorithm::kHdFold:
        algorithms::hdFoldAllreduce(ctx, *planh, work, opts.count, elsize,
                                    fn, slot, timeout,
                                    opts.customFn == nullptr);
        break;
      case AllreduceAlgorithm::kHdBlocks:
        algorithms::hdBinaryBlocksAllreduce(ctx, *planh, work, opts.count,
                                            elsize, fn, slot, timeout,
                                            opts.customFn == nullptr);
        break;
      case AllreduceAlgorithm::kRecursiveDoubling:
        algorithms::recursiveDoublingAllreduce(ctx, *planh, work,
                                               opts.count, elsize, fn,
                                               slot, timeout);
        break;
      case AllreduceAlgorithm::kBcube:
        algorithms::bcubeAllreduce(ctx, *planh, work, opts.count, elsize,
                                   fn, slot, timeout,
                                   opts.customFn == nullptr);
        break;
      case AllreduceAlgorithm::kRingBf16Wire:
        TC_ENFORCE(opts.dtype == DataType::kFloat32,
                   "bf16-wire allreduce requires float32 payloads");
        TC_ENFORCE(opts.op == ReduceOp::kSum,
                   "bf16-wire allreduce supports sum only");
        algorithms::bf16WireRingAllreduce(ctx, *planh, work, opts.count,
                                          slot, timeout);
        break;
      case AllreduceAlgorithm::kRingQ8Wire:
        TC_ENFORCE(opts.dtype == DataType::kFloat32,
                   "q8-wire allreduce requires float32 payloads");
        TC_ENFORCE(opts.op == ReduceOp::kSum,
                   "q8-wire allreduce supports sum only");
        algorithms::q8WireRingAllreduce(ctx, *planh, work, opts.count,
                                        slot, timeout);
        break;
      case AllreduceAlgorithm::kRingQ4Wire:
        TC_ENFORCE(opts.dtype == DataType::kFloat32,
                   "q4-wire allreduce requires float32 payloads");
        TC_ENFORCE(opts.op == ReduceOp::kSum,
                   "q4-wire allreduce supports sum only");
        algorithms::q4WireRingAllreduce(ctx, *planh, work, opts.count,
                                        slot, timeout);
        break;
      default:
        TC_THROW(EnforceError, "unknown allreduce algorithm");
    }
  }

  if (opts.outputs.size() > 1) {
    PhaseScope ps(Phase::kUnpack);
    for (size_t i = 1; i < opts.outputs.size(); i++) {
      std::memcpy(opts.outputs[i], work, nbytes);
    }
  }
}

namespace algorithms {

void ringAllreduce(Context* ctx, plan::Plan& plan, char* work,
                   size_t count, size_t elsize, ReduceFn fn, Slot slot,
                   std::chrono::milliseconds timeout, bool fuseOk) {
  const int size = ctx->size();
  const size_t nbytes = count * elsize;
  const Blocks& blocks =
      plan.blocks(0, [&] { return evenBlocks(count, size, elsize); });
  size_t maxBlock = 0;
  for (size_t b : blocks.bytes) {
    maxBlock = std::max(maxBlock, b);
  }
  const size_t maxSegs = plan.segments(maxBlock, elsize).size();
  auto* workBuf = plan.userBuf(0, work, nbytes);
  ringReduceScatter(ctx, plan, work, blocks, fn, elsize, slot, 0, 0,
                    timeout, workBuf, fuseOk);
  // Allgather phase: rank r starts owning reduced block (r+1); the block
  // then rides the ring into place on every rank.
  ringAllgatherPhase(ctx, plan, workBuf, blocks, elsize, slot,
                     /*slotBase=*/
                     ringReduceScatterSlotSpan(plan, blocks, elsize),
                     maxSegs, /*shift=*/1, timeout);
}

}  // namespace algorithms

namespace {

// Binomial reduction tree: leaves push partials toward the root, halving
// the number of active ranks per round. log2(P) latency steps, but every
// round moves a FULL payload and the root's in-link carries log2(P) * N
// bytes — latency-optimal, bandwidth-hostile.
void binomialReduce(Context* ctx, plan::Plan& plan, char* result,
                    transport::UnboundBuffer* resultBuf, size_t count,
                    size_t elsize, ReduceFn fn, int root, bool fuseOk,
                    Slot slot, std::chrono::milliseconds timeout) {
  const int rank = ctx->rank();
  const int size = ctx->size();
  const size_t nbytes = count * elsize;
  const int vrank = (rank - root + size) % size;
  auto physical = [&](int v) { return (v + root) % size; };
  // Fused receive-reduce: partner partials are combined into `result` by
  // the transport (from the shm ring / stash, no scratch vector at all).
  // Rounds are serialized by waitRecv, so result is never concurrently a
  // send source and a combine target. Custom fns stay on the scratch path
  // (not loop-thread-safe); fuseRecvReduce picks per partner, per round.
  LazyStage stage(plan, kStageBinomial, nbytes);

  int mask = 1;
  uint64_t round = 0;
  while (mask < size) {
    if (vrank & mask) {
      {
        PhaseScope ps(Phase::kPost);
        resultBuf->send(physical(vrank - mask),
                        slot.offset(round).value(), 0, nbytes);
      }
      PhaseScope ps(Phase::kWireWait);
      resultBuf->waitSend(timeout);
      break;
    }
    const int partner = vrank + mask;
    if (partner < size) {
      const int src = physical(partner);
      if (fuseRecvReduce(ctx, fuseOk, elsize, src)) {
        {
          PhaseScope ps(Phase::kPost);
          resultBuf->recvReduce(src, slot.offset(round).value(), fn,
                                elsize, 0, nbytes);
        }
        PhaseScope ps(Phase::kWireWait);
        resultBuf->waitRecv(nullptr, timeout);
      } else {
        {
          PhaseScope ps(Phase::kPost);
          stage.buf()->recv(src, slot.offset(round).value(), 0, nbytes);
        }
        {
          PhaseScope ps(Phase::kWireWait);
          stage.buf()->waitRecv(nullptr, timeout);
        }
        PhaseScope ps(Phase::kReduce);
        fn(result, stage.data(), count);
      }
    }
    mask <<= 1;
    round++;
  }
}

// Bandwidth-optimal reduce-to-root (contract of gloo/reduce.cc:61-246):
// the pipelined ring reduce-scatter leaves rank r owning reduced block r
// in-place, then every rank ships its one block straight to the root —
// ~2N bytes per link total and ~N bytes through the root's in-link,
// vs the binomial's log2(P) * N. Reuses ringReduceScatter wholesale
// (segment pipelining, two-ahead pre-posts, fused receive-reduce).
void ringReduce(Context* ctx, plan::Plan& plan, char* work,
                transport::UnboundBuffer* workBuf, size_t count,
                size_t elsize, ReduceFn fn, int root, bool fuseOk,
                Slot slot, std::chrono::milliseconds timeout) {
  const int rank = ctx->rank();
  const int size = ctx->size();
  const Blocks& blocks =
      plan.blocks(0, [&] { return evenBlocks(count, size, elsize); });
  ringReduceScatter(ctx, plan, work, blocks, fn, elsize, slot, 0,
                    /*startShift=*/-1, timeout, workBuf, fuseOk);
  // Gather phase: block b travels root's in-link exactly once. Slots
  // continue past the reduce-scatter's reserved range.
  const uint64_t gatherBase =
      ringReduceScatterSlotSpan(plan, blocks, elsize);
  if (rank == root) {
    int pending = 0;
    {
      PhaseScope ps(Phase::kPost);
      for (int b = 0; b < size; b++) {
        if (b == rank || blocks.bytes[b] == 0) {
          continue;
        }
        workBuf->recv(b, slot.offset(gatherBase + uint64_t(b)).value(),
                      blocks.offset[b], blocks.bytes[b]);
        pending++;
      }
    }
    PhaseScope ps(Phase::kWireWait);
    for (int i = 0; i < pending; i++) {
      workBuf->waitRecv(nullptr, timeout);
    }
  } else if (blocks.bytes[rank] > 0) {
    {
      PhaseScope ps(Phase::kPost);
      workBuf->send(root,
                    slot.offset(gatherBase + uint64_t(rank)).value(),
                    blocks.offset[rank], blocks.bytes[rank]);
    }
    PhaseScope ps(Phase::kWireWait);
    workBuf->waitSend(timeout);
  }
}

}  // namespace

void reduce(ReduceOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "reduce: null context");
  const auto timeout = detail::effectiveTimeout(opts);
  const int rank = ctx->rank();
  const int size = ctx->size();
  TC_ENFORCE(opts.root >= 0 && opts.root < size, "reduce: bad root");
  const size_t elsize = elementSize(opts.dtype);
  const size_t nbytes = opts.count * elsize;
  MetricsOp metricsOp(&ctx->metrics(), MetricOp::kReduce, nbytes);
  FlightRecOp frOp(&ctx->flightrec(), "reduce", nullptr,
                   Slot::build(SlotPrefix::kReduce, opts.tag).value(),
                   opts.root, nbytes, static_cast<uint8_t>(opts.dtype));
  ProfileOpScope profOp(&ctx->profiler(), "reduce", frOp.cseq(), nbytes);
  span::OpScope spanOp(&ctx->spans(), "reduce", frOp.cseq());
  ReduceFn fn = opts.customFn != nullptr
                  ? opts.customFn
                  : getReduceFn(opts.dtype, opts.op);

  const bool isRoot = rank == opts.root;
  TC_ENFORCE(!isRoot || opts.output != nullptr, "reduce: root needs output");
  if (size == 1 || opts.count == 0) {
    if (isRoot && opts.output != opts.input && nbytes > 0) {
      std::memcpy(opts.output, opts.input, nbytes);
    }
    return;
  }

  Slot slot = Slot::build(SlotPrefix::kReduce, opts.tag);
  const bool fuseOk = opts.customFn == nullptr;
  ReduceAlgorithm algo = opts.algorithm;
  if (algo == ReduceAlgorithm::kAuto) {
    // Measured tuning table first, then the loopback-measured fallback
    // (BASELINE.md reduce-to-root table, r4 re-sweep): the binomial wins
    // p50 through ~4 MiB (its log2(P) full-payload rounds ride the eager
    // pipeline well on one host) but its p99 tail is 3-4x WORSE than the
    // ring's from ~1 MiB up (full-payload rounds spike when the
    // shared-core scheduler misaligns). The fallback follows the p99
    // crossover — tail latency is what a collective's callers stall on —
    // and real multi-host DCN crosses earlier still (the root's in-link
    // serializes): tune there, or drop TPUCOLL_REDUCE_BINOMIAL_MAX to
    // ~256K-1M.
    if (auto tuned = tuning::tableReduce(ctx, opts.dtype, nbytes)) {
      algo = *tuned;
    } else {
      static const size_t binMax = collectives_detail::envBytes(
          "TPUCOLL_REDUCE_BINOMIAL_MAX", 2u << 20);
      algo = nbytes <= binMax ? ReduceAlgorithm::kBinomial
                              : ReduceAlgorithm::kRing;
    }
  }
  auto traceSpan = ctx->tracer().span(
      "reduce", nbytes, -1, tuning::reduceAlgorithmName(algo));
  frOp.setAlgorithm(tuning::reduceAlgorithmName(algo));
  profOp.setAlgorithm(tuning::reduceAlgorithmName(algo));

  PlanKey key;
  key.opcode = static_cast<uint8_t>(PlanOp::kReduce);
  key.algorithm = static_cast<uint8_t>(algo);
  key.dtype = static_cast<uint8_t>(opts.dtype);
  key.op = static_cast<uint8_t>(opts.op);
  key.root = opts.root;
  key.tag = opts.tag;
  key.ptrA = reinterpret_cast<uintptr_t>(opts.input);
  key.ptrB = reinterpret_cast<uintptr_t>(opts.output);
  key.nbytes = nbytes;
  PlanHandle planh =
      fuseOk ? PlanHandle(ctx, key) : PlanHandle(ctx);

  // Non-root ranks work in plan scratch (the ring writes the whole
  // buffer during the reduce-scatter phase, so it must be full-size
  // even though only one block of it is ever sent on). The stage's
  // registration doubles as the schedule's work buffer.
  char* result;
  transport::UnboundBuffer* resultBuf;
  if (isRoot) {
    result = bytePtr(opts.output);
    resultBuf = planh->userBuf(0, result, nbytes);
  } else {
    auto st = planh->stage(kStageReduceResult, nbytes);
    result = st.data;
    resultBuf = st.buf;
  }
  if (result != opts.input) {
    PhaseScope ps(Phase::kPack);
    std::memcpy(result, opts.input, nbytes);
  }

  switch (algo) {
    case ReduceAlgorithm::kBinomial:
      binomialReduce(ctx, *planh, result, resultBuf, opts.count, elsize,
                     fn, opts.root, fuseOk, slot, timeout);
      break;
    case ReduceAlgorithm::kRing:
      ringReduce(ctx, *planh, result, resultBuf, opts.count, elsize, fn,
                 opts.root, fuseOk, slot, timeout);
      break;
    default:
      TC_THROW(EnforceError, "unknown reduce algorithm");
  }
}

// Ring reduce-scatter with per-rank result blocks (reference analog:
// gloo/reduce_scatter.h halving-doubling; the ring keeps per-step traffic
// uniform and handles arbitrary recvCounts without bit-reversal reordering).
void reduceScatter(ReduceScatterOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "reduceScatter: null context");
  auto traceSpan = ctx->tracer().span("reduce_scatter");
  const auto timeout = detail::effectiveTimeout(opts);
  const int rank = ctx->rank();
  const int size = ctx->size();
  TC_ENFORCE_EQ(opts.recvCounts.size(), static_cast<size_t>(size));
  const size_t elsize = elementSize(opts.dtype);
  ReduceFn fn = opts.customFn != nullptr
                  ? opts.customFn
                  : getReduceFn(opts.dtype, opts.op);
  size_t total = 0;
  for (size_t c : opts.recvCounts) {
    total += c * elsize;
  }
  MetricsOp metricsOp(&ctx->metrics(), MetricOp::kReduceScatter, total);
  FlightRecOp frOp(
      &ctx->flightrec(), "reduce_scatter", nullptr,
      Slot::build(SlotPrefix::kReduceScatter, opts.tag).value(), -1, total,
      static_cast<uint8_t>(opts.dtype));
  ProfileOpScope profOp(&ctx->profiler(), "reduce_scatter", frOp.cseq(),
                        total);
  span::OpScope spanOp(&ctx->spans(), "reduce_scatter", frOp.cseq());

  if (size == 1) {
    std::memcpy(opts.output, opts.input, total);
    return;
  }

  Slot slot = Slot::build(SlotPrefix::kReduceScatter, opts.tag);
  const bool fuseOk = opts.customFn == nullptr;
  ReduceScatterAlgorithm algo = opts.algorithm;
  // Flat topology: a hierarchical request has no second plane; run it
  // through the normal auto dispatch instead.
  if (algo == ReduceScatterAlgorithm::kHier && !group::hierEligible(ctx)) {
    algo = ReduceScatterAlgorithm::kAuto;
  }
  if (algo == ReduceScatterAlgorithm::kAuto && fuseOk) {
    // Installed schedule plane first (see allreduce). Generated
    // reduce-scatter schedules assume even chunk geometry (chunk r is
    // rank r's result block); uneven recvCounts fall through to native.
    bool even = true;
    for (size_t c : opts.recvCounts) {
      even = even && c == opts.recvCounts[0];
    }
    if (even) {
      if (auto prog = electedSchedule(ctx, "reduce_scatter", opts.dtype,
                                      total, /*codedOk=*/false)) {
        const char* lbl = schedule::internedLabel(prog->label);
        auto schedSpan =
            ctx->tracer().span("reduce_scatter", total, -1, lbl);
        frOp.setAlgorithm(lbl);
        profOp.setAlgorithm(lbl);
        PlanKey key;
        key.opcode = static_cast<uint8_t>(PlanOp::kReduceScatter);
        key.algorithm = kScheduledAlgorithm;
        key.dtype = static_cast<uint8_t>(opts.dtype);
        key.op = static_cast<uint8_t>(opts.op);
        key.tag = opts.tag;
        key.ptrA = reinterpret_cast<uintptr_t>(opts.input);
        key.ptrB = reinterpret_cast<uintptr_t>(opts.output);
        key.nbytes = total;
        key.aux = plan::hashCounts(opts.recvCounts) ^ fnvName(prog->name);
        PlanHandle planh(ctx, key);
        // Work in a plan-staged copy so the caller's input stays
        // intact; the stage's registration doubles as the schedule's
        // work buffer (the interpreter owns slots 0/1).
        auto st = planh->stage(kStageRsWork, total);
        {
          PhaseScope ps(Phase::kPack);
          std::memcpy(st.data, opts.input, total);
        }
        schedule::run(ctx, *planh, *prog, st.data, total / elsize, elsize,
                      fn, opts.dtype, slot, timeout, st.buf);
        {
          PhaseScope ps(Phase::kUnpack);
          const size_t blockBytes = opts.recvCounts[rank] * elsize;
          std::memcpy(opts.output, st.data + size_t(rank) * blockBytes,
                      blockBytes);
        }
        return;
      }
    }
  }
  if (algo == ReduceScatterAlgorithm::kAuto) {
    // Measured tuning table first (keyed by total payload bytes), then
    // the crossovers measured on loopback P=4/8 (BASELINE.md round 3):
    // recursive halving wins through ~256K, the ring beyond. The
    // single-round direct exchange loses on a shared-core loopback
    // (its P*(P-1) total messages cost more than its one-round latency
    // saves there), so the fallback defaults it OFF; a tuned table on
    // real DCN, where propagation delay dominates per-message CPU, can
    // elect it from measurement. TPUCOLL_RS_DIRECT_MAX /
    // TPUCOLL_RS_HD_MAX move the fallback crossovers (total payload
    // bytes).
    if (auto tuned = tuning::tableReduceScatter(ctx, opts.dtype, total)) {
      algo = *tuned;
    } else {
      static const size_t directMax = collectives_detail::envBytes(
          "TPUCOLL_RS_DIRECT_MAX", 0);
      static const size_t hdMax = collectives_detail::envBytes(
          "TPUCOLL_RS_HD_MAX", 256u << 10);
      algo = total <= directMax ? ReduceScatterAlgorithm::kDirect
             : total <= hdMax   ? ReduceScatterAlgorithm::kHalvingDoubling
                                : ReduceScatterAlgorithm::kRing;
    }
  }
  frOp.setAlgorithm(tuning::reduceScatterAlgorithmName(algo));
  profOp.setAlgorithm(tuning::reduceScatterAlgorithmName(algo));
  if (algo == ReduceScatterAlgorithm::kHier) {
    // Phases are collectives on split sub-contexts with their own plan
    // caches; the parent plan machinery below is skipped.
    group::hierReduceScatter(ctx, opts.input, opts.output,
                             opts.recvCounts, opts.dtype, opts.op,
                             opts.customFn, opts.tag, timeout);
    return;
  }

  PlanKey key;
  key.opcode = static_cast<uint8_t>(PlanOp::kReduceScatter);
  key.algorithm = static_cast<uint8_t>(algo);
  key.dtype = static_cast<uint8_t>(opts.dtype);
  key.op = static_cast<uint8_t>(opts.op);
  key.tag = opts.tag;
  key.ptrA = reinterpret_cast<uintptr_t>(opts.input);
  key.ptrB = reinterpret_cast<uintptr_t>(opts.output);
  key.nbytes = total;
  key.aux = plan::hashCounts(opts.recvCounts);
  PlanHandle planh =
      fuseOk ? PlanHandle(ctx, key) : PlanHandle(ctx);
  const Blocks& blocks = planh->blocks(
      0, [&] { return countBlocks(opts.recvCounts, elsize); });

  // Work in a plan-staged copy so the caller's input stays intact; the
  // stage's registration is the schedule's work buffer.
  auto st = planh->stage(kStageRsWork, total);
  char* work = st.data;
  {
    PhaseScope ps(Phase::kPack);
    std::memcpy(work, opts.input, total);
  }
  switch (algo) {
    case ReduceScatterAlgorithm::kDirect:
      algorithms::directReduceScatter(ctx, *planh, work, st.buf, blocks,
                                      fn, elsize, slot, timeout, fuseOk);
      break;
    case ReduceScatterAlgorithm::kHalvingDoubling:
      algorithms::hdReduceScatter(ctx, *planh, work, st.buf, blocks, fn,
                                  elsize, slot, timeout, fuseOk);
      break;
    case ReduceScatterAlgorithm::kRing:
      ringReduceScatter(ctx, *planh, work, blocks, fn, elsize, slot, 0,
                        /*startShift=*/-1, timeout, st.buf, fuseOk);
      break;
    case ReduceScatterAlgorithm::kRingQ8Wire:
      TC_ENFORCE(opts.dtype == DataType::kFloat32,
                 "q8-wire reduce_scatter requires float32 payloads");
      TC_ENFORCE(opts.op == ReduceOp::kSum && opts.customFn == nullptr,
                 "q8-wire reduce_scatter supports builtin sum only");
      algorithms::q8WireRingReduceScatter(ctx, *planh, work, st.buf,
                                          blocks, slot, timeout);
      break;
    case ReduceScatterAlgorithm::kRingQ4Wire:
      TC_ENFORCE(opts.dtype == DataType::kFloat32,
                 "q4-wire reduce_scatter requires float32 payloads");
      TC_ENFORCE(opts.op == ReduceOp::kSum && opts.customFn == nullptr,
                 "q4-wire reduce_scatter supports builtin sum only");
      algorithms::q4WireRingReduceScatter(ctx, *planh, work, st.buf,
                                          blocks, slot, timeout);
      break;
    default:
      TC_THROW(EnforceError, "unknown reduce_scatter algorithm");
  }
  {
    PhaseScope ps(Phase::kUnpack);
    std::memcpy(opts.output, work + blocks.offset[rank],
                blocks.bytes[rank]);
  }
}

}  // namespace tpucoll
