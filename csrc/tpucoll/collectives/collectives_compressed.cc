// Ring allreduce with bfloat16 wire compression for float32 payloads.
//
// Gradient-averaging traffic is bandwidth-bound and tolerates reduced
// wire precision (standard DDP practice; the EQuARX line of work applies
// the same idea inside XLA for ICI). This schedule keeps accumulation in
// float32 but converts every segment to bfloat16 for the wire, halving
// bytes moved in both ring phases.
//
// Precision contract: each reduce-scatter hop re-quantizes the partial
// sum, so worst-case error grows with the hop count (P-1) at bfloat16's
// ~3 significant digits; the allgather phase transmits each final block
// once, so all ranks decode IDENTICAL results (consensus is preserved —
// every rank rounds the same bf16 stream). Opt in via
// AllreduceAlgorithm::kRingBf16Wire; float32 only.
#include <cstring>

#include "tpucoll/collectives/algorithms.h"
#include "tpucoll/collectives/collectives.h"
#include "tpucoll/collectives/detail.h"
#include "tpucoll/collectives/plan.h"
#include "tpucoll/common/profile.h"

namespace tpucoll {
namespace algorithms {

using collectives_detail::Blocks;
using collectives_detail::evenBlocks;
using collectives_detail::SegSpan;
using collectives_detail::segmentize;
using profile::Phase;
using profile::PhaseScope;

namespace {

inline void compressSegment(const float* src, uint16_t* dst, size_t n) {
  f32StreamToBf16(src, dst, n);
}

// work[i] += decode(in[i])
inline void accumulateCompressed(float* work, const uint16_t* in, size_t n) {
  bf16StreamAccumulate(work, in, n);
}

inline void decodeSegment(const uint16_t* in, float* dst, size_t n) {
  bf16StreamToF32(in, dst, n);
}

// RecvReduceFn-shaped adapters for the typed fused receive (bf16 wire
// elements folded into / decoded into the f32 accumulator; see
// UnboundBuffer::recvReduceTyped).
void accumulateBf16Fn(void* acc, const void* in, size_t n) {
  bf16StreamAccumulate(static_cast<float*>(acc),
                       static_cast<const uint16_t*>(in), n);
}

void decodeBf16Fn(void* acc, const void* in, size_t n) {
  bf16StreamToF32(static_cast<const uint16_t*>(in),
                  static_cast<float*>(acc), n);
}

}  // namespace

void bf16WireRingAllreduce(Context* ctx, plan::Plan& plan,
                           char* workBytes, size_t count, Slot slot,
                           std::chrono::milliseconds timeout) {
  const int rank = ctx->rank();
  const int size = ctx->size();
  float* work = reinterpret_cast<float*>(workBytes);
  const Blocks& blocks = plan.blocks(
      0, [&] { return evenBlocks(count, size, sizeof(float)); });
  size_t maxBlockElems = 0;
  for (size_t b : blocks.bytes) {
    maxBlockElems = std::max(maxBlockElems, b / sizeof(float));
  }
  const int right = (rank + 1) % size;
  const int left = (rank - 1 + size) % size;
  const int steps = size - 1;

  // Typed fused receive: wire bf16 elements fold straight out of the shm
  // ring into the f32 work array (decode+accumulate / decode-in-place),
  // eliminating the rx staging entirely on shm sources (same policy as
  // the plain ring, collectives_detail::fuseRecvReduce; wire elsize 2,
  // accumulator elsize 4). The forward leg of the fused allgather
  // re-compresses from work — exact, because bf16 -> f32 -> bf16 is a
  // lossless roundtrip, so the forwarded wire bytes are identical to the
  // verbatim copy the staged path sends (consensus preserved).
  const bool fuse = collectives_detail::fuseRecvReduce(
      ctx, /*fuseOk=*/true, /*elsize=*/sizeof(uint16_t), left);

  // Wire staging: bf16 segments. tx double-buffered (the sent segment must
  // stay valid until waitSend); rx double-buffered like the fp32 ring,
  // lazily acquired (never touched when fused).
  const size_t wireBlock = std::max(maxBlockElems * sizeof(uint16_t),
                                    size_t(1));
  auto txStage = plan.stage(1, 2 * wireBlock);
  uint16_t* tx = reinterpret_cast<uint16_t*>(txStage.data);
  auto* txBuf = txStage.buf;
  plan::LazyStage rxStage(plan, 2, 2 * wireBlock);
  auto* workBuf = plan.userBuf(0, work, count * sizeof(float));

  auto blockElems = [&](int b) { return blocks.bytes[b] / sizeof(float); };
  auto blockStart = [&](int b) {
    return blocks.offset[b] / sizeof(float);
  };
  auto rx = [&]() {
    return reinterpret_cast<uint16_t*>(rxStage.data());
  };

  // --- reduce-scatter (send block rank-s, reduce block rank-s-1) ---
  for (int step = 0; step < steps; step++) {
    const int sendBlock = (rank - step + 2 * size) % size;
    const int recvBlock = (rank - step - 1 + 2 * size) % size;
    const int txSlot = step % 2;
    const uint64_t s = slot.offset(step).value();
    uint16_t* txSeg = tx + txSlot * maxBlockElems;
    {
      PhaseScope ps(Phase::kPack);
      compressSegment(work + blockStart(sendBlock), txSeg,
                      blockElems(sendBlock));
    }
    {
      PhaseScope ps(Phase::kPost);
      if (fuse) {
        workBuf->recvReduceTyped(left, s, accumulateBf16Fn,
                                 sizeof(uint16_t), sizeof(float),
                                 blockStart(recvBlock) * sizeof(float),
                                 blockElems(recvBlock) * sizeof(uint16_t));
      } else {
        rxStage.buf()->recv(left, s, (step % 2) * wireBlock,
                            blockElems(recvBlock) * sizeof(uint16_t));
      }
    }
    {
      PhaseScope ps(Phase::kPost, right, s,
                    blockElems(sendBlock) * sizeof(uint16_t));
      txBuf->send(right, s, txSlot * wireBlock,
                  blockElems(sendBlock) * sizeof(uint16_t));
    }
    if (fuse) {
      PhaseScope ps(Phase::kWireWait, left, s,
                    blockElems(recvBlock) * sizeof(uint16_t));
      workBuf->waitRecv(nullptr, timeout);
    } else {
      {
        PhaseScope ps(Phase::kWireWait, left, s,
                      blockElems(recvBlock) * sizeof(uint16_t));
        rxStage.buf()->waitRecv(nullptr, timeout);
      }
      PhaseScope ps(Phase::kReduce);
      accumulateCompressed(work + blockStart(recvBlock),
                           rx() + (step % 2) * maxBlockElems,
                           blockElems(recvBlock));
    }
    PhaseScope ps(Phase::kWireWait);
    txBuf->waitSend(timeout);
  }

  // --- allgather: rank r owns reduced block (r+1). The owner compresses
  // its block ONCE; every rank (owner included) adopts the decoded bf16
  // values so results are identical everywhere. Received wire segments
  // are forwarded without re-rounding: verbatim on the staged path,
  // re-compressed from the decoded block on the fused path (byte-
  // identical, see above). ---
  const uint64_t agBase = steps;
  {
    PhaseScope ps(Phase::kPack);
    const int own = (rank + 1) % size;
    compressSegment(work + blockStart(own), tx, blockElems(own));
    decodeSegment(tx, work + blockStart(own), blockElems(own));
  }
  for (int step = 0; step < steps; step++) {
    const int sendBlock = (rank + 1 - step + 2 * size) % size;
    const int recvBlock = (rank - step + 2 * size) % size;
    const uint64_t s = slot.offset(agBase + step).value();
    const int txSlot = step % 2;
    const int rxSlot = step % 2;
    if (step == 0) {
      // Own block already sits compressed in tx slot 0.
    } else if (fuse) {
      // Re-compress the block decoded last step (exact roundtrip).
      PhaseScope ps(Phase::kPack);
      compressSegment(work + blockStart(sendBlock),
                      tx + txSlot * maxBlockElems, blockElems(sendBlock));
    } else {
      // Forward the wire bytes received last step.
      PhaseScope ps(Phase::kPack);
      std::memcpy(tx + txSlot * maxBlockElems,
                  rx() + ((step - 1) % 2) * maxBlockElems,
                  blockElems(sendBlock) * sizeof(uint16_t));
    }
    {
      PhaseScope ps(Phase::kPost);
      if (fuse) {
        workBuf->recvReduceTyped(left, s, decodeBf16Fn, sizeof(uint16_t),
                                 sizeof(float),
                                 blockStart(recvBlock) * sizeof(float),
                                 blockElems(recvBlock) * sizeof(uint16_t));
      } else {
        rxStage.buf()->recv(left, s, rxSlot * wireBlock,
                            blockElems(recvBlock) * sizeof(uint16_t));
      }
    }
    {
      PhaseScope ps(Phase::kPost, right, s,
                    blockElems(sendBlock) * sizeof(uint16_t));
      txBuf->send(right, s, txSlot * wireBlock,
                  blockElems(sendBlock) * sizeof(uint16_t));
    }
    if (fuse) {
      PhaseScope ps(Phase::kWireWait, left, s,
                    blockElems(recvBlock) * sizeof(uint16_t));
      workBuf->waitRecv(nullptr, timeout);
    } else {
      {
        PhaseScope ps(Phase::kWireWait, left, s,
                      blockElems(recvBlock) * sizeof(uint16_t));
        rxStage.buf()->waitRecv(nullptr, timeout);
      }
      PhaseScope ps(Phase::kUnpack);
      decodeSegment(rx() + rxSlot * maxBlockElems,
                    work + blockStart(recvBlock), blockElems(recvBlock));
    }
    PhaseScope ps(Phase::kWireWait);
    txBuf->waitSend(timeout);
  }
}

}  // namespace algorithms
}  // namespace tpucoll
