// Ring allreduce with bfloat16 wire compression for float32 payloads.
//
// Gradient-averaging traffic is bandwidth-bound and tolerates reduced
// wire precision (standard DDP practice; the EQuARX line of work applies
// the same idea inside XLA for ICI). This schedule keeps accumulation in
// float32 but converts every segment to bfloat16 for the wire, halving
// bytes moved in both ring phases.
//
// Precision contract: each reduce-scatter hop re-quantizes the partial
// sum, so worst-case error grows with the hop count (P-1) at bfloat16's
// ~3 significant digits — tightened by the error-feedback residuals
// (TPUCOLL_WIRE_EF, wire_ring.h) on repeated reductions; the allgather
// phase transmits each final block once, so all ranks decode IDENTICAL
// results (consensus is preserved — every rank rounds the same bf16
// stream; bf16 -> f32 -> bf16 is a lossless roundtrip, which is what
// lets fused allgather hops re-encode instead of staging). Opt in via
// AllreduceAlgorithm::kRingBf16Wire; float32 only.
//
// The schedule itself lives in wire_ring.cc (one pipelined engine for
// every codec); this file binds it to the bf16 descriptor.
#include "tpucoll/collectives/algorithms.h"
#include "tpucoll/collectives/wire_ring.h"

namespace tpucoll {
namespace algorithms {

void bf16WireRingAllreduce(Context* ctx, plan::Plan& plan,
                           char* workBytes, size_t count, Slot slot,
                           std::chrono::milliseconds timeout) {
  wireRingAllreduce(ctx, plan, bf16WireCodec(), workBytes, count, slot,
                    timeout);
}

}  // namespace algorithms
}  // namespace tpucoll
