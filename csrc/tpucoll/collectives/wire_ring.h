// Pipelined wire-codec ring engine: ONE schedule serving every lossy
// wire codec (wire_codec.h descriptors — bf16/q8/q4), replacing the
// per-codec rings of collectives_compressed.cc / collectives_q8.cc.
//
// What "pipelined" buys (TPUCOLL_CODEC_PIPELINE = D): each ring hop's
// stream splits into up to D unit-aligned sub-blocks that encode,
// transmit and decode independently — sub k+1 encodes while sub k is on
// the wire, and the receiver decodes each sub AS IT ARRIVES instead of
// after the whole hop lands. Arrival order is taken from the transport
// (UnboundBuffer::waitRecvSlot): striped and non-striped sub-messages
// ride different channel sets, so completion order is NOT posting
// order. With D = 1 the engine reproduces the pre-pipeline wire
// protocol (one message per hop) exactly.
//
// Codec work runs on the codec pool (common/codec_pool.h): at D = 1 the
// hop's stream shards across TPUCOLL_CODEC_THREADS lanes; at D > 1 each
// sub-block is an async pool job whose worker also posts the sub's send
// the moment its encode finishes (in sub order), so the caller's thread
// drains arrivals instead of chaperoning encodes. Both are
// byte-identical to the serial walk (unit-aligned boundaries).
//
// Error feedback (TPUCOLL_WIRE_EF, default on): a per-plan residual
// buffer accumulates each origin encode's quantization error and folds
// it into the next call's encode of the same elements, so repeated
// reductions (the gradient-averaging steady state) see the error
// DITHER toward zero instead of biasing one way. Residuals apply only
// to origin encodes (reduce-scatter sends + the allgather owner's
// encode) — never to allgather forwards, which stay verbatim (q8/q4)
// or exact re-encodes (bf16), preserving cross-rank consensus exactly
// as before. Residuals live in the plan's arena, so they persist
// across calls on a cached plan and start zeroed when (re)allocated.
#pragma once

#include <chrono>

#include "tpucoll/collectives/detail.h"
#include "tpucoll/collectives/plan.h"
#include "tpucoll/collectives/wire_codec.h"
#include "tpucoll/context.h"
#include "tpucoll/types.h"

namespace tpucoll {
namespace algorithms {

// TPUCOLL_WIRE_EF (strict 0/1, default 1): error-feedback residuals on
// the wire rings' origin encodes. Read once per process.
bool wireErrorFeedback();

// Ring allreduce over `codec`'s wire format: reduce-scatter with
// quantized hops (float32 accumulation), then an allgather whose
// forwards preserve bit-identical results on every rank. Slot budget:
// 2 * (P-1) * TPUCOLL_CODEC_PIPELINE deltas from `slot`.
void wireRingAllreduce(Context* ctx, plan::Plan& plan,
                       const WireCodec& codec, char* work, size_t count,
                       Slot slot, std::chrono::milliseconds timeout);

// Ring reduce-scatter over `codec`'s wire (startShift -1: rank r ends
// owning reduced block r of `blocks` in full-precision float32; only
// wire hops quantize). Stage slots 0/1; scratch slots 3/4 (residual +
// encode scratch) — the caller's work copy owns slot 2.
void wireRingReduceScatter(Context* ctx, plan::Plan& plan,
                           const WireCodec& codec, char* work,
                           transport::UnboundBuffer* workBuf,
                           const collectives_detail::Blocks& blocks,
                           Slot slot, std::chrono::milliseconds timeout);

}  // namespace algorithms
}  // namespace tpucoll
