// Persistent collective plans: per-Context LRU cache of the per-call
// setup a repeated collective otherwise rebuilds every step.
//
// Training traffic is the degenerate-best case for caching — the same
// (op, algorithm, ptr, nbytes, dtype, root/tag) tuple every step from a
// gradient bucketer — yet each call used to re-create UnboundBuffers
// (two transport-mutex passes apiece: registration bookkeeping at birth,
// cancel+drain scans at death), re-acquire scratch, and recompute the
// block/segment schedule. A Plan owns all of that across calls:
//
//   - registered UnboundBuffers over the caller's pointers (userBuf);
//   - grow-only scratch arenas with their registrations (stage);
//   - the memoized block layout and segment lists (blocks / segments).
//
// The steady-state Nth call of a repeated collective therefore performs
// zero allocations and zero buffer registrations — only posts and waits.
// `ubuf_creates` in the metrics registry is the enforced evidence;
// `plan_hits`/`plan_misses`/`plan_evictions` expose the cache itself.
//
// Pointer-lifetime contract (docs/design.md, docs/errors.md): a cached
// plan retains a registration over the caller's buffer BETWEEN calls.
// The memory is only dereferenced while a collective is running on the
// same (ptr, nbytes); freeing the buffer afterwards is safe — the stale
// registration is dropped on eviction, invalidation, or context close,
// and a recycled address is re-keyed by (ptr, nbytes) so a different
// size misses. What is NOT safe is re-issuing the collective after the
// buffer was freed — exactly as unsafe as it always was.
//
// Invalidation:
//   - Context::close() / destruction drop every plan (before the
//     transport dies — the registrations point into it);
//   - Context::setTuningTable() drops every plan: a kAuto key embeds the
//     RESOLVED algorithm, and a new table may elect a different one;
//   - an exception unwinding through a planned collective drops that
//     plan (its buffers may still carry in-flight ops; the destructor
//     drains them exactly like a transient buffer's would);
//   - a changed ptr/size/tag simply misses and ages the old entry out
//     of the LRU (capacity: TPUCOLL_PLAN_LRU, default 64).
//
// Concurrency: plans are per-(Context, key). Concurrent collectives on
// one context must use distinct tags (the library-wide contract), and
// tag is part of the key, so two legal concurrent calls never share a
// plan; a same-key race (illegal anyway) falls back to a transient plan
// via the per-plan in-use flag rather than corrupting state.
//
// TPUCOLL_PLAN_CACHE=0 disables caching entirely: every call gets a
// transient Plan whose stages ride the Context scratch pool — byte-for-
// byte the pre-plan behavior (the A/B arm bench.py --latency measures).
#pragma once

#include <atomic>
#include <cstdint>
#include <deque>
#include <list>
#include <memory>
#include <mutex>
#include <optional>
#include <unordered_map>
#include <utility>
#include <vector>

#include "tpucoll/collectives/detail.h"
#include "tpucoll/common/arena.h"
#include "tpucoll/context.h"
#include "tpucoll/transport/unbound_buffer.h"

namespace tpucoll {
namespace plan {

// Opcode namespace for plan keys (decoupled from MetricOp: plans key
// the SCHEDULE actually run, e.g. allgather and allgatherv share one).
enum class PlanOp : uint8_t {
  kAllreduce = 0,
  kReduce,
  kReduceScatter,
  kAllgatherv,
  kBroadcast,
  kBarrier,
  kGatherv,
  kScatter,
  kAlltoallv,
  kAlltoallBruck,
};

struct PlanKey {
  uint8_t opcode{0};
  uint8_t algorithm{0};  // RESOLVED algorithm (post-kAuto), 0 when n/a
  uint8_t dtype{0};
  uint8_t op{0};         // ReduceOp, 0 when n/a
  int32_t root{-1};
  uint32_t tag{0};
  uintptr_t ptrA{0};     // primary caller buffer (work / input)
  uintptr_t ptrB{0};     // secondary caller buffer (output), 0 when n/a
  uint64_t nbytes{0};    // total payload bytes
  uint64_t aux{0};       // counts-vector hash for the v-variants

  bool operator==(const PlanKey& o) const {
    return opcode == o.opcode && algorithm == o.algorithm &&
           dtype == o.dtype && op == o.op && root == o.root &&
           tag == o.tag && ptrA == o.ptrA && ptrB == o.ptrB &&
           nbytes == o.nbytes && aux == o.aux;
  }
};

// FNV-1a over a size_t vector: the aux hash for per-rank count vectors
// (allgatherv/gatherv/reduce_scatter/alltoallv schedules depend on every
// entry, not just the total).
inline uint64_t hashCounts(const std::vector<size_t>& counts) {
  uint64_t h = 1469598103934665603ull;
  for (size_t c : counts) {
    h = (h ^ static_cast<uint64_t>(c)) * 1099511628211ull;
  }
  return h;
}

struct PlanKeyHash {
  size_t operator()(const PlanKey& k) const {
    uint64_t h = 1469598103934665603ull;
    auto mix = [&h](uint64_t v) { h = (h ^ v) * 1099511628211ull; };
    mix(k.opcode | (uint64_t(k.algorithm) << 8) |
        (uint64_t(k.dtype) << 16) | (uint64_t(k.op) << 24) |
        (uint64_t(uint32_t(k.root)) << 32));
    mix(k.tag);
    mix(k.ptrA);
    mix(k.ptrB);
    mix(k.nbytes);
    mix(k.aux);
    return static_cast<size_t>(h);
  }
};

// One collective's reusable resources. Cached instances live in the
// PlanCache and survive across calls; transient instances (cache
// disabled / non-cacheable call / same-key race) live for one call and
// stage through the Context scratch pool, reproducing the pre-plan
// behavior exactly.
class Plan {
 public:
  Plan(Context* ctx, bool cached) : ctx_(ctx), cached_(cached) {}

  Context* context() const { return ctx_; }
  bool isCached() const { return cached_; }

  // Registered buffer over caller memory, slot `idx` (schedules number
  // their buffers 0..: work first). A cached plan returns the previous
  // call's registration when (ptr, nbytes) match — the zero-
  // registration steady state; a mismatch (impossible through the
  // cache, whose key pins the pointers) rebuilds.
  transport::UnboundBuffer* userBuf(size_t idx, void* ptr, size_t nbytes);

  struct Stage {
    char* data{nullptr};
    transport::UnboundBuffer* buf{nullptr};
  };
  // Arena-backed staging memory with its registration, slot `idx`.
  // Cached plans grow their arena to the high watermark once and then
  // return the same block + registration every call; transient plans
  // ride the Context scratch pool.
  Stage stage(size_t idx, size_t minBytes);

  // Staging memory only, no registration (local shuffle buffers, e.g.
  // Bruck's rotation scratch). Shares the stage slot namespace: a given
  // idx is either scratch or stage for a plan's whole life.
  char* scratch(size_t idx, size_t minBytes);

  // scratch() that also reports whether the memory is FRESH — newly
  // allocated or moved, i.e. its prior contents are gone. State that
  // must persist across calls on a cached plan (the wire rings'
  // error-feedback residuals) zero-fills exactly when *fresh is set;
  // transient plans report fresh on every call (pool pages rotate).
  char* scratch(size_t idx, size_t minBytes, bool* fresh);

  // Memoized block layout, slot `idx`: computed by `make()` on the
  // first call, returned by reference afterwards. The returned
  // reference stays valid across later blocks()/segments() calls
  // (deque storage — end-insertion never moves existing slots), so a
  // schedule may hold several layouts at once.
  template <typename Fn>
  const collectives_detail::Blocks& blocks(size_t idx, Fn&& make) {
    while (blocks_.size() <= idx) {
      blocks_.emplace_back();
    }
    auto& slot = blocks_[idx];
    if (!slot.have) {
      slot.value = make();
      slot.have = true;
    }
    return slot.value;
  }

  // Memoized segment list for one block size (collectives_detail::
  // segmentize). A ring schedule asks for at most two distinct block
  // sizes (evenBlocks remainders differ by one element), so a linear
  // scan over a tiny vector beats any map.
  const std::vector<collectives_detail::SegSpan>& segments(size_t blockBytes,
                                                           size_t elsize);

 private:
  friend class PlanCache;
  friend class PlanHandle;

  struct UserSlot {
    uintptr_t ptr{0};
    size_t nbytes{0};
    std::unique_ptr<transport::UnboundBuffer> buf;
  };
  struct StageSlot {
    Arena arena;  // cached plans
    std::optional<Context::Scratch> pooled;  // transient plans
    std::unique_ptr<transport::UnboundBuffer> buf;
  };
  struct BlocksSlot {
    bool have{false};
    collectives_detail::Blocks value;
  };

  Context* const ctx_;
  const bool cached_;
  PlanKey key_{};  // set by the cache; identifies the entry for release
  // One plan serves one collective call at a time; a same-key concurrent
  // acquire (an API-contract violation) degrades to a transient plan
  // instead of sharing live buffers. CAS acquire/release in PlanCache.
  std::atomic<bool> inUse_{false};
  // users_/stages_ hand out raw pointers to heap objects (UnboundBuffer,
  // arena block) that survive container growth; blocks_/segs_ hand out
  // REFERENCES to the elements themselves, so they live in deques,
  // whose end-insertion never relocates existing elements.
  std::vector<UserSlot> users_;
  std::vector<StageSlot> stages_;
  std::deque<BlocksSlot> blocks_;
  std::deque<std::pair<uint64_t, std::vector<collectives_detail::SegSpan>>>
      segs_;
};

// LRU cache of Plans, one per Context (and so one per async-engine lane:
// lanes fork private sub-Contexts). All methods are thread-safe.
class PlanCache {
 public:
  explicit PlanCache(Context* ctx);

  // Lookup-or-create the entry for `key`, marking it in use. Returns
  // nullptr when caching is disabled or the entry is busy (caller runs
  // a transient plan). Counts plan_hits / plan_misses / plan_evictions
  // in the context's metrics registry.
  std::shared_ptr<Plan> acquire(const PlanKey& key);

  // Return a plan acquired above. poisoned=true (an exception unwound
  // through the collective) drops the entry: its buffers may carry
  // in-flight ops that only the destructor's cancel+drain can account
  // for, so it must never serve another call.
  void release(const std::shared_ptr<Plan>& plan, bool poisoned);

  // Drop every entry (close / rebuild / tuning-table install). In-use
  // plans survive via their callers' shared_ptr and die on release.
  void clear();

  size_t size() const;
  bool enabled() const { return enabled_; }

 private:
  struct Entry {
    PlanKey key;
    std::shared_ptr<Plan> plan;
  };
  using Lru = std::list<Entry>;

  Context* const ctx_;
  const bool enabled_;
  const size_t capacity_;
  mutable std::mutex mu_;
  Lru lru_;  // front = most recently used
  std::unordered_map<PlanKey, Lru::iterator, PlanKeyHash> map_;
};

// RAII scope for one collective call: acquires the cached plan (or a
// transient one), releases it at scope exit, and poisons the cache
// entry when unwinding through an exception.
class PlanHandle {
 public:
  // Transient-only handle (non-cacheable call: custom reduction fn).
  explicit PlanHandle(Context* ctx)
      : plan_(std::make_shared<Plan>(ctx, /*cached=*/false)) {}

  PlanHandle(Context* ctx, const PlanKey& key);
  ~PlanHandle();

  PlanHandle(const PlanHandle&) = delete;
  PlanHandle& operator=(const PlanHandle&) = delete;

  Plan& operator*() const { return *plan_; }
  Plan* operator->() const { return plan_.get(); }
  Plan* get() const { return plan_.get(); }

 private:
  std::shared_ptr<Plan> plan_;
  PlanCache* cache_{nullptr};  // non-null when plan_ came from the cache
  int exceptionsAtEntry_{0};
};

// Lazy staging view (the LazyScratch successor): materializes the
// plan's stage slot on first touch, so fully fused schedules never
// allocate (transient) or warm (cached) staging they won't use.
class LazyStage {
 public:
  LazyStage(Plan& plan, size_t idx, size_t minBytes)
      : plan_(plan), idx_(idx), minBytes_(minBytes) {}
  char* data() {
    ensure();
    return stage_.data;
  }
  transport::UnboundBuffer* buf() {
    ensure();
    return stage_.buf;
  }

 private:
  void ensure() {
    if (stage_.buf == nullptr) {
      stage_ = plan_.stage(idx_, minBytes_);
    }
  }
  Plan& plan_;
  const size_t idx_;
  const size_t minBytes_;
  Plan::Stage stage_{};
};

}  // namespace plan
}  // namespace tpucoll
