// Ring collectives with int8 block-quantized wire compression for
// float32 sum payloads (the host-plane analog of the TPU plane's q8
// Pallas ring, gloo_tpu/ops/pallas_ring.py; EQuARX line of work).
//
// Wire format (math.h): consecutive units of [float32 scale][B int8
// codes], B = TPUCOLL_Q8_BLOCK (default 256); the final unit of each
// hop's stream carries the unpadded tail. ~4x fewer bytes on the wire
// than float32 (2x fewer than the bf16 codec) plus one 4-byte scale per
// block.
//
// Precision contract (documented in docs/algorithms.md + docs/errors.md):
//  - accumulation stays float32; only wire hops quantize;
//  - each reduce-scatter hop re-quantizes the partial sum (|x -
//    decode(x)| <= max|block| / 254 per element per hop); with error
//    feedback on (TPUCOLL_WIRE_EF, wire_ring.h) each origin encode
//    also folds in the previous call's quantization error, so repeated
//    reductions see the error dither toward zero instead of biasing;
//  - the allgather phase transmits each final block's quantized stream
//    ONCE and every rank forwards the received bytes verbatim, so all
//    ranks decode bit-identical results (consensus preserved). Unlike
//    the bf16 codec, q8 re-encoding a decoded block is NOT bit-exact
//    (the scale roundtrip through *127/127 double-rounds), so the
//    allgather never re-encodes — it always forwards.
//  - float32 + sum only; non-finite inputs poison their block's scale;
//  - TPUCOLL_Q8_BLOCK and TPUCOLL_CODEC_PIPELINE must match on every
//    rank (unit size and per-hop message count are wire protocol).
//
// The schedule itself lives in wire_ring.cc (one pipelined engine for
// every codec); this file binds it to the q8 descriptor.
#include "tpucoll/collectives/algorithms.h"
#include "tpucoll/collectives/wire_ring.h"

namespace tpucoll {
namespace algorithms {

// The fused arm passes a whole wire unit as the recvReduceTyped element;
// if the transport's combine ceiling ever drops below the largest unit,
// fuseRecvReduce would silently refuse every q8 hop (a pure perf loss no
// test would catch) — pin the invariant at compile time.
static_assert(transport::kMaxCombineElsize >=
                  kQ8ScaleBytes + kQ8MaxBlockElems,
              "q8 wire units must fit the transport combine ceiling "
              "(raise kMaxCombineElsize alongside kQ8MaxBlockElems)");

void q8WireRingAllreduce(Context* ctx, plan::Plan& plan, char* workBytes,
                         size_t count, Slot slot,
                         std::chrono::milliseconds timeout) {
  wireRingAllreduce(ctx, plan, q8WireCodec(), workBytes, count, slot,
                    timeout);
}

void q8WireRingReduceScatter(Context* ctx, plan::Plan& plan,
                             char* workBytes,
                             transport::UnboundBuffer* workBuf,
                             const collectives_detail::Blocks& blocks,
                             Slot slot,
                             std::chrono::milliseconds timeout) {
  wireRingReduceScatter(ctx, plan, q8WireCodec(), workBytes, workBuf,
                        blocks, slot, timeout);
}

}  // namespace algorithms
}  // namespace tpucoll
