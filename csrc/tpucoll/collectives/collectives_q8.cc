// Ring collectives with int8 block-quantized wire compression for
// float32 sum payloads (the host-plane analog of the TPU plane's q8
// Pallas ring, gloo_tpu/ops/pallas_ring.py; EQuARX line of work).
//
// Wire format (math.h): consecutive units of [float32 scale][B int8
// codes], B = TPUCOLL_Q8_BLOCK (default 256); the final unit of each
// hop's stream carries the unpadded tail. ~4x fewer bytes on the wire
// than float32 (2x fewer than the bf16 codec) plus one 4-byte scale per
// block.
//
// Precision contract (documented in docs/algorithms.md + docs/errors.md):
//  - accumulation stays float32; only wire hops quantize;
//  - each reduce-scatter hop re-quantizes the partial sum, so worst-case
//    error grows with the hop count (P-1) at ~2.4 decimal digits per
//    block (|x - decode(x)| <= max|block| / 254 per element per hop);
//  - the allgather phase transmits each final block's quantized stream
//    ONCE and every rank forwards the received bytes verbatim, so all
//    ranks decode bit-identical results (consensus preserved). Unlike
//    the bf16 codec, q8 re-encoding a decoded block is NOT bit-exact
//    (the scale roundtrip through *127/127 double-rounds), so the
//    allgather never re-encodes — it always stages and forwards.
//  - float32 + sum only; non-finite inputs poison their block's scale;
//  - TPUCOLL_Q8_BLOCK must match on every rank (both ends of each wire
//    parse the same unit size).
//
// Schedule shape mirrors collectives_compressed.cc. The reduce-scatter
// phase rides the typed fused receive (UnboundBuffer::recvReduceTyped)
// when the source pair is fuse-eligible AND the hop's block is a whole
// number of q8 units — the RecvReduceFn adapter folds whole units
// (scale header + codes) straight out of the shm ring into the float32
// work array. Ragged blocks and the allgather phase use the staged arm.
#include <cstring>

#include "tpucoll/collectives/algorithms.h"
#include "tpucoll/collectives/collectives.h"
#include "tpucoll/collectives/detail.h"
#include "tpucoll/collectives/plan.h"
#include "tpucoll/common/profile.h"

namespace tpucoll {
namespace algorithms {

// The fused arm passes a whole wire unit as the recvReduceTyped element;
// if the transport's combine ceiling ever drops below the largest unit,
// fuseRecvReduce would silently refuse every q8 hop (a pure perf loss no
// test would catch) — pin the invariant at compile time.
static_assert(transport::kMaxCombineElsize >=
                  kQ8ScaleBytes + kQ8MaxBlockElems,
              "q8 wire units must fit the transport combine ceiling "
              "(raise kMaxCombineElsize alongside kQ8MaxBlockElems)");

using collectives_detail::Blocks;
using collectives_detail::evenBlocks;
using profile::Phase;
using profile::PhaseScope;

namespace {

// RecvReduceFn-shaped adapter for the typed fused receive: `in` is n
// whole wire units (the fuse predicate below guarantees unit alignment),
// `acc` the float32 accumulator. The block size is process-global
// (TPUCOLL_Q8_BLOCK, resolved once), which is what lets a stateless
// function pointer parse the stream.
void accumulateQ8UnitsFn(void* acc, const void* in, size_t nUnits) {
  const size_t block = q8BlockElems();
  q8StreamAccumulate(static_cast<float*>(acc),
                     static_cast<const uint8_t*>(in), nUnits * block,
                     block);
}

// Ring reduce-scatter over `work` with q8-quantized hops. Identical
// block walk to ringReduceScatter (collectives_ring.cc): after P-1
// steps rank r owns block (r + 1 + startShift) mod P fully reduced in
// float32. startShift 0 feeds the allreduce allgather phase; -1 lands
// block r on rank r for the standalone reduce_scatter.
void q8RingReduceScatterPhase(Context* ctx, float* work,
                              const Blocks& blocks, Slot slot,
                              int startShift,
                              std::chrono::milliseconds timeout,
                              transport::UnboundBuffer* workBuf,
                              plan::LazyStage& rxStage,
                              uint8_t* tx,
                              transport::UnboundBuffer* txBuf,
                              size_t wireBlock) {
  const int rank = ctx->rank();
  const int size = ctx->size();
  const size_t block = q8BlockElems();
  const size_t unit = q8UnitBytes(block);
  const int right = (rank + 1) % size;
  const int left = (rank - 1 + size) % size;
  const int steps = size - 1;

  auto blockElems = [&](int b) { return blocks.bytes[b] / sizeof(float); };
  auto blockStart = [&](int b) {
    return blocks.offset[b] / sizeof(float);
  };

  // Fuse-eligibility of the source pair, resolved once (the ring only
  // receives from `left`); unit alignment is checked per hop.
  const bool pairFuse =
      collectives_detail::fuseRecvReduce(ctx, /*fuseOk=*/true, unit, left);

  for (int step = 0; step < steps; step++) {
    const int sendBlock = (rank + startShift - step + 2 * size) % size;
    const int recvBlock = (rank + startShift - step - 1 + 2 * size) % size;
    const int txSlot = step % 2;
    const uint64_t s = slot.offset(step).value();
    const size_t sendElems = blockElems(sendBlock);
    const size_t recvElems = blockElems(recvBlock);
    const size_t sendWire = q8WireBytes(sendElems, block);
    const size_t recvWire = q8WireBytes(recvElems, block);
    uint8_t* txSeg = tx + size_t(txSlot) * wireBlock;
    {
      PhaseScope ps(Phase::kPack);
      f32StreamToQ8(work + blockStart(sendBlock), txSeg, sendElems, block);
    }
    // Whole-unit hops fold straight out of the transport's staging into
    // the float32 accumulator; ragged tails (and empty blocks) stage.
    const bool fuse = pairFuse && recvElems > 0 && recvElems % block == 0;
    {
      PhaseScope ps(Phase::kPost);
      if (fuse) {
        workBuf->recvReduceTyped(left, s, accumulateQ8UnitsFn, unit,
                                 block * sizeof(float),
                                 blockStart(recvBlock) * sizeof(float),
                                 recvWire);
      } else {
        rxStage.buf()->recv(left, s, size_t(step % 2) * wireBlock,
                            recvWire);
      }
    }
    {
      PhaseScope ps(Phase::kPost, right, s, sendWire);
      txBuf->send(right, s, size_t(txSlot) * wireBlock, sendWire);
    }
    if (fuse) {
      PhaseScope ps(Phase::kWireWait, left, s, recvWire);
      workBuf->waitRecv(nullptr, timeout);
    } else {
      {
        PhaseScope ps(Phase::kWireWait, left, s, recvWire);
        rxStage.buf()->waitRecv(nullptr, timeout);
      }
      PhaseScope ps(Phase::kReduce);
      q8StreamAccumulate(
          work + blockStart(recvBlock),
          reinterpret_cast<uint8_t*>(rxStage.data()) +
              size_t(step % 2) * wireBlock,
          recvElems, block);
    }
    PhaseScope ps(Phase::kWireWait);
    txBuf->waitSend(timeout);
  }
}

size_t maxWireBlock(const Blocks& blocks, size_t block) {
  size_t maxElems = 0;
  for (size_t b : blocks.bytes) {
    maxElems = std::max(maxElems, b / sizeof(float));
  }
  return std::max(q8WireBytes(maxElems, block), size_t(1));
}

}  // namespace

void q8WireRingAllreduce(Context* ctx, plan::Plan& plan, char* workBytes,
                         size_t count, Slot slot,
                         std::chrono::milliseconds timeout) {
  const int rank = ctx->rank();
  const int size = ctx->size();
  float* work = reinterpret_cast<float*>(workBytes);
  const size_t block = q8BlockElems();
  const Blocks& blocks = plan.blocks(
      0, [&] { return evenBlocks(count, size, sizeof(float)); });
  const size_t wireBlock = maxWireBlock(blocks, block);
  const int right = (rank + 1) % size;
  const int left = (rank - 1 + size) % size;
  const int steps = size - 1;

  // Wire staging: tx double-buffered (a sent stream must stay valid
  // until waitSend); rx double-buffered, lazily acquired (untouched on
  // fully fused hops). All plan-backed: warm arena + registration on
  // the steady-state replay.
  auto txStage = plan.stage(1, 2 * wireBlock);
  uint8_t* tx = reinterpret_cast<uint8_t*>(txStage.data);
  auto* txBuf = txStage.buf;
  plan::LazyStage rxStage(plan, 2, 2 * wireBlock);
  auto* workBuf = plan.userBuf(0, work, count * sizeof(float));

  auto blockElems = [&](int b) { return blocks.bytes[b] / sizeof(float); };
  auto blockStart = [&](int b) {
    return blocks.offset[b] / sizeof(float);
  };

  q8RingReduceScatterPhase(ctx, work, blocks, slot, /*startShift=*/0,
                           timeout, workBuf, rxStage, tx, txBuf,
                           wireBlock);

  // --- allgather: rank r owns reduced block (r+1). The owner quantizes
  // its block ONCE and adopts the decoded values; every hop then stages
  // the received stream, decodes it into place, and forwards the WIRE
  // BYTES verbatim — never re-encoding (q8 re-encode of a decoded block
  // is not bit-exact, see the header comment), so every rank decodes
  // the exact same stream and results are identical everywhere. ---
  const uint64_t agBase = steps;
  {
    PhaseScope ps(Phase::kPack);
    const int own = (rank + 1) % size;
    f32StreamToQ8(work + blockStart(own), tx, blockElems(own), block);
    q8StreamToF32(tx, work + blockStart(own), blockElems(own), block);
  }
  uint8_t* rx = nullptr;
  for (int step = 0; step < steps; step++) {
    const int sendBlock = (rank + 1 - step + 2 * size) % size;
    const int recvBlock = (rank - step + 2 * size) % size;
    const uint64_t s = slot.offset(agBase + step).value();
    const int txSlot = step % 2;
    const int rxSlot = step % 2;
    const size_t sendWire = q8WireBytes(blockElems(sendBlock), block);
    const size_t recvWire = q8WireBytes(blockElems(recvBlock), block);
    if (step == 0) {
      // Own block already sits quantized in tx slot 0.
    } else {
      // Forward the wire bytes received last step, verbatim.
      PhaseScope ps(Phase::kPack);
      std::memcpy(tx + size_t(txSlot) * wireBlock,
                  rx + size_t((step - 1) % 2) * wireBlock, sendWire);
    }
    {
      PhaseScope ps(Phase::kPost);
      rxStage.buf()->recv(left, s, size_t(rxSlot) * wireBlock, recvWire);
      rx = reinterpret_cast<uint8_t*>(rxStage.data());
    }
    {
      PhaseScope ps(Phase::kPost, right, s, sendWire);
      txBuf->send(right, s, size_t(txSlot) * wireBlock, sendWire);
    }
    {
      PhaseScope ps(Phase::kWireWait, left, s, recvWire);
      rxStage.buf()->waitRecv(nullptr, timeout);
    }
    {
      PhaseScope ps(Phase::kUnpack);
      q8StreamToF32(rx + size_t(rxSlot) * wireBlock,
                    work + blockStart(recvBlock), blockElems(recvBlock),
                    block);
    }
    PhaseScope ps(Phase::kWireWait);
    txBuf->waitSend(timeout);
  }
}

void q8WireRingReduceScatter(Context* ctx, plan::Plan& plan,
                             char* workBytes,
                             transport::UnboundBuffer* workBuf,
                             const Blocks& blocks, Slot slot,
                             std::chrono::milliseconds timeout) {
  float* work = reinterpret_cast<float*>(workBytes);
  const size_t block = q8BlockElems();
  const size_t wireBlock = maxWireBlock(blocks, block);
  // Stage slots 0/1 here: the entry's work copy owns slot 2
  // (kStageRsWork in collectives_ring.cc), and these plans never meet
  // the binomial/ring staging (different algorithm keys).
  auto txStage = plan.stage(0, 2 * wireBlock);
  uint8_t* tx = reinterpret_cast<uint8_t*>(txStage.data);
  plan::LazyStage rxStage(plan, 1, 2 * wireBlock);
  q8RingReduceScatterPhase(ctx, work, blocks, slot, /*startShift=*/-1,
                           timeout, workBuf, rxStage, tx, txStage.buf,
                           wireBlock);
}

}  // namespace algorithms
}  // namespace tpucoll
