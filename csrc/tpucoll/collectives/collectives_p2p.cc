// Schedules built from direct point-to-point exchanges: barrier, broadcast,
// gather(v), scatter, alltoall(v).
#include <cstdlib>
#include <cstring>

#include "tpucoll/collectives/collectives.h"
#include "tpucoll/collectives/detail.h"
#include "tpucoll/collectives/plan.h"
#include "tpucoll/common/profile.h"
#include "tpucoll/group/hier.h"

namespace tpucoll {

using profile::Phase;
using profile::PhaseScope;
using profile::ProfileOpScope;

namespace {

using plan::PlanHandle;
using plan::PlanKey;
using plan::PlanOp;
using transport::UnboundBuffer;

char* bytePtr(void* p) { return static_cast<char*>(p); }
const char* bytePtr(const void* p) { return static_cast<const char*>(p); }

}  // namespace

// Dissemination barrier (Hensgen–Finkel–Manber style, as in reference
// gloo/barrier.cc:23-35): ceil(log2 P) rounds; in round i, signal rank+2^i
// and await rank-2^i. Zero-byte messages carry the signal.
void barrier(BarrierOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "barrier: null context");
  auto traceSpan = ctx->tracer().span("barrier");
  MetricsOp metricsOp(&ctx->metrics(), MetricOp::kBarrier, 0);
  FlightRecOp frOp(&ctx->flightrec(), "barrier", nullptr,
                   Slot::build(SlotPrefix::kBarrier, opts.tag).value(), -1,
                   0, FlightRecorder::kNoDtype);
  ProfileOpScope profOp(&ctx->profiler(), "barrier", frOp.cseq(), 0);
  span::OpScope spanOp(&ctx->spans(), "barrier", frOp.cseq());
  const auto timeout = detail::effectiveTimeout(opts);
  const int rank = ctx->rank();
  const int size = ctx->size();
  if (size == 1) {
    return;
  }
  if (opts.algorithm == HierDispatch::kHier && group::hierEligible(ctx)) {
    frOp.setAlgorithm("hier");
    profOp.setAlgorithm("hier");
    group::hierBarrier(ctx, opts.tag, timeout);
    return;
  }
  Slot slot = Slot::build(SlotPrefix::kBarrier, opts.tag);
  PlanKey key;
  key.opcode = static_cast<uint8_t>(PlanOp::kBarrier);
  key.tag = opts.tag;
  PlanHandle planh(ctx, key);
  auto* buf = planh->userBuf(0, nullptr, 0);
  const uint64_t rounds = log2ceil(static_cast<uint64_t>(size));
  for (uint64_t i = 0; i < rounds; i++) {
    const int dist = 1 << i;
    const int to = (rank + dist) % size;
    const int from = (rank - dist + size) % size;
    {
      PhaseScope ps(Phase::kPost);
      buf->send(to, slot.offset(i).value(), 0, 0);
      buf->recv(from, slot.offset(i).value(), 0, 0);
    }
    PhaseScope ps(Phase::kWireWait);
    buf->waitSend(timeout);
    buf->waitRecv(nullptr, timeout);
  }
}

// Binomial tree broadcast over virtual ranks (vrank 0 = root), matching the
// reference's mask-walk participation scheme (gloo/broadcast.cc:44-84) —
// with segment pipelining: large payloads are split into 1 MiB segments
// that relay toward the leaves as they arrive, so the tree's depth costs
// one segment of latency instead of one full payload per level.
void broadcast(BroadcastOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "broadcast: null context");
  auto traceSpan = ctx->tracer().span("broadcast", opts.count * elementSize(opts.dtype), opts.root);
  MetricsOp metricsOp(&ctx->metrics(), MetricOp::kBroadcast,
                      opts.count * elementSize(opts.dtype));
  FlightRecOp frOp(&ctx->flightrec(), "broadcast", nullptr,
                   Slot::build(SlotPrefix::kBroadcast, opts.tag).value(),
                   opts.root, opts.count * elementSize(opts.dtype),
                   static_cast<uint8_t>(opts.dtype));
  ProfileOpScope profOp(&ctx->profiler(), "broadcast", frOp.cseq(),
                        opts.count * elementSize(opts.dtype));
  span::OpScope spanOp(&ctx->spans(), "broadcast", frOp.cseq());
  const auto timeout = detail::effectiveTimeout(opts);
  const int rank = ctx->rank();
  const int size = ctx->size();
  TC_ENFORCE(opts.root >= 0 && opts.root < size, "broadcast: bad root");
  const size_t elsize = elementSize(opts.dtype);
  const size_t nbytes = opts.count * elsize;
  if (size == 1) {
    return;
  }
  if (opts.algorithm == HierDispatch::kHier && group::hierEligible(ctx)) {
    frOp.setAlgorithm("hier");
    profOp.setAlgorithm("hier");
    group::hierBroadcast(ctx, opts.buffer, opts.count, opts.dtype,
                         opts.root, opts.tag, timeout);
    return;
  }
  Slot slot = Slot::build(SlotPrefix::kBroadcast, opts.tag);
  PlanKey key;
  key.opcode = static_cast<uint8_t>(PlanOp::kBroadcast);
  key.dtype = static_cast<uint8_t>(opts.dtype);
  key.root = opts.root;
  key.tag = opts.tag;
  key.ptrA = reinterpret_cast<uintptr_t>(opts.buffer);
  key.nbytes = nbytes;
  PlanHandle planh(ctx, key);
  auto* buf = planh->userBuf(0, opts.buffer, nbytes);
  const int vrank = (rank - opts.root + size) % size;
  auto physical = [&](int v) { return (v + opts.root) % size; };

  // 4 MiB default: measured knee on loopback (finer segments pay more in
  // per-message overhead than the relay pipelining saves; deep trees on
  // real networks may prefer smaller via TPUCOLL_BCAST_SEG — strict
  // digits-only parse, floored at 4 KiB).
  static const size_t kBroadcastSegment = std::max<size_t>(
      collectives_detail::envBytes("TPUCOLL_BCAST_SEG", 4 << 20), 4096);
  const size_t segBytes =
      std::max(kBroadcastSegment / elsize * elsize, elsize);
  const size_t numSegs = nbytes == 0 ? 1 : (nbytes + segBytes - 1) / segBytes;
  auto segSpan = [&](size_t k) {
    const size_t off = k * segBytes;
    return std::make_pair(off, std::min(segBytes, nbytes - off));
  };

  // Parent (if any) and children at this node.
  int parent = -1;
  int mask = 1;
  while (mask < size) {
    if (vrank & mask) {
      parent = physical(vrank - mask);
      break;
    }
    mask <<= 1;
  }
  std::vector<int> children;
  for (int m = mask >> 1; m > 0; m >>= 1) {
    if (vrank + m < size) {
      children.push_back(physical(vrank + m));
    }
  }

  int pendingSends = 0;
  if (parent >= 0) {
    {
      PhaseScope ps(Phase::kPost);
      for (size_t k = 0; k < numSegs; k++) {
        auto [off, len] = segSpan(k);
        buf->recv(parent, slot.offset(k).value(), off, len);
      }
    }
    for (size_t k = 0; k < numSegs; k++) {
      auto [off, len] = segSpan(k);
      {
        PhaseScope ps(Phase::kWireWait);
        buf->waitRecv(nullptr, timeout);
      }
      // Relay this segment onward the moment it lands (wire order makes
      // completion k the k-th segment).
      PhaseScope ps(Phase::kPost);
      for (int child : children) {
        buf->send(child, slot.offset(k).value(), off, len);
        pendingSends++;
      }
    }
  } else {
    PhaseScope ps(Phase::kPost);
    for (size_t k = 0; k < numSegs; k++) {
      auto [off, len] = segSpan(k);
      for (int child : children) {
        buf->send(child, slot.offset(k).value(), off, len);
        pendingSends++;
      }
    }
  }
  PhaseScope ps(Phase::kWireWait);
  while (pendingSends-- > 0) {
    buf->waitSend(timeout);
  }
}

// Shared schedule behind gather/gatherv; the public entries carry the
// instrumentation, so each op is attributed under ITS OWN name (a
// dashboard watching op="gather" must not read zero forever).
static void gathervRun(GathervOptions& opts);

void gather(GatherOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "gather: null context");
  auto traceSpan = ctx->tracer().span(
      "gather", opts.count * elementSize(opts.dtype), opts.root);
  MetricsOp metricsOp(&ctx->metrics(), MetricOp::kGather,
                      opts.count * elementSize(opts.dtype));
  FlightRecOp frOp(&ctx->flightrec(), "gather", nullptr,
                   Slot::build(SlotPrefix::kGather, opts.tag).value(),
                   opts.root, opts.count * elementSize(opts.dtype),
                   static_cast<uint8_t>(opts.dtype));
  ProfileOpScope profOp(&ctx->profiler(), "gather", frOp.cseq(),
                        opts.count * elementSize(opts.dtype));
  span::OpScope spanOp(&ctx->spans(), "gather", frOp.cseq());
  GathervOptions v;
  static_cast<CollectiveOptions&>(v) = opts;
  v.input = opts.input;
  v.output = opts.output;
  v.counts.assign(opts.context->size(), opts.count);
  v.dtype = opts.dtype;
  v.root = opts.root;
  gathervRun(v);
}

void gatherv(GathervOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "gatherv: null context");
  auto traceSpan = ctx->tracer().span("gatherv", 0, opts.root);
  // Guarded: the counts-size enforce runs inside gathervRun.
  const uint64_t myBytes =
      static_cast<size_t>(ctx->rank()) < opts.counts.size()
          ? opts.counts[ctx->rank()] * elementSize(opts.dtype)
          : 0;
  MetricsOp metricsOp(&ctx->metrics(), MetricOp::kGatherv, myBytes);
  // Fingerprint over the GROUP total: per-rank counts legitimately
  // differ on a matching gatherv schedule, their sum must not.
  uint64_t totalCount = 0;
  for (size_t c : opts.counts) {
    totalCount += c;
  }
  FlightRecOp frOp(&ctx->flightrec(), "gatherv", nullptr,
                   Slot::build(SlotPrefix::kGather, opts.tag).value(),
                   opts.root, myBytes, static_cast<uint8_t>(opts.dtype),
                   totalCount * elementSize(opts.dtype));
  ProfileOpScope profOp(&ctx->profiler(), "gatherv", frOp.cseq(),
                        myBytes);
  span::OpScope spanOp(&ctx->spans(), "gatherv", frOp.cseq());
  gathervRun(opts);
}

// Root posts P-1 receives at per-rank offsets; leaves send once (reference:
// gloo/gather.cc:28-59, gatherv.cc:58-109).
static void gathervRun(GathervOptions& opts) {
  Context* ctx = opts.context;
  const auto timeout = detail::effectiveTimeout(opts);
  const int rank = ctx->rank();
  const int size = ctx->size();
  TC_ENFORCE_EQ(opts.counts.size(), static_cast<size_t>(size),
                "gatherv: counts must have one entry per rank");
  const size_t elsize = elementSize(opts.dtype);
  Slot slot = Slot::build(SlotPrefix::kGather, opts.tag);
  const size_t myBytes = opts.counts[rank] * elsize;
  size_t total = 0;
  for (size_t c : opts.counts) {
    total += c;
  }

  PlanKey key;
  key.opcode = static_cast<uint8_t>(PlanOp::kGatherv);
  key.dtype = static_cast<uint8_t>(opts.dtype);
  key.root = opts.root;
  key.tag = opts.tag;
  key.ptrA = reinterpret_cast<uintptr_t>(opts.input);
  key.ptrB = reinterpret_cast<uintptr_t>(opts.output);
  key.nbytes = total * elsize;
  key.aux = plan::hashCounts(opts.counts);
  PlanHandle planh(ctx, key);

  if (rank == opts.root) {
    auto* out = planh->userBuf(0, opts.output, total * elsize);
    size_t offset = 0;
    int pending = 0;
    for (int j = 0; j < size; j++) {
      const size_t jBytes = opts.counts[j] * elsize;
      if (j == rank) {
        PhaseScope ps(Phase::kPack);
        std::memcpy(bytePtr(opts.output) + offset, opts.input, jBytes);
      } else {
        PhaseScope ps(Phase::kPost);
        out->recv(j, slot.value(), offset, jBytes);
        pending++;
      }
      offset += jBytes;
    }
    PhaseScope ps(Phase::kWireWait);
    while (pending-- > 0) {
      out->waitRecv(nullptr, timeout);
    }
  } else {
    auto* in =
        planh->userBuf(0, const_cast<void*>(opts.input), myBytes);
    {
      PhaseScope ps(Phase::kPost);
      in->send(opts.root, slot.value(), 0, myBytes);
    }
    PhaseScope ps(Phase::kWireWait);
    in->waitSend(timeout);
  }
}

// Root sends slice j to rank j; leaves post one receive (reference:
// gloo/scatter.cc:38-60).
void scatter(ScatterOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "scatter: null context");
  auto traceSpan = ctx->tracer().span("scatter", opts.count * elementSize(opts.dtype), opts.root);
  MetricsOp metricsOp(&ctx->metrics(), MetricOp::kScatter,
                      opts.count * elementSize(opts.dtype));
  FlightRecOp frOp(&ctx->flightrec(), "scatter", nullptr,
                   Slot::build(SlotPrefix::kScatter, opts.tag).value(),
                   opts.root, opts.count * elementSize(opts.dtype),
                   static_cast<uint8_t>(opts.dtype));
  ProfileOpScope profOp(&ctx->profiler(), "scatter", frOp.cseq(),
                        opts.count * elementSize(opts.dtype));
  span::OpScope spanOp(&ctx->spans(), "scatter", frOp.cseq());
  const auto timeout = detail::effectiveTimeout(opts);
  const int rank = ctx->rank();
  const int size = ctx->size();
  const size_t nbytes = opts.count * elementSize(opts.dtype);
  Slot slot = Slot::build(SlotPrefix::kScatter, opts.tag);

  PlanKey key;
  key.opcode = static_cast<uint8_t>(PlanOp::kScatter);
  key.dtype = static_cast<uint8_t>(opts.dtype);
  key.root = opts.root;
  key.tag = opts.tag;
  key.ptrA = reinterpret_cast<uintptr_t>(opts.input);
  key.ptrB = reinterpret_cast<uintptr_t>(opts.output);
  key.nbytes = nbytes;
  PlanHandle planh(ctx, key);

  if (rank == opts.root) {
    auto* in = planh->userBuf(0, const_cast<void*>(opts.input),
                              nbytes * size);
    int pending = 0;
    for (int j = 0; j < size; j++) {
      if (j == rank) {
        PhaseScope ps(Phase::kUnpack);
        std::memcpy(opts.output, bytePtr(opts.input) + j * nbytes, nbytes);
      } else {
        PhaseScope ps(Phase::kPost);
        in->send(j, slot.value(), j * nbytes, nbytes);
        pending++;
      }
    }
    PhaseScope ps(Phase::kWireWait);
    while (pending-- > 0) {
      in->waitSend(timeout);
    }
  } else {
    auto* out = planh->userBuf(0, opts.output, nbytes);
    {
      PhaseScope ps(Phase::kPost);
      out->recv(opts.root, slot.value(), 0, nbytes);
    }
    PhaseScope ps(Phase::kWireWait);
    out->waitRecv(nullptr, timeout);
  }
}

namespace {

// Bruck's log-round alltoall (Bruck et al., "Efficient Algorithms for
// All-to-All Communications in Multiport Message-Passing Systems",
// IEEE TPDS 1997): ceil(log2 P) rounds instead of the pairwise
// exchange's P-1, at the price of each block traveling up to log2 P
// hops (total traffic ~(P/2)log2(P) blocks vs P-1). The win is the
// latency-dominated regime — small blocks, where round count is the
// whole cost — which is exactly the EP/MoE dispatch control case. The
// reference ships only the single-round pattern (gloo/alltoall.cc);
// this tier is beyond it.
//
// Phases: (1) local rotation tmp[j] = in[(rank+j) mod P] so slot j
// holds the block destined to rank+j; (2) for k = 1,2,4,...: gather
// every slot with bit k set into a contiguous staging buffer, send to
// rank+k, receive the same slots from rank-k (already-received blocks
// keep traveling — that is the algorithm); (3) inverse rotation
// out[(rank - j) mod P] = tmp[j].
void bruckAlltoall(Context* ctx, const AlltoallOptions& opts,
                   size_t blockBytes, std::chrono::milliseconds timeout) {
  const int rank = ctx->rank();
  const int size = ctx->size();
  const uint8_t* in = static_cast<const uint8_t*>(opts.input);
  uint8_t* out = static_cast<uint8_t*>(opts.output);

  PlanKey key;
  key.opcode = static_cast<uint8_t>(PlanOp::kAlltoallBruck);
  key.dtype = static_cast<uint8_t>(opts.dtype);
  key.tag = opts.tag;
  key.ptrA = reinterpret_cast<uintptr_t>(opts.input);
  key.ptrB = reinterpret_cast<uintptr_t>(opts.output);
  key.nbytes = blockBytes * size;
  PlanHandle planh(ctx, key);

  // Rotation scratch (slot 0: memory only, never registered) and the
  // per-round wire stages (slots 1/2), all plan-backed.
  uint8_t* tmp = reinterpret_cast<uint8_t*>(
      planh->scratch(0, static_cast<size_t>(size) * blockBytes));
  {
    PhaseScope ps(Phase::kPack);
    for (int j = 0; j < size; j++) {
      std::memcpy(tmp + static_cast<size_t>(j) * blockBytes,
                  in + static_cast<size_t>((rank + j) % size) * blockBytes,
                  blockBytes);
    }
  }

  const size_t maxBlocks = static_cast<size_t>((size + 1) / 2);
  auto sendSt = planh->stage(1, maxBlocks * blockBytes);
  auto recvSt = planh->stage(2, maxBlocks * blockBytes);
  uint8_t* sendStage = reinterpret_cast<uint8_t*>(sendSt.data);
  uint8_t* recvStage = reinterpret_cast<uint8_t*>(recvSt.data);
  auto* sendBuf = sendSt.buf;
  auto* recvBuf = recvSt.buf;
  Slot slot = Slot::build(SlotPrefix::kAlltoall, opts.tag);

  for (int k = 1; k < size; k <<= 1) {
    size_t nblocks = 0;
    {
      PhaseScope ps(Phase::kPack);
      for (int j = k; j < size; j++) {
        if ((j & k) != 0) {
          std::memcpy(sendStage + nblocks * blockBytes,
                      tmp + static_cast<size_t>(j) * blockBytes,
                      blockBytes);
          nblocks++;
        }
      }
    }
    const int sendTo = (rank + k) % size;
    const int recvFrom = (rank - k + size) % size;
    {
      PhaseScope ps(Phase::kPost);
      sendBuf->send(sendTo, slot.value(), 0, nblocks * blockBytes);
      recvBuf->recv(recvFrom, slot.value(), 0, nblocks * blockBytes);
    }
    {
      PhaseScope ps(Phase::kWireWait);
      sendBuf->waitSend(timeout);
      recvBuf->waitRecv(nullptr, timeout);
    }
    PhaseScope ps(Phase::kUnpack);
    size_t b = 0;
    for (int j = k; j < size; j++) {
      if ((j & k) != 0) {
        std::memcpy(tmp + static_cast<size_t>(j) * blockBytes,
                    recvStage + b * blockBytes, blockBytes);
        b++;
      }
    }
  }

  PhaseScope ps(Phase::kUnpack);
  for (int j = 0; j < size; j++) {
    std::memcpy(out + static_cast<size_t>((rank - j + size) % size) *
                          blockBytes,
                tmp + static_cast<size_t>(j) * blockBytes,
                blockBytes);
  }
}

}  // namespace

// Shared schedule behind alltoall/alltoallv (instrumentation lives in
// the public entries, same rationale as gathervRun).
static void alltoallvRun(AlltoallvOptions& opts);

void alltoall(AlltoallOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "alltoall: null context");
  const size_t blockBytes = opts.count * elementSize(opts.dtype);
  MetricsOp metricsOp(&ctx->metrics(), MetricOp::kAlltoall,
                      blockBytes * ctx->size());
  FlightRecOp frOp(&ctx->flightrec(), "alltoall", nullptr,
                   Slot::build(SlotPrefix::kAlltoall, opts.tag).value(),
                   -1, blockBytes * ctx->size(),
                   static_cast<uint8_t>(opts.dtype));
  ProfileOpScope profOp(&ctx->profiler(), "alltoall", frOp.cseq(),
                        blockBytes * ctx->size());
  span::OpScope spanOp(&ctx->spans(), "alltoall", frOp.cseq());
  // Crossover: Bruck's ceil(log2 P) rounds win while per-block payload
  // is latency-dominated; the pairwise exchange's P-1 single-hop
  // rounds win once bandwidth dominates (each Bruck block travels up
  // to log2 P hops). Loopback P=8 measurement (BASELINE.md r4): p50
  // crosses below 2 KiB blocks on the shared-core host (Bruck 2.3x
  // better at 512 B), while min latency favors Bruck through ~4 KiB
  // (8.6 vs 246 us at 512 B — 28x). Default follows the p50 crossover;
  // on real DCN, where a round costs an RTT instead of a scheduler
  // quantum, the knob should move UP.
  static const size_t bruckMax = collectives_detail::envBytes(
      "TPUCOLL_ALLTOALL_BRUCK_MAX", 1 << 10);
  if (ctx->size() > 2 && blockBytes > 0 && blockBytes <= bruckMax) {
    auto traceSpan = ctx->tracer().span("alltoall", blockBytes, -1,
                                        "bruck");
    frOp.setAlgorithm("bruck");
    profOp.setAlgorithm("bruck");
    bruckAlltoall(ctx, opts, blockBytes,
                  detail::effectiveTimeout(opts));
    return;
  }
  auto traceSpan = ctx->tracer().span("alltoall", blockBytes, -1,
                                      "pairwise");
  frOp.setAlgorithm("pairwise");
  profOp.setAlgorithm("pairwise");
  AlltoallvOptions v;
  static_cast<CollectiveOptions&>(v) = opts;
  v.input = opts.input;
  v.output = opts.output;
  v.inCounts.assign(opts.context->size(), opts.count);
  v.outCounts.assign(opts.context->size(), opts.count);
  v.dtype = opts.dtype;
  alltoallvRun(v);
}

void alltoallv(AlltoallvOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "alltoallv: null context");
  auto traceSpan = ctx->tracer().span("alltoallv");
  size_t inCountTotal = 0;
  for (size_t c : opts.inCounts) {
    inCountTotal += c;
  }
  MetricsOp metricsOp(&ctx->metrics(), MetricOp::kAlltoallv,
                      inCountTotal * elementSize(opts.dtype));
  // fpBytes = 0: alltoallv's in/out counts are legitimately different on
  // every rank, so only (op, dtype) participate in the fingerprint.
  FlightRecOp frOp(&ctx->flightrec(), "alltoallv", nullptr,
                   Slot::build(SlotPrefix::kAlltoall, opts.tag).value(),
                   -1, inCountTotal * elementSize(opts.dtype),
                   static_cast<uint8_t>(opts.dtype), /*fpBytes=*/0);
  ProfileOpScope profOp(&ctx->profiler(), "alltoallv", frOp.cseq(),
                        inCountTotal * elementSize(opts.dtype));
  span::OpScope spanOp(&ctx->spans(), "alltoallv", frOp.cseq());
  alltoallvRun(opts);
}

// Rotated pairwise exchange: at step i, send to rank+i and receive from
// rank-i, so every step moves disjoint pairs and link load stays balanced
// (reference: gloo/alltoall.cc:39-50, alltoallv.cc:19-30).
static void alltoallvRun(AlltoallvOptions& opts) {
  Context* ctx = opts.context;
  const auto timeout = detail::effectiveTimeout(opts);
  const int rank = ctx->rank();
  const int size = ctx->size();
  TC_ENFORCE_EQ(opts.inCounts.size(), static_cast<size_t>(size));
  TC_ENFORCE_EQ(opts.outCounts.size(), static_cast<size_t>(size));
  const size_t elsize = elementSize(opts.dtype);

  size_t inTotal = 0, outTotal = 0;
  for (int j = 0; j < size; j++) {
    inTotal += opts.inCounts[j] * elsize;
    outTotal += opts.outCounts[j] * elsize;
  }

  PlanKey key;
  key.opcode = static_cast<uint8_t>(PlanOp::kAlltoallv);
  key.dtype = static_cast<uint8_t>(opts.dtype);
  key.tag = opts.tag;
  key.ptrA = reinterpret_cast<uintptr_t>(opts.input);
  key.ptrB = reinterpret_cast<uintptr_t>(opts.output);
  key.nbytes = inTotal;
  // Both count vectors shape the schedule; mix both into aux.
  key.aux = plan::hashCounts(opts.inCounts) * 1099511628211ull ^
            plan::hashCounts(opts.outCounts);
  PlanHandle planh(ctx, key);
  // countBlocks doubles as the per-peer offset table (memoized).
  const auto& inBlocks = planh->blocks(
      0, [&] { return collectives_detail::countBlocks(opts.inCounts,
                                                      elsize); });
  const auto& outBlocks = planh->blocks(
      1, [&] { return collectives_detail::countBlocks(opts.outCounts,
                                                      elsize); });

  {
    PhaseScope ps(Phase::kPack);
    std::memcpy(bytePtr(opts.output) + outBlocks.offset[rank],
                bytePtr(opts.input) + inBlocks.offset[rank],
                opts.inCounts[rank] * elsize);
  }
  if (size == 1) {
    return;
  }

  Slot slot = Slot::build(SlotPrefix::kAlltoall, opts.tag);
  auto* in =
      planh->userBuf(0, const_cast<void*>(opts.input), inTotal);
  auto* out = planh->userBuf(1, opts.output, outTotal);
  for (int i = 1; i < size; i++) {
    const int sendTo = (rank + i) % size;
    const int recvFrom = (rank - i + size) % size;
    {
      PhaseScope ps(Phase::kPost);
      in->send(sendTo, slot.value(), inBlocks.offset[sendTo],
               opts.inCounts[sendTo] * elsize);
      out->recv(recvFrom, slot.value(), outBlocks.offset[recvFrom],
                opts.outCounts[recvFrom] * elsize);
    }
    PhaseScope ps(Phase::kWireWait);
    in->waitSend(timeout);
    out->waitRecv(nullptr, timeout);
  }
}

}  // namespace tpucoll
