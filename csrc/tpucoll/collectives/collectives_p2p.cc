// Schedules built from direct point-to-point exchanges: barrier, broadcast,
// gather(v), scatter, alltoall(v).
#include <cstdlib>
#include <cstring>

#include "tpucoll/collectives/collectives.h"
#include "tpucoll/collectives/detail.h"

namespace tpucoll {

namespace {

using transport::UnboundBuffer;

char* bytePtr(void* p) { return static_cast<char*>(p); }
const char* bytePtr(const void* p) { return static_cast<const char*>(p); }

}  // namespace

// Dissemination barrier (Hensgen–Finkel–Manber style, as in reference
// gloo/barrier.cc:23-35): ceil(log2 P) rounds; in round i, signal rank+2^i
// and await rank-2^i. Zero-byte messages carry the signal.
void barrier(BarrierOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "barrier: null context");
  auto traceSpan = ctx->tracer().span("barrier");
  const auto timeout = detail::effectiveTimeout(opts);
  const int rank = ctx->rank();
  const int size = ctx->size();
  if (size == 1) {
    return;
  }
  Slot slot = Slot::build(SlotPrefix::kBarrier, opts.tag);
  auto buf = ctx->createUnboundBuffer(nullptr, 0);
  const uint64_t rounds = log2ceil(static_cast<uint64_t>(size));
  for (uint64_t i = 0; i < rounds; i++) {
    const int dist = 1 << i;
    const int to = (rank + dist) % size;
    const int from = (rank - dist + size) % size;
    buf->send(to, slot.offset(i).value(), 0, 0);
    buf->recv(from, slot.offset(i).value(), 0, 0);
    buf->waitSend(timeout);
    buf->waitRecv(nullptr, timeout);
  }
}

// Binomial tree broadcast over virtual ranks (vrank 0 = root), matching the
// reference's mask-walk participation scheme (gloo/broadcast.cc:44-84) —
// with segment pipelining: large payloads are split into 1 MiB segments
// that relay toward the leaves as they arrive, so the tree's depth costs
// one segment of latency instead of one full payload per level.
void broadcast(BroadcastOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "broadcast: null context");
  auto traceSpan = ctx->tracer().span("broadcast", opts.count * elementSize(opts.dtype), opts.root);
  const auto timeout = detail::effectiveTimeout(opts);
  const int rank = ctx->rank();
  const int size = ctx->size();
  TC_ENFORCE(opts.root >= 0 && opts.root < size, "broadcast: bad root");
  const size_t elsize = elementSize(opts.dtype);
  const size_t nbytes = opts.count * elsize;
  if (size == 1) {
    return;
  }
  Slot slot = Slot::build(SlotPrefix::kBroadcast, opts.tag);
  auto buf = ctx->createUnboundBuffer(opts.buffer, nbytes);
  const int vrank = (rank - opts.root + size) % size;
  auto physical = [&](int v) { return (v + opts.root) % size; };

  // 4 MiB default: measured knee on loopback (finer segments pay more in
  // per-message overhead than the relay pipelining saves; deep trees on
  // real networks may prefer smaller via TPUCOLL_BCAST_SEG — strict
  // digits-only parse, floored at 4 KiB).
  static const size_t kBroadcastSegment = std::max<size_t>(
      collectives_detail::envBytes("TPUCOLL_BCAST_SEG", 4 << 20), 4096);
  const size_t segBytes =
      std::max(kBroadcastSegment / elsize * elsize, elsize);
  const size_t numSegs = nbytes == 0 ? 1 : (nbytes + segBytes - 1) / segBytes;
  auto segSpan = [&](size_t k) {
    const size_t off = k * segBytes;
    return std::make_pair(off, std::min(segBytes, nbytes - off));
  };

  // Parent (if any) and children at this node.
  int parent = -1;
  int mask = 1;
  while (mask < size) {
    if (vrank & mask) {
      parent = physical(vrank - mask);
      break;
    }
    mask <<= 1;
  }
  std::vector<int> children;
  for (int m = mask >> 1; m > 0; m >>= 1) {
    if (vrank + m < size) {
      children.push_back(physical(vrank + m));
    }
  }

  int pendingSends = 0;
  if (parent >= 0) {
    for (size_t k = 0; k < numSegs; k++) {
      auto [off, len] = segSpan(k);
      buf->recv(parent, slot.offset(k).value(), off, len);
    }
    for (size_t k = 0; k < numSegs; k++) {
      auto [off, len] = segSpan(k);
      buf->waitRecv(nullptr, timeout);
      // Relay this segment onward the moment it lands (wire order makes
      // completion k the k-th segment).
      for (int child : children) {
        buf->send(child, slot.offset(k).value(), off, len);
        pendingSends++;
      }
    }
  } else {
    for (size_t k = 0; k < numSegs; k++) {
      auto [off, len] = segSpan(k);
      for (int child : children) {
        buf->send(child, slot.offset(k).value(), off, len);
        pendingSends++;
      }
    }
  }
  while (pendingSends-- > 0) {
    buf->waitSend(timeout);
  }
}

void gather(GatherOptions& opts) {
  GathervOptions v;
  static_cast<CollectiveOptions&>(v) = opts;
  v.input = opts.input;
  v.output = opts.output;
  v.counts.assign(opts.context->size(), opts.count);
  v.dtype = opts.dtype;
  v.root = opts.root;
  gatherv(v);
}

// Root posts P-1 receives at per-rank offsets; leaves send once (reference:
// gloo/gather.cc:28-59, gatherv.cc:58-109).
void gatherv(GathervOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "gatherv: null context");
  auto traceSpan = ctx->tracer().span("gatherv", 0, opts.root);
  const auto timeout = detail::effectiveTimeout(opts);
  const int rank = ctx->rank();
  const int size = ctx->size();
  TC_ENFORCE_EQ(opts.counts.size(), static_cast<size_t>(size),
                "gatherv: counts must have one entry per rank");
  const size_t elsize = elementSize(opts.dtype);
  Slot slot = Slot::build(SlotPrefix::kGather, opts.tag);
  const size_t myBytes = opts.counts[rank] * elsize;

  if (rank == opts.root) {
    size_t total = 0;
    for (size_t c : opts.counts) {
      total += c;
    }
    auto out = ctx->createUnboundBuffer(opts.output, total * elsize);
    size_t offset = 0;
    int pending = 0;
    for (int j = 0; j < size; j++) {
      const size_t jBytes = opts.counts[j] * elsize;
      if (j == rank) {
        std::memcpy(bytePtr(opts.output) + offset, opts.input, jBytes);
      } else {
        out->recv(j, slot.value(), offset, jBytes);
        pending++;
      }
      offset += jBytes;
    }
    while (pending-- > 0) {
      out->waitRecv(nullptr, timeout);
    }
  } else {
    auto in = ctx->createUnboundBuffer(const_cast<void*>(opts.input),
                                       myBytes);
    in->send(opts.root, slot.value(), 0, myBytes);
    in->waitSend(timeout);
  }
}

// Root sends slice j to rank j; leaves post one receive (reference:
// gloo/scatter.cc:38-60).
void scatter(ScatterOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "scatter: null context");
  auto traceSpan = ctx->tracer().span("scatter", opts.count * elementSize(opts.dtype), opts.root);
  const auto timeout = detail::effectiveTimeout(opts);
  const int rank = ctx->rank();
  const int size = ctx->size();
  const size_t nbytes = opts.count * elementSize(opts.dtype);
  Slot slot = Slot::build(SlotPrefix::kScatter, opts.tag);

  if (rank == opts.root) {
    auto in = ctx->createUnboundBuffer(const_cast<void*>(opts.input),
                                       nbytes * size);
    int pending = 0;
    for (int j = 0; j < size; j++) {
      if (j == rank) {
        std::memcpy(opts.output, bytePtr(opts.input) + j * nbytes, nbytes);
      } else {
        in->send(j, slot.value(), j * nbytes, nbytes);
        pending++;
      }
    }
    while (pending-- > 0) {
      in->waitSend(timeout);
    }
  } else {
    auto out = ctx->createUnboundBuffer(opts.output, nbytes);
    out->recv(opts.root, slot.value(), 0, nbytes);
    out->waitRecv(nullptr, timeout);
  }
}

void alltoall(AlltoallOptions& opts) {
  AlltoallvOptions v;
  static_cast<CollectiveOptions&>(v) = opts;
  v.input = opts.input;
  v.output = opts.output;
  v.inCounts.assign(opts.context->size(), opts.count);
  v.outCounts.assign(opts.context->size(), opts.count);
  v.dtype = opts.dtype;
  alltoallv(v);
}

// Rotated pairwise exchange: at step i, send to rank+i and receive from
// rank-i, so every step moves disjoint pairs and link load stays balanced
// (reference: gloo/alltoall.cc:39-50, alltoallv.cc:19-30).
void alltoallv(AlltoallvOptions& opts) {
  Context* ctx = opts.context;
  TC_ENFORCE(ctx != nullptr, "alltoallv: null context");
  auto traceSpan = ctx->tracer().span("alltoallv");
  const auto timeout = detail::effectiveTimeout(opts);
  const int rank = ctx->rank();
  const int size = ctx->size();
  TC_ENFORCE_EQ(opts.inCounts.size(), static_cast<size_t>(size));
  TC_ENFORCE_EQ(opts.outCounts.size(), static_cast<size_t>(size));
  const size_t elsize = elementSize(opts.dtype);

  std::vector<size_t> inOff(size, 0), outOff(size, 0);
  size_t inTotal = 0, outTotal = 0;
  for (int j = 0; j < size; j++) {
    inOff[j] = inTotal;
    outOff[j] = outTotal;
    inTotal += opts.inCounts[j] * elsize;
    outTotal += opts.outCounts[j] * elsize;
  }

  std::memcpy(bytePtr(opts.output) + outOff[rank],
              bytePtr(opts.input) + inOff[rank],
              opts.inCounts[rank] * elsize);
  if (size == 1) {
    return;
  }

  Slot slot = Slot::build(SlotPrefix::kAlltoall, opts.tag);
  auto in = ctx->createUnboundBuffer(const_cast<void*>(opts.input), inTotal);
  auto out = ctx->createUnboundBuffer(opts.output, outTotal);
  for (int i = 1; i < size; i++) {
    const int sendTo = (rank + i) % size;
    const int recvFrom = (rank - i + size) % size;
    in->send(sendTo, slot.value(), inOff[sendTo],
             opts.inCounts[sendTo] * elsize);
    out->recv(recvFrom, slot.value(), outOff[recvFrom],
              opts.outCounts[recvFrom] * elsize);
    in->waitSend(timeout);
    out->waitRecv(nullptr, timeout);
  }
}

}  // namespace tpucoll
