// Deterministic fault-injection plane for the transport and resilience
// stack (docs/faults.md).
//
// A process-global table of scripted fault rules interposes on the
// transport layer's outbound wire messages (pair.cc send/sendPut) and
// the pair connect path, and can — per rule — delay or stall a message,
// duplicate it, truncate it on the wire, corrupt its header, hard-kill
// the pair, or refuse connection attempts during the handshake. Rules
// are matched on (rank, peer, opcode, slot, payload size, nth match)
// and fire deterministically: same seed + same schedule + same per-rank
// event sequence => byte-identical firing sequence, asserted via
// report().
//
// The reference proves its failure handling with hand-written kill/abort
// tests (gloo/test/multiproc_test.h); this plane turns every failure
// class into a scriptable, repeatable input so the chaos harness
// (tests/test_chaos.py) can cover the recovery contract instead of
// assuming it.
//
// Cost contract: with no schedule installed the transport pays exactly
// ONE relaxed atomic load + predictable branch per message (armed()),
// nothing else — the plane is compiled in but free on the hot path.
// Every evaluation beyond that gate happens on the (rare) slow path
// under the table mutex; injected sleeps happen after the mutex is
// released, on the calling user thread only (the loop thread is never
// slept — sendOwned responses are deliberately not interposed).
#pragma once

#include <atomic>
#include <cstdint>
#include <string>

namespace tpucoll {

class Metrics;
class Tracer;

namespace fault {

enum class Action : uint8_t {
  kDelay = 0,     // sleep `ms` on the sending thread before enqueue
  kStall,         // same mechanics, watchdog-tripping intent (long ms)
  kDup,           // enqueue a second copy of the message after the first
  kTruncate,      // put only `bytes` payload bytes on the wire, then
                  // fail the pair (receiver sees EOF mid-message)
  kCorrupt,       // corrupt the wire header (receiver: protocol
                  // violation / AEAD failure naming this rank)
  kKill,          // hard-fail the pair before the message is sent
  kConnectRefuse, // throw a retryable IoException from connectAttempt
  kCount,
};

const char* actionName(Action a);

// What the transport must apply to the matched message. Delay/stall have
// already been served (slept) by the time onTxMessage returns; the rest
// are returned because only the pair can apply them.
struct TxDecision {
  bool corrupt{false};
  bool duplicate{false};
  bool truncate{false};
  uint64_t truncateToBytes{0};  // payload bytes to actually transmit
  bool kill{false};
};

// XOR mask applied to WireHeader.magic by a corrupt fault. Any nonzero
// mask guarantees the magic check fails on the receiver; fixed so the
// corruption itself is deterministic.
constexpr uint32_t kCorruptMagicMask = 0xDEAD5A5Au;

namespace detail {
extern std::atomic<bool> g_armed;
}  // namespace detail

// Hot-path gate: one relaxed load. False whenever no schedule is
// installed, so the per-message cost is a single predictable check.
inline bool armed() {
  return detail::g_armed.load(std::memory_order_relaxed);
}

// Install a schedule (JSON, see docs/faults.md), replacing any previous
// one and resetting all rule state and the firing report. Throws
// EnforceError on malformed input.
void install(const std::string& json);

// Remove the schedule and firing report; armed() returns false again.
void clear();

// The deterministic firing log as a JSON array, in firing order:
//   [{"rank","n","rule","action","peer","opcode","slot","nbytes",
//     "channel","domain"}, ...]
// `n` counts fires per (injecting rank, fault domain), so each serial
// stream's subsequence is reproducible even when several in-process
// ranks — or several async lanes of one rank — interleave. Entries
// carry no timestamps — two runs with the same seed, schedule, and
// per-rank workload produce byte-identical per-(rank, domain)
// sequences (sort by (rank, domain, n) to canonicalize a run whose
// global interleaving differs).
std::string report();

// Load TPUCOLL_FAULT_FILE once per process (no-op when unset; malformed
// files throw — an operator's explicit schedule must never be silently
// dropped). Called from Context connect so the schedule also covers the
// bootstrap handshakes.
void maybeLoadEnvFile();

// Slow-path evaluation, called only when armed(). Counts each fired
// fault in `metrics` (when non-null) and stamps a span into `tracer`
// (when enabled); delay/stall sleep here, after the table mutex is
// released. `channel` is the data channel carrying the message
// (0 = the pair's primary connection) and `domain` the transport
// context's fault domain (0 = the root context; async-engine lanes use
// lane + 1): per-rule match/fire/PRNG state is keyed per (rule, rank,
// channel, domain), so a pair whose traffic stripes across channels —
// or a rank whose collectives run concurrently on several async lanes —
// keeps one deterministic firing sequence per serial stream instead of
// a shared stream whose order would depend on thread interleaving. The
// report's per-fire index `n` counts per (rank, domain) for the same
// reason.
TxDecision onTxMessage(int rank, int peer, uint8_t opcode, uint64_t slot,
                       uint64_t nbytes, Metrics* metrics, Tracer* tracer,
                       int channel = 0, int domain = 0);

// Connect-path evaluation: throws IoException when a connect_refuse
// rule fires (the pair's retry loop classifies it as retryable).
void onConnect(int rank, int peer, Metrics* metrics, Tracer* tracer,
               int domain = 0);

// Message a kill fault poisons the pair with (also what the failed
// collective surfaces); exposed so tests can match it exactly.
std::string killMessage(int peer);
std::string truncateMessage(int peer);

}  // namespace fault
}  // namespace tpucoll
