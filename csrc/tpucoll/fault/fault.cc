#include "tpucoll/fault/fault.h"
#include "tpucoll/common/env.h"

#include <cstdlib>
#include <fstream>
#include <map>
#include <memory>
#include <mutex>
#include <sstream>
#include <tuple>
#include <utility>
#include <thread>
#include <vector>

#include "tpucoll/common/json.h"
#include "tpucoll/common/logging.h"
#include "tpucoll/common/metrics.h"
#include "tpucoll/common/tracer.h"
#include "tpucoll/transport/wire.h"

namespace tpucoll {
namespace fault {

namespace {

// Logical opcodes a schedule can target. Matching happens BEFORE the
// transport promotes bulk payloads onto the shm plane, so "data" covers
// a payload whether it travels over TCP or a same-host ring.
constexpr int kOpAny = -1;
constexpr int kOpConnect = -2;

int parseOpcode(const std::string& s) {
  if (s == "any") return kOpAny;
  if (s == "connect") return kOpConnect;
  if (s == "data") return static_cast<int>(transport::Opcode::kData);
  if (s == "put") return static_cast<int>(transport::Opcode::kPut);
  if (s == "get_req") return static_cast<int>(transport::Opcode::kGetReq);
  TC_THROW(EnforceError, "fault schedule: unknown opcode \"", s,
           "\" (want data|put|get_req|connect|any)");
}

const char* opcodeName(int op) {
  switch (op) {
    case static_cast<int>(transport::Opcode::kData): return "data";
    case static_cast<int>(transport::Opcode::kPut): return "put";
    case static_cast<int>(transport::Opcode::kGetReq): return "get_req";
    case kOpConnect: return "connect";
  }
  return "any";
}

Action parseAction(const std::string& s) {
  if (s == "delay") return Action::kDelay;
  if (s == "stall") return Action::kStall;
  if (s == "dup") return Action::kDup;
  if (s == "truncate") return Action::kTruncate;
  if (s == "corrupt") return Action::kCorrupt;
  if (s == "kill") return Action::kKill;
  if (s == "connect_refuse") return Action::kConnectRefuse;
  TC_THROW(EnforceError, "fault schedule: unknown action \"", s, "\"");
}

// splitmix64: turns (seed, rule index, rank) into a well-mixed xorshift
// state so every (rule, rank) stream is independent but reproducible.
uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

uint64_t xorshiftNext(uint64_t& s) {
  s ^= s >> 12;
  s ^= s << 25;
  s ^= s >> 27;
  return s * 0x2545F4914F6CDD1Dull;
}

struct Rule {
  // ---- match (when) ----
  int rank{-1};            // injecting rank; -1 = any
  int peer{-1};            // -1 = any
  int opcode{kOpAny};
  int64_t slot{-1};        // -1 = any
  uint64_t minBytes{0};
  uint64_t maxBytes{~0ull};
  int64_t nth{0};          // >0: fire only on the nth match (1-based)
  // ---- action ----
  Action action{Action::kDelay};
  uint32_t ms{0};
  uint64_t truncBytes{0};  // 0: half the payload
  uint64_t maxFires{~0ull};
  double prob{1.0};
  uint64_t seed{0};        // per-rule seed override (0: schedule seed)
};

// Per-(rule, rank, channel, domain) mutable state. Keyed by the
// injecting rank so that several in-process ranks (thread-per-rank
// tests) each see their own deterministic match/fire/PRNG sequence
// regardless of thread interleaving between ranks — by the data channel
// so a pair whose traffic stripes across channels (TPUCOLL_CHANNELS >
// 1) keeps one deterministic stream per channel — and by the fault
// domain so a rank running concurrent collectives on several async-
// engine lanes (each lane a serial stream on its own sub-context) keeps
// one deterministic stream per lane.
struct RuleState {
  uint64_t matches{0};
  uint64_t fires{0};
  uint64_t rng{0};
  bool rngInit{false};
};

struct Fired {
  int rank;
  uint64_t n;  // per-(rank, domain) firing index
  size_t rule;
  Action action;
  int peer;
  int opcode;
  uint64_t slot;
  uint64_t nbytes;
  int channel;
  int domain;
};

struct Table {
  uint64_t seed{0};
  std::vector<Rule> rules;
  // mutable firing state, guarded by g_mu
  // per rule, per (rank, channel, domain)
  std::vector<std::map<std::tuple<int, int, int>, RuleState>> state;
  std::map<std::pair<int, int>, uint64_t> firesPerRankDomain;
  std::vector<Fired> fired;
};

std::mutex g_mu;
std::unique_ptr<Table> g_table;  // guarded by g_mu
std::once_flag g_envOnce;

uint64_t asCount(const JsonReader::Value& v, const char* what) {
  TC_ENFORCE(v.kind == JsonReader::Value::Kind::kNumber && v.number >= 0,
             "fault schedule: \"", what, "\" must be a non-negative number");
  return static_cast<uint64_t>(v.number);
}

// Reject unknown/misspelled keys outright: a typo'd "rnak" must not
// silently widen a kill rule to every rank. The schedule is an
// operator's explicit instruction — docs/faults.md promises it is
// never silently reinterpreted.
void enforceKnownKeys(const JsonReader::Value& obj,
                      std::initializer_list<const char*> allowed,
                      const char* where) {
  for (const auto& f : obj.fields) {
    bool known = false;
    for (const char* k : allowed) {
      if (f.first == k) {
        known = true;
        break;
      }
    }
    TC_ENFORCE(known, "fault schedule: unknown field \"", f.first,
               "\" in ", where);
  }
}

Rule parseRule(const JsonReader::Value& e, size_t index) {
  using Kind = JsonReader::Value::Kind;
  TC_ENFORCE(e.kind == Kind::kObject, "fault schedule: fault #", index,
             " must be an object");
  enforceKnownKeys(
      e, {"when", "action", "ms", "bytes", "count", "prob", "seed"},
      "fault rule");
  Rule r;
  if (const JsonReader::Value* when = e.field("when")) {
    TC_ENFORCE(when->kind == Kind::kObject,
               "fault schedule: \"when\" must be an object");
    enforceKnownKeys(*when,
                     {"rank", "peer", "opcode", "slot", "min_bytes",
                      "max_bytes", "nth"},
                     "\"when\"");
    if (const auto* f = when->field("rank")) {
      r.rank = static_cast<int>(asCount(*f, "rank"));
    }
    if (const auto* f = when->field("peer")) {
      r.peer = static_cast<int>(asCount(*f, "peer"));
    }
    if (const auto* f = when->field("opcode")) {
      TC_ENFORCE(f->kind == Kind::kString,
                 "fault schedule: \"opcode\" must be a string");
      r.opcode = parseOpcode(f->str);
    }
    if (const auto* f = when->field("slot")) {
      r.slot = static_cast<int64_t>(asCount(*f, "slot"));
    }
    if (const auto* f = when->field("min_bytes")) {
      r.minBytes = asCount(*f, "min_bytes");
    }
    if (const auto* f = when->field("max_bytes")) {
      r.maxBytes = asCount(*f, "max_bytes");
    }
    if (const auto* f = when->field("nth")) {
      r.nth = static_cast<int64_t>(asCount(*f, "nth"));
      TC_ENFORCE(r.nth >= 1, "fault schedule: \"nth\" is 1-based");
    }
  }
  const JsonReader::Value* action = e.field("action");
  TC_ENFORCE(action != nullptr && action->kind == Kind::kString,
             "fault schedule: fault #", index,
             " needs a string \"action\"");
  r.action = parseAction(action->str);
  if (const auto* f = e.field("ms")) {
    r.ms = static_cast<uint32_t>(asCount(*f, "ms"));
  } else if (r.action == Action::kDelay) {
    r.ms = 10;
  } else if (r.action == Action::kStall) {
    r.ms = 1000;
  }
  if (const auto* f = e.field("bytes")) {
    r.truncBytes = asCount(*f, "bytes");
  }
  if (const auto* f = e.field("count")) {
    r.maxFires = asCount(*f, "count");
  }
  if (const auto* f = e.field("prob")) {
    TC_ENFORCE(f->kind == Kind::kNumber && f->number >= 0.0 &&
                   f->number <= 1.0,
               "fault schedule: \"prob\" must be in [0, 1]");
    r.prob = f->number;
  }
  if (const auto* f = e.field("seed")) {
    r.seed = asCount(*f, "seed");
  }
  if (r.action == Action::kConnectRefuse) {
    TC_ENFORCE(r.opcode == kOpAny || r.opcode == kOpConnect,
               "fault schedule: connect_refuse matches opcode "
               "\"connect\" only");
    r.opcode = kOpConnect;
    // A refusal with no cap would starve the bootstrap past its
    // deadline; default to one refusal so the retry path is exercised
    // but connect still succeeds unless the schedule says otherwise.
    if (e.field("count") == nullptr) {
      r.maxFires = 1;
    }
  } else if (r.opcode == kOpConnect) {
    TC_ENFORCE(r.action == Action::kDelay || r.action == Action::kStall,
               "fault schedule: opcode \"connect\" supports "
               "connect_refuse, delay, or stall");
  }
  return r;
}

const char* traceName(Action a) {
  switch (a) {
    case Action::kDelay: return "fault.delay";
    case Action::kStall: return "fault.stall";
    case Action::kDup: return "fault.dup";
    case Action::kTruncate: return "fault.truncate";
    case Action::kCorrupt: return "fault.corrupt";
    case Action::kKill: return "fault.kill";
    case Action::kConnectRefuse: return "fault.connect_refuse";
    case Action::kCount: break;
  }
  return "fault";
}

// Evaluate all rules for one event under g_mu. Returns the fired rule
// actions (in rule order) and the total sleep the caller must serve
// after releasing the lock.
struct Evaluation {
  TxDecision decision;
  uint32_t sleepMs{0};
  Action sleepAction{Action::kDelay};  // span name for the served sleep
  bool connectRefused{false};
  std::vector<std::pair<Action, uint64_t>> firedActions;  // with nbytes
};

Evaluation evaluateLocked(int rank, int peer, int opcode, uint64_t slot,
                          uint64_t nbytes, int channel, int domain) {
  Evaluation ev;
  Table* t = g_table.get();
  if (t == nullptr) {
    return ev;
  }
  const bool connectEvent = opcode == kOpConnect;
  for (size_t i = 0; i < t->rules.size(); i++) {
    Rule& r = t->rules[i];
    // A wildcard-opcode rule with a tx-only destructive action must not
    // match (or consume its count/nth budget on) a connect event — the
    // connect path can only serve refuse/delay/stall, and a silently
    // swallowed kill would falsely appear in the report.
    if (connectEvent && r.action != Action::kConnectRefuse &&
        r.action != Action::kDelay && r.action != Action::kStall) {
      continue;
    }
    if ((r.rank != -1 && r.rank != rank) ||
        (r.peer != -1 && r.peer != peer) ||
        (r.opcode != kOpAny && r.opcode != opcode) ||
        (r.slot != -1 && static_cast<uint64_t>(r.slot) != slot) ||
        nbytes < r.minBytes || nbytes > r.maxBytes) {
      continue;
    }
    RuleState& st = t->state[i][std::make_tuple(rank, channel, domain)];
    st.matches++;
    if (st.fires >= r.maxFires) {
      continue;
    }
    if (r.nth > 0 && st.matches != static_cast<uint64_t>(r.nth)) {
      continue;
    }
    if (r.prob < 1.0) {
      if (!st.rngInit) {
        st.rng = splitmix64((r.seed != 0 ? r.seed : t->seed) ^
                            splitmix64(i * 0x9E37u + 1) ^
                            splitmix64(static_cast<uint64_t>(rank) + 0x51u) ^
                            splitmix64(static_cast<uint64_t>(channel) * 0xC11u) ^
                            splitmix64(static_cast<uint64_t>(domain) * 0xD0A1u));
        st.rngInit = true;
      }
      const double u =
          (xorshiftNext(st.rng) >> 11) * (1.0 / 9007199254740992.0);
      if (u >= r.prob) {
        continue;
      }
    }
    st.fires++;
    const uint64_t n = t->firesPerRankDomain[{rank, domain}]++;
    t->fired.push_back(Fired{rank, n, i, r.action, peer, opcode, slot,
                             nbytes, channel, domain});
    ev.firedActions.emplace_back(r.action, nbytes);
    switch (r.action) {
      case Action::kDelay:
      case Action::kStall:
        ev.sleepMs += r.ms;
        ev.sleepAction = r.action;  // last sleeper names the span
        break;
      case Action::kDup:
        ev.decision.duplicate = true;
        break;
      case Action::kTruncate:
        ev.decision.truncate = true;
        ev.decision.truncateToBytes =
            r.truncBytes != 0 ? std::min(r.truncBytes, nbytes)
                              : nbytes / 2;
        break;
      case Action::kCorrupt:
        ev.decision.corrupt = true;
        break;
      case Action::kKill:
        ev.decision.kill = true;
        break;
      case Action::kConnectRefuse:
        ev.connectRefused = true;
        break;
      case Action::kCount:
        break;
    }
  }
  return ev;
}

void accountFired(const Evaluation& ev, int rank, int peer,
                  Metrics* metrics, Tracer* tracer) {
  (void)rank;
  for (const auto& fa : ev.firedActions) {
    if (metrics != nullptr) {
      metrics->recordFault(actionName(fa.first));
    }
    // Delay/stall get their span stamped around the actual sleep by the
    // caller; the instantaneous actions are stamped here.
    if (tracer != nullptr && tracer->enabled() &&
        fa.first != Action::kDelay && fa.first != Action::kStall) {
      const int64_t now = Tracer::nowUs();
      tracer->record(Tracer::Event{traceName(fa.first), now, now,
                                   fa.second, peer, "fault"});
    }
  }
}

}  // namespace

namespace detail {
std::atomic<bool> g_armed{false};
}  // namespace detail

const char* actionName(Action a) {
  switch (a) {
    case Action::kDelay: return "delay";
    case Action::kStall: return "stall";
    case Action::kDup: return "dup";
    case Action::kTruncate: return "truncate";
    case Action::kCorrupt: return "corrupt";
    case Action::kKill: return "kill";
    case Action::kConnectRefuse: return "connect_refuse";
    case Action::kCount: break;
  }
  return "unknown";
}

std::string killMessage(int peer) {
  return ::tpucoll::detail::strCat(
      "fault injection: killed connection to rank ", peer);
}

std::string truncateMessage(int peer) {
  return ::tpucoll::detail::strCat(
      "fault injection: truncated message to rank ", peer);
}

void install(const std::string& json) {
  using Kind = JsonReader::Value::Kind;
  JsonReader reader(json, "fault schedule JSON");
  const JsonReader::Value root = reader.parse();
  TC_ENFORCE(root.kind == Kind::kObject,
             "fault schedule JSON: root must be an object");
  enforceKnownKeys(root, {"seed", "faults", "version"}, "schedule root");
  auto table = std::make_unique<Table>();
  if (const auto* f = root.field("seed")) {
    table->seed = asCount(*f, "seed");
  }
  const JsonReader::Value* faults = root.field("faults");
  TC_ENFORCE(faults != nullptr && faults->kind == Kind::kArray,
             "fault schedule JSON: needs a \"faults\" array");
  for (size_t i = 0; i < faults->items.size(); i++) {
    table->rules.push_back(parseRule(faults->items[i], i));
  }
  table->state.resize(table->rules.size());
  {
    std::lock_guard<std::mutex> guard(g_mu);
    g_table = std::move(table);
    detail::g_armed.store(!g_table->rules.empty(),
                          std::memory_order_relaxed);
  }
  TC_DEBUG("fault plane: installed ", faults->items.size(), " rule(s)");
}

void clear() {
  std::lock_guard<std::mutex> guard(g_mu);
  detail::g_armed.store(false, std::memory_order_relaxed);
  g_table.reset();
}

std::string report() {
  std::ostringstream out;
  out << "[";
  {
    std::lock_guard<std::mutex> guard(g_mu);
    if (g_table != nullptr) {
      bool first = true;
      for (const Fired& f : g_table->fired) {
        if (!first) {
          out << ",";
        }
        first = false;
        out << "{\"rank\":" << f.rank << ",\"n\":" << f.n
            << ",\"rule\":" << f.rule << ",\"action\":\""
            << actionName(f.action) << "\",\"peer\":" << f.peer
            << ",\"opcode\":\"" << opcodeName(f.opcode)
            << "\",\"slot\":" << f.slot << ",\"nbytes\":" << f.nbytes
            << ",\"channel\":" << f.channel
            << ",\"domain\":" << f.domain << "}";
      }
    }
  }
  out << "]";
  return out.str();
}

void maybeLoadEnvFile() {
  std::call_once(g_envOnce, [] {
    const char* path = envString("TPUCOLL_FAULT_FILE");
    if (path == nullptr) {
      return;
    }
    std::ifstream in(path, std::ios::binary);
    TC_ENFORCE(in.good(), "TPUCOLL_FAULT_FILE: cannot read ", path);
    std::ostringstream buf;
    buf << in.rdbuf();
    install(buf.str());
    TC_DEBUG("fault plane: loaded schedule from ", path);
  });
}

TxDecision onTxMessage(int rank, int peer, uint8_t opcode, uint64_t slot,
                       uint64_t nbytes, Metrics* metrics, Tracer* tracer,
                       int channel, int domain) {
  Evaluation ev;
  {
    std::lock_guard<std::mutex> guard(g_mu);
    ev = evaluateLocked(rank, peer, static_cast<int>(opcode), slot, nbytes,
                        channel, domain);
  }
  accountFired(ev, rank, peer, metrics, tracer);
  if (ev.sleepMs > 0) {
    // The sleep runs on the calling (user) thread with no locks held:
    // it delays this rank's subsequent sends and receive posting — the
    // intended semantics of an injected link delay — without stalling
    // the event loop or sibling ranks.
    const int64_t t0 = Tracer::nowUs();
    std::this_thread::sleep_for(std::chrono::milliseconds(ev.sleepMs));
    if (tracer != nullptr && tracer->enabled()) {
      tracer->record(Tracer::Event{traceName(ev.sleepAction), t0,
                                   Tracer::nowUs(), nbytes, peer,
                                   "fault"});
    }
  }
  return ev.decision;
}

void onConnect(int rank, int peer, Metrics* metrics, Tracer* tracer,
               int domain) {
  Evaluation ev;
  {
    std::lock_guard<std::mutex> guard(g_mu);
    ev = evaluateLocked(rank, peer, kOpConnect, 0, 0, /*channel=*/0,
                        domain);
  }
  accountFired(ev, rank, peer, metrics, tracer);
  if (ev.sleepMs > 0) {
    const int64_t t0 = Tracer::nowUs();
    std::this_thread::sleep_for(std::chrono::milliseconds(ev.sleepMs));
    if (tracer != nullptr && tracer->enabled()) {
      tracer->record(Tracer::Event{traceName(ev.sleepAction), t0,
                                   Tracer::nowUs(), 0, peer, "fault"});
    }
  }
  if (ev.connectRefused) {
    TC_THROW(IoException, "fault injection: connection to rank ", peer,
             " refused");
  }
}

}  // namespace fault
}  // namespace tpucoll
