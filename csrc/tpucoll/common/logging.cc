#include "tpucoll/common/logging.h"

#include <unistd.h>

#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>

#include "tpucoll/common/env.h"

namespace tpucoll {

namespace {

LogLevel parseThreshold() {
  const char* env = envString("TPUCOLL_LOG_LEVEL");
  if (env == nullptr) {
    return LogLevel::kWarn;
  }
  if (strcasecmp(env, "debug") == 0 || strcmp(env, "0") == 0) {
    return LogLevel::kDebug;
  }
  if (strcasecmp(env, "info") == 0 || strcmp(env, "1") == 0) {
    return LogLevel::kInfo;
  }
  if (strcasecmp(env, "warn") == 0 || strcasecmp(env, "warning") == 0 ||
      strcmp(env, "2") == 0) {
    return LogLevel::kWarn;
  }
  if (strcasecmp(env, "error") == 0 || strcmp(env, "3") == 0) {
    return LogLevel::kError;
  }
  // Historically anything unrecognized silently meant ERROR — i.e. a
  // typo'd TPUCOLL_LOG_LEVEL=debgu suppressed the very logs asked for.
  TC_THROW(EnforceError,
           "TPUCOLL_LOG_LEVEL must be debug|info|warn|error or 0-3, "
           "got: ", env);
}

const char* levelName(LogLevel level) {
  switch (level) {
    case LogLevel::kDebug:
      return "D";
    case LogLevel::kInfo:
      return "I";
    case LogLevel::kWarn:
      return "W";
    case LogLevel::kError:
      return "E";
  }
  return "?";
}

std::mutex& logMutex() {
  static std::mutex m;
  return m;
}

}  // namespace

LogLevel logThreshold() {
  static LogLevel threshold = parseThreshold();
  return threshold;
}

void logMessage(LogLevel level, const char* file, int line,
                const std::string& msg) {
  const char* base = strrchr(file, '/');
  base = (base != nullptr) ? base + 1 : file;
  auto now = std::chrono::duration_cast<std::chrono::milliseconds>(
                 std::chrono::system_clock::now().time_since_epoch())
                 .count();
  std::lock_guard<std::mutex> guard(logMutex());
  fprintf(stderr, "[tpucoll %s %lld.%03lld pid=%d %s:%d] %s\n",
          levelName(level), static_cast<long long>(now / 1000),
          static_cast<long long>(now % 1000), getpid(), base, line,
          msg.c_str());
}

}  // namespace tpucoll
